"""Operational wire ops: SAVE (≙ BGSAVE), STATS, and the active sweeper."""

import asyncio
import json

import pytest

from distributedratelimiting.redis_tpu.runtime import wire
from distributedratelimiting.redis_tpu.runtime.checkpoint import load_snapshot
from distributedratelimiting.redis_tpu.runtime.clock import ManualClock
from distributedratelimiting.redis_tpu.runtime.remote import RemoteBucketStore
from distributedratelimiting.redis_tpu.runtime.server import BucketStoreServer
from distributedratelimiting.redis_tpu.runtime.store import (
    DeviceBucketStore,
    InProcessBucketStore,
)


def run(coro):
    return asyncio.run(coro)


class TestSaveOp:
    def test_save_writes_restorable_checkpoint(self, tmp_path):
        path = str(tmp_path / "dump.bin")

        async def main():
            clock = ManualClock()
            backing = InProcessBucketStore(clock=clock)
            async with BucketStoreServer(backing, snapshot_path=path) as srv:
                client = RemoteBucketStore(address=(srv.host, srv.port))
                try:
                    await client.acquire("k", 4, 10.0, 1.0)
                    await client.save()
                finally:
                    await client.aclose()
            restored = InProcessBucketStore(clock=clock)
            load_snapshot(restored, path)
            assert restored.acquire_blocking("k", 6, 10.0, 1.0).granted
            assert not restored.acquire_blocking("k", 1, 10.0, 1.0).granted

        run(main())

    def test_save_without_path_is_remote_error(self):
        async def main():
            async with BucketStoreServer(InProcessBucketStore()) as srv:
                client = RemoteBucketStore(address=(srv.host, srv.port))
                try:
                    with pytest.raises(wire.RemoteStoreError,
                                       match="snapshot-path"):
                        await client.save()
                    # The connection survives the failed SAVE.
                    await client.ping()
                finally:
                    await client.aclose()

        run(main())

    def test_server_cli_restores_snapshot_at_startup(self, tmp_path):
        # The main() path: --snapshot-path pointing at an existing file
        # restores before serving (tested via the module-level pieces the
        # CLI wires: save to file, fresh store, load).
        from distributedratelimiting.redis_tpu.runtime.checkpoint import (
            save_snapshot,
        )

        path = str(tmp_path / "dump.bin")
        clock = ManualClock()
        s = InProcessBucketStore(clock=clock)
        s.acquire_blocking("x", 9, 10.0, 1.0)
        save_snapshot(s, path)
        s2 = InProcessBucketStore(clock=clock)
        load_snapshot(s2, path)
        assert not s2.acquire_blocking("x", 5, 10.0, 1.0).granted


class TestMeshBackendCLI:
    def test_server_cli_serves_mesh_backend(self):
        """`--backend mesh` from the console: the pod-slice deployment
        unit (a TCP server fronting every visible chip) must be
        launchable without code — here against the virtual 8-device CPU
        mesh, exercising buckets, windows, and the bulk op end to end."""
        import os
        import re
        import subprocess
        import sys

        from distributedratelimiting.redis_tpu.utils.cpu_bootstrap import (
            XLA_DEVICE_COUNT_FLAG,
        )

        repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
        env = dict(os.environ, JAX_PLATFORMS="cpu",
                   DRLT_FORCE_CPU_PLATFORM="1",
                   XLA_FLAGS=f"{XLA_DEVICE_COUNT_FLAG}=8")
        proc = subprocess.Popen(
            [sys.executable, "-u", "-m",
             "distributedratelimiting.redis_tpu.runtime.server",
             "--backend", "mesh", "--port", "0", "--slots", "64"],
            cwd=repo, env=env, stdout=subprocess.PIPE, text=True)
        try:
            line = proc.stdout.readline()
            m = re.search(r"listening on (\S+):(\d+)", line)
            assert m, line
            host, port = m.group(1), int(m.group(2))

            async def drive():
                client = RemoteBucketStore(address=(host, port))
                try:
                    assert (await client.acquire("k", 1, 5.0, 1.0)).granted
                    assert (await client.window_acquire(
                        "w", 2, 3.0, 1.0)).granted
                    res = await client.acquire_many(
                        [f"b{i}" for i in range(32)], [1] * 32, 5.0, 1.0)
                    assert res.granted.all()
                    wres = await client.window_acquire_many(
                        [f"wb{i}" for i in range(32)], [1] * 32, 5.0, 1.0)
                    assert wres.granted.all()
                    stats = await client.stats()
                    assert any(k.startswith("bucket[")
                               for k in stats["store"]["tiers"])
                finally:
                    await client.aclose()

            run(drive())
        finally:
            proc.terminate()
            proc.wait(timeout=10)

    def test_server_cli_fp_directory(self):
        """`--directory fp` from the console: the device-resident
        fingerprint directory deployable without code — buckets and keyed
        windows decided straight from fingerprints over TCP."""
        import os
        import re
        import subprocess
        import sys

        repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
        env = dict(os.environ, JAX_PLATFORMS="cpu",
                   DRLT_FORCE_CPU_PLATFORM="1")
        proc = subprocess.Popen(
            [sys.executable, "-u", "-m",
             "distributedratelimiting.redis_tpu.runtime.server",
             "--directory", "fp", "--port", "0", "--slots", "256"],
            cwd=repo, env=env, stdout=subprocess.PIPE, text=True)
        try:
            line = proc.stdout.readline()
            m = re.search(r"listening on (\S+):(\d+)", line)
            assert m, line
            host, port = m.group(1), int(m.group(2))

            async def drive():
                client = RemoteBucketStore(address=(host, port))
                try:
                    got = [(await client.acquire("k", 1, 3.0, 0.0)).granted
                           for _ in range(5)]
                    assert got == [True] * 3 + [False] * 2
                    assert (await client.window_acquire(
                        "w", 2, 3.0, 10.0)).granted
                    res = await client.acquire_many(
                        [f"b{i}" for i in range(32)], [1] * 32, 5.0, 1.0)
                    assert res.granted.all()
                finally:
                    await client.aclose()

            run(drive())
        finally:
            proc.terminate()
            proc.wait(timeout=10)


class TestStatsOp:
    def test_stats_reports_server_and_store_metrics(self):
        async def main():
            store = DeviceBucketStore(n_slots=64, counter_slots=8,
                                      clock=ManualClock(), max_batch=64)
            async with BucketStoreServer(store) as srv:
                client = RemoteBucketStore(address=(srv.host, srv.port))
                try:
                    await client.acquire("a", 1, 10.0, 1.0)
                    stats = await client.stats()
                finally:
                    await client.aclose()
            assert stats["requests_served"] >= 1
            assert stats["connections_served"] == 1
            assert stats["store"]["launches"] >= 1
            json.dumps(stats)  # round-trippable

        run(main())

    def test_stats_reset_flag_opens_fresh_window(self):
        """``stats(reset=True)`` must zero the serving-latency AND flush
        histograms IN PLACE (the MicroBatcher holds its reference) after
        snapshotting — the measurement-window contract the serving-p99
        rig relies on. A plain ``stats()`` must not reset."""

        async def main():
            store = DeviceBucketStore(n_slots=64, counter_slots=8,
                                      clock=ManualClock(), max_batch=64)
            async with BucketStoreServer(store) as srv:
                client = RemoteBucketStore(address=(srv.host, srv.port),
                                           coalesce_requests=False)
                try:
                    for i in range(8):
                        await client.acquire(f"w{i}", 1, 10.0, 1.0)
                    s1 = await client.stats()        # plain: no reset
                    s2 = await client.stats(reset=True)
                    s3 = await client.stats()
                    await client.acquire("post", 1, 10.0, 1.0)
                    s4 = await client.stats()
                finally:
                    await client.aclose()
            assert s1["serving_samples"] >= 8
            assert s2["serving_samples"] >= s1["serving_samples"]  # pre-reset snap
            assert s2["store"]["flush_samples"] >= 1
            # Post-reset: only the stats ops themselves have landed.
            assert s3["serving_samples"] <= 2
            assert s3["store"]["flush_samples"] == 0
            # New samples land in the SAME (in-place-reset) histograms.
            assert s4["serving_samples"] > s3["serving_samples"]
            assert s4["store"]["flush_samples"] >= 1

        run(main())


class TestActiveSweeper:
    def test_sweep_all_evicts_expired_buckets(self):
        clock = ManualClock()
        store = DeviceBucketStore(n_slots=64, counter_slots=8, clock=clock,
                                  max_batch=64)
        store.acquire_blocking("gone", 1, 10.0, 1.0)
        table = store._table(10.0, 1.0)
        assert table.dir.lookup("gone") is not None
        # Past time-to-full TTL (deficit 1 token @ 1/s → ceil + clamp ≥ 1s).
        clock.advance_seconds(5.0)
        store.sweep_all()
        assert table.dir.lookup("gone") is None
        assert store.metrics.slots_evicted >= 1

    def test_background_sweeper_runs_and_stops(self):
        async def main():
            clock = ManualClock()
            store = DeviceBucketStore(n_slots=64, counter_slots=8,
                                      clock=clock, max_batch=64)
            store.acquire_blocking("k", 1, 10.0, 1.0)
            clock.advance_seconds(5.0)
            store.start_sweeper(period_s=0.02)
            store.start_sweeper(period_s=0.02)  # idempotent
            for _ in range(100):
                await asyncio.sleep(0.02)
                if store.metrics.sweeps > 0:
                    break
            assert store.metrics.sweeps > 0
            await store.aclose()
            assert store._sweeper_task is None

        run(main())


class TestSaveCoalescing:
    def test_concurrent_saves_share_one_pull(self, tmp_path):
        path = str(tmp_path / "dump.bin")
        pulls = []

        class CountingStore(InProcessBucketStore):
            def snapshot(self):
                pulls.append(1)
                import time

                time.sleep(0.05)  # keep the save in flight
                return super().snapshot()

        async def main():
            backing = CountingStore()
            backing.acquire_blocking("k", 1, 10.0, 1.0)
            async with BucketStoreServer(backing, snapshot_path=path) as srv:
                client = RemoteBucketStore(address=(srv.host, srv.port))
                try:
                    await asyncio.gather(*(client.save() for _ in range(6)))
                finally:
                    await client.aclose()

        run(main())
        # 6 concurrent requests coalesce onto in-flight saves — far fewer
        # full-state pulls than requests (1-2 depending on arrival timing).
        assert 1 <= len(pulls) <= 2, pulls


class TestSweeperResilience:
    def test_sweeper_survives_failing_sweep(self):
        async def main():
            clock = ManualClock()
            store = DeviceBucketStore(n_slots=64, counter_slots=8,
                                      clock=clock, max_batch=64)
            calls = []
            original = store.sweep_all

            def flaky():
                calls.append(1)
                if len(calls) == 1:
                    raise RuntimeError("transient device error")
                original()

            store.sweep_all = flaky
            store.start_sweeper(period_s=0.02)
            for _ in range(200):
                await asyncio.sleep(0.02)
                if len(calls) >= 2:
                    break
            assert len(calls) >= 2  # kept running after the failure
            await store.aclose()  # and aclose survives a failed task

        run(main())
