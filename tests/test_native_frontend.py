"""Native serving front-end (native/frontend.cc + runtime/native_frontend.py).

The C++ epoll front-end must speak the exact v4 wire protocol the asyncio
server speaks — every test here drives it through the unmodified
:class:`RemoteBucketStore` client (and one raw socket for the malformed
cases), so protocol drift between the two server halves fails loudly.
"""

from __future__ import annotations

import asyncio
import struct

import numpy as np
import pytest

from distributedratelimiting.redis_tpu.runtime import wire
from distributedratelimiting.redis_tpu.runtime.remote import RemoteBucketStore
from distributedratelimiting.redis_tpu.runtime.server import BucketStoreServer
from distributedratelimiting.redis_tpu.runtime.store import InProcessBucketStore
from distributedratelimiting.redis_tpu.utils.native import load_frontend_lib

pytestmark = pytest.mark.skipif(
    load_frontend_lib() is None,
    reason="native front-end library unavailable (no compiler?)")


def run(coro):
    return asyncio.run(coro)


async def _with_server(fn, **server_kw):
    async with BucketStoreServer(InProcessBucketStore(), native_frontend=True,
                                 **server_kw) as srv:
        await fn(srv)


def test_per_request_acquire_and_refill_semantics():
    async def body(srv):
        store = RemoteBucketStore(address=(srv.host, srv.port),
                                  coalesce_requests=False)
        try:
            r = await store.acquire("k", 4, 10.0, 1.0)
            assert r.granted and r.remaining == pytest.approx(6.0)
            r = await store.acquire("k", 7, 10.0, 1.0)
            assert not r.granted  # all-or-nothing: 6 < 7
            r = await store.acquire("k", 6, 10.0, 1.0)
            assert r.granted
        finally:
            await store.aclose()

    run(_with_server(body))


def test_window_ops_route_by_op_byte():
    async def body(srv):
        store = RemoteBucketStore(address=(srv.host, srv.port),
                                  coalesce_requests=False)
        try:
            w = await store.window_acquire("w", 2, 5.0, 60.0)
            assert w.granted and w.remaining == pytest.approx(3.0)
            f = await store.fixed_window_acquire("f", 5, 5.0, 60.0)
            assert f.granted
            f2 = await store.fixed_window_acquire("f", 1, 5.0, 60.0)
            assert not f2.granted
        finally:
            await store.aclose()

    run(_with_server(body))


def test_concurrent_burst_batches_with_exact_grants():
    """64 concurrent single-permit acquires on one 40-token bucket: the
    front-end batches them into few flushes, and exactly 40 grant (the
    store's in-batch duplicate serialization holds through the native
    path)."""
    async def body(srv):
        store = RemoteBucketStore(address=(srv.host, srv.port),
                                  coalesce_requests=False)
        try:
            results = await asyncio.gather(
                *(store.acquire("hot", 1, 40.0, 1e-9) for _ in range(64)))
            assert sum(r.granted for r in results) == 40
            stats = await store.stats()
            assert stats["native_frontend"] is True
            # NOTE: no strict batch-count assert — under core starvation
            # the scheduler can legally deliver one frame per flush (the
            # exact 40-grant count above is the deterministic semantic;
            # coalescing itself is covered by the bench's batch metrics).
            assert 1 <= stats["batches_flushed"] <= 64 + stats[
                "requests_served"]
        finally:
            await store.aclose()

    run(_with_server(body))


def test_mixed_configs_one_batch():
    """Frames with different (capacity, rate) in one burst split into
    per-config store calls with results scattered back correctly."""
    async def body(srv):
        store = RemoteBucketStore(address=(srv.host, srv.port),
                                  coalesce_requests=False)
        try:
            small = [store.acquire(f"s{i}", 1, 1.0, 1e-9) for i in range(8)]
            big = [store.acquire("b", 1, 100.0, 1e-9) for _ in range(8)]
            results = await asyncio.gather(*small, *big)
            assert all(r.granted for r in results[:8])     # distinct keys
            assert all(r.granted for r in results[8:])     # capacity 100
            r2 = await store.acquire("s0", 1, 1.0, 1e-9)
            assert not r2.granted                          # 1-cap spent
        finally:
            await store.aclose()

    run(_with_server(body))


def test_bulk_passthrough_and_stats():
    async def body2(srv):
        store = RemoteBucketStore(address=(srv.host, srv.port))
        try:
            keys = [f"u{i % 10}" for i in range(1000)]
            res = await store.acquire_many(keys, [1] * 1000, 30.0, 1e-9)
            # 10 distinct keys, 100 requests each, capacity 30:
            assert int(res.granted.sum()) == 10 * 30
            st = await store.stats()
            assert st["requests_served"] >= 1
        finally:
            await store.aclose()

    run(_with_server(body2))


def test_ping_and_peek_and_sync_passthrough():
    async def body(srv):
        store = RemoteBucketStore(address=(srv.host, srv.port),
                                  coalesce_requests=False)
        try:
            await store.ping()
            await store.acquire("p", 3, 10.0, 1.0)
            # peek is a blocking client call; run off-loop because the
            # server's passthrough handler shares this test's event loop.
            avail = await asyncio.to_thread(store.peek_blocking,
                                            "p", 10.0, 1.0)
            assert avail == pytest.approx(7.0)
            res = await store.sync_counter("c", 5.0, 1.0)
            assert res.global_score == pytest.approx(5.0)
        finally:
            await store.aclose()

    run(_with_server(body))


def test_auth_required_flow():
    async def ok(srv):
        store = RemoteBucketStore(address=(srv.host, srv.port),
                                  auth_token="sekrit",
                                  coalesce_requests=False)
        try:
            r = await store.acquire("k", 1, 10.0, 1.0)
            assert r.granted
        finally:
            await store.aclose()

    run(_with_server(ok, auth_token="sekrit"))

    async def bad(srv):
        store = RemoteBucketStore(address=(srv.host, srv.port),
                                  auth_token="wrong",
                                  coalesce_requests=False)
        try:
            with pytest.raises(wire.RemoteStoreError):
                await store.acquire("k", 1, 10.0, 1.0)
        finally:
            await store.aclose()

    run(_with_server(bad, auth_token="sekrit"))

    async def unauthed(srv):
        # No HELLO at all: the C side rejects the first scalar op.
        store = RemoteBucketStore(address=(srv.host, srv.port),
                                  coalesce_requests=False)
        try:
            with pytest.raises((wire.RemoteStoreError, TimeoutError,
                                ConnectionError)):
                await store.acquire("k", 1, 10.0, 1.0)
        finally:
            await store.aclose()

    run(_with_server(unauthed, auth_token="sekrit"))


def test_concurrency_semaphore_batched_natively():
    """OP_SEMA rides the hot batch path: concurrent holds against one
    limit grant exactly `limit`, releases restore capacity — all through
    the unmodified client."""
    async def body(srv):
        store = RemoteBucketStore(address=(srv.host, srv.port),
                                  coalesce_requests=False)
        try:
            results = await asyncio.gather(
                *(store.concurrency_acquire("gpu", 1, 10)
                  for _ in range(30)))
            assert sum(r.granted for r in results) == 10
            await asyncio.gather(
                *(store.concurrency_release("gpu", 1) for _ in range(4)))
            r = await store.concurrency_acquire("gpu", 4, 10)
            assert r.granted and r.remaining == pytest.approx(10.0)
            assert not (await store.concurrency_acquire("gpu", 1, 10)).granted
        finally:
            await store.aclose()

    run(_with_server(body))


def test_hello_pipelined_with_request_in_one_segment():
    """HELLO + ACQUIRE written in one TCP segment must both serve (the
    asyncio path handles this by reading frames sequentially; the native
    path parks post-HELLO frames until Python resolves auth)."""
    async def body(srv):
        reader, writer = await asyncio.open_connection(srv.host, srv.port)
        burst = (wire.encode_request(1, wire.OP_HELLO, "sekrit")
                 + wire.encode_request(2, wire.OP_ACQUIRE, "k", 1,
                                       10.0, 1.0))
        writer.write(burst)
        await writer.drain()
        f1 = await asyncio.wait_for(wire.read_frame(reader), 10)
        f2 = await asyncio.wait_for(wire.read_frame(reader), 10)
        by_seq = {}
        for f in (f1, f2):
            seq, kind, vals = wire.decode_response(f)
            by_seq[seq] = (kind, vals)
        assert by_seq[1][0] == wire.RESP_EMPTY          # HELLO ok
        assert by_seq[2][0] == wire.RESP_DECISION       # acquire served
        assert by_seq[2][1][0] is True
        writer.close()

    run(_with_server(body, auth_token="sekrit"))


def test_loadgen_terminates_against_auth_server():
    """The C load generator never HELLOs; an auth-protected server closes
    each conn after one error — the loadgen must return promptly (EOF
    detection), not spin on dead fds."""
    from distributedratelimiting.redis_tpu.runtime.native_frontend import (
        native_loadgen,
    )

    async def body(srv):
        replies, granted, elapsed = await asyncio.wait_for(
            asyncio.to_thread(native_loadgen, srv.host, srv.port,
                              conns=2, depth=4, reqs_per_conn=100), 30)
        assert granted == 0
        assert replies < 200  # conns died early; no grants, no spin

    run(_with_server(body, auth_token="sekrit"))


def test_malformed_frames_get_error_reply_then_close():
    async def body(srv):
        reader, writer = await asyncio.open_connection(srv.host, srv.port)
        # Bad version byte: one RESP_ERROR, then the server closes.
        body_bytes = bytes([9]) + struct.pack("<I", 7) + bytes([wire.OP_PING])
        writer.write(struct.pack("<I", len(body_bytes)) + body_bytes)
        await writer.drain()
        frame = await wire.read_frame(reader)
        assert frame is not None
        _, kind, vals = wire.decode_response(frame)
        assert kind == wire.RESP_ERROR and "version" in vals[0]
        assert await reader.read(1) == b""  # closed
        writer.close()

        reader, writer = await asyncio.open_connection(srv.host, srv.port)
        # Oversized length prefix: error + close, no buffering attempt.
        writer.write(struct.pack("<I", wire.MAX_FRAME + 1))
        await writer.drain()
        frame = await wire.read_frame(reader)
        assert frame is not None
        _, kind, vals = wire.decode_response(frame)
        assert kind == wire.RESP_ERROR
        writer.close()

    run(_with_server(body))


def test_zero_count_probe():
    async def body(srv):
        store = RemoteBucketStore(address=(srv.host, srv.port),
                                  coalesce_requests=False)
        try:
            r = await store.acquire("z", 0, 5.0, 1.0)
            assert r.granted  # zero-permit probe on a fresh bucket
        finally:
            await store.aclose()

    run(_with_server(body))


def test_latency_histogram_and_reset():
    async def body(srv):
        store = RemoteBucketStore(address=(srv.host, srv.port),
                                  coalesce_requests=False)
        try:
            for i in range(50):
                await store.acquire(f"h{i}", 1, 10.0, 1.0)
            st = await store.stats(reset=True)
            assert st["serving_samples"] >= 50
            assert st["serving_p99_ms"] > 0
            st2 = await store.stats()
            assert st2["serving_samples"] < 50  # reset took
        finally:
            await store.aclose()

    run(_with_server(body))


def test_native_loadgen_smoke():
    from distributedratelimiting.redis_tpu.runtime.native_frontend import (
        native_loadgen,
    )

    async def body(srv):
        replies, granted, elapsed = await asyncio.to_thread(
            native_loadgen, srv.host, srv.port, conns=2, depth=8,
            reqs_per_conn=500)
        assert replies == 2 * 500
        assert granted == replies  # huge capacity: everything grants
        assert elapsed > 0

    run(_with_server(body))


def test_chained_bulk_chunks_keep_order():
    """A chunked acquire_many whose duplicate keys span chunk boundaries
    must decide in request order through the passthrough lane (the
    chained-frame bit's contract)."""
    async def body(srv):
        store = RemoteBucketStore(address=(srv.host, srv.port))
        try:
            # Force multi-chunk by shrinking the chunk budget.
            import distributedratelimiting.redis_tpu.runtime.wire as w
            old = w.BULK_CHUNK_BUDGET
            w.BULK_CHUNK_BUDGET = 4096
            try:
                keys = [f"dup{i % 3}" for i in range(2000)]
                res = await store.acquire_many(keys, [1] * 2000, 100.0, 1e-9)
            finally:
                w.BULK_CHUNK_BUDGET = old
            # 3 keys x 100 capacity: exactly the FIRST 100 requests of
            # each key grant (request order), the rest deny.
            g = np.asarray(res.granted)
            assert int(g.sum()) == 300
            for m in range(3):
                idx = np.arange(2000) % 3 == m
                assert g[idx][:100].all() and not g[idx][100:].any()
        finally:
            await store.aclose()

    run(_with_server(body))


def test_invalid_utf8_key_does_not_wedge_the_pump():
    """A key with invalid UTF-8 must neither kill the pump thread nor
    poison its batch: it rate-limits under its own (surrogateescape)
    identity and the connection keeps serving."""
    async def body(srv):
        reader, writer = await asyncio.open_connection(srv.host, srv.port)
        bad_key = b"k\x80\xffbad"
        payload = (struct.pack("<H", len(bad_key)) + bad_key
                   + struct.pack("<idd", 1, 10.0, 1.0))
        body_bytes = (bytes([wire.PROTOCOL_VERSION]) + struct.pack("<I", 5)
                      + bytes([wire.OP_ACQUIRE]) + payload)
        writer.write(struct.pack("<I", len(body_bytes)) + body_bytes)
        await writer.drain()
        frame = await asyncio.wait_for(wire.read_frame(reader), 10)
        assert frame is not None
        seq, kind, vals = wire.decode_response(frame)
        assert seq == 5 and kind == wire.RESP_DECISION and vals[0] is True
        writer.close()

        # The pump survived: a normal client still gets served.
        store = RemoteBucketStore(address=(srv.host, srv.port),
                                  coalesce_requests=False)
        try:
            assert (await store.acquire("fine", 1, 10.0, 1.0)).granted
        finally:
            await store.aclose()

    run(_with_server(body))


def test_shutdown_with_inflight_batch_is_clean():
    """aclose while a batch's store call is still awaiting must drain the
    task before freeing the C handle (use-after-free guard)."""
    class SlowStore(InProcessBucketStore):
        async def acquire_many(self, *a, **kw):
            await asyncio.sleep(0.3)
            return await super().acquire_many(*a, **kw)

    async def body():
        srv = BucketStoreServer(SlowStore(), native_frontend=True)
        await srv.start()
        reader, writer = await asyncio.open_connection(srv.host, srv.port)
        writer.write(wire.encode_request(1, wire.OP_ACQUIRE, "k", 1,
                                         10.0, 1.0))
        await writer.drain()
        await asyncio.sleep(0.05)  # batch flushed, store call in flight
        await srv.aclose()         # must drain the batch, then free
        writer.close()

    run(body())


def test_hostname_resolves_for_native_listener():
    async def body():
        srv = BucketStoreServer(InProcessBucketStore(), host="localhost",
                                native_frontend=True)
        await srv.start()
        try:
            store = RemoteBucketStore(address=("127.0.0.1", srv.port),
                                      coalesce_requests=False)
            try:
                assert (await store.acquire("k", 1, 10.0, 1.0)).granted
            finally:
                await store.aclose()
        finally:
            await srv.aclose()

    run(body())


def test_approximate_limiter_converges_through_native_frontend():
    """The flagship two-level algorithm over the native serving path:
    two approximate limiter instances on separate TCP clients share one
    global decaying counter (OP_SYNC rides the passthrough lane) and
    converge on each other's load."""
    from distributedratelimiting.redis_tpu.models.approximate import (
        ApproximateTokenBucketRateLimiter,
    )
    from distributedratelimiting.redis_tpu.models.options import (
        ApproximateTokenBucketOptions,
    )

    async def body(srv):
        stores = [RemoteBucketStore(address=(srv.host, srv.port))
                  for _ in range(2)]
        lims = [ApproximateTokenBucketRateLimiter(
            ApproximateTokenBucketOptions(
                token_limit=100, tokens_per_period=10,
                instance_name="global"), s) for s in stores]
        try:
            for lim in lims:
                for _ in range(30):
                    lim._try_lease(1)
            for lim in lims:
                await lim.refresh()
            assert sum(l._global_score for l in lims) >= 60
            for lim in lims:
                assert lim.available_tokens < 100 - 30
        finally:
            for lim in lims:
                await lim.aclose()
            for s in stores:
                await s.aclose()

    run(_with_server(body))


def test_clean_shutdown_with_live_connection():
    async def body():
        srv = BucketStoreServer(InProcessBucketStore(), native_frontend=True)
        await srv.start()
        store = RemoteBucketStore(address=(srv.host, srv.port),
                                  coalesce_requests=False)
        r = await store.acquire("k", 1, 10.0, 1.0)
        assert r.granted
        await srv.aclose()  # with the client still connected
        await store.aclose()

    run(body())


def test_pipelined_sema_acquire_release_keeps_order():
    """Regression: an acquire→release pair for one key pipelined into a
    single micro-batch must decide in arrival order — config-grouping
    them apart (releases wire a=0) executed releases first and leaked
    the acquired permit permanently."""
    async def body(srv):
        reader, writer = await asyncio.open_connection(srv.host, srv.port)
        burst = (wire.encode_request(1, wire.OP_SEMA, "gpu", 1, 10.0, 0.0)
                 + wire.encode_request(2, wire.OP_SEMA, "gpu", -1, 0.0,
                                       0.0))
        writer.write(burst)
        await writer.drain()
        for _ in range(2):
            f = await asyncio.wait_for(wire.read_frame(reader), 10)
            seq, kind, vals = wire.decode_response(f)
            assert kind == wire.RESP_DECISION and vals[0] is True
        writer.close()
        store = RemoteBucketStore(address=(srv.host, srv.port),
                                  coalesce_requests=False)
        try:
            # Probe: zero held — the release really followed the acquire.
            r = await store.concurrency_acquire("gpu", 0, 10)
            assert r.granted and r.remaining == pytest.approx(0.0)
        finally:
            await store.aclose()

    run(_with_server(body))


def test_connection_churn_leaks_nothing():
    """500 short-lived connections (one op each, then close): the IO
    thread must reap every socket — no fd growth, and the server keeps
    serving afterward."""
    import os

    def count_fds() -> int:
        return len(os.listdir("/proc/self/fd"))

    async def body(srv):
        before = count_fds()
        for i in range(500):
            reader, writer = await asyncio.open_connection(srv.host,
                                                           srv.port)
            writer.write(wire.encode_request(1, wire.OP_ACQUIRE,
                                             f"churn{i}", 1, 10.0, 1.0))
            await writer.drain()
            f = await asyncio.wait_for(wire.read_frame(reader), 10)
            assert f is not None
            writer.close()
            try:
                await writer.wait_closed()
            except (ConnectionResetError, BrokenPipeError):
                pass
        # Give the IO thread a beat to reap the last EOFs.
        await asyncio.sleep(0.3)
        after = count_fds()
        assert after <= before + 8, (before, after)  # no per-conn leak
        store = RemoteBucketStore(address=(srv.host, srv.port),
                                  coalesce_requests=False)
        try:
            st = await store.stats()
            assert st["connections_served"] >= 500
            assert (await store.acquire("post-churn", 1, 10.0, 1.0)).granted
        finally:
            await store.aclose()

    run(_with_server(body))


def test_native_loadgen_op_sweep():
    """The C load generator drives every hot op kind; sema permits leak
    nothing because the keyspace bounds the distinct keys and the huge
    limit grants everything."""
    from distributedratelimiting.redis_tpu.runtime.native_frontend import (
        native_loadgen,
    )

    async def body(srv):
        for op in ("acquire", "window", "fixed_window", "sema"):
            replies, granted, elapsed = await asyncio.to_thread(
                native_loadgen, srv.host, srv.port, conns=2, depth=8,
                reqs_per_conn=300, keyspace=50, capacity=1e9,
                fill_rate=1e9, op=op)
            assert replies == 600, op
            assert granted == 600, op

    run(_with_server(body))


def test_cluster_over_native_servers():
    """Composition: a ClusterBucketStore sharding keys across two
    native-fronted servers — bulk split/merge rides the passthrough
    lane, per-key capacity is sticky to its owning node, and stats fan
    out per node."""
    from distributedratelimiting.redis_tpu.runtime.cluster import (
        ClusterBucketStore,
    )

    async def body():
        servers = [BucketStoreServer(InProcessBucketStore(),
                                     native_frontend=True)
                   for _ in range(2)]
        for s in servers:
            await s.start()
        cluster = ClusterBucketStore(
            addresses=[(s.host, s.port) for s in servers])
        try:
            keys = [f"ck{i}" for i in range(200)]
            res = await cluster.acquire_many(keys, [1] * 200, 3.0, 1e-9)
            assert res.granted.all()
            # Capacity is sticky per key regardless of which node owns it.
            res2 = await cluster.acquire_many(keys * 2, [2] * 400, 3.0,
                                              1e-9)
            g = np.asarray(res2.granted)
            assert int(g.sum()) == 200  # each key grants once more (1+2=3)
            st = await cluster.stats()
            assert len(st["nodes"]) == 2
            assert all(n.get("native_frontend") for n in st["nodes"])
        finally:
            await cluster.aclose()
            for s in servers:
                await s.aclose()

    run(body())


def test_save_checkpoint_through_native_server(tmp_path):
    """OP_SAVE rides the passthrough lane: the server checkpoints its
    store to the configured path, and a fresh server restores it."""
    from distributedratelimiting.redis_tpu.runtime import checkpoint

    path = str(tmp_path / "native.ckpt")

    async def body():
        backing = InProcessBucketStore()
        srv = BucketStoreServer(backing, native_frontend=True,
                                snapshot_path=path)
        await srv.start()
        store = RemoteBucketStore(address=(srv.host, srv.port),
                                  coalesce_requests=False)
        try:
            await store.acquire("persist", 4, 10.0, 1e-9)
            await store.save()
        finally:
            await store.aclose()
            await srv.aclose()
            await backing.aclose()

        restored = InProcessBucketStore()
        checkpoint.load_snapshot(restored, path)
        r = restored.acquire_blocking("persist", 7, 10.0, 1e-9)
        assert not r.granted  # only 6 left after the restored spend
        r = restored.acquire_blocking("persist", 6, 10.0, 1e-9)
        assert r.granted

    run(body())


def test_native_batching_knobs_configurable():
    """max_batch=1 forces one flush per request — the knob demonstrably
    reaches the C batcher."""
    async def body():
        srv = BucketStoreServer(InProcessBucketStore(),
                                native_frontend=True,
                                native_max_batch=1, native_deadline_us=50)
        await srv.start()
        store = RemoteBucketStore(address=(srv.host, srv.port),
                                  coalesce_requests=False)
        try:
            await asyncio.gather(
                *(store.acquire(f"knob{i}", 1, 10.0, 1.0)
                  for i in range(20)))
            st = await store.stats()
            assert st["batches_flushed"] >= 20  # no coalescing at cap 1
        finally:
            await store.aclose()
            await srv.aclose()

    run(body())


# -- distributed tracing through the native lanes ----------------------------

from distributedratelimiting.redis_tpu.utils import tracing  # noqa: E402


@pytest.fixture
def tracer():
    tr = tracing.configure(enabled=True, sample_rate=1.0, keep_rate=1.0,
                           latency_threshold_s=10.0)
    tr.reset()
    yield tr
    tracing.configure(enabled=False)
    tr.reset()


def test_traced_acquire_through_native_batch_lane(tracer):
    """A trace-stamped ACQUIRE parses in C (trace tail), batches
    normally, and leaves causally-linked client/fe spans — the
    feature-detected fe_batch_traces ABI."""
    if not getattr(load_frontend_lib(), "has_trace", False):
        pytest.skip("front-end binary predates the trace ABI")

    async def body(srv):
        store = RemoteBucketStore(address=(srv.host, srv.port),
                                  coalesce_requests=False)
        try:
            res = await store.acquire("tracee", 50, 5.0, 1.0)
            assert not res.granted  # denied: the tail sampler keeps it
        finally:
            await store.aclose()

    run(_with_server(body))
    traces = [t for t in tracer.traces()
              if any(s["status"] == "denied" for s in t["spans"])]
    assert traces, tracer.traces()
    spans = traces[0]["spans"]
    names = {s["name"] for s in spans}
    assert "client.acquire" in names
    assert "fe.batch" in names  # the C lane's dispatch record
    fe = next(s for s in spans if s["name"] == "fe.batch")
    client = next(s for s in spans if s["name"] == "client.acquire")
    assert fe["parent_id"] == client["span_id"]
    assert fe["status"] == "denied"


def test_traced_tier0_local_decision_still_traces(tracer):
    """Tier-0 local grants never reach Python on the serving path; the
    harvested C trace ring still contributes their ``fe.tier0`` spans —
    'locally-granted requests still trace'."""
    lib = load_frontend_lib()
    if not (getattr(lib, "has_trace", False)
            and getattr(lib, "has_tier0", False)):
        pytest.skip("front-end binary predates the trace/tier-0 ABI")
    from distributedratelimiting.redis_tpu.runtime.native_frontend import (
        Tier0Config,
    )

    async def body():
        backing = InProcessBucketStore()
        async with BucketStoreServer(
                backing, native_frontend=True,
                native_tier0=Tier0Config(sync_interval_s=0.01,
                                         min_budget=8.0)) as srv:
            store = RemoteBucketStore(address=(srv.host, srv.port),
                                      coalesce_requests=False)
            try:
                for _ in range(200):
                    r = await store.acquire("hot", 1, 1000.0, 1e-9)
                    assert r.granted
                st = await store.stats()
                assert st["tier0"]["hits"] >= 100  # tier-0 really served
                await asyncio.sleep(0.05)  # harvest rounds
            finally:
                await store.aclose()

    run(body())
    t0_spans = [s for t in tracer.traces() for s in t["spans"]
                if s["name"] == "fe.tier0"]
    assert t0_spans, "no tier-0 spans harvested"
    assert all(s["attrs"]["local"] for s in t0_spans)
    assert any(s["status"] == "ok" for s in t0_spans)
    # each tier-0 span parents on its request's client span in the SAME
    # exported trace (merged by trace id)
    merged = [t for t in tracer.traces()
              if any(s["name"] == "fe.tier0" for s in t["spans"])]
    linked = 0
    for t in merged:
        ids = {s["span_id"] for s in t["spans"]}
        linked += sum(1 for s in t["spans"]
                      if s["name"] == "fe.tier0" and s["parent_id"] in ids)
    assert linked > 0


def test_config_moved_gate_on_native_batch_lane():
    """Round 7: the C batch lane honors the live-config gate exactly
    like the asyncio lane — a frame carrying a retired (kind, a, b)
    answers the routable "config moved" error per-row (fe_send +
    kRowSkip), the store untouched for that row; the client chases once
    and every later call translates up front (one moved error total,
    window and bucket kinds alike)."""

    async def body():
        backing = InProcessBucketStore()
        async with BucketStoreServer(backing,
                                     native_frontend=True) as srv:
            store = RemoteBucketStore(address=(srv.host, srv.port),
                                      coalesce_requests=False)
            try:
                for _ in range(30):
                    await store.acquire("k", 1, 100.0, 0.0)
                await store.config_announce({"prepare": {
                    "kind": "bucket", "old": [100.0, 0.0],
                    "new": [50.0, 0.0]}, "version": 1})
                await store.config_announce({"commit": 1})
                # stale per-request frames ride the C batch lane: one
                # moved chase, then translated — exact balance carry
                r = await store.acquire("k", 0, 100.0, 0.0)
                assert r.remaining == 20.0  # 50 − 30 spent
                r = await store.acquire("k", 20, 100.0, 0.0)
                assert r.granted and r.remaining == 0.0
                assert not (await store.acquire("k", 1, 100.0,
                                                0.0)).granted
                st = await store.stats()
                assert st["config"]["moved_errors"] == 1
                # window kind gates on the same lane
                await store.window_acquire("w", 3, 10.0, 100.0)
                await store.config_announce({"prepare": {
                    "kind": "window", "old": [10.0, 100.0],
                    "new": [4.0, 100.0]}, "version": 2})
                await store.config_announce({"commit": 2})
                r = await store.window_acquire("w", 1, 10.0, 100.0)
                assert r.granted  # 3 of 4 replayed + 1 = at the limit
                r = await store.window_acquire("w", 1, 10.0, 100.0)
                assert not r.granted
            finally:
                await store.aclose()

    run(body())
