"""Mesh-sharded fingerprint directory (virtual 8-device CPU mesh).

The fingerprint-is-the-route design: shard = fp_lo % n_shards, per-shard
in-kernel probe/insert, psum global tier. Differential anchor: decisions
must match the single-chip fingerprint store for duplicate-free calls."""

import numpy as np
import pytest

from distributedratelimiting.redis_tpu.parallel.fp_sharded import (
    ShardedFpDeviceStore,
)
from distributedratelimiting.redis_tpu.parallel.mesh import create_mesh
from distributedratelimiting.redis_tpu.runtime.clock import ManualClock


@pytest.fixture(scope="module")
def mesh():
    return create_mesh(8)


def make_store(mesh, **kw):
    kw.setdefault("capacity", 5.0)
    kw.setdefault("fill_rate_per_sec", 0.0)
    kw.setdefault("per_shard_slots", 256)
    kw.setdefault("batch", 32)
    kw.setdefault("clock", ManualClock())
    return ShardedFpDeviceStore(mesh, **kw)


class TestShardedFp:
    def test_fresh_keys_grant_across_shards(self, mesh):
        store = make_store(mesh)
        keys = [f"k{i}" for i in range(200)]
        res = store.acquire_many_blocking(keys, [1] * 200)
        assert res.granted.all()
        assert store.fp_unresolved == 0
        # Keys actually spread: every shard's table holds some entries.
        fp = np.asarray(store.fp).reshape(8, -1, 2)
        per_shard = (fp != 0).any(-1).sum(axis=1)
        assert (per_shard > 0).all()

    def test_capacity_enforced_across_calls(self, mesh):
        store = make_store(mesh)
        r1 = store.acquire_many_blocking(["a", "b"], [3, 5])
        assert list(r1.granted) == [True, True]
        r2 = store.acquire_many_blocking(["a", "b"], [3, 1])
        assert list(r2.granted) == [False, False]  # 2 left / 0 left

    def test_in_call_duplicates_serialize(self, mesh):
        store = make_store(mesh)
        res = store.acquire_many_blocking(["dup"] * 8, [1] * 8)
        assert list(res.granted) == [True] * 5 + [False] * 3

    def test_global_counter_sees_all_shards(self, mesh):
        store = make_store(mesh)
        keys = [f"g{i}" for i in range(100)]
        res = store.acquire_many_blocking(keys, [2] * 100)
        assert res.granted.all()
        assert store.global_score == pytest.approx(200.0)

    def test_matches_single_chip_fp_store(self, mesh):
        from distributedratelimiting.redis_tpu.runtime.fp_store import (
            FingerprintBucketStore,
        )

        clock = ManualClock()
        store = make_store(mesh, clock=clock)
        single = FingerprintBucketStore(n_slots=1 << 12, clock=clock)
        rng = np.random.default_rng(3)
        keys = [f"k{i}" for i in range(300)]
        counts = rng.integers(0, 7, 300).tolist()
        got = store.acquire_many_blocking(keys, counts)
        want = single.acquire_many_blocking(keys, counts, 5.0, 0.0)
        np.testing.assert_array_equal(got.granted, want.granted)
        np.testing.assert_allclose(got.remaining, want.remaining, atol=1e-4)
        import asyncio

        asyncio.run(single.aclose())

    def test_refill_over_time(self, mesh):
        clock = ManualClock()
        store = make_store(mesh, fill_rate_per_sec=1.0, clock=clock)
        assert store.acquire_many_blocking(["r"], [5]).granted.all()
        assert not store.acquire_many_blocking(["r"], [1]).granted.any()
        clock.advance_seconds(3.0)
        assert store.acquire_many_blocking(["r"], [3]).granted.all()

    def test_window_pressure_denied_and_counted(self, mesh):
        store = make_store(mesh, per_shard_slots=8, probe_window=4)
        keys = [f"p{i}" for i in range(400)]
        res = store.acquire_many_blocking(keys, [1] * 400)
        assert store.fp_unresolved > 0
        assert int(res.granted.sum()) < 400

    def test_pressure_grows_all_shards_and_keeps_state(self, mesh):
        store = make_store(mesh, per_shard_slots=16, probe_window=8)
        marker = store.acquire_many_blocking(["marker"], [2])
        assert marker.granted.all()
        keys = [f"g{i}" for i in range(600)]
        for _ in range(5):
            res = store.acquire_many_blocking(keys, [1] * 600)
            if res.granted.all():
                break
        assert res.granted.all()
        assert store.grows >= 1
        assert store.per_shard_slots >= 32
        # Marker's consumption survived the per-shard device rehash:
        # capacity 5, consumed 2 ⇒ a 4-token ask must deny.
        r2 = store.acquire_many_blocking(["marker"], [4])
        assert not r2.granted.any()

    def test_sweep_frees_expired(self, mesh):
        clock = ManualClock()
        store = make_store(mesh, fill_rate_per_sec=1.0, clock=clock)
        keys = [f"s{i}" for i in range(50)]
        store.acquire_many_blocking(keys, [1] * 50)
        clock.advance_seconds(3600.0)  # way past time-to-full TTL
        freed = store.sweep()
        assert freed == 50

    def test_zero_permit_probe_granted(self, mesh):
        store = make_store(mesh)
        store.acquire_many_blocking(["z"], [5])
        res = store.acquire_many_blocking(["z", "z"], [0, 1])
        assert bool(res.granted[0]) and not bool(res.granted[1])

    def test_window_store_sliding_and_fixed(self, mesh):
        from distributedratelimiting.redis_tpu.parallel.fp_sharded import (
            ShardedFpWindowStore,
        )
        from distributedratelimiting.redis_tpu.runtime.fp_store import (
            FingerprintBucketStore,
        )
        import asyncio

        clock = ManualClock()
        store = ShardedFpWindowStore(
            mesh, limit=3.0, window_sec=10.0, per_shard_slots=256,
            batch=32, clock=clock)
        # Capacity within one window, across calls and shards.
        keys = [f"w{i}" for i in range(40)]
        r1 = store.acquire_many_blocking(keys, [2] * 40)
        assert r1.granted.all()
        r2 = store.acquire_many_blocking(keys, [2] * 40)
        assert not r2.granted.any()  # 2+2 > 3 within the window
        # Differential vs the single-chip fp window tier.
        single = FingerprintBucketStore(n_slots=1 << 12, clock=clock)
        rng = np.random.default_rng(23)
        dkeys = [f"d{i}" for i in rng.integers(0, 60, 200)]
        counts = rng.integers(0, 3, 200).tolist()
        got = store.acquire_many_blocking(dkeys, counts)
        want = single.window_acquire_many_blocking(dkeys, counts, 3.0, 10.0)
        np.testing.assert_array_equal(got.granted, want.granted)
        # New window: counts roll and interpolation decays.
        clock.advance_seconds(25.0)
        assert store.acquire_many_blocking(["w0"], [3]).granted.all()
        # Fixed-window variant differs from sliding where interpolation
        # would deny.
        fstore = ShardedFpWindowStore(
            mesh, limit=3.0, window_sec=10.0, fixed=True,
            per_shard_slots=256, batch=32, clock=clock)
        assert fstore.acquire_many_blocking(["f"], [3]).granted.all()
        clock.advance_seconds(10.5)  # fresh fixed window: full limit again
        assert fstore.acquire_many_blocking(["f"], [3]).granted.all()
        asyncio.run(single.aclose())

    def test_window_store_growth(self, mesh):
        from distributedratelimiting.redis_tpu.parallel.fp_sharded import (
            ShardedFpWindowStore,
        )

        clock = ManualClock()
        store = ShardedFpWindowStore(
            mesh, limit=5.0, window_sec=60.0, per_shard_slots=16,
            batch=32, probe_window=8, clock=clock)
        marker = store.acquire_many_blocking(["wm"], [4])
        assert marker.granted.all()
        keys = [f"wg{i}" for i in range(600)]
        for _ in range(5):
            res = store.acquire_many_blocking(keys, [1] * 600)
            if res.granted.all():
                break
        assert res.granted.all()
        assert store.grows >= 1
        # Marker's 4-of-5 survived the window rehash.
        assert not store.acquire_many_blocking(["wm"], [2]).granted.any()

    def test_verdict_only(self, mesh):
        store = make_store(mesh)
        res = store.acquire_many_blocking(["v1", "v2"], [1, 99],
                                          with_remaining=False)
        assert list(res.granted) == [True, False]
        assert res.remaining is None


class TestShardedFpCheckpoint:
    def test_snapshot_restore_roundtrip(self, mesh):
        store = make_store(mesh)
        keys = [f"r{i}" for i in range(60)]
        store.acquire_many_blocking(keys, [3] * 60)  # 2 of 5 left each
        snap = store.snapshot()
        other = make_store(mesh)
        other.restore(snap)
        res = other.acquire_many_blocking(keys, [3] * 60,
                                          with_remaining=False)
        assert not res.granted.any()  # consumption survived
        res2 = other.acquire_many_blocking(keys, [2] * 60,
                                           with_remaining=False)
        assert res2.granted.all()

    def test_restore_replaces_legacy_placement(self, mesh):
        # A snapshot without the placement marker (pre-v2, wrapping
        # window bases) must be re-placed through the migrate kernel —
        # verbatim install under the non-wrapping placement would orphan
        # nearly every key and silently reset its consumption.
        store = make_store(mesh)
        keys = [f"lg{i}" for i in range(60)]
        store.acquire_many_blocking(keys, [5] * 60)  # drain to 0
        snap = store.snapshot()
        snap.pop("placement")
        # Move every entry to its OLD wrapping base so the snapshot
        # really is in v1 form (sparse tables: old code placed each key
        # at its window's first cell).
        fp = np.array(snap["fp"])
        n_shards = snap["n_shards"]
        per = snap["per_shard"]
        cols = {f: np.array(snap[f])
                for f in ("tokens", "last_ts", "exists")}
        fp_sh = fp.reshape(n_shards, per, 2)
        cols_sh = {f: a.reshape(n_shards, per) for f, a in cols.items()}
        new_fp = np.zeros_like(fp_sh)
        new_cols = {f: np.zeros_like(a) for f, a in cols_sh.items()}
        for s in range(n_shards):
            live = np.nonzero((fp_sh[s] != 0).any(-1))[0]
            for i in live:
                pair = fp_sh[s][i]
                h = np.uint32(
                    (int(pair[0]) * 0x9E3779B1) & 0xFFFFFFFF) ^ pair[1]
                b = int(h % np.uint32(per))  # the v1 wrapping base
                assert not new_fp[s][b].any(), "collision in test data"
                new_fp[s][b] = pair
                for f in new_cols:
                    new_cols[f][s][b] = cols_sh[f][s][i]
        snap["fp"] = new_fp.reshape(fp.shape)
        for f, a in new_cols.items():
            snap[f] = a.reshape(cols[f].shape)
        other = make_store(mesh)
        other.restore(snap)
        res = other.acquire_many_blocking(keys, [1] * 60,
                                          with_remaining=False)
        assert not res.granted.any(), \
            "legacy restore lost drained-bucket state"


class TestFpSyncCadence:
    def test_launch_cadence_matches_batch(self, mesh):
        """Deferred psum on the fp tier: identical grants, same global
        score (decay 0 ⇒ pure sums, so the accumulator is fully checked)."""
        keys = [f"c{i}" for i in range(150)]
        counts = [2] * len(keys)
        outs = {}
        for cadence in ("batch", "launch"):
            store = make_store(mesh, sync_cadence=cadence)
            res = store.acquire_many_blocking(keys, counts)
            outs[cadence] = (np.asarray(res.granted), store.global_score)
        np.testing.assert_array_equal(outs["batch"][0], outs["launch"][0])
        assert outs["batch"][1] == outs["launch"][1] == 300.0

    def test_invalid_cadence_rejected(self, mesh):
        with pytest.raises(ValueError, match="sync_cadence"):
            make_store(mesh, sync_cadence="hourly")
