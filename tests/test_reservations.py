"""Estimate-reserve-settle (ISSUE 13): the reservation subsystem's unit
surface plus THE seeded streaming soak.

The soak is the acceptance differential: a deterministic streaming
schedule (estimate = actual × log-normal error) driven over the real
wire (OP_RESERVE / OP_SETTLE) under seeded chaos, with a mid-soak
drain-and-handoff AND a live OP_CONFIG budget mutation, audited over
the store's own bucket records — settled tokens reconcile exactly
against the tenant balance (outstanding + settled − debt identity),
stay inside budget + the epsilon envelope, no rid settles twice under
post-send retry, TTL auto-settle fires for killed clients, and the
same seed replays the same grant sequence bit for bit.
``make reserve-soak SEED=…`` replays any schedule (DRL_RESERVE_SEED)."""

from __future__ import annotations

import asyncio
import os

import numpy as np
import pytest

from distributedratelimiting.redis_tpu.models.approximate import (
    headroom_budget,
)
from distributedratelimiting.redis_tpu.runtime import placement, wire
from distributedratelimiting.redis_tpu.runtime.clock import ManualClock
from distributedratelimiting.redis_tpu.runtime.remote import (
    RemoteBucketStore,
)
from distributedratelimiting.redis_tpu.runtime.reservations import (
    EstimatePrior,
    ReservationLedger,
)
from distributedratelimiting.redis_tpu.runtime.server import (
    BucketStoreServer,
)
from distributedratelimiting.redis_tpu.runtime.store import (
    InProcessBucketStore,
)
from distributedratelimiting.redis_tpu.utils import faults
from distributedratelimiting.redis_tpu.utils.faults import (
    FaultInjector,
    FaultRule,
)

SEED = int(os.environ.get("DRL_RESERVE_SEED", "20260804"))

_FILL = 1e-9
_CHILD_CAP, _CHILD_RATE = 1e6, 1e-9


def run(coro):
    return asyncio.run(coro)


# -- EstimatePrior -----------------------------------------------------------

def test_prior_p99_for_interactive_mean_for_batch():
    p = EstimatePrior(window=200)
    for v in range(1, 101):  # 1..100
        p.observe("t", 0, float(v))
        p.observe("t", 1, float(v))
    assert p.estimate("t", 0) == 99.0          # p99 of 1..100
    assert p.estimate("t", 1) == pytest.approx(50.5)  # mean
    # A priority with no samples borrows the tenant's merged history.
    assert p.estimate("t", 2) == pytest.approx(50.5)
    assert p.estimate("nobody", 0) is None


def test_prior_bounded_window_and_groups():
    p = EstimatePrior(window=4, max_groups=2)
    for v in (1.0, 2.0, 3.0, 4.0, 100.0):
        p.observe("a", 0, v)
    # Window keeps the newest 4: mean-of-window for batch read.
    assert p.estimate("a", 1) == pytest.approx((2 + 3 + 4 + 100) / 4)
    p.observe("b", 0, 5.0)
    p.observe("c", 0, 7.0)  # evicts the oldest-touched group
    assert len(p) == 2
    # Bad samples are ignored, never raise.
    p.observe("a", 0, -1.0)
    p.observe("a", 0, float("nan"))


# -- ledger unit surface -----------------------------------------------------

def _ledger(store, **kw):
    t = [0.0]
    led = ReservationLedger(store, clock=lambda: t[0], **kw)
    return led, t


def test_ledger_reserve_settle_refund_and_debt():
    run(_ledger_body())


async def _ledger_body():
    st = InProcessBucketStore(clock=ManualClock())
    led, _t = _ledger(st)
    r = await led.reserve("r1", "t", "k", 100, 1000.0, _FILL,
                          _CHILD_CAP, _CHILD_RATE)
    assert r.granted and r.reserved == 100.0
    assert led.outstanding_tokens() == 100.0
    assert led.outstanding_by_tenant() == {"t": 100.0}
    # Over-estimate: the refund lands in BOTH levels.
    s = await led.settle("r1", "t", 40.0)
    assert s.outcome == "settled" and s.delta == -60.0
    assert s.refunded == 60.0 and s.debt == 0.0
    assert st._buckets[("t", 1000.0, _FILL)][0] == pytest.approx(960.0)
    assert st._buckets[("k", _CHILD_CAP, _CHILD_RATE)][0] == \
        pytest.approx(_CHILD_CAP - 40.0)
    assert led.outstanding_tokens() == 0.0
    # Under-estimate past the whole budget: the uncovered part is debt.
    r2 = await led.reserve("r2", "t", "k", 100, 1000.0, _FILL,
                           _CHILD_CAP, _CHILD_RATE)
    assert r2.granted
    s2 = await led.settle("r2", "t", 1500.0)
    assert s2.outcome == "settled" and s2.delta == 1400.0
    assert s2.debt == pytest.approx(540.0)  # 1400 − 860 available
    assert st._buckets[("t", 1000.0, _FILL)][0] == pytest.approx(0.0)
    # The next reserve must cover the debt first — empty budget: denied.
    r3 = await led.reserve("r3", "t", "k", 10, 1000.0, _FILL,
                           _CHILD_CAP, _CHILD_RATE)
    assert not r3.granted and r3.debt == pytest.approx(540.0)
    assert led.debt_denials == 1


def test_ledger_debt_collected_once_budget_refills():
    run(_debt_refill_body())


async def _debt_refill_body():
    clock = ManualClock()
    st = InProcessBucketStore(clock=clock)
    led, _t = _ledger(st)
    await led.reserve("r1", "t", "k", 100, 1000.0, 50.0,
                      _CHILD_CAP, _CHILD_RATE)
    await led.settle("r1", "t", 1500.0)
    assert led.debts()["t"] > 0
    clock.advance_seconds(120.0)  # refill the tenant bucket fully
    r = await led.reserve("r2", "t", "k", 10, 1000.0, 50.0,
                          _CHILD_CAP, _CHILD_RATE)
    # Debt paid down from the refilled budget, then the reserve admits.
    assert r.granted and r.debt == 0.0
    assert led.debts() == {}
    assert led.debt_tokens_collected > 0


def test_ledger_idempotency_under_retry():
    run(_idem_body())


async def _idem_body():
    st = InProcessBucketStore(clock=ManualClock())
    led, _t = _ledger(st)
    r1 = await led.reserve("r1", "t", "k", 100, 1000.0, _FILL,
                           _CHILD_CAP, _CHILD_RATE)
    # A post-send retry of a GRANTED reserve replays the decision —
    # the tenant balance moves exactly once.
    r1b = await led.reserve("r1", "t", "k", 100, 1000.0, _FILL,
                            _CHILD_CAP, _CHILD_RATE)
    assert r1b.granted and r1b.duplicate
    assert st._buckets[("t", 1000.0, _FILL)][0] == pytest.approx(900.0)
    s1 = await led.settle("r1", "t", 30.0)
    s1b = await led.settle("r1", "t", 30.0)
    assert s1.outcome == "settled" and s1b.outcome == "duplicate"
    assert (s1b.delta, s1b.refunded) == (s1.delta, s1.refunded)
    # Zero double-refunds: the balance reflects ONE settle.
    assert st._buckets[("t", 1000.0, _FILL)][0] == pytest.approx(970.0)
    # Unknown rid: counted no-op.
    s3 = await led.settle("ghost", "t", 10.0)
    assert s3.outcome == "unknown" and led.settle_unknown == 1
    # A reserve retry arriving after the settle replays granted too.
    r1c = await led.reserve("r1", "t", "k", 100, 1000.0, _FILL,
                            _CHILD_CAP, _CHILD_RATE)
    assert r1c.granted and r1c.duplicate
    assert st._buckets[("t", 1000.0, _FILL)][0] == pytest.approx(970.0)


def test_ledger_ttl_auto_settles_at_estimate():
    run(_ttl_body())


async def _ttl_body():
    from distributedratelimiting.redis_tpu.utils.flight_recorder import (
        FlightRecorder,
    )

    st = InProcessBucketStore(clock=ManualClock())
    fr = FlightRecorder(64)
    led, t = _ledger(st, default_ttl_s=5.0)
    led.flight_recorder = fr
    await led.reserve("r1", "t", "k", 100, 1000.0, _FILL,
                      _CHILD_CAP, _CHILD_RATE)
    await led.reserve("r2", "t", "k", 50, 1000.0, _FILL,
                      _CHILD_CAP, _CHILD_RATE, ttl_s=60.0)
    t[0] = 6.0
    assert led.expire() == 1  # r1 only; r2's explicit TTL holds
    assert led.ttl_expired == 1
    assert led.outstanding_by_tenant() == {"t": 50.0}
    # Auto-settle at estimate: no refund, the hold became the spend.
    assert st._buckets[("t", 1000.0, _FILL)][0] == pytest.approx(850.0)
    # Flight-recorded, and the late settle answers the dedup record.
    assert any(f["kind"] == "reservation"
               and f.get("event") == "ttl_expired"
               for f in fr.frames())
    s = await led.settle("r1", "t", 40.0)
    assert s.outcome == "duplicate"
    assert st._buckets[("t", 1000.0, _FILL)][0] == pytest.approx(850.0)


def test_ledger_bounded_denies_loudly():
    run(_bounded_body())


async def _bounded_body():
    st = InProcessBucketStore(clock=ManualClock())
    led, _t = _ledger(st, max_entries=2)
    for i in range(2):
        r = await led.reserve(f"r{i}", "t", "k", 1, 1000.0, _FILL,
                              _CHILD_CAP, _CHILD_RATE)
        assert r.granted
    r = await led.reserve("r9", "t", "k", 1, 1000.0, _FILL,
                          _CHILD_CAP, _CHILD_RATE)
    assert not r.granted and led.ledger_full_denials == 1


# -- review regressions ------------------------------------------------------

def test_debt_rows_dedup_on_abort_retry():
    """Review regression: a debt restored on abort and re-exported by
    the same-epoch retry must not DOUBLE at the new owner (whose copy
    of attempt 1's chunk already landed) — tagged debt rows apply once
    per (tag, tenant)."""
    run(_debt_dedup_body())


async def _debt_dedup_body():
    src = InProcessBucketStore(clock=ManualClock())
    dst = InProcessBucketStore(clock=ManualClock())
    led_src, _ = _ledger(src)
    led_dst, _ = _ledger(dst)
    led_src._debts["t"] = 500.0
    # Attempt 1: export ships, chunk lands at the destination.
    res1, debts1 = led_src.export_rows(lambda _t: True, tag="epoch:7")
    assert led_src.debts() == {}
    led_dst.restore_rows(res1, debts1)
    assert led_dst.debts()["t"] == 500.0
    # Abort: the stash comes home to the source.
    led_src.restore_rows(res1, debts1)
    assert led_src.debts()["t"] == 500.0
    # Attempt 2 (same epoch): re-export + re-deliver — the destination
    # already holds attempt 1's copy and must skip it.
    res2, debts2 = led_src.export_rows(lambda _t: True, tag="epoch:7")
    led_dst.restore_rows(res2, debts2)
    assert led_dst.debts()["t"] == 500.0  # not 1000
    # A LATER legitimate migration (new episode) merges normally.
    led_src._debts["t"] = 100.0
    _res3, debts3 = led_src.export_rows(lambda _t: True, tag="epoch:9")
    led_dst.restore_rows([], debts3)
    assert led_dst.debts()["t"] == 600.0


def test_fallback_charge_floors_at_default_estimate():
    """Review regression: the degraded/old-peer reserve fallbacks must
    not admit an estimate-less stream for a 1-token charge — the
    shared helper floors at the ledger's DEFAULT_ESTIMATE."""
    from distributedratelimiting.redis_tpu.runtime.reservations import (
        DEFAULT_ESTIMATE,
        fallback_charge,
    )

    assert fallback_charge(None) == int(DEFAULT_ESTIMATE)
    assert fallback_charge(0) == int(DEFAULT_ESTIMATE)
    assert fallback_charge(12.3) == 13
    run(_fallback_charge_wire_body())


async def _fallback_charge_wire_body():
    backing = InProcessBucketStore(clock=ManualClock())
    srv = BucketStoreServer(backing)
    real = srv.handle_frame_body

    async def old_peer(body, arrival_s=None):
        if len(body) >= 6 and (body[5] & 0x3F) in (wire.OP_RESERVE,
                                                   wire.OP_SETTLE):
            from distributedratelimiting.redis_tpu.runtime.server import (
                _recover_seq,
            )

            return wire.encode_response(_recover_seq(body),
                                        wire.RESP_ERROR,
                                        f"unknown op {body[5] & 0x3F}")
        return await real(body, arrival_s=arrival_s)

    srv.handle_frame_body = old_peer
    await srv.start()
    st = RemoteBucketStore(address=(srv.host, srv.port),
                           coalesce_requests=False)
    try:
        r = await st.reserve("fc1", "t", "k", None, 1000.0, _FILL,
                             _CHILD_CAP, _CHILD_RATE)
        # The old-peer fallback charged DEFAULT_ESTIMATE, not 1.
        assert r.granted and r.reserved == 64.0
        assert backing._buckets[("t", 1000.0, _FILL)][0] == \
            pytest.approx(936.0)
    finally:
        await st.aclose()
        await srv.aclose()


def test_chunk_entries_sizes_reservation_rows(tmp_path):
    """Review regression: chunk_entries must size a reservation row by
    ALL its string fields (tenant + rid + child key) — long child keys
    otherwise packed chunks past MAX_FRAME."""
    long_key = "k" * 60_000
    rows = [["t", f"rid{i}", long_key, 10.0, 1e6, 1e-9, 1e3, 1e-9, 0,
             30.0] for i in range(40)]
    chunks = placement.chunk_entries({"reservations": rows})
    assert len(chunks) > 1  # 40 × 60KB cannot be one frame-sized chunk
    import json as _json
    for c in chunks:
        assert len(_json.dumps(c)) < 800_000


# -- fp-store negative-debit pin (satellite bugfix sweep) --------------------

def test_fp_store_debit_many_direct_including_refund():
    """Satellite: the fp-store saturating debit lane, pinned DIRECTLY
    (PR 9 exercised it only via hierarchical deny-refund — which in
    fact crashed: _FpTable had no _debit_launch until round 13's
    fp_debit_batch kernel). Positive debits saturate with the clamped
    shortfall; NEGATIVE amounts credit back (the refund primitive the
    reservation settle and the hierarchical deny-refund share), with
    the capacity clamp applying at the next refill."""
    from distributedratelimiting.redis_tpu.runtime.fp_store import (
        FingerprintBucketStore,
    )

    async def body():
        st = FingerprintBucketStore(n_slots=256)
        await st.connect()
        await st.acquire("k1", 40, 100.0, _FILL)
        rem, short = await st.debit_many(["k1"], [30.0], 100.0, _FILL)
        assert rem[0] == pytest.approx(30.0) and short[0] == 0.0
        # Saturating: the debit finds only 30, reports 470 shortfall.
        rem, short = await st.debit_many(["k1"], [500.0], 100.0, _FILL)
        assert rem[0] == 0.0 and short[0] == pytest.approx(470.0)
        # Negative amount = refund; init-on-miss debits a fresh key
        # from capacity (the InProcess debit_many semantics).
        rem, short = await st.debit_many(["k1"], [-25.0], 100.0, _FILL)
        assert rem[0] == pytest.approx(25.0) and short[0] == 0.0
        rem, short = await st.debit_many(["fresh"], [10.0], 100.0,
                                         _FILL)
        assert rem[0] == pytest.approx(90.0) and short[0] == 0.0
        await st.aclose()

    run(body())


def test_fp_store_hier_deny_refund_regression():
    """The PR-9 deny-refund path on the fp store (base compose: parent
    granted, child denied → parent refunded through debit_many with a
    negative amount) used to raise AttributeError — _FpTable had no
    _debit_launch. Pin the repaired behavior: the tenant bucket ends
    exactly where it started."""
    from distributedratelimiting.redis_tpu.runtime.fp_store import (
        FingerprintBucketStore,
    )

    async def body():
        st = FingerprintBucketStore(n_slots=256)
        await st.connect()
        r = await st.acquire_hierarchical("tenantA", "kk", 50,
                                          500.0, _FILL, 20.0, _FILL)
        assert not r.granted  # child cap 20 < 50
        assert st.peek_blocking("tenantA", 500.0, _FILL) == \
            pytest.approx(500.0)
        await st.aclose()

    run(body())


# -- wire lane + old-peer latch + stats-reset immunity -----------------------

def test_wire_reserve_settle_and_stats_reset_immunity():
    run(_wire_body())


async def _wire_body():
    backing = InProcessBucketStore(clock=ManualClock())
    async with BucketStoreServer(backing) as srv:
        st = RemoteBucketStore(address=(srv.host, srv.port),
                               coalesce_requests=False)
        try:
            r = await st.reserve("w1", "t", "k", 100, 1000.0, _FILL,
                                 _CHILD_CAP, _CHILD_RATE)
            assert r.granted and r.reserved == 100.0
            s = await st.settle("w1", "t", 25.0)
            assert s.outcome == "settled" and s.refunded == 75.0
            # A wire retry of the settle is the dedup no-op.
            s2 = await st.settle("w1", "t", 25.0)
            assert s2.outcome == "duplicate"
            # Server-side estimate from the prior: no estimate on the
            # wire → the tenant's settled history (25.0, interactive
            # p99) sizes the charge.
            r2 = await st.reserve("w2", "t", "k", None, 1000.0, _FILL,
                                  _CHILD_CAP, _CHILD_RATE)
            assert r2.granted and r2.reserved == 25.0
            # The satellite contract: stats(reset=True) clears latency
            # WINDOWS, never the reservation ledger (monotonic-counter
            # contract from PR 12).
            before = dict(srv.reservations.numeric_stats())
            stats = await st.stats(reset=True)
            assert stats["reservations"]["reserves"] == 2
            after = srv.reservations.numeric_stats()
            assert after == before
            assert srv.reservations.outstanding_tokens() == 25.0
            # The new families render.
            text = await st.metrics()
            assert 'drl_reservations_outstanding{tenant="t"}' in text
            assert "drl_reservation_reserves_total 2" in text
        finally:
            await st.aclose()


def test_old_peer_latches_acquire_fallback():
    """A server that does not speak the reservation lane answers the
    routable unknown-op error; the client latches once, reserves via
    plain acquire_hierarchical at the estimate, and settles become
    client-side no-ops — counted."""
    run(_old_peer_body())


async def _old_peer_body():
    backing = InProcessBucketStore(clock=ManualClock())
    srv = BucketStoreServer(backing)
    real = srv.handle_frame_body

    async def old_peer(body, arrival_s=None):
        if len(body) >= 6 and (body[5] & 0x3F) in (wire.OP_RESERVE,
                                                   wire.OP_SETTLE):
            from distributedratelimiting.redis_tpu.runtime.server import (
                _recover_seq,
            )

            return wire.encode_response(_recover_seq(body),
                                        wire.RESP_ERROR,
                                        f"unknown op {body[5] & 0x3F}")
        return await real(body, arrival_s=arrival_s)

    srv.handle_frame_body = old_peer
    await srv.start()
    st = RemoteBucketStore(address=(srv.host, srv.port),
                           coalesce_requests=False)
    try:
        r = await st.reserve("f1", "t", "k", 100, 1000.0, _FILL,
                             _CHILD_CAP, _CHILD_RATE)
        assert r.granted and r.fallback and r.reserved == 100.0
        assert not st._peer_reserve
        assert st.resilience_stats()["reserve_fallbacks"] == 1
        # The estimate was charged outright through the hier lane.
        assert backing._buckets[("t", 1000.0, _FILL)][0] == \
            pytest.approx(900.0)
        # Settle: client-side no-op (no hold exists server-side).
        s = await st.settle("f1", "t", 10.0)
        assert s.outcome == "fallback"
        assert st.resilience_stats()["reserve_fallbacks"] == 2
        assert backing._buckets[("t", 1000.0, _FILL)][0] == \
            pytest.approx(900.0)
    finally:
        await st.aclose()
        await srv.aclose()


# -- OP_CONFIG rebase re-homes outstanding reservations ----------------------

def test_config_rebase_rehomes_settles():
    run(_rebase_body())


async def _rebase_body():
    backing = InProcessBucketStore(clock=ManualClock())
    async with BucketStoreServer(backing) as srv:
        st = RemoteBucketStore(address=(srv.host, srv.port),
                               coalesce_requests=False)
        try:
            r = await st.reserve("c1", "t", "k", 100, 1000.0, _FILL,
                                 _CHILD_CAP, _CHILD_RATE)
            assert r.granted
            # Live mutation: tenant budget 1000 → 600. The commit
            # rebases the balance (600 − 100 spent = 500 in the new
            # table) through the rebase debit.
            v = await st.config_announce({
                "prepare": {"kind": "bucket", "old": [1000.0, _FILL],
                            "new": [600.0, _FILL]},
                "version": 1})
            assert v == 0  # prepared, not yet committed
            assert await st.config_announce({"commit": 1}) == 1
            assert backing._buckets[("t", 600.0, _FILL)][0] == \
                pytest.approx(500.0)
            # Settle AFTER the commit: the refund must land in the NEW
            # table (lazy re-home through the forwarding rules), and
            # the entry's retired config counts as re-homed.
            s = await st.settle("c1", "t", 30.0)
            assert s.outcome == "settled" and s.refunded == 70.0
            assert backing._buckets[("t", 600.0, _FILL)][0] == \
                pytest.approx(570.0)
            assert srv.reservations.rehomed >= 1
        finally:
            await st.aclose()


# -- live migration: ledger entries ride MIGRATE_PULL / PUSH -----------------

def test_migration_moves_ledger_and_reroutes_settles():
    run(_migration_body())


async def _migration_body():
    b1 = InProcessBucketStore(clock=ManualClock())
    b2 = InProcessBucketStore(clock=ManualClock())
    s1 = BucketStoreServer(b1)
    s2 = BucketStoreServer(b2)
    await s1.start()
    await s2.start()
    c1 = RemoteBucketStore(address=(s1.host, s1.port),
                           coalesce_requests=False)
    c2 = RemoteBucketStore(address=(s2.host, s2.port),
                           coalesce_requests=False)
    try:
        m0 = placement.PlacementMap.initial(2)
        tenant = next(f"t{i}" for i in range(64)
                      if m0.node_of(f"t{i}") == 0)
        await c1.placement_announce({"map": m0.to_dict(), "node_id": 0})
        await c2.placement_announce({"map": m0.to_dict(), "node_id": 1})
        r = await c1.reserve("m1", tenant, "k", 100, 1000.0, _FILL,
                             _CHILD_CAP, _CHILD_RATE)
        assert r.granted
        # Pull the tenant (an override split) off node 0: the export
        # carries the ledger entry alongside the bucket state.
        pulled = await c1.migrate_pull({"target_epoch": 1,
                                        "keys": [tenant],
                                        "window_s": 30.0})
        assert len(pulled["entries"]["reservations"]) == 1
        assert s1.reservations.outstanding_count() == 0
        # Parked mid-handoff: the settle defers (retry-safe — the op
        # is idempotent), it does NOT vanish into "unknown".
        with pytest.raises(wire.RemoteStoreError,
                           match="handoff in progress"):
            await c1.settle("m1", tenant, 40.0)
        applied = await c2.migrate_push({"target_epoch": 1, "batch": 1,
                                         "entries": pulled["entries"]})
        assert applied >= 1
        assert s2.reservations.outstanding_count() == 1
        m1 = m0.with_assignments(set_overrides={tenant: 1})
        await c1.placement_announce({"map": m1.to_dict(), "node_id": 0})
        await c2.placement_announce({"map": m1.to_dict(), "node_id": 1})
        # Old owner answers MOVED; the new owner settles with the
        # refund landing in ITS store (which received the balances).
        with pytest.raises(wire.RemoteStoreError,
                           match="placement moved"):
            await c1.settle("m1", tenant, 40.0)
        s = await c2.settle("m1", tenant, 40.0)
        assert s.outcome == "settled" and s.refunded == 60.0
        assert b2._buckets[(tenant, 1000.0, _FILL)][0] > 0
    finally:
        await c1.aclose()
        await c2.aclose()
        await s1.aclose()
        await s2.aclose()


def test_migration_abort_restores_ledger():
    run(_abort_body())


async def _abort_body():
    b1 = InProcessBucketStore(clock=ManualClock())
    s1 = BucketStoreServer(b1)
    await s1.start()
    c1 = RemoteBucketStore(address=(s1.host, s1.port),
                           coalesce_requests=False)
    try:
        m0 = placement.PlacementMap.initial(1)
        tenant = "t0"
        await c1.placement_announce({"map": m0.to_dict(), "node_id": 0})
        await c1.reserve("a1", tenant, "k", 100, 1000.0, _FILL,
                         _CHILD_CAP, _CHILD_RATE)
        await c1.migrate_pull({"target_epoch": 1, "keys": [tenant],
                               "window_s": 30.0})
        assert s1.reservations.outstanding_count() == 0
        await c1.placement_announce({"abort_epoch": 1})
        # The entry came home; the settle reconciles locally.
        assert s1.reservations.outstanding_count() == 1
        s = await c1.settle("a1", tenant, 60.0)
        assert s.outcome == "settled" and s.refunded == 40.0
    finally:
        await c1.aclose()
        await s1.aclose()


# -- THE seeded streaming soak (acceptance) ----------------------------------

_TENANTS = {"tenant:a": 3_000.0, "tenant:b": 2_000.0}
#: Mid-soak live mutation: tenant:a's budget shrinks (the rebase debit
#: re-homes the spent balance; outstanding reservations settle into the
#: new table through the lazy re-home).
_NEW_A_CAP = 2_400.0

_RULES = {
    "client.connect": (
        FaultRule("reset", probability=0.08),
        FaultRule("delay", probability=0.2, delay_s=0.001,
                  jitter_s=0.002),
    ),
    "server.dispatch": (
        FaultRule("delay", probability=0.05, delay_s=0.002,
                  jitter_s=0.002),
    ),
}


def _soak_schedule(seed: int, n_rows: int = 220):
    """Deterministic streaming schedule: (tenant, key, actual cost,
    estimate = actual × LogNormal(0, 0.55), priority, dies) rows. A
    ``dies`` row never settles — its TTL auto-settle is part of the
    audit."""
    rng = np.random.default_rng(seed)
    rows = []
    for i in range(n_rows):
        tenant = "tenant:a" if rng.random() < 0.6 else "tenant:b"
        key = f"{tenant}/u{rng.zipf(1.5) % 30}"
        actual = float(min(max(rng.lognormal(3.2, 1.1), 1.0), 2000.0))
        estimate = float(max(actual * rng.lognormal(0.0, 0.55), 1.0))
        prio = int(rng.random() < 0.3)  # 70% interactive, 30% batch
        dies = rng.random() < 0.05
        rows.append((tenant, key, actual, estimate, prio, dies))
    return rows


async def _soak_once(seed: int) -> dict:
    """One full soak run; returns the audit summary (compared across
    runs for determinism)."""
    rows = _soak_schedule(seed)
    inj = FaultInjector(seed, _RULES)
    faults.install(inj)
    backing_a = InProcessBucketStore(clock=ManualClock())
    backing_b = InProcessBucketStore(clock=ManualClock())
    srv_a = BucketStoreServer(backing_a)
    srv_b = BucketStoreServer(backing_b)
    await srv_a.start()
    await srv_b.start()
    client = RemoteBucketStore(address=(srv_a.host, srv_a.port),
                               coalesce_requests=False,
                               resilience_seed=seed)
    successor = RemoteBucketStore(address=(srv_b.host, srv_b.port),
                                  coalesce_requests=False,
                                  resilience_seed=seed + 1)
    grants: list[bool] = []
    settled: dict[str, float] = {t: 0.0 for t in _TENANTS}
    open_rids: list[tuple[str, str, float]] = []  # (rid, tenant, actual)
    dead_rids: list[tuple[str, str]] = []
    settled_rids: list[tuple[str, str]] = []

    async def drive(store, rows_slice, offset, hold=()):
        """``hold`` rows reserve but defer their settle — the cross-
        mutation holds whose settle-time config re-home the soak
        audits."""
        for j, (tenant, key, actual, estimate, prio, dies) in \
                enumerate(rows_slice):
            i = offset + j
            rid = f"r{i}"
            cap = _TENANTS[tenant]
            r = await store.reserve(rid, tenant, key, estimate, cap,
                                    _FILL, _CHILD_CAP, _CHILD_RATE,
                                    priority=prio)
            grants.append(bool(r.granted))
            if not r.granted:
                continue
            if dies:
                dead_rids.append((rid, tenant))
                continue
            if i in hold:
                open_rids.append((rid, tenant, actual))
                continue
            s = await store.settle(rid, tenant, actual)
            if s.outcome == "settled":
                settled[tenant] += actual
                settled_rids.append((rid, tenant))

    try:
        # Phase 1: healthy, under wire chaos. Rows 105-109 hold their
        # settles open all the way into the drain window (the relay
        # audit); rows 110-119 hold across the config mutation (the
        # re-home audit).
        await drive(client, rows[:120], 0, hold=set(range(105, 120)))
        # Differential identity over the store's OWN bucket records
        # (fill ≈ 0, ManualClock → zero refill; exact):
        #   cap − balance == outstanding + settled_actual − debt.
        led = srv_a.reservations
        for tenant, cap in _TENANTS.items():
            entry = backing_a._buckets.get((tenant, cap, _FILL))
            balance = entry[0] if entry is not None else cap
            lhs = cap - balance
            rhs = (led.outstanding_by_tenant().get(tenant, 0.0)
                   + settled[tenant]
                   - led.debts().get(tenant, 0.0))
            assert lhs == pytest.approx(rhs, abs=1e-3), tenant

        # Phase 2: live OP_CONFIG mutation on tenant:a's budget.
        await client.config_announce({
            "prepare": {"kind": "bucket",
                        "old": [_TENANTS["tenant:a"], _FILL],
                        "new": [_NEW_A_CAP, _FILL]},
            "version": 1})
        await client.config_announce({"commit": 1})
        # The held (pre-mutation) reservations from rows 110+ settle
        # NOW: their recorded configs are retired — the ledger's lazy
        # re-home routes every refund/extra-debit into the rebased
        # table. Rows 105-109 stay open for the drain relay.
        for rid, tenant, actual in list(open_rids):
            if int(rid[1:]) < 110:
                continue
            s = await client.settle(rid, tenant, actual)
            if s.outcome == "settled":
                settled[tenant] += actual
                settled_rids.append((rid, tenant))
            open_rids.remove((rid, tenant, actual))
        await drive(client, rows[120:170], 120)

        # Phase 3: drain-and-handoff to the successor mid-stream, with
        # the held reservations (rows 105-109) still outstanding —
        # their ledger entries ship with the export, and settles
        # during the window RELAY through the draining server.
        still_open = list(open_rids)
        open_rids.clear()
        assert still_open, "schedule lost its drain-open holds"
        shutdown_task = asyncio.ensure_future(
            srv_a.shutdown(successor, window_s=1.0))
        for _ in range(300):
            if srv_a._drain_envelope is not None:
                break
            await asyncio.sleep(0.01)
        assert srv_a._drain_envelope is not None
        # Settle two outstanding rids THROUGH the draining server: the
        # relay reaches the successor's migrated ledger.
        relayed = 0
        for rid, tenant, actual in still_open[:2]:
            assert rid in srv_b.reservations._entries, (
                rid, "drain export did not migrate the hold")
            s = await client.settle(rid, tenant, actual)
            if s.outcome == "settled":
                settled[tenant] += actual
                settled_rids.append((rid, tenant))
                relayed += 1
        assert relayed == 2
        await shutdown_task
        # Phase 4: the fleet's LB switched to the successor; the
        # remaining open rids settle there directly.
        for rid, tenant, actual in still_open[2:]:
            if rid in srv_b.reservations._entries:
                s = await successor.settle(rid, tenant, actual)
                if s.outcome == "settled":
                    settled[tenant] += actual
                    settled_rids.append((rid, tenant))

        # Audit: zero double-settles under post-send retry — re-settle
        # a sample of settled rids; refunded totals must not move.
        led_b = srv_b.reservations
        refunded_before = led_b.refunded_tokens + led.refunded_tokens
        for rid, tenant in settled_rids[:20]:
            target = (led_b if rid in led_b._settled else led)
            s = await target.settle(rid, tenant, 99999.0)
            assert s.outcome == "duplicate", rid
        assert led_b.refunded_tokens + led.refunded_tokens == \
            pytest.approx(refunded_before)

        # Audit: TTL auto-settle fires for the killed clients whose
        # reservations migrated to the successor.
        migrated_dead = [rid for rid, _t in dead_rids
                         if rid in led_b._entries]
        if migrated_dead:
            led_b._clock = (lambda base=led_b._clock: base() + 1e6)
            assert led_b.expire() >= len(migrated_dead)
            assert led_b.ttl_expired >= len(migrated_dead)
            for rid in migrated_dead:
                assert rid not in led_b._entries

        # Audit: the epsilon envelope. Settled spend per tenant minus
        # carried debt stays inside the LARGEST budget the tenant ever
        # had plus one fair-share envelope (drain-window serving).
        for tenant, cap in _TENANTS.items():
            env = headroom_budget(cap, fraction=0.5, min_budget=1.0)
            debt = (led.debts().get(tenant, 0.0)
                    + led_b.debts().get(tenant, 0.0))
            assert settled[tenant] - debt <= cap + env + 1e-6, tenant

        return {
            "grants": grants,
            "settled": dict(settled),
            "reserves": led.reserves + led_b.reserves,
            "settles": led.settles + led_b.settles,
            "refunded": round(led.refunded_tokens
                              + led_b.refunded_tokens, 3),
            "debt_created": round(led.debt_tokens_created
                                  + led_b.debt_tokens_created, 3),
            "rehomed": led.rehomed + led_b.rehomed,
            "relayed": relayed,
            "expired": led_b.ttl_expired,
        }
    finally:
        faults.uninstall()
        await client.aclose()
        await successor.aclose()
        await srv_a.aclose()
        await srv_b.aclose()


def test_reservation_streaming_soak():
    """Acceptance (ISSUE 13): the seeded streaming soak — reserve/
    stream/settle under wire chaos with a mid-soak drain-and-handoff
    and a live OP_CONFIG mutation; settled tokens reconcile exactly
    against the stores' own bucket records and stay inside budget +
    epsilon; zero double-settles; TTL auto-settle fires; bit-for-bit
    seed determinism."""
    run(_soak_acceptance())


async def _soak_acceptance():
    out1 = await _soak_once(SEED)
    # The schedule exercises every lane: grants and denials, refunds
    # AND debt, config re-homing, relayed settles.
    assert any(out1["grants"]) and not all(out1["grants"])
    assert out1["refunded"] > 0 and out1["settles"] > 0
    assert out1["rehomed"] >= 1  # pre-mutation holds settled post-commit
    # Determinism: the same seed replays the same grant sequence and
    # the same ledger accounting, bit for bit.
    out2 = await _soak_once(SEED)
    assert out2 == out1
