"""Global quota federation (ISSUE 15): the WAN lease ledger's unit
surface plus THE seeded 3-region soak.

The soak is the acceptance differential: a deterministic 3-region
traffic schedule over real wire servers with wire chaos on the
federation seams, a FULL partition of one region spanning more than two
lease periods (slice serving → monotonic expiry → fair-share envelope,
never unlimited, never hard-down), a home crash/restart recovering
lease state from the v4 checkpoint chain, slice changes applied through
the live OP_CONFIG two-phase lane (regional clients chase the routable
"config moved" error), demand-proportional lend/borrow across renews,
and a differential audit over the stores' own admission records:
Σ regional admits ≤ global cap + ε(RTT, lease_len) across heal, with
the home's final accounting EXACT against every region's reported
total. The same seed reproduces the identical grant sequence and
federation action schedule bit for bit.
``make federation-soak SEED=…`` replays any schedule
(DRL_FEDERATION_SEED)."""

from __future__ import annotations

import asyncio
import os

import numpy as np
import pytest

from distributedratelimiting.redis_tpu.runtime import checkpoint, wire
from distributedratelimiting.redis_tpu.runtime.clock import ManualClock
from distributedratelimiting.redis_tpu.runtime.controller import (
    Controller,
    ControllerConfig,
)
from distributedratelimiting.redis_tpu.runtime.federation import (
    RegionFederation,
    degraded_config,
    federation_epsilon,
    slice_applier,
)
from distributedratelimiting.redis_tpu.runtime.remote import (
    RemoteBucketStore,
)
from distributedratelimiting.redis_tpu.runtime.server import (
    BucketStoreServer,
)
from distributedratelimiting.redis_tpu.runtime.store import (
    InProcessBucketStore,
)
from distributedratelimiting.redis_tpu.utils import faults
from distributedratelimiting.redis_tpu.utils.faults import (
    FaultInjector,
    FaultRule,
    SkewedClock,
)
from distributedratelimiting.redis_tpu.utils.flight_recorder import (
    FlightRecorder,
)

SEED = int(os.environ.get("DRL_FEDERATION_SEED", "20260804"))

TENANT = "tenant:g"
G_CAP, G_RATE = 600.0, 0.0     # pure-burst global budget: exact audits
TTL = 6.0


def run(coro):
    return asyncio.run(coro)


class Mono:
    """Manual monotonic clock (float seconds) for lease TTLs."""

    def __init__(self, t: float = 0.0) -> None:
        self.t = t

    def __call__(self) -> float:
        return self.t

    def advance(self, dt: float) -> None:
        self.t += dt


def _ledger(store=None, **kw):
    store = store or InProcessBucketStore(clock=ManualClock())
    mono = kw.pop("mono", None) or Mono()
    led = store.federation_ledger(clock=mono, default_ttl_s=TTL, **kw)
    return store, led, mono


def _balance(store, key=TENANT, cap=G_CAP, rate=G_RATE) -> float:
    entry = store._buckets.get((key, cap, rate))
    return float(entry[0]) if entry is not None else cap


# -- unit surface ------------------------------------------------------------

def test_degraded_config_never_unlimited_never_harddown():
    cap, rate = degraded_config(200.0, 10.0)
    assert cap == 100.0 and rate == 5.0          # the envelope family
    cap, rate = degraded_config(1.0, 0.0)
    assert cap == 1.0 and rate == 0.0            # floored, not zero
    assert degraded_config(0.0, 0.0)[0] >= 1.0   # never hard-down
    # The epsilon model grows with lease length and partition window.
    e1 = federation_epsilon(3, 200.0, 10.0, 3.0)
    e2 = federation_epsilon(3, 200.0, 10.0, 3.0, partition_s=12.0)
    assert 0 < e1 < e2


def test_lease_renew_reclaim_cycle_exact_accounting():
    run(_cycle_body())


async def _cycle_body():
    store, led, mono = _ledger()
    r = await led.lease({"region": "r0", "lease_id": "L1",
                         "tenant": TENANT, "demand": 4.0,
                         "global_cap": G_CAP, "global_rate": G_RATE})
    assert r["granted"] and r["epoch"] == 1
    # New-lease fairness: at most half the free pool.
    assert r["share"] == pytest.approx(0.5)
    assert r["slice"][0] == 300.0
    assert led.outstanding_leases() == 1
    # Renew reports a monotonic total; the delta lands in the home
    # bucket through the saturating debit — exact with rate 0.
    n1 = await led.renew({"region": "r0", "lease_id": "L1",
                          "tenant": TENANT, "total": 40.0,
                          "demand": 4.0})
    assert n1["outcome"] == "ok" and n1["charged"] == 40.0
    assert _balance(store) == pytest.approx(G_CAP - 40.0)
    # A REPLAYED renew is a zero delta — absorbing by construction.
    n2 = await led.renew({"region": "r0", "lease_id": "L1",
                          "tenant": TENANT, "total": 40.0,
                          "demand": 4.0})
    assert n2["charged"] == 0.0
    assert _balance(store) == pytest.approx(G_CAP - 40.0)
    # Reclaim charges the final delta and frees the share.
    rc = await led.reclaim({"region": "r0", "lease_id": "L1",
                            "tenant": TENANT, "total": 55.0})
    assert rc["outcome"] == "reclaimed" and rc["charged"] == 15.0
    assert led.outstanding_leases() == 0
    assert _balance(store) == pytest.approx(G_CAP - 55.0)


def test_lease_idempotent_by_lease_id():
    run(_lease_idem_body())


async def _lease_idem_body():
    store, led, mono = _ledger()
    r1 = await led.lease({"region": "r0", "lease_id": "L1",
                          "tenant": TENANT, "demand": 1.0,
                          "global_cap": G_CAP, "global_rate": G_RATE})
    r2 = await led.lease({"region": "r0", "lease_id": "L1",
                          "tenant": TENANT, "demand": 1.0,
                          "global_cap": G_CAP, "global_rate": G_RATE})
    assert r2["duplicate"] and r2["epoch"] == r1["epoch"]
    assert r2["slice"] == r1["slice"]
    assert led.leases_granted == 1 and led.lease_duplicates == 1
    assert led.outstanding_leases() == 1


def test_reclaim_retry_at_most_once_audit():
    """The satellite audit: a retried OP_FED_RECLAIM replays the
    recorded result — zero second charge, zero second share-free, and
    across the heal path at most ONE refund per lease id."""
    run(_reclaim_audit_body())


async def _reclaim_audit_body():
    store, led, mono = _ledger()
    await led.lease({"region": "r0", "lease_id": "L1",
                     "tenant": TENANT, "demand": 1.0,
                     "global_cap": G_CAP, "global_rate": G_RATE})
    rc1 = await led.reclaim({"region": "r0", "lease_id": "L1",
                             "tenant": TENANT, "total": 30.0})
    bal = _balance(store)
    rc2 = await led.reclaim({"region": "r0", "lease_id": "L1",
                             "tenant": TENANT, "total": 30.0})
    assert rc1["outcome"] == "reclaimed"
    assert rc2["outcome"] == "duplicate"
    assert rc2["charged"] == rc1["charged"]
    assert _balance(store) == bal              # zero side effects
    assert led.reclaims == 1 and led.reclaim_duplicates == 1
    # Heal-path edition: expire a second lease conservatively, then
    # reclaim it TWICE — one refund, the duplicate replays.
    await led.lease({"region": "r0", "lease_id": "L2",
                     "tenant": TENANT, "demand": 1.0,
                     "global_cap": G_CAP, "global_rate": G_RATE})
    mono.advance(TTL + 0.1)
    assert led.expire() == 1
    h1 = await led.reclaim({"region": "r0", "lease_id": "L2",
                            "tenant": TENANT, "total": 10.0})
    assert h1["outcome"] == "reclaimed" and h1["refunded"] > 0
    bal = _balance(store)
    h2 = await led.reclaim({"region": "r0", "lease_id": "L2",
                            "tenant": TENANT, "total": 10.0})
    assert h2["outcome"] == "duplicate"
    assert _balance(store) == bal              # at-most-once refund


def test_home_expiry_conservative_then_heal_refunds_exactly():
    run(_conservative_body())


async def _conservative_body():
    # resize_threshold huge: the slice must stay put so the
    # conservative-charge arithmetic below is exact by inspection.
    store, led, mono = _ledger(resize_threshold=1e9)
    r = await led.lease({"region": "r2", "lease_id": "L1",
                         "tenant": TENANT, "demand": 1.0,
                         "global_cap": G_CAP, "global_rate": G_RATE})
    slice_cap = r["slice"][0]
    await led.renew({"region": "r2", "lease_id": "L1",
                     "tenant": TENANT, "total": 20.0, "demand": 1.0})
    # Partition: no renew for > TTL on the home's MONOTONIC clock.
    mono.advance(TTL + 1.0)
    assert led.expire() == 1
    await led._settle_expired()
    # Conservative: the unreported slice entitlement is presumed
    # fully spent — the global bound holds THROUGH the partition.
    assert _balance(store) == pytest.approx(G_CAP - 20.0 - slice_cap)
    # Heal: the region's true total reconciles; the over-charge
    # refunds exactly (a refund can only under-credit, and here the
    # arithmetic is exact).
    h = await led.renew({"region": "r2", "lease_id": "L1",
                         "tenant": TENANT, "total": 50.0,
                         "demand": 1.0})
    assert h["outcome"] == "expired"
    assert h["refunded"] == pytest.approx(slice_cap - 30.0)
    assert _balance(store) == pytest.approx(G_CAP - 50.0)
    assert led.heals == 1


# -- lease TTL under injected clock skew -------------------------------------

def test_lease_ttl_immune_to_clock_skew():
    """The satellite contract: the utils/faults.py clock-skew seam
    applied to the federation renew path must show expiry keyed on
    MONOTONIC time — a skewed wall clock neither extends nor
    prematurely kills a lease, on either end."""
    run(_skew_body())


async def _skew_body():
    inj = FaultInjector(SEED, {"federation.renew": (
        FaultRule(kind=faults.CLOCK_SKEW, skew_s=3600.0),)})
    skew = inj.clock_skew("federation.renew")
    assert skew == 3600.0
    import time as _time

    wall = SkewedClock(type("W", (), {"now": staticmethod(_time.time)})(),
                       skew)
    store = InProcessBucketStore(clock=ManualClock())
    mono = Mono()
    led = store.federation_ledger(clock=mono, wall=wall.now,
                                  default_ttl_s=TTL)
    await led.lease({"region": "r0", "lease_id": "L1",
                     "tenant": TENANT, "demand": 1.0,
                     "global_cap": G_CAP, "global_rate": G_RATE})
    # +1h of wall skew, ZERO monotonic elapse: nothing may expire
    # (a skewed wall clock must not prematurely kill the lease).
    assert led.expire() == 0
    assert led.outstanding_leases() == 1
    # Renew under the skewed wall: the TTL re-arms on monotonic time.
    mono.advance(TTL * 0.5)
    n = await led.renew({"region": "r0", "lease_id": "L1",
                         "tenant": TENANT, "total": 0.0,
                         "demand": 1.0})
    assert n["outcome"] == "ok"
    # Monotonic elapse past the TTL expires it REGARDLESS of the wall
    # clock (skew cannot extend the lease either).
    mono.advance(TTL + 0.1)
    assert led.expire() == 1
    assert led.outstanding_leases() == 0
    # Region side: the agent's expiry/degrade decisions are monotonic
    # too — wall skew alone never degrades, monotonic expiry does.
    agent_mono = Mono()
    agent = RegionFederation(
        "r0", led, tenants={TENANT: (G_CAP, G_RATE)},
        ttl_s=TTL, clock=agent_mono, wall=wall.now)
    await agent.tick()
    assert agent.leases_acquired == 1
    assert not agent.degraded(TENANT)
    await agent.tick()          # wall skew present, no mono elapse
    assert not agent.degraded(TENANT)
    agent_mono.advance(TTL + 0.1)
    # The home would happily renew (its lease is fresh) — but the
    # REGION's own monotonic expiry fires first inside the tick, and
    # the subsequent renew heals it in the same round.
    summary = await agent.tick()
    assert summary["degraded"] == 1
    assert agent.degraded_entries == 1


# -- region agent: partition → envelope → heal -------------------------------

def test_region_partition_degrades_to_envelope_then_heals():
    run(_degrade_body())


async def _degrade_body():
    store, led, home_mono = _ledger()
    mono = Mono()
    applied: list[tuple] = []

    async def apply_slice(tenant, old, new):
        applied.append((old, new))

    agent = RegionFederation(
        "r1", led, tenants={TENANT: (G_CAP, G_RATE)},
        apply_slice=apply_slice, ttl_s=TTL, clock=mono)
    await agent.tick()
    assert agent.slice(TENANT) is not None
    slice_cfg = agent.slice(TENANT)
    # Partition: every WAN call fails (the home handle raises).
    broken = agent.home

    class _Down:
        async def lease(self, _p):
            raise ConnectionResetError("wan down")
        fed_lease = fed_renew = fed_reclaim = None

        async def renew(self, _p):
            raise ConnectionResetError("wan down")

        async def reclaim(self, _p):
            raise ConnectionResetError("wan down")

    agent.home = _Down()
    mono.advance(TTL * 0.6)
    await agent.tick()                       # renew due → fails, counted
    assert agent.renew_failures >= 1 and agent.partition_errors >= 1
    assert not agent.degraded(TENANT)        # still inside the lease
    mono.advance(TTL)
    await agent.tick()                       # past expiry → degrade
    assert agent.degraded(TENANT)
    env = agent.slice(TENANT)
    assert env == degraded_config(*slice_cfg)
    assert env[0] >= 1.0                     # never hard-down
    assert env[0] <= slice_cfg[0]            # never unlimited
    # Heal: the WAN returns; home expired the lease meanwhile.
    home_mono.advance(2 * TTL + 1.0)
    agent.home = broken
    await agent.tick()                       # renew → "expired" → drop
    await agent.tick()                       # fresh lease → heal
    assert not agent.degraded(TENANT)
    assert agent.heals >= 1
    assert agent.slice(TENANT)[0] >= env[0]
    assert applied[-1][1] == agent.slice(TENANT)


# -- wire end-to-end + observability surfaces --------------------------------

def test_wire_federation_end_to_end_with_metrics_and_flight():
    run(_wire_body())


async def _wire_body():
    backing = InProcessBucketStore(clock=ManualClock())
    mono = Mono()
    backing.federation_ledger(clock=mono, default_ttl_s=TTL)
    async with BucketStoreServer(backing) as srv:
        st = RemoteBucketStore(address=(srv.host, srv.port),
                               coalesce_requests=False)
        try:
            r = await st.fed_lease({"region": "r0", "lease_id": "W1",
                                    "tenant": TENANT, "demand": 2.0,
                                    "global_cap": G_CAP,
                                    "global_rate": G_RATE})
            assert r["granted"] and r["slice"][0] == 300.0
            n = await st.fed_renew({"region": "r0", "lease_id": "W1",
                                    "tenant": TENANT, "total": 25.0,
                                    "demand": 2.0})
            assert n["outcome"] == "ok" and n["charged"] == 25.0
            # OP_STATS carries the home section; stats(reset=True)
            # never touches the monotonic federation counters.
            before = dict(srv.federation.numeric_stats())
            stats = await st.stats(reset=True)
            fed = stats["federation"]
            assert fed["leases_granted"] == 1 and fed["renews"] == 1
            assert fed["tenants"][TENANT]["leases"]["r0"][
                "reported_total"] == 25.0
            assert srv.federation.numeric_stats() == before
            # The OpenMetrics families render on both surfaces.
            text = await st.metrics()
            assert "drl_federation_leases_granted_total 1" in text
            assert (f'drl_federation_slice_share{{tenant="{TENANT}",'
                    'region="r0"}' in text)
            # Region-side families render once an agent is attached.
            agent = RegionFederation(
                "rX", st, tenants={TENANT: (G_CAP, G_RATE)},
                ttl_s=TTL, clock=Mono())
            srv.federation_agent = agent
            text = await st.metrics()
            assert "drl_federation_region_renews_total 0" in text
            # Flight recorder: lease events under the REGISTERED kind.
            frames = srv.flight_recorder.frames(kind="federation")
            assert any(f["event"] == "lease_granted" for f in frames)
            rc = await st.fed_reclaim({"region": "r0",
                                       "lease_id": "W1",
                                       "tenant": TENANT,
                                       "total": 25.0})
            assert rc["outcome"] == "reclaimed"
            rc2 = await st.fed_reclaim({"region": "r0",
                                        "lease_id": "W1",
                                        "tenant": TENANT,
                                        "total": 25.0})
            assert rc2["outcome"] == "duplicate"
        finally:
            await st.aclose()


def test_old_home_latches_partition_posture():
    """A home that does not speak the federation lane answers the
    routable unknown-op error: the client latches once and every
    federation call answers {"fallback": True} — the region treats it
    exactly like a partition (keep serving, degrade at expiry)."""
    run(_old_home_body())


async def _old_home_body():
    backing = InProcessBucketStore(clock=ManualClock())
    srv = BucketStoreServer(backing)
    real = srv.handle_frame_body

    async def old_peer(body, arrival_s=None):
        if len(body) >= 6 and (body[5] & 0x3F) in (
                wire.OP_FED_LEASE, wire.OP_FED_RENEW,
                wire.OP_FED_RECLAIM):
            from distributedratelimiting.redis_tpu.runtime.server import (
                _recover_seq,
            )

            return wire.encode_response(_recover_seq(body),
                                        wire.RESP_ERROR,
                                        f"unknown op {body[5] & 0x3F}")
        return await real(body, arrival_s=arrival_s)

    srv.handle_frame_body = old_peer
    await srv.start()
    st = RemoteBucketStore(address=(srv.host, srv.port),
                           coalesce_requests=False)
    try:
        r = await st.fed_lease({"region": "r0", "lease_id": "F1",
                                "tenant": TENANT, "demand": 1.0,
                                "global_cap": G_CAP,
                                "global_rate": G_RATE})
        assert r == {"fallback": True}
        assert not st._peer_fed
        # Latched: no further wire round trips, still the fallback.
        n = await st.fed_renew({"region": "r0", "lease_id": "F1",
                                "tenant": TENANT, "total": 0.0,
                                "demand": 1.0})
        assert n == {"fallback": True}
        assert st._fed_fallbacks == 2
        # The agent counts it and stays un-leased (degrade-at-expiry
        # posture is the lease-less region's only mode).
        agent = RegionFederation(
            "r0", st, tenants={TENANT: (G_CAP, G_RATE)},
            ttl_s=TTL, clock=Mono())
        await agent.tick()
        assert agent.fed_fallbacks == 1
        assert agent.slice(TENANT) is None
    finally:
        await st.aclose()
        await srv.aclose()


# -- lease state rides the v4 checkpoint chain -------------------------------

def test_lease_state_rides_checkpoint_chain(tmp_path):
    run(_checkpoint_body(tmp_path))


async def _checkpoint_body(tmp_path):
    path = str(tmp_path / "home.ckpt")
    store, led, mono = _ledger()
    # Realistic base: a few hundred ordinary buckets, so the lease
    # state's churn is a small DELTA (not a compaction trigger).
    for i in range(400):
        await store.acquire(f"pad:{i}", 1, 50.0, 0.0)
    await led.lease({"region": "r0", "lease_id": "C1",
                     "tenant": TENANT, "demand": 1.0,
                     "global_cap": G_CAP, "global_rate": G_RATE})
    await led.renew({"region": "r0", "lease_id": "C1",
                     "tenant": TENANT, "total": 12.0, "demand": 1.0})
    chain = checkpoint.SnapshotChain(path)
    chain.save(store)                    # full base
    await led.renew({"region": "r0", "lease_id": "C1",
                     "tenant": TENANT, "total": 30.0, "demand": 1.0})
    delta_path = chain.save(store)       # v4 delta carries the change
    assert delta_path.endswith(".delta.1")
    # Crash/restart: a fresh store restores base + chain; the ledger
    # is re-anchored against the NEW process's monotonic clock.
    store2 = InProcessBucketStore(clock=ManualClock())
    mono2 = Mono(1000.0)
    led2 = store2.federation_ledger(clock=mono2, default_ttl_s=TTL)
    applied = checkpoint.load_snapshot_chain(store2, path)
    assert applied == 1
    assert led2.restores == 1
    assert led2.outstanding_leases() == 1
    lease = led2._pools[TENANT].leases["r0"]
    assert lease.lease_id == "C1"
    assert lease.reported_total == 30.0
    # TTL re-anchored: expires within one TTL of the restore instant —
    # a restart can only SHORTEN the remaining term, never extend it.
    assert 0.0 < lease.expires_mono - mono2() <= TTL
    # The global bucket state rode along (balances exact)…
    assert _balance(store2) == pytest.approx(G_CAP - 30.0)
    # …and so did the idempotency records: a WAN retry of the original
    # grant still dedups after the restart.
    r = await led2.lease({"region": "r0", "lease_id": "C1",
                          "tenant": TENANT, "demand": 1.0,
                          "global_cap": G_CAP,
                          "global_rate": G_RATE})
    assert r["duplicate"]
    # Monotonic expiry continues against the restored ages.
    mono2.advance(TTL + 0.1)
    assert led2.expire() == 1


# -- controller actuator -----------------------------------------------------

class _FakeCluster:
    """Minimal sensor plane for the controller: a fixed node-stats
    stream (the real scrape shape), no actuator surface."""

    def __init__(self, tenant_rate: float = 5.0) -> None:
        self.total = 0.0
        self.tenant_rate = tenant_rate
        self.degraded = 0.0

    async def stats(self) -> dict:
        self.total += self.tenant_rate
        return {"nodes": [{
            "requests_served": int(self.total),
            "token_velocity": {"admitted": {TENANT: self.total}},
            "federation_region": {"degraded_now": self.degraded},
        }], "resilience": {}, "placement": {}}


def test_controller_federation_actuator_cadence_and_dry_run_parity():
    run(_controller_body())


async def _controller_body():
    store, led, home_mono = _ledger()

    def make(dry_run: bool, prefix: str):
        mono = Mono()
        agent = RegionFederation(
            "r0", led, tenants={TENANT: (G_CAP, G_RATE)},
            ttl_s=TTL, clock=mono,
            lease_id_factory=iter(
                f"{prefix}{i}" for i in range(100)).__next__)
        cfg = ControllerConfig(federation_renew_ticks=3,
                               cooldown_ticks=0, dry_run=dry_run)
        return agent, mono, Controller(
            _FakeCluster(), config=cfg, federation=agent,
            flight_recorder=FlightRecorder(64))

    live_agent, live_mono, live = make(False, "K")
    dry_agent, dry_mono, dry = make(True, "D")
    live_records, dry_records = [], []
    for _ in range(9):
        live_mono.advance(2.0)
        dry_mono.advance(2.0)
        live_records += await live.tick()
        dry_records += await dry.tick()
    # Cadence: the actuator fired on ticks 3, 6, 9 — and EXECUTED a
    # real lease/renew round through the agent only when live.
    fed_live = [r for r in live_records if r["action"] == "federation"]
    fed_dry = [r for r in dry_records if r["action"] == "federation"]
    assert len(fed_live) == 3 and len(fed_dry) == 3
    # Dry-run parity: identical decision schedule (tick + action),
    # execution-only skip.
    assert [(r["tick"], r["action"]) for r in fed_live] \
        == [(r["tick"], r["action"]) for r in fed_dry]
    assert all(r["outcome"] == "dry_run" for r in fed_dry)
    assert all(r["outcome"] == "executed" for r in fed_live)
    assert live_agent.leases_acquired == 1 and live_agent.renews >= 1
    assert dry_agent.leases_acquired == 0 and dry_agent.renews == 0
    # The demand report reached the home ledger: the lease's demand is
    # the controller's velocity-delta rate, not a constructor default.
    assert led._pools[TENANT].leases["r0"].demand > 0
    # Audit surfaces: flight frames + the drl_controller series.
    frames = live.flight_recorder.frames(kind="controller")
    assert any(f["action"] == "federation" for f in frames)
    assert live.numeric_stats()["fed_degraded"] == 0.0


# ===========================================================================
# THE seeded 3-region soak
# ===========================================================================

N_ROUNDS = 26
PARTITION_AT, RESTART_AT, HEAL_AT = 8, 14, 20
REGIONS = ("r0", "r1", "r2")

_CHAOS_RULES = {
    # Wire chaos on the federation seams: tiny delays + occasional
    # injected errors/resets on the WAN control path. The agents
    # absorb every one (partition_errors) — only monotonic expiry may
    # degrade a region.
    "federation.renew": (
        FaultRule(kind=faults.DELAY, probability=0.2, delay_s=0.001),
        FaultRule(kind=faults.ERROR, probability=0.08),
        FaultRule(kind=faults.RESET, probability=0.05),
    ),
    "federation.lease": (
        FaultRule(kind=faults.DELAY, probability=0.2, delay_s=0.001),
        FaultRule(kind=faults.ERROR, probability=0.1),
    ),
    "server.federation": (
        FaultRule(kind=faults.DELAY, probability=0.1, delay_s=0.001),
    ),
}


def _soak_schedule(seed: int):
    """Deterministic per-round, per-region request counts plus the
    demand schedule (r0 heats up mid-soak — the lend/borrow arm)."""
    rng = np.random.default_rng(seed)
    rounds = []
    for i in range(N_ROUNDS):
        counts = {r: int(rng.integers(0, 5)) for r in REGIONS}
        if i >= HEAL_AT:
            counts["r2"] = int(rng.integers(0, 3))
        demands = {"r0": 8.0 if i >= 4 else 4.0,
                   "r1": 2.0 if i >= 4 else 4.0, "r2": 4.0}
        rounds.append((counts, demands))
    return rounds


class _Region:
    """One region: a real wire server (its cluster data plane), a
    traffic client that learns slice changes through the OP_CONFIG
    chase, and the federation agent."""

    def __init__(self, name: str, seed: int) -> None:
        self.name = name
        self.mono = Mono()
        self.backing = InProcessBucketStore(clock=ManualClock())
        self.server = BucketStoreServer(self.backing)
        self.admitted = 0
        self.denied = 0
        self.grants: list[int] = []
        self.client: "RemoteBucketStore | None" = None
        self.config_client: "RemoteBucketStore | None" = None
        self.agent: "RegionFederation | None" = None
        self.first_cfg: "tuple[float, float] | None" = None
        self.partition_start_admitted = 0
        self.partition_admits = 0
        self.seed = seed

    async def start(self, home_client) -> None:
        await self.server.start()
        addr = (self.server.host, self.server.port)
        self.client = RemoteBucketStore(address=addr,
                                        coalesce_requests=False,
                                        resilience_seed=self.seed)
        self.config_client = RemoteBucketStore(
            address=addr, coalesce_requests=False,
            resilience_seed=self.seed + 7)
        inner = slice_applier(self.config_client)
        self.cfg_history: list[tuple] = []

        async def apply(tenant, old, new):
            self.cfg_history.append(tuple(new))
            await inner(tenant, old, new)

        self.agent = RegionFederation(
            self.name, home_client,
            tenants={TENANT: (G_CAP, G_RATE)},
            apply_slice=apply,
            admitted_total=lambda _t: float(self.admitted),
            ttl_s=TTL, clock=self.mono,
            lease_id_factory=self._ids())

    def _ids(self):
        seq = [0]

        def make() -> str:
            seq[0] += 1
            return f"{self.name}:L{seq[0]}"
        return make

    async def drive(self, n: int, partitioned: bool) -> None:
        """n admission requests through the wire data plane. The
        client always sends the FIRST slice's operands — every later
        resize/degrade/heal is an OP_CONFIG rule it chases (the
        live-mutable-slice contract)."""
        cfg = self.agent.slice(TENANT)
        if cfg is None:
            return
        if self.first_cfg is None:
            self.first_cfg = cfg
        for _ in range(n):
            res = await self.client.acquire(TENANT, 1,
                                            self.first_cfg[0],
                                            self.first_cfg[1])
            if res.granted:
                self.admitted += 1
                if partitioned:
                    self.partition_admits += 1
            else:
                self.denied += 1
            self.grants.append(int(res.granted))

    async def aclose(self) -> None:
        for c in (self.client, self.config_client):
            if c is not None:
                await c.aclose()
        await self.server.aclose()
        await self.backing.aclose()


class _DownHome:
    """The full partition: every WAN call from the region dies."""

    async def fed_lease(self, _p, **_kw):
        raise ConnectionResetError("partitioned")

    fed_renew = fed_lease
    fed_reclaim = fed_lease


async def _soak_once(seed: int, tmp_path) -> dict:
    rounds = _soak_schedule(seed)
    inj = FaultInjector(seed, _CHAOS_RULES)
    faults.install(inj)
    tmp_path.mkdir(parents=True, exist_ok=True)
    ckpt_path = str(tmp_path / f"home-{seed}.ckpt")
    chain = checkpoint.SnapshotChain(ckpt_path)
    home_mono = Mono()
    home_backing = InProcessBucketStore(clock=ManualClock())
    home_backing.federation_ledger(clock=home_mono,
                                   default_ttl_s=TTL)
    home_srv = BucketStoreServer(home_backing)
    await home_srv.start()

    def home_client(s):
        return RemoteBucketStore(
            address=(home_srv.host, home_srv.port),
            coalesce_requests=False, resilience_seed=s)

    regions = {n: _Region(n, seed + i * 13)
               for i, n in enumerate(REGIONS)}
    home_clients = {}
    for i, (n, reg) in enumerate(regions.items()):
        home_clients[n] = home_client(seed + 100 + i)
        await reg.start(home_clients[n])
    events: list[str] = []
    epsilon_budget = 0.0
    counter_base: dict[str, float] = {}
    try:
        for rnd, (counts, demands) in enumerate(rounds):
            home_mono.advance(1.0)
            for reg in regions.values():
                reg.mono.advance(1.0)

            if rnd == PARTITION_AT:
                # FULL partition of r2, spanning > 2 lease periods.
                r2 = regions["r2"]
                r2.agent.home = _DownHome()
                r2.partition_start_admitted = r2.admitted
                sl = r2.agent.slice(TENANT)
                # The ε envelope this partition may additionally
                # admit: the degraded config's burst (plus the heal
                # re-mint bounded by the same cap) — DESIGN.md §20.
                epsilon_budget += 2 * degraded_config(*sl)[0]
                events.append("partition:r2")

            if rnd == RESTART_AT:
                # Home crash/restart: lease state rides the chain.
                # (Counters are per-process — carry the dying
                # process's totals so the audit sees the whole soak.)
                for k, v in home_backing._federation.numeric_stats() \
                        .items():
                    counter_base[k] = counter_base.get(k, 0.0) + v
                chain.save(home_backing)
                await home_srv.aclose()
                for c in home_clients.values():
                    await c.aclose()
                new_backing = InProcessBucketStore(clock=ManualClock())
                new_backing.federation_ledger(clock=home_mono,
                                              default_ttl_s=TTL)
                applied = checkpoint.load_snapshot_chain(new_backing,
                                                         ckpt_path)
                new_srv = BucketStoreServer(new_backing)
                await new_srv.start()
                home_backing, home_srv = new_backing, new_srv
                for i, (n, reg) in enumerate(regions.items()):
                    home_clients[n] = RemoteBucketStore(
                        address=(home_srv.host, home_srv.port),
                        coalesce_requests=False,
                        resilience_seed=seed + 200 + i)
                    if n != "r2":
                        reg.agent.home = home_clients[n]
                led = home_backing._federation
                events.append(
                    f"restart:leases={led.outstanding_leases()}"
                    f",deltas={applied}")
                # Post-restart idempotency: a WAN retry of r0's
                # CURRENT grant still dedups from the restored
                # records (the grant ledger rode the chain too).
                held = regions["r0"].agent._leases[TENANT].lease_id
                if held is not None:
                    r = await home_clients["r0"].fed_lease({
                        "region": "r0", "lease_id": held,
                        "tenant": TENANT, "demand": demands["r0"],
                        "global_cap": G_CAP, "global_rate": G_RATE})
                    assert r.get("duplicate"), r

            if rnd == HEAL_AT:
                regions["r2"].agent.home = home_clients["r2"]
                events.append("heal:r2")

            for n, reg in regions.items():
                summary = await reg.agent.tick(
                    demands={TENANT: demands[n]})
                if summary["degraded"]:
                    events.append(f"degraded:{n}@{rnd}")
                if summary["healed"] or (n == "r2"
                                         and summary["leased"]
                                         and rnd >= HEAL_AT):
                    events.append(f"healed:{n}@{rnd}")
                partitioned = (n == "r2"
                               and PARTITION_AT <= rnd < HEAL_AT)
                await reg.drive(counts[n], partitioned)

            if rnd % 4 == 1:
                chain.save(home_backing)   # the incremental chain arm

        # Graceful wind-down: every region reports its final total.
        for reg in regions.values():
            await reg.agent.reclaim_all()

        led = home_backing._federation
        r2 = regions["r2"]

        # -- the differential audit, from the stores' own records ----
        # 1. The partitioned region stayed inside slice + envelope:
        #    its partition-window admits are bounded by what its own
        #    store could hold — never unlimited (it admitted SOME
        #    requests early in the window — never hard-down either).
        sl_cap = r2.first_cfg[0]
        assert r2.partition_admits <= sl_cap + epsilon_budget
        assert r2.agent.degraded_entries >= 1
        assert "partition:r2" in events and "heal:r2" in events

        # 2. The home's final accounting is EXACT against the
        #    regions' reported totals: every admitted token was
        #    reported at reclaim and charged through the settle lane
        #    (heal refunds reconciled the conservative charges).
        total_admitted = sum(r.admitted for r in regions.values())
        home_spent = G_CAP - _balance(home_backing)
        home_debt = sum(led.debts().values())
        assert home_spent + home_debt == pytest.approx(
            total_admitted, abs=1e-6)

        # 3. The global tenant bound across heal: Σ regional admits
        #    ≤ global cap + ε(RTT, lease_len) — with the pure-burst
        #    budget the ε term is the partition envelope alone.
        assert total_admitted <= G_CAP + epsilon_budget

        # 4. Region-store cross-check (the stores' own admission
        #    records): a never-degraded region's bucket NEVER
        #    under-records its grants (no re-mint — the over-admission
        #    direction is impossible store-side), and records them
        #    EXACTLY when its resize history never revisits a config
        #    value (a revisited config's rebase re-homes spend into a
        #    table that still carries its earlier state — saturating,
        #    i.e. UNDER-admission, the conservative direction;
        #    DESIGN.md §20 documents the bound).
        for n in ("r0", "r1"):
            reg = regions[n]
            cfg = reg.agent.slice(TENANT) or reg.first_cfg
            if cfg is None or reg.agent.degraded_entries > 0:
                continue
            bal = _balance(reg.backing, TENANT, cfg[0], cfg[1])
            spent = cfg[0] - bal
            assert spent >= min(reg.admitted, cfg[0]) - 1e-6, n
            if len(set(reg.cfg_history)) == len(reg.cfg_history):
                assert spent == pytest.approx(reg.admitted,
                                              abs=1e-6), n

        # Lend/borrow: r0's demand-proportional share grew past r1's.
        shares = {r: s for _t, r, s in led.shares() if _t == TENANT}
        summary = {
            "grants": {n: regions[n].grants for n in REGIONS},
            "admitted": {n: regions[n].admitted for n in REGIONS},
            "denied": {n: regions[n].denied for n in REGIONS},
            "events": events,
            "ledger": {k: v + counter_base.get(k, 0.0)
                       for k, v in led.numeric_stats().items()
                       if k != "outstanding_leases"},
            "agents": {n: regions[n].agent.numeric_stats()
                       for n in REGIONS},
            "fed_frames": [f["event"] for f in
                           (home_srv.flight_recorder.frames(
                               kind="federation") or [])],
            "shares": shares,
        }
        return summary
    finally:
        faults.uninstall()
        for reg in regions.values():
            await reg.aclose()
        for c in home_clients.values():
            await c.aclose()
        await home_srv.aclose()
        await home_backing.aclose()


def test_federation_soak_3region(tmp_path):
    """THE acceptance soak (module docstring) + bit-for-bit seed
    determinism: the same seed reproduces the identical grant
    sequence, federation event schedule, and ledger counters."""
    s1 = run(_soak_once(SEED, tmp_path / "a"))
    s2 = run(_soak_once(SEED, tmp_path / "b"))
    assert s1 == s2
    # Non-vacuity: traffic flowed everywhere, the partition degraded
    # r2 into its envelope, the heal re-leased it, and the home saw
    # the conservative-charge + heal cycle.
    assert all(s1["admitted"][n] > 0 for n in REGIONS)
    assert any(e.startswith("degraded:r2") for e in s1["events"])
    assert any(e.startswith("healed:r2") for e in s1["events"])
    assert s1["ledger"]["leases_expired"] >= 1
    assert s1["ledger"]["heals"] >= 1
    assert s1["ledger"]["conservative_tokens"] > 0
    assert s1["agents"]["r2"]["partition_errors"] > 0
    # Chaos non-vacuity: the seams actually fired mid-soak.
    assert (s1["agents"]["r0"]["partition_errors"]
            + s1["agents"]["r1"]["partition_errors"]) > 0
