"""Key-directory tests: native C++ vs pure-Python equivalence.

The native directory is a drop-in for the Python one; these tests fuzz the
full lifecycle (resolve / exhaust / remove / grow / snapshot / restore) on
both and require identical observable behavior."""

import numpy as np
import pytest

from distributedratelimiting.redis_tpu.runtime.directory import (
    NativeKeyDirectory,
    PyKeyDirectory,
)
from distributedratelimiting.redis_tpu.utils.native import load_directory_lib

LIB = load_directory_lib()

needs_native = pytest.mark.skipif(LIB is None, reason="no native build")


def make_pair(n_slots):
    return NativeKeyDirectory(n_slots, LIB), PyKeyDirectory(n_slots)


@needs_native
class TestEquivalence:
    def test_resolve_allocation_order_matches(self):
        nd, pd = make_pair(16)
        keys = [f"k{i}" for i in range(10)] + ["k3", "k0", "k9"]
        assert (nd.resolve_batch(keys) == pd.resolve_batch(keys)).all()
        assert len(nd) == len(pd) == 10
        assert nd.free_count == pd.free_count == 6

    def test_exhaustion_marks_minus_one(self):
        nd, pd = make_pair(4)
        keys = [f"k{i}" for i in range(6)]
        ns, ps = nd.resolve_batch(keys), pd.resolve_batch(keys)
        assert (ns == ps).all()
        assert (ns[-2:] == -1).all()
        # Duplicates of resolved keys still resolve while exhausted.
        assert nd.lookup("k1") == pd.lookup("k1") is not None

    def test_remove_and_recycle(self):
        nd, pd = make_pair(8)
        keys = [f"k{i}" for i in range(8)]
        nd.resolve_batch(keys), pd.resolve_batch(keys)
        dead = np.array([1, 3, 5], np.int32)
        assert nd.remove_slots(dead) == pd.remove_slots(dead) == 3
        assert nd.free_count == pd.free_count == 3
        for k in keys:
            assert nd.lookup(k) == pd.lookup(k)
        # Recycled slots are handed out again.
        ns = nd.resolve_batch(["n1", "n2", "n3"])
        ps = pd.resolve_batch(["n1", "n2", "n3"])
        assert sorted(ns.tolist()) == sorted(ps.tolist()) == [1, 3, 5]

    def test_grow_extends_capacity(self):
        nd, pd = make_pair(4)
        nd.resolve_batch(["a", "b", "c", "d"])
        pd.resolve_batch(["a", "b", "c", "d"])
        nd.add_slots(4, 8)
        pd.add_slots(4, 8)
        ns = nd.resolve_batch(["e", "f"])
        ps = pd.resolve_batch(["e", "f"])
        assert (ns == ps).all()
        assert (ns >= 4).all()

    def test_snapshot_roundtrip(self):
        nd, pd = make_pair(16)
        keys = [f"key-{i}" for i in range(12)]
        nd.resolve_batch(keys), pd.resolve_batch(keys)
        nd.remove_slots([2, 7])
        pd.remove_slots([2, 7])
        assert nd.to_dict() == pd.to_dict()
        # Restore into fresh directories.
        nd2, pd2 = make_pair(16)
        nd2.load(nd.to_dict(), 16)
        pd2.load(pd.to_dict(), 16)
        assert nd2.to_dict() == pd2.to_dict() == nd.to_dict()
        assert nd2.free_count == pd2.free_count
        # Post-restore allocation stays equivalent.
        assert (nd2.resolve_batch(["x", "y"]) == pd2.resolve_batch(["x", "y"])).all()

    def test_fuzz_lifecycle(self, rng):
        nd, pd = make_pair(32)
        n_slots = 32
        for step in range(300):
            op = rng.integers(0, 10)
            if op < 6:
                keys = [f"k{rng.integers(0, 64)}"
                        for _ in range(rng.integers(1, 12))]
                ns, ps = nd.resolve_batch(keys), pd.resolve_batch(keys)
                assert (ns == ps).all(), (step, keys, ns, ps)
            elif op < 8:
                dead = rng.integers(0, n_slots, rng.integers(1, 6)).astype(np.int32)
                assert nd.remove_slots(dead) == pd.remove_slots(dead)
            elif op == 8 and n_slots < 256:
                nd.add_slots(n_slots, n_slots * 2)
                pd.add_slots(n_slots, n_slots * 2)
                n_slots *= 2
            else:
                for k in [f"k{rng.integers(0, 64)}" for _ in range(4)]:
                    assert nd.lookup(k) == pd.lookup(k)
            assert len(nd) == len(pd)
            assert nd.free_count == pd.free_count
        assert nd.to_dict() == pd.to_dict()

    def test_unicode_and_long_keys(self):
        nd, pd = make_pair(8)
        keys = ["ключ", "🔑" * 40, "a" * 500, ""]
        assert (nd.resolve_batch(keys) == pd.resolve_batch(keys)).all()
        assert nd.to_dict() == pd.to_dict()


@needs_native
@pytest.mark.jax_backend
def test_store_uses_native_directory():
    from distributedratelimiting.redis_tpu.runtime.store import DeviceBucketStore

    dev = DeviceBucketStore(n_slots=8)
    dev.acquire_blocking("k", 1, 10.0, 1.0)
    table = next(iter(dev._tables.values()))
    assert isinstance(table.dir, NativeKeyDirectory)


@needs_native
def test_arena_compaction_under_key_churn():
    # The C++ arena must not grow with total-keys-ever-seen: churn 200
    # generations of keys through an 8-slot directory and check live bytes
    # stay bounded at the live set.
    nd = NativeKeyDirectory(8, LIB)
    for gen in range(200):
        keys = [f"generation-{gen}-user-{i}" for i in range(8)]
        slots = nd.resolve_batch(keys)
        assert (slots >= 0).all()
        nd.remove_slots(slots)
    final = [f"final-{i}" for i in range(8)]
    nd.resolve_batch(final)
    assert len(nd) == 8
    assert nd.arena_bytes == sum(len(k) for k in final)
    for k in final:
        assert nd.lookup(k) is not None


def test_native_blob_resolve_matches_list_resolve():
    """wire.KeyBlob resolves to the same slots as the list[str] path —
    the zero-copy serving lane and the classic path are one directory."""
    import numpy as np

    from distributedratelimiting.redis_tpu.runtime.directory import (
        make_directory,
    )
    from distributedratelimiting.redis_tpu.runtime.wire import KeyBlob

    d = make_directory(64)
    keys = [f"k{i % 20}" for i in range(50)] + ["dup", "dup"]
    blobs = [k.encode() for k in keys]
    offsets = np.zeros(len(keys) + 1, np.int64)
    np.cumsum([len(b) for b in blobs], out=offsets[1:])
    view = KeyBlob(b"".join(blobs), offsets)
    via_view = d.resolve_batch(view)
    via_list = d.resolve_batch(list(keys))
    assert (via_view == via_list).all()
    assert len(set(via_view.tolist())) == 21  # 20 distinct + "dup"


def test_byte_identity_keys_survive_snapshot_and_restore():
    """Regression (review): a byte-identity key inserted via the KeyBlob
    lane must survive to_dict (strict decode crashed it) and a
    cross-backend load (strict encode crashed it)."""
    import numpy as np

    from distributedratelimiting.redis_tpu.runtime.directory import (
        NativeKeyDirectory, PyKeyDirectory, make_directory,
    )
    from distributedratelimiting.redis_tpu.runtime.wire import KeyBlob

    d = make_directory(8)
    bad = b"\xff\x80key"
    offsets = np.array([0, len(bad)], np.int64)
    slot = int(d.resolve_batch(KeyBlob(bad, offsets))[0])
    assert slot >= 0
    snap = d.to_dict()  # must not raise
    assert len(snap) == 1

    # Cross-backend restore in both directions.
    py = PyKeyDirectory(8)
    py.load(snap, 8)
    assert py.resolve_batch(KeyBlob(bad, offsets))[0] == slot
    d2 = make_directory(8)
    d2.load(snap, 8)
    assert int(d2.resolve_batch(KeyBlob(bad, offsets))[0]) == slot
    if isinstance(d2, NativeKeyDirectory):
        assert d2.lookup(snap and next(iter(snap))) == slot
