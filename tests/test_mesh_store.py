"""MeshBucketStore tests: the full store surface over the 8-device mesh,
including the star topology (TCP server fronting the mesh)."""

import asyncio

import pytest

from distributedratelimiting.redis_tpu.models.approximate import (
    ApproximateTokenBucketRateLimiter,
)
from distributedratelimiting.redis_tpu.models.options import (
    ApproximateTokenBucketOptions,
    TokenBucketOptions,
)
from distributedratelimiting.redis_tpu.models.partitioned import (
    PartitionedRateLimiter,
)
from distributedratelimiting.redis_tpu.parallel.mesh import create_mesh
from distributedratelimiting.redis_tpu.parallel.mesh_store import (
    MeshBucketStore,
)
from distributedratelimiting.redis_tpu.runtime.clock import ManualClock
from distributedratelimiting.redis_tpu.runtime.remote import RemoteBucketStore
from distributedratelimiting.redis_tpu.runtime.server import BucketStoreServer


def run(coro):
    return asyncio.run(coro)


@pytest.fixture
def store():
    return MeshBucketStore(create_mesh(8), per_shard_slots=32,
                           clock=ManualClock(), max_batch=64,
                           max_delay_s=2e-3)


def test_legacy_aux_window_snapshot_migrates_to_sharded_tier():
    """A snapshot taken when windows were served by the aux store must
    restore into the sharded window tier — otherwise every window key
    resets to a full fresh limit after a planned restart."""

    async def main():
        clock = ManualClock()
        # Forge the legacy shape: drive windows through the AUX store of a
        # mesh store, then snapshot with the window state under aux.
        legacy = MeshBucketStore(clock=clock, per_shard_slots=16)
        await legacy.connect()
        legacy._aux.window_acquire_blocking("w", 3, 3.0, 1.0)
        snap = legacy.snapshot()
        snap.pop("windows", None)  # what an old snapshot looks like
        assert snap["aux"]["wtables"]
        await legacy.aclose()

        fresh = MeshBucketStore(clock=ManualClock(), per_shard_slots=16)
        await fresh.connect()
        fresh.restore(snap)
        # The key is at its limit — served from the SHARDED tier now.
        assert not fresh.window_acquire_blocking("w", 1, 3.0, 1.0).granted
        assert not fresh._aux._wtables  # aux copy dropped, no double state
        await fresh.aclose()

    run(main())


class TestBucketTier:
    def test_blocking_semantics_match_reference(self, store):
        clock = store.clock
        for _ in range(5):
            assert store.acquire_blocking("k", 1, 5.0, 1.0).granted
        assert not store.acquire_blocking("k", 1, 5.0, 1.0).granted
        clock.advance_seconds(2.0)
        assert store.acquire_blocking("k", 2, 5.0, 1.0).granted
        assert store.peek_blocking("k", 5.0, 1.0) == 0.0

    def test_async_micro_batched_across_shards(self, store):
        async def main():
            results = await asyncio.gather(*(
                store.acquire(f"key-{i}", 1, 100.0, 1.0) for i in range(48)
            ))
            assert all(r.granted for r in results)
            # A duplicate burst respects per-key atomicity inside a flush.
            dup = await asyncio.gather(*(
                store.acquire("hot", 1, 3.0, 0.1) for _ in range(8)
            ))
            assert sum(r.granted for r in dup) == 3
            await store.aclose()

        run(main())

    def test_two_level_global_tier_visible(self, store):
        store.acquire_blocking("a", 2, 100.0, 1.0)
        store.acquire_blocking("b", 3, 100.0, 1.0)
        sharded = store._sharded(100.0, 1.0)
        assert sharded.global_score == 5.0

    def test_aux_families_share_the_clock(self, store):
        clock = store.clock
        assert store.window_acquire_blocking("w", 3, 3.0, 1.0).granted
        assert not store.window_acquire_blocking("w", 1, 3.0, 1.0).granted
        assert store.concurrency_acquire_blocking("s", 2, 2).granted
        store.concurrency_release_blocking("s", 2)
        res = store.sync_counter_blocking("g", 4.0, 1.0)
        assert res.global_score == 4.0
        clock.advance_seconds(2.0)
        assert store.sync_counter_blocking("g", 0.0, 1.0).global_score == \
            pytest.approx(2.0, abs=0.01)

    def test_snapshot_restore_roundtrip(self, store):
        store.acquire_blocking("k", 4, 10.0, 1.0)
        store.window_acquire_blocking("w", 2, 5.0, 1.0)
        snap = store.snapshot()
        other = MeshBucketStore(create_mesh(8), per_shard_slots=32,
                                clock=ManualClock(), max_batch=64)
        other.restore(snap)
        assert other.acquire_blocking("k", 6, 10.0, 1.0).granted
        assert not other.acquire_blocking("k", 1, 10.0, 1.0).granted
        assert other.window_acquire_blocking("w", 3, 5.0, 1.0).granted
        assert not other.window_acquire_blocking("w", 1, 5.0, 1.0).granted


class TestStarTopologyOverMesh:
    def test_remote_clients_share_the_mesh(self, store):
        """The capstone topology: remote client hosts → TCP server →
        key-sharded mesh store."""

        async def main():
            async with BucketStoreServer(store) as srv:
                a = RemoteBucketStore(address=(srv.host, srv.port))
                b = RemoteBucketStore(address=(srv.host, srv.port))
                lim_a = PartitionedRateLimiter(
                    TokenBucketOptions(token_limit=4, tokens_per_period=1,
                                       instance_name="api"), a)
                lim_b = PartitionedRateLimiter(
                    TokenBucketOptions(token_limit=4, tokens_per_period=1,
                                       instance_name="api"), b)
                try:
                    # Both clients hit the SAME sharded buckets.
                    r = [await lim_a.acquire_async("u1"),
                         await lim_b.acquire_async("u1"),
                         await lim_a.acquire_async("u1"),
                         await lim_b.acquire_async("u1")]
                    assert all(x.is_acquired for x in r)
                    assert not (await lim_a.acquire_async("u1")).is_acquired
                    assert not (await lim_b.acquire_async("u1")).is_acquired
                    # And the approximate two-level family works through
                    # the same server (aux counter tier).
                    ap = ApproximateTokenBucketRateLimiter(
                        ApproximateTokenBucketOptions(
                            token_limit=100, tokens_per_period=10,
                            instance_name="approx"), a)
                    assert (await ap.acquire_async(1)).is_acquired
                    await ap.refresh()
                    assert ap.stats()["syncs"] == 1
                    await ap.aclose()
                finally:
                    await a.aclose()
                    await b.aclose()

        run(main())


class TestCoordinatedRebase:
    def test_all_tiers_rebase_together(self):
        """Regression: crossing the int32 threshold must shift EVERY
        tier's epoch in one step — an independent sub-store rebase would
        strand its siblings' timestamps and freeze their refill."""
        clock = ManualClock(start_ticks=2**30 - 2048)
        store = MeshBucketStore(create_mesh(8), per_shard_slots=32,
                                clock=clock, max_batch=64)
        # Touch two bucket configs + a window + a counter pre-rebase.
        store.acquire_blocking("a", 5, 5.0, 1.0)        # drain config 1
        store.acquire_blocking("b", 3, 30.0, 2.0)       # config 2
        store.window_acquire_blocking("w", 3, 3.0, 1.0)
        store.sync_counter_blocking("g", 10.0, 1.0)
        clock.advance_seconds(4.0)  # crosses the threshold
        store.acquire_blocking("trigger", 1, 5.0, 1.0)  # triggers rebase
        assert clock.now_ticks() < 2**30
        # Every tier still measures elapsed time correctly post-rebase:
        # config 1: 4s elapsed at 1/s -> exactly 4 tokens.
        assert store.acquire_blocking("a", 4, 5.0, 1.0).granted
        assert not store.acquire_blocking("a", 1, 5.0, 1.0).granted
        # config 2 refilled 8 (cap 30): 27+8 capped -> full minus nothing.
        assert store.acquire_blocking("b", 30, 30.0, 2.0).granted
        # window rolled over (4s >> 1s window).
        assert store.window_acquire_blocking("w", 3, 3.0, 1.0).granted
        # counter decayed 4 of 10.
        assert store.sync_counter_blocking("g", 0.0, 1.0).global_score == \
            pytest.approx(6.0, abs=0.05)


class TestMeshPeekReadOnly:
    def test_peek_never_allocates(self, store):
        assert store.peek_blocking("ghost", 5.0, 1.0) == 5.0
        sharded = store._sharded(5.0, 1.0)
        assert "ghost" not in sharded.directory
        # And reads through to live state without consuming.
        store.acquire_blocking("real", 2, 5.0, 1.0)
        assert store.peek_blocking("real", 5.0, 1.0) == 3.0
        assert store.peek_blocking("real", 5.0, 1.0) == 3.0


class TestMeshMetrics:
    def test_stats_cover_the_bucket_tiers(self, store):
        store.acquire_blocking("k", 1, 10.0, 1.0)
        store.window_acquire_blocking("w", 1, 5.0, 1.0)
        snap = store.metrics.snapshot()
        # Sharded bucket launches are visible, not just the aux store's.
        assert snap["launches"] >= 2
        assert any(k.startswith("bucket[") for k in snap["tiers"])


class TestAuxOnlyRebase:
    def test_window_only_workload_still_rebases(self):
        """Regression: a mesh store serving ONLY aux-family traffic (no
        bucket acquires) must still rebase before int32 tick overflow."""
        clock = ManualClock(start_ticks=2**30 - 2048)
        store = MeshBucketStore(create_mesh(8), per_shard_slots=32,
                                clock=clock, max_batch=64)
        store.window_acquire_blocking("w", 3, 3.0, 1.0)
        clock.advance_seconds(4.0)
        store.window_acquire_blocking("w", 1, 3.0, 1.0)  # triggers rebase
        assert clock.now_ticks() < 2**30
        assert store.window_acquire_blocking("w", 2, 3.0, 1.0).granted


class TestFpDirectoryMesh:
    def test_mesh_store_with_fp_directory(self):
        # The full store surface over a mesh with the device-resident
        # directory for buckets AND windows (aux tiers keep the host
        # directory) — drop-in via directory="fp".
        import asyncio

        from distributedratelimiting.redis_tpu.parallel.fp_sharded import (
            ShardedFpDeviceStore,
            ShardedFpWindowStore,
        )

        async def main():
            clock = ManualClock()
            store = MeshBucketStore(per_shard_slots=256, clock=clock,
                                    directory="fp")
            # Buckets: capacity + refill through the fp tier.
            got = [(await store.acquire("k", 1, 3.0, 1.0)).granted
                   for _ in range(5)]
            assert got == [True] * 3 + [False] * 2
            clock.advance_seconds(2.0)
            assert (await store.acquire("k", 2, 3.0, 1.0)).granted
            assert isinstance(store._shards[(3.0, 1.0)],
                              ShardedFpDeviceStore)
            # Bulk across shards.
            res = await store.acquire_many(
                [f"b{i}" for i in range(64)], [1] * 64, 5.0, 1.0)
            assert res.granted.all()
            # Windows ride the fp tier too.
            assert (await store.window_acquire("w", 2, 3.0, 10.0)).granted
            assert not (await store.window_acquire("w", 2, 3.0, 10.0)).granted
            assert any(isinstance(w, ShardedFpWindowStore)
                       for w in store._windows.values())
            # Peek doesn't insert; aux tiers (counters) still work.
            assert store.peek_blocking("ghost", 9.0, 1.0) == 9.0
            r = await store.sync_counter("c", 5.0, 0.0)
            assert r.global_score == pytest.approx(5.0)
            # Checkpoint round-trips through the fp snapshot form.
            snap = store.snapshot()
            fresh = MeshBucketStore(per_shard_slots=256,
                                    clock=ManualClock(), directory="fp")
            fresh.restore(snap)
            assert not (await fresh.acquire("k", 3, 3.0, 1.0)).granted
            await store.aclose()
            await fresh.aclose()

        asyncio.run(main())

    def test_bad_directory_rejected(self):
        with pytest.raises(ValueError, match="directory"):
            MeshBucketStore(directory="cuckoo")


class TestSyncCadencePlumbing:
    def test_option_reaches_sharded_tiers(self):
        async def main():
            store = MeshBucketStore(create_mesh(8), per_shard_slots=32,
                                    clock=ManualClock(),
                                    sync_cadence="launch")
            await store.connect()
            assert (await store.acquire("k", 1, 5.0, 1.0)).granted
            tier = store._shards[(5.0, 1.0)]
            assert tier.sync_cadence == "launch"
            res = await store.acquire_many(
                [f"b{i}" for i in range(64)], [1] * 64, 9.0, 1.0)
            assert res.granted.all()
            assert store._shards[(9.0, 1.0)].sync_cadence == "launch"
            await store.aclose()

        asyncio.run(main())

    def test_invalid_cadence_rejected(self):
        with pytest.raises(ValueError, match="sync_cadence"):
            MeshBucketStore(sync_cadence="yearly")


class TestMeshAuxCardinality:
    """The aux tiers (decaying counters, semaphores) live on one device by
    design (per-limiter traffic), but their tables must GROW past the
    initial ``aux_slots`` allocation — keyed concurrency/counter workloads
    at >16K keys (the r4 VERDICT's doubted ceiling) must work, not wedge."""

    def test_counters_and_semas_grow_past_16k_keys(self):
        store = MeshBucketStore(create_mesh(8), per_shard_slots=16,
                                clock=ManualClock())
        n = 17_000  # initial aux_slots is 2**14 = 16384: forces a doubling
        for i in range(n):
            r = store.sync_counter_blocking(f"c{i}", 1.0, 0.5)
            assert r.global_score >= 1.0
        assert store._aux._counters.value.shape[0] > 16384
        for i in range(n):
            assert store.concurrency_acquire_blocking(f"s{i}", 1, 2).granted
        assert store._aux._semas.active.shape[0] > 16384
        # Entries survived the doublings: an early key still holds its
        # state (second acquire on a limit-2 semaphore grants, third not).
        assert store.concurrency_acquire_blocking("s0", 1, 2).granted
        assert not store.concurrency_acquire_blocking("s0", 1, 2).granted
        assert store.sync_counter_blocking("c0", 0.0, 0.5).global_score > 0
