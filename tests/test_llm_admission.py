"""Token-denominated, SLO-aware admission (ISSUE 10): the admission
subsystem's unit surface plus THE seeded multi-tenant soak.

The soak is the acceptance differential: a deterministic Zipf-tenant ×
log-normal-cost schedule with a noisy neighbor flooding scavenger
traffic, driven over the real wire (OP_ACQUIRE_H + HBUCKET bulk frames)
against an in-memory backing, audited over the STORE'S OWN admission
records — per-tenant admitted tokens never exceed budget + the epsilon
envelope, and under envelope serving (a drain-and-handoff window)
scavenger sheds before interactive. ``make llm-soak SEED=…`` replays
any schedule bit-for-bit (DRL_LLM_SEED)."""

from __future__ import annotations

import asyncio
import os

import numpy as np
import pytest

from distributedratelimiting.redis_tpu.models.approximate import (
    headroom_budget,
)
from distributedratelimiting.redis_tpu.runtime import admission, wire
from distributedratelimiting.redis_tpu.runtime.admission import (
    PRIORITY_BATCH,
    PRIORITY_INTERACTIVE,
    PRIORITY_SCAVENGER,
    AdmissionPolicy,
    TenantBudget,
    TokenVelocity,
    shed_allows,
)
from distributedratelimiting.redis_tpu.runtime.clock import ManualClock
from distributedratelimiting.redis_tpu.runtime.remote import (
    RemoteBucketStore,
)
from distributedratelimiting.redis_tpu.runtime.server import (
    BucketStoreServer,
)
from distributedratelimiting.redis_tpu.runtime.store import (
    InProcessBucketStore,
)

SEED = int(os.environ.get("DRL_LLM_SEED", "20260804"))


def run(coro):
    return asyncio.run(coro)


# -- priority shed gate ------------------------------------------------------

def test_shed_allows_order():
    budget = 100.0
    # Interactive: the plain envelope rule, down to the last token.
    assert shed_allows(PRIORITY_INTERACTIVE, 10.0, 10, budget)
    assert not shed_allows(PRIORITY_INTERACTIVE, 9.0, 10, budget)
    # Batch: cannot spend the reserved half.
    assert shed_allows(PRIORITY_BATCH, 100.0, 50, budget)
    assert not shed_allows(PRIORITY_BATCH, 100.0, 51, budget)
    assert not shed_allows(PRIORITY_BATCH, 55.0, 10, budget)
    # Scavenger: shed outright from any envelope, probes included.
    assert not shed_allows(PRIORITY_SCAVENGER, 100.0, 1, budget)
    assert not shed_allows(PRIORITY_SCAVENGER, 100.0, 0, budget)
    # Negative costs never pass.
    assert not shed_allows(PRIORITY_INTERACTIVE, 100.0, -1, budget)


def test_envelope_step_honors_priority():
    from distributedratelimiting.redis_tpu.runtime.placement import (
        envelope_step,
    )

    # cap 200, fraction 0.5 → budget 100, fresh key born at budget.
    g, tokens = envelope_step(None, 0.0, 10, 200.0, 0.0, 0.5,
                              PRIORITY_INTERACTIVE)
    assert g and tokens == 90.0
    g, _ = envelope_step(None, 0.0, 10, 200.0, 0.0, 0.5,
                         PRIORITY_SCAVENGER)
    assert not g
    g, _ = envelope_step((60.0, 0.0), 0.0, 20, 200.0, 0.0, 0.5,
                         PRIORITY_BATCH)
    assert not g  # 60 − 20 < 50: the reserved half is interactive's


# -- token velocity ----------------------------------------------------------

def test_token_velocity_converges_and_decays():
    t = [0.0]
    tv = TokenVelocity(tau_s=5.0, clock=lambda: t[0])
    # Steady 100 tokens/sec for 60s (1 observation of 100 per second).
    for _ in range(60):
        tv.observe("acme", 100.0)
        t[0] += 1.0
    rate = tv.rate("acme")
    assert rate == pytest.approx(100.0, rel=0.15)
    # Feed stops: the estimate decays with tau.
    t[0] += 5.0
    assert tv.rate("acme") == pytest.approx(rate / np.e, rel=0.05)
    t[0] += 50.0
    assert tv.rate("acme") < 1.0
    assert tv.rate("nobody") == 0.0
    snap = tv.snapshot()
    assert snap["observed_tokens"] == 6000.0 and "acme" in snap["tenants"]


def test_token_velocity_bounded_tenants():
    t = [0.0]
    tv = TokenVelocity(tau_s=5.0, max_tenants=4, clock=lambda: t[0])
    for i in range(10):
        tv.observe(f"t{i}", float(i + 1))
    assert len(tv.rates()) == 4
    # The heaviest stay; the smallest were evicted.
    assert "t9" in tv.rates()


# -- hierarchical semantics (the refund contract) ---------------------------

def test_hier_deny_leaves_both_levels_untouched():
    run(_hier_deny_body())


async def _hier_deny_body():
    st = InProcessBucketStore(clock=ManualClock())
    # Tenant 50, child 100: child admits, tenant denies → NEITHER debited.
    r = await st.acquire_hierarchical("t", "k", 80, 50.0, 1e-9,
                                      100.0, 1e-9)
    assert not r.granted
    assert st._buckets[("t", 50.0, 1e-9)][0] == 50.0
    assert st._buckets[("k", 100.0, 1e-9)][0] == 100.0
    # Child denies, tenant admits → neither debited either.
    r = await st.acquire_hierarchical("t2", "k2", 80, 500.0, 1e-9,
                                      60.0, 1e-9)
    assert not r.granted
    assert st._buckets[("t2", 500.0, 1e-9)][0] == 500.0
    assert st._buckets[("k2", 60.0, 1e-9)][0] == 60.0
    # Grant debits both; remaining is the binding constraint's view.
    r = await st.acquire_hierarchical("t", "k", 30, 50.0, 1e-9,
                                      100.0, 1e-9)
    assert r.granted and r.remaining == pytest.approx(20.0)


def test_hier_validation_is_shared():
    st = InProcessBucketStore()
    with pytest.raises(ValueError, match="distinct tenant and key"):
        st.acquire_hierarchical_blocking("t", "k", 1, 10.0, 1.0,
                                         10.0, 1.0)
    with pytest.raises(ValueError, match=">= 0"):
        st.acquire_hierarchical_blocking("t", "k", -1, 20.0, 1.0,
                                         10.0, 1.0)


# -- AdmissionPolicy ---------------------------------------------------------

def test_admission_policy_budgets_and_shed():
    run(_policy_body())


async def _policy_body():
    st = InProcessBucketStore(clock=ManualClock())
    policy = AdmissionPolicy(st, key_config=(10_000.0, 1e-9))
    policy.set_tenant(TenantBudget("acme", 1000.0, 1e-9))
    with pytest.raises(KeyError):
        await policy.acquire("unknown", "k", 1)
    granted = 0
    for i in range(30):
        r = await policy.acquire("acme", f"k{i % 5}", 100)
        granted += r.granted
    # 1000-token budget admits exactly 10 hundred-token requests.
    assert granted == 10
    assert policy.admitted_tokens == 1000.0
    assert policy.velocity.rate("acme") > 0.0
    # Operator brownout: scavenger shed locally, store untouched.
    policy.set_shed_level(PRIORITY_SCAVENGER)
    r = await policy.acquire("acme", "k", 0,
                             priority=PRIORITY_SCAVENGER)
    assert not r.granted and policy.shed == 1
    assert policy.envelope_budget("acme") == headroom_budget(
        1000.0, fraction=0.5, min_budget=1.0)
    stats = policy.stats()
    assert stats["granted"] == 10 and stats["shed"] == 1
    assert "acme" in stats["token_velocity"]["tenants"]


def test_tenant_budget_validation():
    with pytest.raises(ValueError):
        TenantBudget("", 10.0, 1.0)
    with pytest.raises(ValueError):
        TenantBudget("t", 0.0, 1.0)
    with pytest.raises(ValueError):
        TenantBudget("t", 10.0, -1.0)


# -- old-peer latch ----------------------------------------------------------

def test_old_peer_latches_flat_fallback():
    """A server that does not speak the tenant extension answers the
    routable unknown-op error; the client latches once, falls back to
    FLAT child-only admission, and counts every fallback."""
    run(_old_peer_body())


async def _old_peer_body():
    backing = InProcessBucketStore(clock=ManualClock())
    srv = BucketStoreServer(backing)
    real = srv.handle_frame_body

    async def old_peer(body, arrival_s=None):
        if len(body) >= 6 and (body[5] & 0x3F) == wire.OP_ACQUIRE_H:
            from distributedratelimiting.redis_tpu.runtime.server import (
                _recover_seq,
            )

            return wire.encode_response(_recover_seq(body),
                                        wire.RESP_ERROR,
                                        "unknown op 19")
        return await real(body, arrival_s=arrival_s)

    srv.handle_frame_body = old_peer
    await srv.start()
    store = RemoteBucketStore(address=(srv.host, srv.port),
                              coalesce_requests=False)
    try:
        r = await store.acquire_hierarchical("t", "k", 30, 100.0, 1e-9,
                                             60.0, 1e-9)
        # Flat fallback decided against the CHILD config only.
        assert r.granted and r.remaining == pytest.approx(30.0)
        assert store.resilience_stats()["hier_fallbacks"] == 1
        assert not store._peer_hier
        # The tenant bucket was never touched (unenforced, by contract).
        assert ("t", 100.0, 1e-9) not in backing._buckets
        # Later calls skip the wire probe entirely and keep counting.
        await store.acquire_hierarchical("t", "k2", 1, 100.0, 1e-9,
                                         60.0, 1e-9)
        assert store.resilience_stats()["hier_fallbacks"] == 2
    finally:
        await store.aclose()
        await srv.aclose()


def test_old_peer_hier_fallback_keeps_trace_latch():
    """Review regression: an old peer rejecting OP_ACQUIRE_H must not
    permanently latch TRACE stamping off — the unknown-op answer names
    the base op, not the trace tail, so after the bare re-send also
    fails the trace latch is restored (the deadline latch's posture)."""
    run(_trace_latch_body())


async def _trace_latch_body():
    from distributedratelimiting.redis_tpu.utils import tracing

    backing = InProcessBucketStore(clock=ManualClock())
    srv = BucketStoreServer(backing)
    real = srv.handle_frame_body

    async def old_peer(body, arrival_s=None):
        if len(body) >= 6 and (body[5] & 0x3F) == wire.OP_ACQUIRE_H:
            from distributedratelimiting.redis_tpu.runtime.server import (
                _recover_seq,
            )

            return wire.encode_response(_recover_seq(body),
                                        wire.RESP_ERROR,
                                        "unknown op 19")
        return await real(body, arrival_s=arrival_s)

    srv.handle_frame_body = old_peer
    await srv.start()
    tracing.configure(enabled=True, sample_rate=1.0)
    store = RemoteBucketStore(address=(srv.host, srv.port),
                              coalesce_requests=False)
    try:
        r = await store.acquire_hierarchical("t", "k", 2, 100.0, 1e-9,
                                             60.0, 1e-9)
        assert r.granted  # flat fallback served
        assert store._peer_traces  # the trace latch survived
        assert not store._peer_hier
    finally:
        tracing.configure(enabled=False)
        await store.aclose()
        await srv.aclose()


# -- THE seeded multi-tenant soak (acceptance) -------------------------------

#: Tenant budgets (tokens) and the noisy neighbor: C floods scavenger
#: traffic at 4× everyone's row rate. Fill rates ≈ 0 make the audit
#: exact: admitted tokens can never exceed capacity while healthy.
_TENANTS = {
    "tenant:a": 6000.0,
    "tenant:b": 4000.0,
    "tenant:noisy": 3000.0,
}
_FILL = 1e-9
_CHILD_CAP, _CHILD_RATE = 100_000.0, 1e-9


def _soak_schedule(seed: int, n_rows: int = 900):
    """Deterministic Zipf-tenant × log-normal-cost × mixed-priority
    schedule. The noisy neighbor's rows are all scavenger; tenant:a is
    interactive-heavy, tenant:b batch-heavy."""
    rng = np.random.default_rng(seed)
    rows = []
    for i in range(n_rows):
        r = rng.random()
        if r < 0.5:
            tenant = "tenant:noisy"  # the flood
            prio = PRIORITY_SCAVENGER
        elif r < 0.8:
            tenant = "tenant:a"
            prio = (PRIORITY_INTERACTIVE if rng.random() < 0.8
                    else PRIORITY_BATCH)
        else:
            tenant = "tenant:b"
            prio = (PRIORITY_BATCH if rng.random() < 0.7
                    else PRIORITY_INTERACTIVE)
        key = f"{tenant}/u{rng.zipf(1.5) % 40}"
        cost = int(min(max(rng.lognormal(3.0, 1.3), 1.0), 2000.0))
        bulk = rng.random() < 0.25  # a minority rides HBUCKET frames
        rows.append((tenant, key, cost, prio, bulk))
    return rows


async def _drive(store: RemoteBucketStore, rows) -> list[bool]:
    """Run the schedule sequentially (deterministic); bulk rows batch
    per 8 consecutive same-tenant rows when marked."""
    out: list[bool] = []
    i = 0
    while i < len(rows):
        tenant, key, cost, prio, bulk = rows[i]
        if bulk:
            # Gather a small same-tenant run into one HBUCKET frame.
            j = i
            ks, cs = [], []
            while (j < len(rows) and rows[j][0] == tenant
                   and rows[j][4] and j - i < 8):
                ks.append(rows[j][1])
                cs.append(rows[j][2])
                j += 1
            res = await store.acquire_hierarchical_many(
                [tenant] * len(ks), ks, cs, _TENANTS[tenant], _FILL,
                _CHILD_CAP, _CHILD_RATE, priority=prio)
            out.extend(bool(g) for g in res.granted)
            i = j
        else:
            r = await store.acquire_hierarchical(
                tenant, key, cost, _TENANTS[tenant], _FILL,
                _CHILD_CAP, _CHILD_RATE, priority=prio)
            out.append(r.granted)
            i += 1
    return out


def _audit(rows, grants) -> dict[str, float]:
    admitted: dict[str, float] = {t: 0.0 for t in _TENANTS}
    for (tenant, _k, cost, _p, _b), g in zip(rows, grants):
        if g:
            admitted[tenant] += cost
    return admitted


def test_llm_multitenant_soak():
    """Acceptance: per-tenant admitted tokens ≤ budget + epsilon
    envelope under a noisy-neighbor scavenger flood, scavenger shed
    before interactive under envelope serving, differential audit over
    the store's own admission records, deterministic schedule."""
    run(_soak_body())


async def _soak_body():
    rows = _soak_schedule(SEED)

    async def healthy_run():
        backing = InProcessBucketStore(clock=ManualClock())
        async with BucketStoreServer(backing) as srv:
            store = RemoteBucketStore(address=(srv.host, srv.port),
                                      coalesce_requests=False)
            try:
                grants = await _drive(store, rows)
                stats = await store.stats()
            finally:
                await store.aclose()
            return grants, backing, stats

    grants, backing, stats = await healthy_run()
    admitted = _audit(rows, grants)

    # 1. Tenant isolation while healthy: admitted ≤ budget EXACTLY
    # (fill ≈ 0, the authoritative path has no epsilon), and the noisy
    # neighbor's flood never ate another tenant's budget.
    for tenant, cap in _TENANTS.items():
        assert admitted[tenant] <= cap, (tenant, admitted[tenant])
        assert admitted[tenant] >= cap - 2000.0, (
            tenant, admitted[tenant], "budget left unexhausted — the "
            "schedule no longer saturates; grow n_rows")
        # Differential audit over the store's own records: the tenant
        # bucket's balance is exactly capacity − admitted.
        tokens, _ = backing._buckets[(tenant, cap, _FILL)]
        assert tokens == pytest.approx(cap - admitted[tenant],
                                       abs=1e-3), tenant

    # 2. Healthy-path priorities change nothing: scavenger rows were
    # admitted while the noisy tenant's own budget lasted.
    noisy_granted = sum(
        1 for (t, _k, _c, _p, _b), g in zip(rows, grants)
        if g and t == "tenant:noisy")
    assert noisy_granted > 0

    # 3. The velocity signal saw every tenant, denominated in tokens.
    vel = stats["token_velocity"]["tenants"]
    assert set(vel) == set(_TENANTS)
    assert stats["token_velocity"]["observed_tokens"] == pytest.approx(
        sum(admitted.values()))

    # 4. Determinism: the same seed replays the same grant sequence
    # bit-for-bit on a fresh topology.
    grants2, _backing2, _ = await healthy_run()
    assert grants2 == grants

    # 5. Envelope serving (drain-and-handoff window): scavenger sheds
    # first, the envelope is spent on interactive, and the extra
    # admission is bounded by the envelope — budget + epsilon overall.
    src_backing = InProcessBucketStore(clock=ManualClock())
    dst_backing = InProcessBucketStore(clock=ManualClock())
    src = BucketStoreServer(src_backing, snapshot_path=None)
    dst = BucketStoreServer(dst_backing)
    await src.start()
    await dst.start()
    store = RemoteBucketStore(address=(src.host, src.port),
                              coalesce_requests=False)
    successor = RemoteBucketStore(address=(dst.host, dst.port),
                                  coalesce_requests=False)
    try:
        # Some pre-drain consumption so the export carries state.
        await _drive(store, rows[:120])
        shutdown_task = asyncio.ensure_future(
            src.shutdown(successor, window_s=1.5))
        for _ in range(200):
            if src._drain_envelope is not None:
                break
            await asyncio.sleep(0.01)
        assert src._drain_envelope is not None
        env_budget = headroom_budget(_TENANTS["tenant:a"],
                                     fraction=0.5, min_budget=1.0)
        outcomes: dict[int, list[bool]] = {0: [], 1: [], 2: []}
        env_admitted = 0.0
        for i in range(90):
            prio = (PRIORITY_INTERACTIVE, PRIORITY_BATCH,
                    PRIORITY_SCAVENGER)[i % 3]
            cost = 40
            r = await store.acquire_hierarchical(
                "tenant:a", f"tenant:a/e{i % 6}", cost,
                _TENANTS["tenant:a"], _FILL, _CHILD_CAP, _CHILD_RATE,
                priority=prio)
            outcomes[prio].append(r.granted)
            if r.granted:
                env_admitted += cost
        # Scavenger shed before interactive: zero scavenger grants,
        # interactive served from the envelope.
        assert not any(outcomes[PRIORITY_SCAVENGER])
        assert any(outcomes[PRIORITY_INTERACTIVE])
        # Batch never spends the reserved half; interactive outlives it.
        assert (sum(outcomes[PRIORITY_INTERACTIVE])
                >= sum(outcomes[PRIORITY_BATCH]))
        # The envelope bound: window admission ≤ the tenant's envelope
        # (each level's envelope is ≤ this; the tenant level binds).
        assert env_admitted <= env_budget
        await shutdown_task
    finally:
        await store.aclose()
        await successor.aclose()
        await src.aclose()
        await dst.aclose()
