"""Native bulk lane (round 8): OP_ACQUIRE_MANY end-to-end in C.

Covers what the byte-level differential fuzz (test_native_parity_fuzz)
does not: the tier-0 bulk epsilon envelope (per-row local decisions
share the scalar budget — one envelope, not two), the sync-pump
reconciliation of bulk grants, the C-side hot-key feed into the
heavy-hitter sketch, the OP_STATS / OpenMetrics bulk gauges, and the
pinned fall-through behavior of everything that must STAY on the Python
passthrough lane (SAVE, unknown ops, malformed bulk, --no-fe-bulk).
"""

from __future__ import annotations

import asyncio
import struct

import numpy as np
import pytest

from distributedratelimiting.redis_tpu.models.approximate import (
    headroom_budget,
    overadmit_epsilon,
)
from distributedratelimiting.redis_tpu.runtime import wire
from distributedratelimiting.redis_tpu.runtime.native_frontend import (
    Tier0Config,
)
from distributedratelimiting.redis_tpu.runtime.remote import RemoteBucketStore
from distributedratelimiting.redis_tpu.runtime.server import BucketStoreServer
from distributedratelimiting.redis_tpu.runtime.store import InProcessBucketStore
from distributedratelimiting.redis_tpu.utils.native import load_frontend_lib

pytestmark = pytest.mark.skipif(
    load_frontend_lib() is None,
    reason="native front-end library unavailable (no compiler?)")


def run(coro):
    return asyncio.run(coro)


async def _roundtrip_raw(host, port, frames: "list[bytes]") -> list[bytes]:
    """Send raw frames on one fresh connection, read one reply each."""
    reader, writer = await asyncio.open_connection(host, port)
    try:
        for f in frames:
            writer.write(f)
        await writer.drain()
        out = []
        for _ in frames:
            hdr = await asyncio.wait_for(reader.readexactly(4), 10.0)
            (ln,) = struct.unpack("<I", hdr)
            out.append(hdr + await asyncio.wait_for(
                reader.readexactly(ln), 10.0))
        return out
    finally:
        writer.close()
        try:
            await writer.wait_closed()
        except (ConnectionResetError, BrokenPipeError):
            pass


def test_bulk_rows_decide_locally_and_reconcile():
    """Hot bulk rows decide in C (rows_local grows, frames go fully
    local) and the sync pump debits the authoritative store — the
    balance visibly drops by roughly the locally-granted amount."""
    cfg = Tier0Config(sync_interval_s=0.01)
    capacity, fill = 100000.0, 1e-9

    async def body():
        async with BucketStoreServer(InProcessBucketStore(),
                                     native_frontend=True,
                                     native_tier0=cfg) as srv:
            store = RemoteBucketStore(address=(srv.host, srv.port))
            try:
                keys = [f"hot{i % 4}" for i in range(256)]
                counts = [1] * 256
                # Warm: all-residue frame installs the replicas.
                await store.acquire_many(keys, counts, capacity, fill)
                for _ in range(4):
                    res = await store.acquire_many(keys, counts,
                                                   capacity, fill)
                    assert res.granted.all()
                st = await store.stats()
                bulk = st["native_bulk"]
                assert bulk["frames"] == 5
                assert bulk["rows"] == 5 * 256
                assert bulk["rows_local"] > 0
                assert bulk["frames_local"] > 0
                assert bulk["permits_local"] == bulk["rows_local"]
                assert st["tier0"]["hits"] >= bulk["rows_local"] * 0.5
                await asyncio.sleep(0.1)  # several sync rounds
                bal = await asyncio.to_thread(store.peek_blocking,
                                              "hot0", capacity, fill)
                # 5 frames x 64 rows per key were granted somewhere
                # (store or tier-0); after reconciliation the balance
                # reflects all of them (fill ~ 0).
                assert bal == pytest.approx(capacity - 5 * 64, abs=1.0)
            finally:
                await store.aclose()

    run(body())


def test_bulk_tier0_overadmit_bounded():
    """The epsilon differential, bulk edition: per key, granted ≤
    device-only oracle + overadmit_epsilon(budget, fill, sync_s) — the
    SAME formula and budget as the scalar lane (one envelope, not
    two)."""
    capacity, fill = 200.0, 1e-9
    cfg = Tier0Config(sync_interval_s=0.005)
    budget = headroom_budget(capacity, fraction=cfg.budget_fraction,
                             min_budget=cfg.min_budget,
                             max_budget=cfg.max_budget)
    assert budget > 0  # must exercise tier-0, not bypass it
    epsilon = overadmit_epsilon(budget, fill, cfg.sync_interval_s)
    n_keys, per_frame, frames = 4, 30, 20

    async def body():
        async with BucketStoreServer(InProcessBucketStore(),
                                     native_frontend=True,
                                     native_tier0=cfg) as srv:
            store = RemoteBucketStore(address=(srv.host, srv.port))
            try:
                keys = [f"h{i}" for i in range(n_keys)]
                frame_keys = [keys[i % n_keys]
                              for i in range(n_keys * per_frame)]
                counts = [1] * len(frame_keys)
                admitted = {k: 0 for k in keys}
                results = await asyncio.gather(
                    *(store.acquire_many(frame_keys, counts, capacity,
                                         fill) for _ in range(frames)))
                for res in results:
                    for k, g in zip(frame_keys, res.granted):
                        admitted[k] += bool(g)
                for k in keys:
                    # Oracle: with ~zero fill and unit counts, any
                    # serialization admits exactly capacity per key.
                    assert admitted[k] <= capacity + epsilon, (
                        k, admitted[k], epsilon)
                    assert admitted[k] >= capacity * 0.9, (k, admitted[k])
                st = await store.stats()
                if st["native_bulk"]["rows_local"] == 0:
                    # Slow hosts (the sanitizer legs) can drain the whole
                    # storm before the first sync round installs the
                    # replicas. The keys are exhausted, so one more round
                    # against the now-live tier-0 is all local denies —
                    # the bound above is untouched, the guard below stops
                    # being a race on the first 5 ms tick.
                    await asyncio.sleep(cfg.sync_interval_s * 4)
                    await asyncio.gather(
                        *(store.acquire_many(frame_keys, counts,
                                             capacity, fill)
                          for _ in range(3)))
                    st = await store.stats()
                assert st["native_bulk"]["rows_local"] > 0  # not vacuous
            finally:
                await store.aclose()

    run(body())


def test_bulk_hot_keys_feed_the_sketch():
    """The zero-copy bulk lane's PR-2 sketch exemption is closed for the
    native lane: C aggregates per-frame top-K and the harvest pump
    offers it — the skewed keys surface in the server's top-K."""
    async def body():
        async with BucketStoreServer(InProcessBucketStore(),
                                     native_frontend=True) as srv:
            store = RemoteBucketStore(address=(srv.host, srv.port))
            try:
                rng = np.random.default_rng(11)
                hot = [b"whale-a", b"whale-b"]
                for _ in range(6):
                    pool = list(hot) * 40 + [
                        b"c%d" % rng.integers(0, 5000)
                        for _ in range(200)]
                    counts = [1] * len(pool)
                    await store.acquire_many(
                        [k.decode() for k in pool], counts, 1e9, 1e9)
                await asyncio.sleep(0.8)  # ≥ one harvest cadence
                top = [k for k, _c, _e in srv.heavy_hitters.top()]
                assert "whale-a" in top and "whale-b" in top
                st = await store.stats()
                assert st["native_bulk"]["frames"] >= 6
            finally:
                await store.aclose()

    run(body())


def test_bulk_gauges_in_openmetrics():
    async def body():
        async with BucketStoreServer(InProcessBucketStore(),
                                     native_frontend=True) as srv:
            store = RemoteBucketStore(address=(srv.host, srv.port))
            try:
                await store.acquire_many(["a", "b"], [1, 1], 10.0, 1.0)
                text = srv.registry.render()
                assert "native_bulk_frames_total" in text
                assert "native_bulk_rows_residue_total" in text
            finally:
                await store.aclose()

    run(body())


def test_fall_through_cases_unchanged():
    """Pin the passthrough dispatch list after ACQUIRE_MANY went native:
    SAVE (no snapshot path) and unknown ops answer byte-identically on
    the native and asyncio servers — Python stays the authority for
    every non-hot shape."""
    async def body():
        servers = [
            BucketStoreServer(InProcessBucketStore(),
                              native_frontend=False),
            BucketStoreServer(InProcessBucketStore(),
                              native_frontend=True),
        ]
        for s in servers:
            await s.start()
        try:
            save = wire.encode_request(3, wire.OP_SAVE)
            # Unknown op 99 on the keyed-request layout.
            unknown = bytearray(
                wire.encode_request(4, wire.OP_ACQUIRE, "k", 1, 1.0, 1.0))
            unknown[9] = 99
            unknown = bytes(unknown)
            replies = [await _roundtrip_raw(s.host, s.port,
                                            [save, unknown])
                       for s in servers]
            assert replies[0] == replies[1]
            assert b"snapshot-path" in replies[0][0]
            assert b"unknown op" in replies[0][1]
        finally:
            for s in servers:
                await s.aclose()

    run(body())


def test_no_fe_bulk_knob_keeps_passthrough():
    """native_bulk=False restores the round-7 behavior: bulk frames
    serve via the Python passthrough lane (correct replies, zero native
    bulk frames counted)."""
    async def body():
        async with BucketStoreServer(InProcessBucketStore(),
                                     native_frontend=True,
                                     native_bulk=False) as srv:
            store = RemoteBucketStore(address=(srv.host, srv.port))
            try:
                res = await store.acquire_many(
                    [f"u{i % 10}" for i in range(100)], [1] * 100,
                    30.0, 1e-9)
                # 10 distinct keys x 10 requests, capacity 30: all grant.
                assert int(res.granted.sum()) == 100
                st = await store.stats()
                assert "native_bulk" not in st
            finally:
                await store.aclose()

    run(body())


def test_bulk_without_remaining_and_window_kinds():
    """with_remaining=False frames and window kinds ride the native
    lane (windows are always residue — tier-0 is bucket-only)."""
    async def body():
        async with BucketStoreServer(InProcessBucketStore(),
                                     native_frontend=True,
                                     native_tier0=True) as srv:
            store = RemoteBucketStore(address=(srv.host, srv.port))
            try:
                res = await store.acquire_many(
                    ["a", "b", "a"], [1, 1, 1], 1e6, 1e6,
                    with_remaining=False)
                assert res.granted.all() and res.remaining is None
                res = await store.window_acquire_many(
                    [f"w{i % 3}" for i in range(30)], [1] * 30,
                    5.0, 60.0)
                assert int(res.granted.sum()) == 15
                st = await store.stats()
                assert st["native_bulk"]["frames"] == 2
            finally:
                await store.aclose()

    run(body())
