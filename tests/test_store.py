"""Store tests: device vs in-process semantic equivalence, slot lifecycle,
snapshot/restore, counter sync."""

import asyncio

import numpy as np
import pytest

from distributedratelimiting.redis_tpu.ops.bucket_math import TICKS_PER_SECOND
from distributedratelimiting.redis_tpu.runtime.clock import ManualClock
from distributedratelimiting.redis_tpu.runtime.store import (
    DeviceBucketStore,
    InProcessBucketStore,
)


def run(coro):
    return asyncio.run(coro)


@pytest.fixture
def clock():
    return ManualClock()


def device_store(clock, **kw):
    kw.setdefault("n_slots", 64)
    kw.setdefault("counter_slots", 16)
    kw.setdefault("max_delay_s", 0.001)
    return DeviceBucketStore(clock=clock, **kw)


class TestSemanticEquivalence:
    def test_random_ops_agree(self, clock, rng):
        """The TPU store and the serial in-process store must make identical
        decisions on an identical op stream (deterministic manual clock)."""
        dev = device_store(clock)
        ref = InProcessBucketStore(clock=clock)
        cap, rate = 20.0, 8.0

        async def main():
            for _ in range(120):
                clock.advance_ticks(int(rng.integers(0, TICKS_PER_SECOND)))
                key = f"k{rng.integers(0, 10)}"
                count = int(rng.integers(0, 6))
                got = dev.acquire_blocking(key, count, cap, rate)
                want = ref.acquire_blocking(key, count, cap, rate)
                assert got.granted == want.granted, (key, count)
                assert abs(got.remaining - want.remaining) < 1e-2

        run(main())

    def test_batched_async_agrees_with_serial(self, clock, rng):
        dev = device_store(clock)
        ref = InProcessBucketStore(clock=clock)
        cap, rate = 10.0, 2.0

        async def main():
            for round_ in range(10):
                clock.advance_ticks(TICKS_PER_SECOND // 2)
                keys = [f"k{i}" for i in range(8)]
                counts = [int(rng.integers(1, 4)) for _ in keys]
                got = await asyncio.gather(*(
                    dev.acquire(k, c, cap, rate) for k, c in zip(keys, counts)
                ))
                want = [ref.acquire_blocking(k, c, cap, rate)
                        for k, c in zip(keys, counts)]
                for g, w in zip(got, want):
                    assert g.granted == w.granted

        run(main())


class TestSlotLifecycle:
    def test_grow_on_exhaustion(self, clock):
        dev = device_store(clock, n_slots=4)

        async def main():
            # 10 distinct always-draining keys in a 4-slot table: the table
            # must grow (sweep can't reclaim — all buckets stay non-full).
            for i in range(10):
                res = dev.acquire_blocking(f"k{i}", 5, 10.0, 1.0)
                assert res.granted

        run(main())
        table = next(iter(dev._tables.values()))
        assert table.n_slots >= 10
        assert len(table.dir) == 10

    def test_sweep_reclaims_idle_slots(self, clock):
        dev = device_store(clock, n_slots=4)

        async def main():
            for i in range(4):
                dev.acquire_blocking(f"k{i}", 1, 10.0, 10.0)
            # After 2s the buckets are full again (rate 10/s, deficit 1) →
            # sweep frees them instead of growing.
            clock.advance_seconds(2.0)
            dev.acquire_blocking("fresh", 1, 10.0, 10.0)

        run(main())
        table = next(iter(dev._tables.values()))
        assert table.n_slots == 4  # no growth: sweep reclaimed
        assert table.dir.lookup("fresh") is not None

    def test_distinct_configs_get_distinct_tables(self, clock):
        dev = device_store(clock)
        dev.acquire_blocking("k", 1, 10.0, 1.0)
        dev.acquire_blocking("k", 1, 20.0, 1.0)
        assert len(dev._tables) == 2


class TestCounterSync:
    def test_sync_decay_and_instance_estimate(self, clock):
        dev = device_store(clock)

        async def main():
            clock.advance_seconds(1.0)
            res = await dev.sync_counter("bucket", 30.0, 10.0)
            assert res.global_score == 30.0
            clock.advance_seconds(2.0)
            res = await dev.sync_counter("bucket", 5.0, 10.0)
            # 30 decayed by 2s*10/s → 10, +5 = 15.
            assert abs(res.global_score - 15.0) < 1e-3

        run(main())

    def test_matches_inprocess(self, clock, rng):
        dev = device_store(clock)
        ref = InProcessBucketStore(clock=clock)

        async def main():
            for _ in range(20):
                clock.advance_ticks(int(rng.integers(1, 2 * TICKS_PER_SECOND)))
                count = float(rng.integers(0, 20))
                got = await dev.sync_counter("b", count, 5.0)
                want = await ref.sync_counter("b", count, 5.0)
                assert abs(got.global_score - want.global_score) < 1e-2
                assert abs(got.period_ewma_ticks - want.period_ewma_ticks) < 1.0

        run(main())


class TestWindow:
    def test_window_matches_inprocess(self, clock, rng):
        dev = device_store(clock)
        ref = InProcessBucketStore(clock=clock)

        async def main():
            for _ in range(60):
                clock.advance_ticks(int(rng.integers(0, 3 * TICKS_PER_SECOND)))
                key = f"k{rng.integers(0, 4)}"
                count = int(rng.integers(1, 5))
                got = dev.window_acquire_blocking(key, count, 10.0, 5.0)
                want = ref.window_acquire_blocking(key, count, 10.0, 5.0)
                assert got.granted == want.granted, (key, count)

        run(main())


class TestSnapshotRestore:
    def test_roundtrip(self, clock):
        dev = device_store(clock)

        async def main():
            dev.acquire_blocking("a", 3, 10.0, 1.0)
            dev.acquire_blocking("b", 7, 10.0, 1.0)
            await dev.sync_counter("g", 12.0, 1.0)
            snap = dev.snapshot()

            dev2 = device_store(clock)
            dev2.restore(snap)
            # Same immediate decision surface after restore.
            r1 = dev.acquire_blocking("a", 7, 10.0, 1.0)
            r2 = dev2.acquire_blocking("a", 7, 10.0, 1.0)
            assert r1.granted == r2.granted == True  # noqa: E712  (7 left)
            r1 = dev.acquire_blocking("b", 7, 10.0, 1.0)
            r2 = dev2.acquire_blocking("b", 7, 10.0, 1.0)
            assert r1.granted == r2.granted == False  # noqa: E712

        run(main())


class TestEpochRebase:
    def test_rebase_preserves_decisions(self):
        clock = ManualClock(start_ticks=2**30 - 10)
        dev = device_store(clock)

        async def main():
            dev.acquire_blocking("k", 10, 10.0, 1.0)  # drain at t≈2^30
            clock.advance_seconds(5.0)  # crosses the rebase threshold
            # Rebase happens inside now_ticks_checked; elapsed must still be
            # ~5s → 5 tokens refilled.
            res = dev.acquire_blocking("k", 5, 10.0, 1.0)
            assert res.granted
            res = dev.acquire_blocking("k", 1, 10.0, 1.0)
            assert not res.granted
            assert clock.now_ticks() < 2**30  # clock was rebased

        run(main())


class TestRestoreAcrossProcesses:
    def test_restore_realigns_clock_epoch(self):
        """Regression: restoring into a fresh process (new clock epoch) must
        preserve elapsed-since-touch, not clamp it to zero."""
        old_clock = ManualClock(start_ticks=1000 * TICKS_PER_SECOND)  # old uptime
        old = device_store(old_clock)

        async def main():
            old.acquire_blocking("k", 10, 10.0, 1.0)  # drain at old-t
            snap = old.snapshot()

            new_clock = ManualClock(start_ticks=0)  # fresh process
            new = device_store(new_clock)
            new.restore(snap)
            new_clock.advance_seconds(5.0)
            # 5s elapsed since the drain → 5 tokens, despite epoch change.
            assert new.acquire_blocking("k", 5, 10.0, 1.0).granted
            assert not new.acquire_blocking("k", 1, 10.0, 1.0).granted

        run(main())

    def test_restore_includes_window_tables(self):
        clock = ManualClock()
        dev = device_store(clock)

        async def main():
            dev.window_acquire_blocking("w", 8, 10.0, 5.0)
            snap = dev.snapshot()
            dev2 = device_store(ManualClock())
            dev2.restore(snap)
            # Restored window still remembers 8 of 10 consumed.
            assert not dev2.window_acquire_blocking("w", 5, 10.0, 5.0).granted
            assert dev2.window_acquire_blocking("w", 2, 10.0, 5.0).granted

        run(main())


class TestSweepPinning:
    def test_midbatch_sweep_cannot_steal_batch_slot(self):
        """Regression: a sweep triggered by slot exhaustion mid-batch must
        not free a slot already resolved for an earlier request in the same
        batch."""
        clock = ManualClock()
        dev = device_store(clock, n_slots=2)

        async def main():
            dev.acquire_blocking("a", 1, 10.0, 10.0)
            dev.acquire_blocking("b", 1, 10.0, 10.0)
            # Both buckets refill to full within 1s → sweepable.
            clock.advance_seconds(5.0)
            # Batch touches existing "a" AND new "c": allocating "c" sweeps;
            # "a"'s pinned slot must survive in the directory.
            res = await asyncio.gather(
                dev.acquire(("a"), 10, 10.0, 10.0),
                dev.acquire(("c"), 10, 10.0, 10.0),
            )
            assert all(r.granted for r in res)
            table = next(iter(dev._tables.values()))
            assert table.dir.lookup("a") is not None
            assert table.dir.lookup("a") != table.dir.lookup("c")
            # And "a" was actually drained — no cross-contamination (same
            # tick, so no refill yet).
            assert not dev.acquire_blocking("a", 1, 10.0, 10.0).granted

        run(main())


class TestBulkAcquire:
    """acquire_many: one call decides a whole key array, semantics
    identical to issuing the requests in order (duplicates serialize)."""

    def test_bulk_agrees_with_sequential_inprocess_reference(self, clock, rng):
        """Exact parity on duplicate-free calls (duplicates across calls
        and across time are fine — only in-call duplicates are decided
        conservatively, covered by the next test)."""
        dev = device_store(clock, max_batch=8)  # force multi-chunk dispatch
        ref = InProcessBucketStore(clock=clock)
        cap, rate = 10.0, 4.0
        for _ in range(4):
            perm = rng.permutation(24)
            keys = [f"k{i}" for i in perm]
            counts = [int(rng.integers(0, 4)) for _ in range(24)]
            bulk = dev.acquire_many_blocking(keys, counts, cap, rate)
            seq = [ref.acquire_blocking(k, c, cap, rate)
                   for k, c in zip(keys, counts)]
            assert [bool(g) for g in bulk.granted] == [r.granted for r in seq]
            np.testing.assert_allclose(
                bulk.remaining, [r.remaining for r in seq], atol=1e-4)
            clock.advance_seconds(0.5)

    def test_bulk_duplicates_conservative_never_over_admit(self, clock, rng):
        """In-call duplicates: total granted permits per key never exceed
        what the bucket held (the invariant); denials may be conservative
        relative to a serial replay (the documented trade)."""
        dev = device_store(clock, max_batch=8)
        cap, rate = 10.0, 0.0  # no refill: clean conservation accounting
        keys = [f"k{rng.integers(4)}" for _ in range(60)]
        counts = [int(rng.integers(0, 5)) for _ in range(60)]
        bulk = dev.acquire_many_blocking(keys, counts, cap, rate)
        spent: dict[str, int] = {}
        for k, c, g in zip(keys, counts, bulk.granted):
            if g:
                spent[k] = spent.get(k, 0) + c
        assert all(v <= cap for v in spent.values()), spent

    def test_bulk_async_single_await(self, clock):
        dev = device_store(clock, max_batch=8)

        async def main():
            res = await dev.acquire_many(
                [f"a{i}" for i in range(20)], [1] * 20, 5.0, 1.0)
            assert len(res) == 20
            # cap 5: every fresh key grants once... all distinct keys here.
            assert res.granted_count == 20
            # Same key 8 times, cap 5 -> exactly 5 grants in-order.
            res2 = await dev.acquire_many(["hot"] * 8, [1] * 8, 5.0, 1.0)
            assert [bool(g) for g in res2.granted] == [True] * 5 + [False] * 3
            await dev.aclose()

        run(main())

    def test_bulk_oversized_counts_fall_back_to_split_layout(self, clock):
        dev = device_store(clock, max_batch=8)
        res = dev.acquire_many_blocking(
            ["big", "big", "small"], [300, 300, 1], 500.0, 1.0)
        assert [bool(g) for g in res.granted] == [True, False, True]

    def test_bulk_result_indexing_and_iter(self, clock):
        dev = device_store(clock)
        res = dev.acquire_many_blocking(["x", "y"], [1, 9], 5.0, 1.0)
        assert res[0].granted and not res[1].granted
        as_list = list(res)
        assert as_list[0].granted and not as_list[1].granted
        assert len(res) == 2 and res.granted_count == 1

    def test_bulk_empty_call(self, clock):
        dev = device_store(clock)
        res = dev.acquire_many_blocking([], [], 5.0, 1.0)
        assert len(res) == 0 and res.granted_count == 0

    def test_bulk_zipf_duplicates_coalesce_into_grouped_rows(self, clock,
                                                             rng):
        """Heavy duplication routes the bulk call through the grouped
        kernel: launch rows ≈ distinct (key, count) groups, duplicates
        recorded in rows_coalesced, and decisions identical to the scan
        path's conservative serialization."""
        dev = device_store(clock, max_batch=64)
        cap, rate = 10.0, 0.0
        keys = [f"hot{rng.zipf(1.2) % 8}" for _ in range(400)]
        counts = [1] * 400
        res = dev.acquire_many_blocking(keys, counts, cap, rate)
        assert dev.metrics.rows_coalesced >= 400 - 8 * 2
        # Per key: exactly cap grants, on the FIRST occurrences.
        seen: dict[str, int] = {}
        for k, g in zip(keys, res.granted):
            before = seen.get(k, 0)
            assert bool(g) == (before < cap), (k, before)
            seen[k] = before + 1

        # Remaining view matches the per-row reconstruction.
        dev2 = device_store(clock, max_batch=64, coalesce_duplicates=False)
        res2 = dev2.acquire_many_blocking(keys, counts, cap, rate)
        np.testing.assert_array_equal(res.granted, res2.granted)
        np.testing.assert_allclose(res.remaining, res2.remaining, atol=1e-4)

    def test_bulk_mixed_counts_per_key_fall_back_to_scan(self, clock):
        dev = device_store(clock, max_batch=8)
        # "m" has mixed counts in one call → whole call on the scan path,
        # exact cumulative prefixes.
        res = dev.acquire_many_blocking(
            ["m", "m", "m", "n", "n"], [3, 1, 2, 2, 2], 5.0, 0.0)
        assert [bool(g) for g in res.granted] == [True, True, False,
                                                  True, True]

    def test_bulk_grouped_zero_count_probes(self, clock):
        dev = device_store(clock, max_batch=8)
        res = dev.acquire_many_blocking(
            ["p", "p", "p", "p"], [0, 0, 0, 0], 3.0, 0.0)
        assert res.granted.all()
        # Probes consumed nothing.
        assert dev.acquire_many_blocking(["p"], [3], 3.0, 0.0).granted[0]

    def test_window_bulk_zipf_coalesces_and_agrees(self, clock, rng):
        """window_acquire_many rides the same grouped coalescing as the
        bucket bulk path (one launch row per (key, count) group), with
        decisions identical to the per-row scan path."""
        dev = device_store(clock, max_batch=64)
        keys = [f"hw{rng.zipf(1.2) % 6}" for _ in range(300)]
        res = dev.window_acquire_many_blocking(keys, [1] * 300, 4.0, 1.0)
        assert dev.metrics.rows_coalesced >= 300 - 6 * 2
        seen: dict[str, int] = {}
        for k, g in zip(keys, res.granted):
            before = seen.get(k, 0)
            assert bool(g) == (before < 4), (k, before)
            seen[k] = before + 1
        dev2 = device_store(clock, max_batch=64, coalesce_duplicates=False)
        res2 = dev2.window_acquire_many_blocking(keys, [1] * 300, 4.0, 1.0)
        np.testing.assert_array_equal(res.granted, res2.granted)
        np.testing.assert_allclose(res.remaining, res2.remaining, atol=1e-4)

    def test_window_bulk_fixed_agrees_with_sequential(self, clock, rng):
        dev = device_store(clock, max_batch=8)
        ref = InProcessBucketStore(clock=clock)
        for _ in range(3):
            keys = [f"fw{i}" for i in rng.choice(12, size=8, replace=False)]
            counts = [int(c) for c in rng.integers(0, 3, size=8)]
            got = dev.window_acquire_many_blocking(keys, counts, 5.0, 1.0,
                                                   fixed=True)
            want = [ref.fixed_window_acquire_blocking(k, c, 5.0, 1.0)
                    for k, c in zip(keys, counts)]
            assert [bool(g) for g in got.granted] == [w.granted
                                                      for w in want]
            clock.advance_seconds(0.4)

    def test_bulk_default_path_on_inprocess_and_remote_parity(self, clock):
        ref = InProcessBucketStore(clock=clock)
        res = ref.acquire_many_blocking(["a"] * 7, [1] * 7, 5.0, 1.0)
        assert [bool(g) for g in res.granted] == [True] * 5 + [False] * 2

        async def main():
            ref2 = InProcessBucketStore(clock=clock)
            res2 = await ref2.acquire_many(["b"] * 7, [1] * 7, 5.0, 1.0)
            assert res2.granted_count == 5

        run(main())


class TestBulkLimiter:
    def test_partitioned_acquire_many(self, clock):
        from distributedratelimiting.redis_tpu.models.options import (
            TokenBucketOptions,
        )
        from distributedratelimiting.redis_tpu.models.partitioned import (
            PartitionedRateLimiter,
        )

        dev = device_store(clock, max_batch=8)
        lim = PartitionedRateLimiter(
            TokenBucketOptions(token_limit=5, tokens_per_period=1,
                               instance_name="bulk"), dev)

        async def main():
            res = await lim.acquire_many([f"u{i % 10}" for i in range(50)])
            assert len(res) == 50
            # 10 partitions x cap 5 = 50 grants possible; 5 requests each.
            assert res.granted_count == 50
            res2 = await lim.acquire_many(["u0"] * 3)
            assert res2.granted_count == 0  # u0 drained
            assert lim.metrics.decisions == 53
            return True

        assert run(main())

    def test_partitioned_bulk_per_resource_permits_validated(self, clock):
        from distributedratelimiting.redis_tpu.models.options import (
            TokenBucketOptions,
        )
        from distributedratelimiting.redis_tpu.models.partitioned import (
            PartitionedRateLimiter,
        )

        dev = device_store(clock)
        lim = PartitionedRateLimiter(
            TokenBucketOptions(token_limit=5, tokens_per_period=1,
                               instance_name="bulk2"), dev)
        with pytest.raises(ValueError):
            lim.acquire_many_blocking(["a", "b"], [1, 99])  # over limit
        with pytest.raises(ValueError):
            lim.acquire_many_blocking(["a", "b"], [1])  # length mismatch
        res = lim.acquire_many_blocking(["a", "b"], [2, 9 - 5])
        assert res.granted_count == 2


class TestBulkVerdictOnly:
    def test_bits_path_matches_full_path(self, clock, rng):
        dev = device_store(clock, max_batch=8)
        dev2 = device_store(ManualClock(), max_batch=8)
        keys = [f"k{rng.integers(12)}" for _ in range(64)]
        full = dev.acquire_many_blocking(keys, [1] * 64, 5.0, 1.0)
        bits = dev2.acquire_many_blocking(keys, [1] * 64, 5.0, 1.0,
                                          with_remaining=False)
        assert bits.remaining is None
        assert [bool(g) for g in bits.granted] == \
               [bool(g) for g in full.granted]
        assert bits[0].remaining == 0.0  # indexing still works


def test_partitioned_bulk_zero_permit_probe_always_granted():
    """Bulk keeps the single-request contract: permits=0 is granted
    unconditionally, even riding beside a denied same-key request."""
    from distributedratelimiting.redis_tpu.models.options import (
        TokenBucketOptions,
    )
    from distributedratelimiting.redis_tpu.models.partitioned import (
        PartitionedRateLimiter,
    )

    clock = ManualClock()
    dev = device_store(clock)
    lim = PartitionedRateLimiter(
        TokenBucketOptions(token_limit=5, tokens_per_period=1,
                           instance_name="zp"), dev)
    lim.acquire("k", 2)  # bucket at 3
    res = lim.acquire_many_blocking(["k", "k"], [5, 0])
    assert not res[0].granted         # 5 > 3
    assert res[1].granted             # probe: unconditional, as in acquire()
    assert lim.acquire("k", 0).is_acquired


class TestFlushCoalescing:
    """Same-key requests in one flush collapse to one launch row
    (grouped kernel), verdicts identical to per-row serialization."""

    def test_hot_key_one_row_first_n_granted(self, clock):
        dev = device_store(clock, max_batch=64, max_delay_s=0.005)

        async def main():
            results = await asyncio.gather(
                *(dev.acquire("hot", 1, 5.0, 1.0) for _ in range(32)))
            grants = [r.granted for r in results]
            assert grants == [True] * 5 + [False] * 27
            # 32 requests rode as ONE launch row.
            assert dev.metrics.rows_coalesced == 31
            assert dev.metrics.rows_valid == 1
            await dev.aclose()

        run(main())

    def test_mixed_hot_and_cold_keys(self, clock):
        dev = device_store(clock, max_batch=64, max_delay_s=0.005)

        async def main():
            reqs = [("hot", 1)] * 10 + [("cold1", 2), ("cold2", 2)] \
                + [("hot", 1)] * 10
            results = await asyncio.gather(
                *(dev.acquire(k, c, 5.0, 1.0) for k, c in reqs))
            hot = [r.granted for i, r in enumerate(results)
                   if reqs[i][0] == "hot"]
            assert sum(hot) == 5 and hot == [True] * 5 + [False] * 15
            assert all(r.granted for i, r in enumerate(results)
                       if reqs[i][0] != "hot")
            # 22 requests -> 3 rows (hot group + 2 singles).
            assert dev.metrics.rows_coalesced == 19
            await dev.aclose()

        run(main())

    def test_mixed_counts_same_key_stay_exact(self, clock):
        """A key with differing counts in one flush falls back to exact
        per-row cumulative prefixes: 3+1+1 at cap 5 -> all granted, then
        denial."""
        dev = device_store(clock, max_batch=64, max_delay_s=0.005)

        async def main():
            counts = [3, 1, 1, 2]
            results = await asyncio.gather(
                *(dev.acquire("mk", c, 5.0, 1.0) for c in counts))
            assert [r.granted for r in results] == [True, True, True, False]
            await dev.aclose()

        run(main())

    def test_zero_count_probe_groups(self, clock):
        dev = device_store(clock, max_batch=64, max_delay_s=0.005)

        async def main():
            # Probes beside real requests: granted while balance covers the
            # earlier (conservative) demand.
            results = await asyncio.gather(
                dev.acquire("p", 2, 5.0, 1.0),
                dev.acquire("p", 0, 5.0, 1.0),
                dev.acquire("p", 0, 5.0, 1.0),
            )
            assert [r.granted for r in results] == [True, True, True]
            await dev.aclose()

        run(main())

    def test_coalesced_agrees_with_serial_inprocess(self, clock, rng):
        """Differential: duplicate-heavy async traffic vs the serial
        reference, uniform counts per key (the coalesced regime)."""
        dev = device_store(clock, max_batch=64, max_delay_s=0.005)
        ref = InProcessBucketStore(clock=clock)
        cap, rate = 12.0, 3.0

        async def main():
            for round_ in range(6):
                clock.advance_seconds(1.0)
                keys = [f"k{rng.integers(3)}" for _ in range(24)]
                got = await asyncio.gather(
                    *(dev.acquire(k, 1, cap, rate) for k in keys))
                want = [ref.acquire_blocking(k, 1, cap, rate) for k in keys]
                # Per-key grant totals must match (arrival order inside one
                # flush is the gather order — same as the serial replay).
                for key in set(keys):
                    got_n = sum(g.granted for g, kk in zip(got, keys)
                                if kk == key)
                    want_n = sum(w.granted for w, kk in zip(want, keys)
                                 if kk == key)
                    assert got_n == want_n, (round_, key)
            await dev.aclose()

        run(main())

    def test_window_table_hot_key_coalesces(self, clock):
        """Window limiters share the coalescing machinery: a hot key is one
        launch row, first-n-granted semantics."""
        dev = device_store(clock, max_batch=64, max_delay_s=0.005)

        async def main():
            results = await asyncio.gather(
                *(dev.window_acquire("hot", 1, 5.0, 10.0) for _ in range(20)))
            grants = [r.granted for r in results]
            assert grants == [True] * 5 + [False] * 15
            assert dev.metrics.rows_coalesced == 19
            # Serial reference agreement on a fresh store.
            ref = InProcessBucketStore(clock=clock)
            want = [ref.window_acquire_blocking("hot", 1, 5.0, 10.0)
                    for _ in range(20)]
            assert grants == [w.granted for w in want]
            await dev.aclose()

        run(main())

    def test_ablation_toggle_off_uses_per_row_path(self, clock, rng):
        """coalesce_duplicates=False re-enables the per-row host-prefix
        flush; decisions agree with the serial reference the same way."""
        dev = device_store(clock, max_batch=64, max_delay_s=0.005,
                           coalesce_duplicates=False)
        ref = InProcessBucketStore(clock=clock)

        async def main():
            keys = [f"k{rng.integers(3)}" for _ in range(24)]
            got = await asyncio.gather(
                *(dev.acquire(k, 1, 12.0, 3.0) for k in keys))
            want = [ref.acquire_blocking(k, 1, 12.0, 3.0) for k in keys]
            for key in set(keys):
                assert (sum(g.granted for g, kk in zip(got, keys) if kk == key)
                        == sum(w.granted for w, kk in zip(want, keys)
                               if kk == key))
            assert dev.metrics.rows_coalesced == 0
            await dev.aclose()

        run(main())

    def test_coalesced_remaining_matches_per_row_view(self, clock):
        """Each member's remaining is its exact per-row conservative view,
        not the group-wide post-consumption value."""
        dev = device_store(clock, max_batch=64, max_delay_s=0.005)
        off = device_store(ManualClock(), max_batch=64, max_delay_s=0.005,
                           coalesce_duplicates=False)

        async def main():
            got = await asyncio.gather(
                *(dev.acquire("h", 1, 5.0, 1.0) for _ in range(8)))
            want = await asyncio.gather(
                *(off.acquire("h", 1, 5.0, 1.0) for _ in range(8)))
            assert [(r.granted, r.remaining) for r in got] == \
                   [(r.granted, r.remaining) for r in want]
            # First grant sees 4 left, not the group's post-consumption 0.
            assert got[0] == (True, 4.0)
            await dev.aclose()
            await off.aclose()

        run(main())
