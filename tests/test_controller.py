"""Autonomous control plane (ISSUE 12): the controller's policy unit
surface plus THE seeded diurnal + flash-crowd soak.

Unit surface: hysteresis/cooldown edges, actuation-budget exhaustion,
dry-run parity (dry-run decides identically to live and executes
nothing), breaker-driven drain/rejoin, the sketch-fed split decision,
``TokenVelocity`` decay at tick boundaries (why the controller diffs
the monotonic totals instead), ``CounterDeltas`` (the shared
delta-of-counters helper — two consumers never tear each other's
windows, unlike ``stats(reset=True)``), and the destructive-reset
tripwire.

The soak is the acceptance differential: a seeded diurnal traffic swing
plus a 10× flash crowd with a hot flat key, driven over the real wire
against a 3-node cluster under chaos (connect resets, read delays,
controller-tick faults) with ZERO operator calls — the controller alone
splits the hot key (a live migration), steps the shed ladder up through
the swing and back down after it, over-admission stays inside the
epsilon envelope, scavenger sheds before interactive, every action is a
flight-recorder frame, and the same seed replays the identical action
schedule bit for bit. ``make controller-soak SEED=…``
(DRL_CONTROLLER_SEED) replays any schedule."""

from __future__ import annotations

import asyncio
import math
import os
import types

import numpy as np
import pytest

from distributedratelimiting.redis_tpu.runtime.admission import (
    PRIORITY_BATCH,
    PRIORITY_INTERACTIVE,
    PRIORITY_SCAVENGER,
    AdmissionPolicy,
    TenantBudget,
    TokenVelocity,
)
from distributedratelimiting.redis_tpu.runtime.clock import ManualClock
from distributedratelimiting.redis_tpu.runtime.cluster import (
    ClusterBucketStore,
)
from distributedratelimiting.redis_tpu.runtime.controller import (
    SENSOR_SERIES,
    Controller,
    ControllerConfig,
)
from distributedratelimiting.redis_tpu.runtime.server import (
    BucketStoreServer,
)
from distributedratelimiting.redis_tpu.runtime.store import (
    InProcessBucketStore,
)
from distributedratelimiting.redis_tpu.utils import faults
from distributedratelimiting.redis_tpu.utils.faults import (
    FaultInjector,
    FaultRule,
)
from distributedratelimiting.redis_tpu.utils.flight_recorder import (
    FlightRecorder,
)
from distributedratelimiting.redis_tpu.utils.metrics import (
    CounterDeltas,
    LatencyHistogram,
)

SEED = int(os.environ.get("DRL_CONTROLLER_SEED", "20260804"))


def run(coro):
    return asyncio.run(coro)


# -- CounterDeltas: the shared delta-of-counters helper (satellite) ----------

def test_counter_deltas_basics():
    cd = CounterDeltas()
    assert cd.delta("a", 100) == 0.0  # first observation anchors
    assert cd.delta("a", 130) == 30.0
    assert cd.delta("a", 130) == 0.0
    assert cd.rate("a", 180, 2.0) == 25.0
    # Counter reset (server restart): increase since the restart, never
    # a negative delta.
    assert cd.delta("a", 40) == 40.0
    assert cd.deltas({"a": 50, "b": 7}) == {"a": 10.0, "b": 0.0}


def test_counter_deltas_consumers_are_independent():
    """THE satellite bugfix shape: two scrapers deriving windows over
    the same counters never halve each other — unlike two scrapers
    racing ``stats(reset=True)`` over the one shared server window."""
    a, b = CounterDeltas(), CounterDeltas()
    a.delta("x", 100)
    b.delta("x", 100)
    a.delta("x", 150)  # consumer A reads its 50 ...
    assert b.delta("x", 180) == 80.0  # ... B still sees its FULL window
    assert a.delta("x", 180) == 30.0


def test_counter_deltas_bounded():
    cd = CounterDeltas(max_keys=4)
    for i in range(8):
        cd.delta(f"k{i}", 100)
    assert len(cd) == 4
    # A forgotten key re-anchors (under-reports — conservative).
    assert cd.delta("k0", 500) == 0.0
    with pytest.raises(ValueError):
        CounterDeltas(max_keys=0)


def test_latency_histogram_reset_tripwire():
    """The destructive-reset contract's guard: resets are counted and
    the count survives the reset itself, so a concurrent consumer can
    detect its window was torn."""
    h = LatencyHistogram()
    h.record(0.01)
    assert h.resets == 0
    h.reset()
    assert h.total == 0 and h.resets == 1
    h.reset()
    assert h.resets == 2


# -- TokenVelocity at tick boundaries (satellite) ----------------------------

def test_token_velocity_decay_at_tick_boundaries():
    """The decayed gauge moves with WHEN you read it; the monotonic
    totals don't — which is why the controller derives rates by diffing
    ``totals()`` (scrape-time-independent, deterministic) and leaves
    the decayed ``rate()`` for humans."""
    t = [0.0]
    tv = TokenVelocity(tau_s=4.0, clock=lambda: t[0])
    tv.observe("a", 100.0)
    cd = CounterDeltas()
    assert cd.delta("a", tv.totals()["a"]) == 0.0  # anchor
    t[0] += 1.0  # one tick boundary
    assert tv.rate("a") == pytest.approx(
        100.0 * math.exp(-0.25) / 4.0)
    tv.observe("a", 50.0)
    # Decay folded into the gauge state at the boundary ...
    t[0] += 1.0
    expected_s = (100.0 * math.exp(-0.25) + 50.0) * math.exp(-0.25)
    assert tv.rate("a") == pytest.approx(expected_s / 4.0)
    # ... while the totals stayed exact token accounting.
    assert tv.totals()["a"] == 150.0
    assert cd.delta("a", tv.totals()["a"]) == 50.0
    snap = tv.snapshot()
    assert snap["admitted"] == {"a": 150.0}


# -- unit harness ------------------------------------------------------------

class FakeCluster:
    """Inert actuator surface + scripted sensor feed. Actuators RECORD
    but never mutate the feed — sensor streams stay identical across
    live/dry controllers, which is what the parity contract compares."""

    def __init__(self, feed):
        self.feed = list(feed)
        self.calls: list[tuple] = []
        self.placement = types.SimpleNamespace(overrides={})
        self.flight_recorder = None

    async def stats(self):
        return self.feed.pop(0) if self.feed else self.feed_last

    @property
    def feed_last(self):
        return {"nodes": [], "resilience": {}, "placement": {}}

    async def split_hot_keys(self, top_n=1, min_count=0.0):
        self.calls.append(("split", top_n))
        return ["k/hot"]

    async def rebalance(self, reason=""):
        self.calls.append(("rebalance", reason))
        return 1

    async def drain_node(self, j):
        self.calls.append(("drain", j))
        return 1

    async def rejoin_node(self, j):
        self.calls.append(("rejoin", j))
        return 1


class ShedTarget:
    def __init__(self):
        self.levels: list = []

    def set_shed_level(self, level):
        self.levels.append(level)


def _tick_stats(*, reqs=(100, 100), admitted=None, hot=None,
                breakers=None, slot_counts=None, drained=()):
    nodes = []
    for j, r in enumerate(reqs):
        ns: dict = {"requests_served": r}
        if j == 0:
            if admitted is not None:
                ns["token_velocity"] = {"admitted": dict(admitted)}
            if hot is not None:
                ns["hot_keys"] = {"top": [
                    {"key": k, "count": c, "error": 0.0}
                    for k, c in hot.items()]}
        nodes.append(ns)
    out = {"nodes": nodes, "resilience": {}, "placement": {
        "slot_counts": list(slot_counts or [8] * len(reqs)),
        "drained": list(drained)}}
    if breakers is not None:
        out["resilience"]["breakers"] = [{"state": s} for s in breakers]
    return out


def _pressure_feed(n, tokens_per_tick, hot_per_tick=0.0):
    """n ticks of steady token/hot-key counter growth (plus one anchor
    tick — CounterDeltas reports zero on its first observation)."""
    feed = []
    admitted = hot = 0.0
    for i in range(n + 1):
        feed.append(_tick_stats(
            reqs=(100 * (i + 1), 100 * (i + 1)),
            admitted={"acme": admitted},
            hot={"k/hot": hot, "k/cold": 10.0 * (i + 1)}))
        admitted += tokens_per_tick
        hot += hot_per_tick
    return feed


def _cfg(**kw):
    base = dict(tick_s=1.0, token_rate_capacity=400.0,
                shed_high=0.9, shed_low=0.6,
                shed_raise_ticks=2, shed_lower_ticks=2,
                split_share=0.3, split_min_tokens=50.0,
                split_streak_ticks=2, cooldown_ticks=2,
                budget_actions=8, budget_window_ticks=50)
    base.update(kw)
    return ControllerConfig(**base)


async def _drive_ticks(ctrl, n):
    out = []
    for _ in range(n):
        out.extend(await ctrl.tick())
    return out


# -- config validation -------------------------------------------------------

def test_config_validation():
    with pytest.raises(ValueError, match="hysteresis band"):
        ControllerConfig(shed_high=0.5, shed_low=0.5)
    with pytest.raises(ValueError, match="tick_s"):
        ControllerConfig(tick_s=0.0)
    with pytest.raises(ValueError, match="interactive"):
        ControllerConfig(shed_floor=PRIORITY_INTERACTIVE)
    with pytest.raises(ValueError, match="budget_actions"):
        ControllerConfig(budget_actions=0)
    with pytest.raises(ValueError, match="token_rate_capacity"):
        ControllerConfig(token_rate_capacity=-1.0)


# -- hysteresis / cooldown edges ---------------------------------------------

def test_shed_hysteresis_edges():
    """One tick over the threshold decides nothing; the streak edge
    (raise_ticks consecutive) fires exactly once; the middle band
    resets both streaks."""
    run(_shed_hysteresis_body())


async def _shed_hysteresis_body():
    # 500 tokens/tick over capacity 400 → pressure 1.25 ≥ 0.9.
    feed = _pressure_feed(10, 500.0)
    ctrl = Controller(FakeCluster(feed), config=_cfg())
    await ctrl.tick()  # anchor: rates are 0, nothing can fire
    assert ctrl.actions == [] and ctrl.shed_level is None
    acts = await _drive_ticks(ctrl, 1)  # streak 1 < raise_ticks 2
    assert acts == [] and ctrl.shed_level is None
    acts = await _drive_ticks(ctrl, 1)  # streak 2 → raise
    assert [a["action"] for a in acts] == ["shed_raise"]
    assert ctrl.shed_level == PRIORITY_SCAVENGER
    assert ctrl.last_pressure == pytest.approx(1.25)


def test_reservation_pressure_raises_shed_before_settled_rate():
    """Satellite (round 13): outstanding reserved-but-unsettled tokens
    fold into the shed pressure as a prospective rate over the
    reservation horizon — the ladder steps up while the SETTLED token
    rate alone is still under the threshold (brownout before the
    unsettled load lands), through the same hysteresis streaks."""
    run(_reservation_pressure_body())


async def _reservation_pressure_body():
    # Settled rate 200/tick over capacity 400 → pressure 0.5 alone
    # (below shed_high 0.9). Outstanding 2000 tokens over horizon 10s
    # adds a prospective 200/s → combined pressure 1.0 ≥ 0.9.
    def with_outstanding(feed, tokens):
        for st in feed:
            st["nodes"][0]["reservations"] = {
                "outstanding_tokens": tokens}
        return feed

    calm = Controller(FakeCluster(_pressure_feed(6, 200.0)),
                      config=_cfg(reservation_horizon_s=10.0))
    await _drive_ticks(calm, 6)
    assert calm.shed_level is None  # settled rate alone: no brownout
    assert calm.last_pressure == pytest.approx(0.5)

    feed = with_outstanding(_pressure_feed(6, 200.0), 2000.0)
    ctrl = Controller(FakeCluster(feed),
                      config=_cfg(reservation_horizon_s=10.0))
    acts = await _drive_ticks(ctrl, 3)  # anchor + raise streak of 2
    assert [a["action"] for a in acts] == ["shed_raise"]
    assert ctrl.shed_level == PRIORITY_SCAVENGER
    assert ctrl.last_pressure == pytest.approx(1.0)
    assert ctrl.last_outstanding == pytest.approx(2000.0)
    assert ctrl.numeric_stats()["outstanding_tokens"] == \
        pytest.approx(2000.0)
    # Dry-run parity holds for the new sensor: same feed, identical
    # decision stream, zero shed pushes.
    target = ShedTarget()
    dry = Controller(
        FakeCluster(with_outstanding(_pressure_feed(6, 200.0),
                                     2000.0)),
        config=_cfg(reservation_horizon_s=10.0, dry_run=True),
        shed_targets=[target])
    dry_acts = await _drive_ticks(dry, 3)
    assert [(a["action"], a["target"]) for a in dry_acts] == \
        [(a["action"], a["target"]) for a in acts]
    assert dry.shed_level == PRIORITY_SCAVENGER
    assert target.levels == []


def test_shed_middle_band_resets_streak():
    run(_shed_middle_band_body())


async def _shed_middle_band_body():
    # Alternate high/middle pressure: the raise streak can never reach
    # 2 consecutive → no action, ever.
    feed = []
    admitted = 0.0
    for i in range(12):
        feed.append(_tick_stats(admitted={"acme": admitted}))
        admitted += 500.0 if i % 2 == 0 else 300.0  # 1.25 / 0.75
    ctrl = Controller(FakeCluster(feed), config=_cfg())
    await _drive_ticks(ctrl, 12)
    assert ctrl.actions == [] and ctrl.shed_level is None


def test_shed_ladder_full_cycle_and_floor():
    """Sustained pressure walks the ladder None→scavenger→batch and
    stops at the floor (interactive is never shed autonomously); the
    release walks it back batch→scavenger→None."""
    run(_shed_ladder_body())


async def _shed_ladder_body():
    feed = _pressure_feed(14, 500.0) + _pressure_feed(14, 100.0)[1:]
    ctrl = Controller(FakeCluster(feed), config=_cfg())
    await _drive_ticks(ctrl, 15)  # high-pressure phase
    raises = [a for a in ctrl.actions if a["action"] == "shed_raise"]
    assert [a["target"] for a in raises] == [PRIORITY_SCAVENGER,
                                             PRIORITY_BATCH]
    assert ctrl.shed_level == PRIORITY_BATCH  # the floor: stays there
    await _drive_ticks(ctrl, 14)  # low-pressure phase
    lowers = [a for a in ctrl.actions if a["action"] == "shed_lower"]
    assert [a["target"] for a in lowers] == [PRIORITY_SCAVENGER, None]
    assert ctrl.shed_level is None


def test_cooldown_edge_is_exact():
    """After an actuator fires at tick t, the same actuator cannot fire
    again before tick t + cooldown_ticks + 1 — and fires exactly at the
    edge when its condition held throughout."""
    run(_cooldown_body())


async def _cooldown_body():
    feed = _pressure_feed(20, 500.0)
    ctrl = Controller(FakeCluster(feed), config=_cfg(
        cooldown_ticks=3, shed_raise_ticks=1))
    await ctrl.tick()  # anchor
    acts = await _drive_ticks(ctrl, 1)
    assert [a["action"] for a in acts] == ["shed_raise"]
    first_tick = ctrl.actions[-1]["tick"]
    # Cooldown window: streak keeps qualifying, nothing may fire.
    for _ in range(3):
        assert await ctrl.tick() == []
    acts = await ctrl.tick()  # the edge
    assert [a["action"] for a in acts] == ["shed_raise"]
    assert ctrl.actions[-1]["tick"] == first_tick + 4  # cooldown 3 + 1


# -- actuation budget ---------------------------------------------------------

def test_budget_exhaustion_is_logged_not_silent():
    run(_budget_body())


async def _budget_body():
    # cooldown 0 → the split condition may fire every tick; budget 2
    # per 6-tick window throttles it.
    feed = _pressure_feed(12, 500.0, hot_per_tick=400.0)
    fake = FakeCluster(feed)
    ctrl = Controller(fake, config=_cfg(
        token_rate_capacity=None,  # isolate the split actuator
        cooldown_ticks=0, split_streak_ticks=1,
        budget_actions=2, budget_window_ticks=6))
    await ctrl.tick()  # anchor
    await _drive_ticks(ctrl, 4)
    executed = [a for a in ctrl.actions if a["outcome"] == "executed"]
    starved = [a for a in ctrl.actions
               if a["outcome"] == "budget_exhausted"]
    assert len(executed) == 2
    assert len(starved) >= 1  # visible, not silently dropped
    assert len([c for c in fake.calls if c[0] == "split"]) == 2
    assert ctrl.budget_remaining() == 0
    # The window rolls: eventually the actuator breathes again.
    await _drive_ticks(ctrl, 7)
    assert len([a for a in ctrl.actions
                if a["outcome"] == "executed"]) > 2


# -- dry-run parity -----------------------------------------------------------

def test_dry_run_decides_identically_and_executes_nothing():
    run(_dry_run_body())


async def _dry_run_body():
    def feed():
        return (_pressure_feed(10, 500.0, hot_per_tick=400.0)
                + _pressure_feed(10, 100.0)[1:])

    live_fake, dry_fake = FakeCluster(feed()), FakeCluster(feed())
    live_target, dry_target = ShedTarget(), ShedTarget()
    live = Controller(live_fake, config=_cfg(),
                      shed_targets=[live_target])
    dry = Controller(dry_fake, config=_cfg(dry_run=True),
                     shed_targets=[dry_target])
    await _drive_ticks(live, 20)
    await _drive_ticks(dry, 20)

    def schedule(c):
        return [(a["tick"], a["action"], a["target"]) for a in c.actions]

    assert schedule(live) == schedule(dry)
    assert len(live.actions) > 2  # non-vacuous: decisions happened
    assert all(a["outcome"] == "dry_run" for a in dry.actions)
    # Dry-run touched NOTHING: no actuator calls, no shed pushes …
    assert dry_fake.calls == [] and dry_target.levels == []
    assert live_fake.calls != [] and live_target.levels != []
    # … yet its DECIDED shed level evolved identically (the parity
    # contract: gating state marches in lockstep).
    assert dry.shed_level == live.shed_level


def test_partial_scrape_never_spikes_pressure():
    """Review regression: deltas are taken per node THEN summed. A
    node missing from one scrape (down-node ``{}`` in the fan-out)
    must cost only that node's contribution for the gap — a
    fleet-summed counter would drop below its last value and the
    reset convention would report the whole remaining sum as one
    tick's phantom 'increase', shedding real traffic over a sensor
    blip."""
    run(_partial_scrape_body())


async def _partial_scrape_body():
    def both_nodes(a0, a1):
        return {"nodes": [
            {"requests_served": 100,
             "token_velocity": {"admitted": {"acme": a0}}},
            {"requests_served": 100,
             "token_velocity": {"admitted": {"acme": a1}}},
        ], "resilience": {}, "placement": {"slot_counts": [8, 8],
                                           "drained": []}}

    base = 1_000_000.0  # large lifetime counters make the spike huge
    feed = [
        both_nodes(base, base),                  # anchor
        both_nodes(base + 100, base + 100),      # steady 200/tick
        {"nodes": [{},                           # node0 drops out
                   {"requests_served": 100,
                    "token_velocity": {"admitted":
                                       {"acme": base + 200}}}],
         "resilience": {}, "placement": {"slot_counts": [8, 8],
                                         "drained": []}},
        both_nodes(base + 300, base + 300),      # recovery
        both_nodes(base + 400, base + 400),
    ]
    ctrl = Controller(FakeCluster(feed), config=_cfg(
        shed_raise_ticks=1))  # ANY high-pressure tick would act
    pressures = []
    for _ in range(len(feed)):
        await ctrl.tick()
        pressures.append(ctrl.last_pressure)
    # Steady 200 tokens/tick over capacity 400 → pressure ≤ ~1 even
    # across the outage gap (the recovery delta spans two ticks).
    assert max(pressures) <= 1.01, pressures
    assert ctrl.actions == []


def test_shed_without_targets_is_noop_not_executed():
    """Review regression: a shed decision with no attached gateways
    must not enter the audit trail as a brownout that 'executed' —
    nothing anywhere shed. The decided level still evolves (it is
    scrapeable state gateways can poll)."""
    run(_shed_noop_body())


async def _shed_noop_body():
    ctrl = Controller(FakeCluster(_pressure_feed(6, 500.0)),
                      config=_cfg())  # no shed_targets
    await _drive_ticks(ctrl, 4)
    raises = [a for a in ctrl.actions if a["action"] == "shed_raise"]
    assert raises and all(a["outcome"] == "noop" for a in raises)
    assert ctrl.shed_level == PRIORITY_SCAVENGER


# -- breaker-driven membership ------------------------------------------------

def test_breaker_drain_and_rejoin():
    run(_breaker_body())


async def _breaker_body():
    feed = []
    for _ in range(5):  # open streak builds
        feed.append(_tick_stats(breakers=["closed", "open"]))
    for _ in range(6):  # recovery
        feed.append(_tick_stats(breakers=["closed", "closed"]))
    fake = FakeCluster(feed)
    ctrl = Controller(fake, config=_cfg(
        token_rate_capacity=None, drain_after_open_ticks=3,
        cooldown_ticks=0))
    await _drive_ticks(ctrl, 3)
    assert ("drain", 1) in fake.calls
    assert ctrl.auto_drained == {1}
    drains = [a for a in ctrl.actions if a["action"] == "drain"]
    assert drains[0]["target"] == 1 and drains[0]["outcome"] == "executed"
    # No re-drain while it stays open and already auto-drained.
    await _drive_ticks(ctrl, 2)
    assert len([c for c in fake.calls if c[0] == "drain"]) == 1
    # Closed streak → rejoin, and only because WE drained it.
    await _drive_ticks(ctrl, 6)
    assert ("rejoin", 1) in fake.calls
    assert ctrl.auto_drained == set()


def test_dry_run_membership_parity():
    """Review regression: auto_drained is DECISION state — a dry-run
    controller must decide drain exactly once and later decide the
    rejoin, like live would, instead of re-deciding the drain every
    cooldown and never reaching the rejoin gate."""
    run(_dry_membership_body())


async def _dry_membership_body():
    def feed():
        return ([_tick_stats(breakers=["closed", "open"])
                 for _ in range(5)]
                + [_tick_stats(breakers=["closed", "closed"])
                   for _ in range(6)])

    cfg = dict(token_rate_capacity=None, drain_after_open_ticks=3,
               cooldown_ticks=0)
    live_fake, dry_fake = FakeCluster(feed()), FakeCluster(feed())
    live = Controller(live_fake, config=_cfg(**cfg))
    dry = Controller(dry_fake, config=_cfg(**cfg, dry_run=True))
    await _drive_ticks(live, 11)
    await _drive_ticks(dry, 11)
    assert [(a["tick"], a["action"], a["target"]) for a in live.actions] \
        == [(a["tick"], a["action"], a["target"]) for a in dry.actions]
    assert [a["action"] for a in dry.actions] == ["drain", "rejoin"]
    assert dry_fake.calls == [] and dry.auto_drained == set()


# -- split / rebalance decisions ----------------------------------------------

def test_split_fires_on_sustained_hot_share():
    run(_split_body())


async def _split_body():
    feed = _pressure_feed(8, 500.0, hot_per_tick=400.0)
    fake = FakeCluster(feed)
    ctrl = Controller(fake, config=_cfg(token_rate_capacity=None))
    await ctrl.tick()  # anchor
    await ctrl.tick()  # streak 1
    assert not [c for c in fake.calls if c[0] == "split"]
    await ctrl.tick()  # streak 2 → split
    splits = [a for a in ctrl.actions if a["action"] == "split"]
    assert len(splits) == 1
    assert splits[0]["target"] == "k/hot"
    assert splits[0]["split_keys"] == ["k/hot"]  # sketch-fed executor
    assert splits[0]["outcome"] == "executed"


def test_split_respects_existing_override():
    run(_split_override_body())


async def _split_override_body():
    feed = _pressure_feed(8, 500.0, hot_per_tick=400.0)
    fake = FakeCluster(feed)
    fake.placement.overrides = {"k/hot": 1}  # already pinned
    ctrl = Controller(fake, config=_cfg(token_rate_capacity=None))
    await _drive_ticks(ctrl, 8)
    assert [c for c in fake.calls if c[0] == "split"] == []


def test_rebalance_fires_on_slot_spread():
    run(_rebalance_body())


async def _rebalance_body():
    feed = [_tick_stats(slot_counts=[14, 2]) for _ in range(6)]
    fake = FakeCluster(feed)
    ctrl = Controller(fake, config=_cfg(token_rate_capacity=None))
    await _drive_ticks(ctrl, 3)
    rebs = [a for a in ctrl.actions if a["action"] == "rebalance"]
    assert len(rebs) == 1 and rebs[0]["outcome"] == "executed"
    assert ("rebalance", "controller") in fake.calls


# -- audit surfaces -----------------------------------------------------------

def test_action_log_bounded_like_migration_log():
    ctrl = Controller(FakeCluster([]), config=_cfg())
    for i in range(600):
        ctrl._log_action({"tick": i, "action": "split", "target": "k",
                          "reason": "r", "outcome": "dry_run"})
    assert len(ctrl.actions) == 512
    assert ctrl.actions[0]["tick"] == 88  # newest 512 win
    assert ctrl.actions_recorded == 600


def test_metrics_and_stats_surfaces():
    run(_metrics_body())


async def _metrics_body():
    feed = _pressure_feed(6, 500.0, hot_per_tick=400.0)
    fr = FlightRecorder(capacity=64)
    ctrl = Controller(FakeCluster(feed), config=_cfg(),
                      flight_recorder=fr)
    await _drive_ticks(ctrl, 6)
    assert ctrl.actions  # non-vacuous
    text = ctrl.metrics_registry().render()
    assert "drl_controller_ticks_total 6" in text
    assert 'drl_controller_actions_total{action="split",' \
           'outcome="executed"}' in text
    assert "drl_controller_shed_level" in text
    st = ctrl.stats()
    assert st["ticks"] == 6 and st["actions"]
    assert any(k.startswith("split:") for k in st["actions_total"])
    # Every action is a flight-recorder frame (kind="controller").
    frames = fr.frames(kind="controller")
    assert [(f["tick"], f["action"], f["outcome"]) for f in frames] == \
        [(a["tick"], a["action"], a["outcome"]) for a in ctrl.actions]


def test_tick_seam_fault_fails_tick_loudly():
    run(_seam_body())


async def _seam_body():
    fr = FlightRecorder(capacity=16)
    ctrl = Controller(FakeCluster(_pressure_feed(4, 500.0)),
                      config=_cfg(), flight_recorder=fr)
    faults.install(FaultInjector(1, {
        "controller.tick": (FaultRule("error", probability=1.0,
                                      max_faults=2),)}))
    try:
        assert await ctrl.tick() == []
        assert await ctrl.tick() == []
        assert ctrl.tick_failures == 2 and ctrl.ticks == 0
        fault_frames = [f for f in fr.frames(kind="controller")
                        if f["outcome"] == "fault"]
        assert len(fault_frames) == 2
        # The seam heals (max_faults) → the loop resumes deciding.
        await ctrl.tick()
        assert ctrl.ticks == 1
    finally:
        faults.uninstall()


def test_scrape_never_resets_server_windows():
    """The sensor path must never use the destructive reset — the
    controller composes with operator measurement windows by contract
    (utils/metrics.py)."""
    run(_no_reset_body())


async def _no_reset_body():
    backing = InProcessBucketStore(clock=ManualClock())
    async with BucketStoreServer(backing) as srv:
        cluster = ClusterBucketStore(addresses=[(srv.host, srv.port)],
                                     coalesce_requests=False)
        try:
            ctrl = Controller(cluster, config=_cfg())
            for _ in range(3):
                await ctrl.tick()
            st = await cluster.stats()
            assert st["nodes"][0]["stats_resets"] == 0
            assert st["controller"]["ticks"] == 3  # OP_STATS visibility
        finally:
            await cluster.aclose()


def test_sensor_series_declaration_matches_module_shape():
    # The drl-check metric-name rule parses this tuple; keep it honest.
    assert len(SENSOR_SERIES) >= 5
    assert all(s.startswith("drl_") for s in SENSOR_SERIES)


# -- THE seeded diurnal + flash-crowd soak (acceptance) ----------------------

_TENANTS = {
    "tenant:a": 50_000.0,
    "tenant:b": 30_000.0,
    "tenant:noisy": 60_000.0,
}
_FILL = 1e-9
_CHILD_CAP, _CHILD_RATE = 100_000.0, 1e-9
_FLAT_CAP, _FLAT_RATE = 20_000.0, 1e-9
_FLAT_KEY = "flash/hot"
_N_TICKS = 36
_FLASH = range(12, 24)  # the 10× swing window
_TOKEN_CAPACITY = 800.0  # sustainable tokens/sec for the shed ladder


def _soak_schedule(seed: int):
    """Deterministic per-tick row lists. Normal ticks: a diurnal sine on
    tenant:a plus light tenant:b/noisy traffic (~165 tokens/tick ⇒
    pressure ~0.2). Flash ticks: tenant:noisy floods 10× — interactive
    heavy-cost rows plus a scavenger tail — and a hot FLAT key takes a
    large token share (the split candidate). Rows are
    ``(lane, tenant, key, cost, priority)``."""
    rng = np.random.default_rng(seed)
    ticks = []
    for t in range(_N_TICKS):
        rows = []
        n_a = 3 + int(round(2 * math.sin(2 * math.pi * t / _N_TICKS)))
        for _ in range(max(1, n_a)):
            cost = int(min(max(rng.lognormal(3.0, 0.8), 1.0), 200.0))
            prio = (PRIORITY_INTERACTIVE if rng.random() < 0.7
                    else PRIORITY_BATCH)
            rows.append(("hier", "tenant:a",
                         f"tenant:a/u{rng.integers(20)}", cost, prio))
        for _ in range(2):
            cost = int(min(max(rng.lognormal(3.0, 0.8), 1.0), 200.0))
            prio = (PRIORITY_BATCH if rng.random() < 0.6
                    else PRIORITY_INTERACTIVE)
            rows.append(("hier", "tenant:b",
                         f"tenant:b/u{rng.integers(10)}", cost, prio))
        if t in _FLASH:
            for i in range(6):
                rows.append(("hier", "tenant:noisy",
                             f"tenant:noisy/h{i % 3}",
                             int(100 + rng.integers(50)),
                             PRIORITY_INTERACTIVE))
            for _ in range(4):
                rows.append(("hier", "tenant:noisy",
                             f"tenant:noisy/s{rng.integers(4)}",
                             int(60 + rng.integers(40)),
                             PRIORITY_SCAVENGER))
            for _ in range(8):
                rows.append(("flat", None, _FLAT_KEY, 60,
                             PRIORITY_INTERACTIVE))
        else:
            rows.append(("hier", "tenant:noisy",
                         f"tenant:noisy/u{rng.integers(6)}",
                         int(20 + rng.integers(20)),
                         PRIORITY_INTERACTIVE))
        ticks.append(rows)
    return ticks


_CHAOS_RULES = {
    # Wire chaos: connect resets are provably-before-send (safely
    # retried), read delays stretch RTTs. Both deterministic per seam
    # occurrence; sequential driving pins the occurrence order.
    "client.connect": (FaultRule("reset", probability=0.1),),
    "client.read": (FaultRule("delay", probability=0.05,
                              delay_s=0.0005),),
    # And the controller's own seam: ~1 in 10 reconciliation rounds
    # fails outright — the loop must degrade to inaction, not flap.
    "controller.tick": (FaultRule("error", probability=0.1),),
}


async def _soak_once(seed: int):
    """One full soak run. Returns everything the assertions (and the
    determinism replay) need."""
    schedule = _soak_schedule(seed)
    backings = [InProcessBucketStore(clock=ManualClock())
                for _ in range(3)]
    servers = [BucketStoreServer(b) for b in backings]
    for s in servers:
        await s.start()
    fr = FlightRecorder(capacity=512)
    cluster = ClusterBucketStore(
        addresses=[(s.host, s.port) for s in servers],
        coalesce_requests=False, flight_recorder=fr)
    policy = AdmissionPolicy(cluster, key_config=(_CHILD_CAP,
                                                  _CHILD_RATE))
    for tenant, cap in _TENANTS.items():
        policy.set_tenant(TenantBudget(tenant, cap, _FILL))
    ctrl = Controller(cluster, config=ControllerConfig(
        tick_s=1.0, token_rate_capacity=_TOKEN_CAPACITY,
        shed_high=0.9, shed_low=0.6,
        shed_raise_ticks=2, shed_lower_ticks=2,
        split_share=0.2, split_min_tokens=100.0, split_streak_ticks=2,
        cooldown_ticks=2, budget_actions=12, budget_window_ticks=100),
        shed_targets=[policy], flight_recorder=fr)
    faults.install(FaultInjector(seed, _CHAOS_RULES))
    outcomes = []  # (tick, lane, tenant, prio, cost, granted)
    shed_at_tick = []
    try:
        for t, rows in enumerate(schedule):
            shed_at_tick.append(ctrl.shed_level)
            for lane, tenant, key, cost, prio in rows:
                try:
                    if lane == "hier":
                        r = await policy.acquire(tenant, key, cost,
                                                 priority=prio)
                    else:
                        r = await cluster.acquire(key, cost, _FLAT_CAP,
                                                  _FLAT_RATE)
                    granted = r.granted
                except ConnectionError:
                    granted = False  # injected, deterministic
                outcomes.append((t, lane, tenant, prio, cost, granted))
            for b in backings:
                b.clock.advance_seconds(1.0)
            await ctrl.tick()
        node_stats = await cluster.stats()
    finally:
        faults.uninstall()
        await cluster.aclose()
        for s, b in zip(servers, backings):
            await s.aclose()
            await b.aclose()
    return {
        "outcomes": outcomes,
        "shed_at_tick": shed_at_tick,
        "actions": list(ctrl.actions),
        "controller": ctrl,
        "cluster_stats": node_stats,
        "backings": backings,
        "overrides": dict(cluster.placement.overrides),
        "migration_log": list(cluster.migration_log),
        "flight": fr.frames(kind="controller"),
        "policy": policy,
    }


def _action_schedule(actions):
    return [(a["tick"], a["action"], str(a["target"]), a["outcome"])
            for a in actions]


def test_controller_diurnal_flash_crowd_soak():
    """Acceptance: zero operator calls — the controller alone splits
    the hot key (live migration under chaos), walks the shed ladder up
    through the 10× swing and back, over-admission stays inside the
    epsilon envelope, scavenger sheds before interactive, every action
    is a flight frame, and the same seed replays the same schedule."""
    run(_soak_body())


async def _soak_body():
    res = await _soak_once(SEED)
    ctrl = res["controller"]
    actions = res["actions"]

    # 1. The controller ALONE split the hot flat key: a placement
    # override exists, the migration committed, and the only membership
    # events are the controller's hot-splits (zero operator calls).
    assert _FLAT_KEY in res["overrides"], actions
    splits = [a for a in actions
              if a["action"] == "split" and a["outcome"] == "executed"]
    assert splits and _FLAT_KEY in splits[0].get("split_keys", [])
    commits = [e for e in res["migration_log"] if e["type"] == "commit"]
    assert commits, "the hot split never committed"
    assert all(e["reason"].startswith("hot-split") for e in commits)

    # 2. The shed ladder stepped up during the flash and released after
    # it: scavenger shed first, and interactive was never shed.
    raises = [a for a in actions if a["action"] == "shed_raise"
              and a["outcome"] == "executed"]
    lowers = [a for a in actions if a["action"] == "shed_lower"
              and a["outcome"] == "executed"]
    assert raises and raises[0]["target"] == PRIORITY_SCAVENGER
    assert min(a["target"] for a in raises) >= PRIORITY_BATCH
    assert lowers and lowers[-1]["target"] is None
    assert ctrl.shed_level is None  # the swing fully released
    assert raises[0]["tick"] - 1 in _FLASH  # raised DURING the crowd

    # 3. Shed order honored at the edge: in ticks served at shed level
    # scavenger, every scavenger row was denied while interactive rows
    # were granted in the same ticks.
    shed_ticks = {t for t, lvl in enumerate(res["shed_at_tick"])
                  if lvl == PRIORITY_SCAVENGER}
    scav = [(t, g) for (t, lane, _tn, p, _c, g) in res["outcomes"]
            if p == PRIORITY_SCAVENGER and t in shed_ticks]
    inter = [(t, g) for (t, lane, _tn, p, _c, g) in res["outcomes"]
             if p == PRIORITY_INTERACTIVE and lane == "hier"
             and t in shed_ticks]
    assert scav and not any(g for _, g in scav)
    assert any(g for _, g in inter)
    assert res["policy"].shed > 0  # shed at the EDGE, store untouched

    # 4. Over-admission inside the epsilon envelope, audited over the
    # stores' OWN buckets. Healthy hierarchical admission is exact:
    # tenant balance == capacity − admitted (fill ≈ 0).
    admitted: dict[str, float] = {t: 0.0 for t in _TENANTS}
    for (_t, lane, tenant, _p, cost, granted) in res["outcomes"]:
        if granted and lane == "hier":
            admitted[tenant] += cost
    for tenant, cap in _TENANTS.items():
        assert admitted[tenant] <= cap
        balance = None
        for b in res["backings"]:
            entry = b._buckets.get((tenant, cap, _FILL))
            if entry is not None:
                balance = entry[0]
        assert balance is not None, tenant
        assert balance == pytest.approx(cap - admitted[tenant],
                                        abs=1e-3), tenant
    # The migrated flat key: admission bounded by cap + the handoff
    # envelope (the one dual-ownership window the split opened).
    from distributedratelimiting.redis_tpu.models.approximate import (
        headroom_budget,
    )

    flat_admitted = sum(c for (_t, lane, _tn, _p, c, g)
                        in res["outcomes"] if g and lane == "flat")
    assert 0 < flat_admitted <= _FLAT_CAP + headroom_budget(
        _FLAT_CAP, fraction=0.5, min_budget=1.0)

    # 5. p99 stays bounded through the whole soak (server-side serving
    # latency against in-memory backings).
    for ns in res["cluster_stats"]["nodes"]:
        if ns.get("serving_samples"):
            assert ns["serving_p99_ms"] < 500.0

    # 6. Full audit trail: every action is a flight-recorder frame and
    # the OP_STATS section carries the controller's state.
    assert [(f["tick"], f["action"], f["outcome"])
            for f in res["flight"] if f["action"] != "tick"] == \
        [(a["tick"], a["action"], a["outcome"]) for a in actions]
    assert res["cluster_stats"]["controller"]["ticks"] == ctrl.ticks
    # Chaos hit the loop too — and only cost skipped ticks.
    assert ctrl.tick_failures > 0
    assert ctrl.ticks + ctrl.tick_failures == _N_TICKS

    # 7. Determinism: the same seed replays the identical action
    # schedule AND the identical grant sequence on a fresh fleet.
    res2 = await _soak_once(SEED)
    assert _action_schedule(res2["actions"]) == \
        _action_schedule(actions)
    assert res2["outcomes"] == res["outcomes"]
    assert res2["shed_at_tick"] == res["shed_at_tick"]
