"""Deque ring-buffer tests (mirrors reference ``Deque<T>`` behaviors)."""

import pytest

from distributedratelimiting.redis_tpu.utils.deque import Deque


def test_fifo_head_tail():
    d = Deque()
    for i in range(10):
        d.enqueue_tail(i)
    assert len(d) == 10
    assert d.peek_head() == 0
    assert d.peek_tail() == 9
    assert [d.dequeue_head() for _ in range(10)] == list(range(10))


def test_dequeue_tail_lifo():
    d = Deque()
    for i in range(5):
        d.enqueue_tail(i)
    assert [d.dequeue_tail() for _ in range(5)] == [4, 3, 2, 1, 0]


def test_enqueue_head():
    d = Deque()
    d.enqueue_tail(1)
    d.enqueue_head(0)
    assert list(d) == [0, 1]


def test_grow_preserves_order_with_wrapped_head():
    d = Deque(4)
    for i in range(4):
        d.enqueue_tail(i)
    d.dequeue_head()
    d.dequeue_head()
    d.enqueue_tail(4)
    d.enqueue_tail(5)  # wraps
    d.enqueue_tail(6)  # forces grow with wrapped head
    assert list(d) == [2, 3, 4, 5, 6]


def test_min_grow_four():
    d = Deque(0)
    d.enqueue_tail(1)  # grow from 0 → 4
    assert len(d) == 1


def test_remove_middle_keeps_order():
    d = Deque()
    items = ["a", "b", "c", "d"]
    for x in items:
        d.enqueue_tail(x)
    assert d.remove("b")
    assert list(d) == ["a", "c", "d"]
    assert not d.remove("zz")


def test_empty_raises():
    d = Deque()
    with pytest.raises(IndexError):
        d.dequeue_head()
    with pytest.raises(IndexError):
        d.peek_tail()


def test_interleaved_random_ops_match_model(rng):
    import collections

    d = Deque()
    model = collections.deque()
    for _ in range(2000):
        op = rng.integers(0, 4)
        if op == 0:
            v = int(rng.integers(0, 1000))
            d.enqueue_tail(v)
            model.append(v)
        elif op == 1:
            v = int(rng.integers(0, 1000))
            d.enqueue_head(v)
            model.appendleft(v)
        elif op == 2 and model:
            assert d.dequeue_head() == model.popleft()
        elif op == 3 and model:
            assert d.dequeue_tail() == model.pop()
        assert len(d) == len(model)
    assert list(d) == list(model)
