"""Multi-chip tests on the virtual 8-device CPU mesh (SURVEY.md §4 (d)).

Key-sharded acquire must agree with the serial in-process store; the
two-level global tier must see the psum of all shards' consumption.
"""

import numpy as np
import pytest

from distributedratelimiting.redis_tpu.ops.bucket_math import TICKS_PER_SECOND
from distributedratelimiting.redis_tpu.parallel.mesh import create_mesh
from distributedratelimiting.redis_tpu.parallel.sharded_store import (
    ShardedDeviceStore,
    shard_of_key,
)
from distributedratelimiting.redis_tpu.runtime.clock import ManualClock
from distributedratelimiting.redis_tpu.runtime.store import InProcessBucketStore

import jax


@pytest.fixture(scope="module")
def mesh():
    assert len(jax.devices()) >= 8, "conftest must provide 8 virtual devices"
    return create_mesh(8)


@pytest.fixture
def clock():
    return ManualClock()


def test_mesh_has_8_devices(mesh):
    assert mesh.devices.size == 8


def test_routing_is_stable_and_spread(mesh):
    shards = [shard_of_key(f"key-{i}", 8) for i in range(1000)]
    assert shards == [shard_of_key(f"key-{i}", 8) for i in range(1000)]
    counts = np.bincount(shards, minlength=8)
    assert counts.min() > 50  # roughly uniform


def test_sharded_agrees_with_serial(mesh, clock, rng):
    sharded = ShardedDeviceStore(mesh, 20.0, 8.0, per_shard_slots=64,
                                 clock=clock)
    ref = InProcessBucketStore(clock=clock)
    for _ in range(15):
        clock.advance_ticks(int(rng.integers(0, TICKS_PER_SECOND)))
        keys = [f"k{i}" for i in rng.choice(40, size=24, replace=False)]
        counts = [int(c) for c in rng.integers(0, 6, size=24)]
        got = sharded.acquire_batch_blocking(list(zip(keys, counts)))
        want = [ref.acquire_blocking(k, c, 20.0, 8.0)
                for k, c in zip(keys, counts)]
        for g, w, k, c in zip(got, want, keys, counts):
            assert g.granted == w.granted, (k, c)
            assert abs(g.remaining - w.remaining) < 1e-2


def test_global_tier_psums_all_shards(mesh, clock):
    sharded = ShardedDeviceStore(mesh, 10.0, 0.0, per_shard_slots=16,
                                 clock=clock)
    # 32 distinct keys spread over all shards, each granted 2 permits.
    reqs = [(f"k{i}", 2) for i in range(32)]
    results = sharded.acquire_batch_blocking(reqs, decay_rate_per_sec=0.0)
    assert all(r.granted for r in results)
    # Global counter = psum of per-shard consumption = 64.
    assert sharded.global_score == 64.0


def test_global_tier_decays(mesh, clock):
    sharded = ShardedDeviceStore(mesh, 10.0, 0.0, per_shard_slots=16,
                                 clock=clock)
    sharded.acquire_batch_blocking([("a", 4)], decay_rate_per_sec=2.0)
    assert sharded.global_score == 4.0
    clock.advance_seconds(1.0)
    sharded.acquire_batch_blocking([("b", 0)], decay_rate_per_sec=2.0)
    # 4 − 1s·2/s = 2, +0 consumed (b's probe grants nothing... probe counts 0)
    assert abs(sharded.global_score - 2.0) < 1e-3


def test_per_key_independence_across_shards(mesh, clock):
    sharded = ShardedDeviceStore(mesh, 5.0, 0.0, per_shard_slots=16,
                                 clock=clock)
    reqs = [(f"k{i}", 5) for i in range(16)]
    assert all(r.granted for r in sharded.acquire_batch_blocking(reqs))
    # All drained; second round denied, regardless of shard.
    assert not any(r.granted for r in sharded.acquire_batch_blocking(reqs))


def test_sweep_reclaims_across_shards(mesh, clock):
    sharded = ShardedDeviceStore(mesh, 10.0, 10.0, per_shard_slots=8,
                                 clock=clock)
    sharded.acquire_batch_blocking([(f"k{i}", 1) for i in range(20)])
    assert len(sharded.directory) == 20
    clock.advance_seconds(5.0)  # all buckets refill to full → expire
    freed = sharded.sweep()
    assert freed == 20
    assert len(sharded.directory) == 0


def test_duplicate_keys_in_one_batch_never_over_admit(mesh, clock):
    sharded = ShardedDeviceStore(mesh, 5.0, 0.0, per_shard_slots=16,
                                 clock=clock)
    reqs = [("hot", 1)] * 12
    results = sharded.acquire_batch_blocking(reqs)
    assert sum(r.granted for r in results) == 5


def test_failed_allocation_rolls_back_no_leak(mesh, clock):
    """Regression: an exhaustion error mid-batch must roll back that
    batch's fresh allocations (their exists bits were never set, so a sweep
    could never reclaim them)."""
    tiny = ShardedDeviceStore(mesh, 10.0, 5.0, per_shard_slots=2, clock=clock)
    with pytest.raises(RuntimeError):
        tiny.acquire_batch_blocking([(f"x{i}", 1) for i in range(64)])
    # Nothing leaked: all slots are free again and the directory is empty.
    assert len(tiny.directory) == 0
    assert all(len(f) == 2 for f in tiny.free)
    # The store remains fully usable.
    res = tiny.acquire_batch_blocking([("y1", 1), ("y2", 1)])
    assert all(r.granted for r in res)
