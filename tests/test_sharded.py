"""Multi-chip tests on the virtual 8-device CPU mesh (SURVEY.md §4 (d)).

Key-sharded acquire must agree with the serial in-process store; the
two-level global tier must see the psum of all shards' consumption.
"""

import numpy as np
import pytest

from distributedratelimiting.redis_tpu.ops.bucket_math import TICKS_PER_SECOND
from distributedratelimiting.redis_tpu.parallel.mesh import create_mesh
from distributedratelimiting.redis_tpu.parallel.sharded_store import (
    ShardedDeviceStore,
    shard_of_key,
)
from distributedratelimiting.redis_tpu.runtime.clock import ManualClock
from distributedratelimiting.redis_tpu.runtime.store import InProcessBucketStore

import jax


@pytest.fixture(scope="module")
def mesh():
    assert len(jax.devices()) >= 8, "conftest must provide 8 virtual devices"
    return create_mesh(8)


@pytest.fixture
def clock():
    return ManualClock()


def test_mesh_has_8_devices(mesh):
    assert mesh.devices.size == 8


def test_routing_is_stable_and_spread(mesh):
    shards = [shard_of_key(f"key-{i}", 8) for i in range(1000)]
    assert shards == [shard_of_key(f"key-{i}", 8) for i in range(1000)]
    counts = np.bincount(shards, minlength=8)
    assert counts.min() > 50  # roughly uniform


def test_sharded_agrees_with_serial(mesh, clock, rng):
    sharded = ShardedDeviceStore(mesh, 20.0, 8.0, per_shard_slots=64,
                                 clock=clock)
    ref = InProcessBucketStore(clock=clock)
    for _ in range(15):
        clock.advance_ticks(int(rng.integers(0, TICKS_PER_SECOND)))
        keys = [f"k{i}" for i in rng.choice(40, size=24, replace=False)]
        counts = [int(c) for c in rng.integers(0, 6, size=24)]
        got = sharded.acquire_batch_blocking(list(zip(keys, counts)))
        want = [ref.acquire_blocking(k, c, 20.0, 8.0)
                for k, c in zip(keys, counts)]
        for g, w, k, c in zip(got, want, keys, counts):
            assert g.granted == w.granted, (k, c)
            assert abs(g.remaining - w.remaining) < 1e-2


def test_global_tier_psums_all_shards(mesh, clock):
    sharded = ShardedDeviceStore(mesh, 10.0, 0.0, per_shard_slots=16,
                                 clock=clock)
    # 32 distinct keys spread over all shards, each granted 2 permits.
    reqs = [(f"k{i}", 2) for i in range(32)]
    results = sharded.acquire_batch_blocking(reqs, decay_rate_per_sec=0.0)
    assert all(r.granted for r in results)
    # Global counter = psum of per-shard consumption = 64.
    assert sharded.global_score == 64.0


def test_global_tier_decays(mesh, clock):
    sharded = ShardedDeviceStore(mesh, 10.0, 0.0, per_shard_slots=16,
                                 clock=clock)
    sharded.acquire_batch_blocking([("a", 4)], decay_rate_per_sec=2.0)
    assert sharded.global_score == 4.0
    clock.advance_seconds(1.0)
    sharded.acquire_batch_blocking([("b", 0)], decay_rate_per_sec=2.0)
    # 4 − 1s·2/s = 2, +0 consumed (b's probe grants nothing... probe counts 0)
    assert abs(sharded.global_score - 2.0) < 1e-3


def test_per_key_independence_across_shards(mesh, clock):
    sharded = ShardedDeviceStore(mesh, 5.0, 0.0, per_shard_slots=16,
                                 clock=clock)
    reqs = [(f"k{i}", 5) for i in range(16)]
    assert all(r.granted for r in sharded.acquire_batch_blocking(reqs))
    # All drained; second round denied, regardless of shard.
    assert not any(r.granted for r in sharded.acquire_batch_blocking(reqs))


def test_sweep_reclaims_across_shards(mesh, clock):
    sharded = ShardedDeviceStore(mesh, 10.0, 10.0, per_shard_slots=8,
                                 clock=clock)
    sharded.acquire_batch_blocking([(f"k{i}", 1) for i in range(20)])
    assert len(sharded.directory) == 20
    clock.advance_seconds(5.0)  # all buckets refill to full → expire
    freed = sharded.sweep()
    assert freed == 20
    assert len(sharded.directory) == 0


def test_duplicate_keys_in_one_batch_never_over_admit(mesh, clock):
    sharded = ShardedDeviceStore(mesh, 5.0, 0.0, per_shard_slots=16,
                                 clock=clock)
    reqs = [("hot", 1)] * 12
    results = sharded.acquire_batch_blocking(reqs)
    assert sum(r.granted for r in results) == 5


def test_shard_overflow_grows_and_keeps_serving(mesh, clock):
    """A shard filling past capacity must grow (per-shard doubling, geometry
    kept homogeneous) and keep serving — the single-chip table's behavior,
    previously a hard RuntimeError on the mesh."""
    tiny = ShardedDeviceStore(mesh, 10.0, 5.0, per_shard_slots=2, clock=clock)
    res = tiny.acquire_batch_blocking([(f"x{i}", 1) for i in range(64)])
    assert all(r.granted for r in res)
    assert tiny.per_shard > 2  # grew past the initial geometry
    assert tiny.metrics.pregrows > 0
    assert len(tiny.directory) == 64
    # Earlier keys' state survived the growth re-layout.
    res2 = tiny.acquire_batch_blocking([(f"x{i}", 10) for i in range(64)])
    assert not any(r.granted for r in res2)  # 9 tokens left each, not 10
    # And new keys keep landing.
    res3 = tiny.acquire_batch_blocking([(f"y{i}", 1) for i in range(32)])
    assert all(r.granted for r in res3)


def test_growth_preserves_balances_exactly(mesh, clock):
    store = ShardedDeviceStore(mesh, 100.0, 0.0, per_shard_slots=4,
                               clock=clock)
    store.acquire_batch_blocking([("a", 30), ("b", 7)])
    before = {k: store.peek_blocking(k) for k in ("a", "b")}
    store._grow()
    after = {k: store.peek_blocking(k) for k in ("a", "b")}
    assert before == after == {"a": 70.0, "b": 93.0}


class TestShardedBulk:
    def test_bulk_agrees_with_serial(self, mesh, clock, rng):
        sharded = ShardedDeviceStore(mesh, 20.0, 8.0, per_shard_slots=64,
                                     clock=clock)
        ref = InProcessBucketStore(clock=clock)
        for _ in range(5):
            clock.advance_ticks(int(rng.integers(0, TICKS_PER_SECOND)))
            keys = [f"k{i}" for i in rng.choice(60, size=40, replace=False)]
            counts = [int(c) for c in rng.integers(0, 6, size=40)]
            got = sharded.acquire_many_blocking(keys, counts)
            want = [ref.acquire_blocking(k, c, 20.0, 8.0)
                    for k, c in zip(keys, counts)]
            for g, w, k, c in zip(got, want, keys, counts):
                assert g.granted == w.granted, (k, c)
                assert abs(g.remaining - w.remaining) < 1e-2

    def test_bulk_multi_chunk_when_shard_load_exceeds_width(self, mesh,
                                                            clock):
        # Shrink the scan width so one call needs several fused dispatches.
        sharded = ShardedDeviceStore(mesh, 1e9, 0.0, per_shard_slots=2048,
                                     clock=clock)
        sharded._BULK_B = 8
        n = 4096
        keys = [f"bk{i}" for i in range(n)]
        res = sharded.acquire_many_blocking(keys, [1] * n,
                                            with_remaining=False)
        assert res.remaining is None
        assert res.granted.all()
        assert sharded.metrics.launches > 1

    def test_bulk_duplicates_never_over_admit(self, mesh, clock):
        sharded = ShardedDeviceStore(mesh, 5.0, 0.0, per_shard_slots=16,
                                     clock=clock)
        res = sharded.acquire_many_blocking(["hot"] * 12, [1] * 12)
        assert int(res.granted.sum()) == 5

    def test_bulk_zero_count_probe_granted(self, mesh, clock):
        sharded = ShardedDeviceStore(mesh, 3.0, 0.0, per_shard_slots=16,
                                     clock=clock)
        res = sharded.acquire_many_blocking(
            ["p", "p", "p", "p", "p"], [3, 3, 0, 1, 0])
        # First drains the bucket, second denied, probes granted anyway.
        assert res.granted.tolist() == [True, False, True, False, True]

    def test_bulk_feeds_global_tier(self, mesh, clock):
        sharded = ShardedDeviceStore(mesh, 10.0, 0.0, per_shard_slots=16,
                                     clock=clock)
        res = sharded.acquire_many_blocking(
            [f"g{i}" for i in range(32)], [2] * 32,
            decay_rate_per_sec=0.0)
        assert res.granted.all()
        assert sharded.global_score == 64.0


class TestShardedWindowStore:
    def test_agrees_with_serial_sliding_window(self, mesh, clock, rng):
        from distributedratelimiting.redis_tpu.parallel.sharded_store import (
            ShardedWindowStore,
        )

        ws = ShardedWindowStore(mesh, limit=10.0, window_sec=1.0,
                                per_shard_slots=32, clock=clock)
        ref = InProcessBucketStore(clock=clock)
        for _ in range(8):
            clock.advance_ticks(int(rng.integers(0, TICKS_PER_SECOND // 2)))
            keys = [f"w{i}" for i in rng.choice(30, size=20, replace=False)]
            counts = [int(c) for c in rng.integers(0, 4, size=20)]
            got = ws.acquire_many_blocking(keys, counts)
            want = [ref.window_acquire_blocking(k, c, 10.0, 1.0)
                    for k, c in zip(keys, counts)]
            for g, w, k, c in zip(got, want, keys, counts):
                assert g.granted == w.granted, (k, c)

    def test_fixed_window_semantics(self, mesh, clock):
        from distributedratelimiting.redis_tpu.parallel.sharded_store import (
            ShardedWindowStore,
        )

        ws = ShardedWindowStore(mesh, limit=3.0, window_sec=1.0, fixed=True,
                                per_shard_slots=16, clock=clock)
        res = ws.acquire_many_blocking(["f"] * 4, [1] * 4)
        assert res.granted.tolist() == [True, True, True, False]
        clock.advance_seconds(1.0)  # fresh window: full limit again
        assert ws.acquire_many_blocking(["f"], [3]).granted[0]

    def test_growth_and_sweep(self, mesh, clock):
        from distributedratelimiting.redis_tpu.parallel.sharded_store import (
            ShardedWindowStore,
        )

        ws = ShardedWindowStore(mesh, limit=5.0, window_sec=1.0,
                                per_shard_slots=2, clock=clock)
        res = ws.acquire_many_blocking([f"wk{i}" for i in range(64)],
                                       [1] * 64)
        assert res.granted.all() and ws.per_shard > 2
        clock.advance_seconds(3.0)  # > 2 windows idle → expire
        assert ws.sweep() == 64
        assert len(ws.directory) == 0

    def test_standalone_clock_overflow_rebases(self, mesh):
        """A standalone ShardedWindowStore (no composing MeshBucketStore
        coordinating rebases) must self-rebase before int32 tick overflow
        rather than crash on the i32 now operand."""
        from distributedratelimiting.redis_tpu.parallel.sharded_store import (
            ShardedWindowStore,
        )

        clock = ManualClock(start_ticks=2**30 - 10)
        ws = ShardedWindowStore(mesh, limit=5.0, window_sec=1.0,
                                per_shard_slots=16, clock=clock)
        assert ws.acquire_many_blocking(["o"], [5]).granted[0]
        clock.advance_ticks(100)  # crosses the rebase threshold
        res = ws.acquire_many_blocking(["o"], [1])
        assert not res.granted[0]  # same window post-rebase: still drained
        assert clock.now_ticks() < 2**30  # the clock epoch was rebased

    def test_snapshot_restore_across_epochs(self, mesh):
        from distributedratelimiting.redis_tpu.parallel.sharded_store import (
            ShardedWindowStore,
        )

        c1 = ManualClock(start_ticks=5 * TICKS_PER_SECOND)
        a = ShardedWindowStore(mesh, limit=4.0, window_sec=1.0,
                               per_shard_slots=16, clock=c1)
        a.acquire_many_blocking(["s"], [4])
        snap = a.snapshot()
        c2 = ManualClock(start_ticks=TICKS_PER_SECOND)
        b = ShardedWindowStore(mesh, limit=4.0, window_sec=1.0,
                               per_shard_slots=16, clock=c2)
        b.restore(snap)
        assert not b.acquire_many_blocking(["s"], [1]).granted[0]


def test_fused_and_split_resolve_agree(mesh, clock):
    """The fused one-C-call route+resolve and the split
    route/group/resolve fallback must agree on ROUTING and each be
    self-consistent (stable slots, duplicate keys collapse, re-resolve
    idempotent) through exhaustion-driven growth. Slot-id assignment
    order is not a contract — the paths allocate in different orders."""
    a = ShardedDeviceStore(mesh, 10.0, 1.0, per_shard_slots=4, clock=clock)
    b = ShardedDeviceStore(mesh, 10.0, 1.0, per_shard_slots=4, clock=clock)
    b._resolve_batch_fused = lambda keys: None  # force the split path
    keys = [f"rk{i}" for i in range(96)] + ["rk0", "rk5"]  # + dups
    sa, la = a._resolve_batch(list(keys))
    sb, lb = b._resolve_batch(list(keys))
    np.testing.assert_array_equal(sa, sb)  # identical crc32 routing
    assert a.per_shard == b.per_shard  # same per-shard load ⇒ same growth
    for sh, lo, store in ((sa, la, a), (sb, lb, b)):
        # Duplicate keys resolved to their first slot.
        assert lo[96] == lo[0] and sh[96] == sh[0]
        assert lo[97] == lo[5] and sh[97] == sh[5]
        # Directory agrees with the returned assignment.
        for i in (0, 7, 42, 95):
            assert store.dirs[sh[i]].lookup(keys[i]) == lo[i]
        # Re-resolving is idempotent.
        sh2, lo2 = store._resolve_batch(list(keys))
        np.testing.assert_array_equal(sh, sh2)
        np.testing.assert_array_equal(lo, lo2)


def test_route_keys_matches_scalar(mesh):
    from distributedratelimiting.redis_tpu.parallel.sharded_store import (
        route_keys,
    )

    keys = [f"key-{i}" for i in range(500)] + ["ключ-🔑", "", "x" * 300]
    want = [shard_of_key(k, 8) for k in keys]
    assert route_keys(keys, 8).tolist() == want


class TestTwoLevelScanStep:
    def test_matches_sequential_two_level_steps(self, mesh):
        import jax.numpy as jnp
        from jax.sharding import NamedSharding, PartitionSpec as P

        from distributedratelimiting.redis_tpu.ops import kernels as K
        from distributedratelimiting.redis_tpu.parallel.mesh import SHARD_AXIS
        from distributedratelimiting.redis_tpu.parallel.sharded_store import (
            init_global_counter, make_two_level_scan_step, make_two_level_step,
        )

        n_dev = mesh.devices.size
        per_shard, b, k = 16, 8, 3
        sharding = NamedSharding(mesh, P(SHARD_AXIS))
        rng = np.random.default_rng(21)
        slots = rng.integers(0, per_shard, (n_dev, k, b)).astype(np.int32)
        counts = np.ones((n_dev, k, b), np.int32)
        valid = np.ones((n_dev, k, b), bool)
        nows = np.array([5, 9, 14], np.int32)
        cap, rate, decay = (jnp.float32(4.0), jnp.float32(0.5),
                            jnp.float32(0.25))

        def fresh():
            state = K.BucketState(
                tokens=jax.device_put(
                    jnp.zeros((n_dev * per_shard,), jnp.float32), sharding),
                last_ts=jax.device_put(
                    jnp.zeros((n_dev * per_shard,), jnp.int32), sharding),
                exists=jax.device_put(
                    jnp.zeros((n_dev * per_shard,), bool), sharding),
            )
            g = jax.device_put(init_global_counter(),
                               NamedSharding(mesh, P()))
            return state, g

        scan_step = make_two_level_scan_step(mesh)
        s1, g1 = fresh()
        s1, granted1, rem1, g1 = scan_step(
            s1, jnp.asarray(slots), jnp.asarray(counts), jnp.asarray(valid),
            jnp.asarray(nows), cap, rate, g1, decay)

        step = make_two_level_step(mesh)
        s2, g2 = fresh()
        for i in range(k):
            s2, g2step, rem2, g2 = step(
                s2, jnp.asarray(slots[:, i]), jnp.asarray(counts[:, i]),
                jnp.asarray(valid[:, i]), jnp.int32(nows[i]), cap, rate,
                g2, decay)
            np.testing.assert_array_equal(
                np.asarray(granted1)[:, i], np.asarray(g2step))
        np.testing.assert_allclose(np.asarray(s1.tokens),
                                   np.asarray(s2.tokens), rtol=1e-6)
        np.testing.assert_allclose(float(np.asarray(g1.value)),
                                   float(np.asarray(g2.value)), rtol=1e-6)


class TestDeferredScanStep:
    def test_matches_per_batch_cadence(self, mesh):
        """The per-launch-psum variant must produce identical grants and
        table state; with decay 0 the global counters are exactly equal
        (pure sums), so the one-psum accumulator is fully checked."""
        import jax.numpy as jnp
        from jax.sharding import NamedSharding, PartitionSpec as P

        from distributedratelimiting.redis_tpu.ops import kernels as K
        from distributedratelimiting.redis_tpu.parallel.mesh import SHARD_AXIS
        from distributedratelimiting.redis_tpu.parallel.sharded_store import (
            init_global_counter,
            make_two_level_scan_step,
            make_two_level_scan_step_deferred,
        )

        n_dev = mesh.devices.size
        per_shard, b, k = 16, 8, 3
        sharding = NamedSharding(mesh, P(SHARD_AXIS))
        rng = np.random.default_rng(23)
        slots = rng.integers(0, per_shard, (n_dev, k, b)).astype(np.int32)
        counts = rng.integers(0, 3, (n_dev, k, b)).astype(np.int32)
        valid = np.ones((n_dev, k, b), bool)
        nows = np.array([4, 9, 13], np.int32)
        cap, rate = jnp.float32(5.0), jnp.float32(0.25)

        def fresh():
            state = K.BucketState(
                tokens=jax.device_put(
                    jnp.zeros((n_dev * per_shard,), jnp.float32), sharding),
                last_ts=jax.device_put(
                    jnp.zeros((n_dev * per_shard,), jnp.int32), sharding),
                exists=jax.device_put(
                    jnp.zeros((n_dev * per_shard,), bool), sharding),
            )
            g = jax.device_put(init_global_counter(),
                               NamedSharding(mesh, P()))
            return state, g

        outs = {}
        for name, factory in (("batch", make_two_level_scan_step),
                              ("launch", make_two_level_scan_step_deferred)):
            step = factory(mesh)
            s, g = fresh()
            s, granted, rem, g = step(
                s, jnp.asarray(slots), jnp.asarray(counts),
                jnp.asarray(valid), jnp.asarray(nows), cap, rate, g,
                jnp.float32(0.0))
            outs[name] = (np.asarray(granted), np.asarray(rem),
                          np.asarray(s.tokens), float(np.asarray(g.value)))
        np.testing.assert_array_equal(outs["batch"][0], outs["launch"][0])
        np.testing.assert_allclose(outs["batch"][1], outs["launch"][1],
                                   rtol=1e-6)
        np.testing.assert_allclose(outs["batch"][2], outs["launch"][2],
                                   rtol=1e-6)
        assert outs["batch"][3] == outs["launch"][3] > 0


class TestShardedSnapshotRestore:
    def test_roundtrip_across_clock_epochs(self, mesh):
        c1 = ManualClock(start_ticks=300_000)
        s1 = ShardedDeviceStore(mesh, capacity=10.0, fill_rate_per_sec=1.0,
                                per_shard_slots=16, clock=c1)
        s1.acquire_batch_blocking([("k0", 10), ("k1", 4)])
        snap = s1.snapshot()

        c2 = ManualClock(start_ticks=50)
        s2 = ShardedDeviceStore(mesh, capacity=10.0, fill_rate_per_sec=1.0,
                                per_shard_slots=16, clock=c2)
        s2.restore(snap)
        # k0 drained, k1 has 6 left; global counter restored.
        (r0, r1) = s2.acquire_batch_blocking([("k0", 5), ("k1", 6)])
        assert not r0.granted
        assert r1.granted
        # Elapsed time keeps refilling in the new epoch.
        c2.advance_seconds(5.0)
        (r0,) = s2.acquire_batch_blocking([("k0", 5)])
        assert r0.granted

    def test_shard_count_mismatch_rejected(self, mesh):
        a = ShardedDeviceStore(create_mesh(4), capacity=5.0,
                               fill_rate_per_sec=1.0, per_shard_slots=16)
        b = ShardedDeviceStore(mesh, capacity=5.0, fill_rate_per_sec=1.0,
                               per_shard_slots=16)
        with pytest.raises(ValueError, match="geometry"):
            b.restore(a.snapshot())

    def test_post_growth_snapshot_restores_into_fresh_store(self, mesh):
        """A store that grew before checkpointing must restore into a
        fresh store built at the ORIGINAL size — restore adopts the
        snapshot's per-shard width (growth made width mutable; rejecting
        it would make every post-growth checkpoint unloadable)."""
        clock = ManualClock()
        a = ShardedDeviceStore(mesh, capacity=10.0, fill_rate_per_sec=0.0,
                               per_shard_slots=2, clock=clock)
        a.acquire_batch_blocking([(f"k{i}", 7) for i in range(64)])  # grows
        assert a.per_shard > 2
        snap = a.snapshot()

        b = ShardedDeviceStore(mesh, capacity=10.0, fill_rate_per_sec=0.0,
                               per_shard_slots=2, clock=ManualClock())
        b.restore(snap)
        assert b.per_shard == a.per_shard
        # Balances carried over: 3 tokens left per key.
        (r0, r1) = b.acquire_batch_blocking([("k0", 3), ("k1", 4)])
        assert r0.granted and not r1.granted
        # And the restored store still grows past its adopted width.
        res = b.acquire_batch_blocking(
            [(f"fresh{i}", 1) for i in range(8 * b.per_shard * b.n_shards // 4)])
        assert all(r.granted for r in res)

    def test_config_mismatch_rejected(self, mesh):
        a = ShardedDeviceStore(mesh, capacity=10.0, fill_rate_per_sec=1.0,
                               per_shard_slots=16)
        b = ShardedDeviceStore(mesh, capacity=100.0, fill_rate_per_sec=50.0,
                               per_shard_slots=16)
        with pytest.raises(ValueError, match="config"):
            b.restore(a.snapshot())


class TestSyncCadenceOption:
    """The deployable form of the psum-cadence ablation: the store option
    must select the deferred step and preserve decision semantics."""

    def test_launch_cadence_matches_batch(self, mesh):
        keys = [f"c{i}" for i in range(200)]
        counts = [2] * len(keys)
        outs = {}
        for cadence in ("batch", "launch"):
            store = ShardedDeviceStore(
                mesh, capacity=5.0, fill_rate_per_sec=0.0,
                per_shard_slots=64, clock=ManualClock(),
                sync_cadence=cadence)
            res = store.acquire_many_blocking(keys, counts)
            outs[cadence] = (np.asarray(res.granted), store.global_score)
        np.testing.assert_array_equal(outs["batch"][0], outs["launch"][0])
        assert outs["batch"][1] == outs["launch"][1] == 400.0

    def test_invalid_cadence_rejected(self, mesh):
        with pytest.raises(ValueError, match="sync_cadence"):
            ShardedDeviceStore(mesh, capacity=5.0, fill_rate_per_sec=1.0,
                               per_shard_slots=16, sync_cadence="never")


def test_keyblob_routes_and_resolves_identically():
    """The zero-copy mesh lane: routing and fused resolve from a
    wire.KeyBlob agree bit-for-bit with the list[str] path."""
    import numpy as np

    from distributedratelimiting.redis_tpu.parallel.sharded_store import (
        route_keys,
    )
    from distributedratelimiting.redis_tpu.runtime.wire import KeyBlob

    keys = [f"mk{i % 37}" for i in range(300)] + ["\udcff\udc80odd"]
    blobs = [k.encode("utf-8", "surrogateescape") for k in keys]
    offsets = np.zeros(len(keys) + 1, np.int64)
    np.cumsum([len(b) for b in blobs], out=offsets[1:])
    view = KeyBlob(b"".join(blobs), offsets)
    assert (route_keys(view, 8) == route_keys(list(keys), 8)).all()


def test_mesh_bulk_accepts_keyblob(mesh):
    import numpy as np

    from distributedratelimiting.redis_tpu.parallel.sharded_store import (
        ShardedDeviceStore,
    )
    from distributedratelimiting.redis_tpu.runtime.wire import KeyBlob

    store = ShardedDeviceStore(mesh, 4.0, 1e-9, per_shard_slots=64)
    keys = [f"zb{i % 50}" for i in range(400)]
    blobs = [k.encode() for k in keys]
    offsets = np.zeros(len(keys) + 1, np.int64)
    np.cumsum([len(b) for b in blobs], out=offsets[1:])
    view = KeyBlob(b"".join(blobs), offsets)
    res = store.acquire_many_blocking(view, [1] * 400,
                                      with_remaining=True)
    # 50 distinct keys, 8 requests each, capacity 4 => 200 grants.
    assert int(np.asarray(res.granted).sum()) == 200
    res2 = store.acquire_many_blocking(list(keys), [1] * 400)
    assert int(np.asarray(res2.granted).sum()) == 0  # all spent


def test_fused_blob_resolve_matches_list_resolve(mesh):
    """dir_resolve_sharded_batch (the KeyBlob fused lane) assigns the
    same (shard, local) pairs as the list[str] pylist lane — including a
    byte-identity key."""
    import numpy as np

    from distributedratelimiting.redis_tpu.parallel.sharded_store import (
        ShardedDeviceStore,
    )
    from distributedratelimiting.redis_tpu.runtime.wire import KeyBlob

    a = ShardedDeviceStore(mesh, 10.0, 1.0, per_shard_slots=64)
    b = ShardedDeviceStore(mesh, 10.0, 1.0, per_shard_slots=64)
    keys = [f"fz{i % 60}" for i in range(200)]
    keys.append(b"\xff\x80odd".decode("utf-8", "surrogateescape"))
    blobs = [k.encode("utf-8", "surrogateescape") for k in keys]
    offsets = np.zeros(len(keys) + 1, np.int64)
    np.cumsum([len(x) for x in blobs], out=offsets[1:])
    view = KeyBlob(b"".join(blobs), offsets)
    with a._lock, b._lock:
        sh_v, lo_v = a._resolve_batch(view)
        sh_l, lo_l = b._resolve_batch(list(keys))
    assert (sh_v == sh_l).all()
    assert (lo_v == lo_l).all()
