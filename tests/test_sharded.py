"""Multi-chip tests on the virtual 8-device CPU mesh (SURVEY.md §4 (d)).

Key-sharded acquire must agree with the serial in-process store; the
two-level global tier must see the psum of all shards' consumption.
"""

import numpy as np
import pytest

from distributedratelimiting.redis_tpu.ops.bucket_math import TICKS_PER_SECOND
from distributedratelimiting.redis_tpu.parallel.mesh import create_mesh
from distributedratelimiting.redis_tpu.parallel.sharded_store import (
    ShardedDeviceStore,
    shard_of_key,
)
from distributedratelimiting.redis_tpu.runtime.clock import ManualClock
from distributedratelimiting.redis_tpu.runtime.store import InProcessBucketStore

import jax


@pytest.fixture(scope="module")
def mesh():
    assert len(jax.devices()) >= 8, "conftest must provide 8 virtual devices"
    return create_mesh(8)


@pytest.fixture
def clock():
    return ManualClock()


def test_mesh_has_8_devices(mesh):
    assert mesh.devices.size == 8


def test_routing_is_stable_and_spread(mesh):
    shards = [shard_of_key(f"key-{i}", 8) for i in range(1000)]
    assert shards == [shard_of_key(f"key-{i}", 8) for i in range(1000)]
    counts = np.bincount(shards, minlength=8)
    assert counts.min() > 50  # roughly uniform


def test_sharded_agrees_with_serial(mesh, clock, rng):
    sharded = ShardedDeviceStore(mesh, 20.0, 8.0, per_shard_slots=64,
                                 clock=clock)
    ref = InProcessBucketStore(clock=clock)
    for _ in range(15):
        clock.advance_ticks(int(rng.integers(0, TICKS_PER_SECOND)))
        keys = [f"k{i}" for i in rng.choice(40, size=24, replace=False)]
        counts = [int(c) for c in rng.integers(0, 6, size=24)]
        got = sharded.acquire_batch_blocking(list(zip(keys, counts)))
        want = [ref.acquire_blocking(k, c, 20.0, 8.0)
                for k, c in zip(keys, counts)]
        for g, w, k, c in zip(got, want, keys, counts):
            assert g.granted == w.granted, (k, c)
            assert abs(g.remaining - w.remaining) < 1e-2


def test_global_tier_psums_all_shards(mesh, clock):
    sharded = ShardedDeviceStore(mesh, 10.0, 0.0, per_shard_slots=16,
                                 clock=clock)
    # 32 distinct keys spread over all shards, each granted 2 permits.
    reqs = [(f"k{i}", 2) for i in range(32)]
    results = sharded.acquire_batch_blocking(reqs, decay_rate_per_sec=0.0)
    assert all(r.granted for r in results)
    # Global counter = psum of per-shard consumption = 64.
    assert sharded.global_score == 64.0


def test_global_tier_decays(mesh, clock):
    sharded = ShardedDeviceStore(mesh, 10.0, 0.0, per_shard_slots=16,
                                 clock=clock)
    sharded.acquire_batch_blocking([("a", 4)], decay_rate_per_sec=2.0)
    assert sharded.global_score == 4.0
    clock.advance_seconds(1.0)
    sharded.acquire_batch_blocking([("b", 0)], decay_rate_per_sec=2.0)
    # 4 − 1s·2/s = 2, +0 consumed (b's probe grants nothing... probe counts 0)
    assert abs(sharded.global_score - 2.0) < 1e-3


def test_per_key_independence_across_shards(mesh, clock):
    sharded = ShardedDeviceStore(mesh, 5.0, 0.0, per_shard_slots=16,
                                 clock=clock)
    reqs = [(f"k{i}", 5) for i in range(16)]
    assert all(r.granted for r in sharded.acquire_batch_blocking(reqs))
    # All drained; second round denied, regardless of shard.
    assert not any(r.granted for r in sharded.acquire_batch_blocking(reqs))


def test_sweep_reclaims_across_shards(mesh, clock):
    sharded = ShardedDeviceStore(mesh, 10.0, 10.0, per_shard_slots=8,
                                 clock=clock)
    sharded.acquire_batch_blocking([(f"k{i}", 1) for i in range(20)])
    assert len(sharded.directory) == 20
    clock.advance_seconds(5.0)  # all buckets refill to full → expire
    freed = sharded.sweep()
    assert freed == 20
    assert len(sharded.directory) == 0


def test_duplicate_keys_in_one_batch_never_over_admit(mesh, clock):
    sharded = ShardedDeviceStore(mesh, 5.0, 0.0, per_shard_slots=16,
                                 clock=clock)
    reqs = [("hot", 1)] * 12
    results = sharded.acquire_batch_blocking(reqs)
    assert sum(r.granted for r in results) == 5


def test_failed_allocation_rolls_back_no_leak(mesh, clock):
    """Regression: an exhaustion error mid-batch must roll back that
    batch's fresh allocations (their exists bits were never set, so a sweep
    could never reclaim them)."""
    tiny = ShardedDeviceStore(mesh, 10.0, 5.0, per_shard_slots=2, clock=clock)
    with pytest.raises(RuntimeError):
        tiny.acquire_batch_blocking([(f"x{i}", 1) for i in range(64)])
    # Nothing leaked: all slots are free again and the directory is empty.
    assert len(tiny.directory) == 0
    assert all(len(f) == 2 for f in tiny.free)
    # The store remains fully usable.
    res = tiny.acquire_batch_blocking([("y1", 1), ("y2", 1)])
    assert all(r.granted for r in res)


class TestTwoLevelScanStep:
    def test_matches_sequential_two_level_steps(self, mesh):
        import jax.numpy as jnp
        from jax.sharding import NamedSharding, PartitionSpec as P

        from distributedratelimiting.redis_tpu.ops import kernels as K
        from distributedratelimiting.redis_tpu.parallel.mesh import SHARD_AXIS
        from distributedratelimiting.redis_tpu.parallel.sharded_store import (
            init_global_counter, make_two_level_scan_step, make_two_level_step,
        )

        n_dev = mesh.devices.size
        per_shard, b, k = 16, 8, 3
        sharding = NamedSharding(mesh, P(SHARD_AXIS))
        rng = np.random.default_rng(21)
        slots = rng.integers(0, per_shard, (n_dev, k, b)).astype(np.int32)
        counts = np.ones((n_dev, k, b), np.int32)
        valid = np.ones((n_dev, k, b), bool)
        nows = np.array([5, 9, 14], np.int32)
        cap, rate, decay = (jnp.float32(4.0), jnp.float32(0.5),
                            jnp.float32(0.25))

        def fresh():
            state = K.BucketState(
                tokens=jax.device_put(
                    jnp.zeros((n_dev * per_shard,), jnp.float32), sharding),
                last_ts=jax.device_put(
                    jnp.zeros((n_dev * per_shard,), jnp.int32), sharding),
                exists=jax.device_put(
                    jnp.zeros((n_dev * per_shard,), bool), sharding),
            )
            g = jax.device_put(init_global_counter(),
                               NamedSharding(mesh, P()))
            return state, g

        scan_step = make_two_level_scan_step(mesh)
        s1, g1 = fresh()
        s1, granted1, rem1, g1 = scan_step(
            s1, jnp.asarray(slots), jnp.asarray(counts), jnp.asarray(valid),
            jnp.asarray(nows), cap, rate, g1, decay)

        step = make_two_level_step(mesh)
        s2, g2 = fresh()
        for i in range(k):
            s2, g2step, rem2, g2 = step(
                s2, jnp.asarray(slots[:, i]), jnp.asarray(counts[:, i]),
                jnp.asarray(valid[:, i]), jnp.int32(nows[i]), cap, rate,
                g2, decay)
            np.testing.assert_array_equal(
                np.asarray(granted1)[:, i], np.asarray(g2step))
        np.testing.assert_allclose(np.asarray(s1.tokens),
                                   np.asarray(s2.tokens), rtol=1e-6)
        np.testing.assert_allclose(float(np.asarray(g1.value)),
                                   float(np.asarray(g2.value)), rtol=1e-6)


class TestShardedSnapshotRestore:
    def test_roundtrip_across_clock_epochs(self, mesh):
        c1 = ManualClock(start_ticks=300_000)
        s1 = ShardedDeviceStore(mesh, capacity=10.0, fill_rate_per_sec=1.0,
                                per_shard_slots=16, clock=c1)
        s1.acquire_batch_blocking([("k0", 10), ("k1", 4)])
        snap = s1.snapshot()

        c2 = ManualClock(start_ticks=50)
        s2 = ShardedDeviceStore(mesh, capacity=10.0, fill_rate_per_sec=1.0,
                                per_shard_slots=16, clock=c2)
        s2.restore(snap)
        # k0 drained, k1 has 6 left; global counter restored.
        (r0, r1) = s2.acquire_batch_blocking([("k0", 5), ("k1", 6)])
        assert not r0.granted
        assert r1.granted
        # Elapsed time keeps refilling in the new epoch.
        c2.advance_seconds(5.0)
        (r0,) = s2.acquire_batch_blocking([("k0", 5)])
        assert r0.granted

    def test_geometry_mismatch_rejected(self, mesh):
        a = ShardedDeviceStore(mesh, capacity=5.0, fill_rate_per_sec=1.0,
                               per_shard_slots=16)
        b = ShardedDeviceStore(mesh, capacity=5.0, fill_rate_per_sec=1.0,
                               per_shard_slots=32)
        with pytest.raises(ValueError, match="geometry"):
            b.restore(a.snapshot())

    def test_config_mismatch_rejected(self, mesh):
        a = ShardedDeviceStore(mesh, capacity=10.0, fill_rate_per_sec=1.0,
                               per_shard_slots=16)
        b = ShardedDeviceStore(mesh, capacity=100.0, fill_rate_per_sec=50.0,
                               per_shard_slots=16)
        with pytest.raises(ValueError, match="config"):
            b.restore(a.snapshot())
