"""Smoke-run every BASELINE benchmark config on the CPU test mesh.

The suite is part of the product (the reference has no benchmarks at all,
SURVEY.md §6) — these tests keep all five configs runnable so the real
perf runs never discover bitrot."""

import json

import pytest

from benchmarks import suite


@pytest.mark.parametrize("name", list(suite.CONFIGS))
def test_config_smoke(name):
    result = suite.CONFIGS[name](smoke=True)
    assert result["config"] == name
    assert result["value"] > 0
    assert result["unit"] == "decisions/s"
    json.dumps(result)  # must be JSON-serializable


def test_cli_runs_named_config(capsys):
    assert suite.main(["single_bucket_cpu", "--smoke"]) == 0
    line = capsys.readouterr().out.strip()
    assert json.loads(line)["config"] == "single_bucket_cpu"


def test_scaleout_harness_smoke():
    # The aggregate scale-out harness (benchmarks/scaleout.py) spawns
    # real server/client processes over localhost TCP; keep it runnable.
    from benchmarks import scaleout

    out = scaleout._measure(1, 1, 1.0, "cpu")
    assert out["n_nodes"] == 1 and out["n_clients"] == 1
    assert out["aggregate_decisions_per_sec"] > 0
    json.dumps(out)


def test_two_level_global_tier_accumulates():
    result = suite.CONFIGS["two_level_mesh"](smoke=True)
    # Every request grants (huge capacity), so the psum-fed global counter
    # must have absorbed consumption from all shards of the LAST step at
    # minimum (earlier steps decay).
    assert result["global_score_after"] > 0
    assert result["n_devices"] >= 1


def test_recapture_debt_ledger_semantics(tmp_path):
    """The device-bench debt list (benchmarks/recapture.py): debts are
    owed until an `ok` row that SETTLES lands in the ledger — CPU
    stand-in rows never settle, and a torn tail row hides nothing."""
    from benchmarks import recapture

    names = [n for n, _why, _fn in recapture.DEBTS]
    assert names == ["fp_mesh_fixed", "fp_bulk_optimized",
                     "native_fe_device_sweep"]
    ledger = tmp_path / "recapture.jsonl"
    assert recapture.owed(ledger) == names  # nothing settled yet
    recapture._append(ledger, {"debt": names[0], "status": "ok",
                               "settles_debt": False})  # CPU stand-in
    assert recapture.owed(ledger) == names
    recapture._append(ledger, {"debt": names[0], "status": "ok",
                               "settles_debt": True})  # real device row
    assert recapture.owed(ledger) == names[1:]
    with open(ledger, "a", encoding="utf-8") as f:
        f.write('{"torn json\n')  # a torn tail row must not mask debts
    assert recapture.owed(ledger) == names[1:]
