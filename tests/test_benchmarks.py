"""Smoke-run every BASELINE benchmark config on the CPU test mesh.

The suite is part of the product (the reference has no benchmarks at all,
SURVEY.md §6) — these tests keep all five configs runnable so the real
perf runs never discover bitrot."""

import json

import pytest

from benchmarks import suite


@pytest.mark.parametrize("name", list(suite.CONFIGS))
def test_config_smoke(name):
    result = suite.CONFIGS[name](smoke=True)
    assert result["config"] == name
    assert result["value"] > 0
    assert result["unit"] == "decisions/s"
    json.dumps(result)  # must be JSON-serializable


def test_cli_runs_named_config(capsys):
    assert suite.main(["single_bucket_cpu", "--smoke"]) == 0
    line = capsys.readouterr().out.strip()
    assert json.loads(line)["config"] == "single_bucket_cpu"


def test_scaleout_harness_smoke():
    # The aggregate scale-out harness (benchmarks/scaleout.py) spawns
    # real server/client processes over localhost TCP; keep it runnable.
    from benchmarks import scaleout

    out = scaleout._measure(1, 1, 1.0, "cpu")
    assert out["n_nodes"] == 1 and out["n_clients"] == 1
    assert out["aggregate_decisions_per_sec"] > 0
    json.dumps(out)


def test_two_level_global_tier_accumulates():
    result = suite.CONFIGS["two_level_mesh"](smoke=True)
    # Every request grants (huge capacity), so the psum-fed global counter
    # must have absorbed consumption from all shards of the LAST step at
    # minimum (earlier steps decay).
    assert result["global_score_after"] > 0
    assert result["n_devices"] >= 1


def test_recapture_debt_ledger_semantics(tmp_path):
    """The device-bench debt list (benchmarks/recapture.py): debts are
    owed until an `ok` row that SETTLES lands in the ledger — CPU
    stand-in rows never settle, and a torn tail row hides nothing."""
    from benchmarks import recapture

    names = [n for n, _why, _fn in recapture.DEBTS]
    assert names == ["fp_mesh_fixed", "fp_bulk_optimized",
                     "native_fe_device_sweep", "llm_workload_device",
                     "native_fe_shard_sweep",
                     "llm_reservations_device", "federation_device",
                     "native_fe_uring_sweep", "storm_goodput_device"]
    ledger = tmp_path / "recapture.jsonl"
    assert recapture.owed(ledger) == names  # nothing settled yet
    recapture._append(ledger, {"debt": names[0], "status": "ok",
                               "settles_debt": False})  # CPU stand-in
    assert recapture.owed(ledger) == names
    recapture._append(ledger, {"debt": names[0], "status": "ok",
                               "settles_debt": True})  # real device row
    assert recapture.owed(ledger) == names[1:]
    with open(ledger, "a", encoding="utf-8") as f:
        f.write('{"torn json\n')  # a torn tail row must not mask debts
    assert recapture.owed(ledger) == names[1:]


def test_llm_workload_smoke_and_hier_ratio():
    """The LLM workload bench (ISSUE 10): the in-memory lane runs, is
    JSON-serializable, and holds the acceptance ratio — the
    hierarchical (two-level) path costs ≤ 2× the flat path per row on
    the in-memory backing (one extra bucket touch, amortized loop)."""
    import json as _json

    from benchmarks import llm_workload

    row = llm_workload.run_lane("inprocess", seed=1, n_rows=20_000)
    assert row["rows_per_sec"] > 0 and row["tokens_per_sec"] > 0
    assert row["hier_over_flat_per_row"] <= \
        llm_workload.HIER_RATIO_BUDGET, row
    _json.dumps(row)


def test_llm_workload_generator_is_seed_deterministic():
    from benchmarks import llm_workload

    a = llm_workload.gen_workload(3, 500)
    b = llm_workload.gen_workload(3, 500)
    assert a[0] == b[0] and a[1] == b[1]
    assert (a[2] == b[2]).all() and (a[3] == b[3]).all()
    # The advertised shape: heavy-tailed costs, clamped, all ≥ 1.
    assert int(a[2].min()) >= 1 and int(a[2].max()) <= llm_workload.MAX_COST
    assert a[2].std() > a[2].mean()  # genuinely heavy-tailed


def test_llm_workload_wire_lane_smoke():
    """The bulk wire lane end to end at tiny size (plumbing: HBUCKET
    frames, per-tenant batching, token accounting)."""
    from benchmarks import llm_workload

    row = llm_workload.run_lane("asyncio_bulk", seed=2, n_rows=400)
    assert row["rows"] == 400 and row["frames"] >= 1
    assert row["tokens_per_sec"] > 0
