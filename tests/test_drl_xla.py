"""drl-xla gets checked: the compiled-artifact analyzers must (a) pass
the live tree — the repo ships conformant kernels and an exact budget
ledger — and (b) catch each seeded divergence EXACTLY once, with the
right rule and file:line. The seeded matrix traces real jax kernels in
a synthetic ops/ tree (an un-donated table argument, an XLA-declined
donation, an injected pure_callback, a value leaked through
static_argnames, a loosened ledger), so these tests also pin that the
extractor still derives operands for real decorator shapes — a
refactor that blinds it fails the floor test, not just the live one."""

from __future__ import annotations

import json
import pathlib
import textwrap

import pytest

from tools.drl_check.common import INLINE_SUPPRESSIBLE, KNOWN_RULES
from tools.drl_xla import analyzers, budgets, extract, run_all
from tools.drl_xla.__main__ import main as xla_main

ROOT = pathlib.Path(__file__).resolve().parents[1]
LEDGER = ROOT / "tools" / "drl_xla" / "budgets.json"


# -- shared pipelines (traced once per module, not per test) ----------------

@pytest.fixture(scope="module")
def live():
    """The full pipeline against the live tree, ledger frozen
    (restamp=False): any drift must surface as a finding here, never
    as a silent rewrite inside the test suite."""
    findings, report = run_all(ROOT)
    return findings, report


_SEEDED_SRC = textwrap.dedent("""
    import functools
    import jax
    import jax.numpy as jnp

    @jax.jit
    def missed_donation_kernel(fp, now):
        return fp.at[0, 0].set(jnp.uint32(now)), now + jnp.int32(1)

    @functools.partial(jax.jit, donate_argnums=(0,))
    def declined_donation_kernel(fp, now):
        return (fp[0, 0] + jnp.uint32(now)).astype(jnp.int32)

    @jax.jit
    def callback_kernel(counts, now):
        out = jax.pure_callback(
            lambda x: x,
            jax.ShapeDtypeStruct(counts.shape, counts.dtype), counts)
        return out + now

    @functools.partial(jax.jit, static_argnames=("windows",))
    def leaked_scalar_kernel(counts, windows):
        return counts * windows
""")


def _make_root(base: pathlib.Path, src: str) -> pathlib.Path:
    ops = base / "distributedratelimiting" / "redis_tpu" / "ops"
    ops.mkdir(parents=True)
    (ops / "kernels.py").write_text(src)
    return base


def _def_line(src: str, name: str) -> int:
    for i, line in enumerate(src.splitlines(), start=1):
        if line.startswith(f"def {name}"):
            return i
    raise AssertionError(f"def {name} not in seeded source")


@pytest.fixture(scope="module")
def seeded(tmp_path_factory):
    root = _make_root(tmp_path_factory.mktemp("xla_seeded"), _SEEDED_SRC)
    decls = extract.discover(root, kernel_floor=1)
    arts = extract.trace_kernels(decls, root)
    findings = (analyzers.check_purity(arts)
                + analyzers.check_donation(arts)
                + analyzers.check_retrace(arts))
    return root, arts, findings


# -- the live tree is clean -------------------------------------------------

def test_live_tree_is_clean(live):
    findings, _ = live
    assert findings == [], "\n".join(f.format() for f in findings)


def test_live_ledger_is_exact(live):
    _, report = live
    assert report["budget_status"] == "clean"


def test_extraction_is_rich(live):
    """Non-vacuity: a clean verdict only counts if the extractor saw
    the whole kernel surface. Today's tree holds 46 jitted kernels and
    45 runtime launch sites; the floors trip first on a partial
    regression, this pins the actual population."""
    _, report = live
    assert len(report["decls"]) >= 46 >= extract.KERNEL_FLOOR
    assert sum(len(v) for v in report["sites"].values()) \
        >= 45 >= extract.LAUNCH_SITE_FLOOR
    names = {d.name for d in report["decls"]}
    assert {"acquire_batch_packed", "acquire_hierarchical_packed",
            "fp_debit_batch", "sweep_expired_pallas"} <= names


def test_ledger_stamp_matches_tree():
    """The .so.hash sidecar idiom: the checked-in ledger names the
    exact ops/ sources it measured. A stale stamp here means someone
    edited a kernel without re-running make xla-budget-restamp."""
    ledger = json.loads(LEDGER.read_text())
    assert ledger["stamp"]["sources"] == extract.source_hashes(ROOT)
    assert ledger["stamp"]["dims"] == extract.DIMS


def test_sweep_exists_plane_is_donated_and_aliased(live):
    """Regression pin for the real defect this round fixed:
    sweep_expired_pallas did not donate its exists_i8 occupancy plane,
    double-buffering 1 byte/slot (10 MB transient at 10M slots) on
    every full-table sweep. The fix declares donate_argnums=(2,) — and
    this pin checks the COMPILED artifact, not the decorator: the leaf
    must carry tf.aliasing_output in the lowered StableHLO."""
    _, report = live
    art = next(a for a in report["artifacts"]
               if a.decl.name == "sweep_expired_pallas")
    assert art.decl.donate_argnums == (2,)
    leaf = next(l for l in art.leaves if l.name == "exists_i8")
    assert leaf.donated and leaf.table
    rank = {flat: pos for pos, flat in enumerate(art.kept)}
    assert rank[leaf.index] in art.aliased, \
        "exists_i8 is declared donated but XLA declined the alias"


def test_ledger_records_the_gather_economics(live):
    """The recorded fact the ROADMAP-item-1 fused kernel must beat:
    the two-level hierarchical decision pays strictly more table
    gathers per launch than the flat batch kernel."""
    _, report = live
    m = report["measured"]
    pfx = "distributedratelimiting/redis_tpu/ops/kernels.py::"
    hier = m[pfx + "acquire_hierarchical_packed"]
    flat = m[pfx + "acquire_batch_packed"]
    assert hier["gather"] > flat["gather"] >= 1
    recorded = json.loads(LEDGER.read_text())["kernels"]
    assert recorded[pfx + "acquire_hierarchical_packed"] == hier
    assert recorded[pfx + "acquire_batch_packed"] == flat


# -- seeded divergence matrix -----------------------------------------------

_FILE = "distributedratelimiting/redis_tpu/ops/kernels.py"


def _hits(findings, rule, kernel):
    return [f for f in findings
            if f.rule == rule and f.message.startswith(kernel + ":")]


def test_seeded_missed_donation_fires_once(seeded):
    _, _, findings = seeded
    hits = _hits(findings, "xla-donation", "missed_donation_kernel")
    assert len(hits) == 1
    assert hits[0].file == _FILE
    assert hits[0].line == _def_line(_SEEDED_SRC, "missed_donation_kernel")
    assert "not donated" in hits[0].message


def test_seeded_declined_donation_fires_once(seeded):
    _, _, findings = seeded
    hits = _hits(findings, "xla-donation", "declined_donation_kernel")
    assert len(hits) == 1
    assert hits[0].line == _def_line(_SEEDED_SRC,
                                     "declined_donation_kernel")
    assert "declared donated" in hits[0].message


def test_seeded_callback_fires_once(seeded):
    _, _, findings = seeded
    hits = _hits(findings, "xla-purity", "callback_kernel")
    assert len(hits) == 1
    assert hits[0].line == _def_line(_SEEDED_SRC, "callback_kernel")
    assert "pure_callback" in hits[0].message


def test_seeded_leaked_scalar_fires_once(seeded):
    _, _, findings = seeded
    hits = _hits(findings, "xla-retrace", "leaked_scalar_kernel")
    assert len(hits) == 1
    assert hits[0].line == _def_line(_SEEDED_SRC, "leaked_scalar_kernel")
    assert "cache entries" in hits[0].message


def test_seeded_matrix_is_exact(seeded):
    """Exactly the four seeded defects, nothing else — the analyzers
    neither miss a divergence nor invent one on the clean kernels."""
    _, _, findings = seeded
    assert sorted(f.rule for f in findings) == [
        "xla-donation", "xla-donation", "xla-purity", "xla-retrace"]


def test_seeded_budget_loosening_fails_with_diff(seeded):
    root, arts, _ = seeded
    measured = budgets.measure_all(arts)
    ledger = budgets.make_ledger(root, measured)
    key = (_FILE + "::declined_donation_kernel")
    ledger["kernels"][key]["launches"] -= 1   # recorded tighter than real
    path = root / "budgets.json"
    path.write_text(budgets.dumps(ledger))
    before = path.read_text()
    findings, status = budgets.compare(root, arts, sites=None,
                                       path=path, restamp=True)
    assert status == "loosened"
    assert [f.rule for f in findings] == ["xla-budget"]
    assert "launches 0→1" in findings[0].message
    assert findings[0].file == "budgets.json"
    assert findings[0].line == budgets.key_line(path, key)
    assert findings[0].related[0][1] == _def_line(
        _SEEDED_SRC, "declined_donation_kernel")
    assert path.read_text() == before, \
        "a loosening must never be auto-restamped"


def test_seeded_tightening_restamps_and_staleness_is_loud(seeded):
    root, arts, _ = seeded
    measured = budgets.measure_all(arts)
    ledger = budgets.make_ledger(root, measured)
    key = (_FILE + "::callback_kernel")
    ledger["kernels"][key]["gather"] += 3   # recorded looser than real
    path = root / "tightened.json"
    path.write_text(budgets.dumps(ledger))
    # frozen: the improvement is drift, reported not rewritten
    findings, status = budgets.compare(root, arts, sites=None,
                                       path=path, restamp=False)
    assert status == "stale"
    assert [f.rule for f in findings] == ["xla-stale-ledger"]
    # interactive: the improvement restamps and becomes the new floor
    findings, status = budgets.compare(root, arts, sites=None,
                                       path=path, restamp=True)
    assert (findings, status) == ([], "restamped")
    assert json.loads(path.read_text())["kernels"][key] == measured[key]
    assert budgets.compare(root, arts, sites=None, path=path,
                           restamp=False) == ([], "clean")


def test_missing_ledger_is_a_stale_finding(seeded):
    root, arts, _ = seeded
    findings, status = budgets.compare(
        root, arts, sites=None, path=root / "absent.json", restamp=False)
    assert status == "stale"
    assert [f.rule for f in findings] == ["xla-stale-ledger"]
    assert "no ledger exists" in findings[0].message


# -- suppression: honored at the def line, audited when dormant -------------

_SUPPRESSED_SRC = textwrap.dedent("""
    import jax
    import jax.numpy as jnp

    @jax.jit
    def excused_kernel(fp, now):  # drl-check: ok(xla-donation)
        return fp.at[0, 0].set(jnp.uint32(now))

    @jax.jit
    def dormant_kernel(counts, now):  # drl-check: ok(xla-purity)
        return counts + now
""")


def test_suppression_honored_and_audited(tmp_path):
    root = _make_root(tmp_path, _SUPPRESSED_SRC)
    decls = extract.discover(root, kernel_floor=1)
    arts = extract.trace_kernels(decls, root)
    raw = (analyzers.check_purity(arts)
           + analyzers.check_donation(arts)
           + analyzers.check_retrace(arts))
    assert [f.rule for f in raw] == ["xla-donation"]   # excused_kernel
    kept = analyzers.apply_suppressions(raw, root, decls)
    # the real finding was eaten by its ok(...); the dormant ok(...)
    # became a stale-suppression finding at ITS line
    assert [f.rule for f in kept] == ["stale-suppression"]
    assert kept[0].line == _def_line(_SUPPRESSED_SRC, "dormant_kernel")
    assert "xla-purity" in kept[0].message


def test_xla_rules_are_registered_with_drl_check():
    """drl-check owns the suppression registry: every xla-* rule must
    be a known spelling, suppressible except the freshness rule — a
    stale ledger is a fact about the tree, not a judgment call."""
    assert analyzers.XLA_RULES <= KNOWN_RULES
    assert {"jit-f64", "jit-closed-scalar"} <= KNOWN_RULES
    assert (analyzers.XLA_RULES - {"xla-stale-ledger"}) \
        <= INLINE_SUPPRESSIBLE
    assert "xla-stale-ledger" not in INLINE_SUPPRESSIBLE


# -- extractor non-vacuity: a blind extractor exits 2, never "clean" --------

def test_blind_extractor_raises(tmp_path):
    root = _make_root(tmp_path, _SEEDED_SRC)   # 4 kernels < floor 40
    with pytest.raises(extract.ExtractionError, match="gone blind"):
        extract.discover(root)
    assert len(extract.discover(root, kernel_floor=1)) == 4


def test_underivable_operand_raises(tmp_path):
    src = textwrap.dedent("""
        import jax

        @jax.jit
        def mystery_kernel(enigma):
            return enigma
    """)
    root = _make_root(tmp_path, src)
    decls = extract.discover(root, kernel_floor=1)
    with pytest.raises(extract.ExtractionError, match="no shape rule"):
        extract.trace_kernels(decls, root)


# -- CLI exit codes: 0 clean / 1 findings / 2 blinded -----------------------

def test_cli_exit_0_on_live_tree(capsys):
    assert xla_main(["--no-restamp", "--only", "budget"]) == 0
    out = capsys.readouterr().out
    assert "ledger clean; clean" in out


def test_cli_exit_1_on_loosened_ledger(tmp_path, capsys):
    doctored = json.loads(LEDGER.read_text())
    key = next(k for k, v in sorted(doctored["kernels"].items())
               if v["gather"] > 0)
    doctored["kernels"][key]["gather"] -= 1
    path = tmp_path / "budgets.json"
    path.write_text(budgets.dumps(doctored))
    assert xla_main(["--no-restamp", "--only", "budget",
                     "--ledger", str(path)]) == 1
    out = capsys.readouterr().out
    assert "error[xla-budget]" in out
    assert "kernel definition" in out   # file:line on BOTH sides


def test_cli_exit_2_on_blind_extractor(tmp_path, capsys):
    root = _make_root(tmp_path, _SEEDED_SRC)
    assert xla_main(["--root", str(root)]) == 2
    assert "gone blind" in capsys.readouterr().err


# -- satellite: the recapture ledger names its budget ledger ----------------

def test_recapture_rows_carry_the_budget_ledger_hash():
    from benchmarks.recapture import _budget_ledger_hash
    h = _budget_ledger_hash()
    assert h == budgets.ledger_hash(LEDGER)
    assert isinstance(h, str) and len(h) == 12
