"""Zero-downtime operations: live limit mutation, drain-and-handoff
shutdown, and the rolling-restart soak (ISSUE 7; docs/OPERATIONS.md §10,
DESIGN.md §13).

Three planes under test:

- **Live config mutation** (runtime/liveconfig.py): the versioned
  two-phase ``OP_CONFIG`` plane — prepare/commit/abort idempotence,
  the epoch-rebase balance carry through ``debit_many``, the routable
  "config moved" error and the client's one-chase translation cache,
  and the coordinator's clean abort.
- **Drain-and-handoff shutdown** (``BucketStoreServer.shutdown``): a
  planned exit ships state to a successor through the MIGRATE_PUSH lane
  (or to a final checkpoint), serving stragglers from the withheld
  fair-share envelope for the handoff window.
- **Rolling-restart soak**: restart every node of a 3-node cluster one
  at a time under wire chaos and live traffic, mutate a limit mid-roll,
  and audit from the stores' own admission records that no acquire is
  double-admitted and the hot key's over-admission stays inside the
  epsilon envelope (``make upgrade-soak SEED=…`` replays any run).
"""

from __future__ import annotations

import asyncio
import os
import time

import pytest

from distributedratelimiting.redis_tpu.models.approximate import (
    headroom_budget,
)
from distributedratelimiting.redis_tpu.runtime import liveconfig, wire
from distributedratelimiting.redis_tpu.runtime.cluster import (
    ClusterBucketStore,
)
from distributedratelimiting.redis_tpu.runtime.liveconfig import (
    ConfigError,
    ConfigRule,
    ConfigState,
    StaleConfigError,
)
from distributedratelimiting.redis_tpu.runtime.remote import (
    RemoteBucketStore,
    StoreTimeoutError,
)
from distributedratelimiting.redis_tpu.runtime.server import (
    BucketStoreServer,
)
from distributedratelimiting.redis_tpu.runtime.store import (
    InProcessBucketStore,
)
from distributedratelimiting.redis_tpu.utils import faults
from distributedratelimiting.redis_tpu.utils.faults import (
    FaultInjector,
    FaultRule,
)

SEED = int(os.environ.get("DRL_UPGRADE_SEED", "20260803"))

_NET_ERRORS = (ConnectionError, OSError, StoreTimeoutError,
               wire.RemoteStoreError)


def run(coro):
    return asyncio.run(coro)


@pytest.fixture(autouse=True)
def _no_leaked_injector():
    yield
    faults.uninstall()


# -- liveconfig unit surface -------------------------------------------------

def test_config_rule_validation():
    with pytest.raises(ConfigError):
        ConfigRule("nope", (1.0, 1.0), (2.0, 1.0))
    with pytest.raises(ConfigError):
        ConfigRule("bucket", (1.0, 1.0), (1.0, 1.0))  # self-rewrite
    with pytest.raises(ConfigError):
        ConfigRule("bucket", (0.0, 1.0), (2.0, 1.0))  # a must be > 0
    with pytest.raises(ConfigError):
        ConfigRule("bucket", (float("nan"), 1.0), (2.0, 1.0))
    r = ConfigRule("bucket", (100, 1), (50, 1))
    assert ConfigRule.from_dict(r.to_dict()) == r


def test_moved_message_roundtrip():
    msg = liveconfig.moved_message("window", (10.0, 5.0), (4.0, 5.0), 3)
    assert msg.startswith(liveconfig.CONFIG_MOVED_PREFIX)
    assert liveconfig.parse_moved(msg) == (
        "window", (10.0, 5.0), (4.0, 5.0), 3)
    assert liveconfig.parse_moved("some other error") is None
    assert liveconfig.parse_moved(
        liveconfig.CONFIG_MOVED_PREFIX + ": {broken json") is None


def test_config_state_two_phase_idempotent():
    async def body():
        st = ConfigState()
        store = InProcessBucketStore()
        rule = ConfigRule("bucket", (100.0, 0.0), (50.0, 0.0))
        assert not st.active
        # prepare stages, serving unchanged
        v = await st.announce({"prepare": rule.to_dict(), "version": 1},
                              store)
        assert v == 0 and not st.active
        # re-prepare at the same version with the SAME rule: idempotent
        await st.announce({"prepare": rule.to_dict(), "version": 1},
                          store)
        # a DIFFERENT rule at the same version is a conflict, loudly
        other = ConfigRule("bucket", (100.0, 0.0), (25.0, 0.0))
        with pytest.raises(StaleConfigError):
            await st.announce({"prepare": other.to_dict(), "version": 1},
                              store)
        # commit flips the gate; a retried commit no-ops at the version
        assert await st.announce({"commit": 1}, store) == 1
        assert st.active and st.commits == 1
        assert await st.announce({"commit": 1}, store) == 1
        assert st.commits == 1  # idempotent — no second rebase
        # stale prepare (version not > committed) is typed
        with pytest.raises(StaleConfigError):
            await st.announce({"prepare": other.to_dict(), "version": 1},
                              store)
        # the forwarding gate answers for the retired config only
        assert st.forward("bucket", 100.0, 0.0) == (50.0, 0.0, 1)
        assert st.forward("bucket", 50.0, 0.0) is None
        # commit for an unstaged version is an error, not a silent skip
        with pytest.raises(ConfigError):
            await st.announce({"commit": 5}, store)

    run(body())


def test_config_state_abort_drops_staged_rule():
    async def body():
        st = ConfigState()
        store = InProcessBucketStore()
        rule = ConfigRule("bucket", (10.0, 1.0), (5.0, 1.0))
        await st.announce({"prepare": rule.to_dict(), "version": 1},
                          store)
        await st.announce({"abort": 1}, store)
        assert st.aborts == 1 and not st.active
        with pytest.raises(ConfigError):
            await st.announce({"commit": 1}, store)  # abort dropped it

    run(body())


def test_config_chain_compression_one_chase():
    """Committing A→B then B→C rewrites the A rule to A→C: a client two
    mutations behind chases ONE moved error, not one per hop."""
    async def body():
        st = ConfigState()
        store = InProcessBucketStore()
        a, b, c = (100.0, 0.0), (50.0, 0.0), (25.0, 0.0)
        await st.announce({"prepare": ConfigRule(
            "bucket", a, b).to_dict(), "version": 1}, store)
        await st.announce({"commit": 1}, store)
        await st.announce({"prepare": ConfigRule(
            "bucket", b, c).to_dict(), "version": 2}, store)
        await st.announce({"commit": 2}, store)
        assert st.forward("bucket", *a) == (25.0, 0.0, 2)
        assert st.forward("bucket", *b) == (25.0, 0.0, 2)

    run(body())


def test_rebase_carries_spent_budget_buckets_and_windows():
    async def body():
        store = InProcessBucketStore()
        await store.acquire("k", 30, 100.0, 0.0)     # 30 spent
        await store.window_acquire("w", 7, 10.0, 1000.0)
        st = ConfigState()
        await st.announce({"prepare": ConfigRule(
            "bucket", (100.0, 0.0), (50.0, 0.0)).to_dict(),
            "version": 1}, store)
        await st.announce({"commit": 1}, store)
        # 30 spent of 100 → new table holds 50 − 30 = 20
        assert store.peek_blocking("k", 50.0, 0.0) == 20.0
        await st.announce({"prepare": ConfigRule(
            "window", (10.0, 1000.0), (5.0, 1000.0)).to_dict(),
            "version": 2}, store)
        await st.announce({"commit": 2}, store)
        # 7 of 10 consumed replays clamped into the new limit 5: full
        r = await store.window_acquire("w", 1, 5.0, 1000.0)
        assert not r.granted
        assert st.rebased_rows >= 2

    run(body())


def test_window_rebase_floors_fractional_carry():
    """Review regression: the window replay used to ceil the carried
    count — a fractional carry rounded UP past a fractional new limit
    was DENIED, recording nothing, and the key reset to a fresh full
    budget (over-admission from the carry mechanism itself)."""
    async def body():
        store = InProcessBucketStore()
        wt = int(1000.0 * 1024)  # TICKS_PER_SECOND
        idx = store.clock.now_ticks() // wt
        # current-window count 10.2 under limit 11 (fractional counts
        # arise from envelope pre-charges on migrated windows)
        store._windows[("w", 11.0, wt, True)] = (0.0, 10.2, idx)
        st = ConfigState()
        await st.announce({"prepare": ConfigRule(
            "window", (11.0, 1000.0), (10.5, 1000.0)).to_dict(),
            "version": 1}, store)
        await st.announce({"commit": 1}, store)
        # floor(10.2) = 10 carried: 0.5 of headroom left, 1 is denied
        r = await store.window_acquire("w", 1, 10.5, 1000.0)
        assert not r.granted

    run(body())


def test_rebase_can_only_under_admit():
    """The saturating carry: a spend EXCEEDING the new cap lands at
    zero, never negative, never a fresh full budget."""
    async def body():
        store = InProcessBucketStore()
        await store.acquire("k", 90, 100.0, 0.0)
        st = ConfigState()
        await st.announce({"prepare": ConfigRule(
            "bucket", (100.0, 0.0), (20.0, 0.0)).to_dict(),
            "version": 1}, store)
        await st.announce({"commit": 1}, store)
        assert store.peek_blocking("k", 20.0, 0.0) == 0.0

    run(body())


def test_config_revert_deletes_rule_instead_of_self_forwarding():
    """Review regression: committing A→B then the revert B→A used to
    compress A's rule into A→A — forward(A) bounced every A frame to
    itself and the client (rightly refusing an identity rule) failed
    the call forever. A revert must DELETE A's rule: A is current."""
    async def body():
        st = ConfigState()
        store = InProcessBucketStore()
        a, b = (100.0, 0.0), (50.0, 0.0)
        await st.announce({"prepare": ConfigRule(
            "bucket", a, b).to_dict(), "version": 1}, store)
        await st.announce({"commit": 1}, store)
        await st.announce({"prepare": ConfigRule(
            "bucket", b, a).to_dict(), "version": 2}, store)
        await st.announce({"commit": 2}, store)
        assert st.forward("bucket", *a) is None  # A serves again
        assert st.forward("bucket", *b) == (a[0], a[1], 2)

    run(body())


def test_revert_mutation_converges_stale_clients():
    """E2E revert over the wire: a client that already learned A→B must
    converge back to A after the revert (cycle-safe resolve + inverse
    eviction), not loop or fail."""
    async def body():
        backing = InProcessBucketStore()
        async with BucketStoreServer(backing) as srv:
            c = RemoteBucketStore(address=(srv.host, srv.port),
                                  coalesce_requests=False)
            try:
                await c.acquire("k", 30, 100.0, 0.0)
                await c.config_announce({"prepare": {
                    "kind": "bucket", "old": [100.0, 0.0],
                    "new": [50.0, 0.0]}, "version": 1})
                await c.config_announce({"commit": 1})
                r = await c.acquire("k", 0, 100.0, 0.0)  # learns A→B
                assert r.remaining == 20.0
                await c.config_announce({"prepare": {
                    "kind": "bucket", "old": [50.0, 0.0],
                    "new": [100.0, 0.0]}, "version": 2})
                await c.config_announce({"commit": 2})
                # stale cache says A→B; the revert's moved error teaches
                # B→A, evicts the contradicted entry, and the call lands
                # on A — carried balance: spent 30 then 20-rebase-carry
                r = await c.acquire("k", 0, 100.0, 0.0)
                assert r.granted
                # converged: later calls translate to A up front and the
                # server sees no more moved chases than the two hops
                st = await c.stats()
                moved_before = st["config"]["moved_errors"]
                for _ in range(5):
                    await c.acquire("k", 0, 100.0, 0.0)
                st = await c.stats()
                assert st["config"]["moved_errors"] == moved_before
            finally:
                await c.aclose()

    run(body())


# -- the wire plane (OP_CONFIG + the moved gate) -----------------------------

def test_op_config_fetch_mutate_and_gate_over_wire():
    async def body():
        backing = InProcessBucketStore()
        async with BucketStoreServer(backing) as srv:
            c = RemoteBucketStore(address=(srv.host, srv.port),
                                  coalesce_requests=False)
            try:
                assert await c.config_fetch() == {"version": 0,
                                                  "rules": []}
                for _ in range(30):
                    await c.acquire("k", 1, 100.0, 0.0)
                rule = {"kind": "bucket", "old": [100.0, 0.0],
                        "new": [50.0, 0.0]}
                assert await c.config_announce(
                    {"prepare": rule, "version": 1}) == 0
                assert await c.config_announce({"commit": 1}) == 1
                got = await c.config_fetch()
                assert got["version"] == 1
                assert got["rules"][0]["new"] == [50.0, 0.0]
                # Old config chases ONE moved error, then translates
                # client-side: the server sees exactly one moved answer.
                r = await c.acquire("k", 0, 100.0, 0.0)
                assert r.remaining == 20.0  # 50 − 30 spent
                r = await c.acquire("k", 5, 100.0, 0.0)
                assert r.granted
                st = await c.stats()
                assert st["config"]["moved_errors"] == 1
                # PEEK redirects too (a probe against the retired table
                # would report a number nobody serves from).
                assert await asyncio.to_thread(
                    c.peek_blocking, "k", 100.0, 0.0) == 15.0
            finally:
                await c.aclose()

    run(body())


def test_bulk_lane_chases_config_moved_frame_level():
    async def body():
        backing = InProcessBucketStore()
        async with BucketStoreServer(backing) as srv:
            c = RemoteBucketStore(address=(srv.host, srv.port),
                                  coalesce_requests=False)
            try:
                await c.config_announce({"prepare": {
                    "kind": "bucket", "old": [100.0, 0.0],
                    "new": [50.0, 0.0]}, "version": 1})
                await c.config_announce({"commit": 1})
                keys = [f"b{i}" for i in range(64)]
                res = await c.acquire_many(keys, [1] * 64, 100.0, 0.0)
                assert res.granted.all()
                # every row landed on the NEW table
                assert backing.peek_blocking("b0", 50.0, 0.0) == 49.0
                st = await c.stats()
                assert st["config"]["moved_errors"] == 1
                # …and the translation is cached for the next frame
                res = await c.acquire_many(keys, [1] * 64, 100.0, 0.0)
                assert res.granted.all()
                st = await c.stats()
                assert st["config"]["moved_errors"] == 1
                # window bulk lane gates identically
                await c.config_announce({"prepare": {
                    "kind": "window", "old": [10.0, 100.0],
                    "new": [4.0, 100.0]}, "version": 2})
                await c.config_announce({"commit": 2})
                res = await c.window_acquire_many(
                    keys[:8], [1] * 8, 10.0, 100.0)
                assert res.granted.all()
                assert not (await c.window_acquire_many(
                    ["b0"], [4], 10.0, 100.0)).granted.any()
            finally:
                await c.aclose()

    run(body())


def test_op_config_is_post_send_retry_safe_classified():
    from distributedratelimiting.redis_tpu.runtime import remote

    assert wire.OP_CONFIG in remote._IDEMPOTENT_OPS
    assert wire.OP_CONFIG not in remote._NON_IDEMPOTENT_OPS


def test_cluster_mutation_aborts_cleanly_on_prepare_fault():
    async def body():
        backings = [InProcessBucketStore() for _ in range(2)]
        servers = [BucketStoreServer(b) for b in backings]
        for s in servers:
            await s.start()
        cluster = ClusterBucketStore(
            addresses=[(s.host, s.port) for s in servers],
            coalesce_requests=False, request_timeout_s=1.0,
            retry_policy=None)
        try:
            for _ in range(10):
                await cluster.acquire("k", 1, 100.0, 0.0)
            faults.install(FaultInjector(SEED, {
                "cluster.config": (FaultRule("error", probability=1.0),)}))
            with pytest.raises(ConfigError):
                await cluster.mutate_config("bucket", (100.0, 0.0),
                                            (50.0, 0.0))
            assert cluster.config_aborts == 1
            assert cluster.migration_log[-1]["type"] == "config_abort"
            faults.uninstall()
            # nothing committed anywhere: old config serves untouched
            for s in servers:
                assert not s.liveconfig.active
                assert s.liveconfig.version == 0
            r = await cluster.acquire("k", 0, 100.0, 0.0)
            assert r.granted
            # fault cleared → the SAME mutation commits fleet-wide
            v = await cluster.mutate_config("bucket", (100.0, 0.0),
                                            (50.0, 0.0))
            assert v == 1
            assert all(s.liveconfig.version == 1 for s in servers)
        finally:
            faults.uninstall()
            await cluster.aclose()
            for s in servers:
                await s.aclose()

    run(body())


# -- drain-and-handoff shutdown ----------------------------------------------

def test_shutdown_ships_state_to_successor_exactly():
    async def body():
        old_back, new_back = (InProcessBucketStore(),
                              InProcessBucketStore())
        old = BucketStoreServer(old_back)
        new = BucketStoreServer(new_back)
        await old.start()
        await new.start()
        c = RemoteBucketStore(address=(old.host, old.port),
                              coalesce_requests=False)
        succ = RemoteBucketStore(address=(new.host, new.port),
                                 coalesce_requests=False)
        try:
            for _ in range(30):
                await c.acquire("k", 1, 100.0, 0.0)
            summary = await old.shutdown(successor=succ, window_s=0.05)
            assert summary["shipped_rows"] == 1
            # shipped balance = 70 remaining − 50 envelope withheld
            tokens, _ = new_back._buckets[("k", 100.0, 0.0)]
            assert tokens == pytest.approx(20.0)
            # the OLD store was debited for the shipped amount: even a
            # lingering process cannot re-spend what it handed off
            assert old_back.peek_blocking("k", 100.0, 0.0) <= 50.0
            # idempotent: a second shutdown is a no-op
            assert (await old.shutdown(successor=succ))["already"]
        finally:
            await c.aclose()
            await succ.aclose()
            await new.aclose()

    run(body())


def test_shutdown_serves_envelope_during_drain_window():
    async def body():
        old_back, new_back = (InProcessBucketStore(),
                              InProcessBucketStore())
        old = BucketStoreServer(old_back)
        new = BucketStoreServer(new_back)
        await old.start()
        await new.start()
        c = RemoteBucketStore(address=(old.host, old.port),
                              coalesce_requests=False,
                              request_timeout_s=1.0)
        succ = RemoteBucketStore(address=(new.host, new.port),
                                 coalesce_requests=False)
        try:
            await c.acquire("k", 10, 1000.0, 0.0)
            task = asyncio.ensure_future(
                old.shutdown(successor=succ, window_s=0.4))
            # straggler traffic during the window: bounded envelope
            # answers, not connection resets
            served = denied = 0
            t0 = time.monotonic()
            while not task.done() and time.monotonic() - t0 < 2.0:
                try:
                    r = await c.acquire("k", 1, 1000.0, 0.0)
                    served += 1 if r.granted else 0
                    denied += 0 if r.granted else 1
                except _NET_ERRORS:
                    pass
                await asyncio.sleep(0.01)
            summary = await task
            assert summary["envelope_decisions"] >= 1
            # the envelope is the withheld fair-share budget, hard-capped
            budget = headroom_budget(1000.0, fraction=0.5, min_budget=1.0)
            assert served <= budget
        finally:
            await c.aclose()
            await succ.aclose()
            await new.aclose()

    run(body())


def test_shutdown_without_successor_writes_final_checkpoint(tmp_path):
    async def body():
        from distributedratelimiting.redis_tpu.runtime import checkpoint

        path = str(tmp_path / "final.bin")
        back = InProcessBucketStore()
        srv = BucketStoreServer(back, snapshot_path=path)
        await srv.start()
        c = RemoteBucketStore(address=(srv.host, srv.port),
                              coalesce_requests=False)
        try:
            for _ in range(40):
                await c.acquire("k", 1, 100.0, 0.0)
        finally:
            await c.aclose()
        summary = await srv.shutdown()
        assert summary["checkpoint"] == path
        # the restarted process restores the exact balance
        fresh = InProcessBucketStore()
        checkpoint.load_snapshot_chain(fresh, path)
        assert fresh.peek_blocking("k", 100.0, 0.0) == 60.0

    run(body())


def test_shutdown_checkpoint_uses_incremental_chain(tmp_path):
    async def body():
        from distributedratelimiting.redis_tpu.runtime import checkpoint

        path = str(tmp_path / "snap.bin")
        back = InProcessBucketStore()
        srv = BucketStoreServer(back, snapshot_path=path,
                                snapshot_incremental=True)
        await srv.start()
        c = RemoteBucketStore(address=(srv.host, srv.port),
                              coalesce_requests=False)
        try:
            for i in range(64):
                await c.acquire(f"k{i}", 1, 100.0, 0.0)
            await c.save()  # base
            await c.acquire("k0", 5, 100.0, 0.0)
            await c.save()  # delta 1
            st = await c.stats()
            assert st["snapshot_chain"]["delta_saves"] >= 1
            assert st["snapshot_chain"]["dirty"]["total"] >= 64
        finally:
            await c.aclose()
        summary = await srv.shutdown()  # final save through the chain
        assert summary["checkpoint"]
        fresh = InProcessBucketStore()
        checkpoint.load_snapshot_chain(fresh, path)
        assert fresh.peek_blocking("k0", 100.0, 0.0) == 94.0
        assert fresh.peek_blocking("k63", 100.0, 0.0) == 99.0

    run(body())


def test_failed_drain_falls_back_to_final_checkpoint(tmp_path):
    """Review regression: shutdown() used to latch _shutdown_done
    before doing any work — a push failure left the state neither on
    the successor nor on disk, and the retry answered {'already'}.
    With a snapshot path, a failed drain now lands the state in a
    final checkpoint instead."""
    async def body():
        from distributedratelimiting.redis_tpu.runtime import checkpoint

        path = str(tmp_path / "fallback.bin")
        back = InProcessBucketStore()
        srv = BucketStoreServer(back, snapshot_path=path)
        await srv.start()
        c = RemoteBucketStore(address=(srv.host, srv.port),
                              coalesce_requests=False)
        try:
            for _ in range(30):
                await c.acquire("k", 1, 100.0, 0.0)
        finally:
            await c.aclose()
        # successor at a dead address: the push cannot land
        dead = RemoteBucketStore(address=("127.0.0.1", 1),
                                 coalesce_requests=False,
                                 request_timeout_s=0.3,
                                 retry_policy=None)
        try:
            summary = await srv.shutdown(successor=dead, window_s=0.05)
        finally:
            await dead.aclose()
        assert summary["checkpoint"] == path
        assert "drain_error" in summary
        fresh = InProcessBucketStore()
        checkpoint.load_snapshot_chain(fresh, path)
        # the balance survived (the envelope debit may have landed —
        # conservative direction only, never a fresh full budget)
        assert fresh.peek_blocking("k", 100.0, 0.0) <= 70.0

    run(body())


def test_failed_drain_without_snapshot_is_retryable():
    """…and with no snapshot path the failure re-opens shutdown: the
    retry against a healthy successor ships the state."""
    async def body():
        old_back, new_back = (InProcessBucketStore(),
                              InProcessBucketStore())
        old = BucketStoreServer(old_back)
        new = BucketStoreServer(new_back)
        await old.start()
        await new.start()
        c = RemoteBucketStore(address=(old.host, old.port),
                              coalesce_requests=False)
        try:
            for _ in range(30):
                await c.acquire("k", 1, 100.0, 0.0)
        finally:
            await c.aclose()
        dead = RemoteBucketStore(address=("127.0.0.1", 1),
                                 coalesce_requests=False,
                                 request_timeout_s=0.3,
                                 retry_policy=None)
        with pytest.raises(Exception):
            await old.shutdown(successor=dead, window_s=0.05)
        await dead.aclose()
        # review regression: the failed drain must DISARM the envelope —
        # the still-running server resumes authoritative serving from
        # the (debited) store, it is not envelope-capped forever
        assert old._drain_envelope is None
        c2 = RemoteBucketStore(address=(old.host, old.port),
                               coalesce_requests=False)
        try:
            r = await c2.acquire("k", 0, 100.0, 0.0)
            assert r.granted  # served from the store, post-debit
        finally:
            await c2.aclose()
        succ = RemoteBucketStore(address=(new.host, new.port),
                                 coalesce_requests=False)
        try:
            summary = await old.shutdown(successor=succ, window_s=0.05)
        finally:
            await succ.aclose()
        assert summary.get("already") is None
        assert ("k", 100.0, 0.0) in new_back._buckets
        await new.aclose()

    run(body())


# -- the rolling-restart soak -------------------------------------------------

class RecordingStore(InProcessBucketStore):
    """Backing store stamping every authoritative admission — the ground
    truth the double-admit audit replays. Envelope decisions (drain or
    degraded) never reach a store, by design; they are bounded by the
    epsilon assertion instead."""

    def __init__(self, *a, **kw):
        super().__init__(*a, **kw)
        self.admissions: list[tuple[str, float, bool]] = []

    async def acquire(self, key, count, capacity, fill_rate_per_sec):
        res = await super().acquire(key, count, capacity,
                                    fill_rate_per_sec)
        self.admissions.append((key, time.monotonic(),
                                bool(res.granted and count > 0)))
        return res


class TestRollingRestartSoak:
    RULES = {
        "client.connect": (
            FaultRule("reset", probability=0.08),
            FaultRule("delay", probability=0.2, delay_s=0.001,
                      jitter_s=0.002),
        ),
        "server.dispatch": (
            FaultRule("delay", probability=0.05, delay_s=0.002,
                      jitter_s=0.002),
        ),
    }

    def test_soak_rolling_restart_with_midroll_mutation(self):
        """Restart all 3 nodes one at a time (drain-and-handoff to a
        successor process, LB switch via replace_node) under wire chaos
        and live traffic, mutate the hot limit mid-roll, then audit:
        zero double-admits over the stores' own records, hot-key
        over-admission inside the epsilon envelope, no stranded
        futures, deterministic schedule."""

        async def main():
            inj = FaultInjector(SEED, self.RULES)
            faults.install(inj)
            cap_hot = 40.0
            new_cap = 24.0
            generations = [[RecordingStore()] for _ in range(3)]
            servers = [BucketStoreServer(g[0]) for g in generations]
            for s in servers:
                await s.start()
            cluster = ClusterBucketStore(
                addresses=[(s.host, s.port) for s in servers],
                coalesce_requests=False, request_timeout_s=1.0,
                reconnect_backoff_base_s=0.004, resilience_seed=SEED)

            hot_grants = 0
            unique_sent = 0
            cold_ok = 0
            cold_n = 0
            stop = asyncio.Event()

            async def drive():
                nonlocal hot_grants, unique_sent, cold_ok, cold_n
                i = 0
                while not stop.is_set():
                    i += 1
                    try:
                        # NOTE: always the ORIGINAL operands — after the
                        # mid-roll mutation this lane proves the moved
                        # chase + client-side translation.
                        r = await cluster.acquire("hot", 1, cap_hot,
                                                  1e-9)
                        hot_grants += r.granted
                    except _NET_ERRORS:
                        pass
                    try:
                        # unique-key lane: each logical acquire must be
                        # admitted AT MOST once fleet-wide, ever.
                        unique_sent += 1
                        await cluster.acquire(f"u{i}", 1, 1.0, 1e-9)
                    except _NET_ERRORS:
                        pass
                    cold_n += 1
                    try:
                        r = await cluster.acquire(f"cold{i % 16}", 1,
                                                  1e6, 1.0)
                        cold_ok += r.granted
                    except _NET_ERRORS:
                        pass
                    await asyncio.sleep(0)

            shipped_total = 0

            async def roll(j: int) -> None:
                nonlocal shipped_total
                new_back = RecordingStore()
                new_srv = BucketStoreServer(new_back)
                await new_srv.start()
                succ = RemoteBucketStore(
                    address=(new_srv.host, new_srv.port),
                    coalesce_requests=False)
                try:
                    summary = await servers[j].shutdown(
                        successor=succ, window_s=0.25)
                finally:
                    await succ.aclose()
                shipped_total += summary["shipped_rows"]
                generations[j].append(new_back)
                servers[j] = new_srv
                await cluster.replace_node(
                    j, address=(new_srv.host, new_srv.port))

            async def upgrade():
                await asyncio.sleep(0.15)
                await roll(0)
                await asyncio.sleep(0.10)
                # mid-roll live limit mutation: 40 → 24, balances carry
                v = await cluster.mutate_config(
                    "bucket", (cap_hot, 1e-9), (new_cap, 1e-9))
                assert v == 1
                await asyncio.sleep(0.10)
                await roll(1)
                await asyncio.sleep(0.10)
                await roll(2)
                await asyncio.sleep(0.15)
                stop.set()

            driver = asyncio.ensure_future(drive())
            try:
                await asyncio.wait_for(upgrade(), 60.0)
                await driver
            finally:
                driver.cancel()
                try:
                    await driver
                except (asyncio.CancelledError, Exception):
                    pass

            try:
                # Every node restarted once; state rode the handoff.
                assert all(len(g) == 2 for g in generations)
                assert shipped_total >= 1
                assert cluster.config_mutations == 1
                ev = [e for e in cluster.migration_log
                      if e["type"] == "config_commit"]
                assert len(ev) == 1 and ev[0]["commit_errors"] == 0
                # The fleet's gates all committed the mutation; the
                # stale-operand hot lane really exercised them.
                assert all(s.liveconfig.version == 1 for s in servers)
                assert sum(s.liveconfig.moved_errors
                           for s in servers) >= 1

                # Differential audit over the stores' OWN records:
                # no unique-key acquire admitted twice, ever — not
                # across a handoff, not across the mutation.
                grants: dict[str, int] = {}
                for gen in generations:
                    for store in gen:
                        for key, _t, granted in store.admissions:
                            if granted and key.startswith("u"):
                                grants[key] = grants.get(key, 0) + 1
                doubles = {k: n for k, n in grants.items() if n > 1}
                assert doubles == {}, f"double-admitted: {doubles}"
                assert len(grants) >= 50, "audit must not be vacuous"

                # Epsilon envelope on the hot key: the mutation rebase
                # carries spent budget (can only under-admit), so total
                # grants stay within the ORIGINAL cap plus one
                # fair-share envelope per restart episode.
                budget = headroom_budget(cap_hot, fraction=0.5,
                                         min_budget=1.0)
                assert hot_grants <= cap_hot + budget * 3, (
                    hot_grants, budget)
                assert hot_grants >= 10  # availability through the roll
                assert cold_ok >= cold_n * 0.5

                # Zero stranded futures on any live node client.
                for node in cluster.nodes:
                    assert node._pending == {}

                # Schedule determinism: realized == pure preview.
                for seam in self.RULES:
                    realized = [e for e in inj.events if e.seam == seam]
                    assert realized == inj.schedule_preview(
                        seam, inj.occurrence_count(seam))
                twin = FaultInjector(SEED, self.RULES)
                for seam in self.RULES:
                    assert (twin.schedule_preview(
                        seam, inj.occurrence_count(seam))
                        == inj.schedule_preview(
                            seam, inj.occurrence_count(seam)))
            finally:
                await cluster.aclose()
                for s in servers:
                    await s.aclose()

        run(main())


# -- native front-end: tier-0 × live config ----------------------------------

def _native_tier0_lib():
    from distributedratelimiting.redis_tpu.utils.native import (
        load_frontend_lib,
    )

    lib = load_frontend_lib()
    return lib if lib is not None and getattr(lib, "has_tier0",
                                              False) else None


@pytest.mark.skipif(_native_tier0_lib() is None,
                    reason="native front-end (tier-0 ABI) unavailable")
def test_tier0_retired_config_reroutes_debits_and_stops_serving():
    """A config mutation retiring a tier-0-hosted (cap, rate): the sync
    pump re-routes the harvested debits onto the REPLACEMENT config's
    table and zeroes the replica's headroom, so within a sync interval
    the C fast lane stops admitting against the dead table and stale
    frames fall through to the batch lane's routable moved error."""

    async def body():
        from distributedratelimiting.redis_tpu.runtime.native_frontend \
            import Tier0Config

        backing = InProcessBucketStore()
        async with BucketStoreServer(
                backing, native_frontend=True,
                native_tier0=Tier0Config(min_budget=8.0,
                                         sync_interval_s=0.02,
                                         max_stale_s=10.0)) as srv:
            c = RemoteBucketStore(address=(srv.host, srv.port),
                                  coalesce_requests=False)
            try:
                for _ in range(200):
                    r = await c.acquire("hot", 1, 1000.0, 1e-9)
                    assert r.granted
                await asyncio.sleep(0.08)  # instals + a few syncs
                st = await c.stats()
                assert st["tier0"]["installs"] >= 1
                await c.config_announce({"prepare": {
                    "kind": "bucket", "old": [1000.0, 1e-9],
                    "new": [500.0, 1e-9]}, "version": 1})
                await c.config_announce({"commit": 1})
                # immediately after the commit, stale frames may still
                # be served from the C replica's last-acked headroom —
                # the documented one-sync-interval epsilon
                for _ in range(100):
                    r = await c.acquire("hot", 1, 1000.0, 1e-9)
                    assert r.granted
                await asyncio.sleep(0.1)  # pump retires the replicas
                # now a stale frame falls through to the batch lane and
                # chases the routable moved error exactly once
                r = await c.acquire("hot", 1, 1000.0, 1e-9)
                assert r.granted
                st = await c.stats()
                assert st["config"]["moved_errors"] >= 1
                assert st["tier0"]["retired_config_rows"] >= 1
                # every spent permit is accounted on the NEW table: the
                # authoritative balance reflects all ~301 grants, not
                # just the post-mutation ones (500 − spent, saturating)
                tokens, _ = backing._buckets[("hot", 500.0, 1e-9)]
                assert tokens == pytest.approx(500.0 - 301.0, abs=16.0)
            finally:
                await c.aclose()

    run(body())
