"""Remote store tests: the client-server star topology over localhost TCP.

This is the reference's deployment shape — N limiter instances sharing one
store over the network (SURVEY.md §5.8) — and the test style its TestApp
gestured at with Orleans localhost clustering (§4): multiple clients, one
shared server, per-test free ports."""

import asyncio

import numpy as np
import pytest

from distributedratelimiting.redis_tpu.models.approximate import (
    ApproximateTokenBucketRateLimiter,
)
from distributedratelimiting.redis_tpu.models.options import (
    ApproximateTokenBucketOptions,
    TokenBucketOptions,
)
from distributedratelimiting.redis_tpu.models.token_bucket import (
    TokenBucketRateLimiter,
)
from distributedratelimiting.redis_tpu.runtime import wire
from distributedratelimiting.redis_tpu.runtime.clock import ManualClock
from distributedratelimiting.redis_tpu.runtime.remote import RemoteBucketStore
from distributedratelimiting.redis_tpu.runtime.server import BucketStoreServer
from distributedratelimiting.redis_tpu.runtime.store import InProcessBucketStore


def run(coro):
    return asyncio.run(coro)


class TestWireProtocol:
    def test_request_roundtrip(self):
        frame = wire.encode_request(7, wire.OP_ACQUIRE, "user:42", 3, 100.0, 5.0)
        seq, op, key, count, a, b = wire.decode_request(frame[4:])
        assert (seq, op, key, count, a, b) == (7, wire.OP_ACQUIRE, "user:42",
                                               3, 100.0, 5.0)

    def test_sync_request_roundtrip(self):
        frame = wire.encode_request(9, wire.OP_SYNC, "bucket", 0, 12.5, 1.0)
        seq, op, key, count, a, b = wire.decode_request(frame[4:])
        assert (seq, op, key, a, b) == (9, wire.OP_SYNC, "bucket", 12.5, 1.0)

    def test_response_roundtrips(self):
        for kind, vals in [
            (wire.RESP_DECISION, (True, 4.5)),
            (wire.RESP_VALUE, (3.25,)),
            (wire.RESP_PAIR, (1.5, 2.5)),
            (wire.RESP_EMPTY, ()),
            (wire.RESP_ERROR, ("boom",)),
        ]:
            seq, k, out = wire.decode_response(
                wire.encode_response(11, kind, *vals)[4:])
            assert (seq, k, out) == (11, kind, vals)

    def test_unicode_key(self):
        frame = wire.encode_request(1, wire.OP_PEEK, "ключ-🔑", 0, 1.0, 1.0)
        _, _, key, _, _, _ = wire.decode_request(frame[4:])
        assert key == "ключ-🔑"

    def test_bad_frame_length_rejected(self):
        async def main():
            reader = asyncio.StreamReader()
            reader.feed_data((wire.MAX_FRAME + 1).to_bytes(4, "little"))
            with pytest.raises(wire.RemoteStoreError):
                await wire.read_frame(reader)

        run(main())

    def test_version_mismatch_detected_not_misparsed(self):
        frame = wire.encode_request(3, wire.OP_PING)
        body = bytearray(frame[4:])
        body[0] = wire.PROTOCOL_VERSION + 1  # a future revision
        with pytest.raises(wire.ProtocolVersionError, match="version mismatch"):
            wire.decode_request(bytes(body))
        resp = wire.encode_response(3, wire.RESP_EMPTY)
        rbody = bytearray(resp[4:])
        rbody[0] = 1  # the v1 layout had no version byte at all
        with pytest.raises(wire.ProtocolVersionError):
            wire.decode_response(bytes(rbody))

    def test_large_stats_text_not_truncated(self):
        # > u16 bound: the v1 encoder would have truncated this mid-payload.
        text = '{"x": "' + "й" * 50_000 + '"}'
        seq, kind, (out,) = wire.decode_response(
            wire.encode_response(5, wire.RESP_TEXT, text)[4:])
        assert out == text

    def test_text_beyond_max_frame_is_loud(self):
        with pytest.raises(ValueError, match="MAX_FRAME"):
            wire.encode_response(5, wire.RESP_TEXT, "x" * (wire.MAX_FRAME + 8))

    def test_error_truncates_on_codepoint_boundary(self):
        msg = "е" * 40_000  # 2 bytes each -> 80_000 bytes > u16 bound
        seq, kind, (out,) = wire.decode_response(
            wire.encode_response(5, wire.RESP_ERROR, msg)[4:])
        assert out == "е" * 32_767  # 0xFFFF // 2, cleanly decodable

    def test_hello_roundtrip(self):
        frame = wire.encode_request(2, wire.OP_HELLO, "s3cret")
        seq, op, token, _, _, _ = wire.decode_request(frame[4:])
        assert (seq, op, token) == (2, wire.OP_HELLO, "s3cret")


class TestClientServer:
    def test_acquire_over_tcp(self):
        async def main():
            clock = ManualClock()
            async with BucketStoreServer(InProcessBucketStore(clock=clock)) as srv:
                store = RemoteBucketStore(address=(srv.host, srv.port))
                try:
                    # Fresh bucket grants up to capacity, then declines.
                    results = [await store.acquire("k", 1, 5.0, 1.0)
                               for _ in range(7)]
                    assert [r.granted for r in results] == [True] * 5 + [False] * 2
                    # Server-side refill (server clock is the authority).
                    clock.advance_seconds(2.0)
                    assert (await store.acquire("k", 2, 5.0, 1.0)).granted
                finally:
                    await store.aclose()

        run(main())

    def test_blocking_paths_from_sync_context(self):
        async def setup():
            srv = BucketStoreServer(InProcessBucketStore())
            await srv.start()
            return srv

        # Server must live on a real loop; run it on a background thread.
        import threading

        loop = asyncio.new_event_loop()
        t = threading.Thread(target=loop.run_forever, daemon=True)
        t.start()
        srv = asyncio.run_coroutine_threadsafe(setup(), loop).result(10)
        store = RemoteBucketStore(url=f"{srv.host}:{srv.port}")
        try:
            res = store.acquire_blocking("k", 3, 10.0, 1.0)
            assert res.granted and res.remaining == 7.0
            assert store.peek_blocking("k", 10.0, 1.0) == 7.0
            sync = store.sync_counter_blocking("g", 4.0, 1.0)
            assert sync.global_score == 4.0
            w = store.window_acquire_blocking("w", 1, 5.0, 1.0)
            assert w.granted
        finally:
            run(store.aclose())
            asyncio.run_coroutine_threadsafe(srv.aclose(), loop).result(10)
            loop.call_soon_threadsafe(loop.stop)
            t.join(timeout=5)

    def test_pipelined_concurrent_requests(self):
        async def main():
            async with BucketStoreServer(InProcessBucketStore()) as srv:
                store = RemoteBucketStore(address=(srv.host, srv.port))
                try:
                    # 64 concurrent acquires multiplexed on one connection.
                    results = await asyncio.gather(
                        *(store.acquire(f"k{i % 8}", 1, 4.0, 1.0)
                          for i in range(64)))
                    granted = sum(r.granted for r in results)
                    assert granted == 8 * 4  # 8 buckets × capacity 4
                finally:
                    await store.aclose()

        run(main())

    def test_connection_factory_precedence(self):
        # The factory seam (≙ ConnectionMultiplexerFactory) wins over a
        # bogus address — proving precedence order.
        async def main():
            async with BucketStoreServer(InProcessBucketStore()) as srv:
                async def factory():
                    return await asyncio.open_connection(srv.host, srv.port)

                store = RemoteBucketStore(
                    connection_factory=factory,
                    address=("256.0.0.1", 1),  # would fail if dialed
                )
                try:
                    assert (await store.acquire("k", 1, 5.0, 1.0)).granted
                finally:
                    await store.aclose()

        run(main())

    def test_requires_some_config(self):
        with pytest.raises(ValueError):
            RemoteBucketStore()

    def test_connect_failure_logged_and_retried(self):
        # Default policy: a failed dial provably sent nothing, so the
        # SAME call retries it (bounded, jittered) and self-heals.
        async def main():
            async with BucketStoreServer(InProcessBucketStore()) as srv:
                attempts = 0

                async def flaky_factory():
                    nonlocal attempts
                    attempts += 1
                    if attempts == 1:
                        raise ConnectionRefusedError("store down")
                    return await asyncio.open_connection(srv.host, srv.port)

                store = RemoteBucketStore(connection_factory=flaky_factory,
                                          reconnect_backoff_base_s=0.01,
                                          resilience_seed=7)
                try:
                    assert (await store.acquire("k", 1, 5.0, 1.0)).granted
                    assert attempts == 2
                    assert store.resilience_stats()["retries"] == 1
                finally:
                    await store.aclose()

        run(main())

    def test_connect_failure_without_retry_policy_surfaces(self):
        # retry_policy=None restores the reference posture exactly: the
        # failure surfaces, the NEXT use retries the connect (lazy
        # recovery, invariant 9).
        async def main():
            async with BucketStoreServer(InProcessBucketStore()) as srv:
                attempts = 0

                async def flaky_factory():
                    nonlocal attempts
                    attempts += 1
                    if attempts == 1:
                        raise ConnectionRefusedError("store down")
                    return await asyncio.open_connection(srv.host, srv.port)

                store = RemoteBucketStore(connection_factory=flaky_factory,
                                          retry_policy=None,
                                          reconnect_backoff_base_s=0.0)
                try:
                    with pytest.raises(ConnectionRefusedError):
                        await store.acquire("k", 1, 5.0, 1.0)
                    assert (await store.acquire("k", 1, 5.0, 1.0)).granted
                    assert attempts == 2
                finally:
                    await store.aclose()

        run(main())

    def test_server_error_relayed_not_fatal(self):
        class ExplodingStore(InProcessBucketStore):
            async def acquire(self, key, *a, **kw):
                if key == "bad":
                    raise RuntimeError("kernel exploded")
                return await super().acquire(key, *a, **kw)

        async def main():
            async with BucketStoreServer(ExplodingStore()) as srv:
                store = RemoteBucketStore(address=(srv.host, srv.port))
                try:
                    with pytest.raises(wire.RemoteStoreError):
                        await store.acquire("bad", 1, 5.0, 1.0)
                    # Connection survives; next request works.
                    assert (await store.acquire("good", 1, 5.0, 1.0)).granted
                finally:
                    await store.aclose()

        run(main())

    def test_snapshot_unsupported_remotely(self):
        store = RemoteBucketStore(url="localhost:1")
        with pytest.raises(NotImplementedError):
            store.snapshot()

    def test_stats_report_serving_latency(self):
        # Server-side request-arrival → result-ready histogram: the
        # framework-accountable latency (north star p99 < 2ms), measured
        # where the RTT of the client's link cannot pollute it.
        async def main():
            async with BucketStoreServer(InProcessBucketStore()) as srv:
                store = RemoteBucketStore(address=(srv.host, srv.port))
                try:
                    for _ in range(20):
                        await store.acquire("k", 1, 100.0, 1.0)
                    stats = await store.stats()
                    assert stats["serving_samples"] == 20
                    assert stats["serving_p99_ms"] > 0
                    assert (stats["serving_p50_ms"]
                            <= stats["serving_p99_ms"])
                finally:
                    await store.aclose()

        run(main())

    def test_server_close_with_connected_client_does_not_hang(self):
        # Python 3.12's Server.wait_closed() waits for connection handler
        # tasks; aclose must cancel them first or shutdown deadlocks
        # whenever a client is still attached (found driving the cluster
        # demo: killing one node of a live cluster hung forever).
        async def main():
            srv = BucketStoreServer(InProcessBucketStore())
            await srv.start()
            store = RemoteBucketStore(address=(srv.host, srv.port))
            try:
                assert (await store.acquire("k", 1, 5.0, 1.0)).granted
                await asyncio.wait_for(srv.aclose(), timeout=5.0)
            finally:
                await store.aclose()

        run(main())


class TestWireFuzz:
    def test_garbage_frames_never_kill_the_server(self):
        # Adversarial/corrupt peers: random frame bodies (valid length
        # prefix, arbitrary bytes — including truncated ops, huge counts,
        # bad UTF-8, random bulk flags). The server may error-reply or
        # drop the connection, but must neither crash nor stop serving
        # well-formed clients.
        import random

        async def main():
            rng = random.Random(0xFA22)
            async with BucketStoreServer(InProcessBucketStore()) as srv:
                for round_no in range(40):
                    reader, writer = await asyncio.open_connection(
                        srv.host, srv.port)
                    try:
                        for _ in range(rng.randint(1, 4)):
                            body = bytes(rng.randrange(256) for _ in range(
                                rng.choice((0, 1, 5, 6, 23, 64, 300))))
                            writer.write(
                                len(body).to_bytes(4, "little") + body)
                        await writer.drain()
                        # Read whatever comes back until the server drops
                        # us or stops replying; content is unconstrained.
                        try:
                            await asyncio.wait_for(reader.read(4096), 0.2)
                        except asyncio.TimeoutError:
                            pass
                    finally:
                        writer.close()
                        try:
                            await writer.wait_closed()
                        except (ConnectionResetError, BrokenPipeError):
                            pass
                # The server must still serve a well-formed client.
                store = RemoteBucketStore(address=(srv.host, srv.port))
                try:
                    assert (await store.acquire("ok", 1, 5.0, 1.0)).granted
                finally:
                    await store.aclose()

        run(main())


class TestAuthAndVersion:
    def test_auth_required_server_rejects_tokenless_client(self):
        async def main():
            async with BucketStoreServer(InProcessBucketStore(),
                                         auth_token="hunter2") as srv:
                store = RemoteBucketStore(address=(srv.host, srv.port))
                with pytest.raises(wire.RemoteStoreError,
                                   match="authentication required"):
                    await store.acquire("k", 1, 5.0, 1.0)
                await store.aclose()

        run(main())

    def test_wrong_token_fails_connect(self):
        async def main():
            async with BucketStoreServer(InProcessBucketStore(),
                                         auth_token="hunter2") as srv:
                store = RemoteBucketStore(address=(srv.host, srv.port),
                                          auth_token="wrong")
                with pytest.raises(wire.RemoteStoreError,
                                   match="authentication failed"):
                    await store.acquire("k", 1, 5.0, 1.0)
                await store.aclose()

        run(main())

    def test_right_token_works_and_reconnects(self):
        async def main():
            async with BucketStoreServer(InProcessBucketStore(),
                                         auth_token="hunter2") as srv:
                store = RemoteBucketStore(address=(srv.host, srv.port),
                                          auth_token="hunter2")
                assert (await store.acquire("k", 1, 5.0, 1.0)).granted
                # Hello is per-connection: force a reconnect and keep going.
                await store._await_on_io(_drop(store))
                assert (await store.acquire("k", 1, 5.0, 1.0)).granted
                await store.aclose()

        run(main())

    def test_hello_optional_when_server_has_no_token(self):
        async def main():
            async with BucketStoreServer(InProcessBucketStore()) as srv:
                store = RemoteBucketStore(address=(srv.host, srv.port),
                                          auth_token="anything")
                assert (await store.acquire("k", 1, 5.0, 1.0)).granted
                await store.aclose()

        run(main())

    def test_server_rejects_mismatched_version_frame(self):
        async def main():
            async with BucketStoreServer(InProcessBucketStore()) as srv:
                reader, writer = await asyncio.open_connection(srv.host,
                                                               srv.port)
                good = wire.encode_request(9, wire.OP_PING)
                bad = good[:4] + bytes([wire.PROTOCOL_VERSION + 1]) + good[5:]
                writer.write(bad)
                await writer.drain()
                body = await wire.read_frame(reader)
                seq, kind, vals = wire.decode_response(body)
                assert kind == wire.RESP_ERROR
                assert "version mismatch" in vals[0]
                # The connection is then dropped, not left misparsing.
                assert await wire.read_frame(reader) is None
                writer.close()

        run(main())


async def _drop(store):
    store._drop_connection(ConnectionError("test-forced reconnect"))


class TestBulkWire:
    def test_bulk_request_roundtrip(self):
        keys = ["user:1", "ключ-🔑", "", "z" * 100]
        blobs = [k.encode() for k in keys]
        counts = np.asarray([1, 2, 0, 7], np.uint32)
        frame = wire.encode_bulk_request(5, blobs, counts, 100.0, 2.5,
                                         with_remaining=True)
        seq, out_keys, out_counts, cap, rate, with_rem, kind = (
            wire.decode_bulk_request(frame[4:]))
        assert (seq, out_keys, cap, rate, with_rem, kind) == (
            5, keys, 100.0, 2.5, True, wire.BULK_KIND_BUCKET)
        assert out_counts.tolist() == [1, 2, 0, 7]
        # Window-kind frames carry (limit, window_s) in the same slots.
        wframe = wire.encode_bulk_request(
            6, blobs[:1], counts[:1], 50.0, 2.0, with_remaining=False,
            kind=wire.BULK_KIND_FWINDOW)
        seq, _, _, a, b, with_rem, kind = wire.decode_bulk_request(wframe[4:])
        assert (seq, a, b, with_rem, kind) == (
            6, 50.0, 2.0, False, wire.BULK_KIND_FWINDOW)

    def test_bulk_response_roundtrip(self):
        granted = np.asarray([True, False, True, True, False], bool)
        remaining = np.asarray([4.0, 0.0, 2.5, 1.0, 0.0], np.float32)
        seq, kind, (g, r) = wire.decode_response(
            wire.encode_bulk_response(9, granted, remaining)[4:])
        assert kind == wire.RESP_BULK
        assert g.tolist() == granted.tolist()
        assert r.tolist() == remaining.tolist()
        # Verdict-only variant: 1 bit per decision, no remaining payload.
        seq, kind, (g, r) = wire.decode_response(
            wire.encode_bulk_response(9, granted, None)[4:])
        assert g.tolist() == granted.tolist() and r is None

    def test_chunk_spans_cover_and_fit(self):
        rng = np.random.default_rng(0)
        lens = rng.integers(1, 60, 5000)
        budget = 4096
        spans = wire.bulk_chunk_spans(lens, budget)
        assert spans[0][0] == 0 and spans[-1][1] == len(lens)
        for (s0, e0), (s1, e1) in zip(spans, spans[1:]):
            assert e0 == s1  # contiguous, no gaps or overlaps
        for s, e in spans:
            assert (lens[s:e] + wire.BULK_PER_KEY_OVERHEAD).sum() <= budget

    def test_unknown_bulk_kind_rejected_both_ends(self):
        with pytest.raises(ValueError, match="unknown bulk kind"):
            wire.encode_bulk_request(1, [b"k"], np.ones(1, np.uint32),
                                     1.0, 1.0, kind=4)
        # Kind 3 (BULK_KIND_HBUCKET since ISSUE 10) decodes — but a
        # frame claiming it WITHOUT the tenant extension is a protocol
        # error when the server reads the extension, not silently
        # served as some other table family.
        good = wire.encode_bulk_request(1, [b"k"], np.ones(1, np.uint32),
                                        1.0, 1.0)
        body = bytearray(good[4:])
        body[6] |= 0b110  # force kind bits to HBUCKET (3)
        *_rest, kind = wire.decode_bulk_request(bytes(body))
        assert kind == wire.BULK_KIND_HBUCKET
        with pytest.raises(wire.RemoteStoreError,
                           match="tenant extension"):
            wire.bulk_hier_tail(bytes(body))

    def test_oversized_unchunked_frame_is_loud(self):
        blobs = [b"k" * 60_000] * 20  # ~1.2MB in one frame
        with pytest.raises(ValueError, match="MAX_FRAME"):
            wire.encode_bulk_request(1, blobs, np.ones(20, np.uint32),
                                     1.0, 1.0)


class TestBulkClientServer:
    def test_bulk_acquire_over_tcp(self):
        async def main():
            clock = ManualClock()
            async with BucketStoreServer(InProcessBucketStore(clock=clock)) as srv:
                store = RemoteBucketStore(address=(srv.host, srv.port))
                try:
                    keys = [f"k{i % 4}" for i in range(12)]
                    res = await store.acquire_many(
                        keys, [1] * 12, 2.0, 1.0)
                    # 4 buckets × capacity 2: first two requests per key
                    # grant, the third declines (request order preserved).
                    assert res.granted.tolist() == [True] * 8 + [False] * 4
                    assert res.remaining is not None
                    assert res.remaining[:4].tolist() == [1.0] * 4
                    # Verdict-only round trip.
                    res2 = await store.acquire_many(
                        keys, [1] * 12, 2.0, 1.0, with_remaining=False)
                    assert res2.remaining is None
                    assert not res2.granted.any()
                finally:
                    await store.aclose()

        run(main())

    def test_bulk_blocking_from_sync_context(self):
        import threading

        async def setup():
            srv = BucketStoreServer(InProcessBucketStore())
            await srv.start()
            return srv

        loop = asyncio.new_event_loop()
        t = threading.Thread(target=loop.run_forever, daemon=True)
        t.start()
        srv = asyncio.run_coroutine_threadsafe(setup(), loop).result(10)
        store = RemoteBucketStore(url=f"{srv.host}:{srv.port}")
        try:
            res = store.acquire_many_blocking(
                ["a", "b"], [3, 11], 10.0, 1.0)
            assert res.granted.tolist() == [True, False]
            assert res.remaining.tolist() == [7.0, 10.0]
        finally:
            run(store.aclose())
            asyncio.run_coroutine_threadsafe(srv.aclose(), loop).result(10)
            loop.call_soon_threadsafe(loop.stop)
            t.join(timeout=5)

    def test_bulk_chunked_across_frames(self, monkeypatch):
        # Force tiny chunks so one call spans many frames; results must
        # reassemble in request order across frame boundaries.
        import distributedratelimiting.redis_tpu.runtime.wire as wire_mod

        monkeypatch.setattr(wire_mod, "BULK_CHUNK_BUDGET", 256)

        async def main():
            async with BucketStoreServer(InProcessBucketStore()) as srv:
                store = RemoteBucketStore(address=(srv.host, srv.port))
                try:
                    n = 200
                    keys = [f"key-{i:04d}" for i in range(n)]
                    res = await store.acquire_many(
                        keys, [1] * n, 1.0, 1.0)
                    assert len(res) == n
                    assert res.granted.all()  # n distinct keys, capacity 1
                    res2 = await store.acquire_many(
                        keys, [1] * n, 1.0, 1.0)
                    assert not res2.granted.any()
                finally:
                    await store.aclose()

        run(main())

    def test_bulk_cross_chunk_duplicates_decide_in_order(self, monkeypatch):
        # Chunks of one bulk call are separate frames; the server chains
        # them per connection so a duplicate key spanning a chunk boundary
        # keeps request-order semantics (the grant lands on the EARLIER
        # occurrence). A slow store amplifies any ordering race.
        import distributedratelimiting.redis_tpu.runtime.wire as wire_mod

        monkeypatch.setattr(wire_mod, "BULK_CHUNK_BUDGET", 64)

        class SlowFirstStore(InProcessBucketStore):
            calls = 0

            async def acquire_many(self, keys, *a, **kw):
                SlowFirstStore.calls += 1
                if SlowFirstStore.calls == 1:
                    await asyncio.sleep(0.05)  # chunk 2 would overtake
                return await super().acquire_many(keys, *a, **kw)

        async def main():
            async with BucketStoreServer(SlowFirstStore()) as srv:
                store = RemoteBucketStore(address=(srv.host, srv.port))
                try:
                    # "dup" appears once per chunk (budget 64 → ~4/chunk);
                    # bucket holds 1 token → exactly the FIRST wins.
                    keys = ["dup", "aaa1", "bbb1", "ccc1",
                            "dup", "aaa2", "bbb2", "ccc2"]
                    res = await store.acquire_many(
                        keys, [1] * 8, 1.0, 0.0)
                    assert res.granted.tolist() == [
                        True, True, True, True,
                        False, True, True, True]
                finally:
                    await store.aclose()

        run(main())

    def test_bulk_empty_call_never_touches_wire(self):
        async def main():
            store = RemoteBucketStore(address=("256.0.0.1", 1))
            try:
                res = await store.acquire_many([], [], 1.0, 1.0)
                assert len(res) == 0 and res.remaining is not None
            finally:
                await store.aclose()

        run(main())

    def test_bulk_server_error_relayed(self):
        class ExplodingStore(InProcessBucketStore):
            async def acquire_many(self, keys, *a, **kw):
                if "bad" in keys:
                    raise RuntimeError("bulk kernel exploded")
                return await super().acquire_many(keys, *a, **kw)

        async def main():
            async with BucketStoreServer(ExplodingStore()) as srv:
                store = RemoteBucketStore(address=(srv.host, srv.port))
                try:
                    with pytest.raises(wire.RemoteStoreError,
                                       match="bulk kernel exploded"):
                        await store.acquire_many(["bad"], [1], 5.0, 1.0)
                    # Connection survives; later traffic (which also rides
                    # bulk frames — client coalescing is on by default)
                    # still works.
                    assert (await store.acquire("good", 1, 5.0, 1.0)).granted
                finally:
                    await store.aclose()

        run(main())

    def test_bulk_mid_call_disconnect_fails_cleanly(self):
        # A server that reads one frame then drops the connection: the
        # bulk call's futures must fail with ConnectionError, and a retry
        # against a healthy server must succeed (lazy reconnect).
        async def main():
            async def rude_server(reader, writer):
                await wire.read_frame(reader)
                writer.close()

            srv = await asyncio.start_server(rude_server, "127.0.0.1", 0)
            host, port = srv.sockets[0].getsockname()[:2]
            store = RemoteBucketStore(address=(host, port))
            try:
                with pytest.raises(ConnectionError):
                    await store.acquire_many(["a", "b"], [1, 1], 5.0, 1.0)
            finally:
                await store.aclose()
                srv.close()
                await srv.wait_closed()

        run(main())

    def test_bulk_with_auth(self):
        async def main():
            async with BucketStoreServer(InProcessBucketStore(),
                                         auth_token="hunter2") as srv:
                # Tokenless client: bulk is rejected like any other op.
                bad = RemoteBucketStore(address=(srv.host, srv.port))
                with pytest.raises(wire.RemoteStoreError,
                                   match="authentication required"):
                    await bad.acquire_many(["a"], [1], 5.0, 1.0)
                await bad.aclose()
                good = RemoteBucketStore(address=(srv.host, srv.port),
                                         auth_token="hunter2")
                try:
                    res = await good.acquire_many(["a", "b"], [1, 1], 5.0, 1.0)
                    assert res.granted.all()
                finally:
                    await good.aclose()

        run(main())

    def test_client_coalescing_shares_frames(self):
        """Concurrent single acquires on one client must share
        ACQUIRE_MANY frames: the server sees flushes, not requests —
        and decisions still match per-request semantics."""
        async def main():
            async with BucketStoreServer(InProcessBucketStore()) as srv:
                store = RemoteBucketStore(address=(srv.host, srv.port))
                try:
                    results = await asyncio.gather(
                        *(store.acquire(f"k{i % 8}", 1, 4.0, 1.0)
                          for i in range(64)))
                    assert sum(r.granted for r in results) == 8 * 4
                    assert srv.requests_served < 32  # frames ≪ requests
                finally:
                    await store.aclose()

                off = RemoteBucketStore(address=(srv.host, srv.port),
                                        coalesce_requests=False)
                try:
                    before = srv.requests_served
                    await asyncio.gather(
                        *(off.acquire(f"o{i}", 1, 4.0, 1.0)
                          for i in range(16)))
                    assert srv.requests_served - before == 16  # per-request
                finally:
                    await off.aclose()

        run(main())

    def test_window_bulk_over_tcp(self):
        async def main():
            clock = ManualClock()
            async with BucketStoreServer(InProcessBucketStore(clock=clock)) as srv:
                store = RemoteBucketStore(address=(srv.host, srv.port))
                try:
                    keys = [f"w{i % 3}" for i in range(9)]
                    res = await store.window_acquire_many(
                        keys, [1] * 9, 2.0, 1.0)
                    # 3 window keys × limit 2: first two per key grant.
                    assert res.granted.tolist() == [True] * 6 + [False] * 3
                    clock.advance_seconds(2.5)  # windows roll fully
                    res2 = await store.window_acquire_many(
                        ["w0"], [2], 2.0, 1.0, fixed=True)
                    assert res2.granted.all()
                finally:
                    await store.aclose()

        run(main())

    def test_window_bulk_against_device_store(self):
        from distributedratelimiting.redis_tpu.runtime.store import (
            DeviceBucketStore,
        )

        async def main():
            async with BucketStoreServer(DeviceBucketStore(n_slots=256)) as srv:
                store = RemoteBucketStore(address=(srv.host, srv.port))
                try:
                    n = 120
                    keys = [f"wk{i}" for i in range(n)]
                    res = await store.window_acquire_many(
                        keys, [2] * n, 5.0, 1.0)
                    assert res.granted.all()
                    assert np.allclose(res.remaining, 3.0)
                    # Fixed-window kind hits its own table family.
                    res2 = await store.window_acquire_many(
                        keys, [5] * n, 5.0, 1.0, fixed=True,
                        with_remaining=False)
                    assert res2.granted.all() and res2.remaining is None
                finally:
                    await store.aclose()

        run(main())

    def test_bulk_against_device_store(self):
        # The real deployment shape: RemoteBucketStore -> TCP ->
        # DeviceBucketStore's scanned bulk path.
        from distributedratelimiting.redis_tpu.runtime.store import (
            DeviceBucketStore,
        )

        async def main():
            async with BucketStoreServer(DeviceBucketStore(n_slots=1024)) as srv:
                store = RemoteBucketStore(address=(srv.host, srv.port))
                try:
                    n = 300
                    keys = [f"dk{i}" for i in range(n)]
                    res = await store.acquire_many(keys, [1] * n, 10.0, 1.0)
                    assert res.granted.all()
                    assert np.allclose(res.remaining, 9.0)
                finally:
                    await store.aclose()

        run(main())


class TestDistributedLimiters:
    def test_exact_limiters_share_bucket_across_clients(self):
        async def main():
            clock = ManualClock()
            async with BucketStoreServer(InProcessBucketStore(clock=clock)) as srv:
                a = RemoteBucketStore(address=(srv.host, srv.port))
                b = RemoteBucketStore(address=(srv.host, srv.port))
                lim_a = TokenBucketRateLimiter(
                    TokenBucketOptions(token_limit=6, instance_name="shared"), a)
                lim_b = TokenBucketRateLimiter(
                    TokenBucketOptions(token_limit=6, instance_name="shared"), b)
                try:
                    ga = sum(l.is_acquired for l in await asyncio.gather(
                        *(lim_a.acquire_async(1) for _ in range(6))))
                    gb = sum(l.is_acquired for l in await asyncio.gather(
                        *(lim_b.acquire_async(1) for _ in range(6))))
                    assert ga + gb == 6  # one shared bucket, not two
                finally:
                    await a.aclose()
                    await b.aclose()

        run(main())

    def test_approximate_convergence_across_clients(self):
        # Two approximate limiters on separate TCP clients converge to the
        # shared global counter: after syncs, each sees the other's load.
        async def main():
            clock = ManualClock()
            async with BucketStoreServer(InProcessBucketStore(clock=clock)) as srv:
                stores = [RemoteBucketStore(address=(srv.host, srv.port))
                          for _ in range(2)]
                lims = [ApproximateTokenBucketRateLimiter(
                    ApproximateTokenBucketOptions(
                        token_limit=100, tokens_per_period=10,
                        instance_name="global"), s) for s in stores]
                try:
                    for lim in lims:
                        for _ in range(30):
                            lim._try_lease(1)  # consume locally
                    for lim in lims:
                        await lim.refresh()
                    # Global counter saw 60 consumed permits.
                    assert sum(l._global_score for l in lims) >= 60
                    for lim in lims:
                        assert lim.available_tokens < 100 - 30
                finally:
                    for lim in lims:
                        await lim.aclose()
                    for s in stores:
                        await s.aclose()

        run(main())


def test_post_close_use_fails_fast_without_thread_leak():
    import threading

    async def main():
        async with BucketStoreServer(InProcessBucketStore()) as srv:
            store = RemoteBucketStore(address=(srv.host, srv.port))
            assert (await store.acquire("k", 1, 5.0, 1.0)).granted
            await store.aclose()
            before = threading.active_count()
            with pytest.raises(ConnectionError):
                await store.acquire("k", 1, 5.0, 1.0)
            assert threading.active_count() == before  # no resurrected loop

    run(main())


def test_bulk_frame_with_invalid_utf8_key_serves_by_byte_identity():
    """Bulk keys are byte strings end-to-end on the serving path: an
    invalid-UTF-8 key rate-limits under its own stable identity instead
    of erroring the whole frame (matching the native front-end's
    per-request lane)."""
    import numpy as np

    async def main():
        async with BucketStoreServer(InProcessBucketStore()) as srv:
            reader, writer = await asyncio.open_connection(srv.host,
                                                           srv.port)
            bad = b"\xff\x80key"
            frame = wire.encode_bulk_request(
                9, [bad, bad, b"ok"], np.array([1, 1, 1]), 1.0, 1e-9,
                with_remaining=False)
            writer.write(frame)
            await writer.drain()
            resp = await asyncio.wait_for(wire.read_frame(reader), 10)
            seq, kind, (granted, _) = wire.decode_response(resp)
            assert seq == 9 and kind == wire.RESP_BULK
            # Capacity 1: the duplicate bad key grants once, not twice —
            # both rows resolved to ONE stable identity.
            assert granted.tolist() == [True, False, True]
            writer.close()

    run(main())


def test_byte_identity_key_round_trips_scalar_ops_too():
    """A byte-identity key admitted via the bulk lane must also serve
    through scalar ops on the same server (surrogateescape end-to-end,
    not bulk-only)."""
    async def main():
        async with BucketStoreServer(InProcessBucketStore()) as srv:
            store = RemoteBucketStore(address=(srv.host, srv.port),
                                      coalesce_requests=False)
            try:
                key = b"\xff\x80weird".decode("utf-8", "surrogateescape")
                r = await store.acquire(key, 2, 5.0, 1e-9)
                assert r.granted and r.remaining == 3.0
                avail = await asyncio.to_thread(store.peek_blocking,
                                                key, 5.0, 1e-9)
                assert avail == 3.0  # same identity as the acquire
            finally:
                await store.aclose()

    run(main())
