"""Goodput under overload (ISSUE 20): THE seeded retry-storm soak plus
the controller's retry-storm rung unit surface.

The soak (benchmarks/storm_goodput.py) replays one seeded storm
schedule — client timeout below loaded server latency, multiplicative
backoff — through three arms over the real wire. Acceptance, per
docs/DESIGN.md §24:

- defended goodput (interactive first-attempt grants settled before
  deadline) ≥ 80% of the no-storm baseline; the naive arm < 50%;
- retries and scavenger shed BEFORE any viable interactive first
  attempt (the doomed cohort is unservable by construction and is
  scored separately);
- budget-aware route-to-pool redirects land over-budget interactive
  work in the overflow pool — and only when the defense arms it;
- same seed ⇒ bit-for-bit identical grant/shed/route schedule;
- the differential audit over the stores' own bucket records shows
  zero over-admission: cap − balance == held + settled − debt, exact.

``make storm-soak SEED=…`` (DRL_STORM_SEED) replays any schedule.
"""

from __future__ import annotations

import asyncio
import os
import types

import pytest

from benchmarks import storm_goodput
from distributedratelimiting.redis_tpu.runtime.admission import (
    PRIORITY_INTERACTIVE,
)
from distributedratelimiting.redis_tpu.runtime.controller import (
    Controller,
    ControllerConfig,
)
from distributedratelimiting.redis_tpu.utils import faults

SEED = int(os.environ.get("DRL_STORM_SEED", "20260807"))


def run(coro):
    return asyncio.run(coro)


@pytest.fixture(scope="module")
def soak():
    return run(storm_goodput.run_soak(SEED))


# -- the storm schedule itself (utils/faults.py satellite) -------------------

def test_storm_schedule_seeded_and_decaying():
    """Same seed ⇒ identical event list; a rid's attempts decay its
    remaining deadline monotonically and never exceed the retry cap."""
    a = faults.storm_schedule(SEED)
    b = faults.storm_schedule(SEED)
    assert a == b
    assert a != faults.storm_schedule(SEED + 1)
    by_rid: dict[str, list] = {}
    for e in a:
        by_rid.setdefault(e.rid, []).append(e)
    for events in by_rid.values():
        events.sort(key=lambda e: e.attempt)
        assert [e.attempt for e in events] == list(range(len(events)))
        assert len(events) <= 4  # max_retries=3 → at most 4 attempts
        deadlines = [e.deadline_s for e in events]
        assert deadlines == sorted(deadlines, reverse=True)
        assert all(d > 0.0 for d in deadlines)


# -- THE soak ----------------------------------------------------------------

def test_storm_defended_holds_goodput_naive_collapses(soak):
    """The acceptance ratios: defense holds ≥ 80% of the no-storm
    baseline while the undefended arm collapses below 50%."""
    assert soak["baseline"]["goodput"] > 0
    assert soak["defended_ratio"] >= 0.8, soak
    assert soak["naive_ratio"] < 0.5, soak


def test_storm_sheds_retries_and_scavenger_never_viable_interactive(soak):
    """Shed ordering: the defended arm sheds retries (server gate),
    scavenger (edge ladder), and doomed work — and not one VIABLE
    interactive first attempt is denied or shed."""
    d = soak["defended"]
    assert d["counts"]["retry_shed"] > 0
    assert d["counts"]["edge_shed"] > 0
    assert d["counts"]["doomed"] > 0
    assert d["server"]["retries_shed"] == d["counts"]["retry_shed"]
    assert d["server"]["requests_doomed"] == d["counts"]["doomed"]
    events, doomed = storm_goodput._schedule(SEED, storm=True)
    scored = {e.rid for e in events
              if e.attempt == 0 and e.tenant != "tenant:storm"
              and e.priority == PRIORITY_INTERACTIVE
              and e.rid not in doomed}
    first_attempt_outcomes = {rid: outcome
                              for rid, attempt, outcome, _ in d["outcomes"]
                              if attempt == 0 and rid in scored}
    assert set(first_attempt_outcomes) == scored
    assert set(first_attempt_outcomes.values()) <= {"granted", "routed"}


def test_storm_routes_over_budget_tail_to_pool(soak):
    """Budget-aware routing: the oversubscribed tenant's interactive
    tail lands in the overflow pool — only when the defense arms it —
    and the pool's bucket shows the charge."""
    assert soak["defended"]["counts"]["routed"] > 0
    assert soak["defended"]["server"]["reserves_routed"] > 0
    assert soak["defended"]["audit"]["pool:overflow"]["charged"] > 0
    assert soak["naive"]["counts"]["routed"] == 0
    assert soak["baseline"]["counts"]["routed"] == 0


def test_storm_differential_audit_no_over_admission(soak):
    """Every arm, every budget: the stores' own records balance —
    cap − balance == held + settled − debt, to the epsilon envelope."""
    for arm in ("baseline", "naive", "defended"):
        for name, row in soak[arm]["audit"].items():
            assert abs(row["over_admitted"]) <= 1e-3, (arm, name, row)
            assert row["debt"] == pytest.approx(0.0, abs=1e-3)


def test_storm_same_seed_bit_for_bit(soak):
    """Same seed ⇒ the identical grant/shed/route schedule, down to
    the per-event outcome and load observation."""
    again = run(storm_goodput.run_arm(SEED, storm=True, defended=True))
    assert again["outcomes"] == soak["defended"]["outcomes"]
    assert again["counts"] == soak["defended"]["counts"]
    assert again["audit"] == soak["defended"]["audit"]


# -- the controller's retry-storm rung ---------------------------------------

class _FakeCluster:
    def __init__(self, feed):
        self.feed = list(feed)
        self.placement = types.SimpleNamespace(overrides={})
        self.flight_recorder = None

    async def stats(self):
        if self.feed:
            return self.feed.pop(0)
        return {"nodes": [], "resilience": {}, "placement": {}}


class _StormTarget:
    """A shed target exposing both storm actuators — the probe order
    (set_retry_shed, then set_doomed_gate) is part of the contract."""

    def __init__(self):
        self.calls: list = []

    def set_shed_level(self, level):
        self.calls.append(("level", level))

    def set_retry_shed(self, on):
        self.calls.append(("retry", bool(on)))

    def set_doomed_gate(self, on):
        self.calls.append(("doomed", bool(on)))


def _storm_feed(storm_ticks, calm_ticks):
    """Anchor + storm_ticks of 75% retry share + calm_ticks of zero
    retries, over a 2-node fleet serving 200 req/s."""
    feed = []
    reqs, attempts = 100, 0.0
    for i in range(1 + storm_ticks + calm_ticks):
        feed.append({
            "nodes": [
                {"requests_served": reqs,
                 "retry": {"attempts_seen": attempts}},
                {"requests_served": reqs},
            ],
            "resilience": {},
            "placement": {"slot_counts": [8, 8], "drained": []},
        })
        reqs += 100
        if i < 1 + storm_ticks:
            attempts += 150.0  # 150 of 200 req/s are retries: 75%
    return feed


def test_retry_storm_rung_arms_and_releases():
    run(_retry_storm_rung_body())


async def _retry_storm_rung_body():
    target = _StormTarget()
    ctrl = Controller(
        _FakeCluster(_storm_feed(4, 5)),
        config=ControllerConfig(tick_s=1.0, cooldown_ticks=1),
        shed_targets=[target])
    acts = []
    for _ in range(10):
        acts.extend(await ctrl.tick())
    kinds = [a["action"] for a in acts]
    assert "retry_shed_on" in kinds and "retry_shed_off" in kinds
    assert kinds.index("retry_shed_on") < kinds.index("retry_shed_off")
    storm_calls = [c for c in target.calls if c[0] in ("retry", "doomed")]
    assert storm_calls == [("retry", True), ("doomed", True),
                          ("retry", False), ("doomed", False)]
    assert ctrl.retry_shed_on is False
    assert ctrl.numeric_stats()["retry_shed_on"] == 0
    assert "retry_ratio" in ctrl.numeric_stats()


def test_retry_storm_rung_needs_absolute_rate_floor():
    """An idle fleet where half the trickle is retries must NOT arm
    the defense: the ratio trips but the absolute rate floor holds."""
    run(_rate_floor_body())


async def _rate_floor_body():
    feed = []
    reqs, attempts = 1, 0.0
    for _ in range(7):
        feed.append({
            "nodes": [{"requests_served": reqs,
                       "retry": {"attempts_seen": attempts}}],
            "resilience": {},
            "placement": {"slot_counts": [8], "drained": []},
        })
        reqs += 1
        attempts += 0.5  # ratio 0.5 ≥ high, but 0.5/s < min_rate 1.0
    ctrl = Controller(_FakeCluster(feed),
                      config=ControllerConfig(tick_s=1.0))
    for _ in range(7):
        await ctrl.tick()
    assert ctrl.retry_shed_on is False
    assert [a for a in ctrl.actions
            if a["action"].startswith("retry_shed")] == []


def test_retry_storm_rung_dry_run_parity():
    """Dry-run decides the identical retry rung stream and actuates
    nothing — the §12 dry-run contract extends to the new rung."""
    run(_dry_run_parity_body())


async def _dry_run_parity_body():
    live_t, dry_t = _StormTarget(), _StormTarget()
    live = Controller(_FakeCluster(_storm_feed(4, 5)),
                      config=ControllerConfig(tick_s=1.0,
                                              cooldown_ticks=1),
                      shed_targets=[live_t])
    dry = Controller(_FakeCluster(_storm_feed(4, 5)),
                     config=ControllerConfig(tick_s=1.0,
                                             cooldown_ticks=1,
                                             dry_run=True),
                     shed_targets=[dry_t])
    live_acts, dry_acts = [], []
    for _ in range(10):
        live_acts.extend(await live.tick())
        dry_acts.extend(await dry.tick())
    assert [a["action"] for a in live_acts] == \
        [a["action"] for a in dry_acts]
    assert [c for c in dry_t.calls if c[0] in ("retry", "doomed")] == []
    assert dry.retry_shed_on == live.retry_shed_on
