"""Wire-protocol encoder parity: the span (zero-copy) and list entry
points must emit byte-identical ACQUIRE_MANY frames."""

from distributedratelimiting.redis_tpu.runtime import wire

def test_span_encoder_matches_list_encoder_bytes():
    """encode_bulk_request_span must emit byte-identical frames to
    encode_bulk_request (one frame-layout definition, two entry points) —
    including non-ascii and byte-identity keys, which exercise the
    client's per-key-encode fallback."""
    import numpy as np

    keys = ["plain", "ünïcodé", b"\xff\x80raw".decode("utf-8",
                                                      "surrogateescape"),
            "", "x" * 300]
    key_blobs = [k.encode("utf-8", "surrogateescape") for k in keys]
    counts = np.array([1, 2, 3, 0, 7], np.uint32)
    klens = np.fromiter((len(b) for b in key_blobs), np.int64)
    offsets = np.zeros(len(keys) + 1, np.int64)
    np.cumsum(klens, out=offsets[1:])
    blob = b"".join(key_blobs)
    for kind in (wire.BULK_KIND_BUCKET, wire.BULK_KIND_WINDOW):
        for chained in (False, True):
            a = wire.encode_bulk_request(
                7, key_blobs, counts, 10.0, 2.0, with_remaining=True,
                kind=kind, chained=chained)
            b2 = wire.encode_bulk_request_span(
                7, blob, offsets, klens, counts, 0, len(keys), 10.0, 2.0,
                with_remaining=True, kind=kind, chained=chained)
            assert a == b2
    # Sub-span equals encoding the slice directly.
    a = wire.encode_bulk_request(3, key_blobs[1:4], counts[1:4], 5.0, 1.0)
    b2 = wire.encode_bulk_request_span(3, blob, offsets, klens, counts,
                                       1, 4, 5.0, 1.0)
    assert a == b2


def test_client_bulk_nonascii_fallback_roundtrip():
    """_bulk_prepare's non-ascii branch: the decoded keys on the server
    side equal the client's inputs (surrogateescape identity)."""
    import numpy as np

    from distributedratelimiting.redis_tpu.runtime.remote import (
        RemoteBucketStore,
    )

    store = RemoteBucketStore(url="localhost:1")  # never connects
    keys = ["aß", "ok", b"\xfe".decode("utf-8", "surrogateescape"), "zz"]
    blob, offsets, klens, counts_np, spans = store._bulk_prepare(
        keys, [1, 2, 3, 4])
    frame = wire.encode_bulk_request_span(
        1, blob, offsets, klens, counts_np, 0, len(keys), 1.0, 1.0)
    # read_frame strips the u32 length prefix before decode.
    seq, dec_keys, dec_counts, *_ = wire.decode_bulk_request(frame[4:])
    assert dec_keys == keys
    assert dec_counts.tolist() == [1, 2, 3, 4]
