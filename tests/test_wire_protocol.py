"""Wire-protocol encoder parity: the span (zero-copy) and list entry
points must emit byte-identical ACQUIRE_MANY frames — plus the trace
tail's round-trip and old-peer compatibility contracts."""

import random
import struct

import pytest

from distributedratelimiting.redis_tpu.runtime import wire
from distributedratelimiting.redis_tpu.utils.tracing import TraceContext

def test_span_encoder_matches_list_encoder_bytes():
    """encode_bulk_request_span must emit byte-identical frames to
    encode_bulk_request (one frame-layout definition, two entry points) —
    including non-ascii and byte-identity keys, which exercise the
    client's per-key-encode fallback."""
    import numpy as np

    keys = ["plain", "ünïcodé", b"\xff\x80raw".decode("utf-8",
                                                      "surrogateescape"),
            "", "x" * 300]
    key_blobs = [k.encode("utf-8", "surrogateescape") for k in keys]
    counts = np.array([1, 2, 3, 0, 7], np.uint32)
    klens = np.fromiter((len(b) for b in key_blobs), np.int64)
    offsets = np.zeros(len(keys) + 1, np.int64)
    np.cumsum(klens, out=offsets[1:])
    blob = b"".join(key_blobs)
    for kind in (wire.BULK_KIND_BUCKET, wire.BULK_KIND_WINDOW):
        for chained in (False, True):
            a = wire.encode_bulk_request(
                7, key_blobs, counts, 10.0, 2.0, with_remaining=True,
                kind=kind, chained=chained)
            b2 = wire.encode_bulk_request_span(
                7, blob, offsets, klens, counts, 0, len(keys), 10.0, 2.0,
                with_remaining=True, kind=kind, chained=chained)
            assert a == b2
    # Sub-span equals encoding the slice directly.
    a = wire.encode_bulk_request(3, key_blobs[1:4], counts[1:4], 5.0, 1.0)
    b2 = wire.encode_bulk_request_span(3, blob, offsets, klens, counts,
                                       1, 4, 5.0, 1.0)
    assert a == b2


# -- trace-context wire round-trips ------------------------------------------

def _random_ctx(rng: random.Random) -> TraceContext:
    return TraceContext(rng.getrandbits(64), rng.getrandbits(64),
                        rng.getrandbits(64), rng.getrandbits(1))


class TestTraceTailScalar:
    def test_fuzz_strip_trace_roundtrip(self):
        """Fuzz: any keyed op with any context — strip_trace recovers
        the context exactly and yields a body byte-identical to the
        frame an untraced client would have sent."""
        rng = random.Random(0xDE7)
        ops = (wire.OP_ACQUIRE, wire.OP_WINDOW, wire.OP_FWINDOW,
               wire.OP_SEMA, wire.OP_PEEK, wire.OP_SYNC)
        for _ in range(200):
            op = rng.choice(ops)
            key = "".join(chr(rng.randrange(32, 0x2FF))
                          for _ in range(rng.randrange(0, 40)))
            count = rng.randrange(-5, 1000)
            a, b = rng.random() * 1e9, rng.random() * 1e3
            seq = rng.getrandbits(32)
            ctx = _random_ctx(rng)
            traced = wire.encode_request(seq, op, key, count, a, b,
                                         trace=ctx)
            bare = wire.encode_request(seq, op, key, count, a, b)
            assert traced != bare
            plain, got = wire.strip_trace(traced[4:])
            assert got == ctx
            assert plain == bare[4:]
            # untraced bodies pass through strip_trace untouched
            same, none = wire.strip_trace(bare[4:])
            assert none is None and same == bare[4:]

    def test_old_peer_sees_routable_unknown_op(self):
        """An old decoder (today's decode_request IS the old peer's —
        new servers strip first) must answer a traced frame with the
        routable unknown-op error, never a misparse."""
        ctx = TraceContext(1, 2, 3, 1)
        frame = wire.encode_request(9, wire.OP_ACQUIRE, "k", 1, 5.0, 1.0,
                                    trace=ctx)
        with pytest.raises(wire.RemoteStoreError, match="unknown op"):
            wire.decode_request(frame[4:])

    def test_truncated_trace_tail_is_loud(self):
        frame = wire.encode_request(9, wire.OP_PING, trace=TraceContext(
            1, 2, 3, 1))
        body = frame[4:]
        # op byte flagged but tail sliced off: strip_trace must raise
        # the routable error, not misread payload bytes as a context.
        broken = body[:5] + bytes([body[5]])  # header only, no tail
        with pytest.raises(wire.RemoteStoreError):
            wire.strip_trace(broken)


class TestTraceTailBulk:
    def test_fuzz_bulk_tail_roundtrip_and_old_decoder(self):
        """Fuzz: traced ACQUIRE_MANY frames decode IDENTICALLY through
        decode_bulk_request (whose array reads by explicit counts are
        exactly the old peer's parse — the tail is invisible to it),
        while bulk_trace_tail recovers the context."""
        import numpy as np

        rng = random.Random(0xBEEF)
        for _ in range(60):
            n = rng.randrange(1, 30)
            key_blobs = [bytes(rng.randrange(33, 127)
                               for _ in range(rng.randrange(1, 20)))
                         for _ in range(n)]
            counts = np.array([rng.randrange(0, 99) for _ in range(n)],
                              np.uint32)
            kind = rng.choice((wire.BULK_KIND_BUCKET,
                               wire.BULK_KIND_WINDOW,
                               wire.BULK_KIND_FWINDOW))
            chained = rng.random() < 0.5
            with_rem = rng.random() < 0.5
            ctx = _random_ctx(rng)
            traced = wire.encode_bulk_request(
                5, key_blobs, counts, 7.0, 2.0, with_remaining=with_rem,
                kind=kind, chained=chained, trace=ctx)
            bare = wire.encode_bulk_request(
                5, key_blobs, counts, 7.0, 2.0, with_remaining=with_rem,
                kind=kind, chained=chained)
            assert wire.bulk_trace_tail(traced[4:]) == ctx
            assert wire.bulk_trace_tail(bare[4:]) is None
            dec_t = wire.decode_bulk_request(traced[4:])
            dec_b = wire.decode_bulk_request(bare[4:])
            assert dec_t[1] == dec_b[1]                      # keys
            assert (dec_t[2] == dec_b[2]).all()              # counts
            assert dec_t[3:] == dec_b[3:]                    # a/b/flags
            # chained-bit peek is tail-agnostic too
            assert (wire.bulk_request_chained(traced[4:])
                    == wire.bulk_request_chained(bare[4:]) == chained)

    def test_trace_tail_layout_is_the_documented_struct(self):
        """Pin the wire layout: 25 bytes, <QQQB, at the very end."""
        ctx = TraceContext(0x0102030405060708, 0x1112131415161718,
                           0x2122232425262728, 1)
        frame = wire.encode_request(1, wire.OP_ACQUIRE, "k", 1, 1.0, 1.0,
                                    trace=ctx)
        assert wire.TRACE_TAIL_LEN == 25
        hi, lo, span, flags = struct.unpack(
            "<QQQB", frame[-wire.TRACE_TAIL_LEN:])
        assert (hi, lo, span, flags) == tuple(ctx)


def test_client_bulk_nonascii_fallback_roundtrip():
    """_bulk_prepare's non-ascii branch: the decoded keys on the server
    side equal the client's inputs (surrogateescape identity)."""
    import numpy as np

    from distributedratelimiting.redis_tpu.runtime.remote import (
        RemoteBucketStore,
    )

    store = RemoteBucketStore(url="localhost:1")  # never connects
    keys = ["aß", "ok", b"\xfe".decode("utf-8", "surrogateescape"), "zz"]
    blob, offsets, klens, counts_np, spans = store._bulk_prepare(
        keys, [1, 2, 3, 4])
    frame = wire.encode_bulk_request_span(
        1, blob, offsets, klens, counts_np, 0, len(keys), 1.0, 1.0)
    # read_frame strips the u32 length prefix before decode.
    seq, dec_keys, dec_counts, *_ = wire.decode_bulk_request(frame[4:])
    assert dec_keys == keys
    assert dec_counts.tolist() == [1, 2, 3, 4]


# -- tenant extension (OP_ACQUIRE_H / BULK_KIND_HBUCKET, ISSUE 10) ----------

def test_hierarchical_request_roundtrip():
    frame = wire.encode_request(
        7, wire.OP_ACQUIRE_H, "user:42", 812, 4096.0, 64.0,
        hier=("tenant:acme", 1e6, 5e4, 1))
    seq, key, count, a, b, tenant, ta, tb, prio = (
        wire.decode_hierarchical_request(frame[4:]))
    assert (seq, key, count, a, b) == (7, "user:42", 812, 4096.0, 64.0)
    assert (tenant, ta, tb, prio) == ("tenant:acme", 1e6, 5e4, 1)
    # decode_request routes the op to its own decoder, strictly.
    with pytest.raises(wire.RemoteStoreError,
                       match="decode_hierarchical_request"):
        wire.decode_request(frame[4:])
    # The generic encoder refuses a hier-less OP_ACQUIRE_H.
    with pytest.raises(ValueError, match="tenant extension"):
        wire.encode_request(1, wire.OP_ACQUIRE_H, "k", 1, 1.0, 1.0)


def test_hierarchical_tails_compose_with_deadline_and_trace():
    """Tail order contract: payload (incl. tenant extension), deadline,
    trace — the server strips trace then deadline, and the remaining
    body must decode as a plain hierarchical frame."""
    ctx = (1, 2, 3, 1)
    frame = wire.encode_request(
        9, wire.OP_ACQUIRE_H, "k", 5, 10.0, 1.0,
        hier=("t", 30.0, 2.0, 2), deadline_s=0.25, trace=ctx)
    body = frame[4:]
    assert body[5] & wire.TRACE_FLAG and body[5] & wire.DEADLINE_FLAG
    body, tctx = wire.strip_trace(body)
    body, ddl = wire.strip_deadline(body)
    assert tuple(tctx) == ctx and ddl == 0.25
    seq, key, count, a, b, tenant, ta, tb, prio = (
        wire.decode_hierarchical_request(body))
    assert (key, count, tenant, ta, tb, prio) == ("k", 5, "t", 30.0,
                                                  2.0, 2)


def test_hierarchical_truncated_extension_is_routable():
    frame = wire.encode_request(
        3, wire.OP_ACQUIRE_H, "k", 1, 1.0, 1.0, hier=("t", 2.0, 1.0, 0))
    with pytest.raises(wire.RemoteStoreError, match="tenant extension"):
        wire.decode_hierarchical_request(frame[4:-4])


def test_bulk_hier_tail_roundtrip_and_trace_compose():
    keys = [b"a", b"bb", b"ccc"]
    counts = [10, 0, 77]
    trace = (11, 12, 13, 1)
    frame = wire.encode_bulk_request(
        5, keys, counts, 100.0, 1.0, kind=wire.BULK_KIND_HBUCKET,
        hier=("tenant:x", 500.0, 9.0, 1), trace=trace)
    body = frame[4:]
    seq, dec_keys, dec_counts, a, b, with_rem, kind = (
        wire.decode_bulk_request(body))
    assert kind == wire.BULK_KIND_HBUCKET
    assert dec_keys == ["a", "bb", "ccc"]
    assert dec_counts.tolist() == counts
    tenant, ta, tb, prio = wire.bulk_hier_tail(body)
    assert (tenant, ta, tb, prio) == ("tenant:x", 500.0, 9.0, 1)
    # The trace tail still parses from the end, extension untouched.
    tctx = wire.bulk_trace_tail(body)
    assert tuple(tctx) == trace
    # The extension rides exactly the HBUCKET kind.
    with pytest.raises(ValueError, match="HBUCKET"):
        wire.encode_bulk_request(5, keys, counts, 1.0, 1.0,
                                 hier=("t", 1.0, 1.0, 0))
    with pytest.raises(ValueError, match="HBUCKET"):
        wire.encode_bulk_request(5, keys, counts, 1.0, 1.0,
                                 kind=wire.BULK_KIND_HBUCKET)


def test_bulk_hier_tail_truncation_is_routable():
    frame = wire.encode_bulk_request(
        5, [b"k"], [1], 10.0, 1.0, kind=wire.BULK_KIND_HBUCKET,
        hier=("tenant", 50.0, 1.0, 0))
    with pytest.raises(wire.RemoteStoreError, match="tenant extension"):
        wire.bulk_hier_tail(frame[4:-3])


# -- attempt-counter tail (ISSUE 20: retry-storm fingerprinting) -------------

class TestAttemptTailScalar:
    def test_fuzz_strip_attempt_roundtrip(self):
        """Fuzz: any keyed op, any attempt ≥ 1 — strip_attempt recovers
        the (saturated) counter and yields a body byte-identical to the
        frame a first-attempt client would have sent."""
        rng = random.Random(0xA77)
        ops = (wire.OP_ACQUIRE, wire.OP_WINDOW, wire.OP_FWINDOW,
               wire.OP_SEMA, wire.OP_PEEK, wire.OP_SYNC)
        for _ in range(200):
            op = rng.choice(ops)
            key = "k" * rng.randint(1, 40)
            attempt = rng.randint(1, 1000)
            stamped = wire.encode_request(3, op, key, 1, 10.0, 1.0,
                                          attempt=attempt)
            plain = wire.encode_request(3, op, key, 1, 10.0, 1.0)
            body = stamped[4:]
            assert body[5] & wire.ATTEMPT_FLAG
            stripped, got = wire.strip_attempt(body)
            assert got == min(attempt, 255)  # u8, saturating
            assert stripped == plain[4:]

    def test_first_attempt_never_stamps(self):
        """attempt=0 emits a frame byte-identical to plain v4 — first
        attempts never pay the tail and old peers never see it."""
        plain = wire.encode_request(1, wire.OP_ACQUIRE, "k", 1, 2.0, 1.0)
        explicit = wire.encode_request(1, wire.OP_ACQUIRE, "k", 1, 2.0,
                                       1.0, attempt=0)
        assert explicit == plain
        assert not plain[4 + 5] & wire.ATTEMPT_FLAG
        body, attempt = wire.strip_attempt(plain[4:])
        assert attempt == 0 and body == plain[4:]

    def test_truncated_attempt_tail_is_loud(self):
        """A 1-byte tail is only detectably missing on a pathological
        frame cut to the bare head — the flag byte survives but the
        tail byte can't: that must raise, not misread the payload."""
        frame = wire.encode_request(1, wire.OP_ACQUIRE, "k", 1, 2.0,
                                    1.0, attempt=3)
        head_only = frame[4:10]  # 6-byte head, ATTEMPT_FLAG still set
        assert head_only[5] & wire.ATTEMPT_FLAG
        with pytest.raises(wire.RemoteStoreError,
                           match="truncated attempt tail"):
            wire.strip_attempt(head_only)

    def test_attempt_deadline_trace_compose_and_strip_order(self):
        """All three tails on one frame. Wire order is attempt (inner),
        deadline, trace (outer); the server strips trace → deadline →
        attempt and the remainder is byte-identical to the plain frame
        — each latch peels independently, docs/DESIGN.md §24."""
        ctx = (5, 6, 7, 1)
        frame = wire.encode_request(
            9, wire.OP_ACQUIRE_H, "k", 5, 10.0, 1.0,
            hier=("t", 30.0, 2.0, 2), deadline_s=0.25, trace=ctx,
            attempt=2)
        plain = wire.encode_request(
            9, wire.OP_ACQUIRE_H, "k", 5, 10.0, 1.0,
            hier=("t", 30.0, 2.0, 2))
        body = frame[4:]
        assert body[5] & wire.TRACE_FLAG
        assert body[5] & wire.DEADLINE_FLAG
        assert body[5] & wire.ATTEMPT_FLAG
        body, tctx = wire.strip_trace(body)
        body, ddl = wire.strip_deadline(body)
        body, attempt = wire.strip_attempt(body)
        assert tuple(tctx) == ctx and ddl == 0.25 and attempt == 2
        assert body == plain[4:]

    def test_attempt_and_deadline_latch_independently_on_the_wire(self):
        """A frame stamped with only ONE of the two tails strips clean
        — the byte-level ground truth under the client's independent
        old-peer latches (tests/test_chaos.py drives the client side)."""
        only_attempt = wire.encode_request(2, wire.OP_ACQUIRE, "k", 1,
                                           2.0, 1.0, attempt=7)
        body, attempt = wire.strip_attempt(only_attempt[4:])
        assert attempt == 7
        assert not body[5] & wire.DEADLINE_FLAG
        only_deadline = wire.encode_request(2, wire.OP_ACQUIRE, "k", 1,
                                            2.0, 1.0, deadline_s=0.5)
        body, ddl = wire.strip_deadline(only_deadline[4:])
        assert ddl == 0.5
        assert not body[5] & wire.ATTEMPT_FLAG


class TestBulkDeadlineTail:
    def test_bulk_deadline_tail_roundtrip_old_decoder_unaffected(self):
        """The bulk [f64 deadline][u8 attempt] tail parses from the
        end; decode_bulk_request reads arrays by explicit counts and
        decodes the SAME results with or without the tail — no old-peer
        latch on the bulk lane (same posture as traced bulk frames)."""
        keys = [b"a", b"bb", b"ccc"]
        counts = [10, 0, 77]
        plain = wire.encode_bulk_request(5, keys, counts, 100.0, 1.0)
        stamped = wire.encode_bulk_request(5, keys, counts, 100.0, 1.0,
                                           deadline_s=0.125, attempt=3)
        assert wire.bulk_deadline_tail(plain[4:]) is None
        assert wire.bulk_deadline_tail(stamped[4:]) == (0.125, 3)
        p = wire.decode_bulk_request(plain[4:])
        s = wire.decode_bulk_request(stamped[4:])
        assert p[1] == s[1] and p[0] == s[0]
        assert p[2].tolist() == s[2].tolist()
        assert p[3:] == s[3:]

    def test_bulk_deadline_composes_with_hier_and_trace(self):
        """Full stack: tenant extension, deadline+attempt tail, trace
        tail — each parser finds its own tail, none disturbs another,
        across BOTH bulk entry points byte-identically (the asyncio and
        native lanes share one frame-layout definition)."""
        import numpy as np

        keys = [b"a", b"bb"]
        counts = np.array([1, 2], np.uint32)
        trace = (21, 22, 23, 0)
        frame = wire.encode_bulk_request(
            7, keys, counts, 100.0, 1.0, kind=wire.BULK_KIND_HBUCKET,
            hier=("tenant:x", 500.0, 9.0, 1), deadline_s=0.25,
            attempt=1, trace=trace)
        klens = np.fromiter((len(b) for b in keys), np.int64)
        offsets = np.zeros(len(keys) + 1, np.int64)
        np.cumsum(klens, out=offsets[1:])
        span = wire.encode_bulk_request_span(
            7, b"".join(keys), offsets, klens, counts, 0, len(keys),
            100.0, 1.0, kind=wire.BULK_KIND_HBUCKET,
            hier=("tenant:x", 500.0, 9.0, 1), deadline_s=0.25,
            attempt=1, trace=trace)
        assert span == frame
        body = frame[4:]
        assert wire.bulk_deadline_tail(body) == (0.25, 1)
        assert wire.bulk_hier_tail(body) == ("tenant:x", 500.0, 9.0, 1)
        assert tuple(wire.bulk_trace_tail(body)) == trace
        seq, dec_keys, dec_counts, a, b, with_rem, kind = (
            wire.decode_bulk_request(body))
        assert (seq, dec_keys) == (7, ["a", "bb"])
        assert dec_counts.tolist() == [1, 2]

    def test_truncated_bulk_deadline_tail_is_loud(self):
        """With BOTH the deadline and trace flags up, a frame cut so
        the trace tail would overlap the head leaves no room for the
        9-byte deadline tail — that must raise, not misread arrays."""
        frame = wire.encode_bulk_request(5, [b"k"], [1], 10.0, 1.0,
                                         deadline_s=0.5, attempt=2,
                                         trace=(1, 2, 3, 0))
        body = frame[4:4 + 30]  # head + flags intact, tails gone
        with pytest.raises(wire.RemoteStoreError,
                           match="truncated bulk deadline tail"):
            wire.bulk_deadline_tail(body)
