"""TestApp — the manual console harness, completed (SURVEY.md §2 #12, §4).

The reference's ``TestApp/Program.cs`` had two intended test styles, both
commented out; this harness makes both real:

- ``single``       — the single-process smoke (``:8-22``): an approximate
                     limiter with the reference's exact config (100 ms
                     period, 1 token/period, limit 100, queue 100,
                     ``:13-16``), spun in a loop printing the
                     ``ToString()``-style dump (``:31``, ``:510-513``).
- ``server``/``worker`` — the multi-instance topology the Orleans harness
                     gestured at (``:37-104``): N worker *processes* on
                     localhost, ids from argv, all sharing one store
                     server (the Redis stand-in).
- ``convergence``  — orchestrates server + N workers and checks the
                     property the approximate algorithm exists to provide:
                     aggregate admitted throughput converges to
                     ≤ token_limit regardless of instance count (SURVEY.md
                     §4 implication (c)).

Usage::

    python examples/testapp.py single --seconds 3
    python examples/testapp.py convergence --instances 4 --seconds 8
    # or by hand, Orleans-style (one command per terminal):
    python -m distributedratelimiting.redis_tpu.runtime.server --port 6380 --backend inprocess
    python examples/testapp.py worker --port 6380 --id 0 --seconds 10
    python examples/testapp.py worker --port 6380 --id 1 --seconds 10
"""

from __future__ import annotations

import argparse
import asyncio
import json
import subprocess
import sys
import time

REPO_ROOT = __file__.rsplit("/", 2)[0]

# The reference TestApp's limiter config (TestApp/Program.cs:13-16) scaled
# to a visible rate: period 100 ms, tokens_per_period 1 ⇒ 10 tokens/s,
# burst capacity (token_limit) 100, queue 100.
PERIOD_S = 0.1
TOKENS_PER_PERIOD = 1
TOKEN_LIMIT = 100
QUEUE_LIMIT = 100


def _options():
    from distributedratelimiting.redis_tpu.models.options import (
        ApproximateTokenBucketOptions,
    )

    return ApproximateTokenBucketOptions(
        token_limit=TOKEN_LIMIT,
        tokens_per_period=TOKENS_PER_PERIOD,
        replenishment_period_s=PERIOD_S,
        queue_limit=QUEUE_LIMIT,
        instance_name="testapp",
    )


async def _drive(limiter, seconds: float,
                 print_dumps: bool) -> tuple[int, int, int]:
    """5 concurrent worker tasks acquiring as fast as leases come — the
    Orleans harness's worker-pool shape (TestApp/Program.cs:69-73,81-103).

    Returns ``(granted, denied, granted_late)`` where ``granted_late``
    counts grants in the second half of the window — past the startup
    transient (each fresh instance admits its full local burst before the
    first syncs propagate; convergence is a steady-state property)."""
    granted = denied = granted_late = 0
    deadline = time.monotonic() + seconds
    halfway = deadline - seconds / 2

    async def worker():
        nonlocal granted, denied, granted_late
        while time.monotonic() < deadline:
            lease = await limiter.acquire_async(1)
            if lease.is_acquired:
                granted += 1
                if time.monotonic() >= halfway:
                    granted_late += 1
                await asyncio.sleep(0.001)  # hold, then "release" (consumed)
            else:
                denied += 1
                retry = lease.retry_after or 0.01
                await asyncio.sleep(min(retry, 0.1))

    async def dumper():
        while time.monotonic() < deadline:
            await asyncio.sleep(1.0)
            print(limiter, flush=True)  # ≙ Console.WriteLine(limiter) :31

    tasks = [asyncio.create_task(worker()) for _ in range(5)]
    if print_dumps:
        tasks.append(asyncio.create_task(dumper()))
    results = await asyncio.gather(*tasks, return_exceptions=True)
    # A crashed worker must fail the harness loudly — swallowing it would
    # let a convergence run "pass" having served zero traffic.
    errors = [r for r in results if isinstance(r, BaseException)]
    if errors:
        raise errors[0]
    return granted, denied, granted_late


def cmd_single(args) -> int:
    """Single-process smoke against an in-process store (``:8-22``)."""
    from distributedratelimiting.redis_tpu.models.approximate import (
        ApproximateTokenBucketRateLimiter,
    )
    from distributedratelimiting.redis_tpu.runtime.store import (
        InProcessBucketStore,
    )

    async def main():
        limiter = ApproximateTokenBucketRateLimiter(
            _options(), InProcessBucketStore())
        granted, denied, _ = await _drive(limiter, args.seconds,
                                          print_dumps=True)
        print(json.dumps({"granted": granted, "denied": denied,
                          **limiter.stats()}), flush=True)
        await limiter.aclose()

    asyncio.run(main())
    return 0


def cmd_worker(args) -> int:
    """One limiter instance (≙ one silo) against a shared store server."""
    from distributedratelimiting.redis_tpu.models.approximate import (
        ApproximateTokenBucketRateLimiter,
    )
    from distributedratelimiting.redis_tpu.runtime.remote import (
        RemoteBucketStore,
    )

    async def main():
        store = RemoteBucketStore(address=("127.0.0.1", args.port))
        limiter = ApproximateTokenBucketRateLimiter(_options(), store)
        granted, denied, granted_late = await _drive(limiter, args.seconds,
                                                     print_dumps=args.verbose)
        print(json.dumps({
            "worker_id": args.id, "granted": granted, "denied": denied,
            "granted_late": granted_late,
            "instance_count_estimate": limiter.stats()["instance_count_estimate"],
        }), flush=True)
        await limiter.aclose()
        await store.aclose()

    asyncio.run(main())
    return 0


def cmd_convergence(args) -> int:
    """Spawn 1 store server + N worker processes; assert aggregate admitted
    throughput ≤ token_limit + fill·T (+ one period of staleness per
    instance) — the multi-client convergence property."""
    import socket

    with socket.socket() as s:  # free localhost port
        s.bind(("127.0.0.1", 0))
        port = s.getsockname()[1]

    server = subprocess.Popen(
        [sys.executable, "-m",
         "distributedratelimiting.redis_tpu.runtime.server",
         "--port", str(port), "--backend", args.backend],
        cwd=REPO_ROOT, stdout=subprocess.PIPE, text=True,
    )
    try:
        assert server.stdout is not None
        line = server.stdout.readline()  # wait for "listening" banner
        if "listening" not in line:
            raise RuntimeError(f"server failed to start: {line!r}")
        workers = [
            subprocess.Popen(
                [sys.executable, __file__, "worker", "--port", str(port),
                 "--id", str(i), "--seconds", str(args.seconds)],
                cwd=REPO_ROOT, stdout=subprocess.PIPE, text=True,
            )
            for i in range(args.instances)
        ]
        reports = []
        for w in workers:
            out, _ = w.communicate(timeout=args.seconds + 60)
            for ln in out.splitlines():
                if ln.startswith("{"):
                    reports.append(json.loads(ln))
    finally:
        server.terminate()
        server.wait(timeout=10)

    if len(reports) != args.instances:
        raise RuntimeError(
            f"only {len(reports)}/{args.instances} workers reported — a "
            "worker died before printing its summary"
        )
    total_granted = sum(r["granted"] for r in reports)
    total_late = sum(r["granted_late"] for r in reports)
    # Steady-state admission bound, checked on the second half of the run
    # (the first half absorbs the startup transient: each fresh instance
    # admits its full local burst before syncs propagate). Aggregate
    # admitted rate must settle to ~fill_rate, over-admitting by at most
    # one replenishment period of staleness per instance — the reference's
    # documented bound (SURVEY.md invariant 6) — plus margin for the
    # instance-count EWMA still converging.
    fill_rate = TOKENS_PER_PERIOD / PERIOD_S
    half = args.seconds / 2
    bound = 2.0 * (fill_rate * half
                   + args.instances * fill_rate * PERIOD_S * 2)
    summary = {
        "instances": args.instances,
        "seconds": args.seconds,
        "total_granted": total_granted,
        "steady_state_granted": total_late,
        "steady_state_bound": round(bound, 1),
        "converged": total_late <= bound,
        "per_worker": reports,
    }
    print(json.dumps(summary), flush=True)
    return 0 if summary["converged"] else 1


def cmd_bulk(args) -> int:
    """Bulk serving demo: whole key arrays through PartitionedRateLimiter
    against the device store (the batching the reference's README promised
    and never built), plus the keyed window façade — one await per call,
    no per-request futures."""
    import numpy as np

    from distributedratelimiting.redis_tpu.models.options import (
        SlidingWindowOptions,
        TokenBucketOptions,
    )
    from distributedratelimiting.redis_tpu.models.partitioned import (
        PartitionedRateLimiter,
    )
    from distributedratelimiting.redis_tpu.models.partitioned_window import (
        PartitionedWindowRateLimiter,
    )
    from distributedratelimiting.redis_tpu.runtime.store import (
        DeviceBucketStore,
    )

    async def main():
        store = DeviceBucketStore(n_slots=1 << max(10, args.keys.bit_length()))
        buckets = PartitionedRateLimiter(
            TokenBucketOptions(token_limit=100, tokens_per_period=50,
                               instance_name="bulkdemo"), store)
        windows = PartitionedWindowRateLimiter(
            SlidingWindowOptions(permit_limit=100, window_s=1.0,
                                 instance_name="bulkwin"), store)
        rng = np.random.default_rng(0)
        users = [f"user{i}" for i in rng.integers(0, args.keys, args.n)]
        # Warm: first calls pay kernel compilation, not serving cost.
        await buckets.acquire_many(users[:256], 0, with_remaining=False)
        await windows.acquire_many(users[:256], 0, with_remaining=False)
        t0 = time.perf_counter()
        res = await buckets.acquire_many(users, 1, with_remaining=False)
        bucket_dt = time.perf_counter() - t0
        t0 = time.perf_counter()
        wres = await windows.acquire_many(users, 1, with_remaining=False)
        window_dt = time.perf_counter() - t0
        print(json.dumps({
            "requests": args.n,
            "distinct_keys": args.keys,
            "bucket_granted": int(res.granted_count),
            "bucket_decisions_per_sec": round(args.n / bucket_dt),
            "window_granted": int(wres.granted_count),
            "window_decisions_per_sec": round(args.n / window_dt),
        }), flush=True)
        await store.aclose()

    asyncio.run(main())
    return 0


def cmd_cluster(args) -> int:
    """Cluster demo: N store servers in this process (shared-nothing, each
    its own store), one ClusterBucketStore routing keys across them,
    bulk + single-key traffic, then one node killed to show per-node
    degraded mode (deny policy)."""
    from distributedratelimiting.redis_tpu.runtime.cluster import (
        ClusterBucketStore,
    )
    from distributedratelimiting.redis_tpu.runtime.server import (
        BucketStoreServer,
    )
    from distributedratelimiting.redis_tpu.runtime.store import (
        InProcessBucketStore,
    )

    async def main():
        servers = []
        for _ in range(args.nodes):
            srv = BucketStoreServer(InProcessBucketStore(),
                                    native_frontend=args.native_frontend)
            await srv.start()
            servers.append(srv)
        store = ClusterBucketStore(
            addresses=[(s.host, s.port) for s in servers],
            partial_failures="deny", request_timeout_s=3.0)
        keys = [f"user{i}" for i in range(args.n)]
        res = await store.acquire_many(keys, [1] * args.n, 100.0, 50.0)
        # The placement map is the routing truth (no modulus): epoch 0
        # routes exactly like the legacy crc32 % N, and a resharded
        # cluster's spread follows the map automatically.
        spread = [0] * args.nodes
        for k in keys:
            spread[store.node_index_of(k)] += 1
        stats = await store.stats()
        await servers[0].aclose()  # kill node 0 → its keys deny, rest serve
        res2 = await store.acquire_many(keys, [1] * args.n, 100.0, 50.0)
        live = sum(1 for i, k in enumerate(keys)
                   if store.node_index_of(k) != 0 and res2.granted[i])
        print(json.dumps({
            "nodes": args.nodes,
            "key_spread": spread,
            "granted_all_nodes_up": int(res.granted_count),
            "per_node_requests_served": [
                s["requests_served"] for s in stats["nodes"]],
            "after_node0_killed": {
                "granted": int(res2.granted_count),
                "live_node_grants": live,
                "node0_keys_denied": spread[0],
            },
        }, ), flush=True)
        await store.aclose()
        for s in servers[1:]:
            await s.aclose()

    asyncio.run(main())
    return 0


def main(argv: list[str] | None = None) -> int:
    parser = argparse.ArgumentParser(description=__doc__)
    sub = parser.add_subparsers(dest="cmd", required=True)

    p = sub.add_parser("single", help="single-process smoke")
    p.add_argument("--seconds", type=float, default=3.0)
    p.set_defaults(fn=cmd_single)

    p = sub.add_parser("worker", help="one limiter instance vs shared server")
    p.add_argument("--port", type=int, required=True)
    p.add_argument("--id", type=int, default=0)
    p.add_argument("--seconds", type=float, default=5.0)
    p.add_argument("--verbose", action="store_true")
    p.set_defaults(fn=cmd_worker)

    p = sub.add_parser("convergence", help="server + N workers, check bound")
    p.add_argument("--instances", type=int, default=4)
    p.add_argument("--seconds", type=float, default=8.0)
    p.add_argument("--backend", choices=("inprocess", "device"),
                   default="inprocess",
                   help="store behind the server: device = the TPU/"
                   "device-resident DeviceBucketStore (the production "
                   "topology: N processes → TCP → device store)")
    p.set_defaults(fn=cmd_convergence)

    p = sub.add_parser("bulk", help="whole-array bulk serving demo "
                       "(buckets + keyed windows on the device store)")
    p.add_argument("--n", type=int, default=100_000,
                   help="requests per bulk call")
    p.add_argument("--keys", type=int, default=50_000,
                   help="distinct key pool size")
    p.set_defaults(fn=cmd_bulk)

    p = sub.add_parser("cluster", help="N shared-nothing store servers + "
                       "client-side key routing; kills a node to show "
                       "per-node degraded mode")
    p.add_argument("--nodes", type=int, default=3)
    p.add_argument("--native-frontend", action="store_true",
                   help="serve each node's sockets from the C++ epoll "
                   "front-end (native/frontend.cc)")
    p.add_argument("--n", type=int, default=1000,
                   help="keys in the bulk call")
    p.set_defaults(fn=cmd_cluster)

    args = parser.parse_args(argv)
    return args.fn(args)


if __name__ == "__main__":
    sys.path.insert(0, REPO_ROOT)
    from distributedratelimiting.redis_tpu.utils.cpu_bootstrap import (
        maybe_force_cpu_from_env,
    )

    maybe_force_cpu_from_env()
    sys.exit(main())
