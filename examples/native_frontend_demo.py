"""Consumer-style drive of the native serving front-end + core flows.

Run: python examples/native_frontend_demo.py [cpu|tpu]
(JAX_PLATFORMS=cpu for the CPU backend; the verify skill drives this
file from outside the repo tree on both backends.)

Starts a BucketStoreServer(native_frontend=True) over a DeviceBucketStore,
talks to it only through the public client (RemoteBucketStore) plus one
raw-socket check, and exercises: burst->drain->refill, duplicate-key
batch serialization, zero-probe, window ops, stats, and the native
load generator.
"""
import asyncio
import sys
import time


async def main(platform: str) -> None:
    from distributedratelimiting.redis_tpu.runtime.store import (
        DeviceBucketStore,
    )
    from distributedratelimiting.redis_tpu.runtime.server import (
        BucketStoreServer,
    )
    from distributedratelimiting.redis_tpu.runtime.remote import (
        RemoteBucketStore,
    )
    from distributedratelimiting.redis_tpu.runtime.clock import ManualClock
    from distributedratelimiting.redis_tpu.runtime.native_frontend import (
        native_loadgen,
    )

    clock = ManualClock()
    backing = DeviceBucketStore(n_slots=1 << 14, clock=clock)
    srv = BucketStoreServer(backing, native_frontend=True)
    await srv.start()
    print(f"[{platform}] native front-end listening on {srv.host}:{srv.port}")
    store = RemoteBucketStore(address=(srv.host, srv.port),
                              coalesce_requests=False)

    # Burst -> drain on one hot key: capacity 5, zero refill while the
    # manual clock is frozen. 32 concurrent one-token asks -> exactly 5.
    results = await asyncio.gather(
        *(store.acquire("hot", 1, 5.0, 1.0) for _ in range(32)))
    grants = sum(r.granted for r in results)
    assert grants == 5, f"duplicate serialization broke: {grants} grants"
    print(f"[{platform}] burst: exactly 5/32 granted (cap 5, frozen clock)")

    # Timed refill: advance the injected clock 3s at 1 token/s.
    clock.advance_seconds(3.0)
    r = await store.acquire("hot", 3, 5.0, 1.0)
    assert r.granted, "3s at 1 tok/s should refill 3"
    r = await store.acquire("hot", 1, 5.0, 1.0)
    assert not r.granted, "bucket should be empty again"
    print(f"[{platform}] refill: 3 tokens after 3s, then empty — exact")

    # Zero-permit probe + window family through the same socket.
    assert (await store.acquire("fresh", 0, 5.0, 1.0)).granted
    w = await store.window_acquire("w", 2, 10.0, 60.0)
    assert w.granted and abs(w.remaining - 8.0) < 1e-6
    f = await store.fixed_window_acquire("fw", 10, 10.0, 60.0)
    assert f.granted
    assert not (await store.fixed_window_acquire("fw", 1, 10.0, 60.0)).granted
    print(f"[{platform}] zero-probe + sliding/fixed windows OK")

    # Concurrency semaphore rides the same hot batch path: 30 concurrent
    # holds on a limit-10 key grant exactly 10; releases restore.
    results = await asyncio.gather(
        *(store.concurrency_acquire("gpu", 1, 10) for _ in range(30)))
    assert sum(r.granted for r in results) == 10
    await asyncio.gather(
        *(store.concurrency_release("gpu", 1) for _ in range(10)))
    r = await store.concurrency_acquire("gpu", 10, 10)
    assert r.granted and abs(r.remaining - 10.0) < 1e-6
    print(f"[{platform}] semaphore: exactly 10/30 held, release restores")

    # Stats surface reports the native front-end.
    st = await store.stats()
    assert st["native_frontend"] is True and st["requests_served"] >= 38, st
    print(f"[{platform}] stats: native_frontend=True, "
          f"requests={st['requests_served']}, "
          f"batches={st['batches_flushed']}, "
          f"p99={st['serving_p99_ms']:.3f}ms")

    # Native load generator: closed-loop C client, big-capacity bucket.
    replies, granted, elapsed = await asyncio.to_thread(
        native_loadgen, srv.host, srv.port, conns=2, depth=32,
        reqs_per_conn=5000, capacity=1e9, fill_rate=1e9)
    assert replies == 10000 and granted == replies
    print(f"[{platform}] native loadgen: {replies/elapsed:,.0f} req/s "
          f"({replies} replies, all granted)")

    await store.aclose()
    await srv.aclose()
    await backing.aclose()
    print(f"[{platform}] clean shutdown OK")


if __name__ == "__main__":
    platform = sys.argv[1] if len(sys.argv) > 1 else "?"
    t0 = time.time()
    asyncio.run(main(platform))
    print(f"[{platform}] PASS in {time.time() - t0:.1f}s")
