"""The queued device-bench debt list — repo-resident so it survives
watcher loss (ROADMAP item 4a; ISSUE 7 satellite).

Rounds r04/r05 lost their device windows (tunnel wedge, watcher loss),
so three measurements are still OWED against the kernel-speed story;
until each lands, the headline numbers rest on CPU stand-ins:

1. ``fp_mesh_fixed`` — the r05 fp_mesh rework (total-slot provisioning,
   ``benchmarks/suite.py``) has no TPU number at all: the r05 run
   measured the 8×-underwater per-shard config, not this one.
2. ``fp_bulk_optimized`` — the optimized fp bulk path (fused operand,
   bit-plane verdicts) was reworked after the last healthy window; its
   device rate is extrapolated, never observed.
3. ``native_fe_device_sweep`` — the native front-end has NO number
   against a device-class (multi-ms flush) backing — the one serving
   regime the 2 ms p99 north star actually fears (VERDICT r5 next #3).

Running ``python -m benchmarks.recapture`` probes for a healthy
device-init window with a disposable child (bench.py's r04-proof
discipline: a hung init in the committed process is unrecoverable),
then runs every debt still owed under a hang guard and appends evidence
to ``benchmarks/evidence/recapture.jsonl``. A debt leaves the list by
landing an ``ok`` row there — never by being forgotten. With no healthy
window the run exits 0 having written nothing: the debts persist and
fire on the first window a cron/watcher finds.

``--allow-cpu`` runs the same code paths on the CPU stand-in (smoke for
tests and plumbing work); CPU rows are stamped ``settles_debt: false``
and do not retire anything.
"""

from __future__ import annotations

import argparse
import json
import os
import pathlib
import subprocess
import sys
import threading
import time

_ROOT = pathlib.Path(__file__).resolve().parents[1]
LEDGER = _ROOT / "benchmarks" / "evidence" / "recapture.jsonl"

__all__ = ["DEBTS", "owed", "main"]


# -- the debt sections -------------------------------------------------------

def _debt_fp_mesh_fixed(smoke: bool) -> dict:
    from benchmarks import suite

    return suite.bench_fp_mesh(smoke=smoke)


def _debt_fp_bulk_optimized(smoke: bool) -> dict:
    import asyncio

    import numpy as np

    from distributedratelimiting.redis_tpu.runtime.fp_store import (
        FingerprintBucketStore,
    )

    n = 1 << (10 if smoke else 17)
    store = FingerprintBucketStore(
        n_slots=1 << (12 if smoke else 21),
        max_batch=512 if smoke else 8192)
    rng = np.random.default_rng(3)
    pool = [f"user{i}" for i in range(20_000 if smoke else 1_000_000)]
    calls = [[pool[j] for j in rng.integers(0, len(pool), n)]
             for _ in range(4)]
    counts = [1] * n

    async def run() -> float:
        async def one_round() -> float:
            t0 = time.perf_counter()
            results = await asyncio.gather(
                *(store.acquire_many(c, counts, 1e7, 1e7,
                                     with_remaining=False)
                  for c in calls))
            return sum(len(r) for r in results) / (
                time.perf_counter() - t0)

        await one_round()  # warm: inserts + compile at exact shapes
        rate = max([await one_round() for _ in range(2)])
        await store.aclose()
        return rate

    rate = asyncio.run(run())
    return {"metric": "decisions_per_sec", "value": round(rate),
            "unit": "decisions/s", "keys_per_call": n}


def _debt_native_fe_device_sweep(smoke: bool) -> dict:
    """The native front-end against a device-backed store, via bench.py's
    existing child rig (one server process owning the device, one load
    process driving the C loadgen) — subprocesses so a wedged device op
    costs this section, not the runner. Round 8 added the BULK arm: the
    same device-backed server (tier-0 armed) driven with ACQUIRE_MANY
    frames through the native bulk lane — the native-FE p99 against a
    multi-ms-flush backing that the 2 ms north star actually fears."""
    env = os.environ.copy()
    env.pop("DRL_TPU_FORCE_CPU", None)
    if smoke:
        # CPU stand-in exercises the identical rig end to end.
        env["JAX_PLATFORMS"] = env.get("JAX_PLATFORMS", "cpu")
    out: dict = {}
    for arm, server_args, load_flag, load_args in (
        ("scalar", ["device", "native"], "--native-load-child", []),
        ("bulk", ["device", "native", "tier0"], "--bulk-load-child",
         ["hot"]),
    ):
        server = subprocess.Popen(
            [sys.executable, str(_ROOT / "bench.py"),
             "--serving-server-child", *server_args],
            stdin=subprocess.PIPE, stdout=subprocess.PIPE, text=True,
            env=env, cwd=str(_ROOT))
        try:
            line = server.stdout.readline()
            addr = json.loads(line)
            load = subprocess.run(
                [sys.executable, str(_ROOT / "bench.py"),
                 load_flag, addr["host"], str(addr["port"]), *load_args],
                capture_output=True, text=True, env=env, cwd=str(_ROOT),
                timeout=1200)
            if load.returncode != 0:
                raise RuntimeError(
                    f"{arm} load child failed: "
                    f"{load.stderr.strip()[-400:]}")
            out[arm] = json.loads(load.stdout.strip().splitlines()[-1])
        finally:
            try:
                server.stdin.close()
                server.wait(30)
            except Exception:
                server.kill()
    return {"metric": "depth_sweep", "sweep": out.get("scalar"),
            "bulk": out.get("bulk"), "unit": "req/s + ms"}


def _debt_llm_workload_device(smoke: bool) -> dict:
    """The LLM workload (ISSUE 10) against the DEVICE store: the fused
    two-level kernel (acquire_hierarchical_packed) deciding the Zipf ×
    log-normal tenant workload — its per-chip rows/s and tokens/s have
    only CPU stand-in numbers until this lands on real hardware."""
    from benchmarks import llm_workload
    from distributedratelimiting.redis_tpu.runtime.store import (
        DeviceBucketStore,
    )

    n = 1 << (11 if smoke else 16)
    tenants, keys, costs, prios = llm_workload.gen_workload(9, n)
    store = DeviceBucketStore(n_slots=1 << (12 if smoke else 18),
                              max_batch=1024 if smoke else 4096)

    def one_round() -> float:
        t0 = time.perf_counter()
        store.acquire_hierarchical_many_blocking(
            tenants, keys, costs, llm_workload.TENANT_CAP,
            llm_workload.TENANT_RATE, llm_workload.CHILD_CAP,
            llm_workload.CHILD_RATE, with_remaining=False)
        return time.perf_counter() - t0

    one_round()  # warm: compile + slot inserts at exact shapes
    dt = min(one_round() for _ in range(2))
    total_tokens = int(costs.sum())
    return {"metric": "hier_rows_per_sec", "value": round(n / dt),
            "tokens_per_sec": round(total_tokens / dt),
            "unit": "rows/s + tokens/s", "rows": n}


def _debt_llm_reservations_device(smoke: bool) -> dict:
    """The estimate-reserve-settle lane (ISSUE 13) against the DEVICE
    store: every reserve is a fused hierarchical launch at the
    estimate, every settle a saturating debit (refund or overage
    collection) — the reserve+settle round-trip rate and settled
    tokens/s have only CPU stand-in numbers until this lands on real
    hardware."""
    import asyncio

    from benchmarks import llm_workload
    from distributedratelimiting.redis_tpu.runtime.store import (
        DeviceBucketStore,
    )

    n = 1 << (9 if smoke else 13)
    tenants, keys, costs, prios = llm_workload.gen_workload(9, n)
    np = __import__("numpy")
    rng = np.random.default_rng(llm_workload._RESV_ERR_SEED)
    # The TRACKED estimate identity — must match lane_reservations
    # exactly or the device row stops being comparable to the CPU
    # stand-in it settles.
    estimates = np.maximum(
        costs * rng.lognormal(0.0, llm_workload.RESV_EST_SIGMA, n),
        1.0)
    store = DeviceBucketStore(n_slots=1 << (12 if smoke else 16),
                              max_batch=1024)

    async def one_round(prefix: str) -> float:
        t0 = time.perf_counter()
        _g, _s, _led = await llm_workload._drive_reservations(
            store, tenants, keys, costs, estimates, prios,
            llm_workload.TENANT_CAP, llm_workload.TENANT_RATE, prefix)
        return time.perf_counter() - t0

    asyncio.run(one_round("w"))  # warm: compile + slot inserts
    dt = min(asyncio.run(one_round(p)) for p in ("x", "y"))
    total_tokens = int(costs.sum())
    return {"metric": "reserve_settle_pairs_per_sec",
            "value": round(n / dt),
            "settled_tokens_per_sec": round(total_tokens / dt),
            "unit": "reserve+settle pairs/s", "rows": n}


def _debt_native_fe_shard_sweep(smoke: bool) -> dict:
    """The multi-shard front-end (round 11) against a DEVICE-class
    backing: shards ∈ {1, 2, 4, 8} SO_REUSEPORT epoll shards on one
    port, tier-0 armed, driven by the C bulk loadgen — the node-level
    rows/s curve whose CPU stand-in lives in
    evidence/native_shards_r11.jsonl and BENCH serving_native_shards.
    On a real device the residue rows meet a multi-ms flush, so the
    device arm is the one that prices the shield, not just the shards."""
    import concurrent.futures

    env = os.environ.copy()
    env.pop("DRL_TPU_FORCE_CPU", None)
    if smoke:
        env["JAX_PLATFORMS"] = env.get("JAX_PLATFORMS", "cpu")
    out: dict = {}
    for shards in (1, 2, 4, 8):
        server = subprocess.Popen(
            [sys.executable, str(_ROOT / "bench.py"),
             "--serving-server-child", "device", "native", "tier0",
             f"shards={shards}", "pin"],
            stdin=subprocess.PIPE, stdout=subprocess.PIPE, text=True,
            env=env, cwd=str(_ROOT))
        pool = concurrent.futures.ThreadPoolExecutor(1)
        try:
            line = pool.submit(server.stdout.readline).result(
                timeout=180.0)
            addr = json.loads(line)
            load = subprocess.run(
                [sys.executable, str(_ROOT / "bench.py"),
                 "--shard-load-child", addr["host"],
                 str(addr["port"]), str(shards)],
                capture_output=True, text=True, env=env,
                cwd=str(_ROOT), timeout=600)
            if load.returncode != 0:
                raise RuntimeError(
                    f"s{shards} load child failed: "
                    f"{load.stderr.strip()[-400:]}")
            out[f"s{shards}"] = json.loads(
                load.stdout.strip().splitlines()[-1])
        finally:
            try:
                server.stdin.close()
                server.wait(30)
            except Exception:
                server.kill()
            pool.shutdown(wait=False)
    if "s1" in out and "s4" in out:
        out["speedup_4v1"] = (out["s4"]["rows_per_s"]
                              / out["s1"]["rows_per_s"])
    return {"metric": "shard_sweep", "sweep": out,
            "unit": "rows/s per shard count"}


def _debt_native_fe_uring_sweep(smoke: bool) -> dict:
    """The io_uring data plane (round 16) against a DEVICE-class
    backing: the round-11 shard rig once per transport arm — epoll vs
    io_uring vs io_uring+SQPOLL at 1 and 4 shards — harvesting the
    server child's shutdown line (fe_uring_counts data-plane syscall
    counter + rusage CPU-seconds) so syscalls/frame and cycles/row get
    device-backed numbers instead of the CPU stand-ins in
    evidence/native_uring_r16.jsonl. On a host whose kernel lacks
    io_uring only the epoll arm runs and the probe verdict is
    recorded beside it — a fallback run never masquerades as ring
    numbers (the per-arm rows carry uring_shards/fallbacks)."""
    import concurrent.futures

    from distributedratelimiting.redis_tpu.runtime.native_frontend import (
        uring_probe,
    )

    env = os.environ.copy()
    env.pop("DRL_TPU_FORCE_CPU", None)
    if smoke:
        env["JAX_PLATFORMS"] = env.get("JAX_PLATFORMS", "cpu")
    ok, reason = uring_probe()
    arms = [("epoll", None)]
    if ok:
        arms += [("uring", "on"), ("sqpoll", "sqpoll")]
    out: dict = {"uring_available": ok, "probe": reason}
    for name, uring in arms:
        for shards in (1, 4):
            argv = [sys.executable, str(_ROOT / "bench.py"),
                    "--serving-server-child", "device", "native",
                    "tier0", f"shards={shards}", "pin"]
            if uring is not None:
                argv.append(f"uring={uring}")
            server = subprocess.Popen(
                argv, stdin=subprocess.PIPE, stdout=subprocess.PIPE,
                text=True, env=env, cwd=str(_ROOT))
            pool = concurrent.futures.ThreadPoolExecutor(1)
            try:
                line = pool.submit(server.stdout.readline).result(
                    timeout=180.0)
                addr = json.loads(line)
                load = subprocess.run(
                    [sys.executable, str(_ROOT / "bench.py"),
                     "--shard-load-child", addr["host"],
                     str(addr["port"]), str(shards)],
                    capture_output=True, text=True, env=env,
                    cwd=str(_ROOT), timeout=600)
                if load.returncode != 0:
                    raise RuntimeError(
                        f"{name}_s{shards} load child failed: "
                        f"{load.stderr.strip()[-400:]}")
                res = json.loads(load.stdout.strip().splitlines()[-1])
                server.stdin.close()
                tail = pool.submit(server.stdout.readline).result(
                    timeout=60.0)
                if tail.strip():
                    res.update(json.loads(tail))
                tr = res.get("transport")
                if tr and res.get("frames_sent"):
                    res["syscalls_per_frame"] = round(
                        tr["io_syscalls"] / res["frames_sent"], 3)
                # ε-consumption annotation (round 18): the server
                # child's shutdown line carries the cumulative tier-0
                # grant tokens and the per-slice split (fe_t0_eps) —
                # fold them into the per-slice utilization proxy the
                # conservation auditor renders as
                # drl_epsilon_budget_used_ratio{source="shard"}, so
                # each transport arm prices drift beside its syscall
                # economics.
                eps = res.get("t0_eps_tokens")
                if eps and sum(eps) > 0:
                    res["t0_eps_hot_slice_share"] = round(
                        max(eps) / sum(eps), 4)
                grant = res.get("t0_grant_tokens")
                if grant:
                    res["t0_overadmit_per_grant"] = round(
                        res.get("t0_overadmit_total", 0.0) / grant, 9)
                out[f"{name}_s{shards}"] = res
            finally:
                try:
                    if not server.stdin.closed:
                        server.stdin.close()
                    server.wait(30)
                except Exception:
                    server.kill()
                pool.shutdown(wait=False)
    return {"metric": "uring_transport_sweep", "sweep": out,
            "unit": "syscalls/frame + rows/s per transport arm"}


def _debt_federation_device(smoke: bool) -> dict:
    """The WAN federation lane (ISSUE 15) against the DEVICE store:
    the region's local decisions from a leased slice are ordinary
    device-store acquires and the home's renew charges are
    ``debit_many`` launches — both rates have only CPU stand-in
    numbers (benchmarks/federation.py) until this lands on real
    hardware."""
    import asyncio

    from distributedratelimiting.redis_tpu.runtime.store import (
        DeviceBucketStore,
    )

    n = 1 << (10 if smoke else 14)
    cap, rate = 1e9, 1e6

    async def drive() -> dict:
        store = DeviceBucketStore(n_slots=1 << (12 if smoke else 15),
                                  max_batch=1024)
        led = store.federation_ledger(default_ttl_s=30.0)
        grant = await led.lease({
            "region": "bench", "lease_id": "dev:1",
            "tenant": "tenant:g", "demand": 1.0,
            "global_cap": cap, "global_rate": rate})
        slice_cap, slice_rate = grant["slice"]
        t0 = time.perf_counter()
        granted = 0
        for i in range(n):
            res = await store.acquire("tenant:g", 1, slice_cap,
                                      slice_rate)
            granted += int(res.granted)
        dt = time.perf_counter() - t0
        t1 = time.perf_counter()
        renew = await led.renew({
            "region": "bench", "lease_id": "dev:1",
            "tenant": "tenant:g", "total": float(granted),
            "demand": 1.0})
        renew_s = time.perf_counter() - t1
        await store.aclose()
        return {"metric": "federation_local_decisions",
                "decisions": n, "granted": granted,
                "decisions_per_s": round(n / dt, 1),
                "renew_charge_s": round(renew_s, 5),
                "renew_charged": renew["charged"],
                "unit": "slice-local decisions/s"}

    return asyncio.run(drive())


def _debt_storm_goodput_device(smoke: bool) -> dict:
    """The retry-storm goodput soak (ISSUE 20) — on this rung the CPU
    stand-in IS the full differential (benchmarks/storm_goodput.py over
    the in-process backing); what is owed is the device edition, where
    the doomed-work gate's p99 comes from a real multi-ms device flush
    and the per-row deny runs on the native bulk lane."""
    import asyncio

    from benchmarks import storm_goodput

    out = asyncio.run(storm_goodput.run_soak(storm_goodput.DEFAULT_SEED))
    return {"metric": "storm_goodput_ratio",
            "value": out["defended_ratio"],
            "naive_ratio": out["naive_ratio"],
            "baseline_goodput": out["baseline"]["goodput"],
            "defended_goodput": out["defended"]["goodput"],
            "routed": out["defended"]["counts"]["routed"],
            "retries_shed": out["defended"]["server"]["retries_shed"],
            "unit": "defended/baseline first-attempt goodput"}


#: Ordered debt list: name → (what is owed, runner). The NAME is the
#: ledger identity — renaming one un-retires it, deliberately.
DEBTS: "list[tuple[str, str, object]]" = [
    ("fp_mesh_fixed",
     "r05 fp_mesh total-slot provisioning has no TPU number "
     "(the r05 run measured the underwater per-shard config)",
     _debt_fp_mesh_fixed),
    ("fp_bulk_optimized",
     "optimized fp bulk (fused operand, bit-plane verdicts) device "
     "rate extrapolated, never observed",
     _debt_fp_bulk_optimized),
    ("native_fe_device_sweep",
     "native front-end has no number against a device-class "
     "(multi-ms flush) backing — VERDICT r5 next #3; round 8 added the "
     "native-bulk arm (ACQUIRE_MANY through the C lane, tier-0 armed)",
     _debt_native_fe_device_sweep),
    ("llm_workload_device",
     "the token-denominated LLM workload (ISSUE 10) has no device "
     "number: the fused hierarchical kernel's rows/s + tokens/s rest "
     "on the CPU stand-in (benchmarks/llm_workload.py)",
     _debt_llm_workload_device),
    ("native_fe_shard_sweep",
     "the multi-shard front-end (round 11) has no device number: the "
     "shards x {1,2,4,8} node-level curve rests on the CPU stand-in "
     "(evidence/native_shards_r11.jsonl); the device arm prices the "
     "residue path against a real multi-ms flush",
     _debt_native_fe_shard_sweep),
    ("llm_reservations_device",
     "the estimate-reserve-settle lane (ISSUE 13) has no device "
     "number: reserve = fused hierarchical launch, settle = "
     "saturating debit — the pair rate rests on the CPU stand-in "
     "(benchmarks/llm_workload.py reservations lane)",
     _debt_llm_reservations_device),
    ("federation_device",
     "the WAN federation lane (ISSUE 15) has no device number: the "
     "regional local-decision throughput behind a leased slice (and "
     "the home's debit_many settle lane under renew reports) rest on "
     "the CPU stand-in (benchmarks/federation.py)",
     _debt_federation_device),
    ("native_fe_uring_sweep",
     "the io_uring data plane (round 16) has no device number: the "
     "epoll/uring/sqpoll transport sweep — syscalls/frame and "
     "cycles/row against a real multi-ms flush — rests on the CPU "
     "stand-in (evidence/native_uring_r16.jsonl); round 18 annotates "
     "each arm with the tier-0 ε-consumption counters (fe_t0_eps "
     "per-slice grants, overadmit/grant ratio)",
     _debt_native_fe_uring_sweep),
    ("storm_goodput_device",
     "the retry-storm goodput differential (ISSUE 20) has no device "
     "number: the defended/naive/baseline arms run over the "
     "in-process backing — the doomed-work gate pricing (p99 sensing "
     "+ per-row deny on the native bulk lane) against a real "
     "multi-ms device flush rests on the CPU stand-in "
     "(benchmarks/storm_goodput.py)",
     _debt_storm_goodput_device),
]


# -- ledger ------------------------------------------------------------------

def _settled(ledger: pathlib.Path) -> set[str]:
    done: set[str] = set()
    if not ledger.exists():
        return done
    for line in ledger.read_text().splitlines():
        try:
            row = json.loads(line)
        except ValueError:
            continue  # a torn tail row must not hide the whole ledger
        if row.get("status") == "ok" and row.get("settles_debt"):
            done.add(row.get("debt", ""))
    return done


def owed(ledger: "pathlib.Path | None" = None) -> list[str]:
    """Debt names still lacking an evidence row — THE list a watcher
    (or a human) checks per round."""
    done = _settled(ledger or LEDGER)
    return [name for name, _why, _fn in DEBTS if name not in done]


def _append(ledger: pathlib.Path, row: dict) -> None:
    ledger.parent.mkdir(parents=True, exist_ok=True)
    with open(ledger, "a", encoding="utf-8") as f:
        f.write(json.dumps(row) + "\n")


def _budget_ledger_hash() -> "str | None":
    """Content hash of the checked-in drl-xla op-budget ledger
    (tools/drl_xla/budgets.json). Every debt row carries it so a
    settled number names the compiled-artifact shape it was measured
    under — a later kernel rework that changes gather/launch counts
    visibly orphans the old evidence instead of silently inheriting
    it (docs/OPERATIONS.md §19). ``None`` when the ledger is absent
    (a fresh checkout mid-restamp): the row still lands, unannotated."""
    try:
        from tools.drl_xla import budgets
        return budgets.ledger_hash(budgets.ledger_path(_ROOT))
    except Exception:
        return None


# -- device window probe (bench.py's disposable-child discipline) ------------

def _probe_platform(max_wait_s: float) -> "str | None":
    deadline = time.monotonic() + max_wait_s
    while True:
        child_timeout = min(60.0, max(deadline - time.monotonic(), 5.0))
        try:
            r = subprocess.run(
                [sys.executable, "-c",
                 "import jax; print(jax.devices()[0].platform)"],
                timeout=child_timeout, capture_output=True, text=True,
                env=os.environ.copy())
            if r.returncode == 0:
                return r.stdout.strip().splitlines()[-1]
            return None  # deterministic init failure: retrying won't fix
        except subprocess.TimeoutExpired:
            pass
        if time.monotonic() >= deadline:
            return None
        time.sleep(5)


def _run_guarded(fn, smoke: bool, timeout_s: float):
    box: dict = {}

    def target() -> None:
        try:
            box["v"] = fn(smoke)
        except BaseException as exc:  # noqa: BLE001 — a debt section
            box["e"] = f"{type(exc).__name__}: {exc}"  # must never kill
        # the runner: the remaining debts still deserve their window.

    th = threading.Thread(target=target, daemon=True)
    th.start()
    th.join(timeout_s)
    if th.is_alive():
        return "hung", None
    if "e" in box:
        return f"error: {box['e'][:300]}", None
    return "ok", box.get("v")


def main(argv: "list[str] | None" = None) -> int:
    parser = argparse.ArgumentParser(description=__doc__)
    parser.add_argument("--allow-cpu", action="store_true",
                        help="run the debt sections on the CPU stand-in "
                        "(smoke sizes; rows do not settle debts)")
    parser.add_argument("--force", action="store_true",
                        help="re-run debts that already have evidence")
    parser.add_argument("--only", default=None, metavar="NAME",
                        help="run just this debt (others stay owed "
                        "untouched — e.g. appending one section's CPU "
                        "stand-in row without burning a window on the "
                        "rest)")
    parser.add_argument("--probe-s", type=float, default=float(
        os.environ.get("BENCH_PROBE_S", "240")))
    parser.add_argument("--section-timeout-s", type=float, default=900.0)
    parser.add_argument("--ledger", default=str(LEDGER))
    args = parser.parse_args(argv)
    ledger = pathlib.Path(args.ledger)

    platform = _probe_platform(args.probe_s)
    device = platform is not None and platform != "cpu"
    if not device and not args.allow_cpu:
        print(json.dumps({"status": "no_healthy_device_window",
                          "owed": owed(ledger)}))
        return 0

    pending = owed(ledger) if not args.force else [n for n, _, _ in DEBTS]
    if args.only is not None:
        if args.only not in {n for n, _, _ in DEBTS}:
            print(json.dumps({"status": "unknown_debt",
                              "only": args.only,
                              "known": [n for n, _, _ in DEBTS]}))
            return 2
        pending = [n for n in pending if n == args.only]
    results = {}
    for name, why, fn in DEBTS:
        if args.only is not None and name != args.only:
            results[name] = "skipped_only"
            continue
        if name not in pending:
            results[name] = "already_settled"
            continue
        status, value = _run_guarded(fn, smoke=not device,
                                     timeout_s=args.section_timeout_s)
        row = {"debt": name, "why": why, "status": status,
               "platform": platform, "settles_debt": bool(device),
               "t": time.time(), "budget_ledger": _budget_ledger_hash(),
               "result": value}
        _append(ledger, row)
        results[name] = status
        print(json.dumps(row), flush=True)
    print(json.dumps({"status": "done", "platform": platform,
                      "results": results, "owed": owed(ledger)}))
    return 0


if __name__ == "__main__":
    sys.exit(main())
