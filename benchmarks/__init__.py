"""Reproducible benchmark suite — the five BASELINE.md configurations.

The reference ships no benchmarks at all (SURVEY.md §6); this suite is the
framework's proof surface. ``python -m benchmarks.suite`` runs every config
and prints one JSON line per config; ``--smoke`` shrinks sizes so the same
code paths run in seconds on the CPU test mesh (tests/test_benchmarks.py).
"""
