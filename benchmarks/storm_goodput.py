"""Retry-storm goodput soak (docs/DESIGN.md §24, OPERATIONS.md §20).

THE seeded overload differential for the goodput-under-overload plane:
a deterministic discrete-event simulation of a retry storm — client
timeout below server latency under load, multiplicative backoff — is
driven over the REAL wire (OP_RESERVE / OP_SETTLE through an
``AdmissionPolicy`` edge gateway and a ``RemoteBucketStore`` client
against a ``BucketStoreServer``), three arms from one schedule:

- **baseline** — the primary population alone, defenses off: the
  no-storm goodput reference.
- **naive** — primaries plus an exogenous stormer population whose
  client timeout sits below any loaded service latency, defenses off:
  every stormer retry executes, the load model pushes latency past the
  primaries' timeout, the primaries start retrying too, and goodput
  collapses (the classic metastable retry storm).
- **defended** — same offered traffic, defenses armed: the server's
  retry-shed gate denies attempt-stamped work before the store, the
  doomed-work gate denies deadlines the pinned p99 cannot meet, the
  edge sheds scavenger, and budget-aware route-to-pool redirects the
  over-budget interactive tail into the overflow pool.

Determinism: every admission decision depends only on the seeded
schedule, the stores' ManualClock bucket state (fill ≈ 0 → zero
refill), and the harness's latency MODEL (the server's serving
histogram is swapped for one whose p99 the model pins — this process's
wall clock never reaches a gate). Same seed ⇒ bit-for-bit identical
grant/shed/route schedule.

The latency model is the standard load-linear queue stand-in:
``latency = BASE + PER_REQ × (executed requests in the last WINDOW)``.
Admit-gate sheds (edge or server) are answered fast and add NO load —
that asymmetry is the entire mechanism the defense exploits. Settles
ride the streaming lane and are not charged to the serving window.

``make storm-soak SEED=…`` replays any schedule (DRL_STORM_SEED).
"""

from __future__ import annotations

import asyncio
import dataclasses
import json
from collections import deque

from distributedratelimiting.redis_tpu.runtime.admission import (
    PRIORITY_BATCH,
    PRIORITY_INTERACTIVE,
    PRIORITY_SCAVENGER,
    AdmissionPolicy,
    TenantBudget,
)
from distributedratelimiting.redis_tpu.runtime.clock import ManualClock
from distributedratelimiting.redis_tpu.runtime.remote import (
    RemoteBucketStore,
)
from distributedratelimiting.redis_tpu.runtime.server import (
    BucketStoreServer,
)
from distributedratelimiting.redis_tpu.runtime.store import (
    InProcessBucketStore,
)
from distributedratelimiting.redis_tpu.utils import faults
from distributedratelimiting.redis_tpu.utils.metrics import (
    LatencyHistogram,
)

__all__ = ["run_soak", "run_arm", "DEFAULT_SEED"]

DEFAULT_SEED = 20260807

# -- populations --------------------------------------------------------------
N_PRIMARY = 120          # primaries: the goodput we defend
N_STORMERS = 60          # exogenous stormers: timeout < any loaded latency
PRIMARY_TIMEOUT_S = 0.05
STORMER_TIMEOUT_S = 0.01
DEADLINE_S = 0.2
#: Every ``DOOMED_EVERY``-th interactive primary rid carries a deadline
#: no loaded latency can meet — the doomed-work gate's cohort. Scoring
#: excludes them from the goodput denominator (no arm can serve them);
#: what differs across arms is whether tokens are BURNED on them.
DOOMED_EVERY = 16
DOOMED_DEADLINE_S = 0.010

# -- budgets (fill ≈ 0: bucket state is pure seeded consumption) -------------
_FILL = 1e-9
_CHILD_CAP, _CHILD_RATE = 1e6, 1e-9
TENANT_A_CAP = 200.0     # fits its whole primary demand
TENANT_B_CAP = 70.0      # oversubscribed: the route-to-pool tail
STORM_CAP = 100.0        # stormer first attempts all fit
OVERFLOW_POOL = {"pool": "pool:overflow", "ta": 200.0, "tb": _FILL,
                 "priority": PRIORITY_BATCH}

# -- load-linear latency model ------------------------------------------------
BASE_LAT_S = 0.012
PER_REQ_S = 0.0006
WINDOW_S = 0.25
#: Admit-gate sheds answer in this long — fast enough for every client
#: limit in the schedule, so a shed/deny at admit is always TERMINAL.
ADMIT_LAT_S = 0.002


class _PinnedLatency(LatencyHistogram):
    """Serving histogram whose p99 the harness's latency model sets —
    the doomed gate must sense the MODEL, not this process's wall
    clock, for bit-for-bit replay."""

    def __init__(self) -> None:
        super().__init__()
        self.pinned_p99 = 0.0

    @property
    def p99(self) -> float:  # type: ignore[override]
        return self.pinned_p99


def _schedule(seed: int, *, storm: bool):
    """The arm's event list: primaries (always) + stormers (storm arms),
    merged in time order, with the doomed cohort's deadlines rewritten.
    One schedule per (seed, storm) — both storm arms replay the SAME
    offered traffic."""
    events = list(faults.storm_schedule(
        seed, n_requests=N_PRIMARY, tenants=("tenant:a", "tenant:b"),
        priorities=(PRIORITY_INTERACTIVE, PRIORITY_INTERACTIVE,
                    PRIORITY_BATCH, PRIORITY_SCAVENGER),
        client_timeout_s=PRIMARY_TIMEOUT_S, deadline_s=DEADLINE_S))
    doomed = {f"storm-{seed}-{i}" for i in range(0, N_PRIMARY,
                                                 DOOMED_EVERY)}
    events = [dataclasses.replace(e, deadline_s=min(
        e.deadline_s, DOOMED_DEADLINE_S)) if e.rid in doomed else e
        for e in events]
    if storm:
        events += faults.storm_schedule(
            seed + 1, n_requests=N_STORMERS, tenants=("tenant:storm",),
            priorities=(PRIORITY_INTERACTIVE,),
            client_timeout_s=STORMER_TIMEOUT_S, deadline_s=DEADLINE_S)
        events.sort(key=lambda e: (e.t_s, e.rid, e.attempt))
    return events, doomed


async def run_arm(seed: int, *, storm: bool, defended: bool) -> dict:
    """One arm of the soak; returns its outcome schedule + audit."""
    events, doomed = _schedule(seed, storm=storm)
    clock = ManualClock()
    backing = InProcessBucketStore(clock=clock)
    srv = BucketStoreServer(
        backing, overflow_pool=OVERFLOW_POOL if defended else None)
    lat_model = _PinnedLatency()
    srv.serving_latency = lat_model
    if defended:
        srv.set_retry_shed(True)
        srv.set_doomed_gate(True)
    await srv.start()
    client = RemoteBucketStore(address=(srv.host, srv.port),
                               coalesce_requests=False,
                               resilience_seed=seed)
    gw = AdmissionPolicy(
        client, key_config=(_CHILD_CAP, _CHILD_RATE),
        tenants={
            "tenant:a": TenantBudget("tenant:a", TENANT_A_CAP, _FILL),
            "tenant:b": TenantBudget("tenant:b", TENANT_B_CAP, _FILL),
            "tenant:storm": TenantBudget("tenant:storm", STORM_CAP,
                                         _FILL),
        })
    if defended:
        gw.set_shed_level(PRIORITY_SCAVENGER)

    status: dict[str, tuple[str, int]] = {}   # rid -> (state, attempt)
    settled_charges: dict[str, float] = {}    # budget name -> tokens
    executed: deque = deque()                 # executed-event times
    outcomes: list[tuple[str, int, str, int]] = []
    counts = {"granted": 0, "routed": 0, "denied": 0, "duplicate": 0,
              "edge_shed": 0, "retry_shed": 0, "doomed": 0,
              "skipped": 0, "won": 0}
    try:
        for e in events:
            if status.get(e.rid, ("pending", -1))[0] != "pending":
                counts["skipped"] += 1
                continue  # the client already heard an answer
            clock.set_ticks(int(e.t_s * 1024))
            while executed and executed[0] <= e.t_s - WINDOW_S:
                executed.popleft()
            sim_lat = BASE_LAT_S + PER_REQ_S * len(executed)
            shed0, rshed0 = gw.shed, srv.retries_shed
            doomed0 = srv.requests_doomed
            res = await gw.reserve(
                e.tenant, f"{e.tenant}/k{e.cost}", estimate=float(e.cost),
                priority=e.priority, rid=e.rid, ttl_s=3600.0,
                attempt=e.attempt, deadline_s=e.deadline_s)
            if gw.shed > shed0 and srv.retries_shed == rshed0 \
                    and srv.requests_doomed == doomed0:
                outcome, lat, is_exec = "edge_shed", ADMIT_LAT_S, False
            elif srv.retries_shed > rshed0:
                outcome, lat, is_exec = "retry_shed", ADMIT_LAT_S, False
            elif srv.requests_doomed > doomed0:
                outcome, lat, is_exec = "doomed", ADMIT_LAT_S, False
            elif res.routed:
                outcome, lat, is_exec = "routed", sim_lat, True
            elif res.duplicate:
                outcome, lat, is_exec = "duplicate", sim_lat, True
            elif res.granted:
                outcome, lat, is_exec = "granted", sim_lat, True
            else:
                outcome, lat, is_exec = "denied", sim_lat, True
            counts[outcome] += 1
            outcomes.append((e.rid, e.attempt, outcome, len(executed)))
            if is_exec:
                executed.append(e.t_s)
                lat_model.pinned_p99 = sim_lat
            timeout = (STORMER_TIMEOUT_S if e.tenant == "tenant:storm"
                       else PRIMARY_TIMEOUT_S)
            limit = min(timeout, e.deadline_s)
            if lat > limit:
                continue  # too slow: the client never heard this answer
            if res.granted:
                status[e.rid] = ("won", e.attempt)
                counts["won"] += 1
                settle_tenant = res.pool if res.pool else e.tenant
                await gw.settle(e.rid, settle_tenant, float(e.cost),
                                priority=e.priority)
                settled_charges[settle_tenant] = (
                    settled_charges.get(settle_tenant, 0.0)
                    + float(e.cost))
            else:
                status[e.rid] = ("gave_up", e.attempt)

        # Scoring: interactive primary rids outside the doomed cohort,
        # won on their FIRST attempt (acceptance: "first-attempt grants
        # settled before deadline").
        scored = {e.rid for e in events
                  if e.attempt == 0 and e.tenant != "tenant:storm"
                  and e.priority == PRIORITY_INTERACTIVE
                  and e.rid not in doomed}
        goodput = sum(1 for rid in scored
                      if status.get(rid, ("", -1)) == ("won", 0))

        # Differential audit over the store's OWN bucket records
        # (fill ≈ 0 under ManualClock → zero refill; exact):
        #   cap − balance == outstanding + settled − debt, per budget.
        # Settles ran at actual == estimate, so each settle leaves its
        # full charge in the bucket (zero refund) — the harness's
        # settled_charges tally IS the settled term.
        led = srv.reservations
        audit = {}
        for name, cap in (("tenant:a", TENANT_A_CAP),
                          ("tenant:b", TENANT_B_CAP),
                          ("tenant:storm", STORM_CAP),
                          (str(OVERFLOW_POOL["pool"]),
                           float(OVERFLOW_POOL["ta"]))):
            entry = backing._buckets.get((name, cap, _FILL))
            balance = entry[0] if entry is not None else cap
            charged = cap - balance
            held = led.outstanding_by_tenant().get(name, 0.0)
            settled = settled_charges.get(name, 0.0)
            debt = led.debts().get(name, 0.0)
            audit[name] = {"charged": round(charged, 6),
                           "held": round(held, 6),
                           "settled": round(settled, 6),
                           "debt": round(debt, 6),
                           "over_admitted": round(
                               charged - held - settled + debt, 6)}
        return {
            "goodput": goodput,
            "scored": len(scored),
            "counts": counts,
            "outcomes": outcomes,
            "audit": audit,
            "server": {"retries_shed": srv.retries_shed,
                       "requests_doomed": srv.requests_doomed,
                       "reserves_routed": srv.reserves_routed,
                       "retry_attempts_seen": srv.retry_attempts_seen},
        }
    finally:
        await client.aclose()
        await srv.aclose()


async def run_soak(seed: int = DEFAULT_SEED) -> dict:
    """All three arms from one seed; the summary the soak test pins."""
    baseline = await run_arm(seed, storm=False, defended=False)
    naive = await run_arm(seed, storm=True, defended=False)
    defended = await run_arm(seed, storm=True, defended=True)
    base = max(1, baseline["goodput"])
    return {
        "seed": seed,
        "baseline": baseline,
        "naive": naive,
        "defended": defended,
        "naive_ratio": round(naive["goodput"] / base, 4),
        "defended_ratio": round(defended["goodput"] / base, 4),
    }


def main() -> None:
    import argparse

    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("--seed", type=int, default=DEFAULT_SEED)
    args = ap.parse_args()
    out = asyncio.run(run_soak(args.seed))
    for arm in ("baseline", "naive", "defended"):
        out[arm] = {k: v for k, v in out[arm].items()
                    if k != "outcomes"}
    print(json.dumps(out, indent=2, sort_keys=True))


if __name__ == "__main__":
    main()
