"""The 3-region federation soak lane (ISSUE 15; ROADMAP item 5).

Two tracked numbers for the WAN lease ledger
(:mod:`~distributedratelimiting.redis_tpu.runtime.federation`):

- ``local_decision`` — regional decision throughput vs LEASE LENGTH:
  a region decides from its slice at full local speed while renewing
  over the (simulated-WAN) control plane every ``renew_fraction ×
  lease_len``. The claim under test is the paper's whole posture
  lifted to WAN scale: the data plane's rate is INDEPENDENT of the
  lease length — only the control-plane renew rate changes (reported
  per arm as ``renews_per_1k_decisions``).
- ``partition_epsilon`` — partition-window over-admission vs the
  ε(RTT, lease_len) model: one region is fully partitioned for a
  window spanning several lease periods; its admits past its slice
  (the degraded-envelope serving) are measured against
  :func:`federation_epsilon` — the ratio must stay ≤ 1 (the model is
  an upper bound), and > 0 on a non-vacuous run (the envelope DID
  serve — never hard-down).

Usage::

    python -m benchmarks.federation [--seed 20260804] [--smoke]
        [--json] [--evidence]

One JSON row per lane on stdout; ``--evidence`` appends them to
``benchmarks/evidence/federation_r15.jsonl``. ``benchmarks/
recapture.py`` owes this workload a real-device number
(``federation_device``): every row here is a CPU stand-in
(InProcessBucketStore regions)."""

from __future__ import annotations

import argparse
import asyncio
import json
import pathlib
import time

import numpy as np

__all__ = ["run_local_decision", "run_partition_epsilon", "main"]

_ROOT = pathlib.Path(__file__).resolve().parents[1]
EVIDENCE = _ROOT / "benchmarks" / "evidence" / "federation_r15.jsonl"

TENANT = "tenant:g"
#: local_decision lane: an ample global budget — the lane measures
#: mechanism cost, not budget exhaustion.
G_CAP, G_RATE = 1e9, 1e6
#: partition_epsilon lane: a HUMAN-SCALE budget — the lane drives
#: offered load past the envelope to measure the bound itself.
P_CAP, P_RATE = 20_000.0, 0.0


class _Mono:
    def __init__(self) -> None:
        self.t = 0.0

    def __call__(self) -> float:
        return self.t


async def _rig(lease_len_s: float, *, envelope_fraction: float = 0.5,
               g_cap: float = G_CAP, g_rate: float = G_RATE):
    from distributedratelimiting.redis_tpu.runtime.clock import (
        ManualClock,
    )
    from distributedratelimiting.redis_tpu.runtime.federation import (
        RegionFederation,
    )
    from distributedratelimiting.redis_tpu.runtime.store import (
        InProcessBucketStore,
    )

    home_store = InProcessBucketStore(clock=ManualClock())
    home_mono = _Mono()
    led = home_store.federation_ledger(clock=home_mono,
                                       default_ttl_s=lease_len_s)
    region_store = InProcessBucketStore(clock=ManualClock())
    mono = _Mono()
    admitted = [0]
    agent = RegionFederation(
        "bench", led, tenants={TENANT: (g_cap, g_rate)},
        admitted_total=lambda _t: float(admitted[0]),
        ttl_s=lease_len_s, clock=mono,
        envelope_fraction=envelope_fraction)
    await agent.tick()
    return led, home_mono, region_store, mono, agent, admitted


async def run_local_decision(seed: int, lease_len_s: float,
                             n_decisions: int) -> dict:
    """Regional decisions from the slice at full local speed, the
    renew control plane on its lease-length cadence (simulated time:
    one decision advances the region clock by 0.1 ms)."""
    del seed  # the lane is deterministic; the knob is lease_len_s
    led, home_mono, store, mono, agent, admitted = await _rig(
        lease_len_s)
    cfg = agent.slice(TENANT)
    renew_every = lease_len_s * agent.renew_fraction
    next_renew = renew_every
    dt = 1e-4
    t0 = time.perf_counter()
    for _ in range(n_decisions):
        res = await store.acquire(TENANT, 1, cfg[0], cfg[1])
        if res.granted:
            admitted[0] += 1
        mono.t += dt
        home_mono.t += dt
        if mono.t >= next_renew:
            next_renew += renew_every
            await agent.tick()
            cfg = agent.slice(TENANT)
    elapsed = time.perf_counter() - t0
    return {
        "lane": "local_decision",
        "lease_len_s": lease_len_s,
        "decisions": n_decisions,
        "granted": admitted[0],
        "decisions_per_s": round(n_decisions / elapsed, 1),
        "renews": agent.renews,
        "renews_per_1k_decisions": round(
            1000.0 * agent.renews / n_decisions, 3),
        "elapsed_s": round(elapsed, 4),
    }


async def run_partition_epsilon(seed: int, lease_len_s: float,
                                partition_periods: float) -> dict:
    """One region fully partitioned for ``partition_periods`` lease
    lengths: measure its over-admission past the slice against the
    ε(RTT, lease_len) model (an upper bound — ratio ≤ 1)."""
    from distributedratelimiting.redis_tpu.runtime.federation import (
        degraded_config,
        federation_epsilon,
    )

    rng = np.random.default_rng(seed)
    led, home_mono, store, mono, agent, admitted = await _rig(
        lease_len_s, g_cap=P_CAP, g_rate=P_RATE)
    cfg0 = agent.slice(TENANT)
    # Pre-partition traffic: spend a seeded fraction of the slice.
    pre = int(cfg0[0] * float(rng.uniform(0.1, 0.3)))
    for _ in range(pre):
        res = await store.acquire(TENANT, 1, cfg0[0], cfg0[1])
        if res.granted:
            admitted[0] += 1

    class _Down:
        async def lease(self, _p):
            raise ConnectionResetError("partitioned")
        renew = reclaim = lease

    agent.home = _Down()
    window_s = partition_periods * lease_len_s
    slice_at_partition = cfg0
    # Drive the partition window in lease-length steps: the agent
    # degrades at its monotonic expiry, then serves the envelope.
    partition_admits = 0
    steps = max(4, int(partition_periods * 4))
    step_s = window_s / steps
    per_step = int(cfg0[0])   # demand far above the envelope: measure
    #                           the BOUND, not the offered load
    for _ in range(steps):
        mono.t += step_s
        home_mono.t += step_s
        await agent.tick()
        cfg = agent.slice(TENANT)
        for _ in range(per_step):
            res = await store.acquire(TENANT, 1, cfg[0], cfg[1])
            if res.granted:
                admitted[0] += 1
                partition_admits += 1
    env_cap, env_rate = degraded_config(*slice_at_partition)
    over = max(0.0, partition_admits
               - (slice_at_partition[0] - pre)
               - env_rate * window_s)
    eps = federation_epsilon(1, slice_at_partition[0],
                             slice_at_partition[1],
                             lease_len_s * agent.renew_fraction,
                             partition_s=window_s)
    return {
        "lane": "partition_epsilon",
        "lease_len_s": lease_len_s,
        "partition_periods": partition_periods,
        "slice_cap": slice_at_partition[0],
        "pre_partition_admits": pre,
        "partition_admits": partition_admits,
        "envelope_cap": env_cap,
        "degraded_entries": agent.degraded_entries,
        "over_admission": round(over, 1),
        "epsilon_model": round(eps, 1),
        "ratio_vs_model": round(over / eps, 4) if eps > 0 else 0.0,
        "within_model": bool(over <= eps + 1e-6),
    }


def main(argv: "list[str] | None" = None) -> int:
    parser = argparse.ArgumentParser(
        description="3-region federation soak lane (ISSUE 15)")
    parser.add_argument("--seed", type=int, default=20260804)
    parser.add_argument("--smoke", action="store_true",
                        help="tiny sizes (CI wiring check)")
    parser.add_argument("--json", action="store_true")
    parser.add_argument("--evidence", action="store_true",
                        help=f"append rows to {EVIDENCE}")
    args = parser.parse_args(argv)

    n = 2_000 if args.smoke else 100_000
    lease_lens = ((2.0,) if args.smoke else (2.0, 5.0, 10.0, 30.0))
    rows = []
    for ll in lease_lens:
        rows.append(asyncio.run(run_local_decision(args.seed, ll, n)))
    for periods in ((2.5,) if args.smoke else (2.5, 4.0)):
        rows.append(asyncio.run(run_partition_epsilon(
            args.seed, lease_lens[0], periods)))
    ok = all(r.get("within_model", True) for r in rows)
    for row in rows:
        row["seed"] = args.seed
        row["backend"] = "cpu_standin"
        print(json.dumps(row), flush=True)
        if args.evidence:
            EVIDENCE.parent.mkdir(parents=True, exist_ok=True)
            with EVIDENCE.open("a", encoding="utf-8") as f:
                f.write(json.dumps(row) + "\n")
    if not args.json:
        print("OK: partition over-admission within the "
              "epsilon(RTT, lease_len) model" if ok else
              "FAIL: over-admission exceeded the epsilon model")
    return 0 if ok else 1


if __name__ == "__main__":
    raise SystemExit(main())
