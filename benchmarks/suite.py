"""The five BASELINE benchmark configurations (BASELINE.md "configs").

1. ``single_bucket_cpu``      — TestApp-style single token bucket, pure-CPU
                                store, one op per call (the Redis-class
                                baseline the reference's exact limiter is
                                architecturally bound to).
2. ``partitioned_10k_uniform``— PartitionedRateLimiter over strings, 10K
                                keys uniform, end-to-end asyncio micro-batch
                                path against the device store.
3. ``approximate_1m_zipf``    — 1M keys with Zipf(1.1) hot-key skew: the
                                device scan kernel with in-batch duplicate
                                serialization ON (hot keys collide inside
                                every batch), plus the approximate
                                limiter's local hot-path decision rate (its
                                decisions never leave the host — that IS
                                the algorithm, SURVEY.md invariant 6).
4. ``sliding_window_10m_bursty`` — 10M-slot sliding-window table, bursty
                                Poisson batch occupancy, scanned dispatch.
5. ``two_level_mesh``         — key-sharded two-level step (acquire + psum
                                global tier) over a mesh of ALL visible
                                devices (8 virtual CPU devices in tests,
                                real chips under TPU).

Every config prints ONE JSON line:
``{"config": ..., "metric": ..., "value": ..., "unit": ...}`` plus
config-specific extras. Sizes shrink under ``--smoke`` so the full suite
exercises identical code paths in seconds (tests/test_benchmarks.py).
"""

from __future__ import annotations

import argparse
import asyncio
import json
import sys
import time


def _zipf_slots(rng, n_slots: int, shape, a: float = 1.1):
    """Zipf(a) ranks mapped onto the slot space: rank r → slot r-1, tail
    clipped into the table. Hot slots repeat heavily inside each batch."""
    z = rng.zipf(a, shape)
    return ((z - 1) % n_slots).astype("int32")


def bench_single_bucket_cpu(smoke: bool = False) -> dict:
    """Config 1 — the reference's deployment class: one bucket, one store
    op per acquire, no batching (TestApp/Program.cs:8-22 semantics)."""
    from distributedratelimiting.redis_tpu.models.options import (
        TokenBucketOptions,
    )
    from distributedratelimiting.redis_tpu.models.token_bucket import (
        TokenBucketRateLimiter,
    )
    from distributedratelimiting.redis_tpu.runtime.store import (
        InProcessBucketStore,
    )

    n = 2_000 if smoke else 200_000
    lim = TokenBucketRateLimiter(
        TokenBucketOptions(token_limit=1 << 30, tokens_per_period=1 << 30,
                           instance_name="cfg1"),
        InProcessBucketStore(),
    )
    for _ in range(100):  # warm dict/code paths
        lim.acquire(1)
    t0 = time.perf_counter()
    for _ in range(n):
        lim.acquire(1)
    dt = time.perf_counter() - t0
    return {
        "config": "single_bucket_cpu",
        "metric": "decisions_per_sec",
        "value": round(n / dt),
        "unit": "decisions/s",
        "store": "in-process (Redis-class, one op per call)",
    }


def bench_partitioned_10k_uniform(smoke: bool = False) -> dict:
    """Config 2 — 10K keys uniform through the full asyncio micro-batched
    serving path (closed-loop worker pool)."""
    from distributedratelimiting.redis_tpu.models.options import (
        TokenBucketOptions,
    )
    from distributedratelimiting.redis_tpu.models.partitioned import (
        PartitionedRateLimiter,
    )
    from distributedratelimiting.redis_tpu.runtime.store import (
        DeviceBucketStore,
    )

    n_keys = 256 if smoke else 10_000
    workers = 256 if smoke else 8192
    reqs_per_worker = 2 if smoke else 4

    async def main():
        store = DeviceBucketStore(
            n_slots=1 << (10 if smoke else 15), max_batch=4096,
            max_delay_s=300e-6, max_inflight=16,
        )
        lim = PartitionedRateLimiter(
            TokenBucketOptions(token_limit=1 << 30,
                               tokens_per_period=1 << 30,
                               instance_name="cfg2"),
            store,
        )
        lat: list[float] = []

        async def worker(w):
            for j in range(reqs_per_worker):
                t0 = time.perf_counter()
                await lim.acquire_async(f"user{(w * 31 + j) % n_keys}", 1)
                lat.append(time.perf_counter() - t0)

        await asyncio.gather(*(worker(w) for w in range(min(workers, 512))))
        lat.clear()
        t0 = time.perf_counter()
        await asyncio.gather(*(worker(w) for w in range(workers)))
        dt = time.perf_counter() - t0
        throughput = len(lat) / dt
        lat.sort()
        p99 = lat[int(len(lat) * 0.99)]
        await store.aclose()
        return throughput, p99

    throughput, p99 = asyncio.run(main())
    return {
        "config": "partitioned_10k_uniform",
        "metric": "decisions_per_sec",
        "value": round(throughput),
        "unit": "decisions/s",
        "n_keys": n_keys,
        "p99_ms": round(p99 * 1e3, 3),
    }


def bench_approximate_1m_zipf(smoke: bool = False) -> dict:
    """Config 3 — Zipf(1.1) hot-key skew at 1M keys. Two measurements:
    the device scan kernel with duplicate serialization on (hot keys
    collide inside every batch), and the approximate limiter's local
    decision rate (its hot path never touches the store — invariant 6)."""
    import jax
    import numpy as np
    import jax.numpy as jnp

    from distributedratelimiting.redis_tpu.models.approximate import (
        ApproximateTokenBucketRateLimiter,
    )
    from distributedratelimiting.redis_tpu.models.options import (
        ApproximateTokenBucketOptions,
    )
    from distributedratelimiting.redis_tpu.ops import kernels as K
    from distributedratelimiting.redis_tpu.runtime.store import (
        InProcessBucketStore,
    )

    n_slots = 1 << (12 if smoke else 20)
    batch = 512 if smoke else 8192
    scan_k = 4 if smoke else 16
    iters = 2 if smoke else 30
    rng = np.random.default_rng(3)

    state = K.init_bucket_state(n_slots)
    cap = jnp.float32(1e9)
    rate = jnp.float32(1.0)

    def stage(i):
        # Host-side numpy staging: the timed loop pays the host→device
        # transfer per dispatch, as production serving does (and as
        # bench.py's headline measures).
        slots = _zipf_slots(rng, n_slots, (scan_k, batch))
        counts = np.ones((scan_k, batch), np.uint8)
        nows = np.arange(scan_k, dtype=np.int32) + 1 + i * scan_k
        return slots, counts, nows

    def dispatch(state, arrays):
        slots, counts, nows = arrays
        return K.acquire_scan_compact(
            state, jnp.asarray(slots), jnp.asarray(counts),
            jnp.asarray(nows), cap, rate, handle_duplicates=True)

    staged = [stage(i) for i in range(4)]
    state, granted, _ = dispatch(state, staged[0])
    jax.block_until_ready(granted)
    t0 = time.perf_counter()
    for i in range(iters):
        state, granted, _ = dispatch(state, staged[i % 4])
    jax.block_until_ready(granted)
    device_rate = iters * scan_k * batch / (time.perf_counter() - t0)

    # Local hot path: pure in-memory decisions (the approximate design's
    # point — zero store round-trips between syncs).
    lim = ApproximateTokenBucketRateLimiter(
        ApproximateTokenBucketOptions(token_limit=1 << 30,
                                      tokens_per_period=1 << 30,
                                      instance_name="cfg3"),
        InProcessBucketStore(),
    )
    n_local = 2_000 if smoke else 300_000
    for _ in range(100):
        lim.acquire(1)
    t0 = time.perf_counter()
    for _ in range(n_local):
        lim.acquire(1)
    local_rate = n_local / (time.perf_counter() - t0)

    # Vectorized local bulk admission: one numpy pass decides a whole
    # batch against the same availability formula.
    n_bulk = 10_000 if smoke else 2_000_000
    ones = np.ones(n_bulk, np.int64)
    lim.acquire_many(ones[:100])
    t0 = time.perf_counter()
    lim.acquire_many(ones)
    local_bulk_rate = n_bulk / (time.perf_counter() - t0)

    return {
        "config": "approximate_1m_zipf",
        "metric": "device_decisions_per_sec",
        "value": round(device_rate),
        "unit": "decisions/s",
        "n_keys": n_slots,
        "zipf_a": 1.1,
        "duplicate_serialization": True,
        "local_hot_path_decisions_per_sec": round(local_rate),
        "local_bulk_decisions_per_sec": round(local_bulk_rate),
    }


def bench_sliding_window_10m_bursty(smoke: bool = False) -> dict:
    """Config 4 — sliding-window counters at 10M keys under bursty Poisson
    arrivals: per-scanned-batch occupancy ~ Poisson alternating between a
    high and a low rate (bursts), invalid rows masked."""
    import jax
    import numpy as np
    import jax.numpy as jnp

    from distributedratelimiting.redis_tpu.ops import kernels as K

    n_slots = 4096 if smoke else 10_000_000
    batch = 512 if smoke else 8192
    scan_k = 4 if smoke else 16
    iters = 2 if smoke else 30
    rng = np.random.default_rng(4)

    state = K.init_window_state(n_slots)
    limit = jnp.float32(100.0)
    window = jnp.int32(1024)  # 1s of ticks

    def stage(i):
        slots = rng.integers(0, n_slots, (scan_k, batch)).astype(np.int32)
        counts = np.ones((scan_k, batch), np.uint8)
        # Bursty: batch occupancy ~ Poisson(0.9·B) in bursts, Poisson(0.2·B)
        # between bursts — arrival gaps become padding rows (slot = -1) in
        # the fixed-shape compact layout.
        lam = batch * (0.9 if (i % 4) < 2 else 0.2)
        occ = np.minimum(rng.poisson(lam, scan_k), batch)
        slots[np.arange(batch)[None, :] >= occ[:, None]] = -1
        nows = np.arange(scan_k, dtype=np.int32) * 37 + 1 + i * scan_k * 37
        return (slots, counts, nows), int(occ.sum())

    def dispatch(state, arrays):
        slots, counts, nows = arrays  # np staged; transfer paid in-loop
        return K.window_acquire_scan_compact(
            state, jnp.asarray(slots), jnp.asarray(counts),
            jnp.asarray(nows), limit, window, handle_duplicates=False)

    staged = [stage(i) for i in range(4)]
    (arrays, _) = staged[0]
    state, granted, _ = dispatch(state, arrays)
    jax.block_until_ready(granted)
    decided = 0
    t0 = time.perf_counter()
    for i in range(iters):
        arrays, occ = staged[i % 4]
        state, granted, _ = dispatch(state, arrays)
        decided += occ
    jax.block_until_ready(granted)
    dt = time.perf_counter() - t0

    # The same workload against the MESH store: keyed sliding windows
    # sharded over every visible device (ShardedWindowStore — the serving
    # path MeshBucketStore.window_acquire rides), end-to-end with string
    # keys, routing, and per-shard directories.
    from distributedratelimiting.redis_tpu.parallel.mesh import create_mesh
    from distributedratelimiting.redis_tpu.parallel.sharded_store import (
        ShardedWindowStore,
    )

    mesh = create_mesh(len(jax.devices()))
    ws = ShardedWindowStore(
        mesh, limit=100.0, window_sec=1.0,
        per_shard_slots=1 << (10 if smoke else 17))
    pool = [f"wkey{i}" for i in range(2_000 if smoke else 500_000)]
    n_bulk = 1 << (10 if smoke else 17)
    calls = [[pool[j] for j in rng.integers(0, len(pool), n_bulk)]
             for _ in range(3)]
    ones = [1] * n_bulk
    ws.acquire_many_blocking(calls[0], ones, with_remaining=False)  # warm
    t0 = time.perf_counter()
    served = 0
    for c in calls:
        served += len(ws.acquire_many_blocking(c, ones,
                                               with_remaining=False))
    mesh_rate = served / (time.perf_counter() - t0)

    return {
        "config": "sliding_window_10m_bursty",
        "metric": "decisions_per_sec",
        "value": round(decided / dt),
        "unit": "decisions/s",
        "n_keys": n_slots,
        "arrivals": "poisson bursts (0.9B/0.2B alternating)",
        "mesh_window_serving_decisions_per_sec": round(mesh_rate),
        "mesh_window_devices": mesh.devices.size,
    }


def bench_two_level_mesh(smoke: bool = False) -> dict:
    """Config 5 — the fused two-level step (sharded acquire + psum global
    tier) over a mesh of every visible device."""
    import jax
    import numpy as np
    import jax.numpy as jnp
    from jax.sharding import NamedSharding, PartitionSpec as P

    from distributedratelimiting.redis_tpu.ops import kernels as K
    from distributedratelimiting.redis_tpu.parallel.mesh import (
        SHARD_AXIS,
        create_mesh,
    )
    from distributedratelimiting.redis_tpu.parallel.sharded_store import (
        init_global_counter,
        make_two_level_scan_step,
    )

    n_dev = len(jax.devices())
    mesh = create_mesh(n_dev)
    per_shard = 1 << (10 if smoke else 20)  # ≈ 10M total keys at 8 chips full
    b_local = 256 if smoke else 8192
    scan_k = 2 if smoke else 16
    iters = 4 if smoke else 40
    rng = np.random.default_rng(5)

    sharding = NamedSharding(mesh, P(SHARD_AXIS))
    state = K.BucketState(
        tokens=jax.device_put(jnp.zeros((n_dev * per_shard,), jnp.float32), sharding),
        last_ts=jax.device_put(jnp.zeros((n_dev * per_shard,), jnp.int32), sharding),
        exists=jax.device_put(jnp.zeros((n_dev * per_shard,), bool), sharding),
    )
    gcounter = jax.device_put(init_global_counter(), NamedSharding(mesh, P()))
    step = make_two_level_scan_step(mesh, handle_duplicates=False)

    def stage():
        # numpy staging — the timed loop pays the host→device transfers.
        slots = rng.integers(0, per_shard,
                             (n_dev, scan_k, b_local)).astype(np.int32)
        counts = np.ones((n_dev, scan_k, b_local), np.int32)
        valid = np.ones((n_dev, scan_k, b_local), bool)
        return slots, counts, valid

    staged = [stage() for _ in range(4)]
    cap = jnp.float32(1e9)
    rate = jnp.float32(1.0)
    decay = jnp.float32(1.0)

    def dispatch(state, gcounter, arrays, base):
        slots, counts, valid = arrays
        nows = np.arange(scan_k, dtype=np.int32) + base
        return step(state, jnp.asarray(slots), jnp.asarray(counts),
                    jnp.asarray(valid), jnp.asarray(nows), cap, rate,
                    gcounter, decay)

    state, granted, _, gcounter = dispatch(state, gcounter, staged[0], 1)
    jax.block_until_ready(granted)
    t0 = time.perf_counter()
    for i in range(iters):
        state, granted, _, gcounter = dispatch(
            state, gcounter, staged[i % 4], (i + 1) * scan_k + 1)
    jax.block_until_ready(granted)
    dt = time.perf_counter() - t0

    # End-to-end bulk SERVING path on the same mesh: string keys through
    # ShardedDeviceStore.acquire_many_blocking (vectorized routing +
    # per-shard native resolve + scanned two-level launches + readback).
    from distributedratelimiting.redis_tpu.parallel.sharded_store import (
        ShardedDeviceStore,
    )

    store = ShardedDeviceStore(
        mesh, capacity=1e9, fill_rate_per_sec=1.0,
        per_shard_slots=1 << (10 if smoke else 17))
    pool = [f"user{i}" for i in range(2_000 if smoke else 500_000)]
    n_bulk = 1 << (10 if smoke else 17)
    calls = [[pool[j] for j in rng.integers(0, len(pool), n_bulk)]
             for _ in range(3)]
    ones = [1] * n_bulk
    store.acquire_many_blocking(calls[0], ones, with_remaining=False)  # warm
    t0 = time.perf_counter()
    served = 0
    for c in calls:
        served += len(store.acquire_many_blocking(c, ones,
                                                  with_remaining=False))
    bulk_rate = served / (time.perf_counter() - t0)

    return {
        "config": "two_level_mesh",
        "metric": "aggregate_decisions_per_sec",
        "value": round(iters * n_dev * scan_k * b_local / dt),
        "unit": "decisions/s",
        "n_devices": n_dev,
        "scan_depth": scan_k,
        "n_keys": n_dev * per_shard,
        "global_score_after": float(np.asarray(gcounter.value)),
        "bulk_serving_decisions_per_sec": round(bulk_rate),
    }


def bench_psum_cadence(smoke: bool = False) -> dict:
    """Ablation (SURVEY.md §7 "Two-level sync cadence"): per-BATCH psum
    (one collective per scanned batch — the fused two-level step) vs
    per-LAUNCH psum (one collective after K batches — the reference's
    per-period sync posture). Grant decisions are identical; the trade is
    collective count vs global-counter staleness (bounded by one launch's
    wall time, ≙ the reference's staleness ≤ ReplenishmentPeriod)."""
    import jax
    import numpy as np
    import jax.numpy as jnp
    from jax.sharding import NamedSharding, PartitionSpec as P

    from distributedratelimiting.redis_tpu.ops import kernels as K
    from distributedratelimiting.redis_tpu.parallel.mesh import (
        SHARD_AXIS,
        create_mesh,
    )
    from distributedratelimiting.redis_tpu.parallel.sharded_store import (
        init_global_counter,
        make_two_level_scan_step,
        make_two_level_scan_step_deferred,
    )

    n_dev = len(jax.devices())
    mesh = create_mesh(n_dev)
    per_shard = 1 << (10 if smoke else 18)
    b_local = 256 if smoke else 8192
    scan_k = 4 if smoke else 16
    iters = 4 if smoke else 40
    rng = np.random.default_rng(6)
    sharding = NamedSharding(mesh, P(SHARD_AXIS))

    def fresh():
        state = K.BucketState(
            tokens=jax.device_put(
                jnp.zeros((n_dev * per_shard,), jnp.float32), sharding),
            last_ts=jax.device_put(
                jnp.zeros((n_dev * per_shard,), jnp.int32), sharding),
            exists=jax.device_put(
                jnp.zeros((n_dev * per_shard,), bool), sharding),
        )
        return state, jax.device_put(init_global_counter(),
                                     NamedSharding(mesh, P()))

    staged = [
        (rng.integers(0, per_shard,
                      (n_dev, scan_k, b_local)).astype(np.int32),
         np.ones((n_dev, scan_k, b_local), np.int32),
         np.ones((n_dev, scan_k, b_local), bool))
        for _ in range(4)
    ]
    cap, rate, decay = jnp.float32(1e9), jnp.float32(1.0), jnp.float32(1.0)

    out = {"config": "psum_cadence", "metric": "aggregate_decisions_per_sec",
           "unit": "decisions/s", "n_devices": n_dev, "scan_depth": scan_k}
    grants, gvals = {}, {}
    for name, factory in (
        ("per_batch", make_two_level_scan_step),
        ("per_launch", make_two_level_scan_step_deferred),
    ):
        step = factory(mesh, handle_duplicates=False)
        state, g = fresh()

        def dispatch(state, g, arrays, base):
            slots, counts, valid = arrays
            nows = np.arange(scan_k, dtype=np.int32) + base
            return step(state, jnp.asarray(slots), jnp.asarray(counts),
                        jnp.asarray(valid), jnp.asarray(nows), cap, rate,
                        g, decay)

        state, granted, _, g = dispatch(state, g, staged[0], 1)
        jax.block_until_ready(granted)
        grants[name] = np.asarray(granted).copy()
        t0 = time.perf_counter()
        for i in range(iters):
            state, granted, _, g = dispatch(
                state, g, staged[i % 4], (i + 1) * scan_k + 1)
        jax.block_until_ready(granted)
        dt = time.perf_counter() - t0
        out[f"{name}_decisions_per_sec"] = round(
            iters * n_dev * scan_k * b_local / dt)
        gvals[name] = float(np.asarray(g.value))
    # Decisions are cadence-independent (the acquire path reads no global
    # state inside a launch); counters differ only by decay granularity.
    assert np.array_equal(grants["per_batch"], grants["per_launch"])
    out["value"] = out["per_batch_decisions_per_sec"]
    out["global_counter_per_batch"] = gvals["per_batch"]
    out["global_counter_per_launch"] = gvals["per_launch"]
    return out


def bench_cluster_bulk(smoke: bool = False) -> dict:
    """Cluster scale-out: bulk decisions through N shared-nothing store
    servers over localhost TCP, keys crc32-routed client-side
    (`ClusterBucketStore`) — per-node sub-batches fan out concurrently,
    so the aggregate rides N servers' pipelines."""
    import numpy as np

    from distributedratelimiting.redis_tpu.runtime.cluster import (
        ClusterBucketStore,
    )
    from distributedratelimiting.redis_tpu.runtime.server import (
        BucketStoreServer,
    )
    from distributedratelimiting.redis_tpu.runtime.store import (
        DeviceBucketStore,
    )

    n_nodes = 2 if smoke else 3
    n = 1 << (10 if smoke else 16)
    calls = 2 if smoke else 4

    async def main():
        backings = [DeviceBucketStore(n_slots=1 << (10 if smoke else 18),
                                      max_batch=4096)
                    for _ in range(n_nodes)]
        servers = []
        for b in backings:
            srv = BucketStoreServer(b)
            await srv.start()
            servers.append(srv)
        store = ClusterBucketStore(
            addresses=[(s.host, s.port) for s in servers])
        rng = np.random.default_rng(5)
        pool = [f"user{i}" for i in range(200_000)]
        batches = [[pool[j] for j in rng.integers(0, len(pool), n)]
                   for _ in range(calls)]
        counts = [1] * n
        # Warm the exact shapes (connect + compile on every node).
        await asyncio.gather(*(store.acquire_many(
            b, counts, 1e7, 1e7, with_remaining=False) for b in batches))
        t0 = time.perf_counter()
        await asyncio.gather(*(store.acquire_many(
            b, counts, 1e7, 1e7, with_remaining=False) for b in batches))
        dt = time.perf_counter() - t0
        rate = calls * n / dt
        await store.aclose()
        for s in servers:
            await s.aclose()
        for b in backings:
            await b.aclose()
        return rate

    rate = asyncio.run(main())
    return {
        "config": "cluster_bulk",
        "metric": "decisions_per_sec",
        "value": round(rate),
        "unit": "decisions/s",
        "n_nodes": n_nodes,
        "keys_per_call": n,
    }


def bench_fp_directory(smoke: bool = False) -> dict:
    """Device-resident directory vs host directory: the same bulk
    workload through `FingerprintBucketStore` (in-kernel probe/insert on
    fingerprints; host duty = one hashing pass) and `DeviceBucketStore`
    (native host directory + packed slot operands). Reports both so the
    trade (operand bytes vs host work — docs/DESIGN.md §5b) stays
    measured, not asserted."""
    import numpy as np

    from distributedratelimiting.redis_tpu.runtime.fp_store import (
        FingerprintBucketStore,
    )
    from distributedratelimiting.redis_tpu.runtime.store import (
        DeviceBucketStore,
    )

    n = 1 << (10 if smoke else 17)
    n_slots = 1 << (12 if smoke else 21)
    calls = 2 if smoke else 4

    def run_store(store) -> float:
        rng = np.random.default_rng(9)
        pool = [f"user{i}" for i in range(500_000)]
        batches = [[pool[j] for j in rng.integers(0, len(pool), n)]
                   for _ in range(calls)]
        counts = [1] * n
        for b in batches:  # warm: insert pass + compile at exact shapes
            store.acquire_many_blocking(b, counts, 1e7, 1e7,
                                        with_remaining=False)
        t0 = time.perf_counter()
        for b in batches:
            store.acquire_many_blocking(b, counts, 1e7, 1e7,
                                        with_remaining=False)
        return calls * n / (time.perf_counter() - t0)

    fp_store = FingerprintBucketStore(n_slots=n_slots)
    fp_rate = run_store(fp_store)
    asyncio.run(fp_store.aclose())
    host_store = DeviceBucketStore(n_slots=n_slots)
    host_rate = run_store(host_store)
    asyncio.run(host_store.aclose())
    return {
        "config": "fp_directory",
        "metric": "decisions_per_sec",
        "value": round(fp_rate),
        "unit": "decisions/s",
        "host_directory_decisions_per_sec": round(host_rate),
        "keys_per_call": n,
        "n_slots": n_slots,
    }


def bench_fp_mesh(smoke: bool = False) -> dict:
    """Mesh-sharded fingerprint tier: bulk decisions through
    `ShardedFpDeviceStore` over every visible device — in-kernel
    probe/insert per shard, fingerprint-as-route, psum global tier.
    The fp analogue of `two_level_mesh`."""
    import numpy as np

    from distributedratelimiting.redis_tpu.parallel.fp_sharded import (
        ShardedFpDeviceStore,
    )
    from distributedratelimiting.redis_tpu.parallel.mesh import create_mesh

    import jax

    mesh = create_mesh(len(jax.devices()))
    n = 1 << (10 if smoke else 16)
    calls = 2 if smoke else 4
    # Provision by TOTAL slot budget (2^19 ≈ 3.6× the ~145K unique keys
    # the workload draws), not per-shard: the r05 TPU run of the old
    # per-shard=2^16 config on a 1-device mesh left the table 8× under
    # water — permanent window pressure, sweep+grow cycles inside the
    # timed loop, 14.6K dec/s (RESULTS.md r05 suite table).
    total_slots = 1 << (11 if smoke else 19)
    store = ShardedFpDeviceStore(
        mesh, capacity=1e9, fill_rate_per_sec=1.0,
        per_shard_slots=max(256, total_slots // mesh.devices.size),
        batch=128 if smoke else 2048)
    rng = np.random.default_rng(13)
    pool = [f"user{i}" for i in range(200_000)]
    batches = [[pool[j] for j in rng.integers(0, len(pool), n)]
               for _ in range(calls)]
    counts = [1] * n
    for b in batches:  # warm: inserts + compile at exact shapes
        store.acquire_many_blocking(b, counts, with_remaining=False)
    t0 = time.perf_counter()
    for b in batches:
        store.acquire_many_blocking(b, counts, with_remaining=False)
    rate = calls * n / (time.perf_counter() - t0)
    return {
        "config": "fp_mesh",
        "metric": "decisions_per_sec",
        "value": round(rate),
        "unit": "decisions/s",
        "n_devices": mesh.devices.size,
        "keys_per_call": n,
        "global_score": store.global_score,
    }


CONFIGS = {
    "single_bucket_cpu": bench_single_bucket_cpu,
    "partitioned_10k_uniform": bench_partitioned_10k_uniform,
    "approximate_1m_zipf": bench_approximate_1m_zipf,
    "sliding_window_10m_bursty": bench_sliding_window_10m_bursty,
    "two_level_mesh": bench_two_level_mesh,
    "psum_cadence": bench_psum_cadence,
    "cluster_bulk": bench_cluster_bulk,
    "fp_directory": bench_fp_directory,
    "fp_mesh": bench_fp_mesh,
}


def main(argv: list[str] | None = None) -> int:
    from distributedratelimiting.redis_tpu.utils.cpu_bootstrap import (
        maybe_force_cpu_from_env,
    )

    maybe_force_cpu_from_env()
    parser = argparse.ArgumentParser(description=__doc__)
    parser.add_argument("configs", nargs="*",
                        help=f"subset of configs to run (default: all); "
                             f"choices: {', '.join(CONFIGS)}")
    parser.add_argument("--smoke", action="store_true",
                        help="tiny sizes — exercise code paths, not perf")
    args = parser.parse_args(argv)
    unknown = [c for c in args.configs if c not in CONFIGS]
    if unknown:
        parser.error(f"unknown config(s): {', '.join(unknown)}")
    names = args.configs or list(CONFIGS)
    for name in names:
        result = CONFIGS[name](smoke=args.smoke)
        print(json.dumps(result), flush=True)
    return 0


if __name__ == "__main__":
    sys.exit(main())
