"""The LLM-serving workload benchmark: Zipf tenants × log-normal token
costs × mixed priorities (ISSUE 10; ROADMAP item 2).

Production LLM gateways limit by token budget with wildly heavy-tailed
cost-per-request ("Token-Budget-Aware Pool Routing", "TokenScale" —
PAPERS.md). This benchmark makes that scenario a TRACKED number: one
seeded workload (tenant popularity Zipf(s), costs LogNormal(μ, σ)
clamped to [1, max_cost], priorities mixed 60/30/10) driven through the
serving lanes, reporting rows/s AND tokens/s per lane:

- ``inprocess``      — the serial in-memory store, flat vs hierarchical
                       (two-level) per-row cost; the hierarchical path
                       must stay ≤ 2× the flat path per row (the
                       acceptance ratio — one extra bucket touch).
- ``remote_scalar``  — one OP_ACQUIRE_H frame per row over TCP.
- ``asyncio_bulk``   — HBUCKET ACQUIRE_MANY frames (one per tenant
                       flush) on the asyncio server.
- ``native_bulk``    — the same frames against the native front-end
                       (the tenant extension rides its Python
                       passthrough lane today — the number is the
                       honest current cost, not the C fast lane's).

Usage::

    python -m benchmarks.llm_workload [--rows 40000] [--seed 20260804]
        [--lanes inprocess,remote_scalar,...] [--smoke] [--json]

- ``reservations``    — the estimate-reserve-settle lane (ISSUE 13):
                       every row reserves at ``estimate = actual ×
                       LogNormal(0, σ)``, streams, then settles the
                       actual — reporting SETTLED-token throughput,
                       refund/debt ratios, and two audits on a
                       zero-fill arm: the differential bound (settled
                       tokens ≤ oracle + debt + epsilon, the oracle
                       being the same schedule with a perfect
                       estimator) and the ≤1%% net-drift
                       reconciliation (store-observed spend vs settled
                       − outstanding debt).

One JSON row per lane on stdout; ``--evidence`` appends them to
``benchmarks/evidence/llm_workload.jsonl`` (the reservations lane also
appends to ``benchmarks/evidence/llm_reservations.jsonl``).
``benchmarks/recapture.py`` owes this workload a real-device number
(``llm_workload_device``) and the reservation lane another
(``llm_reservations_device``)."""

from __future__ import annotations

import argparse
import asyncio
import json
import pathlib
import time

import numpy as np

__all__ = ["gen_workload", "run_lane", "LANES", "main"]

_ROOT = pathlib.Path(__file__).resolve().parents[1]
EVIDENCE = _ROOT / "benchmarks" / "evidence" / "llm_workload.jsonl"
EVIDENCE_RESERVATIONS = (_ROOT / "benchmarks" / "evidence"
                         / "llm_reservations.jsonl")

#: Workload shape defaults (the tracked scenario's identity — change
#: them and the numbers stop being comparable across rounds).
N_TENANTS = 64
ZIPF_S = 1.2
LOGN_MU, LOGN_SIGMA = 4.0, 1.3   # median ~55 tokens, heavy tail
MAX_COST = 8192
TENANT_CAP = 5e6                 # tokens; budgets refill fast enough
TENANT_RATE = 1e5                # that the bench measures THROUGHPUT,
CHILD_CAP, CHILD_RATE = 1e6, 1e5  # not denial handling
PRIORITY_MIX = (0.6, 0.3, 0.1)   # interactive / batch / scavenger


def gen_workload(seed: int, n_rows: int):
    """Returns ``(tenants i64[n], keys list, costs i64[n], prios
    i8[n])`` — tenant ids Zipf-ranked, per-tenant user keys, log-normal
    token costs, mixed priorities."""
    rng = np.random.default_rng(seed)
    t_idx = rng.zipf(ZIPF_S, n_rows) % N_TENANTS
    costs = np.minimum(
        np.maximum(rng.lognormal(LOGN_MU, LOGN_SIGMA, n_rows), 1.0),
        MAX_COST).astype(np.int64)
    u = rng.random(n_rows)
    prios = np.where(u < PRIORITY_MIX[0], 0,
                     np.where(u < PRIORITY_MIX[0] + PRIORITY_MIX[1],
                              1, 2)).astype(np.int8)
    keys = [f"t{t}/u{rng.zipf(1.5) % 200}" for t in t_idx]
    tenants = [f"tenant:{t}" for t in t_idx]
    return tenants, keys, costs, prios


def _rate_row(lane: str, n: int, tokens: int, dt: float,
              extra: "dict | None" = None) -> dict:
    row = {
        "bench": "llm_workload", "lane": lane, "rows": n,
        "rows_per_sec": round(n / dt),
        "tokens_per_sec": round(tokens / dt),
        "wall_s": round(dt, 4),
    }
    if extra:
        row.update(extra)
    return row


#: Coalescing window for the bulk lanes: a gateway accumulates this many
#: rows, then flushes one HBUCKET frame per tenant present (the
#: client-side MicroBatcher shape, spelled out so the bench is
#: deterministic).
FLUSH_WINDOW = 2048

#: Acceptance budget for the in-process lane: hierarchical admission
#: may cost at most this multiple of flat per row. The lane reruns its
#: ABBA arms (bounded) while the measured ratio sits above this — the
#: wall-clock-flake guard; tests/test_benchmarks.py pins the same
#: number.
HIER_RATIO_BUDGET = 2.0


def _tenant_batches(tenants) -> list[list[int]]:
    """Row-index batches: within each FLUSH_WINDOW window, one batch
    per tenant (row order preserved inside a batch)."""
    batches: list[list[int]] = []
    for s in range(0, len(tenants), FLUSH_WINDOW):
        by_tenant: dict[str, list[int]] = {}
        for i in range(s, min(s + FLUSH_WINDOW, len(tenants))):
            by_tenant.setdefault(tenants[i], []).append(i)
        batches.extend(by_tenant.values())
    return batches


# -- lanes -------------------------------------------------------------------

def lane_inprocess(tenants, keys, costs, prios) -> dict:
    """Flat vs hierarchical per-row cost on the serial in-memory store
    — the acceptance ratio (hier ≤ 2× flat per row). ABBA-interleaved
    best-of-3 arms (the serving_metrics_overhead discipline): machine
    noise hits both paths, the MIN of each is the structural cost."""
    from distributedratelimiting.redis_tpu.runtime.store import (
        InProcessBucketStore,
    )

    n = len(keys)
    counts = costs.tolist()

    def run_flat() -> float:
        st = InProcessBucketStore()
        acquire = st.acquire_blocking
        t0 = time.perf_counter()
        for k, c in zip(keys, counts):
            acquire(k, c, CHILD_CAP, CHILD_RATE)
        return time.perf_counter() - t0

    last_res = None

    def run_hier() -> float:
        nonlocal last_res
        st = InProcessBucketStore()
        t0 = time.perf_counter()
        last_res = st.acquire_hierarchical_many_blocking(
            tenants, keys, counts, TENANT_CAP, TENANT_RATE, CHILD_CAP,
            CHILD_RATE)
        return time.perf_counter() - t0

    run_flat(), run_hier()  # warm (dict growth, bytecode)
    flats, hiers = [], []
    # Best-of-N with a retry-tolerant tail: the first 3 ABBA arms are
    # the structural measurement; if the min-of-mins ratio still sits
    # over the acceptance budget, the measurement — not the code — is
    # the likely culprit (one GC pause or a noisy CI neighbor in every
    # hier arm), so run up to 3 more ABBA arms keeping the GLOBAL mins
    # before letting the number stand. Bounded, so a real regression
    # still fails after 6 arms.
    for arm in range(6):
        if arm >= 3 and min(hiers) <= HIER_RATIO_BUDGET * min(flats):
            break
        if arm % 2 == 0:
            flats.append(run_flat())
            hiers.append(run_hier())
        else:
            hiers.append(run_hier())
            flats.append(run_flat())
    t_flat, t_hier = min(flats), min(hiers)
    granted_tokens = int(costs[np.asarray(last_res.granted,
                                          bool)].sum())
    ratio = t_hier / t_flat if t_flat > 0 else float("inf")
    return _rate_row("inprocess", n, granted_tokens, t_hier, {
        "flat_rows_per_sec": round(n / t_flat),
        "hier_over_flat_per_row": round(ratio, 3),
        "grant_rate": round(float(np.mean(last_res.granted)), 4),
    })


async def _wire_lane(tenants, keys, costs, prios, *, native: bool,
                     bulk: bool) -> "dict | None":
    from distributedratelimiting.redis_tpu.runtime.remote import (
        RemoteBucketStore,
    )
    from distributedratelimiting.redis_tpu.runtime.server import (
        BucketStoreServer,
    )
    from distributedratelimiting.redis_tpu.runtime.store import (
        InProcessBucketStore,
    )

    backing = InProcessBucketStore()
    srv = BucketStoreServer(backing, native_frontend=native)
    await srv.start()
    if native and srv._native is None:
        await srv.aclose()
        return None  # no compiler in this environment
    store = RemoteBucketStore(address=(srv.host, srv.port),
                              coalesce_requests=False)
    n = len(keys)
    granted_tokens = 0
    n_frames = 0
    try:
        t0 = time.perf_counter()
        if bulk:
            for idx in _tenant_batches(tenants):
                sub_costs = costs[idx]
                res = await store.acquire_hierarchical_many(
                    [tenants[idx[0]]] * len(idx),
                    [keys[i] for i in idx], sub_costs, TENANT_CAP,
                    TENANT_RATE, CHILD_CAP, CHILD_RATE,
                    priority=int(prios[idx[0]]))
                granted_tokens += int(
                    sub_costs[np.asarray(res.granted, bool)].sum())
                n_frames += 1
        else:
            for i in range(n):
                r = await store.acquire_hierarchical(
                    tenants[i], keys[i], int(costs[i]), TENANT_CAP,
                    TENANT_RATE, CHILD_CAP, CHILD_RATE,
                    priority=int(prios[i]))
                if r.granted:
                    granted_tokens += int(costs[i])
        dt = time.perf_counter() - t0
    finally:
        await store.aclose()
        await srv.aclose()
    lane = ("native_bulk" if native else
            "asyncio_bulk" if bulk else "remote_scalar")
    return _rate_row(lane, n, granted_tokens, dt,
                     {"frames": n_frames if bulk else n})


def lane_remote_scalar(tenants, keys, costs, prios):
    return asyncio.run(_wire_lane(tenants, keys, costs, prios,
                                  native=False, bulk=False))


def lane_asyncio_bulk(tenants, keys, costs, prios):
    return asyncio.run(_wire_lane(tenants, keys, costs, prios,
                                  native=False, bulk=True))


def lane_native_bulk(tenants, keys, costs, prios):
    return asyncio.run(_wire_lane(tenants, keys, costs, prios,
                                  native=True, bulk=True))


#: Estimate-error shape of the reservations lane: ``estimate = actual ×
#: LogNormal(0, σ)`` — σ 0.55 puts ~32% of estimates off by more than
#: 1.7× in one direction or the other (both refund and debt lanes run
#: hot). The error stream is seeded independently of the workload seed
#: so the SAME error pattern prices every workload (a tracked-number
#: identity, like the shape constants above).
RESV_EST_SIGMA = 0.55
_RESV_ERR_SEED = 0x5E771E
#: Zero-fill audit arm: per-tenant budget small enough that the Zipf
#: head saturates (denials + debt actually exercise), fill ≈ 0 so the
#: reconciliation identity is exact.
_AUDIT_TENANT_CAP = 20_000.0
_AUDIT_FILL = 1e-9


async def _drive_reservations(store, tenants, keys, costs, estimates,
                              prios, tenant_cap, tenant_rate,
                              prefix: str):
    """Reserve → settle every row through the store-attached ledger;
    returns ``(granted_rows, settled_tokens, ledger)``."""
    led = store.reservation_ledger()
    granted = 0
    settled = 0
    for i in range(len(keys)):
        r = await led.reserve(f"{prefix}{i}", tenants[i], keys[i],
                              float(estimates[i]), tenant_cap,
                              tenant_rate, CHILD_CAP, CHILD_RATE,
                              priority=int(prios[i]))
        if r.granted:
            s = await led.settle(f"{prefix}{i}", tenants[i],
                                 float(costs[i]))
            if s.outcome == "settled":
                granted += 1
                settled += int(costs[i])
    return granted, settled, led


def lane_reservations(tenants, keys, costs, prios) -> dict:
    """The estimate-reserve-settle lane (module docstring): throughput
    at the tracked workload constants, then the zero-fill audit arm
    (differential bound + net-drift reconciliation)."""
    from distributedratelimiting.redis_tpu.runtime.store import (
        InProcessBucketStore,
    )

    n = len(keys)
    rng = np.random.default_rng(_RESV_ERR_SEED)
    estimates = np.maximum(
        costs * rng.lognormal(0.0, RESV_EST_SIGMA, n), 1.0)

    async def throughput() -> dict:
        st = InProcessBucketStore()
        t0 = time.perf_counter()
        granted, settled, led = await _drive_reservations(
            st, tenants, keys, costs, estimates, prios, TENANT_CAP,
            TENANT_RATE, "r")
        dt = time.perf_counter() - t0
        return {"dt": dt, "granted": granted, "settled": settled,
                "refunded": led.refunded_tokens,
                "debt_created": led.debt_tokens_created}

    async def audit() -> dict:
        m = min(n, 8000)
        st = InProcessBucketStore()
        _g, settled, led = await _drive_reservations(
            st, tenants[:m], keys[:m], costs[:m], estimates[:m],
            prios[:m], _AUDIT_TENANT_CAP, _AUDIT_FILL, "a")
        # Store-observed spend per tenant vs the ledger's accounting:
        # spend == settled − outstanding debt, exactly (zero fill).
        spend = 0.0
        for t in set(tenants[:m]):
            bkey = (t, _AUDIT_TENANT_CAP, _AUDIT_FILL)
            entry = st._buckets.get(bkey)
            if entry is not None:
                spend += _AUDIT_TENANT_CAP - entry[0]
        debt_out = sum(led.debts().values())
        drift = (abs(spend - (settled - debt_out)) / settled
                 if settled else 0.0)
        # Oracle: the same schedule with a PERFECT estimator.
        st2 = InProcessBucketStore()
        _g2, oracle, _led2 = await _drive_reservations(
            st2, tenants[:m], keys[:m], costs[:m], costs[:m],
            prios[:m], _AUDIT_TENANT_CAP, _AUDIT_FILL, "o")
        # The differential bound: estimate errors may admit MORE than
        # the oracle only through visible debt (an under-estimated
        # stream spends before the overage is known) — never silently.
        epsilon = led.debt_tokens_created + 0.01 * oracle
        return {"audit_rows": m, "audit_settled": settled,
                "oracle_settled": oracle,
                "audit_debt_created": round(led.debt_tokens_created, 1),
                "audit_debt_outstanding": round(debt_out, 1),
                "net_drift": round(drift, 6),
                "drift_ok": bool(drift <= 0.01),
                "bound_ok": bool(settled <= oracle + epsilon)}

    out = asyncio.run(throughput())
    audits = asyncio.run(audit())
    row = _rate_row("reservations", n, out["settled"], out["dt"], {
        "settled_rows": out["granted"],
        "est_sigma": RESV_EST_SIGMA,
        "refund_ratio": round(out["refunded"]
                              / max(out["settled"], 1), 4),
        "debt_ratio": round(out["debt_created"]
                            / max(out["settled"], 1), 4),
        **audits,
    })
    return row


LANES = {
    "inprocess": lane_inprocess,
    "remote_scalar": lane_remote_scalar,
    "asyncio_bulk": lane_asyncio_bulk,
    "native_bulk": lane_native_bulk,
    "reservations": lane_reservations,
}


def run_lane(name: str, seed: int, n_rows: int) -> "dict | None":
    tenants, keys, costs, prios = gen_workload(seed, n_rows)
    row = LANES[name](tenants, keys, costs, prios)
    if row is not None:
        row.update({"seed": seed, "t": time.time()})
    return row


def main(argv: "list[str] | None" = None) -> int:
    parser = argparse.ArgumentParser(description=__doc__.split("\n")[0])
    parser.add_argument("--rows", type=int, default=40_000)
    parser.add_argument("--seed", type=int, default=20260804)
    parser.add_argument("--lanes", default=",".join(LANES),
                        help=f"comma list from {sorted(LANES)}")
    parser.add_argument("--smoke", action="store_true",
                        help="tiny row count (plumbing check)")
    parser.add_argument("--evidence", action="store_true",
                        help=f"append rows to {EVIDENCE}")
    args = parser.parse_args(argv)
    n_rows = 2000 if args.smoke else args.rows
    rc = 0
    for name in args.lanes.split(","):
        name = name.strip()
        if name not in LANES:
            print(json.dumps({"lane": name, "error": "unknown lane"}))
            rc = 2
            continue
        row = run_lane(name, args.seed, n_rows)
        if row is None:
            row = {"bench": "llm_workload", "lane": name,
                   "skipped": "lane unavailable (no native build)"}
        print(json.dumps(row), flush=True)
        if args.evidence:
            EVIDENCE.parent.mkdir(parents=True, exist_ok=True)
            with open(EVIDENCE, "a", encoding="utf-8") as f:
                f.write(json.dumps(row) + "\n")
            if name == "reservations":
                with open(EVIDENCE_RESERVATIONS, "a",
                          encoding="utf-8") as f:
                    f.write(json.dumps(row) + "\n")
    return rc


if __name__ == "__main__":
    raise SystemExit(main())
