"""Controller-loop overhead benchmark: what one reconciliation tick
costs, and what a whole diurnal + flash-crowd day of decisions costs
(ISSUE 12; ROADMAP item 2's closing leg).

The control plane must be operationally free: a tick is one OP_STATS
fan-out plus pure-Python delta math and threshold checks — nothing on
the serving path pays for it, and the loop itself must stay far below
one core even at aggressive cadences. This benchmark pins that as a
TRACKED number along two lanes:

- ``decide``  — the pure policy half (scrape parsing + CounterDeltas +
  hysteresis/cooldown/budget + the decision) over a synthetic in-memory
  sensor feed: ticks/s with zero I/O, i.e. the loop's own CPU ceiling.
- ``wire``    — full ticks against a live localhost 2-node fleet
  (real OP_STATS scrapes over TCP): ticks/s including the sensor
  plane's round trips — the number an operator compares against the
  chosen ``--controller-tick-ms``.

Both lanes replay the same seeded diurnal + flash-crowd day shape the
acceptance soak uses (tests/test_controller.py), and report the decided
action mix so a policy regression (a flappier loop) shows up as a
DIFFERENT action count at the same seed, not just different latency.

Usage::

    python -m benchmarks.controller_loop [--ticks 2000] [--seed 20260804]
        [--lanes decide,wire] [--smoke] [--json] [--evidence]

One JSON row per lane on stdout; ``--evidence`` appends them to
``benchmarks/evidence/controller_loop.jsonl``."""

from __future__ import annotations

import argparse
import asyncio
import json
import math
import pathlib
import time

import numpy as np

__all__ = ["synthetic_feed", "run_decide_lane", "run_wire_lane", "main"]

_ROOT = pathlib.Path(__file__).resolve().parents[1]
EVIDENCE = _ROOT / "benchmarks" / "evidence" / "controller_loop.jsonl"

#: The tracked scenario's shape (change it and the numbers stop being
#: comparable across rounds): a 36-tick "day" with a 10× flash crowd in
#: ticks 12-23, tiled to the requested tick count.
DAY_TICKS = 36
FLASH = range(12, 24)
BASE_TOKENS = 165.0
FLASH_TOKENS = 1650.0
TOKEN_CAPACITY = 800.0


def _controller_config(**kw):
    from distributedratelimiting.redis_tpu.runtime.controller import (
        ControllerConfig,
    )

    base = dict(tick_s=1.0, token_rate_capacity=TOKEN_CAPACITY,
                shed_high=0.9, shed_low=0.6, shed_raise_ticks=2,
                shed_lower_ticks=2, split_share=0.2,
                split_min_tokens=100.0, split_streak_ticks=2,
                cooldown_ticks=2, budget_actions=64,
                budget_window_ticks=DAY_TICKS)
    base.update(kw)
    return ControllerConfig(**base)


def synthetic_feed(seed: int, n_ticks: int) -> list[dict]:
    """n_ticks of OP_STATS-shaped fleet snapshots replaying the day
    shape: monotonic counters with a diurnal sine, a flash-crowd token
    surge, and a hot key that takes a large share during the flash."""
    rng = np.random.default_rng(seed)
    feed = []
    admitted = {"tenant:a": 0.0, "tenant:noisy": 0.0}
    hot = {"flash/hot": 0.0, "tenant:a/u0": 0.0}
    reqs = [0, 0]
    for i in range(n_ticks):
        t = i % DAY_TICKS
        diurnal = 1.0 + 0.4 * math.sin(2 * math.pi * t / DAY_TICKS)
        flash = t in FLASH
        tokens = (FLASH_TOKENS if flash else BASE_TOKENS) * diurnal
        admitted["tenant:a"] += tokens * 0.4
        admitted["tenant:noisy"] += tokens * 0.6
        hot["flash/hot"] += tokens * (0.4 if flash else 0.02)
        hot["tenant:a/u0"] += tokens * 0.05
        reqs[0] += int(20 * diurnal + rng.integers(4))
        reqs[1] += int(20 * diurnal + rng.integers(4))
        feed.append({
            "nodes": [
                {"requests_served": reqs[0],
                 "token_velocity": {"admitted": dict(admitted)},
                 "hot_keys": {"top": [
                     {"key": k, "count": c, "error": 0.0}
                     for k, c in hot.items()]}},
                {"requests_served": reqs[1]},
            ],
            "resilience": {},
            "placement": {"slot_counts": [8, 8], "drained": []},
        })
    return feed


class _FeedCluster:
    """Inert cluster: scripted sensors, recording actuators."""

    def __init__(self, feed: list[dict]) -> None:
        self.feed = feed
        self.i = 0
        self.actuations = 0
        import types

        self.placement = types.SimpleNamespace(overrides={})
        self.flight_recorder = None

    async def stats(self) -> dict:
        snap = self.feed[min(self.i, len(self.feed) - 1)]
        self.i += 1
        return snap

    async def split_hot_keys(self, top_n: int = 1,
                             min_count: float = 0.0) -> list[str]:
        self.actuations += 1
        return ["flash/hot"]

    async def rebalance(self, reason: str = "") -> int:
        self.actuations += 1
        return 1

    async def drain_node(self, j: int) -> int:
        self.actuations += 1
        return 1

    async def rejoin_node(self, j: int) -> int:
        self.actuations += 1
        return 1


def _action_mix(controller) -> dict[str, int]:
    mix: dict[str, int] = {}
    for a in controller.actions:
        mix[a["action"]] = mix.get(a["action"], 0) + 1
    return mix


async def run_decide_lane(seed: int, n_ticks: int) -> dict:
    from distributedratelimiting.redis_tpu.runtime.controller import (
        Controller,
    )

    feed = synthetic_feed(seed, n_ticks)
    ctrl = Controller(_FeedCluster(feed), config=_controller_config())
    t0 = time.perf_counter()
    for _ in range(n_ticks):
        await ctrl.tick()
    dt = time.perf_counter() - t0
    return {
        "lane": "decide",
        "ticks": n_ticks,
        "wall_s": round(dt, 4),
        "ticks_per_s": round(n_ticks / dt, 1),
        "tick_p50_us_est": round(dt / n_ticks * 1e6, 2),
        "actions": _action_mix(ctrl),
        "actions_recorded": ctrl.actions_recorded,
    }


async def run_wire_lane(seed: int, n_ticks: int) -> dict:
    """Full ticks against a live 2-node localhost fleet: the sensor
    fan-out is real OP_STATS over TCP; actuators are live but the feed
    carries no sustained pressure, so the lane measures the SCRAPE
    cost (the common case: a healthy fleet ticks and does nothing)."""
    from distributedratelimiting.redis_tpu.runtime.cluster import (
        ClusterBucketStore,
    )
    from distributedratelimiting.redis_tpu.runtime.controller import (
        Controller,
    )
    from distributedratelimiting.redis_tpu.runtime.server import (
        BucketStoreServer,
    )
    from distributedratelimiting.redis_tpu.runtime.store import (
        InProcessBucketStore,
    )

    backings = [InProcessBucketStore() for _ in range(2)]
    servers = [BucketStoreServer(b) for b in backings]
    for s in servers:
        await s.start()
    cluster = ClusterBucketStore(
        addresses=[(s.host, s.port) for s in servers],
        coalesce_requests=False)
    ctrl = Controller(cluster, config=_controller_config())
    # Light background traffic so the scrape parses non-trivial stats.
    for i in range(200):
        await cluster.acquire(f"warm/{i % 20}", 1, 1e6, 10.0)
    try:
        t0 = time.perf_counter()
        for _ in range(n_ticks):
            await ctrl.tick()
        dt = time.perf_counter() - t0
    finally:
        await cluster.aclose()
        for s, b in zip(servers, backings):
            await s.aclose()
            await b.aclose()
    return {
        "lane": "wire",
        "ticks": n_ticks,
        "nodes": 2,
        "wall_s": round(dt, 4),
        "ticks_per_s": round(n_ticks / dt, 1),
        "tick_ms_mean": round(dt / n_ticks * 1e3, 3),
        "actions": _action_mix(ctrl),
        "scrape_errors": ctrl.scrape_errors,
    }


LANES = {"decide": run_decide_lane, "wire": run_wire_lane}


def main(argv: "list[str] | None" = None) -> None:
    parser = argparse.ArgumentParser(
        description="controller reconciliation-loop overhead benchmark")
    parser.add_argument("--ticks", type=int, default=2000)
    parser.add_argument("--seed", type=int, default=20260804)
    parser.add_argument("--lanes", default="decide,wire")
    parser.add_argument("--smoke", action="store_true",
                        help="tiny tick counts (CI sanity, not numbers)")
    parser.add_argument("--json", action="store_true")
    parser.add_argument("--evidence", action="store_true",
                        help=f"append rows to {EVIDENCE}")
    args = parser.parse_args(argv)
    n_ticks = 72 if args.smoke else args.ticks
    # Wire ticks cost a real fan-out each; keep the lane bounded.
    wire_ticks = 36 if args.smoke else min(n_ticks, 400)
    rows = []
    for lane in args.lanes.split(","):
        lane = lane.strip()
        if lane not in LANES:
            raise SystemExit(f"unknown lane {lane!r} "
                             f"(have: {sorted(LANES)})")
        n = wire_ticks if lane == "wire" else n_ticks
        row = asyncio.run(LANES[lane](args.seed, n))
        row.update(seed=args.seed, smoke=args.smoke,
                   captured_at=time.strftime("%Y-%m-%dT%H:%M:%S"))
        rows.append(row)
        print(json.dumps(row) if args.json
              else f"{row['lane']}: {row['ticks_per_s']} ticks/s "
                   f"({row['ticks']} ticks, actions={row['actions']})")
    if args.evidence:
        EVIDENCE.parent.mkdir(parents=True, exist_ok=True)
        with EVIDENCE.open("a", encoding="utf-8") as f:
            for row in rows:
                f.write(json.dumps(row) + "\n")


if __name__ == "__main__":
    main()
