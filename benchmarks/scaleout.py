"""Aggregate scale-out curve: N store servers × M bulk-client processes.

The 50M/s north star (BASELINE.json) is an *aggregate serving* target —
kernel-path numbers don't speak to it. This harness measures the only
aggregate the environment can produce: N shared-nothing
``BucketStoreServer`` processes on this box, M client processes each
bulk-driving a ``ClusterBucketStore`` (client-side placement-map
routing — epoch-0 maps route exactly like crc32 % N — with per-node
sub-batches fanned out concurrently; the same composition the
reference would reach with N Redis nodes and cluster-aware clients,
``RedisRateLimiting.Redis/README.md``'s horizontal-scale story).

Run: ``python -m benchmarks.scaleout [--nodes 1,2,4,8] [--clients 2]
[--seconds 6] [--backing cpu|device]``
Prints one JSON line per node count; the parent measures aggregate
decisions/s across all client processes against wall clock.

Topology is configurable (VERDICT r5 item 7 — the harness only):

- ``--hosts a:6380,b:6380`` drives EXTERNAL, already-running store
  servers (one JSONL record for the whole list) instead of spawning
  localhost children — the real multi-host measurement.
- ``--config topo.json`` reads the same knobs from a file
  (``{"nodes": [...], "clients": N, "seconds": S, "backing": ...,
  "hosts": [...], "cores": N}``); CLI flags override file values.
- ``--cores`` records the core count the operator ACTUALLY gave the rig
  (taskset/cgroup), for the interpretation contract below; it defaults
  to ``os.cpu_count()``.

Interpretation contract (RESULTS.md "Aggregate scale-out curve"): when
every server and client timeshares one CPU, the curve measures
*composition overhead* (does adding nodes cost throughput?), not
parallel speedup — the per-node ceiling × N model only applies when
each node owns its own core/chip. The harness therefore records
``nproc`` and ``cores`` so the reader can tell which regime a record
came from.
"""

from __future__ import annotations

import argparse
import asyncio
import concurrent.futures
import json
import os
import subprocess
import sys
import time

# Child roles ---------------------------------------------------------------


def _server_child(shards: int = 0) -> None:
    """One store-server process: CPU-platform device store (the serving
    stand-in) or the real device, prints its address, parks on stdin.
    ``shards > 0`` serves through the native multi-shard front-end
    (round 11): N SO_REUSEPORT epoll shards + tier-0 per node, so the
    scale-out curve can compose node counts from NODE-level (not
    core-level) serving rates."""
    from distributedratelimiting.redis_tpu.utils.cpu_bootstrap import (
        maybe_force_cpu_from_env,
    )

    maybe_force_cpu_from_env()
    from distributedratelimiting.redis_tpu.runtime.server import (
        BucketStoreServer,
    )
    from distributedratelimiting.redis_tpu.runtime.store import (
        DeviceBucketStore,
    )

    async def run() -> None:
        backing = DeviceBucketStore(n_slots=1 << 18, max_batch=4096)
        kwargs = {}
        if shards > 0:
            kwargs = {"native_frontend": True, "native_tier0": True,
                      "native_shards": shards}
        async with BucketStoreServer(backing, **kwargs) as srv:
            print(json.dumps({"host": srv.host, "port": srv.port}),
                  flush=True)
            await asyncio.get_running_loop().run_in_executor(
                None, sys.stdin.read)
        await backing.aclose()

    asyncio.run(run())


def _client_child(addrs_json: str, seconds: str) -> None:
    """One bulk-client process: closed-loop ``acquire_many`` against the
    whole cluster for the given duration; prints its decision count."""
    import numpy as np

    from distributedratelimiting.redis_tpu.runtime.cluster import (
        ClusterBucketStore,
    )

    addrs = [tuple(a) for a in json.loads(addrs_json)]
    dur = float(seconds)
    n = 1 << 16
    rng = np.random.default_rng(os.getpid())
    pool = [f"user{i}" for i in range(200_000)]
    batches = [[pool[j] for j in rng.integers(0, len(pool), n)]
               for _ in range(4)]
    counts = [1] * n

    async def run() -> None:
        # Generous request timeout: at N=8 on a single-core box the warm
        # call rides an 8-process XLA-CPU compile stampede and can exceed
        # the default 30 s (observed) without anything being wrong.
        store = ClusterBucketStore(addresses=addrs,
                                   request_timeout_s=180.0)
        # Warm every node connection + kernel shape.
        await store.acquire_many(batches[0], counts, 1e7, 1e7,
                                 with_remaining=False)
        done = 0
        t0 = time.perf_counter()
        i = 0
        while time.perf_counter() - t0 < dur:
            await store.acquire_many(batches[i % len(batches)], counts,
                                     1e7, 1e7, with_remaining=False)
            done += n
            i += 1
        dt = time.perf_counter() - t0
        await store.aclose()
        print(json.dumps({"decisions": done, "dt": dt}), flush=True)

    asyncio.run(run())


# Parent orchestration ------------------------------------------------------


def _measure(n_nodes: int, n_clients: int, seconds: float,
             backing: str, hosts: "list[list] | None" = None,
             cores: int | None = None, fe_shards: int = 0) -> dict:
    from distributedratelimiting.redis_tpu.utils.cpu_bootstrap import (
        FORCE_CPU_ENV,
    )

    env = os.environ.copy()
    if backing == "cpu":
        env[FORCE_CPU_ENV] = "1"
    me = os.path.abspath(__file__)
    # Children run this file by path, outside the package: put the repo
    # root on their import path.
    root = os.path.dirname(os.path.dirname(me))
    env["PYTHONPATH"] = root + os.pathsep + env.get("PYTHONPATH", "")
    # External topology: the operator's already-running servers replace
    # the spawned localhost children; everything else is identical.
    servers = [] if hosts else [subprocess.Popen(
        [sys.executable, me, "--server-child", str(fe_shards)], env=env,
        stdin=subprocess.PIPE, stdout=subprocess.PIPE, text=True)
        for _ in range(n_nodes)]
    pool = concurrent.futures.ThreadPoolExecutor(1)
    try:
        if hosts:
            addrs = [[h, int(p)] for h, p in
                     (a if isinstance(a, (list, tuple))
                      else a.rsplit(":", 1) for a in hosts)]
            n_nodes = len(addrs)
        else:
            addrs = []
            for s in servers:
                # Pooled readline with a timeout (bench.py's guard):
                # during a tunnel outage a --backing device server child
                # hangs in device init and never prints its address.
                line = pool.submit(s.stdout.readline).result(timeout=180.0)
                a = json.loads(line)
                addrs.append([a["host"], a["port"]])
        addrs_json = json.dumps(addrs)
        t0 = time.perf_counter()
        clients = [subprocess.Popen(
            [sys.executable, me, "--client-child", addrs_json,
             str(seconds)], env=env, stdout=subprocess.PIPE, text=True)
            for _ in range(n_clients)]
        outs = []
        try:
            for c in clients:
                out, _ = c.communicate(timeout=seconds * 8 + 240)
                outs.append(json.loads(out.strip().splitlines()[-1]))
        finally:
            for c in clients:  # a timed-out/garbled client must not keep
                if c.poll() is None:  # spinning against dying servers
                    c.kill()
        wall = time.perf_counter() - t0
        per_client = [o["decisions"] / o["dt"] for o in outs]
        return {
            "config": "scaleout",
            "n_nodes": n_nodes,
            "n_clients": n_clients,
            "fe_shards": fe_shards or None,
            "backing": backing if not hosts else "external",
            # Clients start together and run identical closed-loop
            # windows, so the aggregate is the sum of per-client rates
            # over their own measured windows (parent wall clock would
            # fold one-time compile/warmup into the denominator).
            "aggregate_decisions_per_sec": round(sum(per_client)),
            "per_client_decisions_per_sec": [round(r) for r in per_client],
            "wall_incl_warm_s": round(wall, 1),
            "nproc": os.cpu_count(),
            "cores": cores if cores is not None else os.cpu_count(),
            "hosts": [f"{h}:{p}" for h, p in addrs] if hosts else None,
        }
    finally:
        for s in servers:
            try:
                s.stdin.close()
                s.wait(timeout=10)
            except Exception:
                s.kill()
        pool.shutdown(wait=False)


def main(argv: list[str] | None = None) -> int:
    p = argparse.ArgumentParser(description=__doc__)
    p.add_argument("--nodes", default=None,
                   help="comma-separated node counts to spawn locally "
                   "(default 1,2,4,8; ignored when --hosts is given)")
    p.add_argument("--clients", type=int, default=None)
    p.add_argument("--seconds", type=float, default=None)
    p.add_argument("--backing", choices=("cpu", "device"), default=None)
    p.add_argument("--hosts", default=None,
                   help="comma-separated host:port of EXTERNAL servers "
                   "to drive instead of spawning localhost children")
    p.add_argument("--cores", type=int, default=None,
                   help="core count the rig actually owns (recorded in "
                   "the JSONL; default os.cpu_count())")
    p.add_argument("--shards", type=int, default=None,
                   help="serve each spawned node through the native "
                   "multi-shard front-end with this many SO_REUSEPORT "
                   "epoll shards (0/absent = the asyncio server): the "
                   "node-level arm of the aggregate model — rows/s per "
                   "NODE x node count, not per core")
    p.add_argument("--config", default=None,
                   help="JSON file supplying the same knobs (nodes, "
                   "clients, seconds, backing, hosts, cores, shards); "
                   "CLI flags override it")
    args = p.parse_args(argv)
    cfg: dict = {}
    if args.config:
        with open(args.config, encoding="utf-8") as f:
            cfg = json.load(f)
    nodes = (args.nodes.split(",") if args.nodes
             else cfg.get("nodes", [1, 2, 4, 8]))
    clients = args.clients if args.clients is not None else cfg.get(
        "clients", 2)
    seconds = args.seconds if args.seconds is not None else cfg.get(
        "seconds", 6.0)
    backing = args.backing or cfg.get("backing", "cpu")
    hosts = (args.hosts.split(",") if args.hosts
             else cfg.get("hosts") or None)
    cores = args.cores if args.cores is not None else cfg.get("cores")
    fe_shards = (args.shards if args.shards is not None
                 else int(cfg.get("shards", 0) or 0))
    if hosts:
        print(json.dumps(_measure(len(hosts), clients, seconds, backing,
                                  hosts=hosts, cores=cores)), flush=True)
        return 0
    for n in [int(x) for x in nodes]:
        print(json.dumps(_measure(n, clients, seconds, backing,
                                  cores=cores, fe_shards=fe_shards)),
              flush=True)
    return 0


if __name__ == "__main__":
    if "--server-child" in sys.argv:
        i = sys.argv.index("--server-child")
        shards = (int(sys.argv[i + 1])
                  if len(sys.argv) > i + 1 else 0)
        _server_child(shards)
        sys.exit(0)
    if "--client-child" in sys.argv:
        i = sys.argv.index("--client-child")
        _client_child(sys.argv[i + 1], sys.argv[i + 2])
        sys.exit(0)
    sys.exit(main())
