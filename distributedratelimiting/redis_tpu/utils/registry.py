"""Service registry — the DI/composition layer.

Python translation of ``ServiceCollectionExtensions``
(``ServiceCollectionExtensions.cs:10-26``): each ``add_*`` helper registers
an options-configured limiter as a lazily-constructed singleton under the
``"rate_limiter"`` service type, exactly as the reference registers each
concrete limiter under the ``RateLimiter`` service type
(``:15,:24``) — except that here registering a second limiter under the
same name raises instead of silently creating ambiguity (a known defect:
both reference methods register the same service type, making resolution
ambiguous when both are added; SURVEY.md §2 defects).
"""

from __future__ import annotations

from typing import Any, Callable

from distributedratelimiting.redis_tpu.models.approximate import (
    ApproximateTokenBucketRateLimiter,
)
from distributedratelimiting.redis_tpu.models.concurrency import (
    ConcurrencyLimiter,
)
from distributedratelimiting.redis_tpu.models.fixed_window import (
    FixedWindowRateLimiter,
)
from distributedratelimiting.redis_tpu.models.options import (
    ApproximateTokenBucketOptions,
    ConcurrencyLimiterOptions,
    FixedWindowOptions,
    QueueingTokenBucketOptions,
    SlidingWindowOptions,
    TokenBucketOptions,
)
from distributedratelimiting.redis_tpu.models.queueing_token_bucket import (
    QueueingTokenBucketRateLimiter,
)
from distributedratelimiting.redis_tpu.models.sliding_window import (
    SlidingWindowRateLimiter,
)
from distributedratelimiting.redis_tpu.models.token_bucket import (
    TokenBucketRateLimiter,
)
from distributedratelimiting.redis_tpu.runtime.store import BucketStore

__all__ = [
    "ServiceRegistry",
    "RATE_LIMITER",
    "add_tpu_token_bucket_rate_limiter",
    "add_tpu_approximate_token_bucket_rate_limiter",
    "add_tpu_queueing_token_bucket_rate_limiter",
    "add_tpu_sliding_window_rate_limiter",
    "add_tpu_partitioned_window_rate_limiter",
    "add_tpu_concurrency_limiter",
    "add_tpu_fixed_window_rate_limiter",
]

RATE_LIMITER = "rate_limiter"
BUCKET_STORE = "bucket_store"


class ServiceRegistry:
    """Minimal singleton container: ``add_singleton(name, factory)`` +
    ``resolve(name)`` with lazy construction (the reference's limiters are
    likewise constructed on first resolve, SURVEY.md §3.4)."""

    def __init__(self) -> None:
        self._factories: dict[str, Callable[["ServiceRegistry"], Any]] = {}
        self._instances: dict[str, Any] = {}

    def add_singleton(self, name: str,
                      factory: Callable[["ServiceRegistry"], Any]) -> None:
        if name in self._factories:
            raise ValueError(
                f"service {name!r} is already registered — use a distinct "
                "name per limiter (the reference allowed this collision and "
                "made resolution ambiguous)"
            )
        self._factories[name] = factory

    def resolve(self, name: str) -> Any:
        if name not in self._instances:
            if name not in self._factories:
                raise KeyError(f"no service registered under {name!r}")
            self._instances[name] = self._factories[name](self)
        return self._instances[name]

    def __contains__(self, name: str) -> bool:
        return name in self._factories


def _store_of(registry: ServiceRegistry, store: BucketStore | None) -> BucketStore:
    return store if store is not None else registry.resolve(BUCKET_STORE)


def add_tpu_token_bucket_rate_limiter(
    registry: ServiceRegistry,
    configure: Callable[[], TokenBucketOptions],
    *,
    store: BucketStore | None = None,
    service_name: str = RATE_LIMITER,
) -> None:
    """≙ ``AddRedisTokenBucketRateLimiter`` (``ServiceCollectionExtensions.cs:10-17``)."""
    registry.add_singleton(
        service_name,
        lambda reg: TokenBucketRateLimiter(configure(), _store_of(reg, store)),
    )


def add_tpu_approximate_token_bucket_rate_limiter(
    registry: ServiceRegistry,
    configure: Callable[[], ApproximateTokenBucketOptions],
    *,
    store: BucketStore | None = None,
    service_name: str = RATE_LIMITER,
) -> None:
    """≙ ``AddRedisApproximateTokenBucketRateLimiter`` (``:19-26``)."""
    registry.add_singleton(
        service_name,
        lambda reg: ApproximateTokenBucketRateLimiter(
            configure(), _store_of(reg, store)
        ),
    )


def add_tpu_queueing_token_bucket_rate_limiter(
    registry: ServiceRegistry,
    configure: Callable[[], QueueingTokenBucketOptions],
    *,
    store: BucketStore | None = None,
    service_name: str = RATE_LIMITER,
) -> None:
    """Registers the finished queueing+exact hybrid (the reference's dead
    component #14 had no DI method; its options class was orphaned)."""
    registry.add_singleton(
        service_name,
        lambda reg: QueueingTokenBucketRateLimiter(
            configure(), _store_of(reg, store)
        ),
    )


def add_tpu_concurrency_limiter(
    registry: ServiceRegistry,
    configure: Callable[[], ConcurrencyLimiterOptions],
    *,
    store: BucketStore | None = None,
    service_name: str = RATE_LIMITER,
) -> None:
    """Registers the distributed concurrency (held-permit) limiter — the
    ``System.Threading.RateLimiting`` family member the reference never
    distributed."""
    registry.add_singleton(
        service_name,
        lambda reg: ConcurrencyLimiter(configure(), _store_of(reg, store)),
    )


def add_tpu_fixed_window_rate_limiter(
    registry: ServiceRegistry,
    configure: Callable[[], FixedWindowOptions],
    *,
    store: BucketStore | None = None,
    service_name: str = RATE_LIMITER,
) -> None:
    registry.add_singleton(
        service_name,
        lambda reg: FixedWindowRateLimiter(configure(), _store_of(reg, store)),
    )


def add_tpu_sliding_window_rate_limiter(
    registry: ServiceRegistry,
    configure: Callable[[], SlidingWindowOptions],
    *,
    store: BucketStore | None = None,
    service_name: str = RATE_LIMITER,
) -> None:
    registry.add_singleton(
        service_name,
        lambda reg: SlidingWindowRateLimiter(configure(), _store_of(reg, store)),
    )


def add_tpu_partitioned_window_rate_limiter(
    registry: ServiceRegistry,
    configure: "Callable[[], SlidingWindowOptions | FixedWindowOptions]",
    *,
    store: BucketStore | None = None,
    service_name: str = RATE_LIMITER,
) -> None:
    """Keyed window façade: one window per resource (sliding by default;
    pass :class:`FixedWindowOptions` for boundary-reset semantics)."""
    from distributedratelimiting.redis_tpu.models.partitioned_window import (
        PartitionedWindowRateLimiter,
    )

    registry.add_singleton(
        service_name,
        lambda reg: PartitionedWindowRateLimiter(configure(),
                                                 _store_of(reg, store)),
    )
