"""Deterministic fault injection — the chaos plane's hand on the wire.

A seeded :class:`FaultInjector` decides, per *seam occurrence*, whether
to inject one of the classic distributed failure modes: connection
reset, delay/jitter, partial frame, stall, blackhole (request vanishes,
no reply), injected error, clock skew. Seams are named call sites the
runtime consults when — and only when — an injector is installed:

- ``client.connect`` — :meth:`RemoteBucketStore._connect_io` before the
  dial (a fault here is *provably before anything was sent*, the case
  the at-most-once retry contract may replay; docs/DESIGN.md §11).
- ``client.read`` / ``client.write`` — the wrapped client transport
  (per frame read / write).
- ``server.dispatch`` — :meth:`BucketStoreServer._serve_request` before
  the frame is served.
- ``client.retry`` — :meth:`RemoteBucketStore._retry_sleep` before the
  client re-sends a timed-out/failed request (per retry occurrence,
  never on first attempts). A DELAY rule here stretches the client's
  backoff; a RESET/ERROR rule abandons the retry — the storm soak's
  lever for shaping multiplicative retry traffic deterministically
  (see :func:`storm_schedule` for the shared storm model).
- ``t0.sync`` — one tier-0 reconciliation round in
  :meth:`NativeFrontend._t0_sync_loop` (a fault fails the round; rows
  carry, the degraded streak advances).
- ``server.migrate`` — a MIGRATE_PULL/PUSH dispatch on the serving node
  (:meth:`BucketStoreServer._handle_frame_inner`): a fault here fails
  one handoff step — the coordinator's abort path must fire.
- ``cluster.migrate`` — one membership-change step on the coordinator
  (:meth:`ClusterBucketStore._apply_placement`: health gate, pull, each
  push batch, each commit announce): the membership-change seam the
  reshard soak drives.
- ``controller.tick`` — one reconciliation round of the autonomous
  control plane (:meth:`Controller.tick`, runtime/controller.py): a
  fault fails the whole tick loudly (counted, flight-recorder frame,
  no decisions that round) — the controller soak's proof that a flaky
  sensor plane degrades the loop to inaction, never to flapping.
- ``federation.lease`` / ``federation.renew`` / ``federation.reclaim``
  — one WAN control call from a regional federation agent to the home
  ledger (:meth:`RegionFederation._call_home`,
  runtime/federation.py): a fault here IS a partition symptom — the
  region counts it and keeps serving from its current slice, and only
  monotonic lease expiry degrades it to the envelope.
- ``server.federation`` — an OP_FED_LEASE/RENEW/RECLAIM dispatch at
  the home (:meth:`BucketStoreServer._handle_frame_inner`): a fault
  fails one control frame; the ops are post-send-retry-safe, so the
  region's retry dedups.
- ``audit.leak`` — the scalar OP_ACQUIRE decision site
  (:meth:`BucketStoreServer._handle_frame_inner`, asyncio lane): an
  injected fault flips one DENY into a granted reply WITHOUT the store
  debit — a deliberate token leak between the server's reply/witness
  counters. Unlike every other seam this one injects a *correctness*
  bug, not a failure: it exists so the conservation audit soak
  (runtime/audit.py, tests/test_audit.py) can prove the ε-ledger
  detects exactly this class of drift within its detection budget.
  Consulted through the sync :meth:`FaultInjector.decide` (the hot
  path cannot await); any rule kind fires it.
- clock skew (``CLOCK_SKEW`` rules on any seam, read via
  :meth:`FaultInjector.clock_skew` / :class:`SkewedClock`) — the
  federation tests wrap the WALL clocks on both ends with it and pin
  that lease lifetimes never move: TTLs are monotonic-local by
  contract.

**Determinism.** Each seam owns its own ``random.Random`` seeded from
``(seed, seam)`` and its own occurrence counter, and every occurrence
draws exactly ``len(rules)`` uniforms — so the fault schedule is a pure
function of per-seam occurrence index, independent of task interleaving
across seams. :meth:`schedule_preview` replays that pure function
without touching live state; the chaos soak asserts the realized
:attr:`events` log equals the preview (same seed ⇒ same schedule).

**Zero-cost when off.** Production code guards every seam with
``faults._INJECTOR is not None`` — one module-global read. Nothing else
of this module runs unless an injector is installed explicitly
(:func:`install`) or via the ``DRL_TPU_FAULTS_CONFIG`` env var (a JSON
file: ``{"seed": 7, "rules": {"server.dispatch": [{"kind": "delay",
"probability": 0.1, "delay_s": 0.05}]}}``).
"""

from __future__ import annotations

import json
import os
import random
from dataclasses import dataclass, field, replace
from typing import Mapping, Sequence

__all__ = [
    "FaultRule", "FaultEvent", "FaultInjector", "FaultInjectedError",
    "BlackholeFault", "SkewedClock", "install", "uninstall",
    "get_injector", "seam", "storm_schedule", "StormEvent",
    "RESET", "DELAY", "PARTIAL_FRAME", "STALL", "BLACKHOLE", "ERROR",
    "CLOCK_SKEW",
]

# Fault kinds. RESET raises ConnectionResetError at the seam; DELAY
# sleeps delay_s (+ uniform jitter_s) then proceeds; PARTIAL_FRAME
# (write seam) emits a prefix of the frame then breaks the connection;
# STALL sleeps delay_s then proceeds (distinguished from DELAY only by
# intent: use it with delays past the request timeout); BLACKHOLE
# swallows the event — a write goes nowhere, a dispatch never replies;
# ERROR raises FaultInjectedError (served as a routable store error);
# CLOCK_SKEW contributes skew_s to SkewedClock readers.
RESET = "reset"
DELAY = "delay"
PARTIAL_FRAME = "partial_frame"
STALL = "stall"
BLACKHOLE = "blackhole"
ERROR = "error"
CLOCK_SKEW = "clock_skew"

_KINDS = frozenset({RESET, DELAY, PARTIAL_FRAME, STALL, BLACKHOLE,
                    ERROR, CLOCK_SKEW})


class FaultInjectedError(RuntimeError):
    """An injected (non-transport) failure — served like a store error."""


class BlackholeFault(Exception):
    """Injected blackhole: the event must produce NO observable effect
    (no reply, no write). Seams catch this specifically."""


@dataclass(frozen=True)
class FaultRule:
    """One injection rule on one seam.

    Eligibility is by per-seam occurrence index: ``after <= i < until``
    (``until=None`` = forever) — occurrence windows, not wall clock,
    keep the schedule deterministic under arbitrary interleaving.
    ``probability`` is the per-occurrence chance within the window;
    ``max_faults`` caps the rule's total firings.
    """

    kind: str
    probability: float = 1.0
    after: int = 0
    until: int | None = None
    delay_s: float = 0.0
    jitter_s: float = 0.0
    skew_s: float = 0.0
    max_faults: int | None = None

    def __post_init__(self) -> None:
        if self.kind not in _KINDS:
            raise ValueError(f"unknown fault kind {self.kind!r}")
        if not 0.0 <= self.probability <= 1.0:
            raise ValueError("probability must be in [0, 1]")


@dataclass(frozen=True)
class FaultEvent:
    """One realized injection — the unit of the reproducible schedule."""

    seam: str
    occurrence: int
    kind: str
    delay_s: float = 0.0


@dataclass
class _SeamState:
    rng: random.Random
    count: int = 0
    fired: dict[int, int] = field(default_factory=dict)  # rule idx → fires


class FaultInjector:
    """Seeded, schedule-deterministic fault source (module docstring)."""

    def __init__(self, seed: int = 0,
                 rules: "Mapping[str, Sequence[FaultRule]] | None" = None
                 ) -> None:
        self.seed = seed
        self._rules: dict[str, tuple[FaultRule, ...]] = {
            seam: tuple(rs) for seam, rs in (rules or {}).items()}
        self._seams: dict[str, _SeamState] = {}
        #: Realized injections, in per-seam occurrence order.
        self.events: list[FaultEvent] = []

    @staticmethod
    def _seam_rng(seed: int, seam: str) -> random.Random:
        return random.Random(f"{seed}/{seam}")

    def _seam(self, seam: str) -> _SeamState:
        st = self._seams.get(seam)
        if st is None:
            st = self._seams[seam] = _SeamState(
                self._seam_rng(self.seed, seam))
        return st

    @staticmethod
    def _decide_one(rules: "tuple[FaultRule, ...]", st: _SeamState
                    ) -> "tuple[int, FaultRule, float] | None":
        """One occurrence's decision: draws exactly ``len(rules)``
        uniforms (+1 for jitter on a firing delay rule), so the rng
        stream position is a pure function of the occurrence index."""
        i = st.count
        st.count += 1
        hit: "tuple[int, FaultRule, float] | None" = None
        for r_idx, rule in enumerate(rules):
            u = st.rng.random()
            if hit is not None:
                continue  # stream length stays fixed; first hit wins
            if i < rule.after or (rule.until is not None
                                  and i >= rule.until):
                continue
            if (rule.max_faults is not None
                    and st.fired.get(r_idx, 0) >= rule.max_faults):
                continue
            if u < rule.probability:
                delay = rule.delay_s
                if rule.jitter_s:
                    delay += st.rng.random() * rule.jitter_s
                hit = (r_idx, rule, delay)
        return hit

    def decide(self, seam: str) -> "FaultEvent | None":
        """Advance ``seam`` by one occurrence; the injected event, if
        any, is appended to :attr:`events` and returned."""
        rules = self._rules.get(seam)
        if not rules:
            return None
        st = self._seam(seam)
        occurrence = st.count
        hit = self._decide_one(rules, st)
        if hit is None:
            return None
        r_idx, rule, delay = hit
        st.fired[r_idx] = st.fired.get(r_idx, 0) + 1
        ev = FaultEvent(seam, occurrence, rule.kind, delay)
        self.events.append(ev)
        return ev

    def occurrence_count(self, seam: str) -> int:
        """How many occurrences ``seam`` has seen (for comparing the
        realized :attr:`events` against :meth:`schedule_preview`)."""
        st = self._seams.get(seam)
        return 0 if st is None else st.count

    def schedule_preview(self, seam: str, n: int) -> list["FaultEvent"]:
        """The first ``n`` occurrences' decisions for ``seam``, computed
        on a FRESH rng — live state untouched. Equal to what a live run
        realizes (the determinism contract the soak asserts)."""
        rules = self._rules.get(seam, ())
        st = _SeamState(self._seam_rng(self.seed, seam))
        out: list[FaultEvent] = []
        for _ in range(n):
            occurrence = st.count
            hit = self._decide_one(tuple(rules), st)
            if hit is not None:
                r_idx, rule, delay = hit
                st.fired[r_idx] = st.fired.get(r_idx, 0) + 1
                out.append(FaultEvent(seam, occurrence, rule.kind, delay))
        return out

    # -- seam application ---------------------------------------------------
    async def on_event(self, seam: str) -> None:
        """Async seam hook: sleep for DELAY/STALL, raise for
        RESET/ERROR/BLACKHOLE, no-op otherwise."""
        ev = self.decide(seam)
        if ev is None:
            return
        import asyncio

        if ev.kind in (DELAY, STALL):
            await asyncio.sleep(ev.delay_s)
        elif ev.kind == RESET:
            raise ConnectionResetError(
                f"injected connection reset ({seam}#{ev.occurrence})")
        elif ev.kind == ERROR:
            raise FaultInjectedError(
                f"injected fault ({seam}#{ev.occurrence})")
        elif ev.kind == BLACKHOLE:
            raise BlackholeFault(seam)
        # PARTIAL_FRAME / CLOCK_SKEW are transport/clock-specific; on a
        # generic seam they are recorded but act as no-ops.

    def wrap_connection(self, reader, writer):
        """Client-transport seam: wrap an asyncio stream pair so every
        frame read/write consults ``client.read`` / ``client.write``."""
        return _FaultyReader(reader, self), _FaultyWriter(writer, self)

    def clock_skew(self, seam: str = "clock") -> float:
        """Total skew contributed by the seam's CLOCK_SKEW rules (static
        — derived from the rule set, not the occurrence stream)."""
        return sum(r.skew_s for r in self._rules.get(seam, ())
                   if r.kind == CLOCK_SKEW)

    def with_seed(self, seed: int) -> "FaultInjector":
        """A fresh injector with the same rules under another seed."""
        return FaultInjector(seed, {s: tuple(replace(r) for r in rs)
                                    for s, rs in self._rules.items()})


class _FaultyReader:
    """StreamReader proxy injecting on each ``readexactly`` (the only
    read the wire layer performs)."""

    def __init__(self, inner, injector: FaultInjector) -> None:
        self._inner = inner
        self._inj = injector

    async def readexactly(self, n: int) -> bytes:
        import asyncio

        ev = self._inj.decide("client.read")
        if ev is not None:
            if ev.kind in (DELAY, STALL):
                await asyncio.sleep(ev.delay_s)
            elif ev.kind == RESET:
                raise ConnectionResetError(
                    f"injected read reset (#{ev.occurrence})")
            elif ev.kind == BLACKHOLE:
                # Nothing ever arrives: hold the read until the caller's
                # timeout (or cancellation on teardown) fires.
                await asyncio.sleep(ev.delay_s or 3600.0)
                raise ConnectionResetError(
                    f"injected read blackhole (#{ev.occurrence})")
        return await self._inner.readexactly(n)

    def __getattr__(self, name):
        return getattr(self._inner, name)


class _FaultyWriter:
    """StreamWriter proxy injecting on each ``write``. ``transport``,
    ``drain``, ``close`` … forward to the real writer."""

    def __init__(self, inner, injector: FaultInjector) -> None:
        self._inner = inner
        self._inj = injector
        self._broken = False

    def write(self, data: bytes) -> None:
        if self._broken:
            raise ConnectionResetError("connection broken by injected "
                                       "partial frame")
        ev = self._inj.decide("client.write")
        if ev is None:
            self._inner.write(data)
            return
        if ev.kind == RESET:
            self._inner.close()
            raise ConnectionResetError(
                f"injected write reset (#{ev.occurrence})")
        if ev.kind == PARTIAL_FRAME:
            # A torn frame: the peer sees a prefix, then EOF — its frame
            # reader must treat the truncation as a clean drop, never a
            # misparse.
            self._inner.write(data[: max(1, len(data) // 2)])
            self._inner.close()
            self._broken = True
            raise ConnectionResetError(
                f"injected partial frame (#{ev.occurrence})")
        if ev.kind == BLACKHOLE:
            return  # swallowed: sent-nowhere, the reply never comes
        self._inner.write(data)  # DELAY et al. are read-side concerns

    def __getattr__(self, name):
        return getattr(self._inner, name)


class SkewedClock:
    """A :class:`~..runtime.clock.Clock` running ``skew_s`` ahead of its
    base — the clock-skew fault. Wrapping a CLIENT's clock must change
    nothing (the store is the time authority, invariant 1); wrapping a
    node's store clock models divergent per-node time."""

    def __init__(self, base, skew_s: float) -> None:
        self._base = base
        self.skew_s = skew_s

    def now(self) -> float:
        return self._base.now() + self.skew_s

    def __getattr__(self, name):
        return getattr(self._base, name)


# -- process-global installation (the seams' gate) --------------------------

_INJECTOR: "FaultInjector | None" = None


def get_injector() -> "FaultInjector | None":
    return _INJECTOR


def install(injector: "FaultInjector | None"
            ) -> "FaultInjector | None":
    """Install (or, with ``None``, clear) the process-global injector;
    returns the previous one so tests can restore it."""
    global _INJECTOR
    previous, _INJECTOR = _INJECTOR, injector
    return previous


def uninstall() -> None:
    install(None)


async def seam(name: str) -> None:
    """Consult the installed injector at a named seam — the cold-path
    convenience (control-plane call sites); hot paths keep the inline
    ``faults._INJECTOR is not None`` guard instead of paying a call."""
    if _INJECTOR is not None:
        await _INJECTOR.on_event(name)


# -- the shared retry-storm model (docs/DESIGN.md §24) -----------------------

@dataclass(frozen=True)
class StormEvent:
    """One client attempt in a seeded retry storm: the unit the storm
    soak (tests/test_storm.py) and future chaos tests replay. ``rid``
    is the retry-STABLE request identity (all attempts of one logical
    request share it — the reservation-lane fingerprint);
    ``deadline_s`` is the remaining client budget at send time, which
    DECAYS across retries: the doomed-work gate's input."""

    rid: str
    tenant: str
    priority: int
    attempt: int       # 0 = first attempt, k = k-th retry
    t_s: float         # send offset from storm start, seconds
    deadline_s: float  # remaining end-to-end budget at send time
    cost: int


def storm_schedule(seed: int, *, n_requests: int = 200,
                   tenants: "Sequence[str]" = ("tenant-a", "tenant-b"),
                   priorities: "Sequence[int]" = (0, 0, 1, 2),
                   client_timeout_s: float = 0.05,
                   deadline_s: float = 0.2,
                   max_retries: int = 3,
                   backoff_mult: float = 2.0,
                   arrival_span_s: float = 1.0,
                   cost_range: "tuple[int, int]" = (1, 4),
                   ) -> list[StormEvent]:
    """The seeded timeout-then-retry schedule: ``n_requests`` logical
    requests arrive uniformly over ``arrival_span_s``; each attempt
    that the client gives up on (its ``client_timeout_s`` elapses,
    multiplied by ``backoff_mult`` per retry) spawns the next attempt
    under the SAME rid with the remaining deadline budget decayed by
    the wait — the multiplicative-retry regime of "When Two is Worse
    Than One". Attempts whose budget is already spent are never sent
    (the client is dead by then). Pure function of ``seed`` + kwargs:
    same seed ⇒ byte-for-byte the same event list, the chaos-test
    determinism contract. Returned sorted by send time."""
    rng = random.Random(f"{seed}/storm")
    events: list[StormEvent] = []
    for i in range(n_requests):
        t0 = rng.random() * arrival_span_s
        tenant = tenants[i % len(tenants)]
        priority = priorities[i % len(priorities)]
        cost = rng.randint(*cost_range)
        rid = f"storm-{seed}-{i}"
        t, timeout = t0, client_timeout_s
        for attempt in range(max_retries + 1):
            remaining = deadline_s - (t - t0)
            if remaining <= 0.0:
                break
            events.append(StormEvent(rid, tenant, priority, attempt,
                                     round(t, 9), round(remaining, 9),
                                     cost))
            t += timeout
            timeout *= backoff_mult
    events.sort(key=lambda e: (e.t_s, e.rid, e.attempt))
    return events


def _maybe_install_from_env() -> None:
    path = os.environ.get("DRL_TPU_FAULTS_CONFIG")
    if not path:
        return
    with open(path, encoding="utf-8") as f:
        cfg = json.load(f)
    rules = {seam: tuple(FaultRule(**r) for r in rs)
             for seam, rs in cfg.get("rules", {}).items()}
    install(FaultInjector(int(cfg.get("seed", 0)), rules))


_maybe_install_from_env()
