"""Multi-window SLO burn-rate watchdog — the alerting half of the
conservation audit plane (runtime/audit.py).

"When Two is Worse Than One" (PAPERS.md) is the cautionary tale this
module exists for: a drift regime nobody is told about becomes
metastable collapse. The watchdog turns the monotonic counter plane
into typed alerts using the classic multi-window burn-rate method: for
each service-level objective it tracks a FAST and a SLOW window over
the same error ratio and trips only when BOTH burn faster than the
budget allows — the fast window gives detection latency, the slow
window suppresses one-tick blips (the zero-false-alarm posture the
seeded soaks pin).

Delta-based by contract: every input is a cumulative monotonic counter
sampled once per tick; windows are differences of ring entries, never
``reset=True`` (the destructive-reset contract, utils/metrics.py).
Ticks are COUNTED, not clocked — driven by a seeded schedule the alert
log is a pure function of the sample stream, which is what makes
"same seed ⇒ identical alert schedule" a testable property.

Watched dimensions (the OPERATIONS.md §18 window table):

========== ============================== ==========================
slo        error ratio (windowed)         default objective
========== ============================== ==========================
overadmit  overadmitted / admitted tokens 1e-3 of admitted tokens
latency    samples above p99 SLO / total  1% above 0.25 s (CPU
                                          stand-in; tighten to the
                                          2 ms north star on TPU)
shed       requests_shed / requests       5% of requests
goodput    served rate below floor        disarmed (``None``)
========== ============================== ==========================

:data:`SLO_SERIES` declares every OpenMetrics series the watchdog's
sample stream is derived from — drl-check's ``metric-name`` rule holds
each entry to a live registration site, exactly as it does for the
controller's ``SENSOR_SERIES``, so a rename on the emitting side fails
``make check`` instead of silently blinding the watchdog.
"""

from __future__ import annotations

from collections import deque
from dataclasses import dataclass
from typing import Callable, Mapping

__all__ = ["SLO_SERIES", "SLOConfig", "BurnRateWatchdog"]

#: Every OpenMetrics series the watchdog's tick samples are derived
#: from (through the same counters the families render). drl-check's
#: ``metric-name`` rule resolves each against a registration site —
#: file:line on both sides — so the sensor plane cannot drift.
SLO_SERIES = (
    "drl_requests_served",      # server.py — goodput / shed denominator
    "drl_requests_shed",        # server.py — shed-rate numerator
    "drl_admitted_tokens",      # server.py — over-admission denominator
    "drl_serving_latency_seconds",   # server.py — the p99 latency SLI
    "drl_audit_overadmitted_tokens",  # server.py audit family — the
    # conservation ledger's realized over-admission (runtime/audit.py)
    "drl_epsilon_budget_used_ratio",  # server.py — per-source ε
    # utilization gauges the runbook's symptom table starts from
    "drl_goodput_settled_in_deadline",  # server.py — deadline-true
    # goodput (settles inside the client's propagated deadline): the
    # refinement of the served-rate floor the overload runbook reads
    # during a retry storm (docs/DESIGN.md §24, OPERATIONS.md §20) —
    # served-rate can look healthy while every grant settles late
)

#: The watchdog's dimensions, in a fixed order (the alert log's
#: deterministic iteration order).
_DIMENSIONS = ("overadmit", "latency", "shed", "goodput")


@dataclass(frozen=True)
class SLOConfig:
    """Knobs of one burn-rate watchdog (docs/OPERATIONS.md §18).

    Objectives set to ``None`` disarm their dimension. Windows are in
    TICKS (the caller owns the tick cadence); the burn thresholds are
    the SRE-standard pair — a trip needs the fast window burning hard
    AND the slow window confirming it is not a blip.
    """

    #: Error-budget objectives. ``overadmit_ratio`` is the tolerated
    #: over-admitted fraction of admitted tokens (the Σ-of-ε contract:
    #: realized drift beyond the documented ε budgets is an incident).
    overadmit_ratio: "float | None" = 1e-3
    #: Latency SLO: at most ``latency_bad_fraction`` of requests may
    #: exceed ``latency_slo_s``. The default threshold is the CPU
    #: stand-in's generous envelope — TPU deployments tighten it to
    #: the <2 ms north star (the runbook's knob table).
    latency_slo_s: "float | None" = 0.25
    latency_bad_fraction: float = 0.01
    #: Shed SLO: tolerated fraction of requests dropped unexecuted
    #: (deadline-expired in server queueing).
    shed_ratio: "float | None" = 0.05
    #: Goodput floor in requests/sec; trips when the served rate sits
    #: below it in both windows. Disarmed by default — it needs a
    #: deployment-specific number. Arms itself only after the rate has
    #: first REACHED the floor (a warming-up server is not an outage).
    goodput_floor_rps: "float | None" = None

    #: Window pair, in ticks. fast ≪ slow by construction.
    fast_ticks: int = 6
    slow_ticks: int = 60
    #: Burn-rate thresholds: windowed error ratio ÷ objective must
    #: exceed BOTH for a trip (14.4/6 ≙ the 1h/6h page pair scaled to
    #: tick cadence).
    burn_fast: float = 14.4
    burn_slow: float = 6.0
    #: Hysteresis streaks (the controller's raise/release posture): a
    #: condition must hold ``trip_streak`` consecutive ticks to trip
    #: and clear for ``clear_streak`` to untrip.
    trip_streak: int = 1
    clear_streak: int = 3
    #: Nominal tick seconds — used ONLY to turn the goodput window
    #: delta into a rate; never consulted for expiry or alert logic.
    tick_s: float = 0.5

    def __post_init__(self) -> None:
        if self.fast_ticks <= 0 or self.slow_ticks < self.fast_ticks:
            raise ValueError("need 0 < fast_ticks <= slow_ticks")
        if self.trip_streak <= 0 or self.clear_streak <= 0:
            raise ValueError("streaks must be positive")


class _DimState:
    __slots__ = ("tripped", "hot", "cold", "burn_fast", "burn_slow")

    def __init__(self) -> None:
        self.tripped = False
        self.hot = 0      # consecutive ticks over both thresholds
        self.cold = 0     # consecutive ticks under both
        self.burn_fast = 0.0
        self.burn_slow = 0.0


class BurnRateWatchdog:
    """Multi-window burn-rate alerting over a cumulative sample stream.

    :meth:`tick` consumes one flat mapping of CUMULATIVE counters —
    ``requests``, ``shed``, ``admitted_tokens``, ``overadmitted_tokens``,
    ``latency_total`` (histogram samples) and ``latency_bad`` (samples
    above the latency SLO, derived from the same cumulative buckets) —
    and returns the alerts emitted this tick. Alerts also land as
    ``kind="slo"`` flight-recorder frames and in the bounded
    :attr:`alert_log` (the deterministic schedule the seeded soak
    compares bit for bit); ``on_trip`` fires once per trip transition
    (the incident-bundle hook)."""

    _LOG_CAP = 256

    def __init__(self, cfg: "SLOConfig | None" = None, *,
                 flight_recorder=None,
                 on_trip: "Callable[[str, dict], None] | None" = None
                 ) -> None:
        self.cfg = cfg or SLOConfig()
        self.flight_recorder = flight_recorder
        self.on_trip = on_trip
        self.ticks = 0
        self.alerts = 0
        self.trips = 0
        self.clears = 0
        self._ring: deque[dict] = deque(maxlen=self.cfg.slow_ticks + 1)
        self._dims = {d: _DimState() for d in _DIMENSIONS}
        #: True once goodput has ever reached its floor (arming latch).
        self._goodput_armed = False
        self.alert_log: deque[dict] = deque(maxlen=self._LOG_CAP)

    # -- window math ---------------------------------------------------------
    def _delta(self, key: str, ticks: int) -> float:
        ring = self._ring
        newest = ring[-1]
        oldest = ring[max(0, len(ring) - 1 - ticks)]
        return max(0.0, float(newest.get(key, 0.0))
                   - float(oldest.get(key, 0.0)))

    def _ratio_burn(self, num: str, den: str, budget: float,
                    ticks: int) -> float:
        dd = self._delta(den, ticks)
        if dd <= 0.0:
            return 0.0
        return (self._delta(num, ticks) / dd) / budget

    # -- tick ----------------------------------------------------------------
    def tick(self, sample: Mapping[str, float]) -> list[dict]:
        """Consume one cumulative sample; returns this tick's alerts."""
        self.ticks += 1
        self._ring.append(dict(sample))
        cfg = self.cfg
        burns: dict[str, tuple[float, float]] = {}
        if cfg.overadmit_ratio is not None:
            burns["overadmit"] = (
                self._ratio_burn("overadmitted_tokens", "admitted_tokens",
                                 cfg.overadmit_ratio, cfg.fast_ticks),
                self._ratio_burn("overadmitted_tokens", "admitted_tokens",
                                 cfg.overadmit_ratio, cfg.slow_ticks))
        if cfg.latency_slo_s is not None:
            burns["latency"] = (
                self._ratio_burn("latency_bad", "latency_total",
                                 cfg.latency_bad_fraction, cfg.fast_ticks),
                self._ratio_burn("latency_bad", "latency_total",
                                 cfg.latency_bad_fraction, cfg.slow_ticks))
        if cfg.shed_ratio is not None:
            burns["shed"] = (
                self._ratio_burn("shed", "requests", cfg.shed_ratio,
                                 cfg.fast_ticks),
                self._ratio_burn("shed", "requests", cfg.shed_ratio,
                                 cfg.slow_ticks))
        if cfg.goodput_floor_rps is not None:
            burns["goodput"] = self._goodput_burns()
        out: list[dict] = []
        for dim, (fast, slow) in burns.items():
            st = self._dims[dim]
            st.burn_fast, st.burn_slow = fast, slow
            over = fast >= cfg.burn_fast and slow >= cfg.burn_slow
            alert = self._advance(dim, st, over)
            if alert is not None:
                out.append(alert)
        return out

    def _goodput_burns(self) -> tuple[float, float]:
        """Goodput burns: served rate below the floor reads as burn
        ``floor / rate`` (≥ thresholds once rate collapses), gated by
        the arming latch so a warming-up server never alarms."""
        cfg = self.cfg
        burns = []
        for ticks in (cfg.fast_ticks, cfg.slow_ticks):
            window = min(ticks, max(1, len(self._ring) - 1))
            rate = self._delta("requests", ticks) / (window * cfg.tick_s)
            if not self._goodput_armed:
                if rate >= cfg.goodput_floor_rps:
                    self._goodput_armed = True
                burns.append(0.0)
            elif rate <= 0.0:
                burns.append(max(cfg.burn_fast, cfg.burn_slow))
            else:
                burn = cfg.goodput_floor_rps / rate
                # Map "rate at/above floor" to zero burn so hysteresis
                # clears cleanly.
                burns.append(burn if burn > 1.0 else 0.0)
        return burns[0], burns[1]

    def _advance(self, dim: str, st: _DimState,
                 over: bool) -> "dict | None":
        cfg = self.cfg
        if over:
            st.hot += 1
            st.cold = 0
        else:
            st.cold += 1
            st.hot = 0
        if not st.tripped and st.hot >= cfg.trip_streak:
            st.tripped = True
            self.trips += 1
            return self._emit(dim, st, "trip")
        if st.tripped and st.cold >= cfg.clear_streak:
            st.tripped = False
            self.clears += 1
            return self._emit(dim, st, "clear")
        return None

    def _emit(self, dim: str, st: _DimState, state: str) -> dict:
        alert = {
            "tick": self.ticks,
            "slo": dim,
            "state": state,
            "burn_fast": round(st.burn_fast, 6),
            "burn_slow": round(st.burn_slow, 6),
            "window_fast_ticks": self.cfg.fast_ticks,
            "window_slow_ticks": self.cfg.slow_ticks,
        }
        self.alerts += 1
        self.alert_log.append(alert)
        if self.flight_recorder is not None:
            self.flight_recorder.record("slo", **alert)
        if state == "trip" and self.on_trip is not None:
            self.on_trip(dim, alert)
        return alert

    # -- introspection -------------------------------------------------------
    def tripped(self) -> list[str]:
        return [d for d in _DIMENSIONS if self._dims[d].tripped]

    def numeric_stats(self) -> dict:
        """Flat numeric dict for ``register_numeric_dict`` — the
        ``drl_slo_*`` families."""
        out = {
            "ticks": self.ticks,
            "alerts": self.alerts,
            "trips": self.trips,
            "clears": self.clears,
            "tripped_now": float(len(self.tripped())),
        }
        for dim in _DIMENSIONS:
            st = self._dims[dim]
            out[f"burn_fast_{dim}"] = round(st.burn_fast, 6)
            out[f"burn_slow_{dim}"] = round(st.burn_slow, 6)
        return out

    def snapshot(self) -> dict:
        """JSON-shaped status for OP_AUDIT / OP_STATS embedding."""
        out = self.numeric_stats()
        out["tripped"] = self.tripped()
        out["alert_log"] = list(self.alert_log)[-32:]
        return out
