"""Resilience primitives: retry policy with jittered backoff, and a
per-node circuit breaker.

The reference's whole failure posture is "log and keep serving from the
last-known state" (SURVEY.md invariant 9) — sufficient for one Redis,
but the distributed serving path (client → cluster → N store servers)
needs the two classic guards on top:

- :class:`RetryPolicy` — bounded, jittered exponential backoff. Naive
  synchronized retries are how rate limiters melt their own backends
  ("When Two is Worse Than One", PAPERS.md): a fleet of clients that
  all retry at t+1s is a thundering herd with a timer. Full jitter on
  the top half of the delay decorrelates them. The policy object is
  pure (delay computation only); WHO may retry WHAT is the caller's
  contract — see the at-most-once rules in ``runtime/remote.py`` and
  docs/DESIGN.md §11.
- :class:`CircuitBreaker` — the closed/open/half-open state machine.
  While open, callers shed (or serve a degraded fallback) instead of
  queueing behind a dead peer's timeout; after ``recovery_timeout_s``
  ONE probe at a time re-tests the node (half-open), so a still-down
  node costs one request per window, not a stampede.

Both are deliberately free of I/O and asyncio: deterministic under a
seeded ``random.Random`` / manual clock, so the chaos harness
(tests/test_chaos.py) can replay identical schedules.
"""

from __future__ import annotations

import random
import time
from dataclasses import dataclass
from typing import Callable

__all__ = ["RetryPolicy", "BreakerConfig", "CircuitBreaker"]


@dataclass(frozen=True)
class RetryPolicy:
    """Bounded retry with decorrelated exponential backoff.

    ``delay_s(attempt, rng)`` for attempt 1, 2, … grows as
    ``base · multiplier^(attempt-1)`` capped at ``max_delay_s``, with
    the top ``jitter`` fraction drawn uniformly (full-jitter on half
    the delay by default: herds decorrelate, yet the floor keeps the
    backoff meaningfully exponential).
    """

    max_attempts: int = 3          #: total tries, the first included
    base_delay_s: float = 0.05
    max_delay_s: float = 2.0
    multiplier: float = 2.0
    jitter: float = 0.5            #: fraction of the delay randomized

    def __post_init__(self) -> None:
        if self.max_attempts < 1:
            raise ValueError("max_attempts must be >= 1")
        if not 0.0 <= self.jitter <= 1.0:
            raise ValueError("jitter must be in [0, 1]")

    def delay_s(self, attempt: int, rng: "random.Random") -> float:
        """Backoff before retry number ``attempt`` (1-based)."""
        raw = min(self.max_delay_s,
                  self.base_delay_s * self.multiplier ** max(0, attempt - 1))
        return raw * (1.0 - self.jitter + self.jitter * rng.random())

    def max_total_delay_s(self) -> float:
        """Worst-case sum of all backoff sleeps — what a blocking caller
        adds to its own grace timeout so retries can finish."""
        return sum(
            min(self.max_delay_s, self.base_delay_s * self.multiplier ** i)
            for i in range(self.max_attempts - 1))


@dataclass(frozen=True)
class BreakerConfig:
    """Knobs of one :class:`CircuitBreaker` (docs/OPERATIONS.md §8)."""

    #: Consecutive failures that trip CLOSED → OPEN.
    failure_threshold: int = 5
    #: How long OPEN sheds before admitting a half-open probe.
    recovery_timeout_s: float = 1.0
    #: Consecutive half-open successes required to re-close.
    half_open_successes: int = 1


class CircuitBreaker:
    """Closed/open/half-open circuit breaker, single-threaded by design
    (all mutation happens on one event loop; the GIL guards the stray
    cross-thread read of ``state``).

    ``allow()`` is the admission gate and returns one of:

    - ``"allow"``  — CLOSED: proceed normally.
    - ``"reject"`` — OPEN inside the recovery window, or HALF_OPEN with
      the single probe slot already taken: shed / serve degraded.
    - ``"probe"``  — HALF_OPEN and this caller holds the probe slot: it
      MUST settle the probe via ``record_success``/``record_failure``
      (the cluster store probes with a health op — ``ping`` — before
      risking a real request).
    """

    CLOSED = "closed"
    OPEN = "open"
    HALF_OPEN = "half_open"

    def __init__(self, config: BreakerConfig | None = None, *,
                 clock: Callable[[], float] = time.monotonic,
                 on_transition: "Callable[[str, str], None] | None" = None
                 ) -> None:
        self.config = config or BreakerConfig()
        if self.config.failure_threshold < 1:
            raise ValueError("failure_threshold must be >= 1")
        self._clock = clock
        self._on_transition = on_transition
        self._state = self.CLOSED
        self._failures = 0
        self._successes = 0           # consecutive half-open successes
        self._opened_at = 0.0
        self._probe_inflight = False
        self._probe_started = 0.0
        # Counters for the metrics plane.
        self.opens = 0
        self.probes = 0

    @property
    def state(self) -> str:
        return self._state

    #: Numeric encoding for gauges: 0 closed, 1 half-open, 2 open.
    _STATE_GAUGE = {CLOSED: 0, HALF_OPEN: 1, OPEN: 2}

    def state_gauge(self) -> int:
        return self._STATE_GAUGE[self._state]

    def _transition(self, new: str) -> None:
        old, self._state = self._state, new
        if new == self.OPEN:
            self.opens += 1
            self._opened_at = self._clock()
            self._successes = 0
        elif new == self.CLOSED:
            self._failures = 0
            self._successes = 0
        if self._on_transition is not None and old != new:
            self._on_transition(old, new)

    def quarantined(self) -> bool:
        """True while OPEN inside the recovery window — a NON-consuming
        read (no probe slot is taken), for callers that cannot settle a
        probe (e.g. a blocking peek)."""
        return (self._state == self.OPEN
                and self._clock() - self._opened_at
                < self.config.recovery_timeout_s)

    def allow(self) -> str:
        if self._state == self.CLOSED:
            return "allow"
        if self._state == self.OPEN:
            if (self._clock() - self._opened_at
                    < self.config.recovery_timeout_s):
                return "reject"
            self._transition(self.HALF_OPEN)
            self._probe_inflight = False
        # HALF_OPEN: exactly one probe at a time — a still-down node
        # costs one request per recovery window, never a stampede. An
        # abandoned slot (holder cancelled without settling or calling
        # release_probe) is reclaimed after a recovery window, so a
        # leaked probe can never wedge the node in reject-forever.
        if self._probe_inflight:
            if (self._clock() - self._probe_started
                    < self.config.recovery_timeout_s):
                return "reject"
        self._probe_inflight = True
        self._probe_started = self._clock()
        self.probes += 1
        return "probe"

    def release_probe(self) -> None:
        """Free the half-open probe slot WITHOUT a verdict — for a
        holder cancelled mid-probe. The next ``allow()`` hands the slot
        to someone else. No-op when the slot is not held."""
        self._probe_inflight = False

    def record_success(self) -> None:
        self._probe_inflight = False
        if self._state == self.HALF_OPEN:
            self._successes += 1
            if self._successes >= self.config.half_open_successes:
                self._transition(self.CLOSED)
        elif self._state == self.CLOSED:
            self._failures = 0

    def record_failure(self) -> None:
        self._probe_inflight = False
        if self._state == self.HALF_OPEN:
            self._transition(self.OPEN)
        elif self._state == self.CLOSED:
            self._failures += 1
            if self._failures >= self.config.failure_threshold:
                self._transition(self.OPEN)

    def snapshot(self) -> dict:
        return {
            "state": self._state,
            "failures": self._failures,
            "opens": self.opens,
            "probes": self.probes,
        }
