"""Tracing — per-command profiling AND end-to-end distributed traces.

Two layers live here, one grown out of the other:

1. The reference's ``ProfilingSession`` seam (StackExchange.Redis): each
   options class exposes ``Func<ProfilingSession>? ProfilingSession``
   (``TokenBucket/RedisTokenBucketRateLimiterOptions.cs:70``) and the
   limiter registers it on connect (``TryRegisterProfiler``,
   ``TokenBucket/RedisTokenBucketRateLimiter.cs:166-174``), after which
   per-command timings accrue to whichever session the factory returns.
   Here the "commands" are kernel launches and wire round-trips —
   :class:`ProfilingSession` / :class:`Profiler` below are that seam.

2. Request-scoped causality: :class:`Tracer` grows the per-command seam
   into a full span-tree tracer. A :class:`TraceContext` (128-bit
   trace id, 64-bit span id, sampled flag — the W3C ``traceparent``
   triple) starts at the client wire layer, rides every frame as a
   version-gated optional tail (:mod:`~..runtime.wire`), and re-parents
   each hop's spans: server dispatch → micro-batcher queue/flush →
   store kernel launch → cluster per-node fan-out → native tier-0 local
   decisions. Completed traces land in a bounded in-memory buffer,
   tail-sampled (traces ending ``denied``/``queued``/``error``/
   ``degraded`` or exceeding a latency threshold are always kept;
   otherwise the head-sampling coin already decided), and export as
   Chrome-trace-event JSON loadable in Perfetto / chrome://tracing.

Sampling model (the <3% serving-overhead contract, audited by the
``serving_metrics_overhead`` bench arm):

- head sampling: at trace start a coin with ``sample_rate`` decides
  whether the request records AT ALL. A non-sampled request takes the
  shared :data:`_NULL_SPAN` everywhere — no allocation, no wire tail.
- tail keep: among recorded traces, any span status other than ``ok``
  (``denied``, ``queued``, ``error``, ``degraded``) or any span at or
  above ``latency_threshold_s`` forces the trace into the export
  buffer; boring recorded traces survive with ``keep_rate``.

The default (tracer disabled, no profiling factory) path is
allocation-free: ``span``/``start_span`` return a shared no-op context
manager, so serving-path cost is one-or-two ``if``\\ s.
"""

from __future__ import annotations

import json
import random
import threading
import time
from collections import deque
from contextlib import contextmanager
from contextvars import ContextVar
from typing import Callable, Iterator, NamedTuple

__all__ = [
    "ProfiledCommand",
    "ProfilingSession",
    "Profiler",
    "TraceContext",
    "Span",
    "Tracer",
    "get_tracer",
    "configure",
    "current_context",
    "current_span",
    "mark",
    "start_device_trace",
    "stop_device_trace",
]


class ProfiledCommand(NamedTuple):
    """One store dispatch (≙ StackExchange.Redis's ``IProfiledCommand``)."""

    command: str       # e.g. "acquire_batch", "sync_counter", "sweep"
    start_s: float     # time.perf_counter() at dispatch
    duration_s: float  # host wall time of the dispatch (enqueue, not device)
    rows: int          # valid rows in the batch (1 for scalar commands)


class ProfilingSession:
    """Accumulates profiled commands. Thread-safe; drain with
    :meth:`finish` (≙ ``ProfilingSession.FinishProfiling()``)."""

    def __init__(self) -> None:
        self._commands: list[ProfiledCommand] = []
        self._lock = threading.Lock()

    def record(self, cmd: ProfiledCommand) -> None:
        with self._lock:
            self._commands.append(cmd)

    @property
    def commands(self) -> list[ProfiledCommand]:
        with self._lock:
            return list(self._commands)

    def finish(self) -> list[ProfiledCommand]:
        """Return all captured commands and reset the session."""
        with self._lock:
            out = self._commands
            self._commands = []
            return out


# ---------------------------------------------------------------------------
# Trace context + spans
# ---------------------------------------------------------------------------

class TraceContext(NamedTuple):
    """The wire-propagated triple: (trace id, parent span id, flags) —
    the W3C ``traceparent`` shape with the 128-bit trace id split into
    two u64 halves so the wire tail packs as ``<QQQB``. ``flags`` bit 0
    is the head-sampled flag: a downstream hop records its spans for
    this trace regardless of its own coin."""

    trace_hi: int
    trace_lo: int
    span_id: int
    flags: int = 1

    @property
    def sampled(self) -> bool:
        return bool(self.flags & 1)

    @property
    def trace_id(self) -> str:
        return f"{self.trace_hi:016x}{self.trace_lo:016x}"


#: Context variable holding the ambient (innermost open) span of the
#: current task/thread. Spans set it on ``__enter__``; the batcher and
#: wire layers capture it to link work that crosses tasks/threads.
_CURRENT: "ContextVar[Span | None]" = ContextVar("drl_trace_span",
                                                default=None)

#: Span statuses the tail sampler treats as "always keep".
_INTERESTING = frozenset(("denied", "queued", "error", "degraded"))


class Span:
    """One timed node of a trace tree. Context-manager; cheap by design
    (``__slots__``, two ``perf_counter`` reads, one lock append at
    end)."""

    __slots__ = ("_tracer", "name", "trace_hi", "trace_lo", "span_id",
                 "parent_id", "flags", "start_s", "duration_s", "status",
                 "attrs", "_token")

    def __init__(self, tracer: "Tracer", name: str, trace_hi: int,
                 trace_lo: int, span_id: int, parent_id: int,
                 flags: int) -> None:
        self._tracer = tracer
        self.name = name
        self.trace_hi = trace_hi
        self.trace_lo = trace_lo
        self.span_id = span_id
        self.parent_id = parent_id
        self.flags = flags
        self.start_s = time.perf_counter()
        self.duration_s = 0.0
        self.status = "ok"
        self.attrs: dict | None = None
        self._token = None

    @property
    def context(self) -> TraceContext:
        """This span as a wire-propagatable parent reference."""
        return TraceContext(self.trace_hi, self.trace_lo, self.span_id,
                            self.flags)

    def set_status(self, status: str) -> None:
        self.status = status

    def set_attr(self, key: str, value) -> None:
        if self.attrs is None:
            self.attrs = {}
        self.attrs[key] = value

    def __enter__(self) -> "Span":
        self._token = _CURRENT.set(self)
        return self

    def __exit__(self, exc_type, exc, tb) -> None:
        if self._token is not None:
            _CURRENT.reset(self._token)
            self._token = None
        if exc is not None and self.status == "ok":
            self.status = "error"
            self.set_attr("exception", repr(exc))
        self.end()

    def end(self) -> None:
        self.duration_s = time.perf_counter() - self.start_s
        self._tracer._on_span_end(self)


class _NullSpan:
    """Shared no-op stand-in for :class:`Span` (and the profiler's timed
    span): the untraced path allocates nothing and pays one ``if``."""

    __slots__ = ()

    #: Null spans carry no propagatable context (nothing to stamp on the
    #: wire) — callers test ``span.context is not None``.
    context = None

    def __enter__(self) -> "_NullSpan":
        return self

    def __exit__(self, *exc: object) -> None:
        return None

    def set_status(self, status: str) -> None:
        return None

    def set_attr(self, key: str, value) -> None:
        return None

    def end(self) -> None:
        return None


_NULL_SPAN = _NullSpan()


class _ActiveTrace:
    """Book-keeping for a trace with locally open spans: completed span
    records plus the open-span refcount that triggers finalization."""

    __slots__ = ("spans", "open", "started_mono")

    def __init__(self) -> None:
        self.spans: list[dict] = []
        self.open = 0
        self.started_mono = time.monotonic()


class Tracer:
    """Span recorder + tail sampler + bounded trace buffer.

    Thread-safe: spans may end on the server loop, the remote client's
    I/O loop, the native pump thread, and blocking callers at once.
    A trace finalizes when its last locally-open span ends (the local
    root — client root in-process, server dispatch span on a remote
    node); late completed spans (the native tier-0 harvest) finalize as
    their own single-span entries and merge by trace id at export.
    """

    def __init__(self, *, enabled: bool = False, sample_rate: float = 1.0,
                 keep_rate: float = 0.1, latency_threshold_s: float = 0.05,
                 max_traces: int = 256, max_active: int = 512,
                 service: str = "drl") -> None:
        self.enabled = enabled
        self.sample_rate = sample_rate
        self.keep_rate = keep_rate
        self.latency_threshold_s = latency_threshold_s
        self.max_traces = max_traces
        self.max_active = max_active
        self.service = service
        self._lock = threading.Lock()
        self._active: dict[tuple[int, int], _ActiveTrace] = {}
        self._finished: deque[dict] = deque(maxlen=max_traces)
        # Wall-clock anchor for export: span stamps are perf_counter
        # (CLOCK_MONOTONIC), one shared offset maps them to epoch µs.
        self._wall_base = time.time() - time.perf_counter()
        self.spans_recorded = 0
        self.traces_kept = 0
        self.traces_dropped = 0
        self.traces_evicted = 0

    def configure(self, **kw) -> None:
        """Update knobs in place (the module-level :func:`configure`
        mutates the process-global tracer through this)."""
        for k, v in kw.items():
            if not hasattr(self, k):
                raise AttributeError(f"tracer has no knob {k!r}")
            setattr(self, k, v)
        if "max_traces" in kw:
            with self._lock:
                self._finished = deque(self._finished,
                                       maxlen=self.max_traces)

    # -- span creation ------------------------------------------------------
    def start_span(self, name: str,
                   parent: "TraceContext | Span | None" = None,
                   attrs: dict | None = None) -> "Span | _NullSpan":
        """Open a span. ``parent`` may be an explicit
        :class:`TraceContext` (a wire-decoded remote parent or a context
        captured across threads), a live :class:`Span`, or ``None`` —
        then the ambient span is the parent, and with no ambient span a
        NEW trace starts, subject to the head-sampling coin."""
        if not self.enabled:
            return _NULL_SPAN
        if parent is None:
            parent = _CURRENT.get()
        if parent is None:
            # New trace: the head-sampling coin decides recording; a
            # failed coin is the allocation-free null path end-to-end.
            if self.sample_rate < 1.0 and random.random() >= self.sample_rate:
                return _NULL_SPAN
            hi = random.getrandbits(64) or 1
            lo = random.getrandbits(64) or 1
            span = Span(self, name, hi, lo, random.getrandbits(64) or 1,
                        0, 1)
        else:
            # A live Span and a TraceContext expose the same four
            # fields — one child-construction path serves both.
            span = Span(self, name, parent.trace_hi, parent.trace_lo,
                        random.getrandbits(64) or 1, parent.span_id,
                        parent.flags)
        if attrs:
            span.attrs = dict(attrs)
        key = (span.trace_hi, span.trace_lo)
        with self._lock:
            entry = self._active.get(key)
            if entry is None:
                if len(self._active) >= self.max_active:
                    # Leaked/lost traces must not grow without bound:
                    # evict the stalest active entry.
                    stale = min(self._active,
                                key=lambda k: self._active[k].started_mono)
                    del self._active[stale]
                    self.traces_evicted += 1
                entry = self._active[key] = _ActiveTrace()
            entry.open += 1
        return span

    def record_span(self, name: str, parent: TraceContext,
                    start_s: float, end_s: float, *, status: str = "ok",
                    attrs: dict | None = None) -> None:
        """Add an already-completed span (start/end in ``perf_counter``
        seconds — the same CLOCK_MONOTONIC epoch the native front-end
        stamps). Used for spans reconstructed after the fact: batcher
        queue waits, native tier-0 local decisions harvested from C."""
        if not self.enabled or parent is None:
            return
        rec = {
            "name": name,
            "trace_hi": parent.trace_hi,
            "trace_lo": parent.trace_lo,
            "span_id": random.getrandbits(64) or 1,
            "parent_id": parent.span_id,
            "flags": parent.flags,
            "start_s": start_s,
            "dur_s": max(end_s - start_s, 0.0),
            "status": status,
            "attrs": attrs,
        }
        key = (parent.trace_hi, parent.trace_lo)
        with self._lock:
            self.spans_recorded += 1
            entry = self._active.get(key)
            if entry is not None:
                entry.spans.append(rec)
            else:
                # No locally-open spans for this trace (a late arrival,
                # e.g. the tier-0 harvest on a server that decided the
                # request entirely in C): finalize as its own entry —
                # export merges entries by trace id.
                self._finalize_locked(key, [rec])

    def _on_span_end(self, span: Span) -> None:
        rec = {
            "name": span.name,
            "trace_hi": span.trace_hi,
            "trace_lo": span.trace_lo,
            "span_id": span.span_id,
            "parent_id": span.parent_id,
            "flags": span.flags,
            "start_s": span.start_s,
            "dur_s": span.duration_s,
            "status": span.status,
            "attrs": span.attrs,
        }
        key = (span.trace_hi, span.trace_lo)
        with self._lock:
            self.spans_recorded += 1
            entry = self._active.get(key)
            if entry is None:  # evicted under pressure: orphan entry
                self._finalize_locked(key, [rec])
                return
            entry.spans.append(rec)
            entry.open -= 1
            if entry.open <= 0:
                del self._active[key]
                self._finalize_locked(key, entry.spans)

    # -- tail sampling ------------------------------------------------------
    def _finalize_locked(self, key: tuple[int, int],
                         spans: list[dict]) -> None:
        # Tail decision (lock held — the checks are O(spans), tiny):
        # interesting outcomes and slow spans are ALWAYS kept; boring
        # traces survive the keep_rate coin. The head coin already gated
        # recording, so this prunes the buffer, not the hot path.
        keep = any(s["status"] in _INTERESTING
                   or s["dur_s"] >= self.latency_threshold_s
                   for s in spans)
        if not keep and self.keep_rate < 1.0:
            keep = random.random() < self.keep_rate
        elif not keep:
            keep = True
        if not keep:
            self.traces_dropped += 1
            return
        self.traces_kept += 1
        self._finished.append({
            "trace_id": f"{key[0]:016x}{key[1]:016x}",
            "spans": spans,
        })

    # -- export -------------------------------------------------------------
    def traces(self, drain: bool = False) -> list[dict]:
        """Finished (kept) traces, newest last, entries with one trace id
        merged. ``drain=True`` empties the buffer."""
        with self._lock:
            entries = list(self._finished)
            if drain:
                self._finished.clear()
        merged: dict[str, dict] = {}
        for e in entries:
            tgt = merged.get(e["trace_id"])
            if tgt is None:
                merged[e["trace_id"]] = {"trace_id": e["trace_id"],
                                         "spans": list(e["spans"])}
            else:
                tgt["spans"].extend(e["spans"])
        return list(merged.values())

    def export_chrome(self, drain: bool = False,
                      max_traces: int | None = None) -> dict:
        """Chrome-trace-event JSON (the ``traceEvents`` array form) —
        loadable directly in Perfetto / chrome://tracing. One complete
        (``ph: "X"``) event per span; each trace renders as its own
        thread row; span/parent/trace ids and status travel in
        ``args`` so the UI's selection pane cross-references the
        exemplar and flight-recorder ids."""
        traces = self.traces(drain=drain)
        if max_traces is not None:
            traces = traces[-max_traces:]
        return self._chrome_export(traces)

    def _chrome_export(self, traces: list[dict]) -> dict:
        events: list[dict] = [
            {"ph": "M", "pid": 1, "tid": 0, "name": "process_name",
             "args": {"name": self.service}},
        ]
        for tid, trace in enumerate(traces, start=1):
            events.append({"ph": "M", "pid": 1, "tid": tid,
                           "name": "thread_name",
                           "args": {"name": trace["trace_id"]}})
            for s in trace["spans"]:
                ev = {
                    "ph": "X",
                    "pid": 1,
                    "tid": tid,
                    "name": s["name"],
                    "cat": s["status"],
                    "ts": (self._wall_base + s["start_s"]) * 1e6,
                    "dur": s["dur_s"] * 1e6,
                    "args": {
                        "trace_id": trace["trace_id"],
                        "span_id": f"{s['span_id']:016x}",
                        "parent_span_id": f"{s['parent_id']:016x}",
                        "status": s["status"],
                    },
                }
                if s.get("attrs"):
                    ev["args"].update(s["attrs"])
                events.append(ev)
        return {"traceEvents": events, "displayTimeUnit": "ms"}

    def export_chrome_json(self, max_bytes: int | None = None,
                           drain: bool = False) -> str:
        """Serialized :meth:`export_chrome`, optionally size-capped for
        transports with a frame bound (the ``OP_TRACES`` wire op): the
        newest traces that fit ``max_bytes`` survive. The buffer is
        read (and, when asked, drained) exactly ONCE — the size cap
        halves a snapshot, so capping never costs traces beyond those
        it drops from the oversized export itself."""
        traces = self.traces(drain=drain)
        while True:
            text = json.dumps(self._chrome_export(traces),
                              separators=(",", ":"))
            if max_bytes is None or len(text) <= max_bytes or not traces:
                return text
            # Keep the newest half; a single oversized trace drops to
            # the bare metadata export rather than looping forever.
            traces = (traces[-(len(traces) // 2):]
                      if len(traces) > 1 else [])

    def snapshot(self) -> dict:
        """Counters for OP_STATS / the metrics registry."""
        with self._lock:
            return {
                "enabled": self.enabled,
                "sample_rate": self.sample_rate,
                "spans_recorded": self.spans_recorded,
                "traces_kept": self.traces_kept,
                "traces_dropped": self.traces_dropped,
                "traces_evicted": self.traces_evicted,
                "traces_buffered": len(self._finished),
                "traces_active": len(self._active),
            }

    def reset(self) -> None:
        with self._lock:
            self._active.clear()
            self._finished.clear()
            self.spans_recorded = 0
            self.traces_kept = 0
            self.traces_dropped = 0
            self.traces_evicted = 0


#: Process-global tracer (≙ the jax profiler's process-global trace):
#: every layer references it at call time, so one configure() call turns
#: the whole process's tracing on — client, server, store, native pump.
_GLOBAL_TRACER = Tracer()


def get_tracer() -> Tracer:
    return _GLOBAL_TRACER


def configure(**kw) -> Tracer:
    """Configure the process-global tracer (``enabled``, ``sample_rate``,
    ``keep_rate``, ``latency_threshold_s``, ``max_traces`` …) and return
    it."""
    _GLOBAL_TRACER.configure(**kw)
    return _GLOBAL_TRACER


def current_span() -> "Span | None":
    return _CURRENT.get()


def current_context() -> TraceContext | None:
    """The ambient span's wire-propagatable context (``None`` untraced) —
    what callers capture BEFORE hopping threads/loops, where the context
    variable does not follow."""
    span = _CURRENT.get()
    return None if span is None else span.context


def mark(status: str) -> None:
    """Set the ambient span's status (``queued``, ``degraded``, …) — the
    hook non-wire layers use to make the tail sampler keep a trace."""
    span = _CURRENT.get()
    if span is not None:
        span.set_status(status)


class Profiler:
    """Per-store profiler facade. ``session_factory`` may return ``None``
    to skip recording a given command (the StackExchange contract).
    When the global tracer has an ambient trace, every profiled span is
    ALSO recorded as a child span named ``store.<command>`` — the
    existing dispatch sites double as the kernel-launch layer of the
    distributed trace."""

    __slots__ = ("session_factory",)

    def __init__(
        self,
        session_factory: Callable[[], ProfilingSession | None] | None = None,
    ) -> None:
        self.session_factory = session_factory

    @property
    def enabled(self) -> bool:
        return self.session_factory is not None

    def span(self, command: str, rows: int = 1, *, annotate: bool = True,
             enabled: bool = True):
        """Context manager timing one dispatch. No-op (shared, allocation
        free) unless a session factory is registered or an ambient trace
        is active.

        ``annotate=False`` skips the ``jax.profiler.TraceAnnotation``: trace
        annotations must nest strictly per thread, so spans that wrap
        ``await``s which interleave on one event loop (the remote client's
        wire round-trips) record timings only. ``enabled=False`` forces the
        no-op — for inner dispatches whose rows an outer span already
        counted (the coalesced-acquire flush would double-count its
        requests otherwise)."""
        if not enabled:
            return _NULL_SPAN
        traced = _GLOBAL_TRACER.enabled and _CURRENT.get() is not None
        if self.session_factory is None and not traced:
            return _NULL_SPAN
        return self._timed_span(command, rows, annotate, traced)

    @contextmanager
    def _timed_span(self, command: str, rows: int, annotate: bool,
                    traced: bool = False) -> Iterator[None]:
        session = self.session_factory() if self.session_factory else None
        tspan = (_GLOBAL_TRACER.start_span(f"store.{command}",
                                           attrs={"rows": rows})
                 if traced else _NULL_SPAN)
        start = time.perf_counter()
        if annotate:
            annotation = _trace_annotation()(f"drl/{command}")
            annotation.__enter__()
        try:
            yield
        except BaseException:
            tspan.set_status("error")
            raise
        finally:
            if annotate:
                annotation.__exit__(None, None, None)
            tspan.end()
            if session is not None:
                session.record(ProfiledCommand(
                    command, start, time.perf_counter() - start, rows,
                ))


#: jax.profiler.TraceAnnotation, cached after first use: the annotated
#: hot path must not re-run the ``import jax`` machinery inside every
#: span (a sys.modules lookup per launch, measured as its own line item
#: in the overhead audit). Resolved lazily so importing this module
#: never forces jax in (pure-wire clients import it via remote.py).
_TRACE_ANNOTATION = None


def _trace_annotation():
    global _TRACE_ANNOTATION
    if _TRACE_ANNOTATION is None:
        from jax.profiler import TraceAnnotation

        _TRACE_ANNOTATION = TraceAnnotation
    return _TRACE_ANNOTATION


def start_device_trace(logdir: str) -> None:
    """Begin a device trace (XProf/Perfetto) covering subsequent kernel
    launches; host-side :meth:`Profiler.span` annotations appear inline.
    The TPU analogue of attaching a wire-level profiler to the Redis
    connection."""
    import jax

    jax.profiler.start_trace(logdir)


def stop_device_trace() -> None:
    import jax

    jax.profiler.stop_trace()
