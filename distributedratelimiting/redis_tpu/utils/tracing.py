"""Tracing/profiling — the reference's ``ProfilingSession`` seam, TPU-style.

The reference delegates tracing to StackExchange.Redis: each options class
exposes ``Func<ProfilingSession>? ProfilingSession``
(``TokenBucket/RedisTokenBucketRateLimiterOptions.cs:70``) and the limiter
registers it on connect (``TryRegisterProfiler``,
``TokenBucket/RedisTokenBucketRateLimiter.cs:166-174``), after which the
client library captures per-command timings attributed to whichever session
the factory returns at call time.

Here the "commands" are kernel launches, so the equivalent is:

- :class:`ProfilingSession` — collects :class:`ProfiledCommand` records
  (command name, start, duration, batch rows), thread-safe because launches
  may be dispatched from the event loop and from blocking callers at once.
- :class:`Profiler` — holds the ``session_factory`` (≙ the
  ``Func<ProfilingSession>``; invoked per command so callers can route
  commands to per-request/ambient sessions exactly as the StackExchange
  profiler does) and wraps every store dispatch in :meth:`Profiler.span`.
  Each span also enters ``jax.profiler.TraceAnnotation``, so host-side
  spans line up with device activity in Perfetto/XProf traces captured via
  :func:`start_device_trace`.

The default (no factory) path is allocation-free: ``span`` returns a shared
no-op context manager, so serving-path cost is one ``if``.
"""

from __future__ import annotations

import threading
import time
from contextlib import contextmanager
from typing import Callable, Iterator, NamedTuple

__all__ = [
    "ProfiledCommand",
    "ProfilingSession",
    "Profiler",
    "start_device_trace",
    "stop_device_trace",
]


class ProfiledCommand(NamedTuple):
    """One store dispatch (≙ StackExchange.Redis's ``IProfiledCommand``)."""

    command: str       # e.g. "acquire_batch", "sync_counter", "sweep"
    start_s: float     # time.perf_counter() at dispatch
    duration_s: float  # host wall time of the dispatch (enqueue, not device)
    rows: int          # valid rows in the batch (1 for scalar commands)


class ProfilingSession:
    """Accumulates profiled commands. Thread-safe; drain with
    :meth:`finish` (≙ ``ProfilingSession.FinishProfiling()``)."""

    def __init__(self) -> None:
        self._commands: list[ProfiledCommand] = []
        self._lock = threading.Lock()

    def record(self, cmd: ProfiledCommand) -> None:
        with self._lock:
            self._commands.append(cmd)

    @property
    def commands(self) -> list[ProfiledCommand]:
        with self._lock:
            return list(self._commands)

    def finish(self) -> list[ProfiledCommand]:
        """Return all captured commands and reset the session."""
        with self._lock:
            out = self._commands
            self._commands = []
            return out


class _NullSpan:
    __slots__ = ()

    def __enter__(self) -> None:
        return None

    def __exit__(self, *exc: object) -> None:
        return None


_NULL_SPAN = _NullSpan()


class Profiler:
    """Per-store profiler facade. ``session_factory`` may return ``None``
    to skip recording a given command (the StackExchange contract)."""

    __slots__ = ("session_factory",)

    def __init__(
        self,
        session_factory: Callable[[], ProfilingSession | None] | None = None,
    ) -> None:
        self.session_factory = session_factory

    @property
    def enabled(self) -> bool:
        return self.session_factory is not None

    def span(self, command: str, rows: int = 1, *, annotate: bool = True,
             enabled: bool = True):
        """Context manager timing one dispatch. No-op (shared, allocation
        free) unless a session factory is registered.

        ``annotate=False`` skips the ``jax.profiler.TraceAnnotation``: trace
        annotations must nest strictly per thread, so spans that wrap
        ``await``s which interleave on one event loop (the remote client's
        wire round-trips) record timings only. ``enabled=False`` forces the
        no-op — for inner dispatches whose rows an outer span already
        counted (the coalesced-acquire flush would double-count its
        requests otherwise)."""
        if not enabled or self.session_factory is None:
            return _NULL_SPAN
        return self._timed_span(command, rows, annotate)

    @contextmanager
    def _timed_span(self, command: str, rows: int,
                    annotate: bool) -> Iterator[None]:
        session = self.session_factory() if self.session_factory else None
        start = time.perf_counter()
        if annotate:
            import jax

            annotation = jax.profiler.TraceAnnotation(f"drl/{command}")
            annotation.__enter__()
        try:
            yield
        finally:
            if annotate:
                annotation.__exit__(None, None, None)
            if session is not None:
                session.record(ProfiledCommand(
                    command, start, time.perf_counter() - start, rows,
                ))


def start_device_trace(logdir: str) -> None:
    """Begin a device trace (XProf/Perfetto) covering subsequent kernel
    launches; host-side :meth:`Profiler.span` annotations appear inline.
    The TPU analogue of attaching a wire-level profiler to the Redis
    connection."""
    import jax

    jax.profiler.start_trace(logdir)


def stop_device_trace() -> None:
    import jax

    jax.profiler.stop_trace()
