"""Force a CPU-only jax platform before first backend init.

The environment's ``sitecustomize`` registers a remote TPU PJRT plugin
("axon") at interpreter startup; when its relay is unreachable, *any*
backend init — even CPU-only — hangs indefinitely, and because the env
snapshot happens at import time, setting ``JAX_PLATFORMS`` later is not
enough. The cure (used by both the test suite's conftest and the
multi-chip dry-run child) is to deregister the plugin and pin the
platform at the config level before the first array op.

Keep this the single copy of the workaround: tests/conftest.py and
``__graft_entry__``'s re-exec stub both import it.
"""

from __future__ import annotations

__all__ = [
    "force_cpu_platform",
    "maybe_force_cpu_from_env",
    "set_virtual_device_count",
    "XLA_DEVICE_COUNT_FLAG",
]

#: When this env var is "1", console entry points (store server, testapp)
#: pin jax to CPU before first use. Needed because the environment's
#: sitecustomize overrides ``JAX_PLATFORMS`` programmatically, so child
#: processes cannot opt out of the remote-TPU plugin via env alone.
FORCE_CPU_ENV = "DRLT_FORCE_CPU_PLATFORM"


def maybe_force_cpu_from_env() -> None:
    import os

    if os.environ.get(FORCE_CPU_ENV) == "1":
        force_cpu_platform()

XLA_DEVICE_COUNT_FLAG = "--xla_force_host_platform_device_count"


def set_virtual_device_count(env: dict, n_devices: int) -> None:
    """Point ``env`` at an ``n_devices``-device virtual CPU platform.

    Replaces (never appends next to) any inherited device-count flag —
    two occurrences would leave the effective count at XLA's mercy.
    ``XLA_FLAGS`` is read at backend init, so mutating ``os.environ``
    with this before the first array op also works in-process.
    """
    import re

    env["JAX_PLATFORMS"] = "cpu"
    env["XLA_FLAGS"] = (
        re.sub(rf"{XLA_DEVICE_COUNT_FLAG}=\S+", "", env.get("XLA_FLAGS", ""))
        + f" {XLA_DEVICE_COUNT_FLAG}={n_devices}"
    )


def force_cpu_platform() -> None:
    """Deregister the axon PJRT plugin and pin jax to the CPU platform.

    Must run before jax's first backend init. Raises if the (private)
    deregistration API has moved — failing loudly beats hanging forever
    on an unreachable relay (the silent-failure mode this guards).
    """
    from jax._src import xla_bridge

    xla_bridge._backend_factories.pop("axon", None)
    import jax

    jax.config.update("jax_platforms", "cpu")
