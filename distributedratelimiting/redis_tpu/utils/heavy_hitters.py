"""Space-saving top-K sketch — hot-key telemetry for the serving path.

Which keys dominate admission traffic is the observability input behind
two of the framework's own mechanisms (the tier-0 admission cache hosts
exactly these keys; shard skew is these keys' routing) and the first
question of any rate-limiting incident ("who is being limited?" —
per-tenant visibility is a first-class requirement in the scalable-rate-
limiting literature, PAPERS.md). A full per-key counter table is
unbounded; the space-saving sketch (Metwally et al.) keeps exactly K
monitored keys in O(K) memory with the classic guarantee: any key whose
true count exceeds N/K is monitored, and each reported count overshoots
the true count by at most that entry's recorded ``error``.

Overhead discipline (the <3% serving-plane budget):

- ``offer`` is one dict hit for a monitored key; eviction (unmonitored
  key, full table) finds the minimum through a lazily-repaired heap —
  amortized O(log K), not an O(K) scan (measured: the scan cost
  7.3µs/offer on a cold-tail workload at K=64; the heap ~1.5µs).
- ``offer_buffered`` is the per-request lane's feed: one list append
  (~0.1µs), merged through a C-speed ``Counter`` pass every 1024
  observations (at most ``batch_top`` sketch merges per pass) — the
  sketch lags the stream by at most one buffer (drained on every read),
  and the hot path never pays an eviction.
- ``offer_many`` batches: one C-speed ``Counter`` pass over the batch,
  then at most ``2·K`` sketch merges regardless of batch size. Keys
  below the per-batch top-2K never reach the sketch — a true heavy
  hitter is by definition in its batches' tops, so the truncation costs
  tail fidelity (which space-saving never promised), not head fidelity.
- ``offer_blob`` feeds the zero-copy bulk lane (``wire.KeyBlob``)
  without materializing per-key strings: a bounded (strided) sample of
  the frame's positive-cost rows is tallied as BYTE slices, only the
  per-frame top ``batch_top`` survivors decode to ``str`` and merge —
  the asyncio bulk analogue of the native lane's per-frame C
  aggregation (frontend.cc ``bulk_hot_feed``). Sampling scales the
  surviving weights by the frame's total, so head weight is preserved
  in expectation while per-frame cost stays O(sample).
- Offers are **cost-weighted** everywhere (an N-token admission weighs
  N): the sketch's counts are TOKENS, which is what makes its top-K the
  hot-*cost* split-candidate feed the resharder consumes
  (``ClusterBucketStore.split_hot_keys``) and the denominator of the
  token-velocity signal (runtime/admission.py).
"""

from __future__ import annotations

import heapq
from collections import Counter
from typing import Iterable, Sequence

import numpy as np

__all__ = ["HeavyHitters"]


class HeavyHitters:
    """Bounded top-K frequency sketch over string keys."""

    __slots__ = ("k", "batch_top", "_counts", "_errors", "_heap", "_buf",
                 "buffer_limit", "offered")

    def __init__(self, k: int = 64, batch_top: int | None = None,
                 buffer_limit: int = 1024) -> None:
        if k <= 0:
            raise ValueError("k must be positive")
        self.k = k
        #: Per-``offer_many`` merge cap (default 2·K, the space-saving
        #: working-set rule of thumb).
        self.batch_top = batch_top if batch_top is not None else 2 * k
        self._counts: dict[str, float] = {}
        self._errors: dict[str, float] = {}
        # Lazy min-heap of (count, key): increments leave entries
        # stale-LOW (counts only grow), repaired when they surface at
        # the top — one entry per monitored key, so size ≤ K.
        self._heap: list[tuple[float, str]] = []
        # offer_buffered's unit-weight staging list (see module doc).
        self._buf: list[str] = []
        self.buffer_limit = buffer_limit
        #: Total weight offered (the sketch's N — the error bound is N/K).
        self.offered = 0.0

    def __len__(self) -> int:
        self._drain()
        return len(self._counts)

    def _drain(self) -> None:
        if self._buf:
            buf = self._buf
            self._buf = []
            self.offer_many(buf)

    def offer(self, key: str, count: float = 1.0) -> None:
        """Count one observation of ``key`` with weight ``count``."""
        self.offered += count
        counts = self._counts
        if key in counts:
            counts[key] += count  # heap entry goes stale; repaired lazily
            return
        if len(counts) < self.k:
            counts[key] = count
            self._errors[key] = 0.0
            heapq.heappush(self._heap, (count, key))
            return
        # Surface the true minimum: pop/repair stale tops (each repair
        # re-sinks an entry with its current count; every entry is
        # repaired at most once per real increment, so the lazy heap is
        # amortized O(log K) where a dict min-scan was O(K)).
        heap = self._heap
        while True:
            cnt, victim = heap[0]
            actual = counts.get(victim)
            if actual == cnt:
                break
            heapq.heappop(heap)
            if actual is not None:
                heapq.heappush(heap, (actual, victim))
        # Evict it; the newcomer inherits its count as the overestimate
        # bound (the space-saving replacement rule).
        heapq.heappop(heap)
        floor = counts.pop(victim)
        self._errors.pop(victim, None)
        counts[key] = floor + count
        self._errors[key] = floor
        heapq.heappush(heap, (floor + count, key))

    def offer_buffered(self, key: str) -> None:
        """Unit-weight per-request feed: stage the key and merge every
        ``buffer_limit`` observations (one append on the hot path; reads
        drain the buffer first, so nothing is ever lost — only deferred)."""
        buf = self._buf
        buf.append(key)
        if len(buf) >= self.buffer_limit:
            self._buf = []
            self.offer_many(buf)

    def offer_many(self, keys: "Sequence[str] | Iterable[str]",
                   counts: "Sequence[float] | None" = None) -> None:
        """Batch feed: count the batch once at C speed, merge only its
        top ``batch_top`` keys (bounded work per call — see module doc)."""
        if counts is None:
            tally = Counter(keys)
        else:
            tally = Counter()
            for key, c in zip(keys, counts):
                tally[key] += c
        total = float(sum(tally.values()))
        merged = 0.0
        # most_common(k) is heapq.nlargest — O(n log batch_top), no full
        # sort of the batch's unique keys.
        for key, c in tally.most_common(self.batch_top):
            self.offer(key, float(c))
            merged += c
        self.offered += total - merged  # truncated tail still counts in N

    def offer_blob(self, blob: bytes, offsets, counts, *,
                   sample: int = 4096) -> None:
        """Cost-weighted feed straight off a bulk frame's key blob (see
        module doc). ``offsets`` is the ``i64[n+1]`` boundary array of a
        :class:`~.runtime.wire.KeyBlob`; ``counts`` the per-row token
        costs (rows with cost <= 0 — probes — carry no admission
        weight). Bounded work per call: at most ``sample`` byte-slice
        tallies and ``batch_top`` string decodes."""
        counts_np = np.asarray(counts, np.float64)
        n = len(counts_np)
        if n == 0:
            return
        pos = np.nonzero(counts_np > 0)[0]
        if len(pos) == 0:
            return
        total = float(counts_np[pos].sum())
        scale = 1.0
        if len(pos) > sample:
            # Deterministic strided sample (no rng on the serving
            # path); the scale preserves the frame's total weight in
            # expectation — head keys dominate any stride.
            step = -(-len(pos) // sample)
            pos = pos[::step]
            sampled = float(counts_np[pos].sum())
            if sampled <= 0.0:
                return
            scale = total / sampled
        off = np.asarray(offsets, np.int64)
        tally: dict[bytes, float] = {}
        for i in pos.tolist():
            kb = blob[off[i]:off[i + 1]]
            tally[kb] = tally.get(kb, 0.0) + counts_np[i]
        merged = 0.0
        for kb, c in heapq.nlargest(self.batch_top, tally.items(),
                                    key=lambda kv: kv[1]):
            w = c * scale
            self.offer(kb.decode("utf-8", "surrogateescape"), w)
            merged += w
        self.offered += total - merged  # truncated tail still counts in N

    def top(self, n: int | None = None) -> list[tuple[str, float, float]]:
        """``[(key, count, error), ...]`` sorted by count descending.
        ``count`` may overshoot the true count by at most ``error``."""
        self._drain()
        items = sorted(self._counts.items(), key=lambda kv: -kv[1])
        if n is not None:
            items = items[:n]
        return [(k, c, self._errors.get(k, 0.0)) for k, c in items]

    def reset(self) -> None:
        self._counts.clear()
        self._errors.clear()
        self._heap.clear()
        self._buf.clear()
        self.offered = 0.0

    def snapshot(self) -> dict:
        """JSON-shaped summary for OP_STATS embedding."""
        top = self.top(10)  # drains the buffer first
        return {
            "k": self.k,
            "offered": self.offered,
            "tracked": len(self._counts),
            "top": [{"key": k, "count": c, "error": e}
                    for k, c, e in top],
        }
