"""Ring-buffer double-ended queue backing the waiter queue.

Functional mirror of the reference's internal ``Deque<T>``
(``System.Collections.Generic/Deque.cs:19-135``): amortized-doubling growth
with a minimum grow of 4, head/tail enqueue/dequeue/peek. Python's
``collections.deque`` would do, but it cannot pop efficiently from arbitrary
positions nor expose the exact eviction order we need; keeping the same
structure as the reference also keeps the queueing semantics auditable
against it line-by-line.

Bounds discipline matches the reference: callers check ``count`` first
(``Deque.cs:49`` — "no bounds checks, caller's responsibility"); here we
raise ``IndexError`` instead of corrupting state, which costs one branch.
"""

from __future__ import annotations

from typing import Generic, Iterator, TypeVar

T = TypeVar("T")

_MIN_GROW = 4


class Deque(Generic[T]):
    __slots__ = ("_buf", "_head", "_size")

    def __init__(self, initial_capacity: int = 0) -> None:
        self._buf: list[T | None] = [None] * initial_capacity
        self._head = 0  # index of the head element
        self._size = 0

    def __len__(self) -> int:
        return self._size

    @property
    def count(self) -> int:
        return self._size

    def enqueue_tail(self, item: T) -> None:
        """``EnqueueTail`` (``Deque.cs:19-32``)."""
        if self._size == len(self._buf):
            self._grow()
        idx = (self._head + self._size) % len(self._buf)
        self._buf[idx] = item
        self._size += 1

    def enqueue_head(self, item: T) -> None:
        if self._size == len(self._buf):
            self._grow()
        self._head = (self._head - 1) % len(self._buf)
        self._buf[self._head] = item
        self._size += 1

    def dequeue_head(self) -> T:
        """``DequeueHead`` (``Deque.cs:47-61``)."""
        if self._size == 0:
            raise IndexError("deque is empty")
        item = self._buf[self._head]
        self._buf[self._head] = None
        self._head = (self._head + 1) % len(self._buf)
        self._size -= 1
        return item  # type: ignore[return-value]

    def dequeue_tail(self) -> T:
        """``DequeueTail`` (``Deque.cs:80-94``)."""
        if self._size == 0:
            raise IndexError("deque is empty")
        idx = (self._head + self._size - 1) % len(self._buf)
        item = self._buf[idx]
        self._buf[idx] = None
        self._size -= 1
        return item  # type: ignore[return-value]

    def peek_head(self) -> T:
        """``PeekHead`` (``Deque.cs:63-70``)."""
        if self._size == 0:
            raise IndexError("deque is empty")
        return self._buf[self._head]  # type: ignore[return-value]

    def peek_tail(self) -> T:
        """``PeekTail`` (``Deque.cs:71-78``)."""
        if self._size == 0:
            raise IndexError("deque is empty")
        return self._buf[(self._head + self._size - 1) % len(self._buf)]  # type: ignore[return-value]

    def remove(self, item: T) -> bool:
        """Remove the first occurrence (identity) — used by cancellation to
        unlink a parked waiter without disturbing order. O(n)."""
        for i in range(self._size):
            idx = (self._head + i) % len(self._buf)
            if self._buf[idx] is item:
                # shift the shorter side
                for j in range(i, self._size - 1):
                    a = (self._head + j) % len(self._buf)
                    b = (self._head + j + 1) % len(self._buf)
                    self._buf[a] = self._buf[b]
                self._buf[(self._head + self._size - 1) % len(self._buf)] = None
                self._size -= 1
                return True
        return False

    def __iter__(self) -> Iterator[T]:
        for i in range(self._size):
            yield self._buf[(self._head + i) % len(self._buf)]  # type: ignore[misc]

    def _grow(self) -> None:
        """Amortized doubling, min grow 4 (``Deque.cs:107-135``)."""
        new_cap = max(len(self._buf) * 2, len(self._buf) + _MIN_GROW)
        new_buf: list[T | None] = [None] * new_cap
        for i in range(self._size):
            new_buf[i] = self._buf[(self._head + i) % len(self._buf)]
        self._buf = new_buf
        self._head = 0
