"""Structured log events.

Mirror of the reference's source-generated ``LoggerMessage`` partials
(``RedisApproximateTokenBucketRateLimiter.Log.cs:9-13``): two error events,
same ids — 1 = could not connect/reach the store, 2 = error executing the
store kernel. Called from the refresh path only, matching the reference's
degraded-mode posture (log and keep serving; SURVEY.md invariant 9).

The chaos plane (cluster breakers, node quarantine) adds two more:
3 = a named cluster node failed a store operation (the event that makes
partitions VISIBLE — the old code swallowed them), 4 = a node's circuit
breaker changed state. Both carry the node index in ``extra`` so log
pipelines can pivot per node.

The membership plane adds 5 = a migration committed or aborted (the
full event dict — moved slots/keys, epochs, handoff window — rides in
``extra``, mirroring ``ClusterBucketStore.migration_log``).

The autonomous control plane adds 6 = the controller decided an action
(split / rebalance / drain / rejoin / shed step — executed, dry-run,
budget-starved, or failed; the full record mirrors
``Controller.actions``).
"""

from __future__ import annotations

import logging

logger = logging.getLogger("distributedratelimiting.redis_tpu")

EVENT_COULD_NOT_CONNECT = 1
EVENT_ERROR_EVALUATING = 2
EVENT_CLUSTER_NODE_ERROR = 3
EVENT_BREAKER_TRANSITION = 4
EVENT_CLUSTER_MIGRATION = 5
EVENT_CONTROLLER_ACTION = 6


def could_not_connect_to_store(exc: BaseException) -> None:
    """Event id 1 — ``Log.CouldNotConnectToRedis``."""
    logger.error(
        "Could not connect to the backing store",
        exc_info=exc,
        extra={"event_id": EVENT_COULD_NOT_CONNECT},
    )


def error_evaluating_kernel(exc: BaseException) -> None:
    """Event id 2 — ``Log.ErrorEvaluatingRedisScript``."""
    logger.error(
        "Error executing store kernel",
        exc_info=exc,
        extra={"event_id": EVENT_ERROR_EVALUATING},
    )


def cluster_node_error(node: int, exc: BaseException) -> None:
    """Event id 3 — a cluster node failed a store operation. Always
    paired with the ``cluster_node_errors`` counter so a partition shows
    up in BOTH the logs and the metrics plane."""
    logger.error(
        "Cluster node %d failed a store operation",
        node,
        exc_info=exc,
        extra={"event_id": EVENT_CLUSTER_NODE_ERROR, "node": node},
    )


def breaker_transition(node: int, old: str, new: str) -> None:
    """Event id 4 — a node's circuit breaker changed state (quarantine
    on ``-> open``, probe on ``-> half_open``, rejoin on ``-> closed``)."""
    logger.warning(
        "Cluster node %d circuit breaker: %s -> %s",
        node, old, new,
        extra={"event_id": EVENT_BREAKER_TRANSITION, "node": node,
               "breaker_old": old, "breaker_new": new},
    )


def cluster_migration(event: dict) -> None:
    """Event id 5 — a membership migration or live config mutation
    committed or aborted. The event dict is the same record
    ``ClusterBucketStore.migration_log`` keeps (migrations: type,
    reason, epochs, moved slots/keys, window times; config mutations:
    kind, old/new operands, version)."""
    if str(event.get("type", "")).startswith("config"):
        logger.warning(
            "Cluster config %s: %s %s -> %s (version %s)",
            event.get("type"), event.get("kind"), event.get("old"),
            event.get("new"), event.get("version"),
            extra={"event_id": EVENT_CLUSTER_MIGRATION,
                   "migration": dict(event)},
        )
        return
    logger.warning(
        "Cluster migration %s: %s -> epoch %s (%s)",
        event.get("type"), event.get("from_epoch"),
        event.get("target_epoch"), event.get("reason"),
        extra={"event_id": EVENT_CLUSTER_MIGRATION,
               "migration": dict(event)},
    )


def controller_action(record: dict) -> None:
    """Event id 6 — the autonomous controller decided an action. The
    record is the same dict ``Controller.actions`` keeps (tick, action,
    target, reason, outcome, actuator extras) — the log pipeline's view
    of every autonomous move, executed or not."""
    logger.warning(
        "Controller %s -> %s (%s): %s",
        record.get("action"), record.get("target"),
        record.get("outcome"), record.get("reason"),
        extra={"event_id": EVENT_CONTROLLER_ACTION,
               "controller": dict(record)},
    )
