"""Structured log events.

Mirror of the reference's source-generated ``LoggerMessage`` partials
(``RedisApproximateTokenBucketRateLimiter.Log.cs:9-13``): two error events,
same ids — 1 = could not connect/reach the store, 2 = error executing the
store kernel. Called from the refresh path only, matching the reference's
degraded-mode posture (log and keep serving; SURVEY.md invariant 9).
"""

from __future__ import annotations

import logging

logger = logging.getLogger("distributedratelimiting.redis_tpu")

EVENT_COULD_NOT_CONNECT = 1
EVENT_ERROR_EVALUATING = 2


def could_not_connect_to_store(exc: BaseException) -> None:
    """Event id 1 — ``Log.CouldNotConnectToRedis``."""
    logger.error(
        "Could not connect to the backing store",
        exc_info=exc,
        extra={"event_id": EVENT_COULD_NOT_CONNECT},
    )


def error_evaluating_kernel(exc: BaseException) -> None:
    """Event id 2 — ``Log.ErrorEvaluatingRedisScript``."""
    logger.error(
        "Error executing store kernel",
        exc_info=exc,
        extra={"event_id": EVENT_ERROR_EVALUATING},
    )
