"""Shared utilities: deque, metrics, structured logging, service registry."""
