"""Loader for the native host-runtime library (``native/directory.cc``).

The C++ directory is a performance component, not a correctness one: the
store works identically on the pure-Python fallback (see
:mod:`~.runtime.directory`). Build strategy: compile with ``g++`` into
``native/build/`` on first import if the shared object is missing or older
than its source; any failure (no compiler, read-only checkout, exotic
platform) silently yields ``None`` and callers fall back. Set
``DRL_TPU_NO_NATIVE=1`` to force the fallback.

Sanitizer legs (``make asan-test`` / ``make tsan-test``, VERDICT r5 #4):
``DRL_TPU_SANITIZE`` selects an instrumented build into a separate
directory (the production ``.so`` is never clobbered):

- ``asan`` (or the legacy ``1``): ``-fsanitize=address,undefined -g -O1``
  into ``native/build/asan/`` — run the native test files with
  ``libasan`` preloaded.
- ``tsan``: ``-fsanitize=thread -g -O1`` into ``native/build/tsan/`` —
  run with ``libtsan`` preloaded and the ``native/tsan.supp``
  suppressions file (jaxlib's uninstrumented thread pools).

See the ``native/Makefile`` targets for the full invocations.
"""

from __future__ import annotations

import ctypes
import hashlib
import os
import pathlib
import subprocess

__all__ = ["load_directory_lib", "load_frontend_lib",
           "URING_OFF", "URING_ON", "URING_SQPOLL"]

#: Transport mode for ``fe_start_sharded2`` — MUST mirror the
#: ``kUringOff``/``kUringOn``/``kUringSqpoll`` constexprs in
#: ``native/frontend.cc`` (drl-check's ``transport-flag`` rule pins the
#: pair both directions; a drift here is a build break, not a silent
#: transport swap).
URING_OFF = 0
URING_ON = 1
URING_SQPOLL = 2

_REPO_NATIVE = pathlib.Path(__file__).resolve().parents[3] / "native"
_LIB: ctypes.CDLL | None = None
_TRIED = False

#: Serializes first-load across threads. The load generators are run
#: from worker THREADS (the multi-shard bench rig starts several at
#: once); without the lock, racing first callers see ``_TRIED`` set by
#: a loader still mid-build and return ``None`` for a library that is
#: about to exist.
import threading as _threading

_LOAD_LOCK = _threading.Lock()

#: Sanitizer opt-in (the `make asan-test` / `make tsan-test` env hook):
#: value selects the instrumented build directory and flag set ("asan"
#: or legacy "1" → build/asan, "tsan" → build/tsan). -O1 keeps stack
#: traces honest; these binaries are for the sanitizer legs, not serving.
SANITIZE_ENV = "DRL_TPU_SANITIZE"
_SANITIZE_MODES = {
    "asan": (["-fsanitize=address,undefined", "-g", "-O1",
              "-fno-omit-frame-pointer"], "asan"),
    "tsan": (["-fsanitize=thread", "-g", "-O1",
              "-fno-omit-frame-pointer"], "tsan"),
}


def _sanitize_mode() -> tuple[list[str], str] | None:
    """``(extra_flags, build_subdir)`` for the selected sanitizer, or
    ``None`` for a production build. ``1`` keeps its historical meaning
    (the ASan leg); any other unrecognized value raises — silently
    serving an ASan binary to someone who asked for ``thread``/a typo'd
    ``tsna`` would hand them a race-free "pass" with no thread
    instrumentation at all."""
    val = os.environ.get(SANITIZE_ENV, "").strip().lower()
    if not val:
        return None
    if val == "1":
        val = "asan"
    if val not in _SANITIZE_MODES:
        raise ValueError(
            f"{SANITIZE_ENV}={val!r} is not a known sanitizer; use "
            f"{sorted(_SANITIZE_MODES)} (or legacy '1' for asan)")
    flags, subdir = _SANITIZE_MODES[val]
    return list(flags), subdir


def _out_path(name: str) -> pathlib.Path:
    build = _REPO_NATIVE / "build"
    mode = _sanitize_mode()
    if mode is not None:
        return build / mode[1] / name
    return build / name


def _extra_flags() -> list[str]:
    mode = _sanitize_mode()
    return mode[0] if mode is not None else []


def _source_hash(src: pathlib.Path) -> str:
    return hashlib.sha256(src.read_bytes()).hexdigest()


def _hash_path(out: pathlib.Path) -> pathlib.Path:
    return out.with_name(out.name + ".hash")


def _is_stale(src: pathlib.Path, out: pathlib.Path) -> bool:
    """A binary is fresh only when its sidecar records the CURRENT source
    hash. Mtime comparison is not enough: a fresh clone materializes
    source and committed binary with equal mtimes, so source/binary
    drift in the repo would silently serve the stale ``.so``."""
    if not out.exists():
        return True
    try:
        return _hash_path(out).read_text().strip() != _source_hash(src)
    except OSError:
        return True  # no/unreadable sidecar: rebuild to establish one


def _build(src: pathlib.Path, out: pathlib.Path) -> bool:
    """Prefer a build with the CPython API enabled (zero-copy list[str]
    resolve); fall back to the plain C ABI if headers are unavailable.
    A successful build stamps the source-hash sidecar ``_is_stale``
    checks on load."""
    import sysconfig

    out.parent.mkdir(parents=True, exist_ok=True)
    base = ["g++", "-O3", "-std=c++17", "-fPIC", "-shared"]
    base += _extra_flags()  # sanitizer leg: DRL_TPU_SANITIZE=1
    include = sysconfig.get_paths().get("include")
    attempts = []
    if include and (pathlib.Path(include) / "Python.h").exists():
        attempts.append(base + ["-DDRL_WITH_PYTHON", f"-I{include}",
                                str(src), "-o", str(out)])
    attempts.append(base + [str(src), "-o", str(out)])
    for cmd in attempts:
        try:
            proc = subprocess.run(cmd, capture_output=True, timeout=120)
        except (OSError, subprocess.TimeoutExpired):
            return False
        if proc.returncode == 0 and out.exists():
            try:
                _hash_path(out).write_text(_source_hash(src) + "\n")
            except OSError:
                pass  # read-only checkout: next load re-checks and
                # rebuilds into the same (tmpfs/overlay) place
            return True
    return False


def _bind(lib: ctypes.CDLL) -> ctypes.CDLL:
    c = ctypes
    lib.dir_new.argtypes = [c.c_int64]
    lib.dir_new.restype = c.c_void_p
    lib.dir_free.argtypes = [c.c_void_p]
    lib.dir_free.restype = None
    lib.dir_size.argtypes = [c.c_void_p]
    lib.dir_size.restype = c.c_int64
    lib.dir_free_count.argtypes = [c.c_void_p]
    lib.dir_free_count.restype = c.c_int64
    lib.dir_resolve_batch.argtypes = [
        c.c_void_p, c.c_char_p, c.POINTER(c.c_int64), c.c_int64,
        c.POINTER(c.c_int32)]
    lib.dir_resolve_batch.restype = c.c_int64
    lib.dir_lookup.argtypes = [c.c_void_p, c.c_char_p, c.c_int64]
    lib.dir_lookup.restype = c.c_int32
    lib.dir_remove_slots.argtypes = [c.c_void_p, c.POINTER(c.c_int32),
                                     c.c_int64]
    lib.dir_remove_slots.restype = c.c_int64
    lib.dir_add_slots.argtypes = [c.c_void_p, c.c_int32, c.c_int32]
    lib.dir_add_slots.restype = None
    lib.dir_insert.argtypes = [c.c_void_p, c.c_char_p, c.c_int64, c.c_int32]
    lib.dir_insert.restype = c.c_int32
    lib.dir_set_free.argtypes = [c.c_void_p, c.POINTER(c.c_int32), c.c_int64]
    lib.dir_set_free.restype = None
    lib.dir_arena_bytes.argtypes = [c.c_void_p]
    lib.dir_arena_bytes.restype = c.c_int64
    lib.dir_dump.argtypes = [c.c_void_p, c.c_char_p, c.POINTER(c.c_int64),
                             c.POINTER(c.c_int32)]
    lib.dir_dump.restype = c.c_int64
    lib.dir_route_batch.argtypes = [
        c.c_char_p, c.POINTER(c.c_int64), c.c_int64, c.c_int32,
        c.POINTER(c.c_int32)]
    lib.dir_route_batch.restype = None
    lib.dir_resolve_sharded_batch.argtypes = [
        c.c_char_p, c.POINTER(c.c_int64), c.c_int64,
        c.POINTER(c.c_void_p), c.c_int32, c.POINTER(c.c_int32),
        c.POINTER(c.c_int32)]
    lib.dir_resolve_sharded_batch.restype = c.c_int64
    lib.dir_fp64_batch.argtypes = [
        c.c_char_p, c.POINTER(c.c_int64), c.c_int64,
        c.POINTER(c.c_uint32)]
    lib.dir_fp64_batch.restype = c.c_int64
    try:
        lib.dir_resolve_pylist.argtypes = [c.c_void_p, c.py_object,
                                           c.POINTER(c.c_int32)]
        lib.dir_resolve_pylist.restype = c.c_int64
        lib.dir_route_pylist.argtypes = [c.py_object, c.c_int32,
                                         c.POINTER(c.c_int32)]
        lib.dir_route_pylist.restype = c.c_int64
        lib.dir_resolve_sharded_pylist.argtypes = [
            c.py_object, c.POINTER(c.c_void_p), c.c_int32,
            c.POINTER(c.c_int32), c.POINTER(c.c_int32)]
        lib.dir_resolve_sharded_pylist.restype = c.c_int64
        lib.dir_fp64_pylist.argtypes = [c.py_object,
                                        c.POINTER(c.c_uint32)]
        lib.dir_fp64_pylist.restype = c.c_int64
        lib.has_pylist = True
    except AttributeError:  # built without Python.h
        lib.has_pylist = False
    return lib


def load_directory_lib() -> ctypes.CDLL | None:
    """Load (building if needed) the native directory; ``None`` on any
    failure — callers must fall back to the Python implementation."""
    global _LIB, _TRIED
    if _TRIED:
        return _LIB
    with _LOAD_LOCK:
        if _TRIED:
            return _LIB
        return _load_directory_locked()


def _load_directory_locked() -> ctypes.CDLL | None:
    # _TRIED is published LAST (see the tail): the unlocked fast path in
    # load_directory_lib reads it before taking the lock, so setting it
    # before the build/load completes would hand concurrent first
    # callers a permanent None for a library that is about to exist.
    global _LIB, _TRIED
    if os.environ.get("DRL_TPU_NO_NATIVE"):
        _TRIED = True
        return None
    src = _REPO_NATIVE / "directory.cc"
    out = _out_path("_directory.so")
    try:
        if not src.exists():
            return None
        if _is_stale(src, out):
            if not _build(src, out):
                return None
        # PyDLL: calls hold the GIL, required for dir_resolve_pylist (which
        # reads str objects); the remaining calls are short host ops already
        # serialized under the store lock, so no parallelism is lost.
        _LIB = _bind(ctypes.PyDLL(str(out)))
    except Exception:
        _LIB = None
    finally:
        _TRIED = True
    return _LIB


_FE_LIB: ctypes.CDLL | None = None
_FE_TRIED = False


def _bind_frontend(lib: ctypes.CDLL) -> ctypes.CDLL:
    c = ctypes
    lib.fe_start.argtypes = [c.c_char_p, c.c_int, c.c_int, c.c_int, c.c_int]
    lib.fe_start.restype = c.c_void_p
    lib.fe_port.argtypes = [c.c_void_p]
    lib.fe_port.restype = c.c_int
    lib.fe_wait.argtypes = [c.c_void_p, c.c_int]
    lib.fe_wait.restype = c.c_int
    lib.fe_batch_id.argtypes = [c.c_void_p]
    lib.fe_batch_id.restype = c.c_longlong
    lib.fe_batch_n.argtypes = [c.c_void_p]
    lib.fe_batch_n.restype = c.c_int
    lib.fe_batch_key_bytes.argtypes = [c.c_void_p]
    lib.fe_batch_key_bytes.restype = c.c_longlong
    lib.fe_batch_copy.argtypes = [
        c.c_void_p, c.c_char_p, c.POINTER(c.c_int32), c.POINTER(c.c_int32),
        c.POINTER(c.c_uint8), c.POINTER(c.c_uint32), c.POINTER(c.c_uint64),
        c.POINTER(c.c_double), c.POINTER(c.c_double)]
    lib.fe_batch_copy.restype = None
    lib.fe_complete.argtypes = [c.c_void_p, c.c_longlong,
                                c.POINTER(c.c_uint8), c.POINTER(c.c_double)]
    lib.fe_complete.restype = None
    lib.fe_fail.argtypes = [c.c_void_p, c.c_longlong, c.c_char_p]
    lib.fe_fail.restype = None
    lib.fe_pt_conn.argtypes = [c.c_void_p]
    lib.fe_pt_conn.restype = c.c_longlong
    lib.fe_pt_len.argtypes = [c.c_void_p]
    lib.fe_pt_len.restype = c.c_int
    lib.fe_pt_copy.argtypes = [c.c_void_p, c.c_char_p]
    lib.fe_pt_copy.restype = None
    lib.fe_send.argtypes = [c.c_void_p, c.c_uint64, c.c_char_p, c.c_int]
    lib.fe_send.restype = None
    lib.fe_set_authed.argtypes = [c.c_void_p, c.c_uint64, c.c_int]
    lib.fe_set_authed.restype = None
    lib.fe_close_conn.argtypes = [c.c_void_p, c.c_uint64]
    lib.fe_close_conn.restype = None
    lib.fe_counts.argtypes = [c.c_void_p, c.POINTER(c.c_longlong),
                              c.POINTER(c.c_longlong),
                              c.POINTER(c.c_longlong)]
    lib.fe_counts.restype = None
    lib.fe_hist.argtypes = [c.c_void_p, c.POINTER(c.c_uint64)]
    lib.fe_hist.restype = c.c_longlong
    lib.fe_hist_reset.argtypes = [c.c_void_p]
    lib.fe_hist_reset.restype = None
    try:
        lib.fe_stage_hist.argtypes = [c.c_void_p, c.c_int,
                                      c.POINTER(c.c_uint64),
                                      c.POINTER(c.c_double)]
        lib.fe_stage_hist.restype = c.c_longlong
        lib.has_stage_hist = True
    except AttributeError:  # stale binary without the stage-hist ABI
        lib.has_stage_hist = False
    try:
        lib.fe_batch_traced_n.argtypes = [c.c_void_p]
        lib.fe_batch_traced_n.restype = c.c_int
        lib.fe_batch_traces.argtypes = [c.c_void_p, c.POINTER(c.c_uint64),
                                        c.POINTER(c.c_uint64),
                                        c.POINTER(c.c_uint64),
                                        c.POINTER(c.c_uint8)]
        lib.fe_batch_traces.restype = None
        lib.fe_trace_harvest.argtypes = [c.c_void_p,
                                         c.POINTER(c.c_uint64), c.c_int]
        lib.fe_trace_harvest.restype = c.c_int
        lib.has_trace = True
    except AttributeError:  # stale binary without the trace ABI
        lib.has_trace = False
    try:
        lib.fe_has_row_skip.argtypes = []
        lib.fe_has_row_skip.restype = c.c_int
        lib.has_row_skip = True
    except AttributeError:
        # Stale binary whose fe_complete would read the kRowSkip
        # sentinel as "granted" — Python must fall back to deny-only
        # gating on the batch lane.
        lib.has_row_skip = False
    lib.fe_stop.argtypes = [c.c_void_p]
    lib.fe_stop.restype = None
    lib.fe_free.argtypes = [c.c_void_p]
    lib.fe_free.restype = None
    try:
        # Round 11 (multi-shard front-end): N epoll shards accepting on
        # SO_REUSEPORT listeners bound to one port. fe_shard hands out
        # per-shard sub-handles every fe_* entry accepts; stats/harvest
        # calls aggregate across shards for the Frontend handle and
        # slice per shard for a sub-handle. A stale binary without
        # these exports serves single-shard (has_shards gates it).
        lib.fe_start_sharded.argtypes = [c.c_char_p, c.c_int, c.c_int,
                                         c.c_int, c.c_int, c.c_int,
                                         c.c_int]
        lib.fe_start_sharded.restype = c.c_void_p
        lib.fe_shard_count.argtypes = [c.c_void_p]
        lib.fe_shard_count.restype = c.c_int
        lib.fe_shard.argtypes = [c.c_void_p, c.c_int]
        lib.fe_shard.restype = c.c_void_p
        lib.fe_lg_bulk.argtypes = [
            c.c_char_p, c.c_int, c.c_int, c.c_int, c.c_int, c.c_int,
            c.c_int, c.c_double, c.c_double, c.POINTER(c.c_double),
            c.POINTER(c.c_longlong), c.POINTER(c.c_longlong),
            c.POINTER(c.c_longlong)]
        lib.fe_lg_bulk.restype = c.c_int
        lib.has_shards = True
    except AttributeError:  # stale binary without the shard ABI
        lib.has_shards = False
    lib.fe_loadgen.argtypes = [
        c.c_char_p, c.c_int, c.c_int, c.c_int, c.c_int, c.c_int, c.c_double,
        c.c_double, c.c_int, c.POINTER(c.c_double),
        c.POINTER(c.c_longlong), c.POINTER(c.c_longlong)]
    lib.fe_loadgen.restype = c.c_int
    try:
        lib.fe_t0_configure.argtypes = [
            c.c_void_p, c.c_int, c.c_double, c.c_double, c.c_double,
            c.c_int, c.c_int]
        lib.fe_t0_configure.restype = c.c_int
        lib.fe_t0_harvest.argtypes = [
            c.c_void_p, c.c_char_p, c.c_int, c.POINTER(c.c_int32),
            c.POINTER(c.c_double), c.POINTER(c.c_double),
            c.POINTER(c.c_double), c.c_int]
        lib.fe_t0_harvest.restype = c.c_int
        lib.fe_t0_ack.argtypes = [
            c.c_void_p, c.c_char_p, c.POINTER(c.c_int32),
            c.POINTER(c.c_double), c.POINTER(c.c_double),
            c.POINTER(c.c_double), c.c_int]
        lib.fe_t0_ack.restype = None
        lib.fe_t0_counts.argtypes = [c.c_void_p, c.POINTER(c.c_longlong)]
        lib.fe_t0_counts.restype = None
        lib.has_tier0 = True
    except AttributeError:  # stale binary without the tier-0 ABI
        lib.has_tier0 = False
    try:
        # Round 7 (live config mutation): retire one (cap, rate)
        # config's replicas, returning their un-harvested grants.
        lib.fe_t0_retire.argtypes = [
            c.c_void_p, c.c_double, c.c_double, c.c_char_p, c.c_int,
            c.POINTER(c.c_int32), c.POINTER(c.c_double), c.c_int]
        lib.fe_t0_retire.restype = c.c_int
        lib.has_t0_retire = True
    except AttributeError:  # stale binary without the retire ABI
        lib.has_t0_retire = False
    try:
        # Round 18 (conservation audit plane): per-slice cumulative
        # locally-granted tokens — the C-side ε-consumption witness.
        lib.fe_t0_eps.argtypes = [c.c_void_p, c.POINTER(c.c_double),
                                  c.c_int]
        lib.fe_t0_eps.restype = c.c_int
        lib.has_t0_eps = True
    except AttributeError:  # stale binary without the eps ABI
        lib.has_t0_eps = False
    try:
        # Round 8 (native bulk lane): OP_ACQUIRE_MANY parses, tier-0
        # decides, and RESP_BULK encodes in C; fe_wait returns 3 for a
        # residue job. Armed explicitly via fe_bulk_configure so a new
        # binary under an older pump keeps the passthrough behavior.
        lib.fe_bulk_configure.argtypes = [c.c_void_p, c.c_int, c.c_int,
                                          c.c_int]
        lib.fe_bulk_configure.restype = c.c_int
        lib.fe_bulk_id.argtypes = [c.c_void_p]
        lib.fe_bulk_id.restype = c.c_longlong
        lib.fe_bulk_meta.argtypes = [c.c_void_p, c.POINTER(c.c_uint64),
                                     c.POINTER(c.c_double)]
        lib.fe_bulk_meta.restype = None
        lib.fe_bulk_ptrs.argtypes = [c.c_void_p, c.POINTER(c.c_uint64)]
        lib.fe_bulk_ptrs.restype = None
        lib.fe_bulk_complete.argtypes = [c.c_void_p, c.c_longlong,
                                         c.POINTER(c.c_uint8),
                                         c.POINTER(c.c_double)]
        lib.fe_bulk_complete.restype = None
        lib.fe_bulk_discard.argtypes = [c.c_void_p, c.c_longlong]
        lib.fe_bulk_discard.restype = None
        lib.fe_bulk_fail.argtypes = [c.c_void_p, c.c_longlong, c.c_char_p]
        lib.fe_bulk_fail.restype = None
        lib.fe_bulk_counts.argtypes = [c.c_void_p,
                                       c.POINTER(c.c_longlong)]
        lib.fe_bulk_counts.restype = None
        lib.fe_hot_harvest.argtypes = [
            c.c_void_p, c.c_char_p, c.c_int, c.POINTER(c.c_int32),
            c.POINTER(c.c_double), c.c_int]
        lib.fe_hot_harvest.restype = c.c_int
        lib.has_bulk = True
    except AttributeError:  # stale binary without the bulk ABI
        lib.has_bulk = False
    try:
        # Round 16 (io_uring data plane): fe_start_sharded2 is
        # fe_start_sharded plus an explicit transport mode (URING_OFF /
        # URING_ON / URING_SQPOLL module constants); fe_uring_* expose
        # the runtime probe, per-shard transport status + fallback
        # reason, and ring counters; fe_lg_bulk_uring is the bulk
        # loadgen's uring submission path (returns -2 when the ring is
        # unavailable — callers fall back to fe_lg_bulk). A stale
        # binary without these exports serves epoll-only (has_uring
        # gates it; the epoll lane is byte-identical by contract).
        lib.fe_start_sharded2.argtypes = [c.c_char_p, c.c_int, c.c_int,
                                          c.c_int, c.c_int, c.c_int,
                                          c.c_int, c.c_int]
        lib.fe_start_sharded2.restype = c.c_void_p
        lib.fe_uring_available.argtypes = []
        lib.fe_uring_available.restype = c.c_int
        lib.fe_uring_probe.argtypes = [c.c_char_p, c.c_int]
        lib.fe_uring_probe.restype = c.c_int
        lib.fe_uring_shards.argtypes = [c.c_void_p]
        lib.fe_uring_shards.restype = c.c_int
        lib.fe_uring_reason.argtypes = [c.c_void_p, c.c_int, c.c_char_p,
                                        c.c_int]
        lib.fe_uring_reason.restype = c.c_int
        lib.fe_uring_counts.argtypes = [c.c_void_p,
                                        c.POINTER(c.c_longlong)]
        lib.fe_uring_counts.restype = None
        lib.fe_lg_bulk_uring.argtypes = [
            c.c_char_p, c.c_int, c.c_int, c.c_int, c.c_int, c.c_int,
            c.c_int, c.c_double, c.c_double, c.POINTER(c.c_double),
            c.POINTER(c.c_longlong), c.POINTER(c.c_longlong),
            c.POINTER(c.c_longlong)]
        lib.fe_lg_bulk_uring.restype = c.c_int
        lib.has_uring = True
    except AttributeError:  # stale binary without the uring ABI
        lib.has_uring = False
    return lib


def load_frontend_lib() -> ctypes.CDLL | None:
    """Load (building if needed) the native serving front-end
    (``native/frontend.cc``); ``None`` on any failure — the server then
    falls back to the asyncio socket path. Loaded as plain ``CDLL`` (NOT
    PyDLL): its blocking ``fe_wait`` must release the GIL so the pump
    thread's wait never stalls the event loop."""
    global _FE_LIB, _FE_TRIED
    if _FE_TRIED:
        return _FE_LIB
    with _LOAD_LOCK:
        if _FE_TRIED:
            return _FE_LIB
        return _load_frontend_locked()


def _load_frontend_locked() -> ctypes.CDLL | None:
    # Same publication order as _load_directory_locked: _FE_TRIED last.
    global _FE_LIB, _FE_TRIED
    if os.environ.get("DRL_TPU_NO_NATIVE"):
        _FE_TRIED = True
        return None
    src = _REPO_NATIVE / "frontend.cc"
    out = _out_path("_frontend.so")
    try:
        if not src.exists():
            return None
        if _is_stale(src, out):
            if not _build(src, out):
                return None
        _FE_LIB = _bind_frontend(ctypes.CDLL(str(out)))
    except Exception:
        _FE_LIB = None
    finally:
        _FE_TRIED = True
    return _FE_LIB
