"""Flight recorder — a bounded ring of recent serving-state frames that
dumps itself to JSONL when things go wrong.

The r04/r05 outage windows were diagnosed from prose (RESULTS.md
"degraded window" notes): by the time anyone looked, the state that
explained the window — flush sizes and latencies leading in, sync
failure streaks, carry growth — was gone. This module keeps the last N
frames in memory at ~zero cost (one dict append per flush/sync round)
and writes them out the moment a degraded-mode trigger fires, so the
next outage leaves evidence instead of recollection.

Frames are whatever the feeding layer records — the store's flush
observer records ``flush`` frames (batch size, wall time, error), the
tier-0 sync pump records ``t0_sync`` frames (keys drained, shortfall,
failure streak). Triggers: degraded-mode entry (first failure after
healthy operation), a sync-failure streak, or an explicit operator
request (``OP_STATS`` flag bit 1 / the ``/flight`` HTTP path). Automatic
dumps are rate-limited so a flapping trigger cannot fill a disk.
"""

from __future__ import annotations

import json
import os
import tempfile
import time
from collections import deque

__all__ = ["FlightRecorder", "REGISTERED_KINDS"]

#: THE frame-kind registry. Every ``record(kind=...)`` call site and
#: every ``frames(kind=...)`` filter must use a kind from this table —
#: drl-check's ``flight-kind`` rule enforces it statically, because a
#: typo'd kind on either side fails SILENTLY (``frames(kind="flsh")``
#: matches nothing and an audit assertion passes vacuously). Add the
#: kind here first, then record it. ``"header"`` is the dump-file
#: header line's own kind.
REGISTERED_KINDS = frozenset({
    "flush",         # store flush observer (runtime/store.py)
    "t0_sync",       # tier-0 sync pump (runtime/native_frontend.py)
    "breaker",       # cluster breaker transitions (runtime/cluster.py)
    "node_error",    # cluster node failures (runtime/cluster.py)
    "controller",    # control-plane actions (runtime/controller.py)
    "reservation",   # reserve/settle events (runtime/reservations.py)
    "federation",    # WAN lease events (runtime/federation.py):
                     # grants/resizes/expiries/heals at the home,
                     # degrade/heal transitions at the region
    "slo",           # burn-rate watchdog alerts (utils/slo.py)
    "audit",         # conservation-ledger breaches (runtime/audit.py)
    "header",        # the dump file's header line
})


class FlightRecorder:
    """Bounded in-memory frame ring with triggered JSONL dumps."""

    def __init__(self, capacity: int = 512,
                 dump_dir: str | None = None,
                 min_dump_interval_s: float = 30.0,
                 name: str = "store") -> None:
        if capacity <= 0:
            raise ValueError("capacity must be positive")
        self._frames: deque[dict] = deque(maxlen=capacity)
        self.capacity = capacity
        self.dump_dir = dump_dir or os.environ.get(
            "DRL_TPU_FLIGHT_DIR") or tempfile.gettempdir()
        self.min_dump_interval_s = min_dump_interval_s
        self.name = name
        self.frames_recorded = 0
        self.dumps_written = 0
        self.dumps_suppressed = 0
        self.last_dump_path: str | None = None
        # None, not 0.0: time.monotonic() counts from boot, so a zero
        # sentinel reads as "dumped at boot" and wrongly suppresses the
        # FIRST automatic dump on any machine whose uptime is still
        # below min_dump_interval_s (a fresh container losing its first
        # — often only — outage capture).
        self._last_dump_mono: float | None = None

    def record(self, kind: str, **fields) -> None:
        """Append one frame. Cheap by design (one dict + deque append);
        called once per flush / sync round, never per request."""
        frame = {"t": time.time(), "mono": time.monotonic(), "kind": kind}
        frame.update(fields)
        self._frames.append(frame)
        self.frames_recorded += 1

    def frames(self, kind: str | tuple[str, ...] | None = None
               ) -> list[dict]:
        """The ring's frames, oldest first; ``kind`` filters to one
        frame kind (e.g. ``"controller"`` — the audit path the control
        plane's action-log assertions read) or, given a tuple, any of
        several kinds (the incident-bundle assembly path pulls
        ``("slo", "audit", "controller")`` in one correlated slice)."""
        if kind is None:
            return list(self._frames)
        if isinstance(kind, tuple):
            wanted = frozenset(kind)
            return [f for f in self._frames if f.get("kind") in wanted]
        return [f for f in self._frames if f.get("kind") == kind]

    def dump(self, reason: str, extra: dict | None = None, *,
             force: bool = True) -> str | None:
        """Write the ring to ``<dump_dir>/flight-<name>-<ts>-<reason>.jsonl``
        (header line first, then frames oldest→newest) and return the
        path. ``force=False`` applies the rate limit — automatic triggers
        use it; explicit operator requests bypass it. Returns ``None``
        when suppressed or the write fails (a full disk must never take
        the serving path down with it)."""
        now = time.monotonic()
        if (not force and self._last_dump_mono is not None
                and now - self._last_dump_mono < self.min_dump_interval_s):
            self.dumps_suppressed += 1
            return None
        safe_reason = "".join(c if c.isalnum() or c in "-_" else "_"
                              for c in reason)[:64]
        path = os.path.join(
            self.dump_dir,
            f"flight-{self.name}-{int(time.time() * 1e3)}-{safe_reason}"
            ".jsonl")
        header = {
            "kind": "header",
            "reason": reason,
            "dumped_at": time.time(),
            "frames": len(self._frames),
            "frames_recorded": self.frames_recorded,
            "capacity": self.capacity,
        }
        if extra:
            header.update(extra)
        try:
            with open(path, "w", encoding="utf-8") as f:
                f.write(json.dumps(header) + "\n")
                for frame in self._frames:
                    f.write(json.dumps(frame, default=repr) + "\n")
        except OSError:
            return None
        self._last_dump_mono = now
        self.dumps_written += 1
        self.last_dump_path = path
        return path

    def auto_dump(self, reason: str, extra: dict | None = None
                  ) -> str | None:
        """Rate-limited trigger for automatic (degraded-mode) dumps."""
        return self.dump(reason, extra, force=False)

    def snapshot(self) -> dict:
        """JSON-shaped status for OP_STATS embedding."""
        return {
            "frames": len(self._frames),
            "frames_recorded": self.frames_recorded,
            "dumps_written": self.dumps_written,
            "dumps_suppressed": self.dumps_suppressed,
            "last_dump_path": self.last_dump_path,
            "dump_dir": self.dump_dir,
        }
