"""Metrics — decisions/sec, denial rate, batch occupancy, sync lag, latency.

The reference's observability is skeletal (two error log events plus a
``ToString()`` dump, SURVEY.md §5.5); real metrics are a gap the new
framework fills since the north-star metric is decisions/sec + p99 latency.
Counters are plain ints guarded by the GIL (single event loop); latency uses
fixed log-spaced buckets so p50/p99 are O(1) to read and recording is
allocation-free.

:class:`MetricsRegistry` is the exposition layer over those counters: it
names and namespaces every family (``drl_`` prefix) and renders OpenMetrics
text — served by the store server both as the ``OP_METRICS`` wire op and
as a plain HTTP ``/metrics`` endpoint (``--metrics-port``), and aggregated
across cluster nodes by :func:`aggregate_openmetrics` /
``ClusterBucketStore.cluster_metrics``. Exposition is pull-only: rendering
walks live callables at scrape time; nothing on the serving path pays for
it between scrapes.

**The destructive-reset contract.** ``stats(reset=True)`` (OP_STATS flag
bit 0) zeroes the server's latency-measurement windows IN PLACE — there
is exactly ONE window per server, shared by every scraper. Two scrapers
racing ``reset=True`` silently halve each other's windows: each believes
it owns ``[its-last-reset, now)`` but the other's reset tore the window
in the middle, and neither can tell from the numbers alone. Reset is
therefore reserved for a single operator-driven measurement run (the
bench's warmup exclusion); *automation* — the autonomous controller
above all (``runtime/controller.py``) — derives rates with
:class:`CounterDeltas` instead: keep your OWN last-seen snapshot and
diff the monotonic counters, which composes with any number of
concurrent consumers and never mutates the source. Every histogram
counts its resets (:attr:`LatencyHistogram.resets`, surfaced as
``stats_resets`` in OP_STATS) so a consumer can at least DETECT that
someone else tore a window it was relying on.
"""

from __future__ import annotations

import math
import time
from dataclasses import dataclass, field
from typing import Callable, Iterable, Mapping


class LatencyHistogram:
    """Log-spaced buckets from 1µs to ~70s (factor 1.25, 82 buckets).

    Base 1.25 bounds quantile error at +25% of the true value everywhere
    (a quantile reports its bucket's upper edge) — the old √2 base's ±41%
    was too coarse exactly where the <2ms p99 north star lives (the
    0.5-16ms decade spans ~15 buckets now vs ~10 before at twice the
    width; VERDICT r4 weak #2). Still O(1) memory and allocation-free
    recording.

    Exemplars: ``record(seconds, trace_id=...)`` (or :meth:`exemplar`)
    attaches the most recent trace id observed per bucket, rendered as
    OpenMetrics exemplars on the ``_bucket`` series — the jump-off from
    "the p99 moved" to the exact exported trace that moved it. Lazy: a
    histogram that never sees a trace id allocates nothing extra."""

    BASE = 1.25
    MIN_S = 1e-6
    N_BUCKETS = 82

    def __init__(self) -> None:
        self.counts = [0] * self.N_BUCKETS
        self.total = 0
        self.sum_s = 0.0  # running sum → OpenMetrics _sum / mean
        # Measurement-window resets survive reset() by design: the
        # count is the destructive-reset contract's tripwire (module
        # docstring) — a delta-consumer watching it can detect that a
        # concurrent scraper tore the window it was reading.
        self.resets = 0
        # bucket idx -> (trace_id, observed value, unix ts); None until
        # the first traced observation.
        self.exemplars: dict[int, tuple[str, float, float]] | None = None

    def reset(self) -> None:
        """Zero in place. Holders keep their reference (the MicroBatcher
        captures the histogram at construction), so a measurement-window
        reset must NOT swap in a fresh object.

        DESTRUCTIVE for every other consumer of this histogram (module
        docstring): the window is shared, so concurrent scrapers that
        both reset halve each other's measurements. Rate-deriving
        consumers use :class:`CounterDeltas` over the cumulative
        counters instead and never call this."""
        self.counts = [0] * self.N_BUCKETS
        self.total = 0
        self.sum_s = 0.0
        self.resets += 1
        self.exemplars = None

    def _bucket_index(self, seconds: float) -> int:
        if seconds <= self.MIN_S:
            return 0
        return min(
            self.N_BUCKETS - 1,
            int(math.log(seconds / self.MIN_S, self.BASE)) + 1,
        )

    def record(self, seconds: float, trace_id: str | None = None) -> None:
        idx = self._bucket_index(seconds)
        self.counts[idx] += 1
        self.total += 1
        self.sum_s += seconds
        if trace_id is not None:
            if self.exemplars is None:
                self.exemplars = {}
            self.exemplars[idx] = (trace_id, seconds, time.time())

    def exemplar(self, seconds: float, trace_id: str) -> None:
        """Attach an exemplar WITHOUT counting a sample — for callers
        whose sample is recorded elsewhere with a marginally different
        measurement of the same request (the server's serving span)."""
        if self.exemplars is None:
            self.exemplars = {}
        self.exemplars[self._bucket_index(seconds)] = (
            trace_id, seconds, time.time())

    @classmethod
    def bucket_upper_bounds(cls) -> list[float]:
        """Upper edge of each bucket in seconds (bucket ``i`` holds samples
        ≤ ``MIN_S·BASE^i``; the last bucket is the overflow catch-all and
        renders as ``+Inf`` in OpenMetrics exposition)."""
        return [cls.MIN_S * (cls.BASE ** i) for i in range(cls.N_BUCKETS)]

    def quantile(self, q: float) -> float:
        """Upper bound of the bucket containing quantile ``q`` (0..1)."""
        if self.total == 0:
            return 0.0
        target = q * self.total
        seen = 0
        for i, c in enumerate(self.counts):
            seen += c
            if seen >= target:
                return self.MIN_S * (self.BASE ** i)
        return self.MIN_S * (self.BASE ** (self.N_BUCKETS - 1))

    @property
    def p50(self) -> float:
        return self.quantile(0.50)

    @property
    def p99(self) -> float:
        return self.quantile(0.99)


@dataclass
class LimiterMetrics:
    """Per-limiter counters. ``snapshot()`` returns a plain dict for export."""

    decisions: int = 0
    grants: int = 0
    denials: int = 0
    queued: int = 0
    evicted: int = 0
    cancelled: int = 0
    sync_failures: int = 0
    syncs: int = 0
    last_sync_lag_s: float = 0.0
    acquire_latency: LatencyHistogram = field(default_factory=LatencyHistogram)

    def record_decision(self, granted: bool, latency_s: float | None = None) -> None:
        self.decisions += 1
        if granted:
            self.grants += 1
        else:
            self.denials += 1
        if latency_s is not None:
            self.acquire_latency.record(latency_s)

    def record_bulk(self, n: int, granted: int,
                    latency_s: float | None = None) -> None:
        """One bulk call = ``n`` decisions; latency recorded once (it is
        the whole call's, not any single request's)."""
        self.decisions += n
        self.grants += granted
        self.denials += n - granted
        if latency_s is not None:
            self.acquire_latency.record(latency_s)

    @property
    def denial_rate(self) -> float:
        return self.denials / self.decisions if self.decisions else 0.0

    def snapshot(self) -> dict:
        return {
            "decisions": self.decisions,
            "grants": self.grants,
            "denials": self.denials,
            "denial_rate": self.denial_rate,
            "queued": self.queued,
            "evicted": self.evicted,
            "cancelled": self.cancelled,
            "syncs": self.syncs,
            "sync_failures": self.sync_failures,
            "last_sync_lag_s": self.last_sync_lag_s,
            "acquire_p50_s": self.acquire_latency.p50,
            "acquire_p99_s": self.acquire_latency.p99,
        }


@dataclass
class Tier0Metrics:
    """Python-side half of the native front-end's tier-0 admission-cache
    observability (the C side counts hits/denies/misses/installs/
    evictions; ``NativeFrontend.tier0_stats`` merges both). Tracks the
    sync pump: reconciliation rounds, degraded-mode failures, and the
    over-admission the saturating debit actually observed — the gauges
    the documented epsilon bound is audited against."""

    syncs: int = 0
    sync_failures: int = 0
    keys_synced: int = 0
    #: Total drained permits that found no tokens (clamped shortfall) —
    #: realized over-admission, to be compared against epsilon.
    overadmit_total: float = 0.0
    #: Largest single-key shortfall seen in any one sync round.
    overadmit_max: float = 0.0
    #: monotonic timestamp of the last successful sync (0 = never) —
    #: ``last_sync_age_s`` in snapshots is the staleness gauge.
    last_sync_mono: float = 0.0
    #: Harvested rows whose (cap, rate) a live config mutation retired
    #: mid-flight: their debits re-routed to the replacement config and
    #: the replica's headroom for the old config was zeroed
    #: (docs/OPERATIONS.md §10).
    retired_config_rows: int = 0

    def record_sync(self, n_keys: int, shortfalls, now_mono: float) -> None:
        self.syncs += 1
        self.keys_synced += n_keys
        if len(shortfalls):
            total = float(sum(shortfalls))
            self.overadmit_total += total
            self.overadmit_max = max(self.overadmit_max,
                                     float(max(shortfalls)))
        self.last_sync_mono = now_mono

    def snapshot(self, now_mono: float) -> dict:
        return {
            "syncs": self.syncs,
            "sync_failures": self.sync_failures,
            "keys_synced": self.keys_synced,
            "overadmit_total": self.overadmit_total,
            "overadmit_max": self.overadmit_max,
            "retired_config_rows": self.retired_config_rows,
            "last_sync_age_s": (now_mono - self.last_sync_mono
                                if self.last_sync_mono else -1.0),
        }


@dataclass
class StoreMetrics:
    """Per-store (device) counters: kernel launches and batch occupancy."""

    launches: int = 0
    rows_processed: int = 0
    rows_valid: int = 0
    sweeps: int = 0
    slots_evicted: int = 0
    # Pallas streaming-sweep fallbacks: nonzero means the compiled Mosaic
    # path failed on this platform and sweeps silently use the XLA kernel —
    # the bench asserts this stays 0 on real TPU.
    pallas_sweep_failures: int = 0
    # Duplicate requests merged away by flush coalescing (requests minus
    # launch rows) — the Zipf hot-key win's direct measure.
    rows_coalesced: int = 0
    # Table growths (single-chip: background pre-warm compilations;
    # sharded: in-place per-shard doublings).
    pregrows: int = 0
    # Device-resident directory: requests denied because no probe-window
    # slot could be claimed (table pressure — a sweep/grow follows).
    fp_unresolved: int = 0
    # Wall time of each micro-batch flush (dispatch + device kernel +
    # readback, measured inside MicroBatcher._run_flush). Serving p99
    # minus flush p99 is the framework's own queueing/fan-out share —
    # the decomposition the <2ms north star needs (VERDICT r4 #3b).
    flush_latency: LatencyHistogram = field(default_factory=LatencyHistogram)
    # Stage 1 of the per-request decomposition: enqueue → flush dispatch,
    # recorded once per flush for the OLDEST request in the batch (its
    # wait upper-bounds every other member's, so this is the conservative
    # envelope of queueing — and costs one perf_counter diff per flush,
    # not per request). serving p99 ≈ queue + flush + reply, each its own
    # scrapeable histogram instead of a bench-time inference.
    queue_latency: LatencyHistogram = field(default_factory=LatencyHistogram)
    # Optional FlightRecorder (utils/flight_recorder.py) fed one frame per
    # flush by the store's flush observer; attached by the serving layer,
    # excluded from snapshot() (not a number).
    flight_recorder: object | None = None

    def record_launch(self, batch_rows: int, valid_rows: int) -> None:
        self.launches += 1
        self.rows_processed += batch_rows
        self.rows_valid += valid_rows

    @property
    def batch_occupancy(self) -> float:
        return self.rows_valid / self.rows_processed if self.rows_processed else 0.0

    def snapshot(self) -> dict:
        return {
            "launches": self.launches,
            "rows_processed": self.rows_processed,
            "rows_valid": self.rows_valid,
            "batch_occupancy": self.batch_occupancy,
            "sweeps": self.sweeps,
            "slots_evicted": self.slots_evicted,
            "pallas_sweep_failures": self.pallas_sweep_failures,
            "rows_coalesced": self.rows_coalesced,
            "pregrows": self.pregrows,
            "fp_unresolved": self.fp_unresolved,
            "flush_p50_ms": self.flush_latency.p50 * 1e3,
            "flush_p99_ms": self.flush_latency.p99 * 1e3,
            "flush_samples": self.flush_latency.total,
            "queue_p50_ms": self.queue_latency.p50 * 1e3,
            "queue_p99_ms": self.queue_latency.p99 * 1e3,
            "queue_samples": self.queue_latency.total,
        }


class CounterDeltas:
    """Per-CONSUMER monotonic-counter differ — THE non-destructive way to
    turn cumulative counters into windowed rates (and the guard half of
    the destructive-reset contract in the module docstring).

    Each consumer owns one instance: :meth:`delta` returns the
    non-negative increase of a named counter since *this consumer's*
    previous observation, so any number of scrapers derive rates over
    the same source concurrently without coordinating and without ever
    mutating server state (no ``reset=True``). Counter resets — a
    restarted server reporting a smaller value — restart the window:
    the new value counts as the increase since the reset (the
    Prometheus ``rate()`` convention), never a negative delta.

    Bounded: at ``max_keys`` tracked names the least-recently-observed
    one is forgotten (dynamic series like per-key sketch counts churn;
    a forgotten key's next observation re-anchors at zero delta, which
    only ever under-reports — the conservative direction for every
    consumer this class has)."""

    __slots__ = ("max_keys", "_last")

    def __init__(self, max_keys: int = 8192) -> None:
        if max_keys <= 0:
            raise ValueError("max_keys must be positive")
        self.max_keys = max_keys
        # Insertion order == recency order (moved on every touch).
        self._last: dict[str, float] = {}

    def __len__(self) -> int:
        return len(self._last)

    def delta(self, key: str, value: float) -> float:
        """Increase of counter ``key`` since the previous observation
        (0.0 on the first — the window anchors, it does not report the
        counter's whole lifetime as one burst)."""
        value = float(value)
        last = self._last.pop(key, None)
        if last is None and len(self._last) >= self.max_keys:
            del self._last[next(iter(self._last))]
        self._last[key] = value
        if last is None:
            return 0.0
        if value < last:
            return value  # counter reset: increase since the restart
        return value - last

    def rate(self, key: str, value: float, dt_s: float) -> float:
        """``delta / dt_s`` — the per-second rate over one window."""
        d = self.delta(key, value)
        return d / dt_s if dt_s > 0 else 0.0

    def deltas(self, samples: "Mapping[str, float]") -> dict[str, float]:
        """Vector :meth:`delta` over a ``{name: value}`` snapshot."""
        return {k: self.delta(k, v) for k, v in samples.items()}


# ---------------------------------------------------------------------------
# OpenMetrics exposition
# ---------------------------------------------------------------------------

def _escape_label(value: str) -> str:
    """OpenMetrics label-value escaping: backslash, double-quote, newline."""
    return (value.replace("\\", "\\\\").replace('"', '\\"')
            .replace("\n", "\\n"))


def _format_labels(labels: "Mapping[str, str] | None") -> str:
    if not labels:
        return ""
    inner = ",".join(f'{k}="{_escape_label(str(v))}"'
                     for k, v in labels.items())
    return "{" + inner + "}"


def _format_value(v: float) -> str:
    """Compact numeric rendering: integers stay integral, floats use
    repr (full precision — scrapers diff counters, so rounding loses
    information)."""
    if isinstance(v, bool):
        return "1" if v else "0"
    if isinstance(v, int):
        return str(v)
    f = float(v)
    return str(int(f)) if f.is_integer() and abs(f) < 2**53 else repr(f)


class MetricsRegistry:
    """Named metric families rendered as OpenMetrics text exposition.

    Families are registered once with a *callable* that reads the live
    value at scrape time — the registry holds no state of its own, so
    registration costs the serving path nothing and a scrape sees the
    counters exactly as the GIL-guarded writers left them. Three family
    kinds cover everything the framework tracks:

    - ``counter(name, help, fn)`` — monotonically increasing; rendered
      with the OpenMetrics-required ``_total`` sample suffix.
    - ``gauge(name, help, fn)`` — point-in-time value.
    - ``histogram(name, help, fn)`` — ``fn`` returns a
      :class:`LatencyHistogram` (or None to skip); rendered as cumulative
      ``_bucket{le=...}`` series plus ``_count``/``_sum``.

    ``labels`` lets one family carry several series (e.g. per-stage
    latency: ``drl_stage_latency_seconds{stage="queue"}``); register the
    same ``name`` repeatedly with distinct label sets.
    ``register_numeric_dict`` bulk-adopts an existing ``snapshot()``-style
    dict (StoreMetrics, Tier0Metrics, LimiterMetrics) as one gauge/counter
    family per numeric key.
    """

    NAMESPACE = "drl"

    def __init__(self, namespace: str | None = None) -> None:
        self.namespace = namespace if namespace is not None else self.NAMESPACE
        # name -> (type, help); insertion-ordered so exposition is stable.
        self._families: dict[str, tuple[str, str]] = {}
        # (name, labels-tuple, kind, fn) sample sources in registration order.
        self._samples: list[tuple[str, tuple, str, Callable]] = []

    def _full(self, name: str) -> str:
        return f"{self.namespace}_{name}" if self.namespace else name

    def _add(self, name: str, mtype: str, help_text: str,
             fn: Callable, labels: "Mapping[str, str] | None") -> None:
        full = self._full(name)
        prev = self._families.get(full)
        if prev is not None and prev[0] != mtype:
            raise ValueError(
                f"metric {full} already registered as {prev[0]}, "
                f"not {mtype}")
        self._families.setdefault(full, (mtype, help_text))
        self._samples.append(
            (full, tuple((labels or {}).items()), mtype, fn))

    def counter(self, name: str, help_text: str, fn: Callable[[], float],
                labels: "Mapping[str, str] | None" = None) -> None:
        self._add(name, "counter", help_text, fn, labels)

    def gauge(self, name: str, help_text: str, fn: Callable[[], float],
              labels: "Mapping[str, str] | None" = None) -> None:
        self._add(name, "gauge", help_text, fn, labels)

    def histogram(self, name: str, help_text: str,
                  fn: "Callable[[], LatencyHistogram | None]",
                  labels: "Mapping[str, str] | None" = None) -> None:
        self._add(name, "histogram", help_text, fn, labels)

    def labeled_gauges(self, name: str, help_text: str,
                       fn: "Callable[[], Iterable[tuple[dict, float]]]"
                       ) -> None:
        """One gauge family whose SERIES SET is dynamic at scrape time —
        ``fn`` yields ``(labels_dict, value)`` pairs (the heavy-hitter
        top-K, whose keys change between scrapes)."""
        self._add(name, "gauge", help_text, fn, {"__dynamic__": "1"})

    def labeled_counters(self, name: str, help_text: str,
                         fn: "Callable[[], Iterable[tuple[dict, float]]]"
                         ) -> None:
        """Counter twin of :meth:`labeled_gauges`: a dynamic series set
        rendered with the OpenMetrics-required ``_total`` sample suffix
        (e.g. the controller's
        ``drl_controller_actions_total{action=,outcome=}`` family)."""
        self._add(name, "counter", help_text, fn, {"__dynamic__": "1"})

    def register_numeric_dict(self, prefix: str, help_prefix: str,
                              fn: "Callable[[], Mapping | None]",
                              counters: "set[str] | frozenset[str]" = frozenset(),
                              labels: "Mapping[str, str] | None" = None
                              ) -> None:
        """Adopt a ``snapshot()``-style dict wholesale: every numeric key
        becomes ``<prefix>_<key>`` (counter when named in ``counters``,
        gauge otherwise; non-numeric and nested values are skipped). The
        key set is re-read per scrape, so optional keys (e.g. tier-0 off)
        simply don't render."""

        def emit():
            d = fn()
            if not d:
                return []
            out = []
            for k, v in d.items():
                if isinstance(v, bool) or not isinstance(v, (int, float)):
                    continue
                out.append((k, float(v)))
            return out

        # Registered as one dynamic family per numeric key at scrape time:
        # store under a sentinel so render() expands names per key.
        full = self._full(prefix)
        self._families.setdefault(full, ("dict", help_prefix))
        self._samples.append(
            (full, tuple((labels or {}).items()) + (
                ("__counters__", frozenset(counters)),), "dict", emit))

    # -- rendering -----------------------------------------------------------
    CONTENT_TYPE = ("application/openmetrics-text; version=1.0.0; "
                    "charset=utf-8")

    def render(self, exemplars: bool = True) -> str:
        """The full OpenMetrics text exposition, terminated by ``# EOF``.
        ``exemplars=False`` suppresses exemplar annotations — for the
        Prometheus text-0.0.4 fallback the HTTP listener serves to
        scrapers that did not ``Accept`` openmetrics (exemplars are an
        OpenMetrics-only construct)."""
        lines: list[str] = []
        seen_type: set[str] = set()

        def type_line(name: str, mtype: str, help_text: str) -> None:
            if name in seen_type:
                return
            seen_type.add(name)
            lines.append(f"# TYPE {name} {mtype}")
            if help_text:
                lines.append(f"# HELP {name} {_escape_label(help_text)}")

        for full, labels_t, kind, fn in self._samples:
            mtype, help_text = self._families[full]
            if kind == "dict":
                labels = dict(labels_t)
                counters = labels.pop("__counters__", frozenset())
                try:
                    items = fn()
                except Exception:
                    continue  # a broken reader must not kill the scrape
                lbl = _format_labels(labels)
                for key, value in items:
                    name = f"{full}_{key}"
                    is_counter = key in counters
                    type_line(name, "counter" if is_counter else "gauge",
                              help_text and f"{help_text}: {key}")
                    suffix = "_total" if is_counter else ""
                    lines.append(
                        f"{name}{suffix}{lbl} {_format_value(value)}")
                continue
            labels = dict(labels_t)
            dynamic = labels.pop("__dynamic__", None)
            try:
                value = fn()
            except Exception:
                continue
            type_line(full, mtype, help_text)
            if dynamic:
                suffix = "_total" if mtype == "counter" else ""
                for series_labels, v in value:
                    lines.append(f"{full}{suffix}"
                                 f"{_format_labels(series_labels)} "
                                 f"{_format_value(v)}")
            elif mtype == "histogram":
                if value is None:
                    continue
                self._render_histogram(lines, full, labels, value,
                                       exemplars)
            else:
                if value is None:
                    continue
                suffix = "_total" if mtype == "counter" else ""
                lines.append(f"{full}{suffix}{_format_labels(labels)} "
                             f"{_format_value(value)}")
        lines.append("# EOF")
        return "\n".join(lines) + "\n"

    @staticmethod
    def _render_histogram(lines: list[str], full: str, labels: dict,
                          hist: LatencyHistogram,
                          exemplars: bool = True) -> None:
        bounds = hist.bucket_upper_bounds()
        ex = hist.exemplars if exemplars else None
        cum = 0
        for i, c in enumerate(hist.counts):
            cum += c
            if (c == 0 and i < len(hist.counts) - 1
                    and (ex is None or i not in ex)):
                continue  # sparse: only emit buckets that move the cdf
            le = ("+Inf" if i == len(hist.counts) - 1
                  else repr(bounds[i]))
            lbl = _format_labels({**labels, "le": le})
            line = f"{full}_bucket{lbl} {cum}"
            if ex is not None and i in ex:
                # OpenMetrics exemplar: `value # {labels} ex_value ex_ts`
                tid, val, ts = ex[i]
                line += (f' # {{trace_id="{_escape_label(tid)}"}} '
                         f"{_format_value(val)} {round(ts, 3)}")
            lines.append(line)
        lbl = _format_labels(labels)
        lines.append(f"{full}_count{lbl} {hist.total}")
        lines.append(f"{full}_sum{lbl} {_format_value(hist.sum_s)}")


def parse_openmetrics(text: str) -> tuple[dict[str, str],
                                          list[tuple[str, tuple, float]]]:
    """Minimal OpenMetrics parser for aggregation: returns
    ``(types_by_name, samples)`` where each sample is
    ``(sample_name, ((label, value), ...), float)``. Handles the subset
    :class:`MetricsRegistry` emits (exemplar annotations are stripped;
    timestamps are not emitted)."""
    types: dict[str, str] = {}
    samples: list[tuple[str, tuple, float]] = []
    for line in text.splitlines():
        line = line.strip()
        if not line or line == "# EOF":
            continue
        # Exemplars ride after ` # {...}` on bucket lines — aggregation
        # sums sample values, so they drop here (quote-aware: a label
        # VALUE may legitimately contain " # ").
        if not line.startswith("#"):
            line = _strip_exemplar(line)
        if line.startswith("#"):
            parts = line.split(None, 3)
            if len(parts) >= 4 and parts[1] == "TYPE":
                types[parts[2]] = parts[3]
            continue
        if "{" in line:
            name, rest = line.split("{", 1)
            lbl_text, _, val_text = rest.rpartition("}")
            labels = []
            for piece in _split_labels(lbl_text):
                k, _, v = piece.partition("=")
                labels.append((k, _unescape_label(v.strip('"'))))
            labels_t = tuple(labels)
        else:
            name, _, val_text = line.rpartition(" ")
            labels_t = ()
        try:
            samples.append((name.strip(), labels_t, float(val_text)))
        except ValueError:
            continue
    return types, samples


def _strip_exemplar(line: str) -> str:
    """Drop a sample line's exemplar annotation (`` # {...} val ts``).
    The split must happen AFTER the label set's closing brace — label
    values are user-controlled (hot keys) and may contain ``\" # \"``
    themselves — so the label block is skipped with the same
    quote/escape rules :func:`_split_labels` uses."""
    start = 0
    brace = line.find("{")
    space = line.find(" ")
    if brace != -1 and (space == -1 or brace < space):
        in_q = esc = False
        for i in range(brace + 1, len(line)):
            ch = line[i]
            if esc:
                esc = False
            elif ch == "\\":
                esc = True
            elif ch == '"':
                in_q = not in_q
            elif ch == "}" and not in_q:
                start = i + 1
                break
        else:
            return line  # unterminated label set: leave as-is
    cut = line.find(" # ", start)
    return line[:cut].rstrip() if cut != -1 else line


def _split_labels(text: str) -> list[str]:
    """Split ``a="x",b="y"`` on commas outside quotes."""
    out, buf, in_q, esc = [], [], False, False
    for ch in text:
        if esc:
            buf.append(ch)
            esc = False
            continue
        if ch == "\\":
            buf.append(ch)
            esc = True
            continue
        if ch == '"':
            in_q = not in_q
            buf.append(ch)
            continue
        if ch == "," and not in_q:
            out.append("".join(buf))
            buf = []
            continue
        buf.append(ch)
    if buf:
        out.append("".join(buf))
    return out


def _unescape_label(value: str) -> str:
    return (value.replace("\\n", "\n").replace('\\"', '"')
            .replace("\\\\", "\\"))


def aggregate_openmetrics(node_texts: "Iterable[str]",
                          node_label: str = "node") -> str:
    """Merge N nodes' OpenMetrics expositions into one: every sample is
    re-emitted per node with a ``node="<i>"`` label, and samples that sum
    meaningfully (counters, histogram ``_bucket``/``_count``/``_sum``, and
    additive gauges) also get an aggregated series without the node label.
    Non-additive gauges (rates, quantile gauges) aggregate as sums too —
    consumers who care read the per-node series; the summed series is the
    fleet-roll-up convention (the same one ``ClusterBucketStore.stats``
    already uses for its JSON totals). Output is grouped per family (one
    ``# TYPE`` line, then that family's aggregated + per-node samples,
    contiguously) — OpenMetrics forbids interleaving a family's samples
    with another's, and compliant scrapers enforce it."""
    agg: dict[tuple[str, tuple], float] = {}
    agg_order: list[tuple[str, tuple]] = []
    per_node: dict[str, list[str]] = {}  # family -> per-node sample lines
    types: dict[str, str] = {}
    fam_order: list[str] = []

    def base_family(sample_name: str) -> str:
        for suffix in ("_bucket", "_count", "_sum", "_total"):
            if sample_name.endswith(suffix):
                root = sample_name[: -len(suffix)]
                if root in types:
                    return root
        return sample_name

    for i, text in enumerate(node_texts):
        node_types, samples = parse_openmetrics(text)
        types.update(node_types)
        for name, labels_t, value in samples:
            key = (name, labels_t)
            if key not in agg:
                agg[key] = 0.0
                agg_order.append(key)
            agg[key] += value
            fam = base_family(name)
            if fam not in per_node:
                per_node[fam] = []
                fam_order.append(fam)
            lbl = _format_labels(dict(labels_t) | {node_label: str(i)})
            per_node[fam].append(f"{name}{lbl} {_format_value(value)}")
    agg_by_family: dict[str, list[str]] = {}
    for name, labels_t in agg_order:
        fam = base_family(name)
        agg_by_family.setdefault(fam, []).append(
            f"{name}{_format_labels(dict(labels_t))} "
            f"{_format_value(agg[(name, labels_t)])}")
    lines: list[str] = []
    for fam in fam_order:
        if fam in types:
            lines.append(f"# TYPE {fam} {types[fam]}")
        lines.extend(agg_by_family.get(fam, []))
        lines.extend(per_node[fam])
    lines.append("# EOF")
    return "\n".join(lines) + "\n"
