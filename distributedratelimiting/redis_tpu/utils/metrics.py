"""Metrics — decisions/sec, denial rate, batch occupancy, sync lag, latency.

The reference's observability is skeletal (two error log events plus a
``ToString()`` dump, SURVEY.md §5.5); real metrics are a gap the new
framework fills since the north-star metric is decisions/sec + p99 latency.
Counters are plain ints guarded by the GIL (single event loop); latency uses
fixed log-spaced buckets so p50/p99 are O(1) to read and recording is
allocation-free.
"""

from __future__ import annotations

import math
from dataclasses import dataclass, field


class LatencyHistogram:
    """Log-spaced buckets from 1µs to ~70s (factor 1.25, 82 buckets).

    Base 1.25 bounds quantile error at +25% of the true value everywhere
    (a quantile reports its bucket's upper edge) — the old √2 base's ±41%
    was too coarse exactly where the <2ms p99 north star lives (the
    0.5-16ms decade spans ~15 buckets now vs ~10 before at twice the
    width; VERDICT r4 weak #2). Still O(1) memory and allocation-free
    recording."""

    BASE = 1.25
    MIN_S = 1e-6
    N_BUCKETS = 82

    def __init__(self) -> None:
        self.counts = [0] * self.N_BUCKETS
        self.total = 0

    def reset(self) -> None:
        """Zero in place. Holders keep their reference (the MicroBatcher
        captures the histogram at construction), so a measurement-window
        reset must NOT swap in a fresh object."""
        self.counts = [0] * self.N_BUCKETS
        self.total = 0

    def record(self, seconds: float) -> None:
        if seconds <= self.MIN_S:
            idx = 0
        else:
            idx = min(
                self.N_BUCKETS - 1,
                int(math.log(seconds / self.MIN_S, self.BASE)) + 1,
            )
        self.counts[idx] += 1
        self.total += 1

    def quantile(self, q: float) -> float:
        """Upper bound of the bucket containing quantile ``q`` (0..1)."""
        if self.total == 0:
            return 0.0
        target = q * self.total
        seen = 0
        for i, c in enumerate(self.counts):
            seen += c
            if seen >= target:
                return self.MIN_S * (self.BASE ** i)
        return self.MIN_S * (self.BASE ** (self.N_BUCKETS - 1))

    @property
    def p50(self) -> float:
        return self.quantile(0.50)

    @property
    def p99(self) -> float:
        return self.quantile(0.99)


@dataclass
class LimiterMetrics:
    """Per-limiter counters. ``snapshot()`` returns a plain dict for export."""

    decisions: int = 0
    grants: int = 0
    denials: int = 0
    queued: int = 0
    evicted: int = 0
    cancelled: int = 0
    sync_failures: int = 0
    syncs: int = 0
    last_sync_lag_s: float = 0.0
    acquire_latency: LatencyHistogram = field(default_factory=LatencyHistogram)

    def record_decision(self, granted: bool, latency_s: float | None = None) -> None:
        self.decisions += 1
        if granted:
            self.grants += 1
        else:
            self.denials += 1
        if latency_s is not None:
            self.acquire_latency.record(latency_s)

    def record_bulk(self, n: int, granted: int,
                    latency_s: float | None = None) -> None:
        """One bulk call = ``n`` decisions; latency recorded once (it is
        the whole call's, not any single request's)."""
        self.decisions += n
        self.grants += granted
        self.denials += n - granted
        if latency_s is not None:
            self.acquire_latency.record(latency_s)

    @property
    def denial_rate(self) -> float:
        return self.denials / self.decisions if self.decisions else 0.0

    def snapshot(self) -> dict:
        return {
            "decisions": self.decisions,
            "grants": self.grants,
            "denials": self.denials,
            "denial_rate": self.denial_rate,
            "queued": self.queued,
            "evicted": self.evicted,
            "cancelled": self.cancelled,
            "syncs": self.syncs,
            "sync_failures": self.sync_failures,
            "last_sync_lag_s": self.last_sync_lag_s,
            "acquire_p50_s": self.acquire_latency.p50,
            "acquire_p99_s": self.acquire_latency.p99,
        }


@dataclass
class Tier0Metrics:
    """Python-side half of the native front-end's tier-0 admission-cache
    observability (the C side counts hits/denies/misses/installs/
    evictions; ``NativeFrontend.tier0_stats`` merges both). Tracks the
    sync pump: reconciliation rounds, degraded-mode failures, and the
    over-admission the saturating debit actually observed — the gauges
    the documented epsilon bound is audited against."""

    syncs: int = 0
    sync_failures: int = 0
    keys_synced: int = 0
    #: Total drained permits that found no tokens (clamped shortfall) —
    #: realized over-admission, to be compared against epsilon.
    overadmit_total: float = 0.0
    #: Largest single-key shortfall seen in any one sync round.
    overadmit_max: float = 0.0
    #: monotonic timestamp of the last successful sync (0 = never) —
    #: ``last_sync_age_s`` in snapshots is the staleness gauge.
    last_sync_mono: float = 0.0

    def record_sync(self, n_keys: int, shortfalls, now_mono: float) -> None:
        self.syncs += 1
        self.keys_synced += n_keys
        if len(shortfalls):
            total = float(sum(shortfalls))
            self.overadmit_total += total
            self.overadmit_max = max(self.overadmit_max,
                                     float(max(shortfalls)))
        self.last_sync_mono = now_mono

    def snapshot(self, now_mono: float) -> dict:
        return {
            "syncs": self.syncs,
            "sync_failures": self.sync_failures,
            "keys_synced": self.keys_synced,
            "overadmit_total": self.overadmit_total,
            "overadmit_max": self.overadmit_max,
            "last_sync_age_s": (now_mono - self.last_sync_mono
                                if self.last_sync_mono else -1.0),
        }


@dataclass
class StoreMetrics:
    """Per-store (device) counters: kernel launches and batch occupancy."""

    launches: int = 0
    rows_processed: int = 0
    rows_valid: int = 0
    sweeps: int = 0
    slots_evicted: int = 0
    # Pallas streaming-sweep fallbacks: nonzero means the compiled Mosaic
    # path failed on this platform and sweeps silently use the XLA kernel —
    # the bench asserts this stays 0 on real TPU.
    pallas_sweep_failures: int = 0
    # Duplicate requests merged away by flush coalescing (requests minus
    # launch rows) — the Zipf hot-key win's direct measure.
    rows_coalesced: int = 0
    # Table growths (single-chip: background pre-warm compilations;
    # sharded: in-place per-shard doublings).
    pregrows: int = 0
    # Device-resident directory: requests denied because no probe-window
    # slot could be claimed (table pressure — a sweep/grow follows).
    fp_unresolved: int = 0
    # Wall time of each micro-batch flush (dispatch + device kernel +
    # readback, measured inside MicroBatcher._run_flush). Serving p99
    # minus flush p99 is the framework's own queueing/fan-out share —
    # the decomposition the <2ms north star needs (VERDICT r4 #3b).
    flush_latency: LatencyHistogram = field(default_factory=LatencyHistogram)

    def record_launch(self, batch_rows: int, valid_rows: int) -> None:
        self.launches += 1
        self.rows_processed += batch_rows
        self.rows_valid += valid_rows

    @property
    def batch_occupancy(self) -> float:
        return self.rows_valid / self.rows_processed if self.rows_processed else 0.0

    def snapshot(self) -> dict:
        return {
            "launches": self.launches,
            "rows_processed": self.rows_processed,
            "rows_valid": self.rows_valid,
            "batch_occupancy": self.batch_occupancy,
            "sweeps": self.sweeps,
            "slots_evicted": self.slots_evicted,
            "pallas_sweep_failures": self.pallas_sweep_failures,
            "rows_coalesced": self.rows_coalesced,
            "pregrows": self.pregrows,
            "fp_unresolved": self.fp_unresolved,
            "flush_p50_ms": self.flush_latency.p50 * 1e3,
            "flush_p99_ms": self.flush_latency.p99 * 1e3,
            "flush_samples": self.flush_latency.total,
        }
