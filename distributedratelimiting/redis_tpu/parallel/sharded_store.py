"""Key-sharded bucket state over a device mesh + the two-level psum step.

This is the scale-out tier (SURVEY.md §5.7-5.8, §7 L4): the
``(key → {tokens, last_ts})`` table becomes 1-D arrays sharded along the
key axis of a ``Mesh``; key→shard routing is a stable hash on the host;
per-key independence means the hot acquire path needs **zero cross-chip
communication** — each shard decides its own keys' requests in its own
HBM. The only collective is the approximate algorithm's global tier: one
``lax.psum`` of per-chip consumed counts per sync (replacing the
reference's per-period Redis round-trip,
``RedisApproximateTokenBucketRateLimiter.cs:439``), so the ICI cost is one
scalar all-reduce per period, not per request.

``make_two_level_step`` builds the flagship fused step — sharded batched
acquire + psum + decaying replicated global counter — which is also the
framework's ``dryrun_multichip`` / bench entry (BASELINE config 5).
"""

from __future__ import annotations

import zlib
from functools import partial
from typing import NamedTuple, Sequence

import jax
import jax.numpy as jnp
import numpy as np
from distributedratelimiting.redis_tpu.parallel._shard_compat import (
    pcast_varying,
    shard_map,
)
from jax.sharding import NamedSharding, PartitionSpec as P

from distributedratelimiting.redis_tpu.ops import bucket_math as bm
from distributedratelimiting.redis_tpu.ops import kernels as K
from distributedratelimiting.redis_tpu.parallel.mesh import SHARD_AXIS
from distributedratelimiting.redis_tpu.runtime.clock import Clock, MonotonicClock
from distributedratelimiting.redis_tpu.runtime.directory import make_directory
from distributedratelimiting.redis_tpu.runtime.store import (
    AcquireResult,
    BulkAcquireResult,
    _pad_size,
    _REBASE_MARGIN_TICKS,
    _REBASE_THRESHOLD_TICKS,
    _shift_ts,
)
from distributedratelimiting.redis_tpu.utils.metrics import StoreMetrics
from distributedratelimiting.redis_tpu.utils.native import load_directory_lib

__all__ = [
    "GlobalCounter",
    "make_sharded_acquire_step",
    "make_two_level_step",
    "make_two_level_scan_step",
    "make_two_level_scan_step_deferred",
    "make_sharded_window_scan_step",
    "ShardedDeviceStore",
    "ShardedWindowStore",
    "shard_of_key",
    "route_keys",
]


class GlobalCounter(NamedTuple):
    """Replicated decaying global counter (one logical limiter's shared
    tier): scalar ``{v, p, t}`` hash, same as the reference's global bucket
    (``RedisApproximateTokenBucketRateLimiter.cs:265-268``)."""

    value: jax.Array    # f32[] decaying throttle score
    period: jax.Array   # f32[] EWMA of inter-sync interval (ticks)
    last_ts: jax.Array  # i32[]
    exists: jax.Array   # bool[]


def global_tier_update(g: GlobalCounter, total, now,
                       decay_rate) -> GlobalCounter:
    """ONE recurrence of the two-level global tier (SURVEY.md invariant
    6): decay the replicated counter to ``now``, add the psum'd
    consumption, refresh the period EWMA. The single definition keeps
    every step variant (per-batch, per-launch, fingerprint) bit-identical
    by construction."""
    decayed, new_period = bm.decay_core(
        g.value, g.period, g.last_ts, g.exists, now, decay_rate)
    return GlobalCounter(value=decayed + total, period=new_period,
                         last_ts=jnp.asarray(now, jnp.int32),
                         exists=jnp.asarray(True))


def init_global_counter() -> GlobalCounter:
    return GlobalCounter(
        value=jnp.float32(0), period=jnp.float32(0),
        last_ts=jnp.int32(0), exists=jnp.asarray(False),
    )


@jax.jit
def _peek_gather(state: K.BucketState, idx):
    """Read one slot's ``(tokens, last_ts, exists)`` as one f32[3] — a
    single dispatch + readback regardless of the index's value. The i32
    timestamp travels bitcast (exact); the host views it back."""
    return jnp.stack([
        state.tokens[idx],
        jax.lax.bitcast_convert_type(state.last_ts[idx], jnp.float32),
        state.exists[idx].astype(jnp.float32),
    ])


def shard_of_key(key: str, n_shards: int) -> int:
    """Stable key→shard routing (host side). crc32 so every client process
    on every host routes identically — the distributed directory needs no
    coordination."""
    return zlib.crc32(key.encode("utf-8", "surrogateescape")) % n_shards


def route_keys(keys: "Sequence[str] | list[str]", n_shards: int) -> np.ndarray:
    """Vectorized :func:`shard_of_key` over a batch: one native C call for
    the whole batch when the directory library is built (the same zero-copy
    list[str] path the key directory uses), a Python crc32 loop otherwise.
    Both agree bit-for-bit with ``zlib.crc32(key) % n_shards``."""
    import ctypes

    n = len(keys)
    lib = load_directory_lib()
    blob = getattr(keys, "blob", None)
    if lib is not None and blob is not None:
        # wire.KeyBlob fast path: crc32-route straight off the frame's
        # key bytes (no Python strings — the mesh serving lane's half of
        # the zero-copy bulk path).
        out = np.empty(n, np.int32)
        lib.dir_route_batch(
            blob,
            keys.offsets.ctypes.data_as(ctypes.POINTER(ctypes.c_int64)),
            n, n_shards,
            out.ctypes.data_as(ctypes.POINTER(ctypes.c_int32)))
        return out
    if lib is not None and lib.has_pylist:
        if not isinstance(keys, list):
            keys = list(keys)
        out = np.empty(n, np.int32)
        if lib.dir_route_pylist(
                keys, n_shards,
                out.ctypes.data_as(ctypes.POINTER(ctypes.c_int32))) == 0:
            return out
    return np.fromiter(
        (zlib.crc32(k.encode("utf-8", "surrogateescape")) % n_shards
         for k in keys),
                       np.int32, n)


def make_sharded_acquire_step(mesh, *, handle_duplicates: bool = True):
    """Jitted sharded acquire: state sharded along keys, batch laid out as
    ``[n_shards, B_local]`` with shard-LOCAL slot ids. No collectives —
    each shard serves its keys independently.
    """
    state_specs = K.BucketState(P(SHARD_AXIS), P(SHARD_AXIS), P(SHARD_AXIS))
    batch_spec = P(SHARD_AXIS, None)

    def block(state, slots, counts, valid, now, capacity, rate):
        # Block sees its own [per_shard] slice and [1, B] batch rows.
        new_state, granted, remaining = K.acquire_core(
            state, slots[0], counts[0], valid[0], now, capacity, rate,
            handle_duplicates=handle_duplicates,
        )
        return new_state, granted[None], remaining[None]

    mapped = shard_map(
        block, mesh=mesh,
        in_specs=(state_specs, batch_spec, batch_spec, batch_spec, P(), P(), P()),
        out_specs=(state_specs, batch_spec, batch_spec),
    )
    return jax.jit(mapped, donate_argnums=0)


def make_two_level_step(mesh, *, handle_duplicates: bool = True):
    """The flagship fused multi-chip step (BASELINE config 5):

    1. sharded batched acquire over the key-sharded table (no comm);
    2. per-chip consumed = Σ granted counts;
    3. ``lax.psum`` over ICI → total consumed this step;
    4. replicated global counter decays and absorbs the total
       (``new_v = max(0, v − Δt·decay) + Σ``, the sync-script recurrence).

    Returns ``(new_state, granted, remaining, new_global, global_score)``.
    In production the global tier runs once per replenishment period; fusing
    it here costs one scalar psum and gives the dry-run/bench a single step
    exercising sharding + collective together.
    """
    state_specs = K.BucketState(P(SHARD_AXIS), P(SHARD_AXIS), P(SHARD_AXIS))
    gspecs = GlobalCounter(P(), P(), P(), P())
    batch_spec = P(SHARD_AXIS, None)

    def block(state, slots, counts, valid, now, capacity, rate,
              gcounter, decay_rate):
        new_state, granted, remaining = K.acquire_core(
            state, slots[0], counts[0], valid[0], now, capacity, rate,
            handle_duplicates=handle_duplicates,
        )
        consumed = jnp.sum(
            jnp.asarray(counts[0], jnp.float32) * granted
        )
        total = jax.lax.psum(consumed, SHARD_AXIS)  # the only collective
        new_g = global_tier_update(gcounter, total, now, decay_rate)
        return new_state, granted[None], remaining[None], new_g

    mapped = shard_map(
        block, mesh=mesh,
        in_specs=(state_specs, batch_spec, batch_spec, batch_spec,
                  P(), P(), P(), gspecs, P()),
        out_specs=(state_specs, batch_spec, batch_spec, gspecs),
    )
    return jax.jit(mapped, donate_argnums=(0, 7))


def make_two_level_scan_step(mesh, *, handle_duplicates: bool = True):
    """Scanned variant of :func:`make_two_level_step`: K micro-batches per
    launch (``lax.scan`` inside each shard's block), one psum + global-
    counter decay per scanned batch. Amortizes per-dispatch host overhead
    the same way :func:`~.ops.kernels.acquire_scan_compact` does on one
    chip — the sharded path is dispatch-bound at small per-step work, so
    scanning multiplies multi-chip throughput without touching semantics
    (each batch keeps its own ``now``; the global counter sees batches in
    order).

    Batch layout: ``slots_k/counts_k/valid_k: [n_shards, K, B_local]``
    (sharded on axis 0), ``nows_k: i32[K]`` replicated. Returns
    ``(new_state, granted [n_shards, K, B], remaining likewise,
    new_gcounter, )``.
    """
    state_specs = K.BucketState(P(SHARD_AXIS), P(SHARD_AXIS), P(SHARD_AXIS))
    gspecs = GlobalCounter(P(), P(), P(), P())
    batch_spec = P(SHARD_AXIS, None, None)

    def block(state, slots, counts, valid, nows, capacity, rate,
              gcounter, decay_rate):
        def body(carry, xs):
            st, g = carry
            sl, ct, va, now = xs
            st, granted, remaining = K.acquire_core(
                st, sl, ct, va, now, capacity, rate,
                handle_duplicates=handle_duplicates,
            )
            consumed = jnp.sum(jnp.asarray(ct, jnp.float32) * granted)
            total = jax.lax.psum(consumed, SHARD_AXIS)
            g = global_tier_update(g, total, now, decay_rate)
            return (st, g), (granted, remaining)

        # Blocks see [1, K, B] slices; scan over K.
        (state, gcounter), (granted, remaining) = jax.lax.scan(
            body, (state, gcounter),
            (slots[0], counts[0], valid[0], nows),
        )
        return state, granted[None], remaining[None], gcounter

    mapped = shard_map(
        block, mesh=mesh,
        in_specs=(state_specs, batch_spec, batch_spec, batch_spec,
                  P(), P(), P(), gspecs, P()),
        out_specs=(state_specs, batch_spec, batch_spec, gspecs),
    )
    return jax.jit(mapped, donate_argnums=(0, 7))


def make_two_level_scan_step_deferred(mesh, *, handle_duplicates: bool = True):
    """Cadence ablation counterpart of :func:`make_two_level_scan_step`:
    the K scanned batches run with NO collectives (acquire only,
    accumulating each chip's consumed count); ONE psum + ONE global-counter
    decay-and-add runs after the scan — i.e. per-LAUNCH sync instead of
    per-batch, the analogue of the reference's per-``ReplenishmentPeriod``
    sync against per-request (SURVEY.md §7 "Two-level sync cadence").

    Grant decisions are bit-identical to the per-batch variant — the
    acquire path never reads the global counter inside a launch (fair-share
    feedback happens between launches, in the approximate limiter). What
    changes is (a) collective count: 1/launch vs K/launch, and (b) the
    returned counter's decay granularity: one ``Δt·decay`` step at the last
    batch's ``now`` instead of K steps — staleness bounded by one launch's
    time span, exactly the reference's staleness ≤ period bound with
    "period" = launch cadence. Measured trade: benchmarks/RESULTS.md
    "Psum cadence ablation".
    """
    state_specs = K.BucketState(P(SHARD_AXIS), P(SHARD_AXIS), P(SHARD_AXIS))
    gspecs = GlobalCounter(P(), P(), P(), P())
    batch_spec = P(SHARD_AXIS, None, None)

    def block(state, slots, counts, valid, nows, capacity, rate,
              gcounter, decay_rate):
        def body(carry, xs):
            st, consumed_acc = carry
            sl, ct, va, now = xs
            st, granted, remaining = K.acquire_core(
                st, sl, ct, va, now, capacity, rate,
                handle_duplicates=handle_duplicates,
            )
            consumed = jnp.sum(jnp.asarray(ct, jnp.float32) * granted)
            return (st, consumed_acc + consumed), (granted, remaining)

        # The accumulator is per-shard ("varying" over the mesh axis inside
        # shard_map); the initial zero must be cast to match.
        zero = pcast_varying(jnp.zeros((), jnp.float32), SHARD_AXIS)
        (state, consumed_total), (granted, remaining) = jax.lax.scan(
            body, (state, zero),
            (slots[0], counts[0], valid[0], nows),
        )
        total = jax.lax.psum(consumed_total, SHARD_AXIS)  # ONE per launch
        gcounter = global_tier_update(gcounter, total, nows[-1], decay_rate)
        return state, granted[None], remaining[None], gcounter

    mapped = shard_map(
        block, mesh=mesh,
        in_specs=(state_specs, batch_spec, batch_spec, batch_spec,
                  P(), P(), P(), gspecs, P()),
        out_specs=(state_specs, batch_spec, batch_spec, gspecs),
    )
    return jax.jit(mapped, donate_argnums=(0, 7))


class _ShardedKeyedTable:
    """Shared host runtime for key-sharded device tables (buckets and
    windows): per-shard native key directories, one vectorized crc32
    routing call per batch, sweep/grow reclaim with cross-shard pinning,
    and per-shard doubling growth. Subclasses provide the device pieces:

    - ``_widen_state(old, new)`` — re-lay the sharded state arrays at the
      doubled per-shard width;
    - ``_device_sweep()`` — run the table's TTL sweep kernel against the
      current clock and return the freed-mask as a host bool array.

    Requires attributes: ``n_shards``, ``per_shard``, ``dirs``, ``_lock``,
    ``metrics``.
    """

    #: Max scanned batches per fused dispatch / per-shard row width of one
    #: scanned batch (bounds the jit cache to power-of-two K variants —
    #: see DeviceBucketStore._BULK_MAX_K).
    _BULK_MAX_K = 32
    _BULK_B = 2048

    # -- hooks -------------------------------------------------------------
    def _widen_state(self, old: int, new: int) -> None:
        raise NotImplementedError

    def _device_sweep(self) -> np.ndarray:
        raise NotImplementedError

    def force_rebase(self, offset: int) -> None:
        """Shift the table's stored time state by ``-offset`` ticks WITHOUT
        touching the clock (the composing store's coordinated-rebase
        hook)."""
        raise NotImplementedError

    # -- shared machinery --------------------------------------------------
    def now_ticks_checked(self) -> int:
        """Store clock read with int32-overflow protection: rebase the
        table and the clock together before ~24 days of tick time can
        overflow (composing stores disable this via
        ``rebase_threshold_ticks`` and coordinate one rebase across every
        table sharing the clock)."""
        now = self.clock.now_ticks()
        if now >= self._rebase_threshold:
            with self._lock:
                now = self.clock.now_ticks()
                if now >= self._rebase_threshold:
                    offset = now - _REBASE_MARGIN_TICKS
                    self.force_rebase(offset)
                    self.clock.rebase(offset)  # type: ignore[attr-defined]
                    now = self.clock.now_ticks()
        return now

    def _bulk_decide(self, keys: Sequence[str], counts: Sequence[int],
                     with_remaining: bool, launch_chunk) -> BulkAcquireResult:
        """Shared whole-array bulk path: vectorized key→(shard, local)
        resolve, ``[n_shards, K, B]`` chunk layout, readback fan-out, and
        the zero-permit probe override. ``launch_chunk(slots, counts,
        valid, nows)`` runs the table's scanned step and returns the
        ``(granted, remaining)`` device arrays."""
        n = len(keys)
        counts_np = np.asarray(counts, np.int64)
        granted_out = np.empty(n, bool)
        rem_out = np.empty(n, np.float32) if with_remaining else None
        if n == 0:
            return BulkAcquireResult(granted_out, rem_out)
        with self._lock:
            shards, locs = self._resolve_batch(keys)  # KeyBlob-aware
            jpos, shard_counts = self._group_by_shard(shards)
            max_rows = int(shard_counts.max(initial=1))
            b = _pad_size(min(max_rows, self._BULK_B), floor=8)
            pos = 0
            while pos < max_rows:
                rows = -(-(max_rows - pos) // b)  # ceil
                k = 1
                while k < rows and k < self._BULK_MAX_K:
                    k *= 2
                take_rows = k * b
                sel = (jpos >= pos) & (jpos < pos + take_rows)
                rel = (jpos[sel] - pos).astype(np.int64)
                s_sel = shards[sel]
                slots_chunk = np.full((self.n_shards, k, b), -1, np.int32)
                counts_chunk = np.zeros((self.n_shards, k, b), np.int32)
                valid_chunk = np.zeros((self.n_shards, k, b), bool)
                slots_chunk[s_sel, rel // b, rel % b] = locs[sel]
                counts_chunk[s_sel, rel // b, rel % b] = counts_np[sel]
                valid_chunk[s_sel, rel // b, rel % b] = True
                nows = np.full((k,), self.now_ticks_checked(), np.int32)
                granted, remaining = launch_chunk(
                    jnp.asarray(slots_chunk), jnp.asarray(counts_chunk),
                    jnp.asarray(valid_chunk), jnp.asarray(nows))
                g_np = np.asarray(granted)
                granted_out[sel] = g_np[s_sel, rel // b, rel % b] > 0.5
                if rem_out is not None:
                    r_np = np.asarray(remaining)
                    rem_out[sel] = r_np[s_sel, rel // b, rel % b]
                self.metrics.record_launch(self.n_shards * take_rows,
                                           int(sel.sum()))
                pos += take_rows
        if (counts_np == 0).any():
            # Zero-permit probes are granted unconditionally on every
            # single-request path; the bulk path's conservative in-batch
            # prefix could deny one riding beside denied same-key demand.
            granted_out[counts_np == 0] = True
        return BulkAcquireResult(granted_out, rem_out)

    @property
    def directory(self) -> dict[str, tuple[int, int]]:
        """Merged ``key → (shard, local slot)`` view (diagnostics/tests;
        the serving path never materializes this)."""
        return {
            key: (shard, local)
            for shard, d in enumerate(self.dirs)
            for key, local in d.to_dict().items()
        }

    def _resolve_batch(self, keys: list[str]) -> tuple[np.ndarray, np.ndarray]:
        """Vectorized key→(shard, local) resolution for a whole batch: one
        native routing call + one directory batch-resolve per touched shard
        (the mesh analogue of the single-chip one-C-call-per-flush resolve).
        On free-list exhaustion: sweep (pinning this batch's already-
        resolved slots), then grow every shard, re-resolving until all keys
        land — the single-chip reclaim discipline (store.py
        ``_resolve_with_reclaim``), with growth keeping the geometry
        homogeneous across shards."""
        fused = self._resolve_batch_fused(keys)
        if fused is not None:
            return fused
        if not isinstance(keys, list):
            keys = list(keys)  # split path indexes str refs via numpy
        shards = route_keys(keys, self.n_shards)
        locs = np.empty(len(keys), np.int32)
        # Object-array gather: numpy fancy indexing moves the str refs at
        # C speed — a Python `[keys[i] for i in …]` loop here was the
        # resolve path's dominant cost (measured 4x of everything else).
        keys_arr = np.asarray(keys, dtype=object)
        # (shard, locals) already resolved for THIS batch, across every
        # shard processed so far — a sweep triggered by a later shard's
        # exhaustion must not reclaim an earlier shard's TTL-expired slot
        # that this batch is about to dispatch to (the mid-batch
        # cross-contamination hazard). Kept as shard-tagged arrays and
        # materialized into a flat-id set ONLY when a sweep actually runs
        # (the rare path): growth mid-loop re-lays the flat index space,
        # and per-key Python tuple building is hot-path cost.
        done: list[tuple[int, np.ndarray]] = []
        # One stable argsort groups every shard's requests (8 per-shard
        # boolean scans + gathers cost ~2x this on large batches).
        order = np.argsort(shards, kind="stable")
        sorted_keys = keys_arr[order]
        sorted_shards = shards[order]
        bounds = np.searchsorted(sorted_shards,
                                 np.arange(self.n_shards + 1))
        for shard in range(self.n_shards):
            lo, hi = int(bounds[shard]), int(bounds[shard + 1])
            if lo == hi:
                continue
            idx = order[lo:hi]
            sub = sorted_keys[lo:hi].tolist()
            d = self.dirs[shard]
            slots = d.resolve_batch(sub)
            while (slots < 0).any():
                pinned = {
                    int(sh) * self.per_shard + int(loc)
                    for sh, arr in done for loc in arr
                }
                pinned.update(int(shard) * self.per_shard + int(s)
                              for s in slots[slots >= 0])
                self._sweep_locked(pinned)
                if d.free_count * 16 <= self.per_shard:
                    # Sweep-first hysteresis: a trickle of reclaimed slots
                    # on a near-full table would re-sweep on every batch —
                    # grow instead (all shards, keeping geometry uniform).
                    self._grow()
                slots = d.resolve_batch(sub)
            locs[idx] = slots
            done.append((int(shard), slots))
        return shards, locs

    def _resolve_batch_fused(self, keys: list[str]):
        """One C call routes AND resolves the whole batch (crc32 → shard →
        that shard's open-addressing probe, allocating on miss) — the mesh
        analogue of the single-chip one-call resolve, available when every
        per-shard directory is native. Returns ``None`` to fall back to
        the split route/group/resolve path (pure-Python directories, or a
        non-str key)."""
        import ctypes

        from distributedratelimiting.redis_tpu.runtime.directory import (
            NativeKeyDirectory,
        )

        # Capability is invariant after construction (dirs are created in
        # __init__ and reloaded in place by restore) — cache the verdict
        # so the hot path pays zero re-checks.
        fused_ok = getattr(self, "_fused_ok", None)
        if fused_ok is None:
            lib = load_directory_lib()
            # Blob inputs need only the plain C ABI; the pylist branch
            # additionally needs the CPython-API build (has_pylist).
            fused_ok = self._fused_ok = bool(
                lib is not None
                and all(isinstance(d, NativeKeyDirectory)
                        for d in self.dirs))
        if not fused_ok:
            return None
        lib = load_directory_lib()
        blob = getattr(keys, "blob", None)
        if blob is None:
            if not lib.has_pylist:
                return None  # split path handles the encode fallback
            if not isinstance(keys, list):
                keys = list(keys)
        n = len(keys)
        shards = np.empty(n, np.int32)
        locs = np.empty(n, np.int32)
        sh_ptr = shards.ctypes.data_as(ctypes.POINTER(ctypes.c_int32))
        lo_ptr = locs.ctypes.data_as(ctypes.POINTER(ctypes.c_int32))

        def call() -> int:
            # Handles re-read per call: restore()'s directory load swaps
            # the underlying native handle.
            handles = (ctypes.c_void_p * self.n_shards)(
                *(d._h for d in self.dirs))
            if blob is not None:
                # wire.KeyBlob zero-copy lane: route + probe straight off
                # the frame's key bytes (no Python strings).
                return int(lib.dir_resolve_sharded_batch(
                    blob,
                    keys.offsets.ctypes.data_as(
                        ctypes.POINTER(ctypes.c_int64)),
                    n, handles, self.n_shards, sh_ptr, lo_ptr))
            return int(lib.dir_resolve_sharded_pylist(
                keys, handles, self.n_shards, sh_ptr, lo_ptr))

        unresolved = call()
        if unresolved < 0:  # non-str key: let the split path raise naturally
            return None
        while unresolved > 0:
            ok = locs >= 0
            pinned = set((shards[ok].astype(np.int64) * self.per_shard
                          + locs[ok]).tolist())
            self._sweep_locked(pinned)
            dry = np.unique(shards[~ok])
            if any(self.dirs[s].free_count * 16 <= self.per_shard
                   for s in dry):
                # Sweep-first hysteresis (see the split path).
                self._grow()
            unresolved = call()  # already-resolved keys are idempotent
        return shards, locs

    def _grow(self) -> None:
        """Double every shard's slot capacity in place. The sharded layout
        is contiguous per shard, so growth re-lays the flat arrays as
        ``[n_shards, per_shard]`` blocks padded to twice the width — one
        host round-trip, amortized O(log growth) times over a store's life
        (the single-chip table's doubling discipline, store.py ``_grow``).
        Kernels recompile at the new shape on next launch."""
        old, new = self.per_shard, self.per_shard * 2
        self._widen_state(old, new)
        for d in self.dirs:
            d.add_slots(old, new)
        self.per_shard = new
        self.metrics.pregrows += 1

    def _widen_host(self, arr, old: int, new: int) -> np.ndarray:
        host = np.asarray(arr).reshape(self.n_shards, old)
        out = np.zeros((self.n_shards, new), host.dtype)
        out[:, :old] = host
        return out.reshape(-1)

    def _group_by_shard(self, shards: np.ndarray
                        ) -> tuple[np.ndarray, np.ndarray]:
        """Per-request row position within its shard's queue (stable in
        request order — duplicate keys keep arrival order for the kernel's
        prefix serialization) plus the per-shard load histogram."""
        n = len(shards)
        shard_counts = np.bincount(shards, minlength=self.n_shards)
        starts = np.zeros(self.n_shards + 1, np.int64)
        np.cumsum(shard_counts, out=starts[1:])
        order = np.argsort(shards, kind="stable")
        jpos = np.empty(n, np.int64)
        jpos[order] = np.arange(n) - starts[shards[order]]
        return jpos, shard_counts

    def sweep(self) -> int:
        """TTL eviction across all shards (elementwise → partitioned by XLA
        along the existing sharding, no resharding)."""
        with self._lock:
            return self._sweep_locked(None)

    def _sweep_locked(self, pinned: set[int] | None) -> int:
        """``pinned`` flat slot ids — slots already resolved for an
        in-flight batch — are exempt from reclamation (same mid-batch
        cross-contamination hazard as the single-chip store's sweep)."""
        freed_np = self._device_sweep()
        n_freed = 0
        if freed_np.any():
            dead = np.nonzero(freed_np)[0].astype(np.int64)
            if pinned:
                dead = dead[~np.isin(dead, np.fromiter(pinned, np.int64,
                                                       len(pinned)))]
            dead_shards = dead // self.per_shard
            dead_locals = (dead % self.per_shard).astype(np.int32)
            for shard in np.unique(dead_shards):
                n_freed += self.dirs[shard].remove_slots(
                    dead_locals[dead_shards == shard])
        self.metrics.sweeps += 1
        self.metrics.slots_evicted += n_freed
        return n_freed


class ShardedDeviceStore(_ShardedKeyedTable):
    """Host runtime for one key-sharded, homogeneous-config bucket table.

    Mirrors ``_DeviceTable``'s role in the single-chip store, scaled over a
    mesh: host directory maps key → (shard, local slot); requests are
    grouped by shard, padded to a common per-shard width, and decided in
    one launch of the sharded step. The global tier (two-level) is fused
    into the same launch.
    """

    def __init__(self, mesh, capacity: float, fill_rate_per_sec: float,
                 *, per_shard_slots: int = 2**14,
                 clock: Clock | None = None,
                 handle_duplicates: bool = True,
                 sync_cadence: str = "batch",
                 rebase_threshold_ticks: int = _REBASE_THRESHOLD_TICKS) -> None:
        if sync_cadence not in ("batch", "launch"):
            raise ValueError("sync_cadence must be 'batch' or 'launch'")
        self.mesh = mesh
        self.n_shards = mesh.devices.size
        self.per_shard = per_shard_slots
        self.capacity = float(capacity)
        self.fill_rate_per_sec = float(fill_rate_per_sec)
        self.rate_per_tick = fill_rate_per_sec / bm.TICKS_PER_SECOND
        self.clock = clock or MonotonicClock()
        self.metrics = StoreMetrics()
        # See DeviceBucketStore: a composing store coordinates rebases.
        self._rebase_threshold = rebase_threshold_ticks

        n_total = self.n_shards * per_shard_slots
        sharding = NamedSharding(mesh, P(SHARD_AXIS))
        self.state = K.BucketState(
            tokens=jax.device_put(jnp.zeros((n_total,), jnp.float32), sharding),
            last_ts=jax.device_put(jnp.zeros((n_total,), jnp.int32), sharding),
            exists=jax.device_put(jnp.zeros((n_total,), bool), sharding),
        )
        self.gcounter = jax.device_put(
            init_global_counter(), NamedSharding(mesh, P())
        )
        self._step = make_two_level_step(mesh,
                                         handle_duplicates=handle_duplicates)
        # Global-tier sync cadence (deployable form of the RESULTS.md
        # "Psum cadence ablation", +22% measured on the virtual mesh):
        # "batch" = one psum per scanned batch (K collectives/launch,
        # counter staleness ≤ one batch); "launch" = consumed counts
        # accumulate in-scan and ONE psum lands after it (staleness ≤ one
        # launch's time span — the reference's staleness ≤ period bound
        # with "period" = launch cadence). Grant decisions are
        # bit-identical either way; only the counter's decay granularity
        # and the collective count change.
        self.sync_cadence = sync_cadence
        scan_factory = (make_two_level_scan_step_deferred
                        if sync_cadence == "launch"
                        else make_two_level_scan_step)
        self._scan_step = scan_factory(
            mesh, handle_duplicates=handle_duplicates)
        # One key→local-slot directory per shard (C++ batch-resolve when
        # buildable — runtime/directory.py); routing key→shard is crc32.
        self.dirs = [make_directory(per_shard_slots)
                     for _ in range(self.n_shards)]
        import threading

        self._lock = threading.RLock()

    # -- _ShardedKeyedTable hooks ------------------------------------------
    def _widen_state(self, old: int, new: int) -> None:
        sharding = NamedSharding(self.mesh, P(SHARD_AXIS))
        self.state = K.BucketState(
            tokens=jax.device_put(
                self._widen_host(self.state.tokens, old, new), sharding),
            last_ts=jax.device_put(
                self._widen_host(self.state.last_ts, old, new), sharding),
            exists=jax.device_put(
                self._widen_host(self.state.exists, old, new), sharding),
        )

    def _device_sweep(self) -> np.ndarray:
        now = self.now_ticks_checked()
        self.state, freed = K.sweep_expired(
            self.state, jnp.int32(now), jnp.float32(self.capacity),
            jnp.float32(self.rate_per_tick),
        )
        return np.asarray(freed)

    def force_rebase(self, offset: int) -> None:
        """Shift table + global-counter timestamps without touching the
        clock (the composing store's coordinated-rebase hook — see
        ``DeviceBucketStore.force_rebase``)."""
        with self._lock:
            self.state = K.rebase_bucket_epoch(self.state, jnp.int32(offset))
            self.gcounter = GlobalCounter(
                value=self.gcounter.value,
                period=self.gcounter.period,
                last_ts=jnp.maximum(
                    self.gcounter.last_ts - jnp.int32(offset), 0),
                exists=self.gcounter.exists,
            )

    def peek_blocking(self, key: str) -> float:
        """Read-only availability estimate: never allocates a slot or
        writes device state (the ``GetAvailablePermits`` contract)."""
        with self._lock:
            shard = shard_of_key(key, self.n_shards)
            local = self.dirs[shard].lookup(key)
            if local is None:
                return float(np.floor(self.capacity))
            idx = shard * self.per_shard + local
            now = self.now_ticks_checked()
            # One jitted gather with the index as an OPERAND (a Python-int
            # subscript would bake the index into the computation — one
            # compile per distinct slot) and one packed readback.
            out = np.asarray(_peek_gather(self.state, jnp.int32(idx)))
        tokens = float(out[0])
        ts = int(np.float32(out[1]).view(np.int32))
        exists = bool(out[2])
        if not exists:
            return float(np.floor(self.capacity))
        refilled = min(self.capacity,
                       tokens + max(0, now - ts) * self.rate_per_tick)
        return float(np.floor(refilled))

    # -- decisions ---------------------------------------------------------
    def acquire_batch_blocking(
        self, requests: Sequence[tuple[str, int]],
        decay_rate_per_sec: float | None = None,
    ) -> list[AcquireResult]:
        """Decide a batch of ``(key, count)`` requests in one fused launch.
        Returns results in request order."""
        decay = (decay_rate_per_sec if decay_rate_per_sec is not None
                 else self.fill_rate_per_sec) / bm.TICKS_PER_SECOND
        with self._lock:
            return self._acquire_locked(requests, decay)

    def _acquire_locked(self, requests, decay) -> list[AcquireResult]:
        n = len(requests)
        keys = [k for k, _ in requests]
        counts = np.fromiter((c for _, c in requests), np.int64, n)
        shards, locs = self._resolve_batch(keys)
        jpos, shard_counts = self._group_by_shard(shards)
        b_local = _pad_size(int(shard_counts.max(initial=1)), floor=8)
        slots_np = np.full((self.n_shards, b_local), -1, np.int32)
        counts_np = np.zeros((self.n_shards, b_local), np.int32)
        valid_np = np.zeros((self.n_shards, b_local), bool)
        slots_np[shards, jpos] = locs
        counts_np[shards, jpos] = counts
        valid_np[shards, jpos] = True
        now = self.now_ticks_checked()
        self.state, granted, remaining, self.gcounter = self._step(
            self.state,
            jnp.asarray(slots_np), jnp.asarray(counts_np), jnp.asarray(valid_np),
            jnp.int32(now), jnp.float32(self.capacity),
            jnp.float32(self.rate_per_tick), self.gcounter, jnp.float32(decay),
        )
        g_np = np.asarray(granted)[shards, jpos]
        r_np = np.asarray(remaining)[shards, jpos]
        self.metrics.record_launch(self.n_shards * b_local, n)
        return [AcquireResult(bool(g), float(r)) for g, r in zip(g_np, r_np)]

    # -- bulk decisions (the mesh serving surface for acquire_many) --------
    def acquire_many_blocking(
        self, keys: Sequence[str], counts: Sequence[int], *,
        with_remaining: bool = True,
        decay_rate_per_sec: float | None = None,
    ) -> BulkAcquireResult:
        """Whole-array bulk acquire over the mesh: the shared
        ``_bulk_decide`` chunking (``[n_shards, K, B]`` layout) over the
        scanned two-level step — sharded acquire + one psum per scanned
        batch. Each dispatch decides up to ``n_shards × K × B`` requests
        in one fused launch."""
        decay = (decay_rate_per_sec if decay_rate_per_sec is not None
                 else self.fill_rate_per_sec) / bm.TICKS_PER_SECOND
        cap = jnp.float32(self.capacity)
        rate = jnp.float32(self.rate_per_tick)
        decay_dev = jnp.float32(decay)

        def launch_chunk(slots, counts_dev, valid, nows):
            self.state, granted, remaining, self.gcounter = self._scan_step(
                self.state, slots, counts_dev, valid, nows, cap, rate,
                self.gcounter, decay_dev,
            )
            return granted, remaining

        return self._bulk_decide(keys, counts, with_remaining, launch_chunk)

    @property
    def global_score(self) -> float:
        return float(np.asarray(self.gcounter.value))

    # -- checkpoint (SURVEY.md §5.4, parity with DeviceBucketStore) --------
    def snapshot(self) -> dict:
        """Pull the sharded state to host for a planned-restart checkpoint.
        Restorable into a store with the same mesh size and per-shard
        capacity; timestamps re-align via the captured ``now_ticks``."""
        with self._lock:
            return {
                "now_ticks": self.clock.now_ticks(),
                "n_shards": self.n_shards,
                "per_shard": self.per_shard,
                "capacity": self.capacity,
                "fill_rate_per_sec": self.fill_rate_per_sec,
                "directories": [d.to_dict() for d in self.dirs],
                "tokens": np.asarray(self.state.tokens),
                "last_ts": np.asarray(self.state.last_ts),
                "exists": np.asarray(self.state.exists),
                "gcounter": {
                    "value": np.asarray(self.gcounter.value),
                    "period": np.asarray(self.gcounter.period),
                    "last_ts": np.asarray(self.gcounter.last_ts),
                    "exists": np.asarray(self.gcounter.exists),
                },
            }

    def restore(self, snap: dict) -> None:
        with self._lock:
            if snap["n_shards"] != self.n_shards:
                # Re-sharding a snapshot is real key-redistribution work;
                # adopting a different per-shard WIDTH is not — the state
                # arrays and directories below are rebuilt wholesale from
                # the snapshot, so a store that grew (per-shard doubling)
                # before checkpointing restores into a fresh store fine.
                raise ValueError(
                    f"snapshot geometry {snap['n_shards']}x{snap['per_shard']}"
                    f" != store geometry {self.n_shards}x{self.per_shard} "
                    "(shard count must match)"
                )
            self.per_shard = int(snap["per_shard"])
            if (snap["capacity"] != self.capacity
                    or snap["fill_rate_per_sec"] != self.fill_rate_per_sec):
                # Token balances are only meaningful under the config they
                # accrued under (the single-chip store gets this for free —
                # its tables are keyed by (cap, rate)).
                raise ValueError(
                    f"snapshot config (cap={snap['capacity']}, "
                    f"rate={snap['fill_rate_per_sec']}) != store config "
                    f"(cap={self.capacity}, rate={self.fill_rate_per_sec})"
                )
            shift = int(self.clock.now_ticks()) - int(snap["now_ticks"])
            sharding = NamedSharding(self.mesh, P(SHARD_AXIS))
            self.state = K.BucketState(
                tokens=jax.device_put(jnp.asarray(snap["tokens"]), sharding),
                last_ts=jax.device_put(
                    jnp.asarray(_shift_ts(snap["last_ts"], shift)), sharding),
                exists=jax.device_put(jnp.asarray(snap["exists"]), sharding),
            )
            g = snap["gcounter"]
            g_ts = int(_shift_ts(g["last_ts"], shift))
            self.gcounter = jax.device_put(
                GlobalCounter(
                    value=jnp.asarray(g["value"], jnp.float32),
                    period=jnp.asarray(g["period"], jnp.float32),
                    last_ts=jnp.int32(g_ts),
                    exists=jnp.asarray(bool(g["exists"])),
                ),
                NamedSharding(self.mesh, P()),
            )
            for d, mapping in zip(self.dirs, snap["directories"]):
                d.load(mapping, self.per_shard)


def make_sharded_window_scan_step(mesh, *, interpolate: bool = True,
                                  handle_duplicates: bool = True):
    """Scanned key-sharded window step: K micro-batches per launch inside
    each shard's block (the window analogue of
    :func:`make_two_level_scan_step`, minus the global tier — windows have
    no cross-key state, so the hot path needs ZERO collectives).
    ``interpolate=False`` gives fixed-window semantics over the same state.

    Batch layout: ``slots_k/counts_k/valid_k: [n_shards, K, B_local]``
    (sharded on axis 0, shard-LOCAL slot ids), ``nows_k: i32[K]``
    replicated. Returns ``(new_state, granted, remaining)``.
    """
    state_specs = K.WindowState(P(SHARD_AXIS), P(SHARD_AXIS),
                                P(SHARD_AXIS), P(SHARD_AXIS))
    batch_spec = P(SHARD_AXIS, None, None)

    def block(state, slots, counts, valid, nows, limit, window_ticks):
        def body(st, xs):
            sl, ct, va, now = xs
            st, granted, remaining = K._window_acquire_core(
                st, sl, ct, va, now, limit, window_ticks,
                handle_duplicates=handle_duplicates,
                interpolate=interpolate,
            )
            return st, (granted, remaining)

        state, (granted, remaining) = jax.lax.scan(
            body, state, (slots[0], counts[0], valid[0], nows),
        )
        return state, granted[None], remaining[None]

    mapped = shard_map(
        block, mesh=mesh,
        in_specs=(state_specs, batch_spec, batch_spec, batch_spec,
                  P(), P(), P()),
        out_specs=(state_specs, batch_spec, batch_spec),
    )
    return jax.jit(mapped, donate_argnums=0)


class ShardedWindowStore(_ShardedKeyedTable):
    """Key-sharded sliding/fixed-window table over a mesh — BASELINE
    config 4 at mesh scale. Mirrors :class:`ShardedDeviceStore`'s host
    runtime (same directories, routing, growth, sweeps) over
    ``WindowState`` with the scanned window step; one homogeneous
    ``(limit, window, fixed?)`` config per instance, matching the
    single-chip ``_DeviceWindowTable``."""

    def __init__(self, mesh, limit: float, window_sec: float, *,
                 fixed: bool = False, per_shard_slots: int = 2**14,
                 clock: Clock | None = None,
                 handle_duplicates: bool = True,
                 rebase_threshold_ticks: int = _REBASE_THRESHOLD_TICKS) -> None:
        self.mesh = mesh
        self.n_shards = mesh.devices.size
        self.per_shard = per_shard_slots
        self.limit = float(limit)
        self.window_ticks = int(window_sec * bm.TICKS_PER_SECOND)
        self.fixed = fixed
        self.clock = clock or MonotonicClock()
        self.metrics = StoreMetrics()
        # See ShardedDeviceStore: a composing store coordinates rebases.
        self._rebase_threshold = rebase_threshold_ticks
        n_total = self.n_shards * per_shard_slots
        sharding = NamedSharding(mesh, P(SHARD_AXIS))
        init = K.init_window_state(n_total)
        self.state = K.WindowState(
            prev_count=jax.device_put(init.prev_count, sharding),
            curr_count=jax.device_put(init.curr_count, sharding),
            window_idx=jax.device_put(init.window_idx, sharding),
            exists=jax.device_put(init.exists, sharding),
        )
        self._scan_step = make_sharded_window_scan_step(
            mesh, interpolate=not fixed,
            handle_duplicates=handle_duplicates)
        self.dirs = [make_directory(per_shard_slots)
                     for _ in range(self.n_shards)]
        import threading

        self._lock = threading.RLock()

    # -- _ShardedKeyedTable hooks ------------------------------------------
    def _widen_state(self, old: int, new: int) -> None:
        sharding = NamedSharding(self.mesh, P(SHARD_AXIS))
        self.state = K.WindowState(
            prev_count=jax.device_put(
                self._widen_host(self.state.prev_count, old, new), sharding),
            curr_count=jax.device_put(
                self._widen_host(self.state.curr_count, old, new), sharding),
            window_idx=jax.device_put(
                self._widen_host(self.state.window_idx, old, new), sharding),
            exists=jax.device_put(
                self._widen_host(self.state.exists, old, new), sharding),
        )

    def _device_sweep(self) -> np.ndarray:
        self.state, freed = K.sweep_windows(
            self.state, jnp.int32(self.now_ticks_checked()),
            jnp.int32(self.window_ticks),
        )
        return np.asarray(freed)

    def force_rebase(self, offset_ticks: int) -> None:
        """Window tables rebase by whole windows (see
        ``kernels.rebase_window_epoch``) — called by the composing store's
        coordinated rebase, or by ``now_ticks_checked`` standalone."""
        with self._lock:
            self.state = K.rebase_window_epoch(
                self.state, jnp.int32(offset_ticks // self.window_ticks))

    # -- decisions ---------------------------------------------------------
    def acquire_many_blocking(
        self, keys: Sequence[str], counts: Sequence[int], *,
        with_remaining: bool = True,
    ) -> BulkAcquireResult:
        """Whole-array bulk window acquire over the mesh — the shared
        ``_bulk_decide`` chunking over the scanned window step."""
        limit_dev = jnp.float32(self.limit)
        window_dev = jnp.int32(self.window_ticks)

        def launch_chunk(slots, counts_dev, valid, nows):
            self.state, granted, remaining = self._scan_step(
                self.state, slots, counts_dev, valid, nows,
                limit_dev, window_dev,
            )
            return granted, remaining

        return self._bulk_decide(keys, counts, with_remaining, launch_chunk)

    def acquire_batch_blocking(
        self, requests: Sequence[tuple[str, int]],
    ) -> list[AcquireResult]:
        res = self.acquire_many_blocking(
            [k for k, _ in requests], [c for _, c in requests])
        return list(res)

    # -- checkpoint --------------------------------------------------------
    def snapshot(self) -> dict:
        with self._lock:
            return {
                "now_ticks": self.clock.now_ticks(),
                "n_shards": self.n_shards,
                "per_shard": self.per_shard,
                "limit": self.limit,
                "window_ticks": self.window_ticks,
                "fixed": self.fixed,
                "directories": [d.to_dict() for d in self.dirs],
                "prev_count": np.asarray(self.state.prev_count),
                "curr_count": np.asarray(self.state.curr_count),
                "window_idx": np.asarray(self.state.window_idx),
                "exists": np.asarray(self.state.exists),
            }

    def restore(self, snap: dict) -> None:
        with self._lock:
            if snap["n_shards"] != self.n_shards:
                raise ValueError(
                    f"snapshot geometry {snap['n_shards']}x"
                    f"{snap['per_shard']} != store geometry "
                    f"{self.n_shards}x{self.per_shard} (shard count must "
                    "match)")
            if (snap["limit"] != self.limit
                    or snap["window_ticks"] != self.window_ticks
                    or snap["fixed"] != self.fixed):
                raise ValueError("snapshot config != store config")
            self.per_shard = int(snap["per_shard"])
            # Window indices re-align by whole windows, with the SAME
            # signed clamp as the single-chip restore (_shift_ts): a large
            # negative shift must leave stale indices negative — i.e.
            # long-expired — not clip them to "current window", which
            # would enforce stale counts against fresh requests.
            shift_w = ((int(self.clock.now_ticks()) - int(snap["now_ticks"]))
                       // self.window_ticks)
            idx = _shift_ts(snap["window_idx"], shift_w)
            sharding = NamedSharding(self.mesh, P(SHARD_AXIS))
            self.state = K.WindowState(
                prev_count=jax.device_put(
                    jnp.asarray(snap["prev_count"]), sharding),
                curr_count=jax.device_put(
                    jnp.asarray(snap["curr_count"]), sharding),
                window_idx=jax.device_put(jnp.asarray(idx), sharding),
                exists=jax.device_put(jnp.asarray(snap["exists"]), sharding),
            )
            for d, mapping in zip(self.dirs, snap["directories"]):
                d.load(mapping, self.per_shard)
