"""Version compatibility for the shard_map-based kernels.

Two jax API gaps this package spans:

- ``shard_map`` moved from ``jax.experimental.shard_map`` to the top
  level in jax ≥ 0.5. The experimental version's replication checker
  also predates scan-carry "varying" types — the exact mismatch its own
  error message prescribes ``check_rep=False`` for — so the fallback
  disables it. Decisions are value-identical either way; only the static
  typing pass differs.
- ``jax.lax.pcast`` (typed-replication casts) only exists where that
  checker does; without it the cast is unnecessary.

One home for both shims so the sharded stores cannot drift apart.
"""

from __future__ import annotations

import jax

__all__ = ["shard_map", "pcast_varying"]

try:  # jax >= 0.5 exports shard_map at the top level
    from jax import shard_map
except ImportError:
    from functools import partial as _partial

    from jax.experimental.shard_map import shard_map as _shard_map_exp

    shard_map = _partial(_shard_map_exp, check_rep=False)


def pcast_varying(x, axis: str):
    """Mark a scan-carry init as per-shard ("varying" over ``axis``)
    where this jax has the typed-replication API; elsewhere (check_rep
    disabled above) the cast is unnecessary — the value is identical."""
    if hasattr(jax.lax, "pcast"):
        return jax.lax.pcast(x, (axis,), to="varying")
    return x
