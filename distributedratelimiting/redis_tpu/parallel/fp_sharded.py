"""Mesh-sharded device-resident directory: fingerprints over ICI.

Combines the two flagship designs: the key-sharded mesh tables of
:mod:`~.sharded_store` (keys never interact ⇒ zero hot-path collectives;
the only collective is the two-level global tier's psum — SURVEY.md §5.7/8)
with the device-resident fingerprint directory of
:mod:`~..ops.fp_directory` (in-kernel probe/insert; the host's per-batch
duty is one hashing pass).

Routing falls out for free: the fingerprint IS the route. Shard =
``fp_lo % n_shards`` — no second hash, no crc32 pass; every host routes
identically because every host hashes identically. Each shard holds an
independent fingerprint table + state slice in its own HBM and probes
shard-locally; TTL sweeps stay elementwise (the single-chip sweep
kernels applied to sharded arrays preserve the sharding with no
collectives), and growth is a per-shard device rehash (the route is
resize-invariant). Both table families ship: token buckets
(:class:`ShardedFpDeviceStore`, with the psum global tier) and
sliding/fixed windows (:class:`ShardedFpWindowStore`, collective-free).
"""

from __future__ import annotations

from typing import Sequence

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import NamedSharding, PartitionSpec as P
from distributedratelimiting.redis_tpu.parallel._shard_compat import (
    pcast_varying,
    shard_map,
)

from distributedratelimiting.redis_tpu.ops import bucket_math as bm
from distributedratelimiting.redis_tpu.ops import fp_directory as F
from distributedratelimiting.redis_tpu.ops import kernels as K
from distributedratelimiting.redis_tpu.parallel.mesh import SHARD_AXIS
from distributedratelimiting.redis_tpu.parallel.sharded_store import (
    GlobalCounter,
    global_tier_update,
    init_global_counter,
)
from distributedratelimiting.redis_tpu.runtime.clock import Clock, MonotonicClock
from distributedratelimiting.redis_tpu.utils.metrics import StoreMetrics
from distributedratelimiting.redis_tpu.runtime.store import (
    BulkAcquireResult,
    _grant_zero_probes,
    _rate_per_tick,
    _REBASE_MARGIN_TICKS,
    _REBASE_THRESHOLD_TICKS,
)

__all__ = ["make_sharded_fp_scan_step",
           "make_sharded_fp_window_scan_step",
           "make_sharded_fp_migrate_step",
           "ShardedFpDeviceStore", "ShardedFpWindowStore"]


def make_sharded_fp_migrate_step(mesh, state_cls=None, *,
                                 probe_window: int = 16,
                                 rounds: int = 4):
    """Jitted per-shard rehash chunk for mesh growth: each shard claims
    slots for a chunk of ITS old entries in its doubled slice and
    scatters the per-slot state columns across — no collectives (shard =
    ``fp_lo % n_shards`` is invariant under resize, so entries never move
    between shards; only within their shard's table). ``state_cls`` picks
    the table family (:class:`~..ops.kernels.BucketState` default, or
    ``WindowState``)."""
    state_cls = state_cls or K.BucketState
    nf = len(state_cls._fields)
    fp_spec = P(SHARD_AXIS, None)
    state_specs = state_cls(*([P(SHARD_AXIS)] * nf))
    kpair_spec = P(SHARD_AXIS, None, None)
    col_spec = P(SHARD_AXIS, None)

    def block(fp, state, kpair, *rest):
        cols, valid = rest[:-1], rest[-1]
        fp, state, placed = F._fp_migrate_core(
            fp, state, kpair[0], tuple(c[0] for c in cols), valid[0],
            probe_window=probe_window, rounds=rounds)
        return fp, state, placed[None]

    mapped = shard_map(
        block, mesh=mesh,
        in_specs=(fp_spec, state_specs, kpair_spec)
        + (col_spec,) * (nf + 1),
        out_specs=(fp_spec, state_specs, P(SHARD_AXIS)),
    )
    return jax.jit(mapped, donate_argnums=(0, 1))


def make_sharded_fp_window_scan_step(mesh, *, probe_window: int = 16,
                                     rounds: int = 4,
                                     handle_duplicates: bool = True,
                                     interpolate: bool = True):
    """Window-family analogue of :func:`make_sharded_fp_scan_step` —
    fused in-shard probe/insert + sliding/fixed-window decision, no
    collectives at all (windows have no cross-key state; the global tier
    is the approximate BUCKET algorithm's). ``interpolate=False`` =
    fixed-window semantics. Same one-operand/one-result transfer shape:
    takes ``fused_k u32[n_shards, K, B, 3]``, returns
    ``(fp, state, out f32[n_shards, K, 2, B])``."""
    fp_spec = P(SHARD_AXIS, None)
    state_specs = K.WindowState(P(SHARD_AXIS), P(SHARD_AXIS),
                                P(SHARD_AXIS), P(SHARD_AXIS))
    fused_spec = P(SHARD_AXIS, None, None, None)
    out_spec = P(SHARD_AXIS, None, None, None)

    def block(fp, state, fused, nows, limit, window_ticks):
        def body(carry, xs):
            f, st = carry
            fu, now = xs
            kp, ct, va = F._unpack_fp12(fu)
            f, st, granted, remaining, resolved = F._fp_window_core(
                f, st, kp, ct, va, now, limit, window_ticks,
                probe_window=probe_window, rounds=rounds,
                handle_duplicates=handle_duplicates,
                interpolate=interpolate)
            code = (granted.astype(jnp.float32)
                    + 2.0 * resolved.astype(jnp.float32))
            return (f, st), jnp.stack([code, remaining])

        (fp, state), out = jax.lax.scan(
            body, (fp, state), (fused[0], nows))
        return (fp, state, out[None])

    mapped = shard_map(
        block, mesh=mesh,
        in_specs=(fp_spec, state_specs, fused_spec, P(), P(), P()),
        out_specs=(fp_spec, state_specs, out_spec),
    )
    return jax.jit(mapped, donate_argnums=(0, 1))


def make_sharded_fp_scan_step(mesh, *, probe_window: int = 16,
                              rounds: int = 4,
                              handle_duplicates: bool = True,
                              sync_cadence: str = "batch"):
    """Jitted sharded fused resolve+acquire with the psum global tier.

    Layout: ``fp u32[N, 2]`` and bucket state sharded along keys
    (``P(SHARD_AXIS)``); batch ``fused_k u32[n_shards, K, B, 3]`` (the
    :func:`~..ops.fp_directory.pack_fp12` layout — ONE operand array per
    launch, shard-LOCAL fingerprints) sharded on axis 0; ``nows_k
    i32[K]`` replicated. Each scanned batch runs probe/insert + decision
    in-shard; the scalar psum feeding the replicated decaying global
    counter runs per scanned batch (``sync_cadence="batch"``) or once
    per launch over the accumulated consumed count (``"launch"`` — same
    deployable cadence trade as
    :func:`~.sharded_store.make_two_level_scan_step_deferred`; grants are
    bit-identical, counter staleness ≤ one launch's span).

    Returns ``(fp, state, out f32[n_shards, K, 2, B], gcounter)`` — the
    result rides ONE array per launch: row 0 encodes
    ``granted + 2·resolved`` exactly, row 1 is remaining.
    """
    if sync_cadence not in ("batch", "launch"):
        raise ValueError("sync_cadence must be 'batch' or 'launch'")
    fp_spec = P(SHARD_AXIS, None)
    state_specs = K.BucketState(P(SHARD_AXIS), P(SHARD_AXIS), P(SHARD_AXIS))
    gspecs = GlobalCounter(P(), P(), P(), P())
    fused_spec = P(SHARD_AXIS, None, None, None)
    out_spec = P(SHARD_AXIS, None, None, None)
    deferred = sync_cadence == "launch"

    def block(fp, state, fused, nows, capacity, rate, gcounter, decay_rate):
        def body(carry, xs):
            f, st, g, consumed_acc = carry
            fu, now = xs
            kp, ct, va = F._unpack_fp12(fu)
            f, st, granted, remaining, resolved = F._fp_acquire_core(
                f, st, kp, ct, va, now, capacity, rate,
                probe_window=probe_window, rounds=rounds,
                handle_duplicates=handle_duplicates)
            consumed = jnp.sum(jnp.asarray(ct, jnp.float32) * granted)
            if deferred:
                consumed_acc = consumed_acc + consumed
            else:
                total = jax.lax.psum(consumed, SHARD_AXIS)
                g = global_tier_update(g, total, now, decay_rate)
            code = (granted.astype(jnp.float32)
                    + 2.0 * resolved.astype(jnp.float32))
            return (f, st, g, consumed_acc), jnp.stack([code, remaining])

        # The accumulator is per-shard ("varying" over the mesh axis inside
        # shard_map); the initial zero must be cast to match.
        zero = pcast_varying(jnp.zeros((), jnp.float32), SHARD_AXIS)
        ((fp, state, gcounter, consumed_total), out) = jax.lax.scan(
            body, (fp, state, gcounter, zero), (fused[0], nows))
        if deferred:
            total = jax.lax.psum(consumed_total, SHARD_AXIS)  # ONE/launch
            gcounter = global_tier_update(gcounter, total, nows[-1],
                                          decay_rate)
        return (fp, state, out[None], gcounter)

    mapped = shard_map(
        block, mesh=mesh,
        in_specs=(fp_spec, state_specs, fused_spec, P(), P(), P(), gspecs,
                  P()),
        out_specs=(fp_spec, state_specs, out_spec, gspecs),
    )
    return jax.jit(mapped, donate_argnums=(0, 1, 6))


class ShardedFpDeviceStore:
    """Serving wrapper: bulk decisions against mesh-sharded fingerprint
    tables. One homogeneous config per instance (like
    :class:`~.sharded_store.ShardedDeviceStore`); the bulk path hashes
    once, routes by ``fp_lo % n_shards`` (vectorized numpy — the
    fingerprint is the route), groups order-stably per shard, and decides
    the whole call in scanned fused launches.

    Window pressure (a request whose shard-local probe window can't place
    it) denies the row, counts it in ``fp_unresolved``, and heals:
    sweep, then — if the sweep freed (almost) nothing — an all-shard
    doubling via the device-side per-shard rehash
    (:func:`make_sharded_fp_migrate_step`; entries never cross shards
    because the route ``fp_lo % n_shards`` is resize-invariant). Denied
    requests are not retried in-call (deny-and-heal, as on the single
    chip); the caller's next attempt lands in the relieved table. Set
    ``auto_grow=False`` to presize instead.
    """

    _BULK_MAX_K = 8

    def __init__(self, mesh, *, capacity: float, fill_rate_per_sec: float,
                 per_shard_slots: int = 1 << 16, batch: int = 512,
                 probe_window: int = 16, rounds: int = 4,
                 decay_rate_per_sec: float = 0.0,
                 clock: Clock | None = None,
                 auto_grow: bool = True,
                 sync_cadence: str = "batch",
                 rebase_threshold_ticks: int = _REBASE_THRESHOLD_TICKS
                 ) -> None:
        import threading

        if sync_cadence not in ("batch", "launch"):
            raise ValueError("sync_cadence must be 'batch' or 'launch'")
        # Global-tier psum cadence; irrelevant to the window subclass
        # (its step has no global tier) but accepted uniformly so the
        # mesh store can pass one config to every sharded tier.
        self.sync_cadence = sync_cadence
        self.mesh = mesh
        # Donated-state launches must serialize (the codebase-wide rule:
        # a second launch while one is in flight would reuse a deleted
        # buffer); sweeps and rebases take the same lock.
        self._lock = threading.RLock()
        self._rebase_threshold = rebase_threshold_ticks
        self.n_shards = mesh.devices.size
        if per_shard_slots < probe_window:
            # Same contract as _FpTable: the non-wrapping placement
            # (n - L + 1 modulus) is undefined below one window per
            # shard, and would silently wrap to garbage bases.
            raise ValueError(
                f"per_shard_slots ({per_shard_slots}) must be >= "
                f"probe_window ({probe_window})")
        self.capacity = float(capacity)
        self.rate_per_tick = _rate_per_tick(fill_rate_per_sec)
        self.decay_per_tick = _rate_per_tick(decay_rate_per_sec)
        self.per_shard_slots = per_shard_slots
        self.batch = batch
        self.probe_window = probe_window
        self.rounds = rounds
        self.clock = clock or MonotonicClock()
        self.auto_grow = auto_grow
        self.metrics = StoreMetrics()
        self.fp_unresolved = 0
        self.grows = 0
        self._peek_step = None

        fp_shard = NamedSharding(mesh, P(SHARD_AXIS, None))
        n = per_shard_slots * self.n_shards
        self.fp = jax.device_put(F.init_fp_table(n), fp_shard)
        self.state = self._fresh_sharded_state(n)
        self.gcounter = jax.device_put(
            init_global_counter(), NamedSharding(mesh, P()))
        self._step = self._make_step()

    # -- table-family hooks (the window subclass swaps these) --------------
    def _init_state_host(self, n: int):
        return K.init_bucket_state(n)

    def _fresh_sharded_state(self, n: int):
        shard = NamedSharding(self.mesh, P(SHARD_AXIS))
        st = self._init_state_host(n)
        return type(st)(*(jax.device_put(a, shard) for a in st))

    def _make_step(self):
        return make_sharded_fp_scan_step(
            self.mesh, probe_window=self.probe_window, rounds=self.rounds,
            sync_cadence=self.sync_cadence)

    def _launch(self, fused, nows):
        """One scanned fused dispatch (caller holds the lock); updates
        the table in place, returns the ``f32[S, K, 2, B]`` result
        handle (code row = granted + 2·resolved, row 1 = remaining)."""
        self.fp, self.state, out, self.gcounter = self._step(
            self.fp, self.state, jnp.asarray(fused), jnp.asarray(nows),
            jnp.float32(self.capacity), jnp.float32(self.rate_per_tick),
            self.gcounter, jnp.float32(self.decay_per_tick))
        return out

    @property
    def global_score(self) -> float:
        return float(np.asarray(self.gcounter.value))

    def now_ticks_checked(self) -> int:
        """Clock read with int32-overflow protection (the codebase-wide
        rule: rebase table + clock together before ~24 days of tick time
        can overflow the i32 ``now`` operand)."""
        now = self.clock.now_ticks()
        if now >= self._rebase_threshold:
            with self._lock:
                now = self.clock.now_ticks()
                if now >= self._rebase_threshold:
                    offset = now - _REBASE_MARGIN_TICKS
                    self.force_rebase(offset)
                    self.clock.rebase(offset)  # type: ignore[attr-defined]
                    now = self.clock.now_ticks()
        return now

    def force_rebase(self, offset: int) -> None:
        """Shift bucket + global-counter timestamps without touching the
        clock (fingerprints carry no time state)."""
        with self._lock:
            self.state = K.rebase_bucket_epoch(self.state, jnp.int32(offset))
            self.gcounter = GlobalCounter(
                value=self.gcounter.value, period=self.gcounter.period,
                last_ts=jnp.maximum(
                    self.gcounter.last_ts - jnp.int32(offset), 0),
                exists=self.gcounter.exists)

    def acquire_many_blocking(self, keys: Sequence[str],
                              counts: Sequence[int], *,
                              with_remaining: bool = True
                              ) -> BulkAcquireResult:
        from distributedratelimiting.redis_tpu.runtime.fp_store import (
            fingerprints,
        )

        n = len(keys)
        if n == 0:
            return BulkAcquireResult(
                np.zeros(0, bool),
                np.zeros(0, np.float32) if with_remaining else None)
        counts_np = np.asarray(counts, np.int64)
        fps = fingerprints(keys)  # KeyBlob-aware
        routes = fps[:, 0] % np.uint32(self.n_shards)
        order = np.argsort(routes, kind="stable")  # per-shard arrival order
        bounds = np.searchsorted(routes[order], np.arange(self.n_shards + 1))
        per_shard = np.diff(bounds)
        rows = int(per_shard.max())

        granted = np.zeros(n, bool)
        remaining = np.zeros(n, np.float32) if with_remaining else None
        b = self.batch
        pos = 0  # row offset within each shard's group, advanced per launch
        self._lock.acquire()  # donated-state launches serialize
        try:
            # Sampled under the lock: a concurrent epoch rebase must not
            # pair a pre-rebase `now` with post-rebase state.
            now = self.now_ticks_checked()
            call_pressure = 0
            # Per-DEVICE operand budget (each shard's slice rides its own
            # host→device link): scan depth shrinks before one device's
            # slice crosses the ~768KB-1MB transfer collapse — the
            # single-chip fp store's _BULK_BYTE_BUDGET discipline.
            max_k = self._BULK_MAX_K
            while max_k > 1 and max_k * b * 12 > 640 * 1024:
                max_k //= 2
            while pos < rows:
                k = 1
                need_rows = -(-(rows - pos) // b)
                while k < need_rows and k < max_k:
                    k *= 2
                take = k * b
                # ONE fused operand per launch (pack_fp12 layout: lo, hi,
                # count; 0xFFFFFFFF count ⇒ padding) and ONE result array
                # back — transfer-count discipline, same as the
                # single-chip fp bulk path.
                fused = np.zeros((self.n_shards, k * b, 3), np.uint32)
                fused[:, :, 2] = np.uint32(0xFFFFFFFF)
                sel = []  # (shard, local slice, global order slice)
                n_valid = 0
                for s in range(self.n_shards):
                    lo = bounds[s] + pos
                    hi = min(bounds[s + 1], lo + take)
                    m = max(0, hi - lo)
                    if m == 0:
                        continue
                    idx = order[lo:hi]
                    fused[s, :m] = F.pack_fp12(fps[idx], counts_np[idx])
                    n_valid += m
                    sel.append((s, m, idx))
                nows = np.full((k,), now, np.int32)
                out_d = self._launch(
                    fused.reshape(self.n_shards, k, b, 3), nows)
                self.metrics.record_launch(self.n_shards * k * b, n_valid)
                out_np = np.asarray(out_d)  # [S, K, 2, B]
                code = out_np[:, :, 0, :].reshape(
                    self.n_shards, -1).astype(np.int32)
                r_np = out_np[:, :, 1, :].reshape(self.n_shards, -1)
                for s, m, idx in sel:
                    granted[idx] = (code[s, :m] & 1).astype(bool)
                    if remaining is not None:
                        remaining[idx] = r_np[s, :m]
                    call_pressure += int((~((code[s, :m] & 2) > 0)).sum())
                pos += take
            self.fp_unresolved += call_pressure
            self.metrics.fp_unresolved += call_pressure
            if call_pressure and self.auto_grow:
                # Deny-and-heal (single-chip discipline, both clauses —
                # see _FpTable._relieve_pressure): sweep, then grow when
                # the sweep freed (almost) nothing OR the table is past
                # the growth threshold (live keys can saturate a probe
                # window at modest load factors).
                n_total = self.per_shard_slots * self.n_shards
                freed = self._sweep_locked()
                if (freed < max(1, n_total // 16)
                        or self._occupancy() >= 0.7 * n_total):
                    self._grow_locked()
        finally:
            self._lock.release()
        _grant_zero_probes(granted, counts_np)
        return BulkAcquireResult(granted, remaining)

    def _occupancy(self) -> int:
        # Caller holds the lock (donated buffers).
        return int(np.asarray((np.asarray(self.fp) != 0).any(-1).sum()))

    def _grow_locked(self) -> None:
        """All-shard doubling via the device-side per-shard rehash: each
        shard's entries re-place within the shard's doubled slice (the
        route is resize-invariant, so nothing crosses shards)."""
        old_fp = np.asarray(self.fp).reshape(self.n_shards, -1, 2)
        olds = [np.asarray(a).reshape(self.n_shards, -1)
                for a in self.state]
        self._rehash_locked(old_fp, olds, old_fp.shape[1] * 2)
        self.grows += 1
        self.metrics.pregrows += 1

    def _rehash_locked(self, old_fp: np.ndarray, olds: list,
                       per_start: int,
                       probe_window: int | None = None) -> None:
        """Re-place every shard's live entries into fresh sharded tables
        (``old_fp`` is ``[S, per_old, 2]``, ``olds`` state columns in
        field order, same shape) — the shared driver behind growth and
        legacy-snapshot adoption. Caller holds the lock; nothing mutates
        until placement succeeds. ``probe_window`` lets snapshot adoption
        place under the snapshot's geometry before the caller commits it.

        A shard entry whose whole window fills with other entries is
        unplaceable at a given size — a density accident; double and
        retry (load halves per attempt, so this converges), with a cap
        so a pathological set still fails loudly. Same discipline as
        _FpTable._rehash."""
        pw = self.probe_window if probe_window is None else probe_window
        entries = [np.nonzero((old_fp[s] != 0).any(-1))[0]
                   for s in range(self.n_shards)]
        migrate = make_sharded_fp_migrate_step(
            self.mesh, type(self.state), probe_window=pw,
            rounds=self.rounds)
        b = self.batch
        per_new = per_start  # committed only after the rehash
        leftover = 0
        for _attempt in range(4):
            n = per_new * self.n_shards
            fp_shard = NamedSharding(self.mesh, P(SHARD_AXIS, None))
            fp = jax.device_put(F.init_fp_table(n), fp_shard)
            state = self._fresh_sharded_state(n)
            pending = entries
            stuck = False
            # Unplaced entries (bounded insert rounds under in-chunk
            # window contention) retry in later passes; zero-progress ⇒
            # some window is genuinely full at this size.
            while any(len(p) for p in pending):
                next_pending = [[] for _ in range(self.n_shards)]
                rows = max(len(p) for p in pending)
                pos = 0
                while pos < rows:
                    kpair = np.zeros((self.n_shards, b, 2), np.uint32)
                    cols = [np.zeros((self.n_shards, b), a.dtype)
                            for a in olds]
                    valid = np.zeros((self.n_shards, b), bool)
                    chunk_idx = [None] * self.n_shards
                    for s in range(self.n_shards):
                        idx = pending[s][pos:pos + b]
                        m = len(idx)
                        if m == 0:
                            continue
                        chunk_idx[s] = idx
                        kpair[s, :m] = old_fp[s][idx]
                        for c, a in zip(cols, olds):
                            c[s, :m] = a[s][idx]
                        valid[s, :m] = True
                    fp, state, placed = migrate(
                        fp, state, jnp.asarray(kpair),
                        *(jnp.asarray(c) for c in cols), jnp.asarray(valid))
                    placed_np = np.asarray(placed).reshape(self.n_shards, -1)
                    for s in range(self.n_shards):
                        idx = chunk_idx[s]
                        if idx is None:
                            continue
                        miss = ~placed_np[s, :len(idx)]
                        if miss.any():
                            next_pending[s].append(idx[miss])
                    pos += b
                new_pending = [
                    np.concatenate(p) if p else np.zeros((0,), np.int64)
                    for p in next_pending]
                if (sum(len(p) for p in new_pending)
                        >= sum(len(p) for p in pending)):
                    stuck = True
                    leftover = sum(len(p) for p in new_pending)
                    break
                pending = new_pending
            if not stuck:
                self.fp, self.state = fp, state
                self.per_shard_slots = per_new
                return
            per_new *= 2
        raise RuntimeError(
            f"sharded fingerprint rehash cannot place {leftover} entries "
            f"even at {per_new // 2} slots/shard")

    def sweep(self) -> int:
        """Elementwise TTL sweep across every shard — the single-chip
        kernel applied to the sharded arrays (sharding is preserved, no
        collectives). Returns slots freed."""
        with self._lock:
            return self._sweep_locked()

    def _sweep_locked(self) -> int:
        # `now` FIRST: now_ticks_checked can fire an epoch rebase that
        # donates-and-replaces self.state — arguments already evaluated
        # would then reference deleted (or stale pre-rebase) buffers.
        now = self.now_ticks_checked()
        self.fp, self.state, n_freed = F.fp_sweep_expired(
            self.fp, self.state, jnp.int32(now),
            jnp.float32(self.capacity), jnp.float32(self.rate_per_tick))
        freed = int(np.asarray(n_freed))
        self.metrics.sweeps += 1
        self.metrics.slots_evicted += freed
        return freed

    # -- per-request flush surface (the mesh front-end's batcher) ----------
    def acquire_batch_blocking(
            self, requests: "Sequence[tuple[str, int]]"
    ) -> "list[AcquireResult]":
        """Decide a batch of ``(key, count)`` requests in one bulk call;
        results in request order (same in-call duplicate conservatism as
        :meth:`acquire_many_blocking`)."""
        return list(self.acquire_many_blocking(
            [k for k, _ in requests], [c for _, c in requests]))

    def peek_blocking(self, key: str) -> float:
        """Read-only availability estimate — shard-local lookup WITHOUT
        insert (peeking at an unseen key must not claim a slot)."""
        from distributedratelimiting.redis_tpu.runtime.fp_store import (
            fingerprints,
        )

        if self._peek_step is None:
            self._peek_step = _make_sharded_fp_peek_step(
                self.mesh, probe_window=self.probe_window)
        fp1 = fingerprints([key])[0]
        shard = int(fp1[0] % np.uint32(self.n_shards))
        kpair = np.zeros((self.n_shards, 8, 2), np.uint32)
        valid = np.zeros((self.n_shards, 8), bool)
        kpair[shard, 0] = fp1
        valid[shard, 0] = True
        with self._lock:
            now = self.now_ticks_checked()
            est = self._peek_step(
                self.fp, self.state, jnp.asarray(kpair),
                jnp.asarray(valid), jnp.int32(now),
                jnp.float32(self.capacity), jnp.float32(self.rate_per_tick))
        return float(np.asarray(est)[shard, 0])

    # -- checkpoint --------------------------------------------------------
    def _config_snap(self) -> dict:
        return {"capacity": self.capacity,
                "rate_per_tick": self.rate_per_tick}

    def _check_config_snap(self, snap: dict) -> None:
        want = self._config_snap()
        got = {k: snap.get(k) for k in want}
        if got != want:
            raise ValueError(
                f"snapshot config {got} != store config {want} — a "
                "fingerprint snapshot restores only into a same-config "
                "store")

    def snapshot(self) -> dict:
        with self._lock:
            snap = {
                "now_ticks": self.clock.now_ticks(),
                "n_shards": self.n_shards,
                "per_shard": self.per_shard_slots,
                "probe_window": self.probe_window,
                "placement": F.PLACEMENT_VERSION,
                "fp": np.asarray(self.fp),
                "gcounter": {
                    "value": float(np.asarray(self.gcounter.value)),
                    "period": float(np.asarray(self.gcounter.period)),
                    "last_ts": int(np.asarray(self.gcounter.last_ts)),
                    "exists": bool(np.asarray(self.gcounter.exists)),
                },
            }
            snap.update(self._config_snap())
            for f in type(self.state)._fields:
                snap[f] = np.asarray(getattr(self.state, f))
            return snap

    def restore(self, snap: dict) -> None:
        from distributedratelimiting.redis_tpu.runtime.store import _shift_ts

        with self._lock:
            if "fp" not in snap:
                raise ValueError(
                    "snapshot's tables use the host key directory — "
                    "restore into the host-directory sharded store")
            if snap["n_shards"] != self.n_shards:
                raise ValueError(
                    f"snapshot shard count {snap['n_shards']} != "
                    f"store {self.n_shards} (fingerprints route by "
                    "fp % n_shards — re-sharding is key redistribution)")
            self._check_config_snap(snap)
            shift = int(self.clock.now_ticks()) - int(snap["now_ticks"])
            new_pw = int(snap.get("probe_window", self.probe_window))
            cls = type(self.state)
            raw_cols = []
            for f in cls._fields:
                a = snap[f]
                if f == "last_ts":
                    a = _shift_ts(a, shift)
                elif f == "window_idx":
                    a = _shift_ts(a, shift // self.window_ticks)
                raw_cols.append(np.asarray(a))
            # Install the tables FIRST — the legacy re-place below can
            # raise, and config committed before a failed install would
            # leave a half-restored store whose probe geometry no longer
            # matches its live tables.
            if snap.get("placement") != F.PLACEMENT_VERSION:
                # Pre-v2 snapshots placed entries with the wrapping h % n
                # window; verbatim install under the non-wrapping
                # placement would orphan nearly every key. Re-place
                # through the migrate kernel (shard routing is
                # placement-invariant, so entries stay in their shards).
                self._rehash_locked(
                    np.asarray(snap["fp"]).reshape(self.n_shards, -1, 2),
                    [c.reshape(self.n_shards, -1) for c in raw_cols],
                    int(snap["per_shard"]), probe_window=new_pw)
            else:
                fp_shard = NamedSharding(self.mesh, P(SHARD_AXIS, None))
                shard = NamedSharding(self.mesh, P(SHARD_AXIS))
                self.fp = jax.device_put(jnp.asarray(snap["fp"]), fp_shard)
                self.state = cls(*(jax.device_put(jnp.asarray(a), shard)
                                   for a in raw_cols))
                self.per_shard_slots = int(snap["per_shard"])
            if new_pw != self.probe_window:
                # The jitted steps bake probe_window in at construction;
                # entries placed deep in a wider window would be
                # invisible to a narrower scan.
                self.probe_window = new_pw
                self._step = self._make_step()
                self._peek_step = None
            g = snap.get("gcounter")
            if g is not None:
                self.gcounter = jax.device_put(GlobalCounter(
                    value=jnp.float32(g["value"]),
                    period=jnp.float32(g["period"]),
                    last_ts=jnp.int32(max(0, g["last_ts"] + shift)),
                    exists=jnp.asarray(g["exists"])),
                    NamedSharding(self.mesh, P()))


def _make_sharded_fp_peek_step(mesh, *, probe_window: int):
    """Shard-local read-only lookup: the key's fingerprint sits in ITS
    shard's batch row; every shard probes its own slice (wrong-shard rows
    are invalid ⇒ 0)."""
    fp_spec = P(SHARD_AXIS, None)
    state_specs = K.BucketState(P(SHARD_AXIS), P(SHARD_AXIS), P(SHARD_AXIS))
    kpair_spec = P(SHARD_AXIS, None, None)
    row_spec = P(SHARD_AXIS, None)

    def block(fp, state, kpair, valid, now, capacity, rate):
        est = F.fp_peek_batch(fp, state, kpair[0], valid[0], now, capacity,
                              rate, probe_window=probe_window)
        return est[None]

    mapped = shard_map(
        block, mesh=mesh,
        in_specs=(fp_spec, state_specs, kpair_spec, row_spec, P(), P(), P()),
        out_specs=row_spec,
    )
    return jax.jit(mapped)


class ShardedFpWindowStore(ShardedFpDeviceStore):
    """Sliding/fixed-window tables with the device-resident directory
    over a mesh — the window member of the fp family's matrix (single
    chip × mesh, buckets × windows). No collectives at all: windows have
    no cross-key state, and the global tier belongs to the approximate
    bucket algorithm. Everything else (route-by-fingerprint bulk path,
    pressure heal, per-shard rehash growth, epoch rebase) is inherited.
    """

    def __init__(self, mesh, *, limit: float, window_sec: float,
                 fixed: bool = False, **kw) -> None:
        self.limit = float(limit)
        self.window_ticks = int(
            window_sec * bm.TICKS_PER_SECOND)
        self.fixed = fixed
        # capacity/fill-rate are bucket-family operands; unused here (the
        # base stores them, the window step never reads them).
        super().__init__(mesh, capacity=limit, fill_rate_per_sec=0.0, **kw)

    def _init_state_host(self, n: int):
        return K.init_window_state(n)

    def _make_step(self):
        return make_sharded_fp_window_scan_step(
            self.mesh, probe_window=self.probe_window, rounds=self.rounds,
            interpolate=not self.fixed)

    def _launch(self, fused, nows):
        self.fp, self.state, out = self._step(
            self.fp, self.state, jnp.asarray(fused), jnp.asarray(nows),
            jnp.float32(self.limit), jnp.int32(self.window_ticks))
        return out

    def peek_blocking(self, key: str) -> float:
        raise NotImplementedError(
            "window tables expose no peek (matching the single-chip "
            "window tiers)")

    def _config_snap(self) -> dict:
        return {"limit": self.limit, "window_ticks": self.window_ticks,
                "fixed": self.fixed}

    def _sweep_locked(self) -> int:
        now = self.now_ticks_checked()  # before the args (rebase hazard)
        self.fp, self.state, n_freed = F.fp_sweep_windows(
            self.fp, self.state, jnp.int32(now),
            jnp.int32(self.window_ticks))
        freed = int(np.asarray(n_freed))
        self.metrics.sweeps += 1
        self.metrics.slots_evicted += freed
        return freed

    def force_rebase(self, offset: int) -> None:
        with self._lock:
            self.state = K.rebase_window_epoch(
                self.state, jnp.int32(offset // self.window_ticks))
