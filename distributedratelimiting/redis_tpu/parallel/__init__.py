"""Multi-chip scale-out: mesh helpers, key-sharded state, psum global tier.

The reference's entire "distributed backend" is a client-server star over
TCP — every client talks to one Redis, never to each other (SURVEY.md §5.8).
On TPU the star inverts into a mesh: bucket state shards over devices along
the key axis (keys never interact → zero cross-chip traffic on the hot
path, §5.7), and the only collective is the two-level approximate
algorithm's global tier — a ``lax.psum`` of per-chip consumed counts over
ICI, replacing the per-period Redis round-trip.
"""
