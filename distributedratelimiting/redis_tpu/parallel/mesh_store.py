"""MeshBucketStore — the full store interface over a device mesh.

This is the piece that joins the two deployment shapes (docs/DESIGN.md §6):
:class:`~.server.BucketStoreServer` can front a whole TPU pod slice, so N
remote client hosts (the reference's star topology) share bucket state
sharded across every chip (the mesh-native scale-out). Request flow::

    client hosts ──TCP──▶ server ──micro-batch──▶ two-level fused step
                                                   (sharded acquire + psum)

Routing of the abstract surface:

- **Token buckets** — the scale-out path: one :class:`ShardedDeviceStore`
  per ``(capacity, fill_rate)`` config (mirroring ``DeviceBucketStore``'s
  one homogeneous table per config), each micro-batched so concurrent
  acquires across all keys coalesce into single fused launches.
- **Sliding/fixed windows** — also key-sharded
  (:class:`ShardedWindowStore`, one per ``(limit, window, fixed?)``
  config): window keys scale with the keyed workload exactly like bucket
  keys (BASELINE config 4 is 10M window keys), and the hot path needs no
  collectives either.
- **Decaying counters, semaphores** — delegated to an inner single-device
  :class:`DeviceBucketStore`: these tables are small (one row per
  *limiter*, not per key) and their traffic is per-period, not
  per-request, so sharding them would buy nothing and cost a collective.
  They are not capacity-capped: the aux tables grow by doubling past
  ``aux_slots`` (tested >16K keys each,
  ``tests/test_mesh_store.py::TestMeshAuxCardinality``; posture
  documented in docs/OPERATIONS.md §3).

Both layers share one clock: a single time authority for every table
(invariant 1), one rebase path, one snapshot epoch.
"""

from __future__ import annotations

import asyncio
import threading
from typing import Sequence

import jax

from distributedratelimiting.redis_tpu.parallel.mesh import create_mesh
from distributedratelimiting.redis_tpu.parallel.sharded_store import (
    ShardedDeviceStore,
    ShardedWindowStore,
)
from distributedratelimiting.redis_tpu.runtime.batcher import MicroBatcher
from distributedratelimiting.redis_tpu.runtime.clock import Clock, MonotonicClock
from distributedratelimiting.redis_tpu.runtime.store import (
    AcquireResult,
    BucketStore,
    DeviceBucketStore,
    SyncResult,
    _AcquireReq,
    _REBASE_MARGIN_TICKS,
    _REBASE_THRESHOLD_TICKS,
    start_periodic_sweeper,
)

__all__ = ["MeshBucketStore"]

#: Sub-stores never self-rebase (the mesh store coordinates): any value
#: the int32 tick clock can never reach.
_NEVER_REBASE = 1 << 62


class _CombinedMetrics:
    """Snapshot view merging the aux store's metrics with every sharded
    bucket tier's (the OP_STATS surface for a mesh-backed server)."""

    def __init__(self, store: "MeshBucketStore") -> None:
        self._store = store

    def snapshot(self) -> dict:
        out = self._store._aux.metrics.snapshot()
        with self._store._registry_lock:
            shards = {
                f"bucket[cap={cap},rate={rate}]": s.metrics.snapshot()
                for (cap, rate), s in self._store._shards.items()
            }
            shards.update({
                f"window[limit={limit},wticks={wt},fixed={fx}]":
                    s.metrics.snapshot()
                for (limit, wt, fx), s in self._store._windows.items()
            })
        for sub in shards.values():
            for k in ("launches", "rows_processed", "rows_valid",
                      "sweeps", "slots_evicted"):
                out[k] = out.get(k, 0) + sub[k]
        out["batch_occupancy"] = (
            out["rows_valid"] / out["rows_processed"]
            if out.get("rows_processed") else 0.0
        )
        out["tiers"] = shards
        return out


class MeshBucketStore(BucketStore):
    """``BucketStore`` whose token-bucket tier is key-sharded over a mesh."""

    def __init__(
        self,
        mesh=None,
        *,
        per_shard_slots: int = 2**14,
        clock: Clock | None = None,
        max_batch: int = 4096,
        max_delay_s: float = 200e-6,
        max_inflight: int = 8,
        aux_slots: int = 2**14,
        directory: str = "host",
        sync_cadence: str = "batch",
    ) -> None:
        if directory not in ("host", "fp"):
            raise ValueError("directory must be 'host' or 'fp'")
        if sync_cadence not in ("batch", "launch"):
            raise ValueError("sync_cadence must be 'batch' or 'launch'")
        # Global-tier psum cadence for the sharded bucket tiers: "batch"
        # (K collectives per scanned launch, counter staleness ≤ one
        # batch) or "launch" (ONE collective per launch, staleness ≤ one
        # launch's span, ~+22% bulk throughput measured —
        # docs/OPERATIONS.md §3, benchmarks/RESULTS.md "Psum cadence").
        self.sync_cadence = sync_cadence
        # Key-directory home for the sharded keyed tiers (buckets +
        # windows): "host" = per-shard native C tables; "fp" = the
        # device-resident fingerprint directory (docs/OPERATIONS.md §2).
        # Aux tiers (counters/semaphores) keep the host directory either
        # way — their cardinality is per-limiter.
        self.directory = directory
        self.mesh = mesh if mesh is not None else create_mesh(
            len(jax.devices()))
        self.clock = clock or MonotonicClock()
        self.per_shard_slots = per_shard_slots
        self.max_batch = max_batch
        self.max_delay_s = max_delay_s
        self.max_inflight = max_inflight
        # Small per-limiter tables (windows/counters/semas) live on one
        # device; bucket tables are NOT created here (n_slots minimal).
        # Sub-stores never self-rebase — see _maybe_rebase_all.
        self._aux = DeviceBucketStore(
            n_slots=64, counter_slots=aux_slots, clock=self.clock,
            max_batch=max_batch, max_delay_s=max_delay_s,
            max_inflight=max_inflight, rebase_threshold_ticks=_NEVER_REBASE,
        )
        self._shards: dict[tuple[float, float], ShardedDeviceStore] = {}
        self._batchers: dict[tuple[float, float],
                             MicroBatcher[_AcquireReq, AcquireResult]] = {}
        self._windows: dict[tuple[float, int, bool], ShardedWindowStore] = {}
        self._wbatchers: dict[tuple[float, int, bool],
                              MicroBatcher[_AcquireReq, AcquireResult]] = {}
        self._registry_lock = threading.RLock()
        self._connected = False
        self._connect_gate = asyncio.Lock()
        self._sweeper_task: asyncio.Task | None = None

    @property
    def metrics(self) -> _CombinedMetrics:
        return _CombinedMetrics(self)

    # -- coordinated epoch rebase ------------------------------------------
    def _maybe_rebase_all(self) -> None:
        """ONE rebase for every table sharing the clock. Sub-stores have
        their own thresholds disabled; if any rebased independently, its
        siblings' timestamps would strand in the old epoch and regression
        clamps would freeze their refill for days.

        Stop-the-world: ALL sub-store locks are held (fixed order: aux
        first, then shards by config key) across the table shifts AND the
        clock rebase, so no concurrent op can stamp a pre-rebase ``now``
        into an already-shifted table. Deadlock-free: every other code
        path takes at most ONE sub-store lock."""
        if self.clock.now_ticks() < _REBASE_THRESHOLD_TICKS:
            return
        from contextlib import ExitStack

        with self._registry_lock:
            now = self.clock.now_ticks()
            if now < _REBASE_THRESHOLD_TICKS:
                return
            offset = now - _REBASE_MARGIN_TICKS
            with ExitStack() as stack:
                stack.enter_context(self._aux._lock)
                for key in sorted(self._shards):
                    stack.enter_context(self._shards[key]._lock)
                for key in sorted(self._windows):
                    stack.enter_context(self._windows[key]._lock)
                self._aux.force_rebase(offset)
                for store in self._shards.values():
                    store.force_rebase(offset)
                for wstore in self._windows.values():
                    wstore.force_rebase(offset)
                self.clock.rebase(offset)  # type: ignore[attr-defined]

    # -- lifecycle ---------------------------------------------------------
    async def connect(self) -> None:
        if self._connected:
            return
        async with self._connect_gate:
            if self._connected:
                return
            await self._aux.connect()
            self._connected = True

    async def aclose(self) -> None:
        if self._sweeper_task is not None:
            self._sweeper_task.cancel()
            try:
                await self._sweeper_task
            except (asyncio.CancelledError, Exception):
                pass
            self._sweeper_task = None
        with self._registry_lock:
            batchers = (list(self._batchers.values())
                        + list(self._wbatchers.values()))
        for b in batchers:
            await b.aclose()
        await self._aux.aclose()

    # -- sharded token-bucket tier -----------------------------------------
    def _sharded(self, capacity: float,
                 fill_rate_per_sec: float) -> ShardedDeviceStore:
        key = (float(capacity), float(fill_rate_per_sec))
        with self._registry_lock:  # event loop + blocking threads race here
            store = self._shards.get(key)
            if store is None:
                if self.directory == "fp":
                    from distributedratelimiting.redis_tpu.parallel.fp_sharded import (
                        ShardedFpDeviceStore,
                    )

                    cls = ShardedFpDeviceStore
                else:
                    cls = ShardedDeviceStore
                store = cls(
                    self.mesh, capacity=capacity,
                    fill_rate_per_sec=fill_rate_per_sec,
                    per_shard_slots=self.per_shard_slots, clock=self.clock,
                    sync_cadence=self.sync_cadence,
                    rebase_threshold_ticks=_NEVER_REBASE,
                )
                self._shards[key] = store
            return store

    def _get_batcher(self, cache: dict, key, store_getter
                     ) -> MicroBatcher[_AcquireReq, AcquireResult]:
        """Shared batcher factory for the sharded tiers (buckets and
        windows): per-config MicroBatcher whose flush runs the tier's
        fused launch + readback off-loop so the event loop keeps
        accumulating the next flush."""
        with self._registry_lock:
            batcher = cache.get(key)
            if batcher is None:
                store = store_getter()

                async def flush(reqs: Sequence[_AcquireReq],
                                _s=store) -> list[AcquireResult]:
                    loop = asyncio.get_running_loop()
                    return await loop.run_in_executor(
                        None, _s.acquire_batch_blocking,
                        [(r.key, r.count) for r in reqs],
                    )

                batcher = MicroBatcher(
                    flush, max_batch=self.max_batch,
                    max_delay_s=self.max_delay_s,
                    max_inflight=self.max_inflight,
                )
                cache[key] = batcher
            return batcher

    def _batcher(self, capacity: float, fill_rate_per_sec: float
                 ) -> MicroBatcher[_AcquireReq, AcquireResult]:
        key = (float(capacity), float(fill_rate_per_sec))
        return self._get_batcher(
            self._batchers, key,
            lambda: self._sharded(capacity, fill_rate_per_sec))

    async def acquire(self, key: str, count: int, capacity: float,
                      fill_rate_per_sec: float) -> AcquireResult:
        await self.connect()
        self._maybe_rebase_all()
        return await self._batcher(capacity, fill_rate_per_sec).submit(
            _AcquireReq(key, count))

    def acquire_blocking(self, key: str, count: int, capacity: float,
                         fill_rate_per_sec: float) -> AcquireResult:
        self._maybe_rebase_all()
        return self._sharded(capacity, fill_rate_per_sec
                             ).acquire_batch_blocking([(key, count)])[0]

    # -- sharded window tier -----------------------------------------------
    def _sharded_window(self, limit: float, window_sec: float,
                        fixed: bool) -> ShardedWindowStore:
        from distributedratelimiting.redis_tpu.ops import bucket_math as bm

        key = (float(limit), int(window_sec * bm.TICKS_PER_SECOND), fixed)
        with self._registry_lock:
            store = self._windows.get(key)
            if store is None:
                if self.directory == "fp":
                    from distributedratelimiting.redis_tpu.parallel.fp_sharded import (
                        ShardedFpWindowStore,
                    )

                    wcls = ShardedFpWindowStore
                else:
                    wcls = ShardedWindowStore
                store = wcls(
                    self.mesh, limit=limit, window_sec=window_sec,
                    fixed=fixed, per_shard_slots=self.per_shard_slots,
                    clock=self.clock,
                    rebase_threshold_ticks=_NEVER_REBASE,
                )
                self._windows[key] = store
            return store

    def _wbatcher(self, limit: float, window_sec: float, fixed: bool
                  ) -> MicroBatcher[_AcquireReq, AcquireResult]:
        from distributedratelimiting.redis_tpu.ops import bucket_math as bm

        key = (float(limit), int(window_sec * bm.TICKS_PER_SECOND), fixed)
        return self._get_batcher(
            self._wbatchers, key,
            lambda: self._sharded_window(limit, window_sec, fixed))

    async def acquire_many(self, keys, counts, capacity: float,
                           fill_rate_per_sec: float, *,
                           with_remaining: bool = True):
        """Bulk path over the mesh: the whole array rides the scanned
        two-level step (sharded acquire + psum per scanned batch) — no
        per-request futures. This is what a BucketStoreServer fronting a
        pod slice serves OP_ACQUIRE_MANY with."""
        await self.connect()
        self._maybe_rebase_all()
        store = self._sharded(capacity, fill_rate_per_sec)
        loop = asyncio.get_running_loop()
        # The fused launches + readback block; run off-loop so the event
        # loop keeps serving other connections' traffic.
        return await loop.run_in_executor(
            None, lambda: store.acquire_many_blocking(
                keys, counts, with_remaining=with_remaining))

    def acquire_many_blocking(self, keys, counts, capacity: float,
                              fill_rate_per_sec: float, *,
                              with_remaining: bool = True):
        self._maybe_rebase_all()
        return self._sharded(capacity, fill_rate_per_sec).acquire_many_blocking(
            keys, counts, with_remaining=with_remaining)

    def peek_blocking(self, key: str, capacity: float,
                      fill_rate_per_sec: float) -> float:
        # Read-only: never allocates a slot or writes device state.
        self._maybe_rebase_all()
        return self._sharded(capacity, fill_rate_per_sec).peek_blocking(key)

    # -- delegated small tables --------------------------------------------
    # Every delegated path checks the coordinated rebase too: an aux-only
    # workload (windows/counters/semaphores, no bucket acquires) must not
    # run into int32 tick overflow just because the bucket tier is idle.
    async def sync_counter(self, key, local_count, decay_rate_per_sec):
        self._maybe_rebase_all()
        return await self._aux.sync_counter(key, local_count,
                                            decay_rate_per_sec)

    def sync_counter_blocking(self, key, local_count, decay_rate_per_sec):
        self._maybe_rebase_all()
        return self._aux.sync_counter_blocking(key, local_count,
                                               decay_rate_per_sec)

    # -- key-sharded windows (BASELINE config 4 at mesh scale) --------------
    async def window_acquire(self, key, count, limit, window_sec):
        await self.connect()
        self._maybe_rebase_all()
        return await self._wbatcher(limit, window_sec, False).submit(
            _AcquireReq(key, count))

    def window_acquire_blocking(self, key, count, limit, window_sec):
        self._maybe_rebase_all()
        return self._sharded_window(limit, window_sec, False
                                    ).acquire_batch_blocking([(key, count)])[0]

    async def fixed_window_acquire(self, key, count, limit, window_sec):
        await self.connect()
        self._maybe_rebase_all()
        return await self._wbatcher(limit, window_sec, True).submit(
            _AcquireReq(key, count))

    def fixed_window_acquire_blocking(self, key, count, limit, window_sec):
        self._maybe_rebase_all()
        return self._sharded_window(limit, window_sec, True
                                    ).acquire_batch_blocking([(key, count)])[0]

    async def window_acquire_many(self, keys, counts, limit, window_sec, *,
                                  fixed: bool = False,
                                  with_remaining: bool = True):
        await self.connect()
        self._maybe_rebase_all()
        store = self._sharded_window(limit, window_sec, fixed)
        loop = asyncio.get_running_loop()
        return await loop.run_in_executor(
            None, lambda: store.acquire_many_blocking(
                keys, counts, with_remaining=with_remaining))

    def window_acquire_many_blocking(self, keys, counts, limit, window_sec,
                                     *, fixed: bool = False,
                                     with_remaining: bool = True):
        self._maybe_rebase_all()
        return self._sharded_window(limit, window_sec, fixed
                                    ).acquire_many_blocking(
            keys, counts, with_remaining=with_remaining)

    async def concurrency_acquire(self, key, count, limit):
        self._maybe_rebase_all()
        return await self._aux.concurrency_acquire(key, count, limit)

    def concurrency_acquire_blocking(self, key, count, limit):
        self._maybe_rebase_all()
        return self._aux.concurrency_acquire_blocking(key, count, limit)

    async def concurrency_release(self, key, count):
        self._maybe_rebase_all()
        await self._aux.concurrency_release(key, count)

    def concurrency_release_blocking(self, key, count):
        self._maybe_rebase_all()
        self._aux.concurrency_release_blocking(key, count)

    # -- TTL maintenance ---------------------------------------------------
    def sweep_all(self) -> None:
        """Active TTL expiry across every tier (≙ DeviceBucketStore.
        sweep_all — the server's --sweep-period hooks this)."""
        self._aux.sweep_all()
        with self._registry_lock:
            stores = (list(self._shards.values())
                      + list(self._windows.values()))
        for store in stores:
            store.sweep()

    def start_sweeper(self, period_s: float = 30.0) -> None:
        if self._sweeper_task is not None and not self._sweeper_task.done():
            return
        self._sweeper_task = start_periodic_sweeper(self.sweep_all, period_s)

    # -- checkpoint --------------------------------------------------------
    def snapshot(self) -> dict:
        # No mesh-level now_ticks: each sub-snapshot carries and re-aligns
        # its own epoch (they all read the same shared clock).
        with self._registry_lock:
            return {
                "aux": self._aux.snapshot(),
                "shards": {
                    key: store.snapshot()
                    for key, store in self._shards.items()
                },
                "windows": {
                    key: store.snapshot()
                    for key, store in self._windows.items()
                },
            }

    def restore(self, snap: dict) -> None:
        self._aux.restore(snap["aux"])
        self._migrate_legacy_aux_windows()
        for (cap, rate), sub in snap["shards"].items():
            self._sharded(cap, rate).restore(sub)
        from distributedratelimiting.redis_tpu.ops import bucket_math as bm

        for (limit, wticks, fixed), sub in snap.get("windows", {}).items():
            self._sharded_window(limit, wticks / bm.TICKS_PER_SECOND,
                                 fixed).restore(sub)

    def _migrate_legacy_aux_windows(self) -> None:
        """Snapshots taken before window serving moved to the sharded tier
        hold window tables inside the aux store; leaving them there would
        silently reset every window key (the serving path reads
        ``self._windows``, init-on-miss) — up to one full extra limit per
        key right after a planned restart. Move each restored aux window
        row into the sharded tier (aux restore already re-aligned the
        window indices to this process's epoch) and drop the aux table."""
        import numpy as np

        from distributedratelimiting.redis_tpu.ops import bucket_math as bm

        if self._aux._wtables and self.directory == "fp":
            # The migration scatters into host-directory slots; the fp
            # tier has no host directory to scatter into. Refuse BEFORE
            # touching the aux tables so nothing is lost.
            raise ValueError(
                "legacy snapshot holds aux-tier window tables; restore it "
                "into a directory='host' mesh store (its windows then "
                "re-checkpoint in the sharded form)")
        for key3 in list(self._aux._wtables):
            limit, wticks, fixed = key3
            table = self._aux._wtables[key3]
            mapping = table.dir.to_dict()  # key → aux slot
            del self._aux._wtables[key3]
            if not mapping:
                continue
            ws = self._sharded_window(limit, wticks / bm.TICKS_PER_SECOND,
                                      fixed)
            keys = list(mapping)
            aux_slots = np.fromiter((mapping[k] for k in keys), np.int64,
                                    len(keys))
            with ws._lock:
                shards, locs = ws._resolve_batch(keys)  # grows as needed
                flat = shards.astype(np.int64) * ws.per_shard + locs
                import jax
                import jax.numpy as jnp
                from jax.sharding import NamedSharding, PartitionSpec as P

                from distributedratelimiting.redis_tpu.ops import kernels as K
                from distributedratelimiting.redis_tpu.parallel.mesh import (
                    SHARD_AXIS,
                )

                sharding = NamedSharding(ws.mesh, P(SHARD_AXIS))
                host = {
                    name: np.array(getattr(ws.state, name))  # writable copy
                    for name in ("prev_count", "curr_count", "window_idx",
                                 "exists")
                }
                for name in host:
                    src = np.asarray(getattr(table.state, name))
                    host[name][flat] = src[aux_slots]
                ws.state = K.WindowState(**{
                    name: jax.device_put(jnp.asarray(arr), sharding)
                    for name, arr in host.items()
                })
