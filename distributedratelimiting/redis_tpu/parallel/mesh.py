"""Mesh construction helpers.

One logical axis, ``"shard"``, carries the key dimension. On real hardware
the axis should follow the physical ICI topology (jax's default device
order does); on CPU it maps over the virtual devices created by
``--xla_force_host_platform_device_count`` (the test/dry-run path replacing
the reference's Orleans-localhost multi-silo trick,
``TestApp/Program.cs:37-104``).
"""

from __future__ import annotations

import jax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

__all__ = ["SHARD_AXIS", "create_mesh", "shard_spec", "replicated_spec"]

SHARD_AXIS = "shard"


def create_mesh(n_devices: int | None = None) -> Mesh:
    """A 1-D device mesh over the first ``n_devices`` devices (all by
    default)."""
    devices = jax.devices()
    if n_devices is not None:
        if n_devices > len(devices):
            raise ValueError(
                f"requested {n_devices} devices, only {len(devices)} present"
            )
        devices = devices[:n_devices]
    import numpy as np

    return Mesh(np.array(devices), (SHARD_AXIS,))


def shard_spec(mesh: Mesh) -> NamedSharding:
    """First-axis sharding over the key dimension."""
    return NamedSharding(mesh, P(SHARD_AXIS))


def replicated_spec(mesh: Mesh) -> NamedSharding:
    return NamedSharding(mesh, P())
