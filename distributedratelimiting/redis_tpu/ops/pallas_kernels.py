"""Pallas TPU kernels — the ops where a hand-written kernel beats XLA.

Scope note (deliberate, hardware-driven): the acquire hot path is random
gather/scatter over an HBM-resident table. XLA lowers those to the TPU's
native dynamic-(update)-slice hardware path; Mosaic/Pallas exposes no
scatter primitive at all and only a 2D gather, and any dense one-hot
reformulation is O(B·N) — profitable only when gathering >= 128 features
per row (embedding tables), not 3 scalars. So the per-batch decision kernel
stays on XLA (see ``kernels.acquire_batch_packed``), and Pallas is used
where it actually wins: **streaming whole-table passes**, which are
HBM-bandwidth-bound and fuse naturally.

:func:`sweep_expired_pallas` is the TTL eviction pass (SURVEY.md invariant
5) as one fused streaming kernel:

- reads ``tokens``/``last_ts``/``exists`` once, tile by tile;
- computes the expiry predicate (idle past time-to-full TTL, clamped
  ``[1s, 1yr]`` — ``RedisTokenBucketRateLimiter.cs:234-235``);
- clears ``exists`` in place for expired slots;
- emits a **per-tile expired count** alongside the mask, accumulated in
  SMEM across the sequential TPU grid.

The count vector is tiny (N/TILE int32), so the host can decide whether a
10M-slot sweep freed anything by fetching ~KBs instead of a 10 MB bool
mask — on remote/tunneled links that is the difference between a no-op
sweep costing one small readback and costing a bulk transfer.

Falls back to interpret mode off-TPU so the same code path is unit-tested
on the CPU mesh (tests/conftest.py).
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from distributedratelimiting.redis_tpu.ops import bucket_math as bm

__all__ = ["sweep_expired_pallas", "LANES", "SUBLANES"]

LANES = 128      # TPU lane count — last dim of every tile
SUBLANES = 8     # f32 sublane count — second-to-last dim granularity
TILE_ROWS = 256  # rows of 128 lanes per grid step (32K slots, 384 KB VMEM)


def _sweep_kernel(now_ref, cap_ref, rate_ref, tokens_ref, last_ts_ref,
                  exists_ref, exists_out_ref, mask_ref, counts_ref):
    """One grid step: TTL-expire one [TILE_ROWS, 128] tile."""
    now = now_ref[0]
    capacity = cap_ref[0]
    rate = rate_ref[0]

    tokens = tokens_ref[:]
    last_ts = last_ts_ref[:]
    exists = exists_ref[:]

    # time_to_full_ttl, inlined on the VPU (same math as bucket_math).
    deficit = jnp.maximum(capacity - tokens, 0.0)
    ttl = jnp.ceil(deficit / jnp.maximum(rate, 1e-30))
    ttl = jnp.clip(ttl, bm.MIN_TTL_TICKS,
                   min(bm.MAX_TTL_TICKS, 2**31 - 1)).astype(jnp.int32)
    elapsed = jnp.maximum(0, now - last_ts)
    expired = (exists != 0) & (elapsed >= ttl)

    exists_out_ref[:] = jnp.where(expired, 0, exists).astype(jnp.int8)
    mask_ref[:] = expired.astype(jnp.int8)
    # One count per grid step, broadcast over a minimum-size (8, 128) vector
    # tile (the host reads element [0, 0] of each step's tile).
    counts_ref[:] = jnp.broadcast_to(
        jnp.sum(expired.astype(jnp.int32)), (SUBLANES, LANES)
    )


@functools.partial(jax.jit, donate_argnums=(2,),
                   static_argnames=("interpret",))
def sweep_expired_pallas(tokens, last_ts, exists_i8, now, capacity,
                         fill_rate_per_tick, *, interpret: bool = False):
    """Fused streaming TTL sweep over the whole table.

    Args:
      tokens: f32[N] token balances, N a multiple of ``TILE_ROWS * LANES``
        is NOT required — inputs are padded here (padding rows carry
        ``exists = 0`` so they can never count as expired).
      last_ts: i32[N]; exists_i8: i8[N] (0/1 occupancy — int8 keeps the
        occupancy traffic and mask readback at 1 byte/slot). ``exists_i8``
        is **donated**: its buffer is aliased to ``new_exists`` so the
        occupancy plane is not double-buffered during a full-table sweep
        (1 byte/slot — 10 MB transient at 10M slots; drl-xla
        ``xla-donation`` pins the alias in the lowered artifact). Callers
        pass a fresh array (every call site builds one via ``astype``)
        and must not reuse it after the call. ``tokens``/``last_ts`` are
        read-only here and stay un-donated — the caller keeps them.
      now/capacity/fill_rate_per_tick: scalars (host-side Python/np values
        or 0-d arrays).

    Returns:
      ``(new_exists i8[N], expired_mask i8[N], tile_counts i32[T])`` where
      ``T = ceil(N / (TILE_ROWS*LANES))``. ``tile_counts.sum() == 0`` means
      the sweep freed nothing — a decision the host reaches by reading T
      ints, not N bytes.
    """
    n = tokens.shape[0]
    tile = TILE_ROWS * LANES
    t = -(-n // tile)
    padded = t * tile
    if padded != n:
        pad = padded - n
        tokens = jnp.concatenate([tokens, jnp.zeros((pad,), tokens.dtype)])
        last_ts = jnp.concatenate([last_ts, jnp.zeros((pad,), last_ts.dtype)])
        exists_i8 = jnp.concatenate(
            [exists_i8, jnp.zeros((pad,), exists_i8.dtype)])

    tokens2 = tokens.reshape(t * TILE_ROWS, LANES)
    last2 = last_ts.reshape(t * TILE_ROWS, LANES)
    exists2 = exists_i8.reshape(t * TILE_ROWS, LANES)

    now_arr = jnp.asarray(now, jnp.int32).reshape(1)
    cap_arr = jnp.asarray(capacity, jnp.float32).reshape(1)
    rate_arr = jnp.asarray(fill_rate_per_tick, jnp.float32).reshape(1)

    tile_spec = pl.BlockSpec((TILE_ROWS, LANES), lambda i: (i, 0),
                             memory_space=pltpu.VMEM)
    scalar_spec = pl.BlockSpec(memory_space=pltpu.SMEM)

    new_exists2, mask2, counts = pl.pallas_call(
        _sweep_kernel,
        grid=(t,),
        in_specs=[scalar_spec, scalar_spec, scalar_spec,
                  tile_spec, tile_spec, tile_spec],
        out_specs=[
            tile_spec,
            tile_spec,
            pl.BlockSpec((SUBLANES, LANES), lambda i: (i, 0),
                         memory_space=pltpu.VMEM),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((t * TILE_ROWS, LANES), jnp.int8),
            jax.ShapeDtypeStruct((t * TILE_ROWS, LANES), jnp.int8),
            jax.ShapeDtypeStruct((t * SUBLANES, LANES), jnp.int32),
        ],
        interpret=interpret,
    )(now_arr, cap_arr, rate_arr, tokens2, last2, exists2)

    return (new_exists2.reshape(-1)[:n], mask2.reshape(-1)[:n],
            counts[::SUBLANES, 0])
