"""Device-resident key directory: probe/insert on fingerprints, in-kernel.

The classic store keeps key→slot routing on the host (`runtime/directory.py`
+ ``native/directory.cc``) and ships resolved slot ids to the device. This
module moves the directory INTO device memory — the "device-side
hashing/eviction/TTL without host round-trips per key" hard part called out
in SURVEY.md §7: the host's entire per-batch duty shrinks to one hashing
pass (``dir_fp64_pylist`` — 64-bit FNV-1a fingerprints), and the kernel
itself finds-or-claims each key's slot against a fingerprint table in HBM,
fused with the refill-and-decrement decision.

Design (all shapes static, XLA-friendly — no data-dependent control flow):

- **Table**: ``fp: u32[N, 2]`` — (lo, hi) halves of each slot's key
  fingerprint; ``(0, 0)`` means EMPTY (the host hasher never emits it).
  Bucket state stays the ordinary :class:`~.kernels.BucketState`; a freshly
  claimed slot keeps ``exists=False`` so the decision kernel's init-on-miss
  (invariant: ``RedisTokenBucketRateLimiter.cs:210-215``) initializes the
  bucket — insert only writes the fingerprint.
- **Probe**: each request scans a fixed window of ``L`` cells starting at
  ``mix(fp) % N`` (one ``[B, L, 2]`` gather). Full-window scans make
  deletion trivially safe: clearing a cell cannot hide a key placed later
  in the window, because lookups never early-stop at an empty cell (the
  tombstone problem of classic linear probing does not arise).
- **Insert**: unresolved requests claim their window's first empty cell by
  scattering their fingerprint ROW (``[B, 2]`` into ``[N, 2]`` — one
  scatter, so a contested cell ends up with exactly one winner's coherent
  pair) and re-gathering to see who won. Losers retry next round against
  the updated occupancy; duplicates of the same new key pick the same cell
  and all "win" (identical fingerprint). ``R`` rounds bound the retries;
  requests still unresolved after ``R`` (pathological window pressure)
  come back with slot ``-1`` — the caller denies and reports, and the
  host can grow/sweep before the next batch.
- **Sweep**: expired buckets (same TTL rule as :func:`~.kernels
  .sweep_expired`) get BOTH ``exists`` and their fingerprint cleared — the
  table self-expires with zero host bookkeeping (no free-lists).

Collision disclosure: two distinct keys share a bucket iff their 64-bit
fingerprints collide (probability ≈ n²/2⁶⁵ — about 3·10⁻⁶ at 10M keys);
the classic host directory compares full key bytes and has no such case.
The trade is explicit: this path removes the host table (RAM, insert cost,
growth machinery) and its per-batch resolve from the serving path.
"""

from __future__ import annotations

from functools import partial
from typing import NamedTuple

import jax
import jax.numpy as jnp
import numpy as np

from distributedratelimiting.redis_tpu.ops import bucket_math as bm
from distributedratelimiting.redis_tpu.ops import kernels as K

__all__ = [
    "init_fp_table",
    "fp_resolve_core",
    "fp_acquire_batch",
    "fp_acquire_scan_fused",
    "fp_acquire_scan_fused_bits",
    "pack_fp12",
    "fp_debit_batch",
    "fp_peek_batch",
    "fp_migrate_chunk",
    "fp_sweep_expired",
    "fp_window_acquire_batch",
    "fp_window_acquire_scan_fused",
    "fp_window_acquire_scan_fused_bits",
    "fp_migrate_window_chunk",
    "fp_sweep_windows",
    "FpResolveOut",
]

#: Golden-ratio multiplier for the lo/hi mix → base probe index. Plain
#: int, NOT a jnp scalar: a module-level jnp constant initializes the
#: backend at import time, before any force-CPU bootstrap can run — on
#: the tunneled-TPU rig that wedges every process that imports the
#: package while another holds the device (observed; cost hours).
_MIX = 0x9E3779B1

#: Placement-mapping version carried in checkpoints: v2 = non-wrapping
#: slice-gather windows (base = h % (n - L + 1)); v1/absent = the old
#: wrapping h % n. Restores re-place entries from a different placement
#: through the migrate kernel instead of installing tables verbatim.
PLACEMENT_VERSION = 2


def init_fp_table(n: int) -> jax.Array:
    """Empty fingerprint table: ``u32[n, 2]`` of zeros."""
    return jnp.zeros((n, 2), jnp.uint32)


class FpResolveOut(NamedTuple):
    fp: jax.Array        # u32[N, 2] — table after inserts
    slots: jax.Array     # i32[B] — resolved slot per request, -1 unresolved
    resolved: jax.Array  # bool[B] — False only under window pressure


def _base_index(kpair, n: int, probe_window: int):
    # np.uint32, not a bare int (jit would parse it int32 → overflow) and
    # not jnp.uint32 at module scope (import-time backend init, above).
    # Bases land in [0, n - L]: the probe window NEVER wraps, so every
    # window read is one contiguous (L, 2) slice — a slice-gather the TPU
    # executes ~5× faster than L independent row gathers (r05 microbench;
    # 128-byte contiguous bursts vs 8-byte random rows). The last L-1
    # cells are reachable only as window tails, a negligible uniformity
    # trade against the gather shape.
    h = kpair[:, 0] * np.uint32(_MIX) ^ kpair[:, 1]
    return (h % jnp.uint32(n - probe_window + 1)).astype(jnp.int32)


def _window_cells(fp, base, probe_window: int):
    """Gather each request's contiguous probe window: ``[B, L, 2]`` via
    one slice-gather (``slice_sizes=(L, 2)``), start rows ``base``."""
    dn = jax.lax.GatherDimensionNumbers(
        offset_dims=(1, 2), collapsed_slice_dims=(),
        start_index_map=(0,))
    return jax.lax.gather(fp, base[:, None], dn,
                          slice_sizes=(probe_window, 2), mode="clip")


def fp_resolve_core(fp, kpair, valid, *, probe_window: int,
                    rounds: int) -> FpResolveOut:
    """Find-or-claim a slot for each fingerprint (traceable core).

    Args:
      fp: ``u32[N, 2]`` table.
      kpair: ``u32[B, 2]`` request fingerprints (never ``(0, 0)``).
      valid: ``bool[B]`` — padding rows neither match nor insert.
      probe_window: cells scanned per request (static).
      rounds: insert retry rounds (static; ≥1).
    """
    n = fp.shape[0]
    b = kpair.shape[0]
    # Static (trace-time) guard: the non-wrapping placement needs at
    # least one full window; smaller tables would wrap the uint32
    # modulus in _base_index into garbage bases silently.
    assert n >= probe_window, (
        f"fp table of {n} slots is smaller than probe_window "
        f"{probe_window}")
    rows = jnp.arange(b, dtype=jnp.int32)
    base = _base_index(kpair, n, probe_window)
    # [B, L] candidate cells (contiguous, non-wrapping window).
    widx = base[:, None] + jnp.arange(probe_window, dtype=jnp.int32)[None, :]

    slots = jnp.full((b,), -1, jnp.int32)
    resolved = ~valid  # padding rows are "done" (slot stays -1)

    def probe(fp, slots, resolved):
        """Match pass: find each unresolved request's cell if present."""
        cells = _window_cells(fp, base, probe_window)   # [B, L, 2]
        occ = (cells != 0).any(-1)              # [B, L]
        match = (occ
                 & (cells[..., 0] == kpair[:, None, 0])
                 & (cells[..., 1] == kpair[:, None, 1]))
        hit = match.any(1) & ~resolved
        hpos = jnp.argmax(match, axis=1).astype(jnp.int32)
        slots = jnp.where(hit, widx[rows, hpos], slots)
        return slots, resolved | hit, occ

    # Steady-state fast path: one pure gather resolves every present key.
    # The insert machinery (scatter + verify re-gather, the expensive part
    # of this kernel) runs ONLY while some request is still unresolved —
    # a `while_loop` whose condition reduces on device, so a warm serving
    # batch costs one probe gather and zero insert rounds.
    slots, resolved, _ = probe(fp, slots, resolved)

    def round_needed(carry):
        _, _, resolved, r = carry
        return (r < rounds) & ~resolved.all()

    # Per-KEY free-cell preference: contenders sharing a window spread
    # across its free cells instead of all fighting for argmax(free) (one
    # winner per round — pathological when n is close to L and every base
    # collapses to the same window). Derived from the fingerprint, not
    # the row, so in-batch duplicates of one new key still pick the SAME
    # cell and all win its insert (docstring contract).
    pref = ((kpair[:, 0] ^ (kpair[:, 1] * np.uint32(0x85EBCA6B)))
            % jnp.uint32(probe_window)).astype(jnp.int32)
    lane = jnp.arange(probe_window, dtype=jnp.int32)[None, :]
    rot_idx = (pref[:, None] + lane) % probe_window  # [B, L]

    def insert_round(carry):
        fp, slots, resolved, r = carry
        slots, resolved, occ = probe(fp, slots, resolved)
        free = ~occ
        has_free = free.any(1)
        need = ~resolved & has_free
        free_rot = jnp.take_along_axis(free, rot_idx, axis=1)
        first = jnp.argmax(free_rot, axis=1).astype(jnp.int32)
        tpos = jnp.take_along_axis(rot_idx, first[:, None], axis=1)[:, 0]
        target = jnp.where(need, widx[rows, tpos], n)  # n ⇒ dropped
        # One scatter of whole (lo, hi) ROWS: a contested cell gets one
        # winner's coherent pair (two per-half scatters could interleave
        # different writers into a fingerprint that belongs to no key).
        fp = fp.at[target].set(kpair, mode="drop")
        got = fp[jnp.where(need, target, 0)]
        won = need & (got == kpair).all(-1)
        slots = jnp.where(won, target, slots)
        resolved = resolved | won
        return fp, slots, resolved, r + 1

    fp, slots, resolved, _ = jax.lax.while_loop(
        round_needed, insert_round,
        (fp, slots, resolved, jnp.int32(0)))
    return FpResolveOut(fp, slots, resolved)


def _fp_acquire_core(fp, state, kpair, counts, valid, now, capacity,
                     fill_rate_per_tick, *, probe_window: int, rounds: int,
                     handle_duplicates: bool):
    out = fp_resolve_core(fp, kpair, valid, probe_window=probe_window,
                          rounds=rounds)
    live = valid & out.resolved
    state, granted, remaining = K.acquire_core(
        state, out.slots, counts, live, now, capacity, fill_rate_per_tick,
        handle_duplicates=handle_duplicates)
    return out.fp, state, granted, remaining, out.resolved


@partial(jax.jit, donate_argnums=(0, 1),
         static_argnames=("probe_window", "rounds", "handle_duplicates"))
def fp_acquire_batch(fp, state: K.BucketState, kpair, counts, valid, now,
                     capacity, fill_rate_per_tick, *, probe_window: int = 16,
                     rounds: int = 4, handle_duplicates: bool = True):
    """Fused directory-resolve + refill-and-decrement: ONE kernel launch
    decides a batch straight from key fingerprints — the whole Lua-script
    role (``RedisTokenBucketRateLimiter.cs:176-239``) including the key
    lookup Redis does in its hash table before the script body runs.

    Returns ``(fp, state, granted, remaining, resolved)``; unresolved rows
    (window pressure, see module docstring) are denied with
    ``remaining = 0`` and reported so the host can sweep/grow.
    """
    return _fp_acquire_core(fp, state, kpair, counts, valid, now, capacity,
                            fill_rate_per_tick, probe_window=probe_window,
                            rounds=rounds,
                            handle_duplicates=handle_duplicates)


def pack_fp12(fps: np.ndarray, counts: np.ndarray) -> np.ndarray:
    """Host-side packing for the fused fp dispatches: ``u32[B, 3]`` =
    (lo, hi, count), padding rows marked by count ``0xFFFFFFFF``. ONE
    operand array per dispatch instead of three (kpair/counts/valid) —
    per-transfer floors on tunneled links make the transfer COUNT matter
    as much as the bytes (the :func:`~.kernels.pack_compact5` lesson,
    RESULTS.md r04). 12 bytes/decision.

    ``fps`` is ``u32[B, 2]`` (padding rows arbitrary), ``counts`` is the
    valid prefix's counts — rows past ``len(counts)`` become padding.
    """
    b = fps.shape[0]
    fused = np.empty((b, 3), np.uint32)
    fused[:, :2] = fps
    fused[:, 2] = np.uint32(0xFFFFFFFF)
    n = len(counts)
    # Clamp BOTH sides: a negative count must stay a valid row (it grants,
    # like every other path's kernel does for count ≤ 0), not wrap into
    # the uint32 sign-bit range and get silently reclassified as padding.
    fused[:n, 2] = np.clip(counts, 0, 2**31 - 1).astype(np.uint32)
    return fused


def _unpack_fp12(fused):
    """Device-side unpack of :func:`pack_fp12`: the count column read as
    i32 makes padding exactly ``-1`` via the sign bit."""
    kpair = fused[..., :2]
    counts = fused[..., 2].astype(jnp.int32)
    valid = counts >= 0
    return kpair, jnp.maximum(counts, 0), valid


def _bitpack2(granted, resolved):
    """Pack two bool[B] planes into ``u8[2, B//8]`` (little-endian bit
    order, host side ``np.unpackbits(..., bitorder="little")``): plane 0
    grants, plane 1 resolve status — ONE device→host fetch carries both
    verdict and window-pressure report at 2 bits/decision."""
    shifts = jnp.arange(8, dtype=jnp.uint8)
    g = (granted.reshape(-1, 8).astype(jnp.uint8) << shifts).sum(
        axis=1, dtype=jnp.uint8)
    r = (resolved.reshape(-1, 8).astype(jnp.uint8) << shifts).sum(
        axis=1, dtype=jnp.uint8)
    return jnp.stack([g, r])


@partial(jax.jit, donate_argnums=(0, 1),
         static_argnames=("probe_window", "rounds", "handle_duplicates"))
def fp_acquire_scan_fused_bits(fp, state: K.BucketState, fused_k, nows_k,
                               capacity, fill_rate_per_tick, *,
                               probe_window: int = 16, rounds: int = 4,
                               handle_duplicates: bool = True):
    """Minimum-transfer fp bulk dispatch: ONE fused operand up
    (:func:`pack_fp12`), ONE bit-packed result down — the fp analogue of
    :func:`~.kernels.acquire_scan_fused_bits`. On high-RTT tunnel days
    the fetch count, not the kernel, dominates the fp bulk path (measured
    ~70 ms/fetch, r05), so the verdict-only path ships granted+resolved
    as two bit-planes in a single ``u8[K, 2, B//8]`` array.

    Returns ``(fp, state, bits u8[K, 2, B//8])``; ``B % 8 == 0``.
    """

    def body(carry, xs):
        fp, st = carry
        fused, now = xs
        kpair, counts, valid = _unpack_fp12(fused)
        fp, st, granted, _, res = _fp_acquire_core(
            fp, st, kpair, counts, valid, now, capacity,
            fill_rate_per_tick, probe_window=probe_window, rounds=rounds,
            handle_duplicates=handle_duplicates)
        return (fp, st), _bitpack2(granted, res)

    (fp, state), bits = jax.lax.scan(body, (fp, state), (fused_k, nows_k))
    return fp, state, bits


@partial(jax.jit, donate_argnums=(0, 1),
         static_argnames=("probe_window", "rounds", "handle_duplicates"))
def fp_acquire_scan_fused(fp, state: K.BucketState, fused_k, nows_k,
                          capacity, fill_rate_per_tick, *,
                          probe_window: int = 16, rounds: int = 4,
                          handle_duplicates: bool = True):
    """Fused-operand fp bulk dispatch WITH per-request remaining: ONE
    operand up, ONE ``f32[K, 2, B]`` result down — row 0 encodes
    ``granted + 2·resolved`` (both recovered exactly from the small
    integer), row 1 is remaining. One fetch replaces three.

    Returns ``(fp, state, out f32[K, 2, B])``.
    """

    def body(carry, xs):
        fp, st = carry
        fused, now = xs
        kpair, counts, valid = _unpack_fp12(fused)
        fp, st, granted, remaining, res = _fp_acquire_core(
            fp, st, kpair, counts, valid, now, capacity,
            fill_rate_per_tick, probe_window=probe_window, rounds=rounds,
            handle_duplicates=handle_duplicates)
        code = granted.astype(jnp.float32) + 2.0 * res.astype(jnp.float32)
        return (fp, st), jnp.stack([code, remaining])

    (fp, state), out = jax.lax.scan(body, (fp, state), (fused_k, nows_k))
    return fp, state, out


@partial(jax.jit, donate_argnums=(0, 1),
         static_argnames=("probe_window", "rounds"))
def fp_debit_batch(fp, state: K.BucketState, kpair, amounts, valid, now,
                   capacity, fill_rate_per_tick, *,
                   probe_window: int = 16, rounds: int = 4):
    """Saturating bulk debit with in-kernel slot resolution — the
    fingerprint edition of :func:`~.kernels.debit_batch_packed`, and
    the lane the hierarchical deny-refund (``debit_many`` with a
    negative amount, runtime/store.py) rides on the fp store. The debit
    algebra is byte-for-byte the packed kernel's: refill-or-init, then
    subtract clamped at zero (a NEGATIVE amount credits back; the next
    refill's capacity clamp bounds any overshoot — refunds can only
    under-credit, the safe direction), duplicate fingerprints
    serialized via the demand prefix.

    Resolution inserts on miss (a debit of an absent key initializes
    it at capacity and debits from there — the host-dict
    ``InProcessBucketStore.debit_many`` semantics); rows still
    unresolved after ``rounds`` (window pressure) apply nothing and
    report their full amount as shortfall.

    Returns ``(fp, state, out f32[2, B])``: row 0 the post-debit
    balance, row 1 the clamped shortfall.
    """
    out = fp_resolve_core(fp, kpair, valid, probe_window=probe_window,
                          rounds=rounds)
    live = valid & out.resolved
    amounts = jnp.asarray(amounts, jnp.float32)
    size = state.tokens.shape[0]
    gs = jnp.where(live, out.slots, 0)
    refilled = bm.refill_or_init(state.tokens[gs], state.last_ts[gs],
                                 state.exists[gs], now, capacity,
                                 fill_rate_per_tick)
    prefix = bm.duplicate_prefix(out.slots, amounts, live)
    avail = jnp.maximum(refilled - prefix, 0.0)
    applied = jnp.where(live, jnp.minimum(amounts, avail), 0.0)
    # Unresolved-but-valid rows (window pressure) applied nothing: a
    # positive debit reports its full amount as shortfall; a refund
    # reports zero (shortfall means "tokens the debit did not find",
    # a refund has none — it just went un-credited, the safe side).
    shortfall = jnp.where(live, amounts - applied,
                          jnp.where(valid, jnp.maximum(amounts, 0.0),
                                    0.0))
    remaining = jnp.where(live, avail - applied, 0.0)
    ss = jnp.where(live, out.slots, size)  # size ⇒ scatter-dropped
    new_tokens = state.tokens.at[ss].set(refilled, mode="drop")
    new_tokens = new_tokens.at[ss].add(-applied, mode="drop")
    new_last_ts = state.last_ts.at[ss].set(
        jnp.asarray(now, jnp.int32), mode="drop")
    new_exists = state.exists.at[ss].set(True, mode="drop")
    return (out.fp,
            K.BucketState(new_tokens, new_last_ts, new_exists),
            jnp.stack([remaining, shortfall]))


@partial(jax.jit, static_argnames=("probe_window",))
def fp_peek_batch(fp, state: K.BucketState, kpair, valid, now, capacity,
                  fill_rate_per_tick, *, probe_window: int = 16):
    """Read-only availability estimate straight from fingerprints
    (``GetAvailablePermits``): lookup WITHOUT insert — peeking at an
    unseen key must not claim a slot — and missing keys report a full
    bucket (init-on-miss semantics read-only)."""
    n = fp.shape[0]
    b = kpair.shape[0]
    rows = jnp.arange(b, dtype=jnp.int32)
    base = _base_index(kpair, n, probe_window)
    widx = base[:, None] + jnp.arange(probe_window, dtype=jnp.int32)[None, :]
    cells = _window_cells(fp, base, probe_window)
    occ = (cells != 0).any(-1)
    match = (occ
             & (cells[..., 0] == kpair[:, None, 0])
             & (cells[..., 1] == kpair[:, None, 1]))
    hit = match.any(1)
    slots = jnp.where(hit, widx[rows, jnp.argmax(match, 1)], 0)
    refilled = bm.refill_or_init(
        state.tokens[slots], state.last_ts[slots], state.exists[slots] & hit,
        now, capacity, fill_rate_per_tick)
    return jnp.where(valid, jnp.floor(refilled), 0.0)


def _fp_migrate_core(fp, state, kpair, cols, valid, *, probe_window: int,
                     rounds: int):
    """Claim slots for old-table entries in the new table and scatter
    their per-slot state columns across (traceable core — also the
    per-shard block body of the mesh migrate step). Returns the per-entry
    ``placed`` mask: under heavy in-chunk window contention (tiny or
    crowded tables) the bounded insert rounds can leave entries unplaced,
    and the host retries exactly those in another pass — each pass places
    at least one contender per contested cell, so retries terminate."""
    out = fp_resolve_core(fp, kpair, valid, probe_window=probe_window,
                          rounds=rounds)
    live = valid & out.resolved
    ss = jnp.where(live, out.slots, fp.shape[0])  # n ⇒ dropped
    new_state = type(state)(*(
        getattr(state, f).at[ss].set(c, mode="drop")
        for f, c in zip(state._fields, cols)))
    return out.fp, new_state, live


@partial(jax.jit, donate_argnums=(0, 1),
         static_argnames=("probe_window", "rounds"))
def fp_migrate_chunk(fp, state: K.BucketState, kpair, tokens, last_ts,
                     exists, valid, *, probe_window: int = 16,
                     rounds: int = 4):
    """Growth/rehash step, on-device: claim slots for a chunk of OLD-table
    entries in the new (larger) table, then scatter their bucket state to
    the claimed slots. The host's whole role in a grow is reading the old
    fingerprints back, chunking, and retrying unplaced entries —
    placement and state movement never leave the device. Returns
    ``(fp, state, placed bool[B])``."""
    return _fp_migrate_core(fp, state, kpair, (tokens, last_ts, exists),
                            valid, probe_window=probe_window, rounds=rounds)


def _fp_window_core(fp, state, kpair, counts, valid, now, limit,
                    window_ticks, *, probe_window: int, rounds: int,
                    handle_duplicates: bool, interpolate: bool):
    out = fp_resolve_core(fp, kpair, valid, probe_window=probe_window,
                          rounds=rounds)
    live = valid & out.resolved
    state, granted, remaining = K._window_acquire_core(
        state, out.slots, counts, live, now, limit, window_ticks,
        handle_duplicates=handle_duplicates, interpolate=interpolate)
    return out.fp, state, granted, remaining, out.resolved


@partial(jax.jit, donate_argnums=(0, 1),
         static_argnames=("probe_window", "rounds", "handle_duplicates",
                          "interpolate"))
def fp_window_acquire_batch(fp, state: K.WindowState, kpair, counts, valid,
                            now, limit, window_ticks, *,
                            probe_window: int = 16, rounds: int = 4,
                            handle_duplicates: bool = True,
                            interpolate: bool = True):
    """Fused resolve + sliding/fixed-window decision — the window-family
    analogue of :func:`fp_acquire_batch` (``interpolate=False`` = fixed
    window). Same insert/claim discipline; a freshly claimed slot's
    window state initializes via the core's init-on-miss."""
    return _fp_window_core(fp, state, kpair, counts, valid, now, limit,
                           window_ticks, probe_window=probe_window,
                           rounds=rounds,
                           handle_duplicates=handle_duplicates,
                           interpolate=interpolate)


@partial(jax.jit, donate_argnums=(0, 1),
         static_argnames=("probe_window", "rounds", "handle_duplicates",
                          "interpolate"))
def fp_window_acquire_scan_fused_bits(fp, state: K.WindowState, fused_k,
                                      nows_k, limit, window_ticks, *,
                                      probe_window: int = 16,
                                      rounds: int = 4,
                                      handle_duplicates: bool = True,
                                      interpolate: bool = True):
    """Window-family analogue of :func:`fp_acquire_scan_fused_bits`:
    one :func:`pack_fp12` operand up, ``u8[K, 2, B//8]`` bit-planes down
    (granted, resolved)."""

    def body(carry, xs):
        fp, st = carry
        fused, now = xs
        kpair, counts, valid = _unpack_fp12(fused)
        fp, st, granted, _, res = _fp_window_core(
            fp, st, kpair, counts, valid, now, limit, window_ticks,
            probe_window=probe_window, rounds=rounds,
            handle_duplicates=handle_duplicates, interpolate=interpolate)
        return (fp, st), _bitpack2(granted, res)

    (fp, state), bits = jax.lax.scan(body, (fp, state), (fused_k, nows_k))
    return fp, state, bits


@partial(jax.jit, donate_argnums=(0, 1),
         static_argnames=("probe_window", "rounds", "handle_duplicates",
                          "interpolate"))
def fp_window_acquire_scan_fused(fp, state: K.WindowState, fused_k, nows_k,
                                 limit, window_ticks, *,
                                 probe_window: int = 16, rounds: int = 4,
                                 handle_duplicates: bool = True,
                                 interpolate: bool = True):
    """Window-family analogue of :func:`fp_acquire_scan_fused`: one
    operand up, one ``f32[K, 2, B]`` result down (row 0 =
    ``granted + 2·resolved``, row 1 = remaining)."""

    def body(carry, xs):
        fp, st = carry
        fused, now = xs
        kpair, counts, valid = _unpack_fp12(fused)
        fp, st, granted, remaining, res = _fp_window_core(
            fp, st, kpair, counts, valid, now, limit, window_ticks,
            probe_window=probe_window, rounds=rounds,
            handle_duplicates=handle_duplicates, interpolate=interpolate)
        code = granted.astype(jnp.float32) + 2.0 * res.astype(jnp.float32)
        return (fp, st), jnp.stack([code, remaining])

    (fp, state), out = jax.lax.scan(body, (fp, state), (fused_k, nows_k))
    return fp, state, out


@partial(jax.jit, donate_argnums=(0, 1),
         static_argnames=("probe_window", "rounds"))
def fp_migrate_window_chunk(fp, state: K.WindowState, kpair, prev_count,
                            curr_count, window_idx, exists, valid, *,
                            probe_window: int = 16, rounds: int = 4):
    """Window-table growth step (the :func:`fp_migrate_chunk` analogue):
    claim slots in the new table, scatter the four window-state arrays
    across. Returns ``(fp, state, placed bool[B])``."""
    return _fp_migrate_core(
        fp, state, kpair, (prev_count, curr_count, window_idx, exists),
        valid, probe_window=probe_window, rounds=rounds)


@partial(jax.jit, donate_argnums=(0, 1))
def fp_sweep_windows(fp, state: K.WindowState, now, window_ticks):
    """Window-table TTL eviction with fingerprint clearing: a slot idle
    two full windows carries no information (:func:`~.kernels
    .sweep_windows`); its cell becomes claimable immediately."""
    idx_now = (jnp.asarray(now, jnp.int32)
               // jnp.asarray(window_ticks, jnp.int32))
    expired = state.exists & (idx_now - state.window_idx >= 2)
    fp = jnp.where(expired[:, None], jnp.uint32(0), fp)
    new_state = K.WindowState(state.prev_count, state.curr_count,
                              state.window_idx, state.exists & ~expired)
    return fp, new_state, expired.sum(dtype=jnp.int32)


@partial(jax.jit, donate_argnums=(0, 1))
def fp_sweep_expired(fp, state: K.BucketState, now, capacity,
                     fill_rate_per_tick):
    """TTL eviction with zero host bookkeeping: clear ``exists`` AND the
    fingerprint of every expired slot (same TTL rule as
    :func:`~.kernels.sweep_expired`, invariant 5). Freed cells become
    claimable immediately; full-window probing makes the clear safe for
    every other key (module docstring). Returns ``(fp, state, n_freed)``
    — a scalar readback, not an N-byte mask."""
    ttl = bm.time_to_full_ttl(state.tokens, capacity, fill_rate_per_tick)
    expired = state.exists & (bm.elapsed_ticks(now, state.last_ts) >= ttl)
    new_exists = state.exists & ~expired
    fp = jnp.where(expired[:, None], jnp.uint32(0), fp)
    return (fp, K.BucketState(state.tokens, state.last_ts, new_exists),
            expired.sum(dtype=jnp.int32))
