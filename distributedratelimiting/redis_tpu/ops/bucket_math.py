"""L0 — pure token-bucket math, time always an explicit operand.

These are the deterministic cores of the reference's two Lua kernels,
re-derived as vectorized jax-numpy functions over structure-of-arrays state:

- :func:`refill_and_decrement` ≙ the exact-bucket Lua script
  (``TokenBucket/RedisTokenBucketRateLimiter.cs:176-239``): lazy refill from
  elapsed store time, clock-regression clamp, refill clamp to
  ``[0, capacity]``, all-or-nothing grant, init-on-miss to a full bucket.
- :func:`decay_and_add` ≙ the approximate-bucket sync script
  (``ApproximateTokenBucket/RedisApproximateTokenBucketRateLimiter.cs:216-271``):
  decaying global consumption counter plus an EWMA of the inter-sync
  interval, from which callers derive a membership-free instance-count
  estimate.
- :func:`sliding_window_estimate` — the sliding-window counter variant
  (a BASELINE.json config; absent from the reference, which only sketched
  it in dead code).

Representation choices (TPU-first, see SURVEY.md §7 "Numerics"):

- **Time** is an ``int32`` tick count, ``TICKS_PER_SECOND = 1024`` (a power
  of two so second↔tick conversions are exact in float32). A batch kernel
  receives ONE scalar ``now`` — every key in the batch observes the same
  clock, the consistency property the reference got from Redis ``TIME``
  (``RedisTokenBucketRateLimiter.cs:202-203``). Clients never supply time
  (invariant 1, SURVEY.md §2).
- **Tokens** are ``float32``. Grant comparison is ``tokens >= count`` with
  no epsilon: float rounding can only under-admit, never over-admit, which
  is the safe direction for a rate limiter. The reference's accidental
  Lua-number truncation semantics (SURVEY.md invariant 10) are replaced by
  explicit ``floor`` at the observation boundary only.

Everything here is shape-polymorphic and dtype-stable so it can be jitted,
vmapped, and shard_mapped without retracing per config: capacities and rates
arrive as (broadcastable) array operands, not Python constants baked into
the trace — unlike the reference, which re-generates and re-compiles the Lua
script text per limiter instance
(``RedisTokenBucketRateLimiter.cs:184-185``).
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

# One tick = 1/1024 s. Power of two → exact in float32, and a full int32 range
# covers ~24 days of uptime, far beyond any flush interval. Idle slots are
# reclaimed by TTL eviction long before tick wraparound can matter; see
# DeviceBucketStore.sweep().
TICKS_PER_SECOND = 1024

# Lua kernel TTL clamp: max(1s, min(1yr, time-to-full-refill))
# (RedisTokenBucketRateLimiter.cs:234-235).
MIN_TTL_TICKS = TICKS_PER_SECOND  # 1 second
MAX_TTL_TICKS = 365 * 24 * 3600 * TICKS_PER_SECOND  # 1 year (clamped to int32 below)
_INT32_MAX = 2**31 - 1

# The approximate global counter's fixed TTL: 86400 s
# (RedisApproximateTokenBucketRateLimiter.cs:268).
GLOBAL_COUNTER_TTL_TICKS = 86400 * TICKS_PER_SECOND

# EWMA smoothing of the inter-sync interval: new_p = 0.8*prev + 0.2*delta
# (RedisApproximateTokenBucketRateLimiter.cs:260-262).
PERIOD_EWMA_ALPHA = 0.2


def seconds_to_ticks(seconds: float) -> int:
    """Host-side convenience: convert seconds to integer ticks (floor)."""
    return int(seconds * TICKS_PER_SECOND)


def ticks_to_seconds(ticks) -> float:
    return ticks / TICKS_PER_SECOND


def elapsed_ticks(now, last_ts):
    """Elapsed store time with the clock-regression clamp.

    ``max(0, now - last)`` — after a store failover the new authority's clock
    may be behind; negative elapsed must not mint or destroy tokens
    (``RedisTokenBucketRateLimiter.cs:218``; invariant 1).
    """
    return jnp.maximum(0, now - last_ts).astype(jnp.int32)


def refill(tokens, last_ts, now, capacity, fill_rate_per_tick):
    """Lazy refill: tokens materialize arithmetically from elapsed time.

    ``min(capacity, tokens + elapsed * rate)`` — the upper clamp bounds what
    a forward clock jump can grant to one full bucket
    (``RedisTokenBucketRateLimiter.cs:221`` and comment ``:179-180``;
    invariants 1-2). No background replenishment ever touches per-key state,
    which is what makes 10M idle keys free.
    """
    delta = elapsed_ticks(now, last_ts).astype(jnp.float32)
    return jnp.minimum(
        jnp.asarray(capacity, jnp.float32),
        tokens + delta * jnp.asarray(fill_rate_per_tick, jnp.float32),
    )


def refill_or_init(tokens, last_ts, exists, now, capacity, fill_rate_per_tick):
    """Refill where the slot exists; init-on-miss to a FULL bucket elsewhere
    (``RedisTokenBucketRateLimiter.cs:210-215``) — shared by the decision
    kernels and the read-only peek path."""
    return jnp.where(
        exists,
        refill(tokens, last_ts, now, capacity, fill_rate_per_tick),
        jnp.asarray(capacity, jnp.float32) + jnp.zeros_like(tokens),
    )


def decay_core(value, period_ewma, last_ts, exists, now, decay_rate_per_tick):
    """Decay-without-add core shared by :func:`decay_and_add` and the batched
    sync kernel (which needs the decayed value separately so consumption can
    be applied via scatter-add). Returns ``(decayed, new_period)``."""
    # Init-on-miss must not read a stale/garbage timestamp: a fresh counter's
    # "previous touch" is the store epoch (tick 0).
    delta = elapsed_ticks(now, jnp.where(exists, last_ts, 0)).astype(jnp.float32)
    decayed = jnp.where(
        exists,
        jnp.maximum(
            0.0, value - delta * jnp.asarray(decay_rate_per_tick, jnp.float32)
        ),
        0.0,
    )
    new_period = jnp.where(
        exists,
        (1.0 - PERIOD_EWMA_ALPHA) * period_ewma + PERIOD_EWMA_ALPHA * delta,
        delta,
    )
    return decayed, new_period


def refill_and_decrement(tokens, last_ts, exists, now, counts, capacity,
                         fill_rate_per_tick):
    """The exact-bucket kernel core: one atomic refill-then-grant step.

    Mirrors the Lua program at ``RedisTokenBucketRateLimiter.cs:176-239``:

    - ``exists == False`` ⇒ init-on-miss to a full bucket (``:210-215``) —
      a wiped store self-heals to "full" rather than "empty".
    - refill with regression clamp + capacity clamp (``:218,:221``);
    - all-or-nothing grant: ``count`` permits are consumed iff
      ``refilled >= count`` (``:224-227``; invariant 4). ``count == 0`` is a
      probe: it "succeeds" trivially and consumes nothing — callers decide
      probe semantics at the API layer.

    Args:
      tokens:  f32[...] current token balances (garbage where ``~exists``).
      last_ts: i32[...] last-touch store ticks (garbage where ``~exists``).
      exists:  bool[...] slot-occupancy mask.
      now:     i32 scalar — THE batch timestamp (store is time authority).
      counts:  i32/f32[...] requested permits per key, >= 0.
      capacity, fill_rate_per_tick: broadcastable f32 bucket parameters.

    Returns:
      ``(new_tokens, new_last_ts, granted)`` — post-decision state and a
      bool grant mask. State for every touched key advances its timestamp to
      ``now`` whether or not the grant succeeded (the refill was applied).
    """
    counts = jnp.asarray(counts, jnp.float32)
    refilled = refill_or_init(tokens, last_ts, exists, now, capacity,
                              fill_rate_per_tick)
    granted = refilled >= counts
    new_tokens = refilled - jnp.where(granted, counts, 0.0)
    new_last_ts = jnp.broadcast_to(jnp.asarray(now, jnp.int32), new_tokens.shape)
    return new_tokens, new_last_ts, granted


def time_to_full_ttl(tokens, capacity, fill_rate_per_tick):
    """Per-key state TTL: time until the bucket would be full again.

    ``clamp(ceil((capacity - tokens) / rate), 1s, 1yr)`` — once a bucket has
    sat untouched long enough to be full, its state is indistinguishable from
    init-on-miss, so it can be evicted (``RedisTokenBucketRateLimiter.cs:234-235``;
    invariant 5). Returns i32 ticks.
    """
    rate = jnp.maximum(jnp.asarray(fill_rate_per_tick, jnp.float32), 1e-30)
    deficit = jnp.maximum(jnp.asarray(capacity, jnp.float32) - tokens, 0.0)
    ttl = jnp.ceil(deficit / rate)
    ttl = jnp.clip(ttl, MIN_TTL_TICKS, min(MAX_TTL_TICKS, _INT32_MAX))
    return ttl.astype(jnp.int32)


def decay_and_add(value, period_ewma, last_ts, exists, now, local_counts,
                  decay_rate_per_tick):
    """The approximate-bucket sync kernel core: decaying consumption counter.

    The global bucket is *inverted* relative to the exact one: it tracks a
    decaying **throttle score** (consumption), not a token balance
    (``RedisApproximateTokenBucketRateLimiter.cs:216-271``):

      ``new_v = max(0, v - delta * decay_rate) + local_counts``   (``:258``)
      ``new_p = 0.8 * p + 0.2 * delta``                           (``:260-262``)

    ``new_p`` is the EWMA of the observed inter-sync interval for THIS
    counter across ALL client instances: with k clients each syncing every
    replenishment period, syncs arrive k times per period, so
    ``period / new_p ≈ k`` — the membership-free instance-count estimate
    (``:443``; invariant 6, SURVEY.md §5.3d).

    Init-on-miss: a fresh counter starts at ``v = local_counts`` with
    ``p = delta`` undefined — we seed the EWMA with the replenishment-period
    hint via the caller passing ``period_ewma`` prefilled, or simply with
    ``delta=0`` contribution (matching the Lua script, which initializes
    ``p`` to the first observed delta).

    Returns ``(new_value, new_period_ewma, new_last_ts)``.
    """
    local_counts = jnp.asarray(local_counts, jnp.float32)
    decayed, new_period = decay_core(
        value, period_ewma, last_ts, exists, now, decay_rate_per_tick
    )
    new_value = decayed + local_counts
    new_last_ts = jnp.broadcast_to(jnp.asarray(now, jnp.int32), new_value.shape)
    return new_value, new_period, new_last_ts


def instance_count_estimate(replenishment_period_ticks, period_ewma):
    """``max(1, round(period / observed_sync_interval))``.

    (``RedisApproximateTokenBucketRateLimiter.cs:443``.) Elasticity is
    automatic: clients joining or leaving reshapes the estimate within
    ~O(period) with no membership protocol.
    """
    p = jnp.maximum(jnp.asarray(period_ewma, jnp.float32), 1.0)
    est = jnp.round(jnp.asarray(replenishment_period_ticks, jnp.float32) / p)
    return jnp.maximum(1.0, est).astype(jnp.int32)


def available_tokens(token_limit, global_score, instance_count, local_score):
    """The approximate limiter's local availability formula.

    ``max(0, ceil((token_limit - global_score) / instance_count) - local_score)``
    (``RedisApproximateTokenBucketRateLimiter.cs:37``) — each client
    self-limits to its estimated fair share of the global remainder, minus
    what it has already consumed locally since the last sync.
    """
    share = jnp.ceil(
        (jnp.asarray(token_limit, jnp.float32) - global_score)
        / jnp.maximum(jnp.asarray(instance_count, jnp.float32), 1.0)
    )
    avail = share - local_score
    return jnp.maximum(0.0, avail)


def retry_after_ticks(deficit, fill_rate_per_tick):
    """Time until ``deficit`` more tokens exist: ``deficit / fill_rate``.

    The reference computes ``deficit * FillRatePerSecond``
    (``RedisApproximateTokenBucketRateLimiter.cs:393-394``) which is
    dimensionally inverted — a known defect (SURVEY.md §2) we deliberately
    correct rather than replicate.
    """
    rate = jnp.maximum(jnp.asarray(fill_rate_per_tick, jnp.float32), 1e-30)
    return jnp.ceil(jnp.asarray(deficit, jnp.float32) / rate).astype(jnp.int32)


def sliding_window_advance(prev_count, curr_count, window_idx, exists, now,
                           window_ticks):
    """Advance a two-bucket sliding-window counter to the window containing ``now``.

    State per key: counts for the current and previous fixed windows plus the
    integer index of the current window. On advance by one window, current
    rolls into previous; on advance by 2+, both zero. Init-on-miss zeros.

    Returns ``(prev_count', curr_count', window_idx')``.
    """
    idx_now = (jnp.asarray(now, jnp.int32) // jnp.asarray(window_ticks, jnp.int32))
    idx_now = jnp.broadcast_to(idx_now, jnp.shape(window_idx)).astype(jnp.int32)
    # Clock-regression clamp: never move the window backwards.
    idx_now = jnp.maximum(idx_now, jnp.where(exists, window_idx, idx_now))
    steps = idx_now - jnp.where(exists, window_idx, idx_now)
    same = steps == 0
    one = steps == 1
    prev_new = jnp.where(same, prev_count, jnp.where(one, curr_count, 0.0))
    curr_new = jnp.where(same, curr_count, 0.0)
    prev_new = jnp.where(exists, prev_new, 0.0)
    curr_new = jnp.where(exists, curr_new, 0.0)
    return prev_new, curr_new, idx_now


def sliding_window_estimate(prev_count, curr_count, window_idx, now, window_ticks):
    """Weighted sliding-window estimate of consumption in the trailing window.

    ``curr + prev * (1 - frac_elapsed_of_current_window)`` — the standard
    interpolation (Cloudflare-style) giving a smooth approximation of a true
    sliding log at two counters per key.
    """
    # Compute the small in-window remainder in int32 FIRST: casting absolute
    # ticks to f32 loses precision past 2^24 ticks (~4.5 h uptime) and the
    # cancellation error would let the estimate over-admit.
    rem = jnp.asarray(now, jnp.int32) - window_idx * jnp.asarray(window_ticks, jnp.int32)
    frac = rem.astype(jnp.float32) / jnp.asarray(window_ticks, jnp.float32)
    frac = jnp.clip(frac, 0.0, 1.0)
    return curr_count + prev_count * (1.0 - frac)


def sliding_window_acquire(prev_count, curr_count, window_idx, exists, now,
                           counts, limit, window_ticks):
    """Atomic advance + estimate + all-or-nothing grant for the window variant.

    Grant iff ``estimate + count <= limit``; on grant the current-window
    counter absorbs ``count``. Same shape contract as
    :func:`refill_and_decrement`.

    Returns ``(prev', curr', idx', granted)``.
    """
    counts = jnp.asarray(counts, jnp.float32)
    prev_new, curr_new, idx_new = sliding_window_advance(
        prev_count, curr_count, window_idx, exists, now, window_ticks
    )
    est = sliding_window_estimate(prev_new, curr_new, idx_new, now, window_ticks)
    granted = est + counts <= jnp.asarray(limit, jnp.float32)
    curr_new = curr_new + jnp.where(granted, counts, 0.0)
    return prev_new, curr_new, idx_new, granted


def duplicate_prefix(slots, counts, valid):
    """Per-request prefix of earlier same-slot demand within one batch.

    ``prefix[i] = sum_{j < i, slots[j] == slots[i], valid[j]} counts[j]``.

    Used to serialize duplicate keys inside one batch conservatively: request
    ``i`` is granted only if the refilled balance covers ``prefix[i] +
    counts[i]``. Counting *all* earlier same-slot demand (granted or not) can
    only under-admit relative to true serial order — never over-admit —
    preserving atomicity (invariant 3) at batch granularity. The serving
    flush path additionally coalesces same-key requests gathered into one
    flush into grouped rows (``store._DeviceTable._flush`` →
    ``kernels.acquire_batch_packed_grouped``), so hot keys occupy one row
    instead of many and this in-kernel sort only serves paths that ship no
    host prefix (SURVEY.md §7 "Hard parts").

    Implemented as a stable sort by slot + segmented exclusive prefix sum —
    O(B log B) with O(B) memory traffic, cheap enough that the dup-safe
    kernel variant is simply always used (no per-flush host dup detection,
    no second compiled variant).
    """
    slots = jnp.asarray(slots)
    counts_f = jnp.asarray(counts, jnp.float32) * jnp.asarray(valid, jnp.float32)
    # Stable sort groups equal slots while preserving original request order
    # within each group, so an in-segment exclusive prefix is exactly the
    # "earlier same-slot demand" sum.
    order = jnp.argsort(slots, stable=True)
    c_sorted = counts_f[order]
    s_sorted = slots[order]
    seg_start = jnp.concatenate(
        [jnp.ones((1,), bool), s_sorted[1:] != s_sorted[:-1]]
    )
    # Segmented inclusive scan: sums reset at each segment boundary, so
    # accumulation (and float32 rounding) stays per-key — a whole-batch
    # cumsum would lose integer precision past 2^24 total demand and could
    # over-admit duplicates.
    def seg_combine(a, b):
        a_flag, a_val = a
        b_flag, b_val = b
        return a_flag | b_flag, jnp.where(b_flag, b_val, a_val + b_val)

    _, inc = jax.lax.associative_scan(seg_combine, (seg_start, c_sorted))
    prefix_sorted = inc - c_sorted
    return jnp.zeros_like(counts_f).at[order].set(prefix_sorted)
