"""Device-side operations: pure bucket math (L0) and jitted batch kernels (L1).

This package is the TPU equivalent of the reference's "store execution layer"
— the Lua scripts embedded in
``TokenBucket/RedisTokenBucketRateLimiter.cs:176-239`` and
``ApproximateTokenBucket/RedisApproximateTokenBucketRateLimiter.cs:216-271``.
Where Redis ran one Lua program atomically per key per call, we run one
jitted/Pallas kernel over a whole micro-batch of keys per launch.
"""
