"""L1 — jitted batch kernels over the keyed state table.

Each function here is the moral equivalent of one prepared Lua script in the
reference (``LuaScript.Prepare`` at ``RedisTokenBucketRateLimiter.cs:45``):
traced and compiled once, then invoked per micro-batch. Differences, by
design (TPU-first, SURVEY.md §7):

- One launch serves a whole batch of keys (the reference paid one network
  RTT per key per acquire, ``RedisTokenBucketRateLimiter.cs:63``).
- Bucket parameters (capacity, fill rate) are *operands*, not constants
  baked into compiled text, so one compilation serves every limiter config.
- State buffers are donated: steady-state operation re-uses the same HBM
  allocation, no copies of the (potentially multi-GB) table per launch.
- Atomicity (invariant 3) holds at batch granularity: XLA executes the
  whole gather → decide → scatter program as one serialized step over the
  state arrays, exactly as Redis serialized Lua scripts. Duplicate keys
  within one batch are serialized conservatively via
  :func:`~.bucket_math.duplicate_prefix` (never over-admit) — a sort-based
  O(B log B) pass cheap enough to run unconditionally.

State layout is structure-of-arrays in HBM — ``tokens: f32[N]``,
``last_ts: i32[N]``, ``exists: bool[N]`` — 9 bytes/key, so 10M keys ≈ 90 MB,
comfortably resident on one chip and shardable along N over a mesh.
"""

from __future__ import annotations

from functools import partial
from typing import NamedTuple

import jax
import jax.numpy as jnp

from distributedratelimiting.redis_tpu.ops import bucket_math as bm

__all__ = [
    "BucketState",
    "CounterState",
    "WindowState",
    "init_bucket_state",
    "init_counter_state",
    "init_window_state",
    "acquire_core",
    "acquire_batch",
    "acquire_batch_packed",
    "acquire_batch_packed_grouped",
    "acquire_scan",
    "acquire_scan_compact",
    "acquire_scan_compact_packed",
    "acquire_scan_compact_bits",
    "acquire_scan_packed24",
    "pack_slots24",
    "SLOT24_PAD",
    "acquire_hierarchical_packed",
    "debit_batch_packed",
    "sync_batch",
    "sync_batch_packed",
    "SemaState",
    "init_sema_state",
    "sema_batch_packed",
    "sweep_semas",
    "rebase_sema_epoch",
    "window_acquire_batch",
    "window_acquire_batch_packed",
    "window_acquire_batch_packed_grouped",
    "window_acquire_scan",
    "window_acquire_scan_compact",
    "sweep_expired",
    "sweep_counters",
    "sweep_windows",
    "rebase_bucket_epoch",
    "rebase_counter_epoch",
    "rebase_window_epoch",
    "peek_batch",
    "peek_batch_packed",
]


class BucketState(NamedTuple):
    """SoA token-bucket table ≙ the Redis hash ``{v, t}`` per key
    (``RedisTokenBucketRateLimiter.cs:210-230``), plus an occupancy mask
    standing in for Redis key existence."""

    tokens: jax.Array   # f32[N]
    last_ts: jax.Array  # i32[N]
    exists: jax.Array   # bool[N]


class CounterState(NamedTuple):
    """SoA decaying-counter table ≙ the Redis hash ``{v, p, t}``
    (``RedisApproximateTokenBucketRateLimiter.cs:265-268``)."""

    value: jax.Array    # f32[N] decaying throttle score
    period: jax.Array   # f32[N] EWMA of inter-sync interval (ticks)
    last_ts: jax.Array  # i32[N]
    exists: jax.Array   # bool[N]


class WindowState(NamedTuple):
    """SoA two-bucket sliding-window table (BASELINE config 4)."""

    prev_count: jax.Array  # f32[N]
    curr_count: jax.Array  # f32[N]
    window_idx: jax.Array  # i32[N]
    exists: jax.Array      # bool[N]


def init_bucket_state(n: int) -> BucketState:
    return BucketState(
        tokens=jnp.zeros((n,), jnp.float32),
        last_ts=jnp.zeros((n,), jnp.int32),
        exists=jnp.zeros((n,), bool),
    )


def init_counter_state(n: int) -> CounterState:
    return CounterState(
        value=jnp.zeros((n,), jnp.float32),
        period=jnp.zeros((n,), jnp.float32),
        last_ts=jnp.zeros((n,), jnp.int32),
        exists=jnp.zeros((n,), bool),
    )


def init_window_state(n: int) -> WindowState:
    return WindowState(
        prev_count=jnp.zeros((n,), jnp.float32),
        curr_count=jnp.zeros((n,), jnp.float32),
        window_idx=jnp.zeros((n,), jnp.int32),
        exists=jnp.zeros((n,), bool),
    )


def _valid_slots(slots, valid, size):
    """A row is live only if marked valid AND its slot is in range — an
    out-of-range slot with ``valid=True`` (e.g. a stale directory entry) must
    become a denied padding row, not a phantom grant against row 0/N-1."""
    return valid & (slots >= 0) & (slots < size)


def _gather_slots(slots, valid):
    """Clamp invalid/padding rows to slot 0 for the gather; their results are
    masked out and their scatters dropped."""
    return jnp.where(valid, slots, 0)


def _scatter_slots(slots, valid, size):
    """Padding rows map past the end of the table ⇒ dropped by
    ``mode='drop'`` scatters. (Negative indices would *wrap*, not drop.)"""
    return jnp.where(valid, slots, size)


def acquire_core(state: BucketState, slots, counts, valid, now, capacity,
                 fill_rate_per_tick, *, handle_duplicates: bool = True,
                 prefix=None):
    """Traceable core of :func:`acquire_batch` — also the per-shard block
    body under ``shard_map`` (where ``state`` is one shard's slice and
    ``slots`` are shard-local ids). See :func:`acquire_batch` for the full
    contract. ``prefix`` (f32[B]) overrides the in-kernel same-slot demand
    computation when the caller already knows it (the host batcher computes
    it exactly during batch assembly)."""
    valid = _valid_slots(slots, valid, state.tokens.shape[0])
    gs = _gather_slots(slots, valid)
    t_old = state.tokens[gs]
    ts_old = state.last_ts[gs]
    ex_old = state.exists[gs]

    counts_f = jnp.asarray(counts, jnp.float32)
    refilled = bm.refill_or_init(t_old, ts_old, ex_old, now, capacity,
                                 fill_rate_per_tick)

    if prefix is None and handle_duplicates:
        prefix = bm.duplicate_prefix(slots, counts, valid)
    elif prefix is None:
        prefix = jnp.zeros_like(counts_f)
    else:
        prefix = jnp.asarray(prefix, jnp.float32)

    granted = valid & (refilled >= prefix + counts_f)
    consumed = jnp.where(granted, counts_f, 0.0)
    remaining = jnp.where(valid, jnp.maximum(refilled - prefix - consumed, 0.0), 0.0)

    ss = _scatter_slots(slots, valid, state.tokens.shape[0])
    # Duplicates all write the identical refilled value (same now, same old
    # state), then consumption accumulates via scatter-add.
    new_tokens = state.tokens.at[ss].set(refilled, mode="drop")
    new_tokens = new_tokens.at[ss].add(-consumed, mode="drop")
    new_last_ts = state.last_ts.at[ss].set(
        jnp.asarray(now, jnp.int32), mode="drop"
    )
    new_exists = state.exists.at[ss].set(True, mode="drop")

    return BucketState(new_tokens, new_last_ts, new_exists), granted, remaining


@partial(jax.jit, donate_argnums=0, static_argnames=("handle_duplicates",))
def acquire_batch(state: BucketState, slots, counts, valid, now, capacity,
                  fill_rate_per_tick, *, handle_duplicates: bool = True):
    """Atomic batched refill-and-decrement — the exact-bucket Lua kernel
    (``RedisTokenBucketRateLimiter.cs:176-239``) over a micro-batch.

    Args:
      state: donated ``BucketState`` (buffers re-used in place).
      slots: i32[B] table indices (-1 or any out-of-range ⇒ padding row).
      counts: i32[B] requested permits (>= 0; 0 behaves as a probe).
      valid: bool[B] real-request mask.
      now: i32 scalar batch timestamp (host is time authority, invariant 1).
      capacity, fill_rate_per_tick: f32 scalars (operands, not constants).
      handle_duplicates: statically enables the same-slot serialization
        pass (sort-based, O(B log B)). On by default; False exists for
        ablation and for callers that guarantee duplicate-free batches.

    Returns:
      ``(new_state, granted bool[B], remaining f32[B])`` where ``remaining``
      is each request's post-decision view of its bucket (conservative under
      in-batch duplication) — the analogue of the script's ``new_v`` reply
      (``:238``).
    """
    return acquire_core(state, slots, counts, valid, now, capacity,
                        fill_rate_per_tick, handle_duplicates=handle_duplicates)


def _unpack_requests(packed):
    """Split the single packed i32[4, B] flush operand: row 0 = slots
    (negative ⇒ padding), row 1 = counts, row 2 = broadcast batch timestamp,
    row 3 = host-computed same-slot demand prefix. One packed array = ONE
    host→device transfer per flush; per-transfer latency on tunneled/remote
    TPU links is tens of ms, so operand count — not operand bytes — is what
    the hot path must minimize."""
    slots = packed[0]
    counts = packed[1]
    now = packed[2, 0]
    prefix = packed[3]
    valid = slots >= 0
    return slots, counts, valid, now, prefix


@partial(jax.jit, donate_argnums=0)
def acquire_batch_packed(state: BucketState, packed, capacity,
                         fill_rate_per_tick):
    """:func:`acquire_batch` with single-transfer operands and a single
    packed result: ``packed`` as in :func:`_unpack_requests`; ``capacity`` /
    ``fill_rate_per_tick`` are device-resident per-table constants (no
    per-flush scalar uploads). Returns ``(new_state, out f32[2, B])`` where
    ``out[0] = granted`` (0/1) and ``out[1] = remaining`` — one device→host
    transfer resolves the whole flush."""
    slots, counts, valid, now, prefix = _unpack_requests(packed)
    new_state, granted, remaining = acquire_core(
        state, slots, counts, valid, now, capacity, fill_rate_per_tick,
        prefix=prefix,
    )
    out = jnp.stack([granted.astype(jnp.float32), remaining])
    return new_state, out


@partial(jax.jit, donate_argnums=0)
def acquire_batch_packed_grouped(state: BucketState, packed, capacity,
                                 fill_rate_per_tick):
    """Coalesced-duplicates flush kernel: one row per ``(key, count)``
    GROUP instead of one row per request (SURVEY.md §7 "Hard parts" —
    Zipf hot keys hammering one slot must not eat the whole batch).

    ``packed i32[5, B]``: row 0 slots (-1 ⇒ padding), row 1 per-request
    count ``c``, row 2 broadcast batch timestamp, row 3 host-computed
    same-slot demand prefix (earlier groups' total integer demand), row 4
    group size ``n`` (number of identical requests).

    Grant rule — exactly the per-row conservative serialization, closed
    over ``n`` identical requests: the first ``n_granted = clamp(floor(
    (refilled − prefix) / c), 0, n)`` members are granted (``c == 0``
    probe groups grant all ``n``, consuming nothing). Consumption is
    ``n_granted · c``, so a group decision is bit-identical to ``n``
    per-row decisions with cumulative prefixes.

    Returns ``(new_state, out f32[2, B])``: ``out[0] = n_granted`` per
    group, ``out[1] = post-consumption remaining`` (every member's view).
    """
    slots = packed[0]
    counts = packed[1]
    now = packed[2, 0]
    prefix = jnp.asarray(packed[3], jnp.float32)
    n_reqs = packed[4]
    size = state.tokens.shape[0]
    valid = _valid_slots(slots, slots >= 0, size)
    gs = _gather_slots(slots, valid)

    refilled = bm.refill_or_init(state.tokens[gs], state.last_ts[gs],
                                 state.exists[gs], now, capacity,
                                 fill_rate_per_tick)
    c = jnp.asarray(counts, jnp.float32)
    n = jnp.asarray(n_reqs, jnp.float32)
    avail = refilled - prefix
    n_granted = jnp.where(
        c > 0,
        jnp.clip(jnp.floor(avail / jnp.maximum(c, 1.0)), 0.0, n),
        # c == 0 probe group: granted iff the balance covers the prefix —
        # the same `refilled >= prefix + 0` rule as the per-row kernel.
        jnp.where(avail >= 0, n, 0.0),
    )
    n_granted = jnp.where(valid, n_granted, 0.0)
    consumed = n_granted * c
    remaining = jnp.where(valid, jnp.maximum(avail - consumed, 0.0), 0.0)

    ss = _scatter_slots(slots, valid, size)
    new_tokens = state.tokens.at[ss].set(refilled, mode="drop")
    new_tokens = new_tokens.at[ss].add(-consumed, mode="drop")
    new_last_ts = state.last_ts.at[ss].set(jnp.asarray(now, jnp.int32),
                                           mode="drop")
    new_exists = state.exists.at[ss].set(True, mode="drop")
    out = jnp.stack([n_granted, remaining])
    return BucketState(new_tokens, new_last_ts, new_exists), out


@partial(jax.jit, donate_argnums=0, static_argnames=("handle_duplicates",))
def acquire_scan(state: BucketState, slots_k, counts_k, valid_k, nows_k,
                 capacity, fill_rate_per_tick, *,
                 handle_duplicates: bool = True):
    """Pipelined dispatch: K micro-batches decided in ONE kernel launch via
    ``lax.scan`` — amortizes launch overhead when the host has several
    flushes queued. Semantics are identical to K sequential
    :func:`acquire_batch` calls: each scanned batch keeps its own ``now``
    operand (``nows_k[k]``), preserving the one-timestamp-per-batch
    time-authority property.

    Shapes: ``slots_k/counts_k/valid_k: [K, B]``, ``nows_k: i32[K]``.
    Returns ``(new_state, granted [K, B], remaining [K, B])``.
    """

    def body(st, xs):
        slots, counts, valid, now = xs
        st, granted, remaining = acquire_core(
            st, slots, counts, valid, now, capacity, fill_rate_per_tick,
            handle_duplicates=handle_duplicates,
        )
        return st, (granted, remaining)

    state, (granted, remaining) = jax.lax.scan(
        body, state, (slots_k, counts_k, valid_k, nows_k)
    )
    return state, granted, remaining


@partial(jax.jit, donate_argnums=0, static_argnames=("handle_duplicates",))
def acquire_scan_compact(state: BucketState, slots_k, counts_k, nows_k,
                         capacity, fill_rate_per_tick, *,
                         handle_duplicates: bool = True):
    """Transfer-minimal scanned dispatch for the throughput path.

    Measured on tunneled TPU: the decision kernel itself runs at ~3.3B
    decisions/s once operands are resident — the pipeline is entirely
    host→device *transfer*-bound, and transfers overlap across queued
    dispatches, so sustained throughput ≈ link bandwidth / bytes-per-
    decision. This variant ships 5 bytes/decision (i32 slot + u8 count;
    validity is ``slots >= 0``, so no mask array travels) versus 9-16 for
    the split/packed layouts. The in-kernel duplicate sort is kept ON by
    default — its device cost is noise next to the transfer cost, and it
    preserves invariant 3 exactly.

    ``counts_k: u8[K, B]`` caps per-request permits at 255 on this path;
    larger requests belong on the packed serving path
    (:func:`acquire_batch_packed`, i32 counts).

    Shapes: ``slots_k: i32[K, B]``, ``counts_k: u8[K, B]``,
    ``nows_k: i32[K]``. Returns ``(new_state, granted bool[K, B],
    remaining f32[K, B])``.
    """

    def body(st, xs):
        slots, counts, now = xs
        st, granted, remaining = acquire_core(
            st, slots, counts.astype(jnp.int32), slots >= 0, now, capacity,
            fill_rate_per_tick, handle_duplicates=handle_duplicates,
        )
        return st, (granted, remaining)

    state, (granted, remaining) = jax.lax.scan(
        body, state, (slots_k, counts_k, nows_k)
    )
    return state, granted, remaining


@partial(jax.jit, donate_argnums=0, static_argnames=("handle_duplicates",))
def acquire_scan_compact_packed(state: BucketState, slots_k, counts_k,
                                nows_k, capacity, fill_rate_per_tick, *,
                                handle_duplicates: bool = True):
    """:func:`acquire_scan_compact` with a SINGLE packed result array.

    Device→host fetches on tunneled links are round-trip-bound (~tens of
    ms each regardless of size), so the bulk serving path must resolve a
    whole call with ONE fetch: ``out f32[K, 2, B]`` stacks ``granted``
    (0/1, row 0) and ``remaining`` (row 1) per scanned batch. Same
    decision semantics as the unpacked variant.

    Returns ``(new_state, out f32[K, 2, B])``.
    """

    def body(st, xs):
        slots, counts, now = xs
        st, granted, remaining = acquire_core(
            st, slots, counts.astype(jnp.int32), slots >= 0, now, capacity,
            fill_rate_per_tick, handle_duplicates=handle_duplicates,
        )
        return st, jnp.stack([granted.astype(jnp.float32), remaining])

    state, out = jax.lax.scan(body, state, (slots_k, counts_k, nows_k))
    return state, out


@partial(jax.jit, donate_argnums=0, static_argnames=("handle_duplicates",))
def acquire_scan_compact_bits(state: BucketState, slots_k, counts_k,
                              nows_k, capacity, fill_rate_per_tick, *,
                              handle_duplicates: bool = True):
    """Verdict-only scanned dispatch: grants return BIT-PACKED.

    For bulk callers that don't need per-request ``remaining`` (admission
    gates), the result shrinks from 8 bytes/decision to 1 *bit*/decision —
    ``out u8[K, B//8]``, little-endian bit order (host side:
    ``np.unpackbits(..., bitorder="little")``). On tunneled links this
    turns the device→host fetch from the dominant cost into noise.
    Requires ``B % 8 == 0`` (every batch size here is a power of two).

    Returns ``(new_state, grant_bits u8[K, B//8])``.
    """

    def body(st, xs):
        slots, counts, now = xs
        st, granted, _ = acquire_core(
            st, slots, counts.astype(jnp.int32), slots >= 0, now, capacity,
            fill_rate_per_tick, handle_duplicates=handle_duplicates,
        )
        bits = (granted.reshape(-1, 8).astype(jnp.uint8)
                << jnp.arange(8, dtype=jnp.uint8)).sum(
                    axis=1, dtype=jnp.uint8)
        return st, bits

    state, out = jax.lax.scan(body, state, (slots_k, counts_k, nows_k))
    return state, out


def _unpack_compact5(fused):
    """Device-side unpack of the :func:`pack_compact5` layout: LE i32 slot
    from bytes 0-3 (int32 bit-ops land -1 padding exactly via the sign bit
    in ``<<24``), count from byte 4."""
    p = fused.astype(jnp.int32)
    slots_k = (p[..., 0] | (p[..., 1] << 8) | (p[..., 2] << 16)
               | (p[..., 3] << 24))
    return slots_k, p[..., 4]


@partial(jax.jit, donate_argnums=0, static_argnames=("handle_duplicates",))
def acquire_scan_fused_bits(state: BucketState, fused, nows_k, capacity,
                            fill_rate_per_tick, *,
                            handle_duplicates: bool = True):
    """The bulk serving path's minimum-transfer dispatch: ONE fused
    operand up (:func:`pack_compact5`, 5 bytes/decision), ONE bit-packed
    result down (1 bit/decision) — per-transfer floors on tunneled links
    make the transfer COUNT matter as much as the bytes (RESULTS.md r04).

    Returns ``(new_state, grant_bits u8[K, B//8])`` (little-endian bit
    order, ``B % 8 == 0``)."""
    slots_k, counts_k = _unpack_compact5(fused)

    def body(st, xs):
        slots, counts, now = xs
        st, granted, _ = acquire_core(
            st, slots, counts, slots >= 0, now, capacity,
            fill_rate_per_tick, handle_duplicates=handle_duplicates,
        )
        bits = (granted.reshape(-1, 8).astype(jnp.uint8)
                << jnp.arange(8, dtype=jnp.uint8)).sum(
                    axis=1, dtype=jnp.uint8)
        return st, bits

    state, out = jax.lax.scan(body, state, (slots_k, counts_k, nows_k))
    return state, out


@partial(jax.jit, donate_argnums=0, static_argnames=("handle_duplicates",))
def acquire_scan_fused_packed(state: BucketState, fused, nows_k, capacity,
                              fill_rate_per_tick, *,
                              handle_duplicates: bool = True):
    """Fused-input variant of :func:`acquire_scan_compact_packed`: one
    operand up, one ``f32[K, 2, B]`` result down (row 0 grants, row 1
    remaining)."""
    slots_k, counts_k = _unpack_compact5(fused)

    def body(st, xs):
        slots, counts, now = xs
        st, granted, remaining = acquire_core(
            st, slots, counts, slots >= 0, now, capacity,
            fill_rate_per_tick, handle_duplicates=handle_duplicates,
        )
        return st, jnp.stack([granted.astype(jnp.float32), remaining])

    state, out = jax.lax.scan(body, state, (slots_k, counts_k, nows_k))
    return state, out


#: Padding sentinel for the 24-bit packed slot layout (all-ones 24 bits).
SLOT24_PAD = (1 << 24) - 1


def pack_compact5(slots, counts):
    """Host-side packing for :func:`acquire_scan_compact_fused`: i32 slot
    ids (-1 = padding) + u8 counts → little-endian u8[..., 5] (bytes 0-3
    the slot, byte 4 the count). One array per dispatch: on tunneled links
    each host→device transfer pays a per-transfer floor on top of
    bandwidth, so the 5-byte layout must travel as ONE operand — shipping
    slots and counts separately halves the sustained rate (measured; see
    benchmarks/RESULTS.md round-4 notes)."""
    import numpy as np

    slots = np.asarray(slots, np.int32)
    out = np.empty((*slots.shape, 5), np.uint8)
    out[..., :4] = slots.astype("<i4").view(np.uint8).reshape(
        *slots.shape, 4)
    out[..., 4] = counts
    return out


def pack_slots24(slots):
    """Host-side packing for :func:`acquire_scan_packed24`: i32 slot ids
    (or ``SLOT24_PAD`` for padding rows) → little-endian u8[..., 3].
    Vectorized numpy; ~0.8ms for a [32, 8192] stage — off the device
    critical path (staging overlaps dispatches)."""
    import numpy as np

    slots = np.asarray(slots)
    if slots.size and (slots.min() < 0 or slots.max() > SLOT24_PAD):
        # Out-of-range ids would silently truncate to SOME in-range slot —
        # debiting an unrelated key's bucket. Fail at pack time instead.
        raise ValueError(
            f"slot ids must be within [0, {SLOT24_PAD}] (SLOT24_PAD = "
            "padding); use acquire_scan_compact for larger tables"
        )
    out = np.empty((*slots.shape, 3), np.uint8)
    out[..., 0] = slots & 0xFF
    out[..., 1] = (slots >> 8) & 0xFF
    out[..., 2] = (slots >> 16) & 0xFF
    return out


@partial(jax.jit, donate_argnums=0, static_argnames=("handle_duplicates",))
def acquire_scan_compact_fused(state: BucketState, fused, nows_k, capacity,
                               fill_rate_per_tick, *,
                               handle_duplicates: bool = True):
    """:func:`acquire_scan_compact` with slots + counts fused into ONE
    operand array: ``fused u8[K, B, 5]`` from :func:`pack_compact5`.
    Decision semantics identical; transfer count per dispatch drops from
    two arrays to one, which on per-transfer-floor-bound links (the
    tunneled TPU) roughly doubles the sustained rate of the mixed-count
    path. Padding rows carry slot -1 (all-ones bytes 0-3).

    Returns ``(new_state, granted bool[K, B], remaining f32[K, B])``.
    """
    slots_k, counts_k = _unpack_compact5(fused)

    def body(st, xs):
        slots, counts, now = xs
        st, granted, remaining = acquire_core(
            st, slots, counts, slots >= 0, now, capacity,
            fill_rate_per_tick, handle_duplicates=handle_duplicates,
        )
        return st, (granted, remaining)

    state, (granted, remaining) = jax.lax.scan(
        body, state, (slots_k, counts_k, nows_k)
    )
    return state, granted, remaining


@partial(jax.jit, donate_argnums=0, static_argnames=("handle_duplicates",))
def acquire_scan_packed24(state: BucketState, packed, nows_k, capacity,
                          fill_rate_per_tick, *,
                          handle_duplicates: bool = True):
    """Minimum-transfer scanned dispatch: 3 bytes per decision.

    The serving pipeline on remote/tunneled TPU links is host→device
    transfer-bound with a sharp sustained-rate cliff above ~1MB per
    dispatch (measured; see benchmarks/RESULTS.md), so the headline
    throughput path packs each unit-permit request into a 24-bit slot id:
    ``packed: u8[K, B, 3]`` little-endian, :data:`SLOT24_PAD` = padding.
    Requires ``n_slots < 2**24 - 1`` (16.7M keys/table — the 10M-key
    BASELINE target fits; larger tables use :func:`acquire_scan_compact`).

    Every request asks exactly 1 permit — the canonical rate-limit
    request. Mixed-count batches belong on the compact or packed paths.
    Duplicate serialization stays ON by default: device compute is noise
    next to transfer cost, and invariant 3 holds exactly.

    Returns ``(new_state, granted bool[K, B], remaining f32[K, B])``.
    """
    p = packed.astype(jnp.int32)
    slots_k = p[..., 0] | (p[..., 1] << 8) | (p[..., 2] << 16)

    def body(st, xs):
        slots, now = xs
        valid = slots != SLOT24_PAD
        st, granted, remaining = acquire_core(
            st, slots, jnp.ones_like(slots), valid, now, capacity,
            fill_rate_per_tick, handle_duplicates=handle_duplicates,
        )
        return st, (granted, remaining)

    state, (granted, remaining) = jax.lax.scan(
        body, state, (slots_k, nows_k)
    )
    return state, granted, remaining


@partial(jax.jit, donate_argnums=0,
         static_argnames=("handle_duplicates", "interpolate"))
def window_acquire_scan(state: WindowState, slots_k, counts_k, valid_k,
                        nows_k, limit, window_ticks, *,
                        handle_duplicates: bool = True,
                        interpolate: bool = True):
    """Pipelined sliding-window dispatch: K micro-batches in ONE launch via
    ``lax.scan`` — the window analogue of :func:`acquire_scan`, with the
    same per-batch ``now`` time-authority property. ``interpolate=False``
    gives fixed-window semantics."""

    def body(st, xs):
        slots, counts, valid, now = xs
        st, granted, remaining = _window_acquire_core(
            st, slots, counts, valid, now, limit, window_ticks,
            handle_duplicates=handle_duplicates, interpolate=interpolate,
        )
        return st, (granted, remaining)

    state, (granted, remaining) = jax.lax.scan(
        body, state, (slots_k, counts_k, valid_k, nows_k)
    )
    return state, granted, remaining


@partial(jax.jit, donate_argnums=0,
         static_argnames=("handle_duplicates", "interpolate"))
def window_acquire_scan_fused_bits(state: WindowState, fused, nows_k,
                                   limit, window_ticks, *,
                                   handle_duplicates: bool = True,
                                   interpolate: bool = True):
    """Verdict-only fused window dispatch: 1 bit/decision down (the window
    analogue of :func:`acquire_scan_fused_bits`; ``B % 8 == 0``)."""
    slots_k, counts_k = _unpack_compact5(fused)

    def body(st, xs):
        slots, counts, now = xs
        st, granted, _ = _window_acquire_core(
            st, slots, counts, slots >= 0, now, limit, window_ticks,
            handle_duplicates=handle_duplicates, interpolate=interpolate,
        )
        bits = (granted.reshape(-1, 8).astype(jnp.uint8)
                << jnp.arange(8, dtype=jnp.uint8)).sum(
                    axis=1, dtype=jnp.uint8)
        return st, bits

    state, out = jax.lax.scan(body, state, (slots_k, counts_k, nows_k))
    return state, out


@partial(jax.jit, donate_argnums=0,
         static_argnames=("handle_duplicates", "interpolate"))
def window_acquire_scan_fused_packed(state: WindowState, fused, nows_k,
                                     limit, window_ticks, *,
                                     handle_duplicates: bool = True,
                                     interpolate: bool = True):
    """The window bulk path's minimum-transfer dispatch: ONE fused operand
    up (:func:`pack_compact5`), ONE ``f32[K, 2, B]`` result down (row 0
    grants, row 1 remaining) — the window analogue of
    :func:`acquire_scan_fused_packed`. ``interpolate=False`` = fixed
    windows."""
    slots_k, counts_k = _unpack_compact5(fused)

    def body(st, xs):
        slots, counts, now = xs
        st, granted, remaining = _window_acquire_core(
            st, slots, counts, slots >= 0, now, limit, window_ticks,
            handle_duplicates=handle_duplicates, interpolate=interpolate,
        )
        return st, jnp.stack([granted.astype(jnp.float32), remaining])

    state, out = jax.lax.scan(body, state, (slots_k, counts_k, nows_k))
    return state, out


@partial(jax.jit, donate_argnums=0, static_argnames=("handle_duplicates",))
def window_acquire_scan_compact(state: WindowState, slots_k, counts_k,
                                nows_k, limit, window_ticks, *,
                                handle_duplicates: bool = True):
    """Transfer-minimal scanned sliding-window dispatch — the window
    analogue of :func:`acquire_scan_compact`: 5 bytes/decision (i32 slot +
    u8 count), validity implied by slot sign, per-batch ``now`` operands.
    Same transfer-cliff rationale (see benchmarks/RESULTS.md)."""

    def body(st, xs):
        slots, counts, now = xs
        st, granted, remaining = _window_acquire_core(
            st, slots, counts.astype(jnp.int32), slots >= 0, now, limit,
            window_ticks, handle_duplicates=handle_duplicates,
        )
        return st, (granted, remaining)

    state, (granted, remaining) = jax.lax.scan(
        body, state, (slots_k, counts_k, nows_k)
    )
    return state, granted, remaining


@partial(jax.jit, donate_argnums=0)
def sync_batch(state: CounterState, slots, local_counts, valid, now,
               decay_rate_per_tick):
    """Batched decaying-counter sync — the approximate-bucket Lua kernel
    (``RedisApproximateTokenBucketRateLimiter.cs:216-271``) over a batch of
    global counters.

    One row per counter per flush (the host aggregates each limiter's local
    score before syncing, so duplicate slots do not occur in practice; if
    they do, decayed-value writes coincide and count adds accumulate, which
    over-counts only the EWMA, never the score).

    Returns ``(new_state, global_scores f32[B], period_ewmas f32[B])`` — the
    script's ``{new_v, new_p}`` reply (``:270``).
    """
    return _sync_core(state, slots, local_counts, valid, now,
                      decay_rate_per_tick)


def _sync_core(state: CounterState, slots, local_counts, valid, now,
               decay_rate_per_tick):
    valid = _valid_slots(slots, valid, state.value.shape[0])
    gs = _gather_slots(slots, valid)
    v_old = state.value[gs]
    p_old = state.period[gs]
    ts_old = state.last_ts[gs]
    ex_old = state.exists[gs]

    counts_f = jnp.asarray(local_counts, jnp.float32)
    decayed, new_period = bm.decay_core(
        v_old, p_old, ts_old, ex_old, now, decay_rate_per_tick
    )
    new_value = decayed + counts_f

    ss = _scatter_slots(slots, valid, state.value.shape[0])
    value_arr = state.value.at[ss].set(decayed, mode="drop")
    value_arr = value_arr.at[ss].add(counts_f * valid, mode="drop")
    period_arr = state.period.at[ss].set(new_period, mode="drop")
    ts_arr = state.last_ts.at[ss].set(jnp.asarray(now, jnp.int32), mode="drop")
    ex_arr = state.exists.at[ss].set(True, mode="drop")

    return CounterState(value_arr, period_arr, ts_arr, ex_arr), new_value, new_period


@partial(jax.jit, donate_argnums=0,
         static_argnames=("handle_duplicates", "interpolate"))
def window_acquire_batch(state: WindowState, slots, counts, valid, now, limit,
                         window_ticks, *, handle_duplicates: bool = True,
                         interpolate: bool = True):
    """Batched sliding-window acquire (BASELINE config 4).

    Same contract as :func:`acquire_batch`; grant iff the interpolated
    trailing-window estimate plus this request stays within ``limit``
    (``interpolate=False`` = fixed-window: current-window count only).
    """
    return _window_acquire_core(state, slots, counts, valid, now, limit,
                                window_ticks,
                                handle_duplicates=handle_duplicates,
                                interpolate=interpolate)


def _window_acquire_core(state: WindowState, slots, counts, valid, now, limit,
                         window_ticks, *, handle_duplicates: bool = True,
                         prefix=None, interpolate: bool = True):
    """``interpolate=True`` → sliding window (trailing-window estimate);
    ``False`` → fixed window (current-window count only — the
    ``FixedWindowRateLimiter`` family member's semantics). Same state,
    advance, atomicity, and sweep machinery either way."""
    valid = _valid_slots(slots, valid, state.prev_count.shape[0])
    gs = _gather_slots(slots, valid)
    prev_old = state.prev_count[gs]
    curr_old = state.curr_count[gs]
    idx_old = state.window_idx[gs]
    ex_old = state.exists[gs]

    counts_f = jnp.asarray(counts, jnp.float32)
    prev_new, curr_new, idx_new = bm.sliding_window_advance(
        prev_old, curr_old, idx_old, ex_old, now, window_ticks
    )
    if interpolate:
        est = bm.sliding_window_estimate(prev_new, curr_new, idx_new, now,
                                         window_ticks)
    else:
        est = curr_new

    if prefix is None and handle_duplicates:
        prefix = bm.duplicate_prefix(slots, counts, valid)
    elif prefix is None:
        prefix = jnp.zeros_like(counts_f)
    else:
        prefix = jnp.asarray(prefix, jnp.float32)

    granted = valid & (est + prefix + counts_f <= jnp.asarray(limit, jnp.float32))
    consumed = jnp.where(granted, counts_f, 0.0)
    remaining = jnp.where(
        valid,
        jnp.maximum(jnp.asarray(limit, jnp.float32) - est - prefix - consumed, 0.0),
        0.0,
    )

    ss = _scatter_slots(slots, valid, state.prev_count.shape[0])
    prev_arr = state.prev_count.at[ss].set(prev_new, mode="drop")
    curr_arr = state.curr_count.at[ss].set(curr_new, mode="drop")
    curr_arr = curr_arr.at[ss].add(consumed, mode="drop")
    idx_arr = state.window_idx.at[ss].set(idx_new, mode="drop")
    ex_arr = state.exists.at[ss].set(True, mode="drop")

    return WindowState(prev_arr, curr_arr, idx_arr, ex_arr), granted, remaining


class SemaState(NamedTuple):
    """SoA concurrency-semaphore table: ``active`` = permits currently
    held per key. No reference analogue (the reference implements only
    token buckets); this backs the ``ConcurrencyLimiter`` member of the
    ``System.Threading.RateLimiting`` family, whose leases RETURN permits
    on dispose."""

    active: jax.Array   # i32[N] held permits
    last_ts: jax.Array  # i32[N] last touch (for idle-slot sweeps)
    exists: jax.Array   # bool[N]


def init_sema_state(n: int) -> SemaState:
    return SemaState(
        active=jnp.zeros((n,), jnp.int32),
        last_ts=jnp.zeros((n,), jnp.int32),
        exists=jnp.zeros((n,), bool),
    )


@partial(jax.jit, donate_argnums=0)
def sema_batch_packed(state: SemaState, packed):
    """Atomic batched semaphore update. ``packed: i32[4, B]`` — row 0
    slots (-1 padding), row 1 signed deltas (+n acquire / -n release),
    row 2 per-row permit limits, row 3 the batch timestamp.

    Acquire (+n) grants iff ``active + same-slot-earlier-demand + n <=
    limit`` — all-or-nothing, duplicates serialized conservatively like
    the token-bucket kernels (invariant 3 at batch granularity). Release
    (-n) always applies, clamped at 0 (over-release is a caller bug the
    store must survive, not amplify). Init-on-miss: a slot with
    ``exists=False`` starts at 0 held.

    Returns ``(new_state, out f32[2, B])``: row 0 ok (0/1 — releases are
    always 1), row 1 post-op active count as seen by that row — computed
    from the same serialization prefix that admitted the row, so
    duplicate acquire rows read their own serialized value, not the
    post-batch total.

    Caller contract: a batch must not mix releases with other rows of
    the SAME slot — the state write clamps the slot's NET delta at zero,
    which would let an over-release swallow a granted acquire's permit
    (`DeviceBucketStore.concurrency_acquire_many` routes such rows
    through sequential single-op dispatches instead).
    """
    slots = packed[0]
    deltas = packed[1]
    limits = packed[2]
    now = packed[3, 0]
    valid = _valid_slots(slots, slots >= 0, state.active.shape[0])
    gs = _gather_slots(slots, valid)
    active_old = jnp.where(state.exists[gs], state.active[gs], 0)

    # Serialize same-slot rows: earlier acquires reserve, earlier releases
    # free. Net prefix = sum of earlier applied deltas, conservatively
    # approximated by granting against (active + prefix of earlier GRANTS).
    # Two-pass exact serialization would need a scan over the batch; the
    # conservative form never over-admits: treat all earlier acquires in
    # the batch as granted, ignore earlier releases for admission.
    acq = jnp.maximum(deltas, 0)
    prefix = bm.duplicate_prefix(slots, acq, valid)

    is_release = deltas < 0
    # f32 comparison (exact to 2^24 — far above any real permit limit)
    # avoids int32 overflow when a batch's worth of acquires sums large.
    fits = (active_old.astype(jnp.float32) + prefix.astype(jnp.float32)
            + acq.astype(jnp.float32)) <= limits.astype(jnp.float32)
    ok = valid & (is_release | fits)
    applied = jnp.where(ok, deltas, 0)

    ss = _scatter_slots(slots, valid, state.active.shape[0])
    active_arr = state.active.at[ss].set(active_old, mode="drop")
    active_arr = active_arr.at[ss].add(applied, mode="drop")
    active_arr = jnp.maximum(active_arr, 0)
    # delta == 0 is a read-only probe: it must not allocate the slot or
    # refresh its TTL (a monitoring poll would otherwise keep dead slots
    # alive past the sweep forever).
    touch = _scatter_slots(slots, valid & (deltas != 0),
                           state.active.shape[0])
    ts_arr = state.last_ts.at[touch].set(jnp.asarray(now, jnp.int32),
                                         mode="drop")
    ex_arr = state.exists.at[touch].set(True, mode="drop")

    # Per-row post-op view: active + earlier same-slot APPLIED deltas +
    # this row's applied delta, clamped like the state itself. Admission
    # used the conservative demand prefix above (earlier acquires count
    # whether granted or not — no scan needed, never over-admits), but
    # the REPORTED count sums only what actually landed, so a denied
    # row can never read an impossible held value above the limit. For
    # a single row per slot this equals the slot's new value.
    applied_prefix = bm.duplicate_prefix(slots, applied, valid)
    after = jnp.maximum(
        active_old.astype(jnp.float32) + applied_prefix.astype(jnp.float32)
        + applied.astype(jnp.float32), 0.0)
    out = jnp.stack([
        ok.astype(jnp.float32),
        jnp.where(valid, after, 0.0),
    ])
    return SemaState(active_arr, ts_arr, ex_arr), out


@partial(jax.jit, donate_argnums=0)
def sweep_semas(state: SemaState, now):
    """Reclaim idle semaphore slots: zero held permits AND untouched past
    the global-counter TTL (86400 s). A slot with permits still held is
    never swept — leaked permits are an operator problem (`active` reset
    requires an explicit release), not something expiry may silently
    forgive."""
    expired = state.exists & (state.active <= 0) & (
        bm.elapsed_ticks(now, state.last_ts) >= bm.GLOBAL_COUNTER_TTL_TICKS
    )
    return SemaState(
        state.active, state.last_ts, state.exists & ~expired
    ), expired


@partial(jax.jit, donate_argnums=0)
def rebase_sema_epoch(state: SemaState, offset_ticks):
    return SemaState(
        state.active,
        jnp.maximum(state.last_ts - offset_ticks, 0),
        state.exists,
    )


@partial(jax.jit, donate_argnums=(0, 1))
def acquire_hierarchical_packed(child_state: BucketState,
                                parent_state: BucketState, packed,
                                child_capacity, child_rate_per_tick,
                                parent_capacity, parent_rate_per_tick):
    """Fused two-level (tenant → key) weighted-cost admission — the
    token-denominated plane's kernel (runtime/admission.py, DESIGN.md
    §15): ONE launch gathers the child key row AND the parent tenant
    row, refills both, and grants iff BOTH levels admit, with
    both-or-neither state change (the "parent refund on child deny"
    contract, closed algebraically: neither side is debited unless the
    row is granted, and every touched slot still advances its refill
    timestamp exactly like a denied flat acquire would).

    ``packed i32[4, B]``: row 0 child slots (-1 ⇒ padding), row 1
    token costs, row 2 broadcast batch timestamp, row 3 parent slots.
    The two states are distinct tables (the store rejects identical
    child/parent configs — one donated buffer cannot be donated
    twice).

    Duplicate serialization is conservative on BOTH axes, mirroring
    the flat bulk paths' documented posture: the child prefix counts
    ALL earlier same-key demand; the parent prefix counts earlier
    same-tenant demand that the child level admitted (a
    child-admitted-but-parent-denied row still reserves ahead on its
    tenant within the batch). Exact on serial stores and whenever the
    in-call demand fits — the same latitude ``acquire_many``
    documents.

    Returns ``(child_state', parent_state', out f32[2, B])`` with
    ``out[0] = granted`` (0/1) and ``out[1] = min(child_remaining,
    parent_remaining)`` — each row's post-decision view of its binding
    constraint."""
    cslots = packed[0]
    counts = packed[1]
    now = packed[2, 0]
    pslots = packed[3]
    c_size = child_state.tokens.shape[0]
    p_size = parent_state.tokens.shape[0]
    valid = (_valid_slots(cslots, cslots >= 0, c_size)
             & _valid_slots(pslots, pslots >= 0, p_size))
    counts_f = jnp.asarray(counts, jnp.float32)

    cgs = _gather_slots(cslots, valid)
    pgs = _gather_slots(pslots, valid)
    c_ref = bm.refill_or_init(child_state.tokens[cgs],
                              child_state.last_ts[cgs],
                              child_state.exists[cgs], now,
                              child_capacity, child_rate_per_tick)
    p_ref = bm.refill_or_init(parent_state.tokens[pgs],
                              parent_state.last_ts[pgs],
                              parent_state.exists[pgs], now,
                              parent_capacity, parent_rate_per_tick)

    c_prefix = bm.duplicate_prefix(cslots, counts, valid)
    child_ok = valid & (c_ref >= c_prefix + counts_f)
    # Parent axis: only child-admitted demand reserves ahead (a row the
    # child already denied cannot double-charge its tenant's headroom).
    p_demand = jnp.where(child_ok, counts_f, 0.0)
    p_prefix = bm.duplicate_prefix(pslots, p_demand, valid)
    granted = child_ok & (p_ref >= p_prefix + counts_f)

    consumed = jnp.where(granted, counts_f, 0.0)
    c_rem = jnp.where(valid,
                      jnp.maximum(c_ref - c_prefix - consumed, 0.0), 0.0)
    p_rem = jnp.where(valid,
                      jnp.maximum(p_ref - p_prefix - consumed, 0.0), 0.0)
    remaining = jnp.minimum(c_rem, p_rem)

    css = _scatter_slots(cslots, valid, c_size)
    new_c_tokens = child_state.tokens.at[css].set(c_ref, mode="drop")
    new_c_tokens = new_c_tokens.at[css].add(-consumed, mode="drop")
    new_c_ts = child_state.last_ts.at[css].set(
        jnp.asarray(now, jnp.int32), mode="drop")
    new_c_exists = child_state.exists.at[css].set(True, mode="drop")

    pss = _scatter_slots(pslots, valid, p_size)
    new_p_tokens = parent_state.tokens.at[pss].set(p_ref, mode="drop")
    new_p_tokens = new_p_tokens.at[pss].add(-consumed, mode="drop")
    new_p_ts = parent_state.last_ts.at[pss].set(
        jnp.asarray(now, jnp.int32), mode="drop")
    new_p_exists = parent_state.exists.at[pss].set(True, mode="drop")

    out = jnp.stack([granted.astype(jnp.float32), remaining])
    return (BucketState(new_c_tokens, new_c_ts, new_c_exists),
            BucketState(new_p_tokens, new_p_ts, new_p_exists), out)


@partial(jax.jit, donate_argnums=0)
def debit_batch_packed(state: BucketState, packed, capacity,
                       fill_rate_per_tick):
    """Saturating bulk debit — the tier-0 replica reconciliation kernel.

    The native front-end's tier-0 cache admits permits locally and drains
    the accumulated counts here in one launch: refill exactly like
    :func:`acquire_batch_packed`, then subtract each row's drained amount
    clamped at zero. This is :func:`sync_batch`'s decaying-counter
    semantic mirrored onto the bucket table (``score == capacity −
    tokens``: the counter's decay-then-add is the bucket's
    refill-then-subtract, both saturating), which keeps ONE authority —
    the same table the exact fall-through path decides against — so
    tier-0 and per-request decisions reconcile without double-accounting.

    ``packed i32[3, B]``: row 0 slots (-1 ⇒ padding), row 1 the float32
    drained amounts bitcast to int32 (exact, like the counter-sync
    operand), row 2 the batch timestamp (store-stamped time,
    invariant 1). Duplicate slots are serialized conservatively via the
    demand prefix (callers pre-aggregate per key, so duplicates only
    arise from misuse and can at worst under-debit, never corrupt).

    Returns ``(new_state, out f32[2, B])``: row 0 the post-debit balance
    (each row's serialized view), row 1 the clamped shortfall — the part
    of the drained amount that found no tokens, i.e. the observed
    over-admission the sync pump surfaces as a gauge.
    """
    slots = packed[0]
    amounts = jax.lax.bitcast_convert_type(packed[1], jnp.float32)
    now = packed[2, 0]
    size = state.tokens.shape[0]
    valid = _valid_slots(slots, slots >= 0, size)
    gs = _gather_slots(slots, valid)
    refilled = bm.refill_or_init(state.tokens[gs], state.last_ts[gs],
                                 state.exists[gs], now, capacity,
                                 fill_rate_per_tick)
    prefix = bm.duplicate_prefix(slots, amounts, valid)
    avail = jnp.maximum(refilled - prefix, 0.0)
    applied = jnp.where(valid, jnp.minimum(amounts, avail), 0.0)
    shortfall = jnp.where(valid, amounts - applied, 0.0)
    remaining = jnp.where(valid, avail - applied, 0.0)

    ss = _scatter_slots(slots, valid, size)
    new_tokens = state.tokens.at[ss].set(refilled, mode="drop")
    new_tokens = new_tokens.at[ss].add(-applied, mode="drop")
    new_last_ts = state.last_ts.at[ss].set(jnp.asarray(now, jnp.int32),
                                           mode="drop")
    new_exists = state.exists.at[ss].set(True, mode="drop")
    out = jnp.stack([remaining, shortfall])
    return BucketState(new_tokens, new_last_ts, new_exists), out


@partial(jax.jit, donate_argnums=0)
def sync_batch_packed(state: CounterState, packed, decay_rate_per_tick):
    """:func:`sync_batch` with single-transfer operands/results. The
    counter-sync operand is i32[3, B] (unlike the acquire kernels' i32[4, B]
    — there is no duplicate-prefix row here): row 0 slots, row 1 the
    float32 local counts bitcast to int32 (exact — no quantization), row 2
    the timestamp. The reply is ``f32[2, B]`` = (global scores, period
    EWMAs), the Lua ``{new_v, new_p}`` pair in one readback."""
    slots = packed[0]
    local_counts = jax.lax.bitcast_convert_type(packed[1], jnp.float32)
    now = packed[2, 0]
    valid = slots >= 0
    new_state, scores, periods = _sync_core(
        state, slots, local_counts, valid, now, decay_rate_per_tick
    )
    return new_state, jnp.stack([scores, periods])


@partial(jax.jit, donate_argnums=0, static_argnames=("interpolate",))
def window_acquire_batch_packed(state: WindowState, packed, limit,
                                window_ticks, *, interpolate: bool = True):
    """:func:`window_acquire_batch` with the single-transfer operand/result
    convention of :func:`acquire_batch_packed`."""
    slots, counts, valid, now, prefix = _unpack_requests(packed)
    new_state, granted, remaining = _window_acquire_core(
        state, slots, counts, valid, now, limit, window_ticks,
        prefix=prefix, interpolate=interpolate,
    )
    out = jnp.stack([granted.astype(jnp.float32), remaining])
    return new_state, out


@partial(jax.jit, donate_argnums=0, static_argnames=("interpolate",))
def window_acquire_batch_packed_grouped(state: WindowState, packed, limit,
                                        window_ticks, *,
                                        interpolate: bool = True):
    """Coalesced-duplicates window flush — the window-table analogue of
    :func:`acquire_batch_packed_grouped` (same ``packed i32[5, B]``
    layout). Grant rule per group: ``n_granted = clamp(floor((limit −
    est − prefix) / c), 0, n)`` (``c == 0`` probes grant all ``n`` iff the
    window estimate plus prefix still fits the limit), bit-identical to
    ``n`` per-row decisions with cumulative prefixes.

    Returns ``(new_state, out f32[2, B])``: ``out[0] = n_granted``,
    ``out[1] = post-consumption remaining``.
    """
    slots = packed[0]
    counts = packed[1]
    now = packed[2, 0]
    prefix = jnp.asarray(packed[3], jnp.float32)
    n_reqs = packed[4]
    size = state.prev_count.shape[0]
    valid = _valid_slots(slots, slots >= 0, size)
    gs = _gather_slots(slots, valid)

    prev_new, curr_new, idx_new = bm.sliding_window_advance(
        state.prev_count[gs], state.curr_count[gs], state.window_idx[gs],
        state.exists[gs], now, window_ticks,
    )
    if interpolate:
        est = bm.sliding_window_estimate(prev_new, curr_new, idx_new, now,
                                         window_ticks)
    else:
        est = curr_new

    c = jnp.asarray(counts, jnp.float32)
    n = jnp.asarray(n_reqs, jnp.float32)
    avail = jnp.asarray(limit, jnp.float32) - est - prefix
    n_granted = jnp.where(
        c > 0,
        jnp.clip(jnp.floor(avail / jnp.maximum(c, 1.0)), 0.0, n),
        jnp.where(avail >= 0, n, 0.0),
    )
    n_granted = jnp.where(valid, n_granted, 0.0)
    consumed = n_granted * c
    remaining = jnp.where(valid, jnp.maximum(avail - consumed, 0.0), 0.0)

    ss = _scatter_slots(slots, valid, size)
    prev_arr = state.prev_count.at[ss].set(prev_new, mode="drop")
    curr_arr = state.curr_count.at[ss].set(curr_new, mode="drop")
    curr_arr = curr_arr.at[ss].add(consumed, mode="drop")
    idx_arr = state.window_idx.at[ss].set(idx_new, mode="drop")
    ex_arr = state.exists.at[ss].set(True, mode="drop")
    out = jnp.stack([n_granted, remaining])
    return WindowState(prev_arr, curr_arr, idx_arr, ex_arr), out


@partial(jax.jit, donate_argnums=0)
def sweep_expired(state: BucketState, now, capacity, fill_rate_per_tick):
    """TTL eviction pass — invariant 5 (state self-expiry, bounded memory).

    A slot whose bucket has been idle past its time-to-full-refill TTL
    (clamped ``[1s, 1yr]``, ``RedisTokenBucketRateLimiter.cs:234-235``) is
    indistinguishable from init-on-miss, so `exists` is simply cleared. One
    vectorized pass over the whole table; the host runs it on a slow cadence
    (it also bounds int32 tick staleness far below wraparound).

    Returns ``(new_state, freed bool[N])`` — `freed` lets the host directory
    reclaim slot ids.
    """
    ttl = bm.time_to_full_ttl(state.tokens, capacity, fill_rate_per_tick)
    expired = state.exists & (bm.elapsed_ticks(now, state.last_ts) >= ttl)
    new_exists = state.exists & ~expired
    return BucketState(state.tokens, state.last_ts, new_exists), expired


@jax.jit
def peek_batch(state: BucketState, slots, valid, now, capacity,
               fill_rate_per_tick):
    """Read-only availability estimate (``GetAvailablePermits`` support,
    invariant 7) — refill math applied without writing state back."""
    valid = _valid_slots(slots, valid, state.tokens.shape[0])
    gs = _gather_slots(slots, valid)
    refilled = bm.refill_or_init(
        state.tokens[gs], state.last_ts[gs], state.exists[gs], now, capacity,
        fill_rate_per_tick,
    )
    return jnp.where(valid, jnp.floor(refilled), 0.0)


@jax.jit
def peek_batch_packed(state: BucketState, packed, capacity,
                      fill_rate_per_tick):
    """:func:`peek_batch` with the packed operand convention (rows 1/3 of
    ``packed`` are ignored — peeks carry no counts)."""
    slots, _, valid, now, _ = _unpack_requests(packed)
    valid = _valid_slots(slots, valid, state.tokens.shape[0])
    gs = _gather_slots(slots, valid)
    refilled = bm.refill_or_init(
        state.tokens[gs], state.last_ts[gs], state.exists[gs], now, capacity,
        fill_rate_per_tick,
    )
    return jnp.where(valid, jnp.floor(refilled), 0.0)


@partial(jax.jit, donate_argnums=0)
def sweep_counters(state: CounterState, now):
    """TTL eviction for the decaying-counter table: fixed 86400 s TTL, the
    reference's ``EXPIRE`` on the global counter hash
    (``RedisApproximateTokenBucketRateLimiter.cs:268``)."""
    expired = state.exists & (
        bm.elapsed_ticks(now, state.last_ts) >= bm.GLOBAL_COUNTER_TTL_TICKS
    )
    return CounterState(
        state.value, state.period, state.last_ts, state.exists & ~expired
    ), expired


@partial(jax.jit, donate_argnums=0)
def sweep_windows(state: WindowState, now, window_ticks):
    """TTL eviction for the sliding-window table: a slot idle for two full
    windows carries no information (both counters would roll to zero)."""
    idx_now = jnp.asarray(now, jnp.int32) // jnp.asarray(window_ticks, jnp.int32)
    expired = state.exists & (idx_now - state.window_idx >= 2)
    return WindowState(
        state.prev_count, state.curr_count, state.window_idx,
        state.exists & ~expired,
    ), expired


@partial(jax.jit, donate_argnums=0)
def rebase_bucket_epoch(state: BucketState, offset_ticks):
    """Shift every timestamp back by ``offset_ticks`` — the host calls this
    (and rebases its clock epoch identically) before int32 tick time can
    overflow (~24 days of uptime at 1024 ticks/s). Elapsed values are
    invariant under the joint shift."""
    new_ts = jnp.where(
        state.exists,
        jnp.maximum(state.last_ts - jnp.asarray(offset_ticks, jnp.int32), 0),
        state.last_ts,
    )
    return BucketState(state.tokens, new_ts, state.exists)


@partial(jax.jit, donate_argnums=0)
def rebase_counter_epoch(state: CounterState, offset_ticks):
    new_ts = jnp.where(
        state.exists,
        jnp.maximum(state.last_ts - jnp.asarray(offset_ticks, jnp.int32), 0),
        state.last_ts,
    )
    return CounterState(state.value, state.period, new_ts, state.exists)


@partial(jax.jit, donate_argnums=0)
def rebase_window_epoch(state: WindowState, offset_windows):
    """Epoch rebase for window tables: indices shift by whole windows
    (``offset_windows = offset_ticks // window_ticks``, host-computed). The
    sub-window phase remainder introduces at most one window of boundary
    skew, once per rebase (~6 days) — without this the advance clamp would
    pin old indices forever and freeze those keys."""
    new_idx = jnp.where(
        state.exists,
        jnp.maximum(state.window_idx - jnp.asarray(offset_windows, jnp.int32), 0),
        state.window_idx,
    )
    return WindowState(state.prev_count, state.curr_count, new_idx, state.exists)
