"""Waiter-queue machinery for async acquires that can't be served instantly.

Semantics cloned (behavior, not code) from the reference's queue logic —
itself a faithful clone of .NET's in-memory ``TokenBucketRateLimiter``
(SURVEY.md §2 #5, ``RedisApproximateTokenBucketRateLimiter.cs:139-183,
462-501,515-557``):

- ``queue_limit`` is counted in **cumulative permits**, not waiter count
  (``:178``).
- ``OLDEST_FIRST``: a newcomer that would overflow the queue is rejected
  (``:159-163``). ``NEWEST_FIRST``: oldest entries are evicted (failed) to
  make room for the newcomer (``:143-158``).
- Waiters park on futures (≙ ``TaskCompletionSource``, ``:515-529``).
- Cancellation unwinds the queue accounting (``CancelQueueState``,
  ``:531-557``). The reference's drain loop *double-counts* consumption for
  waiters found cancelled after speculative grant (``:486-492`` — known
  defect, SURVEY.md §2); here cancelled waiters are detected before any
  consumption is applied, so the accounting bug cannot occur by
  construction (regression-tested).
- Disposal fails all queued waiters; they never hang (``:291-298``).
"""

from __future__ import annotations

import asyncio
import enum
from typing import Callable, Iterable

from distributedratelimiting.redis_tpu.utils.deque import Deque

__all__ = ["QueueProcessingOrder", "Registration", "WaiterQueue"]


class QueueProcessingOrder(enum.Enum):
    """≙ ``System.Threading.RateLimiting.QueueProcessingOrder``."""

    OLDEST_FIRST = "oldest_first"
    NEWEST_FIRST = "newest_first"


class Registration:
    """One parked waiter (≙ ``RequestRegistration`` struct ``:515-529``)."""

    __slots__ = ("count", "future")

    def __init__(self, count: int, future: asyncio.Future) -> None:
        self.count = count
        self.future = future


class WaiterQueue:
    """Permit-counted waiter queue. Single-threaded (event loop) use."""

    def __init__(self, queue_limit: int,
                 order: QueueProcessingOrder = QueueProcessingOrder.OLDEST_FIRST
                 ) -> None:
        if queue_limit < 0:
            raise ValueError("queue_limit must be >= 0")
        self.queue_limit = queue_limit
        self.order = order
        self._deque: Deque[Registration] = Deque()
        self._queue_count = 0  # cumulative permits queued
        # Set by fail_all: once the queue has been failed (disposal), a
        # drain_async waiter returning from its in-flight round-trip must
        # be settled with this factory, never re-parked.
        self._fail_factory: Callable[[], object] | None = None

    def __len__(self) -> int:
        return len(self._deque)

    @property
    def queue_count(self) -> int:
        return self._queue_count

    def try_enqueue(self, count: int
                    ) -> tuple[asyncio.Future | None, list[Registration]]:
        """Park a waiter for ``count`` permits.

        Returns ``(future, evicted)``. ``future is None`` ⇒ the request was
        rejected (queue full under OLDEST_FIRST, or ``count`` alone exceeds
        the whole queue_limit). ``evicted`` holds NEWEST_FIRST victims the
        caller must complete with failed leases.
        """
        evicted: list[Registration] = []
        if count > self.queue_limit:
            return None, evicted
        if self._queue_count + count > self.queue_limit:
            if self.order is QueueProcessingOrder.OLDEST_FIRST:
                return None, evicted  # reject the newcomer (:159-163)
            # NEWEST_FIRST: evict oldest entries until the newcomer fits
            # (:143-158).
            while self._deque.count and self._queue_count + count > self.queue_limit:
                victim = self._deque.dequeue_head()
                self._queue_count -= victim.count
                if not victim.future.done():
                    evicted.append(victim)
        loop = asyncio.get_running_loop()
        reg = Registration(count, loop.create_future())
        self._deque.enqueue_tail(reg)
        self._queue_count += count
        # Cancellation unwinds accounting immediately (corrected semantics:
        # detect-before-consume, so no double count is possible).
        reg.future.add_done_callback(
            lambda fut, reg=reg: self._on_done(reg, fut)
        )
        return reg.future, evicted

    def _on_done(self, reg: Registration, fut: asyncio.Future) -> None:
        if fut.cancelled():
            if self._deque.remove(reg):
                self._queue_count -= reg.count

    def drain(self, try_grant: Callable[[int], bool],
              make_lease: Callable[[], object]) -> int:
        """Release waiters while permits are available (the refresh drain
        loop, ``:462-501``). ``try_grant(count)`` must atomically consume
        ``count`` permits or decline; granted waiters get
        ``make_lease()``.

        Returns the number of waiters granted. Cancelled waiters are
        discarded *before* any grant is attempted — the accounting defect
        in the reference cannot arise.
        """
        granted = 0
        while self._deque.count:
            newest = self.order is QueueProcessingOrder.NEWEST_FIRST
            reg = self._deque.peek_tail() if newest else self._deque.peek_head()
            if reg.future.done():  # cancelled while parked
                (self._deque.dequeue_tail if newest else self._deque.dequeue_head)()
                self._queue_count -= reg.count
                continue
            if not try_grant(reg.count):
                break
            (self._deque.dequeue_tail if newest else self._deque.dequeue_head)()
            self._queue_count -= reg.count
            reg.future.set_result(make_lease())
            granted += 1
        return granted

    async def drain_async(self, try_grant, make_lease: Callable[[], object]
                          ) -> int:
        """Async drain for limiters whose grants are store round-trips (the
        queueing+exact hybrid, the intent of the reference's dead
        ``TokenBucketWithQueue/RedisTokenBucketRateLimiter.cs``):
        ``await try_grant(count)`` consumes from the shared store or
        declines. Cancelled waiters are discarded before any store traffic.

        The waiter under grant is **dequeued before the await** (and
        re-queued at the same end on decline), so nothing else — NEWEST_FIRST
        eviction, cancellation callbacks, a concurrent ``fail_all`` — can
        settle it while its store round-trip is in flight; ``_queue_count``
        still includes it, so queue-limit accounting is unchanged. The one
        unavoidable hazard: a waiter cancelled in the window between the
        store grant and completion has its cost consumed (token-bucket cost
        is not returnable); the drain proceeds normally."""
        granted = 0
        while self._deque.count:
            newest = self.order is QueueProcessingOrder.NEWEST_FIRST
            reg = self._deque.peek_tail() if newest else self._deque.peek_head()
            if reg.future.done():  # cancelled while parked
                (self._deque.dequeue_tail if newest else self._deque.dequeue_head)()
                self._queue_count -= reg.count
                continue
            # Take ownership for the duration of the store round-trip.
            (self._deque.dequeue_tail if newest else self._deque.dequeue_head)()
            try:
                ok = await try_grant(reg.count)
            except BaseException:
                # Drain task cancelled (disposal) or grant raised: hand the
                # waiter back so dispose's fail_all can settle it — a
                # checked-out registration must never be stranded unsettled.
                # If fail_all already ran, settle directly instead.
                if self._fail_factory is not None:
                    self._queue_count -= reg.count
                    if not reg.future.done():
                        reg.future.set_result(self._fail_factory())
                else:
                    (self._deque.enqueue_tail if newest
                     else self._deque.enqueue_head)(reg)
                raise
            if reg.future.done():  # cancelled mid-flight (callback saw it
                self._queue_count -= reg.count  # gone; unwind here instead)
                if ok:
                    continue  # grant consumed with no lease — documented loss
                break
            if self._fail_factory is not None:
                # fail_all ran while the round-trip was in flight; it
                # couldn't see this checked-out waiter, so settle it here —
                # re-parking would strand it in a disposed queue forever.
                self._queue_count -= reg.count
                reg.future.set_result(
                    make_lease() if ok else self._fail_factory())
                if ok:
                    granted += 1
                break
            if ok:
                self._queue_count -= reg.count
                reg.future.set_result(make_lease())
                granted += 1
            else:
                # Put it back where it came from; it keeps its turn.
                (self._deque.enqueue_tail if newest
                 else self._deque.enqueue_head)(reg)
                break
        return granted

    def peek_next(self) -> Registration | None:
        """Order-aware live head: discards cancelled entries (unwinding
        their permit accounting) and returns the next waiter WITHOUT
        removing it, or ``None``. For drains whose grant is an await and
        whose cost is returnable (the concurrency limiter): the caller
        acquires for the peeked waiter, then re-peeks to confirm it is
        still next before popping — if not (cancelled mid-flight), the
        caller returns the permits instead of stranding them."""
        while self._deque.count:
            newest = self.order is QueueProcessingOrder.NEWEST_FIRST
            reg = self._deque.peek_tail() if newest else self._deque.peek_head()
            if reg.future.done():
                (self._deque.dequeue_tail if newest
                 else self._deque.dequeue_head)()
                self._queue_count -= reg.count
                continue
            return reg
        return None

    def pop_next(self) -> Registration | None:
        """Remove and return the next live waiter (see :meth:`peek_next`),
        unwinding its permit accounting."""
        reg = self.peek_next()
        if reg is not None:
            newest = self.order is QueueProcessingOrder.NEWEST_FIRST
            (self._deque.dequeue_tail if newest
             else self._deque.dequeue_head)()
            self._queue_count -= reg.count
        return reg

    def fail_all(self, make_lease: Callable[[], object]) -> int:
        """Disposal path: every parked waiter completes with a failed lease
        (``:291-298``), drained in queue-processing order. Also marks the
        queue failed so a waiter checked out by an in-flight
        :meth:`drain_async` settles on return instead of re-parking."""
        self._fail_factory = make_lease
        failed = 0
        while self._deque.count:
            newest = self.order is QueueProcessingOrder.NEWEST_FIRST
            reg = (self._deque.dequeue_tail if newest else self._deque.dequeue_head)()
            self._queue_count -= reg.count
            if not reg.future.done():
                reg.future.set_result(make_lease())
                failed += 1
        return failed

    def __iter__(self) -> Iterable[Registration]:
        return iter(self._deque)
